"""Iterative rule-based plan optimizer + channel pruning.

Reference surface: presto-main-base's IterativeOptimizer driving the
159 rules in sql/planner/iterative/rule/ (each rule = a presto-matching
Pattern + an apply), plus the PruneUnreferencedOutputs /
PruneJoinColumns / PruneAggregationSourceColumns family of narrowing
rules. The TPU engine runs the same two shapes:

  * `IterativeOptimizer`: bottom-up fixpoint application of local
    rewrite rules declared with the `plan.matching` DSL
    (MergeAdjacentFilters, PushFilterThroughProject, InlineProjections,
    RemoveIdentityProject, MergeLimits, PushLimitThroughProject,
    LimitOverSortToTopN — the core simplification set).
  * `prune_unreferenced`: one top-down channel-requirement pass that
    narrows projections, scans, join outputs, aggregates, and window
    functions to what the consumer actually reads (the reference does
    this with per-node iterative pruning rules; a single threaded pass
    is equivalent on this IR because symbols are already channels).

Pruning matters doubly here: narrower intermediates mean narrower
all_to_all exchanges on the mesh (ICI bytes) and fewer device columns
resident in HBM. Reference-ingested PlanFragments (server/protocol.py)
arrive un-pruned, so the pass is load-bearing for the protocol path,
not just hygiene.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import types as T
from ..expr import ir as E
from ..expr.logical import (and_all, conjuncts, input_channels,
                            map_input_channels)
from ..ops.aggregation import AggSpec
from . import nodes as N
from .matching import Capture, Pattern, node

__all__ = ["Rule", "IterativeOptimizer", "DEFAULT_RULES",
           "prune_unreferenced", "optimize_plan"]


# ---------------------------------------------------------------------------
# Rule machinery
# ---------------------------------------------------------------------------

class Rule:
    """One local rewrite: `pattern` guards, `apply` returns a
    replacement node or None (no-op). Mirrors iterative.Rule."""
    pattern: Pattern = node()

    def apply(self, n: N.PlanNode) -> Optional[N.PlanNode]:
        raise NotImplementedError


class IterativeOptimizer:
    """Bottom-up fixpoint driver (IterativeOptimizer analog; the memo/
    group machinery collapses away because rules here rewrite in place
    on an immutable-enough dataclass tree)."""

    def __init__(self, rules: Sequence[Rule], max_iterations: int = 100):
        self.rules = list(rules)
        self.max_iterations = max_iterations

    def optimize(self, root: N.PlanNode) -> N.PlanNode:
        for _ in range(self.max_iterations):
            new_root, changed = self._rewrite(root)
            if not changed:
                return new_root
            root = new_root
        return root

    def _rewrite(self, n: N.PlanNode) -> Tuple[N.PlanNode, bool]:
        changed = False
        # children first
        new_srcs = []
        for s in n.sources:
            ns, ch = self._rewrite(s)
            new_srcs.append(ns)
            changed |= ch
        if changed:
            n = _replace_sources(n, new_srcs)
        for rule in self.rules:
            if rule.pattern.match(n) is None:
                continue
            out = rule.apply(n)
            if out is not None and out is not n:
                return out, True
        return n, changed


def _replace_sources(n: N.PlanNode, new_sources: List[N.PlanNode]
                     ) -> N.PlanNode:
    if isinstance(n, N.JoinNode):
        return dataclasses.replace(n, left=new_sources[0],
                                   right=new_sources[1])
    if isinstance(n, N.SemiJoinNode):
        return dataclasses.replace(n, source=new_sources[0],
                                   filtering_source=new_sources[1])
    if isinstance(n, N.UnionNode):
        return dataclasses.replace(n, inputs=list(new_sources))
    if not new_sources:
        return n
    return dataclasses.replace(n, source=new_sources[0])


# ---------------------------------------------------------------------------
# Core simplification rules
# ---------------------------------------------------------------------------

class MergeAdjacentFilters(Rule):
    """Filter(Filter(s, p2), p1) -> Filter(s, p2 AND p1)
    (iterative/rule/MergeFilters analog)."""
    pattern = node(N.FilterNode).with_source(node(N.FilterNode))

    def apply(self, n):
        inner = n.source
        return N.FilterNode(inner.source,
                            and_all(conjuncts(inner.predicate)
                                    + conjuncts(n.predicate)))


class RemoveTrueFilter(Rule):
    """Filter(s, TRUE) -> s."""
    pattern = node(N.FilterNode).matching(
        lambda n: isinstance(n.predicate, E.Constant)
        and n.predicate.value is True)

    def apply(self, n):
        return n.source


def _inlinable(project: N.ProjectNode, used: Set[int]) -> bool:
    """Safe to substitute project expressions into a consumer: every
    used expression is a bare input/constant (never duplicates work)."""
    return all(isinstance(project.expressions[c],
                          (E.InputReference, E.Constant))
               for c in used)


class PushFilterThroughProject(Rule):
    """Filter(Project(s, es), p) -> Project(Filter(s, p'), es) where p'
    inlines the (cheap) project expressions
    (iterative/rule/PushDownFilterThroughProject analog). Only fires
    when every predicate-referenced projection is a bare ref/constant,
    so predicates migrate toward scans through renaming projections."""
    pattern = node(N.FilterNode).with_source(node(N.ProjectNode))

    def apply(self, n):
        proj: N.ProjectNode = n.source
        used = input_channels(n.predicate)
        if not _inlinable(proj, used):
            return None

        def sub(x):
            if isinstance(x, E.InputReference):
                return proj.expressions[x.channel]
            return x
        from ..expr.logical import rewrite_bottom_up
        pred = rewrite_bottom_up(n.predicate, sub)
        return N.ProjectNode(N.FilterNode(proj.source, pred),
                             proj.expressions)


class InlineProjections(Rule):
    """Project(Project(s, inner), outer) -> Project(s, outer') when the
    inner expressions the outer one references are bare refs/constants
    (iterative/rule/InlineProjections analog)."""
    pattern = node(N.ProjectNode).with_source(node(N.ProjectNode))

    def apply(self, n):
        inner: N.ProjectNode = n.source
        used = set()
        for e in n.expressions:
            used |= input_channels(e)
        if not _inlinable(inner, used):
            return None
        from ..expr.logical import rewrite_bottom_up

        def sub(x):
            if isinstance(x, E.InputReference):
                return inner.expressions[x.channel]
            return x
        return N.ProjectNode(inner.source,
                             [rewrite_bottom_up(e, sub)
                              for e in n.expressions])


def _is_identity(p: N.ProjectNode) -> bool:
    src_types = p.source.output_types()
    if len(p.expressions) != len(src_types):
        return False
    return all(isinstance(e, E.InputReference) and e.channel == i
               for i, e in enumerate(p.expressions))


class RemoveIdentityProject(Rule):
    """Project that reproduces its input verbatim -> source
    (RemoveRedundantIdentityProjections analog)."""
    pattern = node(N.ProjectNode).matching(_is_identity)

    def apply(self, n):
        return n.source


class MergeLimits(Rule):
    """Limit(Limit(s, b), a) -> Limit(s, min(a, b))."""
    pattern = node(N.LimitNode).with_source(node(N.LimitNode))

    def apply(self, n):
        return N.LimitNode(n.source.source, min(n.count, n.source.count))


class PushLimitThroughProject(Rule):
    """Limit(Project(s), k) -> Project(Limit(s, k))
    (iterative/rule/PushLimitThroughProject analog) — moves the row cut
    below projection work."""
    pattern = node(N.LimitNode).with_source(node(N.ProjectNode))

    def apply(self, n):
        proj = n.source
        return N.ProjectNode(N.LimitNode(proj.source, n.count),
                             proj.expressions)


class LimitOverSortToTopN(Rule):
    """Limit(Sort(s, keys), k) -> TopN(s, keys, k)
    (MergeLimitWithSort analog). The SQL planner emits TopN directly;
    this catches composed/ingested plans."""
    pattern = node(N.LimitNode).with_source(node(N.SortNode))

    def apply(self, n):
        srt = n.source
        return N.TopNNode(srt.source, list(srt.keys), n.count)


DEFAULT_RULES: List[Rule] = [
    MergeAdjacentFilters(), RemoveTrueFilter(), PushFilterThroughProject(),
    InlineProjections(), RemoveIdentityProject(), MergeLimits(),
    PushLimitThroughProject(), LimitOverSortToTopN(),
]


# ---------------------------------------------------------------------------
# Channel pruning (PruneUnreferencedOutputs family)
# ---------------------------------------------------------------------------

def prune_unreferenced(root: N.PlanNode) -> N.PlanNode:
    """Narrow every node's output to the channels its consumer reads.
    Returns an equivalent plan; the root's own output layout is
    preserved exactly."""
    n_out = len(root.output_types())
    new_root, mapping = _prune(root, set(range(n_out)))
    assert all(mapping[i] == i for i in range(n_out)), \
        "root layout must be stable"
    return new_root


def _ident(n: int) -> Dict[int, int]:
    return {i: i for i in range(n)}


def _prune(nd: N.PlanNode, needed: Set[int]
           ) -> Tuple[N.PlanNode, Dict[int, int]]:
    """Returns (new_node, old->new channel mapping covering `needed`,
    possibly more)."""
    width = len(nd.output_types())
    needed = {c for c in needed if c < width}

    if isinstance(nd, N.OutputNode):
        src, m = _prune(nd.source, set(range(width)))
        assert all(m[i] == i for i in range(width))
        return dataclasses.replace(nd, source=src), _ident(width)

    if isinstance(nd, N.TableScanNode):
        keep = sorted(needed) or [0]  # keep >=1 column for row counts
        if len(keep) == len(nd.columns):
            return nd, _ident(width)
        return (dataclasses.replace(
            nd, columns=[nd.columns[c] for c in keep],
            column_types=[nd.column_types[c] for c in keep]),
            {c: i for i, c in enumerate(keep)})

    if isinstance(nd, N.ValuesNode):
        # a zero-column VALUES (the FROM-less SELECT dual row) stays
        # zero-column; it still carries the row count
        keep = sorted(needed) or ([0] if nd.types else [])
        if len(keep) == len(nd.types):
            return nd, _ident(width)
        return (dataclasses.replace(
            nd, types=[nd.types[c] for c in keep],
            rows=[[r[c] for c in keep] for r in nd.rows]),
            {c: i for i, c in enumerate(keep)})

    if isinstance(nd, N.ProjectNode):
        # a zero-width projection (count(*) plans) stays zero-width;
        # otherwise keep >=1 expression as the row-count carrier
        keep = sorted(needed) or ([0] if nd.expressions else [])
        exprs = [nd.expressions[c] for c in keep]
        need_src: Set[int] = set()
        for e in exprs:
            need_src |= input_channels(e)
        if not need_src:
            # all-constant projection still needs the row count
            need_src = {0}
        src, m = _prune(nd.source, need_src)
        exprs = [map_input_channels(e, m) for e in exprs]
        return (N.ProjectNode(src, exprs, id=nd.id),
                {c: i for i, c in enumerate(keep)})

    if isinstance(nd, N.FilterNode):
        need_src = needed | input_channels(nd.predicate)
        src, m = _prune(nd.source, need_src)
        return (N.FilterNode(src, map_input_channels(nd.predicate, m),
                             id=nd.id), m)

    if isinstance(nd, (N.LimitNode, N.SampleNode)):
        src, m = _prune(nd.source, needed)
        return dataclasses.replace(nd, source=src), m

    if isinstance(nd, (N.SortNode, N.TopNNode)):
        need_src = needed | {k[0] for k in nd.keys}
        src, m = _prune(nd.source, need_src)
        keys = [(m[c], d, nl) for c, d, nl in nd.keys]
        return dataclasses.replace(nd, source=src, keys=keys), m

    if isinstance(nd, N.DistinctNode):
        kc = nd.key_channels
        if kc is None:  # DISTINCT over the full row: everything is a key
            src, m = _prune(nd.source, set(range(width)))
            return dataclasses.replace(nd, source=src), m
        src, m = _prune(nd.source, needed | set(kc))
        return (dataclasses.replace(nd, source=src,
                                    key_channels=[m[c] for c in kc]), m)

    if isinstance(nd, N.ExchangeNode):
        need_src = needed | set(nd.partition_channels)
        if nd.sort_keys:
            need_src |= {k[0] for k in nd.sort_keys}
        src, m = _prune(nd.source, need_src)
        return (dataclasses.replace(
            nd, source=src,
            partition_channels=[m[c] for c in nd.partition_channels],
            sort_keys=[(m[c], d, nl) for c, d, nl in nd.sort_keys]
            if nd.sort_keys else nd.sort_keys), m)

    if isinstance(nd, N.AggregationNode) and nd.step == "SINGLE":
        nk = len(nd.group_channels)
        keep_aggs = [i for i in range(len(nd.aggregates))
                     if (nk + i) in needed]
        # a keyless aggregation's single row IS its aggregates: keep one
        if nk == 0 and nd.aggregates and not keep_aggs:
            keep_aggs = [0]
        need_src: Set[int] = set(nd.group_channels)
        for i in keep_aggs:
            a = nd.aggregates[i]
            if a.input_channel is not None:
                need_src.add(a.input_channel)
            if a.second_channel is not None:
                need_src.add(a.second_channel)
        if not need_src:
            need_src = {0}
        src, m = _prune(nd.source, need_src)
        aggs = []
        for i in keep_aggs:
            a = nd.aggregates[i]
            aggs.append(dataclasses.replace(
                a,
                input_channel=None if a.input_channel is None
                else m[a.input_channel],
                second_channel=None if a.second_channel is None
                else m[a.second_channel]))
        new = dataclasses.replace(
            nd, source=src, group_channels=[m[c] for c in nd.group_channels],
            aggregates=aggs)
        mapping = {i: i for i in range(nk)}
        for pos, i in enumerate(keep_aggs):
            mapping[nk + i] = nk + pos
        return new, mapping

    if isinstance(nd, N.JoinNode):
        lt = len(nd.left.output_types())
        rsel = nd.right_output_channels
        if rsel is None:
            rsel = list(range(len(nd.right.output_types())))
        need_left = {c for c in needed if c < lt} | set(nd.left_keys)
        keep_right_pos = sorted(c - lt for c in needed if c >= lt)
        need_right = {rsel[p] for p in keep_right_pos} | set(nd.right_keys)
        left, ml = _prune(nd.left, need_left)
        right, mr = _prune(nd.right, need_right)
        new_lt = len(left.output_types())
        new = dataclasses.replace(
            nd, left=left, right=right,
            left_keys=[ml[c] for c in nd.left_keys],
            right_keys=[mr[c] for c in nd.right_keys],
            right_output_channels=[mr[rsel[p]] for p in keep_right_pos])
        # join output = full (pruned) left width ++ selected right
        mapping = {old: new_pos for old, new_pos in ml.items() if old < lt}
        for i, p in enumerate(keep_right_pos):
            mapping[lt + p] = new_lt + i
        return new, mapping

    if isinstance(nd, N.SemiJoinNode):
        sk = nd.source_key if isinstance(nd.source_key, list) \
            else [nd.source_key]
        fk = nd.filtering_key if isinstance(nd.filtering_key, list) \
            else [nd.filtering_key]
        src_w = width - 1  # output = source channels + match flag
        need_src = {c for c in needed if c < src_w} | set(sk)
        src, m = _prune(nd.source, need_src)
        filt, mf = _prune(nd.filtering_source, set(fk))
        new_sk = [m[c] for c in sk]
        new_fk = [mf[c] for c in fk]
        new = dataclasses.replace(
            nd, source=src, filtering_source=filt,
            source_key=new_sk if isinstance(nd.source_key, list)
            else new_sk[0],
            filtering_key=new_fk if isinstance(nd.filtering_key, list)
            else new_fk[0])
        mapping = {old: pos for old, pos in m.items() if old < src_w}
        mapping[src_w] = len(src.output_types())
        return new, mapping

    if isinstance(nd, N.WindowNode):
        src_w = width - len(nd.functions)
        keep_fns = [i for i in range(len(nd.functions))
                    if (src_w + i) in needed]
        need_src = {c for c in needed if c < src_w}
        need_src |= set(nd.partition_channels)
        need_src |= {k[0] for k in nd.order_keys}
        for i in keep_fns:
            ch = nd.functions[i][1]
            if ch is not None:
                need_src.add(ch)
        if not need_src:
            need_src = {0}
        src, m = _prune(nd.source, need_src)
        fns = []
        for i in keep_fns:
            name, ch, ty, frame, k = nd.functions[i]
            fns.append((name, None if ch is None else m[ch], ty, frame, k))
        new_src_w = len(src.output_types())
        new = dataclasses.replace(
            nd, source=src,
            partition_channels=[m[c] for c in nd.partition_channels],
            order_keys=[(m[c], d, nl) for c, d, nl in nd.order_keys],
            functions=fns)
        mapping = {old: pos for old, pos in m.items() if old < src_w}
        for pos, i in enumerate(keep_fns):
            mapping[src_w + i] = new_src_w + pos
        return new, mapping

    if isinstance(nd, N.UnionNode):
        keep = sorted(needed) or [0]
        target = {c: i for i, c in enumerate(keep)}
        new_inputs = []
        for inp in nd.inputs:
            child, m = _prune(inp, set(keep))
            if [m[c] for c in keep] != list(range(len(keep))) or \
                    len(child.output_types()) != len(keep):
                # normalize this child to the target layout
                tys = child.output_types()
                child = N.ProjectNode(child, [
                    E.input_ref(m[c], tys[m[c]]) for c in keep])
            new_inputs.append(child)
        return dataclasses.replace(nd, inputs=new_inputs), target

    # fallback (appended-column and not-yet-modeled kinds): keep the
    # node intact, require everything from each source, prune deeper
    new_srcs = []
    for s in nd.sources:
        ns, m = _prune(s, set(range(len(s.output_types()))))
        assert all(m[i] == i for i in range(len(s.output_types())))
        new_srcs.append(ns)
    if new_srcs:
        nd = _replace_sources(nd, new_srcs)
    return nd, _ident(width)


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def fold_plan_constants(root: N.PlanNode) -> N.PlanNode:
    """Constant-fold every expression in the plan (the sidecar
    expression-optimization seam; identity-memoized for CTE DAGs)."""
    from ..expr.logical import fold_constants
    memo: dict = {}

    def walk(n: N.PlanNode) -> N.PlanNode:
        if id(n) in memo:
            return memo[id(n)]
        changes = {}
        for f in dataclasses.fields(n):
            v = getattr(n, f.name)
            if isinstance(v, N.PlanNode):
                w = walk(v)
                if w is not v:
                    changes[f.name] = w
            elif isinstance(v, list) and v and isinstance(v[0], N.PlanNode):
                w = [walk(x) for x in v]
                if any(a is not b for a, b in zip(w, v)):
                    changes[f.name] = w
        if isinstance(n, N.FilterNode):
            p = fold_constants(n.predicate)
            if p is not n.predicate:
                changes["predicate"] = p
        elif isinstance(n, N.ProjectNode):
            ex = [fold_constants(e) for e in n.expressions]
            if any(a is not b for a, b in zip(ex, n.expressions)):
                changes["expressions"] = ex
        out = dataclasses.replace(n, **changes) if changes else n
        memo[id(n)] = out
        return out

    return walk(root)


def optimize_plan(root: N.PlanNode, rules: Sequence[Rule] = None,
                  prune: bool = True) -> N.PlanNode:
    """The PlanOptimizers pipeline analog for logical (pre-exchange)
    plans: constant folding, iterative simplification rules to
    fixpoint, then one channel-pruning pass, then a final rule sweep
    (pruning can expose identity projections)."""
    root = fold_plan_constants(root)
    opt = IterativeOptimizer(DEFAULT_RULES if rules is None else rules)
    root = opt.optimize(root)
    if prune:
        root = prune_unreferenced(root)
        root = opt.optimize(root)
    return root
