"""Narrow-width execution: plan-level physical-lane inference.

PERF.md's roofline shows the q1 hot path bandwidth-bound with int64
lanes everywhere (jax x64; v5e emulates int64 as i32 pairs): the staged
bytes -- and therefore the HBM reads the scan pipeline pays -- are 2-4x
wider than the value domains require. This pass derives, per scan
column, the narrowest PHYSICAL lane the catalog can PROVE safe:

  * dates stage as int32 epoch-days (already) or int16 when the date
    domain fits;
  * int64 key/measure columns whose value range provably fits stage as
    int32/int16/int8 lanes;
  * short-decimal (scaled int64) columns narrow by their scaled range.

Safety contract (what makes narrowed execution bit-exact):

  * narrowing applies ONLY to the staged representation. Every compute
    site that can overflow a narrow lane widens first: comparisons and
    decimal arithmetic upcast to int64 in expr/functions, aggregation
    sums upcast via ``_sum_dtype`` / 13-bit (or 8-bit) limb widening at
    accumulation (ops/aggregation.py), key words upcast to uint64
    (ops/keys.py). min/max/group-keys are order-preserving under a
    range-proven downcast.
  * a column narrows only when the connector proves its range
    (``column_range``); no stats -> the logical width stands.
  * the staging site re-checks the actual host array against the
    proven range (``checked_physical_dtypes``) so a stale statistic can
    never wrap values -- it falls back to the logical width instead.

Gates: env ``PRESTO_TPU_NARROW`` (default on; ``0`` = wide A/B) and the
``narrow_width_execution`` session property. The kernel-side forms
(bf16 one-hot operands, the fused cross-aggregate limb pool in
ops/aggregation.py) key off the same env flag at trace time.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import types as T
from . import nodes as N

__all__ = ["narrow_enabled", "kernel_narrow_enabled", "infer_column_width",
           "infer_scan_widths", "infer_table_widths", "annotate_widths",
           "checked_physical_dtypes", "batch_narrowed_bytes_saved",
           "note_narrowed", "narrowing_totals", "widths_summary"]


def narrow_enabled(session=None) -> bool:
    """Plan-level gate: env default-on, per-query session override."""
    if os.environ.get("PRESTO_TPU_NARROW", "1") == "0":
        return False
    from ..utils.config import session_flag
    return session_flag(session, "narrow_width_execution", True)


def kernel_narrow_enabled() -> bool:
    """Trace-time kernel gate (bf16 one-hot operands, fused limb pool).
    Env-only: kernels are compiled per backend, not per session."""
    return os.environ.get("PRESTO_TPU_NARROW", "1") != "0"


# physical candidates, narrowest first (never float -- bit-exactness)
_CANDIDATES = (np.dtype(np.int8), np.dtype(np.int16), np.dtype(np.int32))

# logical bases narrowing may apply to: fixed-width signed-int lanes
# whose every consumer either upcasts before arithmetic or is
# order/equality-preserving under a range-proven downcast
_NARROWABLE_BASES = ("tinyint", "smallint", "integer", "bigint", "date",
                     "time", "timestamp")


def _narrowable(ty: T.Type) -> bool:
    if ty.is_decimal:
        return ty.is_short_decimal  # int64 lanes; long decimals are 128-bit
    return ty.base in _NARROWABLE_BASES


def infer_column_width(ty: T.Type, lo: int, hi: int) -> Optional[str]:
    """Narrowest physical dtype name for a column of logical type `ty`
    whose values provably lie in [lo, hi]; None = keep the logical
    lane."""
    if not _narrowable(ty):
        return None
    logical = np.dtype(ty.to_dtype())
    for cand in _CANDIDATES:
        if cand.itemsize >= logical.itemsize:
            break
        info = np.iinfo(cand)
        if info.min <= lo and hi <= info.max:
            return cand.name
    return None


def _column_range(conn, table: str, column: str, sf: float
                  ) -> Optional[Tuple[int, int]]:
    fn = getattr(conn, "column_range", None)
    if fn is None:
        return None
    try:
        return fn(table, column, sf)
    except KeyError:
        return None


def infer_table_widths(connector: str, table: str, columns: Sequence[str],
                       column_types: Sequence[T.Type], sf: float
                       ) -> Optional[Tuple[Optional[str], ...]]:
    """Per-column physical dtype names (None = logical) for one scan;
    None overall when nothing narrows."""
    from ..connectors import catalog
    try:
        conn = catalog(connector)
    except KeyError:
        return None
    out: List[Optional[str]] = []
    for col, ty in zip(columns, column_types):
        rng = _column_range(conn, table, col, sf)
        if rng is None:
            out.append(None)  # stats can't prove the range: refuse
            continue
        out.append(infer_column_width(ty, int(rng[0]), int(rng[1])))
    if not any(out):
        return None
    return tuple(out)


def infer_scan_widths(node: N.TableScanNode, sf: float
                      ) -> Optional[Tuple[Optional[str], ...]]:
    return infer_table_widths(node.connector, node.table, node.columns,
                              node.column_types, sf)


def annotate_widths(root: N.PlanNode, sf: float, _memo=None) -> N.PlanNode:
    """Width-inference pass: rewrite every range-proven TableScanNode
    with its `physical_dtypes` annotation (identity-memoized so shared
    CTE subtrees stay shared). Runs after the logical optimizer so
    channel pruning has already dropped unused columns."""
    if _memo is None:
        _memo = {}
    if id(root) in _memo:
        return _memo[id(root)]
    orig = id(root)

    replaced = {}
    for f in dataclasses.fields(root):
        v = getattr(root, f.name)
        if isinstance(v, N.PlanNode):
            nv = annotate_widths(v, sf, _memo)
            if nv is not v:
                replaced[f.name] = nv
        elif isinstance(v, list) and v and isinstance(v[0], N.PlanNode):
            nl = [annotate_widths(s, sf, _memo) for s in v]
            if any(a is not b for a, b in zip(nl, v)):
                replaced[f.name] = nl
    if replaced:
        root = dataclasses.replace(root, **replaced)

    if isinstance(root, N.TableScanNode) and root.physical_dtypes is None \
            and not _pushdown_bypasses_staging(root):
        widths = infer_scan_widths(root, sf)
        if widths is not None:
            root = dataclasses.replace(root, physical_dtypes=widths)
    _memo[orig] = root
    return root


def _pushdown_bypasses_staging(node: N.TableScanNode) -> bool:
    """A scan with connector predicate pushdown stages through the
    connector's own row-group reader (exec/runner._scan_batch), which
    bypasses the narrowed staging path -- don't annotate what staging
    would ignore (the annotation would render in EXPLAIN and then
    silently not happen)."""
    if node.pushdown is None:
        return False
    from ..connectors import catalog
    try:
        return hasattr(catalog(node.connector), "row_groups_matching")
    except KeyError:
        return False


def checked_physical_dtypes(phys: Sequence[Optional[str]],
                            types: Sequence[T.Type],
                            arrays: Sequence[np.ndarray],
                            nulls: Optional[Sequence[
                                Optional[np.ndarray]]] = None
                            ) -> Tuple[Optional[str], ...]:
    """Staging-time guard: drop any narrowing the actual host values
    would overflow (stale statistics / mutated tables can never wrap --
    the column silently stages wide instead). NULL positions are
    excluded from the range check (mirroring column_range's non-null
    bounds; a null slot's stored payload is unspecified and narrowing
    may wrap it -- padded/null lanes are masked by every kernel)."""
    out: List[Optional[str]] = []
    for i, (dt, ty, arr) in enumerate(zip(phys, types, arrays)):
        if dt is None:
            out.append(None)
            continue
        if arr.dtype == object or arr.dtype.kind not in "iu" or not len(arr):
            out.append(None)
            continue
        live = arr
        if nulls is not None and nulls[i] is not None:
            live = arr[~np.asarray(nulls[i], dtype=bool)]
            if not len(live):
                out.append(dt)  # all-null: any lane holds the mask
                continue
        info = np.iinfo(np.dtype(dt))
        lo, hi = int(live.min()), int(live.max())
        out.append(dt if info.min <= lo and hi <= info.max else None)
    return tuple(out)


def batch_narrowed_bytes_saved(batch) -> Tuple[int, int]:
    """(columns narrowed, staged bytes saved vs logical lanes) for one
    staged Batch -- the QueryStats `narrowed_bytes_saved` source."""
    from ..block import Column
    cols = saved = 0
    for b in batch.columns:
        if not isinstance(b, Column) or not b.type.is_fixed_width:
            continue
        try:
            logical = np.dtype(b.type.to_dtype())
        except ValueError:
            continue
        phys = np.dtype(b.values.dtype)
        if phys.kind in "iu" and phys.itemsize < logical.itemsize:
            cols += 1
            saved += (logical.itemsize - phys.itemsize) * b.capacity
    return cols, saved


def widths_summary(node: N.TableScanNode) -> str:
    """`col:int16,...` rendering of a scan's narrowed lanes (EXPLAIN /
    EXPLAIN ANALYZE node annotation)."""
    phys = node.physical_dtypes
    if not phys:
        return ""
    parts = [f"{c}:{d}" for c, d in zip(node.columns, phys) if d]
    return ",".join(parts)


# --------------------------------------------------------------------------
# process-lifetime narrowing totals (the /v1/metrics families)
# --------------------------------------------------------------------------

_totals_lock = threading.Lock()
_TOTALS: Dict[str, int] = {"bytes_saved": 0, "columns": 0}


def note_narrowed(columns: int, bytes_saved: int) -> None:
    if not columns and not bytes_saved:
        return
    with _totals_lock:
        _TOTALS["columns"] += int(columns)
        _TOTALS["bytes_saved"] += int(bytes_saved)


def narrowing_totals() -> Dict[str, int]:
    with _totals_lock:
        return dict(_TOTALS)
