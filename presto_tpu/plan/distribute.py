"""AddExchanges: make a single-node plan SPMD-correct.

Reference surface: sql/planner/optimizations/AddExchanges.java:183 --
the pass that decides distribution and inserts remote ExchangeNodes so
every operator sees the rows it semantically needs. Without it, a
SINGLE-step aggregation lowered under shard_map would aggregate each
shard independently and emit per-shard partials as if they were final
results (exactly the drift the verifier catches).

Distribution rules (cost-based join choice per ROADMAP):
  * Aggregation(SINGLE, keys)   -> PARTIAL -> REPARTITION(keys) -> FINAL
  * Aggregation(SINGLE, global) -> PARTIAL -> GATHER -> FINAL
  * Distinct                    -> REPARTITION(keys) -> Distinct
  * Sort (order observable at root)
                                -> MERGE exchange over local Sort: on
                                   the mesh a sampled range repartition
                                   + per-worker sort (globally sorted,
                                   stays distributed); on the HTTP tier
                                   producers sort locally and the
                                   consumer k-way merges
                                   (MergeOperator.java:45)
  * Sort (order consumed above) -> GATHER -> Sort (single-node)
  * TopN / Limit                -> partial per worker -> GATHER -> final
                                   (full input never gathers)
  * Window / RowNumber with PARTITION BY
                                -> REPARTITION(partition keys) -> local
                                   (partitions are wholly local)
  * Window / RowNumber unpartitioned
                                -> GATHER -> op (single-node semantics)
  * MarkDistinct                -> REPARTITION(keys) -> MarkDistinct
  * Join                        -> distribution=broadcast (build side is
                                   all_gathered by the lowering)
  * SemiJoin                    -> filtering side broadcast (lowering)
"""

from __future__ import annotations

import dataclasses as _dc
from . import nodes as N

__all__ = ["add_exchanges", "split_single_agg"]


def split_single_agg(agg: "N.AggregationNode",
                     exchange_kind: str = None) -> "N.PlanNode":
    """The one home of the SINGLE -> PARTIAL -> exchange -> FINAL rewrite
    (layout-sensitive: FINAL's group channels are 0..nkeys-1 of the
    exchanged partial table). exchange_kind defaults to REPARTITION by
    keys (GATHER when global); the coordinator's simple scheduler passes
    GATHER explicitly."""
    partial = N.AggregationNode(agg.source, agg.group_channels,
                                agg.aggregates, step="PARTIAL",
                                max_groups=agg.max_groups)
    nkeys = len(agg.group_channels)
    kind = exchange_kind or ("REPARTITION" if nkeys else "GATHER")
    if kind == "REPARTITION":
        ex = N.ExchangeNode(partial, kind="REPARTITION", scope="REMOTE",
                            partition_channels=list(range(nkeys)),
                            slot_capacity=agg.max_groups)
    else:
        ex = N.ExchangeNode(partial, kind="GATHER", scope="REMOTE")
    return N.AggregationNode(ex, list(range(nkeys)), agg.aggregates,
                             step="FINAL", max_groups=agg.max_groups)


def _is_repartition_on(node: N.PlanNode, keys) -> bool:
    return (isinstance(node, N.ExchangeNode)
            and node.kind == "REPARTITION"
            and list(node.partition_channels) == list(keys))


def _is_remote_exchange(node: N.PlanNode, *kinds: str) -> bool:
    """True when `node` is a REMOTE exchange of one of `kinds` (any kind
    when none given). Idempotency guards must name the kinds THIS pass
    inserts below the operator in question -- treating any remote
    exchange as already-distributed would skip e.g. a Sort above a
    pre-existing REPARTITION, leaving per-worker order only."""
    return (isinstance(node, N.ExchangeNode) and node.scope == "REMOTE"
            and (not kinds or node.kind in kinds))


def _is_merge_on(node: N.PlanNode, keys) -> bool:
    return (_is_remote_exchange(node, "MERGE")
            and list(node.sort_keys) == list(keys))


# node kinds through which output ordering survives to the root (the
# runner materializes distributed output in worker-then-row order, so a
# globally range-sorted distributed batch concatenates correctly)
_ORDER_TRANSPARENT = (N.ProjectNode, N.OutputNode)


# AUTOMATIC: build sides estimated at or below this many rows broadcast;
# larger builds repartition both sides (the reference's
# join-max-broadcast-table-size knob, expressed in rows because the
# engine's capacities are row-static)
_BROADCAST_ROW_LIMIT = 1 << 20


def add_exchanges(node: N.PlanNode,
                  join_strategy: str = "broadcast",
                  sf: float = None) -> N.PlanNode:
    """join_strategy: "broadcast" replicates every build side (the safe
    default); "partitioned" repartitions BOTH join sides by the join
    keys (DetermineJoinDistributionType's PARTITIONED choice -- right
    for large builds); "automatic" decides per join from connector
    statistics (DetermineJoinDistributionType.java's AUTOMATIC with a
    row-count cost model) and needs `sf` for the row estimates --
    without it, unknown-size builds fall back to broadcast."""
    return _visit(node, join_strategy, order_root=True, under=None, sf=sf,
                  memo={})


def _visit(node: N.PlanNode, join_strategy: str, order_root: bool,
           under, sf=None, memo=None) -> N.PlanNode:
    """`order_root`: this node's output order is observable at the plan
    root (only Project/Output ancestors). `under`: the exchange kind
    directly above, so already-distributed partials (the local Sort of a
    MERGE, the partial TopN/Limit of a GATHER) are not rewritten again
    on idempotent re-application. `memo` keys on (node identity,
    context) so a shared CTE subtree (plan DAG) stays SHARED through
    the rewrite instead of splitting into copies."""
    if memo is None:
        memo = {}
    memo_key = (id(node), order_root, under)
    if memo_key in memo:
        return memo[memo_key]
    child_order = order_root and isinstance(node, _ORDER_TRANSPARENT)
    # rebuild children first
    replaced = {}
    for f in _dc.fields(node):
        v = getattr(node, f.name)
        child_under = node.kind if isinstance(node, N.ExchangeNode) \
            and node.scope == "REMOTE" else None
        if isinstance(v, N.PlanNode):
            nv = _visit(v, join_strategy, child_order, child_under, sf, memo)
            if nv is not v:
                replaced[f.name] = nv
        elif isinstance(v, list) and v and isinstance(v[0], N.PlanNode):
            nl = [_visit(s, join_strategy, child_order, child_under, sf, memo)
                  for s in v]
            if any(a is not b for a, b in zip(nl, v)):
                replaced[f.name] = nl
    if replaced:
        node = _dc.replace(node, **replaced)
    memo[memo_key] = _rewrite(node, join_strategy, order_root, sf, under)
    return memo[memo_key]


def _rewrite(node: N.PlanNode, join_strategy: str, order_root: bool,
             sf, under) -> N.PlanNode:

    if isinstance(node, N.AggregationNode) and node.step == "SINGLE":
        if any(a.canonical in ("count_distinct", "approx_percentile")
               for a in node.aggregates):
            # non-mergeable partials: move RAW ROWS so every group is
            # wholly local, then aggregate in one step
            nkeys = len(node.group_channels)
            if nkeys:
                ex = N.ExchangeNode(node.source, kind="REPARTITION",
                                    scope="REMOTE",
                                    partition_channels=list(node.group_channels))
            else:
                ex = N.ExchangeNode(node.source, kind="GATHER", scope="REMOTE")
            return _dc.replace(node, source=ex)
        return split_single_agg(node)

    if isinstance(node, N.DistinctNode):
        keys = node.key_channels
        if keys is None:
            keys = list(range(len(node.source.output_types())))
        if _is_repartition_on(node.source, keys):
            return node
        ex = N.ExchangeNode(node.source, kind="REPARTITION", scope="REMOTE",
                            partition_channels=keys,
                            slot_capacity=node.max_groups)
        return _dc.replace(node, source=ex)

    if isinstance(node, N.SortNode):
        if under == "MERGE" or _is_remote_exchange(node.source, "GATHER") \
                or _is_merge_on(node.source, node.keys):
            return node  # the local sort of a MERGE / already gathered
        if order_root:
            local = N.SortNode(node.source, node.keys)
            return N.ExchangeNode(local, kind="MERGE", scope="REMOTE",
                                  sort_keys=list(node.keys))
        ex = N.ExchangeNode(node.source, kind="GATHER", scope="REMOTE")
        return _dc.replace(node, source=ex)

    if isinstance(node, (N.TopNNode, N.LimitNode)):
        if under == "GATHER" or _is_remote_exchange(node.source, "GATHER") \
                or (isinstance(node, N.TopNNode)
                    and _is_merge_on(node.source, node.keys)):
            return node  # the partial below / the final above the gather
        if isinstance(node, N.TopNNode):
            partial = N.TopNNode(node.source, node.keys, node.count)
        else:
            partial = N.LimitNode(node.source, node.count)
        ex = N.ExchangeNode(partial, kind="GATHER", scope="REMOTE")
        return _dc.replace(node, source=ex)

    if isinstance(node, (N.WindowNode, N.RowNumberNode)):
        keys = list(node.partition_channels)
        if keys:
            if _is_repartition_on(node.source, keys):
                return node
            # every PARTITION BY group lands wholly on one worker; the
            # window then runs partition-local with no gather
            ex = N.ExchangeNode(node.source, kind="REPARTITION",
                                scope="REMOTE", partition_channels=keys)
        else:
            if _is_remote_exchange(node.source, "GATHER"):
                return node
            ex = N.ExchangeNode(node.source, kind="GATHER", scope="REMOTE")
        return _dc.replace(node, source=ex)

    if isinstance(node, N.MarkDistinctNode):
        if _is_repartition_on(node.source, node.key_channels):
            return node
        ex = N.ExchangeNode(node.source, kind="REPARTITION", scope="REMOTE",
                            partition_channels=list(node.key_channels))
        return _dc.replace(node, source=ex)

    if isinstance(node, N.JoinNode):
        strategy = join_strategy
        if node.join_type in ("right", "full"):
            # outer-build emission requires each build row to live on
            # exactly ONE worker (a replicated build would emit its
            # unmatched rows once per worker) -- PARTITIONED always,
            # like the reference's mustPartition join-type check in
            # DetermineJoinDistributionType
            strategy = "partitioned"
        if strategy == "automatic":
            # cost model: broadcast only when the build side is provably
            # small (its replicated copy must fit every worker); unknown
            # sizes (or no sf to cost with) default to broadcast,
            # matching the pre-CBO behavior
            strategy = "broadcast"
            if sf is not None:
                from .stats import estimate_rows
                build = estimate_rows(node.right, sf)
                if build is not None and build > _BROADCAST_ROW_LIMIT:
                    strategy = "partitioned"
        if strategy == "partitioned":
            # repartition BOTH sides by the join keys: consumers then see
            # co-partitioned inputs and join locally (the large-build
            # PARTITIONED distribution). An existing exchange is reused
            # ONLY when it already repartitions on exactly these keys;
            # anything else (e.g. a GATHER under an ORDER BY subquery)
            # gets re-exchanged, else fanned-out consumers would probe a
            # side that lives wholly on task 0.
            left, right = node.left, node.right
            if not _is_repartition_on(left, node.left_keys):
                left = N.ExchangeNode(left, kind="REPARTITION",
                                      scope="REMOTE",
                                      partition_channels=list(node.left_keys))
            if not _is_repartition_on(right, node.right_keys):
                right = N.ExchangeNode(right, kind="REPARTITION",
                                       scope="REMOTE",
                                       partition_channels=list(node.right_keys))
            return _dc.replace(node, left=left, right=right,
                               distribution="partitioned")
        # broadcast: replicate the build side via an explicit REMOTE
        # REPLICATE exchange (the mesh tier lowers it to all_gather; the
        # HTTP tier cuts a fragment whose one buffer all consumers pull).
        right = node.right
        if not (isinstance(right, N.ExchangeNode)
                and right.kind == "REPLICATE"):
            right = N.ExchangeNode(right, kind="REPLICATE", scope="REMOTE")
        return _dc.replace(node, right=right, distribution="broadcast")

    if isinstance(node, N.SemiJoinNode):
        filt = node.filtering_source
        if not (isinstance(filt, N.ExchangeNode)
                and filt.kind == "REPLICATE"):
            filt = N.ExchangeNode(filt, kind="REPLICATE", scope="REMOTE")
        return _dc.replace(node, filtering_source=filt)

    return node
