"""AddExchanges: make a single-node plan SPMD-correct.

Reference surface: sql/planner/optimizations/AddExchanges.java:183 --
the pass that decides distribution and inserts remote ExchangeNodes so
every operator sees the rows it semantically needs. Without it, a
SINGLE-step aggregation lowered under shard_map would aggregate each
shard independently and emit per-shard partials as if they were final
results (exactly the drift the verifier catches).

Round-1 rules (correctness-first; cost-based variants per ROADMAP):
  * Aggregation(SINGLE, keys)   -> PARTIAL -> REPARTITION(keys) -> FINAL
  * Aggregation(SINGLE, global) -> PARTIAL -> GATHER -> FINAL
  * Distinct                    -> REPARTITION(keys) -> Distinct
  * Sort / TopN / Limit / Window / RowNumber / MarkDistinct
                                -> GATHER -> op (single-node semantics)
  * Join                        -> distribution=broadcast (build side is
                                   all_gathered by the lowering)
  * SemiJoin                    -> filtering side broadcast (lowering)
"""

from __future__ import annotations

import dataclasses as _dc
from . import nodes as N

__all__ = ["add_exchanges", "split_single_agg"]


def split_single_agg(agg: "N.AggregationNode",
                     exchange_kind: str = None) -> "N.PlanNode":
    """The one home of the SINGLE -> PARTIAL -> exchange -> FINAL rewrite
    (layout-sensitive: FINAL's group channels are 0..nkeys-1 of the
    exchanged partial table). exchange_kind defaults to REPARTITION by
    keys (GATHER when global); the coordinator's simple scheduler passes
    GATHER explicitly."""
    partial = N.AggregationNode(agg.source, agg.group_channels,
                                agg.aggregates, step="PARTIAL",
                                max_groups=agg.max_groups)
    nkeys = len(agg.group_channels)
    kind = exchange_kind or ("REPARTITION" if nkeys else "GATHER")
    if kind == "REPARTITION":
        ex = N.ExchangeNode(partial, kind="REPARTITION", scope="REMOTE",
                            partition_channels=list(range(nkeys)),
                            slot_capacity=agg.max_groups)
    else:
        ex = N.ExchangeNode(partial, kind="GATHER", scope="REMOTE")
    return N.AggregationNode(ex, list(range(nkeys)), agg.aggregates,
                             step="FINAL", max_groups=agg.max_groups)

_GATHER_OPS = (N.SortNode, N.TopNNode, N.LimitNode, N.WindowNode,
               N.RowNumberNode, N.MarkDistinctNode)


def _is_repartition_on(node: N.PlanNode, keys) -> bool:
    return (isinstance(node, N.ExchangeNode)
            and node.kind == "REPARTITION"
            and list(node.partition_channels) == list(keys))


def add_exchanges(node: N.PlanNode,
                  join_strategy: str = "broadcast") -> N.PlanNode:
    """join_strategy: "broadcast" replicates every build side (the safe
    default); "partitioned" repartitions BOTH join sides by the join
    keys (DetermineJoinDistributionType's PARTITIONED choice -- right
    for large builds; cost-based selection is a ROADMAP item)."""
    # rebuild children first
    replaced = {}
    for f in _dc.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, N.PlanNode):
            nv = add_exchanges(v, join_strategy)
            if nv is not v:
                replaced[f.name] = nv
        elif isinstance(v, list) and v and isinstance(v[0], N.PlanNode):
            nl = [add_exchanges(s, join_strategy) for s in v]
            if any(a is not b for a, b in zip(nl, v)):
                replaced[f.name] = nl
    if replaced:
        node = _dc.replace(node, **replaced)

    if isinstance(node, N.AggregationNode) and node.step == "SINGLE":
        if any(a.canonical in ("count_distinct", "approx_percentile")
               for a in node.aggregates):
            # non-mergeable partials: move RAW ROWS so every group is
            # wholly local, then aggregate in one step
            nkeys = len(node.group_channels)
            if nkeys:
                ex = N.ExchangeNode(node.source, kind="REPARTITION",
                                    scope="REMOTE",
                                    partition_channels=list(node.group_channels))
            else:
                ex = N.ExchangeNode(node.source, kind="GATHER", scope="REMOTE")
            return _dc.replace(node, source=ex)
        return split_single_agg(node)

    if isinstance(node, N.DistinctNode):
        keys = node.key_channels
        if keys is None:
            keys = list(range(len(node.source.output_types())))
        ex = N.ExchangeNode(node.source, kind="REPARTITION", scope="REMOTE",
                            partition_channels=keys,
                            slot_capacity=node.max_groups)
        return _dc.replace(node, source=ex)

    if isinstance(node, _GATHER_OPS):
        src = node.sources[0]
        if not isinstance(src, N.ExchangeNode):
            ex = N.ExchangeNode(src, kind="GATHER", scope="REMOTE")
            return _dc.replace(node, source=ex)
        return node

    if isinstance(node, N.JoinNode):
        if join_strategy == "partitioned":
            # repartition BOTH sides by the join keys: consumers then see
            # co-partitioned inputs and join locally (the large-build
            # PARTITIONED distribution). An existing exchange is reused
            # ONLY when it already repartitions on exactly these keys;
            # anything else (e.g. a GATHER under an ORDER BY subquery)
            # gets re-exchanged, else fanned-out consumers would probe a
            # side that lives wholly on task 0.
            left, right = node.left, node.right
            if not _is_repartition_on(left, node.left_keys):
                left = N.ExchangeNode(left, kind="REPARTITION",
                                      scope="REMOTE",
                                      partition_channels=list(node.left_keys))
            if not _is_repartition_on(right, node.right_keys):
                right = N.ExchangeNode(right, kind="REPARTITION",
                                       scope="REMOTE",
                                       partition_channels=list(node.right_keys))
            return _dc.replace(node, left=left, right=right,
                               distribution="partitioned")
        # broadcast: replicate the build side via an explicit REMOTE
        # REPLICATE exchange (the mesh tier lowers it to all_gather; the
        # HTTP tier cuts a fragment whose one buffer all consumers pull).
        right = node.right
        if not (isinstance(right, N.ExchangeNode)
                and right.kind == "REPLICATE"):
            right = N.ExchangeNode(right, kind="REPLICATE", scope="REMOTE")
        return _dc.replace(node, right=right, distribution="broadcast")

    if isinstance(node, N.SemiJoinNode):
        filt = node.filtering_source
        if not (isinstance(filt, N.ExchangeNode)
                and filt.kind == "REPLICATE"):
            filt = N.ExchangeNode(filt, kind="REPLICATE", scope="REMOTE")
        return _dc.replace(node, filtering_source=filt)

    return node
