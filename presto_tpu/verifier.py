"""Verifier: replay a query corpus across engine configurations and
compare results.

Reference surface: presto-verifier (24k LoC: replays production queries
against control/test clusters with per-column checksums and drift
resolvers). Here the "clusters" are execution configurations of one
engine -- single-batch local, streaming splits, SPMD mesh -- and results
must match EXACTLY (decimals are scaled int64: no tolerance needed,
checksums are literal equality on sorted row sets).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

__all__ = ["VerifierResult", "verify_corpus", "DEFAULT_CORPUS",
           "TPCDS_CORPUS"]


@dataclasses.dataclass
class VerifierResult:
    query: str
    configs: List[str]
    ok: bool
    detail: str = ""


def _canon(res) -> list:
    rows = [tuple(None if v is None else v for v in r) for r in res.rows()]
    return sorted(rows, key=lambda r: tuple(str(x) for x in r))


def verify_corpus(corpus: Sequence[str], sf: float = 0.01,
                  mesh=None, split_rows: Optional[int] = None,
                  max_groups: int = 1 << 14,
                  cluster_urls: Optional[Sequence[str]] = None
                  ) -> List[VerifierResult]:
    """Run each query under every applicable configuration; compare
    sorted row sets for exact equality. `cluster_urls` adds the
    multi-worker HTTP tier (coordinator-scheduled fragments)."""
    from .sql import plan_sql, sql

    out: List[VerifierResult] = []
    for text in corpus:
        runs: Dict[str, object] = {}
        errors: Dict[str, str] = {}

        def attempt(name: str, **kwargs):
            try:
                runs[name] = _canon(sql(text, sf=sf, max_groups=max_groups,
                                        **kwargs))
            except Exception as e:  # noqa: BLE001 - verifier records drift
                errors[name] = f"{type(e).__name__}: {e}"

        attempt("control")
        if split_rows is not None:
            attempt("streaming", split_rows=split_rows)
        if mesh is not None:
            attempt("mesh", mesh=mesh)
        if cluster_urls:
            try:
                from .exec.runner import QueryResult
                from .plan.distribute import add_exchanges
                from .server import Coordinator
                from .server.coordinator import SchedulerGap
                plan = add_exchanges(plan_sql(text, max_groups=max_groups))
                cols, names = Coordinator(list(cluster_urls)).execute(plan,
                                                                      sf=sf)
                nrows = len(cols[0][0]) if cols else 0
                res = QueryResult(columns=[v for v, _ in cols],
                                  nulls=[n for _, n in cols],
                                  names=names, row_count=nrows)
                runs["cluster"] = _canon(res)
            except SchedulerGap:
                pass  # declared scheduler-depth gap, not drift
            except Exception as e:  # noqa: BLE001
                errors["cluster"] = f"{type(e).__name__}: {e}"

        if errors:
            out.append(VerifierResult(text, list(runs) + list(errors), False,
                                      f"errors: {errors}"))
            continue
        names = list(runs)
        base = runs[names[0]]
        mismatch = [n for n in names[1:] if runs[n] != base]
        if mismatch:
            out.append(VerifierResult(text, names, False,
                                      f"result drift in {mismatch}"))
        else:
            out.append(VerifierResult(text, names, True))
    return out


DEFAULT_CORPUS = [
    "SELECT returnflag, linestatus, sum(quantity), count(*) FROM lineitem "
    "WHERE shipdate <= date '1998-09-02' GROUP BY returnflag, linestatus",
    "SELECT sum(extendedprice * discount) FROM lineitem "
    "WHERE discount BETWEEN 0.05 AND 0.07 AND quantity < 24",
    "SELECT custkey, count(*) FROM orders GROUP BY custkey "
    "HAVING count(*) >= 25",
    "SELECT shipmode, min(quantity), max(quantity) FROM lineitem "
    "WHERE shipmode IN ('AIR', 'MAIL') GROUP BY shipmode",
    "SELECT count(*) FROM lineitem WHERE orderkey IN "
    "(SELECT orderkey FROM orders WHERE totalprice > 300000.00)",
    # set operations (NULL=NULL membership, precedence)
    "SELECT regionkey FROM nation INTERSECT "
    "SELECT regionkey FROM region WHERE regionkey >= 2",
    "SELECT nationkey FROM nation WHERE nationkey < 5 UNION "
    "SELECT regionkey FROM region",
    # join + aggregation
    "SELECT n.name, count(*) FROM supplier s "
    "JOIN nation n ON s.nationkey = n.nationkey GROUP BY n.name",
    # distinct aggregates (non-mergeable partials: raw-row repartition)
    "SELECT custkey, count(DISTINCT orderpriority) FROM orders "
    "GROUP BY custkey HAVING count(*) > 20",
    # HLL sketch states (mergeable registers across the mesh)
    "SELECT returnflag, approx_distinct(partkey) FROM lineitem "
    "GROUP BY returnflag",
    # scalar subquery
    "SELECT count(*) FROM customer WHERE acctbal > "
    "(SELECT avg(acctbal) FROM customer)",
    # grouping sets
    "SELECT returnflag, linestatus, sum(quantity) AS q FROM lineitem "
    "GROUP BY ROLLUP(returnflag, linestatus) ORDER BY q DESC",
    # window functions
    "SELECT orderkey, linenumber, "
    "lag(quantity) OVER (PARTITION BY orderkey ORDER BY linenumber) AS p "
    "FROM lineitem WHERE orderkey <= 30",
    # correlated EXISTS
    "SELECT count(*) FROM orders o WHERE EXISTS "
    "(SELECT l.orderkey FROM lineitem l WHERE l.orderkey = o.orderkey "
    " AND l.quantity > 49.00)",
    # long-decimal (int128 lane) sums + avg finalization across the
    # PARTIAL -> exchange -> FINAL path (round-2's shipped regressions)
    "SELECT returnflag, sum(extendedprice) AS s, avg(extendedprice) AS a "
    "FROM lineitem GROUP BY returnflag ORDER BY returnflag",
    # MERGE exchange: root-observable global order, no gather
    "SELECT orderkey, totalprice FROM orders "
    "WHERE totalprice > 400000.00 ORDER BY totalprice DESC, orderkey",
    # round-5 surface: RANGE value frames over the mesh repartition
    "SELECT orderkey, quantity, sum(quantity) OVER (PARTITION BY orderkey "
    "ORDER BY quantity RANGE BETWEEN 5 PRECEDING AND CURRENT ROW) "
    "FROM lineitem WHERE orderkey <= 20",
    # round-5 surface: array lambdas capture grouped columns (pure-JAX
    # lanes: safe under shard_map; host-callback fns stay off the mesh)
    "SELECT regionkey, sum(reduce(sequence(1, 4), 0, (s, x) -> s + x * "
    "regionkey, s -> s)) FROM nation GROUP BY regionkey",
    # round-5 surface: interval arithmetic + date filters (a 180-day
    # window lands INSIDE the data range -- ~360 rows at sf 0.01 -- so
    # wrong interval math is observable, not a trivially-empty result)
    "SELECT count(*) FROM orders WHERE orderdate >= "
    "date '1998-12-01' - interval '180' day",
    # RIGHT/FULL OUTER: unmatched-build emission under partitioned
    # distribution
    "SELECT r.name, count(n.nationkey) FROM nation n "
    "RIGHT JOIN region r ON n.regionkey = r.regionkey GROUP BY r.name",
    "SELECT count(*), count(o.orderkey), count(c.custkey) FROM orders o "
    "FULL OUTER JOIN customer c ON o.custkey = c.custkey",
    # large-cardinality group-by (sorted-mode kernel): ~15k groups at
    # sf=0.01 -- kernel output must be OBSERVABLE (a filter that empties
    # the result would compare empty==empty and hide drift)
    "SELECT orderkey, count(*), sum(quantity) FROM lineitem "
    "GROUP BY orderkey HAVING sum(quantity) >= 90.00",
]

# TPC-DS shapes resolved against the tpcds catalog (star join + dim
# filters -- the q3 family the CBO/dynamic-filter work targets)
TPCDS_CORPUS = [
    "SELECT dt.d_year, item.i_brand_id, sum(ss_sales_price) AS s "
    "FROM date_dim dt, store_sales, item "
    "WHERE dt.d_date_sk = store_sales.ss_sold_date_sk "
    "  AND store_sales.ss_item_sk = item.i_item_sk "
    "  AND item.i_manufact_id = 128 AND dt.d_moy = 11 "
    "GROUP BY dt.d_year, item.i_brand_id "
    "ORDER BY dt.d_year, s DESC, item.i_brand_id",
]


def check_plan_determinism(corpus: Sequence[str], repeats: int = 3
                           ) -> List[str]:
    """PlanDeterminismChecker analog: plan each query `repeats` times
    and diff the structural fingerprints (node ids excluded). Returns
    the queries whose plans drifted -- an empty list is the pass."""
    from .exec.plan_cache import plan_fingerprint
    from .sql import plan_sql

    drifted = []
    for q in corpus:
        fps = {plan_fingerprint(plan_sql(q)) for _ in range(repeats)}
        if len(fps) != 1:
            drifted.append(q)
    return drifted
