"""Execution timeline & occupancy profiler: split-level interval
tracing with pipeline-bubble attribution.

The observability gap this closes: ROADMAP item 1 wants the staging
path to become a pipelined producer/consumer "visible as overlapping
hop walls in /v1/datapath" -- but the datapath waterfall records
per-hop SUMS, which are blind to concurrency: q1's ~0.3 GB/s staging
verdict cannot distinguish "each hop is slow" from "the hops run
strictly serially with the device idle between splits". Presto's own
EXPLAIN ANALYZE cpu-vs-wall split and the metadata-caching paper's
overlap analysis both show that pipeline OCCUPANCY, not hop
throughput, is the number an async-ingest change must be gated
against. This module is that instrument, built BEFORE the pipeline
work lands, so today's measured ~0 overlap on q1 becomes the
committed baseline the async split pipeline must visibly move.

Model -- three layers, one merge law (the datapath/accuracy template):

  * ``Interval`` -- one ``(lane, hop, split_id, t0_us, t1_us, bytes)``
    record on the per-process monotonic clock (``datapath.now_us``,
    the SAME clock the hop walls use, so hop sums and interval
    durations reconcile by construction). Lanes partition the engine's
    two execution streams: ``host`` (staging threads: connector read,
    decode, narrow cast, device put, serde, fetch, drain) and
    ``device`` (the compiled-program dispatch stream -- the ``kernel``
    hop). Hops within one lane may overlap (exchange_fetch CONTAINS
    decode, exactly as in the hop catalog); occupancy math unions
    them.
  * ``TimelineSlice`` -- one query's bounded interval ledger slice.
    The merge law: interval multisets union then keep the
    ``max_intervals`` earliest under a total sort order (keep-k-
    smallest is associative + commutative), dropped counts and per-hop
    totals add -- the empty slice is the identity -- so worker slices
    stitch through the existing task-status path
    (``QueryStats.timeline``, folded by ``QueryStats.merge``).
    Cross-process JSON ships AGES, never absolute timestamps
    (``endAgeUs`` + ``durUs``, the exec/progress.py trick): the
    receiver rebases onto its own clock, so clock skew can shift a
    remote slice but can never produce a negative interval.
  * process-lifetime registry: the ``GET /v1/timeline`` slice (worker
    serves it; the statement tier merges slices cluster-wide via
    server/client.pull_worker_docs, processId-deduped, stable zero
    shape), ``system.occupancy``, metrics.timeline_families(),
    flight-dump embeds, the Chrome trace export
    (scripts/timeline_view.py), and the bench.py per-query
    overlap_fraction / device_idle_us artifact keys.

The occupancy engine is PURE (no clocks, no env -- perfgate-style, so
identical intervals always produce identical verdicts): per-lane busy
fractions over the execute wall, the overlap fraction (share of
device-busy wall during which host staging is concurrently busy --
the pipelining number), and the bubble verdict, which sweeps the
device-idle gaps and names the host hop the device was waiting on:
"device idle 71% of execute wall; bubbles attributed: connector_read
(54%), device_put (17%)". Deterministic tiebreak: attributed idle
desc, hop name asc.

Bounded and fail-open everywhere: per-query interval caps with
totals-only degradation (intervals drop, per-hop busy/bytes totals
keep counting -- counted, never failing the query), an LRU'd
per-query registry, a ``timeline.record`` failpoint proving the
degradation path, and a ``timeline`` session property /
``PRESTO_TPU_TIMELINE`` env gate registered in KERNEL_MODE_ENVS.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import uuid
from typing import Dict, List, Optional, Tuple

from .. import failpoints
from ..utils.locks import OrderedLock
from .datapath import HOPS, now_us

__all__ = ["LANES", "LANE_OF", "TIMELINE_ENV", "MAX_INTERVALS",
           "Interval", "TimelineSlice", "TimelineLedger", "recording",
           "record_interval", "split_scope", "current_split",
           "timeline_enabled", "occupancy", "bubble_verdict",
           "ascii_gantt", "to_chrome_trace", "note_query",
           "timeline_for_query", "last_occupancy", "timeline_totals",
           "clear_timeline", "timeline_doc", "merge_timeline_docs",
           "cluster_timeline_doc", "snapshot", "timeline_summary"]

TIMELINE_ENV = "PRESTO_TPU_TIMELINE"

# the lane catalog: ONE closed vocabulary every surface shares (the
# Gantt rows, /v1/timeline zero shape, system.occupancy rows, Chrome
# trace thread names). `device` is the compiled-program dispatch
# stream; everything else on the hop catalog is host-side staging.
LANES = ("host", "device")
LANE_OF = {hop: ("device" if hop == "kernel" else "host")
           for hop in HOPS}

# per-query interval cap: beyond it the slice degrades to totals-only
# (dropped counted). 4096 covers thousands of splits x hops; one
# Interval is ~100 bytes, so a full slice stays ~400 KB.
MAX_INTERVALS = 4096

# one id per process: the cluster merge deduplicates slices by it, so
# two server shells over one process (the test topology) count once
_PROCESS_ID = uuid.uuid4().hex


@dataclasses.dataclass(frozen=True)
class Interval:
    """One timed window on the per-process monotonic clock. Treated
    as immutable: slices share Interval objects freely across
    merges."""
    lane: str
    hop: str
    split_id: int = -1
    t0_us: int = 0
    t1_us: int = 0
    bytes: int = 0

    def sort_key(self) -> tuple:
        # a TOTAL order: keep-k-smallest truncation under it is
        # associative, which is what makes the slice merge a law
        return (self.t0_us, self.t1_us, self.lane, self.hop,
                self.split_id, self.bytes)


def _zero_hop_total() -> Dict[str, int]:
    return {"busyUs": 0, "bytes": 0, "count": 0}


@dataclasses.dataclass
class TimelineSlice:
    """One query's interval-ledger slice. Merges with the usual law:
    interval multisets union (truncated to the earliest
    ``MAX_INTERVALS`` under the total sort order, overflow counted in
    ``dropped``), dropped adds, per-hop totals add -- associative and
    commutative with the empty slice as identity, like QueryStats.
    ``totals`` keep counting after interval degradation: the
    totals-only floor every surface can still render."""
    intervals: List[Interval] = dataclasses.field(default_factory=list)
    dropped: int = 0
    totals: Dict[str, Dict[str, int]] = \
        dataclasses.field(default_factory=dict)

    def merge(self, other: "TimelineSlice") -> "TimelineSlice":
        ivs = sorted(self.intervals + other.intervals,
                     key=Interval.sort_key)
        dropped = self.dropped + other.dropped
        if len(ivs) > MAX_INTERVALS:
            dropped += len(ivs) - MAX_INTERVALS
            ivs = ivs[:MAX_INTERVALS]
        totals: Dict[str, Dict[str, int]] = {}
        for src in (self.totals, other.totals):
            for hop, t in src.items():
                out = totals.setdefault(hop, _zero_hop_total())
                for k in out:
                    out[k] += int(t.get(k, 0))
        return TimelineSlice(ivs, dropped, totals)

    def copy(self) -> "TimelineSlice":
        return TimelineSlice(list(self.intervals), self.dropped,
                             {h: dict(t) for h, t in self.totals.items()})

    def to_json(self, now: Optional[int] = None) -> dict:
        """Serialize for cross-process shipping. Absolute monotonic
        times are meaningless on another host, so each interval ships
        as (endAgeUs, durUs) relative to ``now`` -- the progress.py
        skew-free trick. ``now`` is injectable for deterministic
        tests; production callers take the ambient clock."""
        ref = now_us() if now is None else int(now)
        return {"intervals": [[iv.lane, iv.hop, iv.split_id,
                               max(ref - iv.t1_us, 0),
                               max(iv.t1_us - iv.t0_us, 0),
                               iv.bytes]
                              for iv in self.intervals],
                "dropped": self.dropped,
                "totals": {h: dict(t)
                           for h, t in self.totals.items()}}

    @classmethod
    def from_json(cls, doc: dict,
                  now: Optional[int] = None) -> "TimelineSlice":
        """Rebase a shipped slice onto THIS process's clock: t1 =
        now - endAge, t0 = t1 - dur, both deltas clamped >= 0 -- a
        skewed remote clock can shift a slice, never produce a
        negative interval. Old-doc tolerance: a missing/partial doc
        deserializes to the empty slice (merge identity); unknown
        keys are ignored."""
        ref = now_us() if now is None else int(now)
        ivs = []
        for row in (doc or {}).get("intervals") or ():
            lane, hop, split, end_age, dur = (str(row[0]), str(row[1]),
                                              int(row[2]), int(row[3]),
                                              int(row[4]))
            nbytes = int(row[5]) if len(row) > 5 else 0
            t1 = ref - max(end_age, 0)
            ivs.append(Interval(lane, hop, split, t1 - max(dur, 0),
                                t1, nbytes))
        ivs.sort(key=Interval.sort_key)
        totals = {str(h): {k: int(t.get(k, 0))
                           for k in _zero_hop_total()}
                  for h, t in ((doc or {}).get("totals") or {}).items()}
        return cls(ivs, int((doc or {}).get("dropped") or 0), totals)

    def rows(self) -> List[list]:
        """Raw in-process rows (t0/t1 on the local monotonic clock)
        for flight dumps and the Chrome export -- post-mortem surfaces
        on the SAME host, where absolute monotonic times align."""
        return [[iv.lane, iv.hop, iv.split_id, iv.t0_us, iv.t1_us,
                 iv.bytes] for iv in self.intervals]

    def is_empty(self) -> bool:
        return not (self.intervals or self.dropped or self.totals)


class TimelineLedger:
    """Per-query interval accumulator (the ambient collection target).
    Thread-safe: host staging threads and the device dispatch stream
    record concurrently. ``enabled=False`` makes every record a no-op
    (the session-property gate); ``degraded`` is the sticky totals-only
    floor a failed record path drops to."""

    _GUARDED_BY = {"_lock": ("intervals", "dropped", "totals",
                             "degraded")}

    def __init__(self, query_id: str = "", enabled: bool = True,
                 max_intervals: int = MAX_INTERVALS):
        self.query_id = query_id
        self.enabled = enabled
        self.max_intervals = int(max_intervals)
        self.intervals: List[Interval] = []
        self.dropped = 0
        self.totals: Dict[str, Dict[str, int]] = {}
        self.degraded = False
        self._lock = OrderedLock("timeline.TimelineLedger._lock")

    def record(self, hop: str, nbytes: int, t0_us: int, t1_us: int,
               split_id: int = -1) -> None:
        lane = LANE_OF.get(hop, "host")
        with self._lock:
            self._fold_total_locked(hop, nbytes, t1_us - t0_us)
            if self.degraded or len(self.intervals) >= \
                    self.max_intervals:
                self.dropped += 1
                return
            self.intervals.append(Interval(lane, hop, int(split_id),
                                           int(t0_us), int(t1_us),
                                           int(nbytes)))

    def degrade(self, hop: str, nbytes: int, t0_us: int,
                t1_us: int) -> bool:
        """Totals-only floor for a record that failed mid-flight: the
        observation still counts (busy/bytes totals), the interval is
        dropped, and the ledger stays degraded for the rest of the
        query. Returns True on the FIRST degradation (the caller
        emits one flight event per query, not per record)."""
        with self._lock:
            self._fold_total_locked(hop, nbytes, t1_us - t0_us)
            self.dropped += 1
            first = not self.degraded
            self.degraded = True
            return first

    def _fold_total_locked(self, hop: str, nbytes: int, dur_us: int) -> None:
        t = self.totals.get(hop)
        if t is None:
            t = self.totals[hop] = _zero_hop_total()
        t["busyUs"] += max(int(dur_us), 0)
        t["bytes"] += int(nbytes)
        t["count"] += 1

    def snapshot_slice(self) -> TimelineSlice:
        with self._lock:
            return TimelineSlice(
                list(self.intervals), self.dropped,
                {h: dict(t) for h, t in self.totals.items()})


# -- ambient (thread-local) attribution ---------------------------------

_tls = threading.local()


def _current_ledger() -> Optional[TimelineLedger]:
    return getattr(_tls, "ledger", None)


class recording:
    """Install `ledger` as this thread's ambient timeline target
    (exec/runner.py wraps each run_query; nested invocations shadow
    and restore, like datapath.recording and accuracy.recording)."""

    def __init__(self, ledger: TimelineLedger):
        self.ledger = ledger

    def __enter__(self):
        self.prev = _current_ledger()
        _tls.ledger = self.ledger
        return self.ledger

    def __exit__(self, *exc):
        _tls.ledger = self.prev
        return False


class split_scope:
    """Tag every interval recorded inside the block with `split_id`
    (the runner's staging loop wraps each scan split, so the
    connector_read/decode/narrow_cast/device_put seams attribute to
    their split without threading an index through every signature)."""

    def __init__(self, split_id: int):
        self.split_id = int(split_id)

    def __enter__(self):
        self.prev = current_split()
        _tls.split = self.split_id
        return self

    def __exit__(self, *exc):
        _tls.split = self.prev
        return False


def current_split() -> int:
    return getattr(_tls, "split", -1)


def record_interval(hop: str, nbytes: int, t0_us: int, t1_us: int,
                    split_id: int = -1) -> None:
    """Fold one timed window into the ambient ledger (when one is
    installed). Never raises: this sits on the staging/serde/dispatch
    hot paths. A failure inside the record path (including the
    ``timeline.record`` failpoint) degrades the ledger to counted
    totals -- the query keeps running and keeps counting."""
    try:
        ledger = _current_ledger()
        if ledger is None or not ledger.enabled:
            return
        sid = split_id if split_id >= 0 else current_split()
        try:
            if failpoints.ARMED:
                failpoints.hit("timeline.record")
            ledger.record(hop, nbytes, t0_us, t1_us, sid)
        except Exception as e:  # noqa: BLE001 - degrade, never fail
            first = ledger.degrade(hop, nbytes, t0_us, t1_us)
            _note_degraded(ledger.query_id if first else None, e)
    except Exception as e:  # noqa: BLE001 - attribution must never
        # fail the query it observes; leave the counted trace
        try:
            from ..server.metrics import record_suppressed
            record_suppressed("timeline", "record_interval", e)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


def timeline_enabled(session) -> bool:
    """Session property ``timeline``; process default from
    PRESTO_TPU_TIMELINE (default ON -- the instrument is cheap and the
    occupancy baseline must exist before the pipeline PR). Spelled
    literally so tpulint R001 proves the knob is registered in
    KERNEL_MODE_ENVS."""
    import os
    env_on = os.environ.get("PRESTO_TPU_TIMELINE", "1") \
        not in ("0", "", "false")
    from ..utils.config import session_flag
    return session_flag(session, "timeline", env_on)


# -- occupancy engine (pure: no clocks, no env) --------------------------


def _as_interval(iv) -> Interval:
    """Interval or its raw row -> Interval (both shapes flow through
    the engine: QueryStats carries objects, flight dumps carry
    rows)."""
    if isinstance(iv, Interval):
        return iv
    lane, hop, split, t0, t1 = (str(iv[0]), str(iv[1]), int(iv[2]),
                                int(iv[3]), int(iv[4]))
    return Interval(lane, hop, split, t0, t1,
                    int(iv[5]) if len(iv) > 5 else 0)


def _merge_segments(segs: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """Sorted (t0, t1) windows -> their disjoint union sweep."""
    # M001: at most one output segment per input segment
    _BOUNDED_BY = {"out": "one merged segment per input interval"}
    out: List[Tuple[int, int]] = []
    for a, b in sorted(segs):
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def _span_us(segs: List[Tuple[int, int]]) -> int:
    return sum(b - a for a, b in segs)


def _intersect(xs: List[Tuple[int, int]],
               ys: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """Two disjoint sorted segment lists -> their intersection."""
    # M001: each advance consumes one input segment
    _BOUNDED_BY = {"out": "at most |xs| + |ys| intersection segments"}
    out: List[Tuple[int, int]] = []
    i = j = 0
    while i < len(xs) and j < len(ys):
        a = max(xs[i][0], ys[j][0])
        b = min(xs[i][1], ys[j][1])
        if a < b:
            out.append((a, b))
        if xs[i][1] <= ys[j][1]:
            i += 1
        else:
            j += 1
    return out


def _subtract(window: Tuple[int, int],
              segs: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """The window's complement of a disjoint sorted segment list."""
    # M001: one gap per busy segment plus the tail
    _BOUNDED_BY = {"out": "at most |segs| + 1 gap segments"}
    out: List[Tuple[int, int]] = []
    cur = window[0]
    for a, b in segs:
        if a > cur:
            out.append((cur, min(a, window[1])))
        cur = max(cur, b)
        if cur >= window[1]:
            break
    if cur < window[1]:
        out.append((cur, window[1]))
    return out


def occupancy(intervals) -> Optional[dict]:
    """The occupancy document of one interval set: per-lane busy
    fractions over the execute wall (min t0 .. max t1), the overlap
    fraction (|device-busy AND host-busy| / device-busy -- the
    pipelining number, ~0 on today's serial staging), the device-idle
    share, and the bubble attribution: per host hop, how much of the
    device-idle wall that hop was busy during (the hop the device was
    WAITING on). Pure function of its inputs -- no clocks, no env --
    so identical intervals always produce identical documents. None
    when no intervals were recorded (totals-only degradation leaves
    the per-hop totals, not an occupancy)."""
    # M001: one bubble row per catalog hop, one entry per lane
    _BOUNDED_BY = {"bubbles": "one row per catalog hop",
                   "lane_segs": "one union per lane"}
    ivs = [_as_interval(iv) for iv in intervals]
    if not ivs:
        return None
    w0 = min(iv.t0_us for iv in ivs)
    w1 = max(iv.t1_us for iv in ivs)
    wall = max(w1 - w0, 0)
    lane_segs = {
        lane: _merge_segments([(iv.t0_us, iv.t1_us) for iv in ivs
                               if iv.lane == lane
                               and iv.t1_us > iv.t0_us])
        for lane in LANES}
    lanes = {}
    for lane in LANES:
        busy = _span_us(lane_segs[lane])
        lanes[lane] = {"busyUs": busy,
                       "busyFraction": round(busy / wall, 4)
                       if wall else 0.0}
    dev_busy = lanes["device"]["busyUs"]
    overlap = _span_us(_intersect(lane_segs["device"],
                                  lane_segs["host"]))
    idle_segs = _subtract((w0, w1), lane_segs["device"])
    idle = _span_us(idle_segs)
    bubbles = []
    for hop in HOPS:
        if LANE_OF.get(hop) == "device":
            continue
        hop_segs = _merge_segments([(iv.t0_us, iv.t1_us) for iv in ivs
                                    if iv.hop == hop
                                    and iv.t1_us > iv.t0_us])
        attr = _span_us(_intersect(hop_segs, idle_segs))
        if attr > 0:
            bubbles.append({"hop": hop, "idleUs": attr,
                            "share": round(attr / wall, 4)
                            if wall else 0.0})
    # deterministic order: attributed idle desc, hop name asc
    bubbles.sort(key=lambda b: (-b["idleUs"], b["hop"]))
    return {"wallUs": wall,
            "lanes": lanes,
            "overlapUs": overlap,
            "overlapFraction": round(overlap / dev_busy, 4)
            if dev_busy else 0.0,
            "deviceIdleUs": idle,
            "deviceIdleFraction": round(idle / wall, 4)
            if wall else 0.0,
            "bubbles": bubbles}


def bubble_verdict(intervals, occ: Optional[dict] = None
                   ) -> Optional[dict]:
    """The named verdict: the host hop owning the largest share of the
    device-idle wall -- "device idle 71% of execute wall; bubbles
    attributed: connector_read (54%), device_put (17%)". Pure function
    of its inputs (``occ`` may be passed to reuse a computed occupancy
    document). Deterministic tiebreak rides the bubble ordering: idle
    desc, hop asc. None when no intervals were recorded."""
    if occ is None:
        occ = occupancy(intervals)
    if occ is None:
        return None
    idle_frac = occ["deviceIdleFraction"]
    bubbles = occ["bubbles"]
    if not bubbles:
        return {"hop": "", "idleUs": 0, "share": 0.0,
                "deviceIdleFraction": idle_frac,
                "overlapFraction": occ["overlapFraction"],
                "message": (f"device idle {idle_frac:.0%} of execute "
                            f"wall; no bubbles attributed")}
    top = bubbles[0]
    attributed = ", ".join(f"{b['hop']} ({b['share']:.0%})"
                           for b in bubbles[:3])
    return {"hop": top["hop"], "idleUs": top["idleUs"],
            "share": top["share"],
            "deviceIdleFraction": idle_frac,
            "overlapFraction": occ["overlapFraction"],
            "message": (f"device idle {idle_frac:.0%} of execute "
                        f"wall; bubbles attributed: {attributed}")}


def ascii_gantt(intervals, width: int = 48) -> List[str]:
    """One fixed-width Gantt row per lane ('#' busy, '.' idle), the
    EXPLAIN ANALYZE tail's rendering. Pure function of its inputs."""
    # M001: one rendered line per catalog lane
    _BOUNDED_BY = {"lines": "one Gantt row per lane"}
    ivs = [_as_interval(iv) for iv in intervals]
    if not ivs:
        return []
    w0 = min(iv.t0_us for iv in ivs)
    w1 = max(iv.t1_us for iv in ivs)
    span = max(w1 - w0, 1)
    lines = []
    for lane in LANES:
        cells = ["."] * width
        for iv in ivs:
            if iv.lane != lane or iv.t1_us <= iv.t0_us:
                continue
            a = (iv.t0_us - w0) * width // span
            b = -((iv.t1_us - w0) * width // -span)  # ceil
            for c in range(max(a, 0), min(max(b, a + 1), width)):
                cells[c] = "#"
        lines.append(f"{lane:<7}[{''.join(cells)}]")
    return lines


# -- process registry ----------------------------------------------------

# request handlers (/v1/timeline, system tables), engine threads
# (note_query after each run, record_interval's degradation counter)
# and the flight recorder all touch these
_LOCK = OrderedLock("timeline._LOCK")
# query id -> merged slice (the flight-dump cross-link AND the
# /v1/timeline payload); bounded like datapath's query ledgers
_QUERY_SLICES: "collections.OrderedDict[str, TimelineSlice]" = \
    collections.OrderedDict()
_QUERY_SLICES_MAX = 256
# query id -> /v1/trace trace id (the Chrome export cross-link)
_QUERY_TRACE: Dict[str, str] = {}
# lifetime counters (stable zero shape from process start)
_TOTALS = {"intervals": 0, "dropped": 0, "queries": 0, "degraded": 0}
# the last finalized query's occupancy headline (metrics gauges +
# bench.py read this; {} until the first query lands)
_LAST: Dict[str, object] = {}

_GUARDED_BY = {"_LOCK": ("_QUERY_SLICES", "_QUERY_TRACE", "_TOTALS",
                         "_LAST")}


def _note_degraded(query_id: Optional[str], exc: Exception) -> None:
    """Count one totals-only degradation; on the FIRST per query
    (query_id non-None) leave the flight-recorder trace. Never
    raises."""
    try:
        with _LOCK:
            _TOTALS["degraded"] += 1
        from ..server.metrics import record_suppressed
        record_suppressed("timeline", "record_interval", exc)
        if query_id is not None:
            from ..server.flight_recorder import record_event
            record_event("timeline_degraded", query_id=query_id,
                         reason=str(exc)[:200])
    except Exception:  # noqa: BLE001 - interpreter teardown
        pass


def note_query(query_id: str, sl: TimelineSlice,
               trace_id: str = "") -> None:
    """Retain one query's slice for flight-dump embeds and the
    /v1/timeline payload (bounded); re-notes of the same query id
    merge (worker task slices stitch). Folds the lifetime counters
    and refreshes the last-query occupancy headline. Never raises --
    the runner calls this on every exit path."""
    if sl is None or sl.is_empty():
        return
    try:
        with _LOCK:
            _TOTALS["intervals"] += len(sl.intervals)
            _TOTALS["dropped"] += sl.dropped
            have = _QUERY_SLICES.get(query_id)
            if have is not None:
                merged = have.merge(sl)
                _QUERY_SLICES[query_id] = merged
                _QUERY_SLICES.move_to_end(query_id)
            else:
                _TOTALS["queries"] += 1
                merged = sl.copy()
                _QUERY_SLICES[query_id] = merged
                while len(_QUERY_SLICES) > _QUERY_SLICES_MAX:
                    old, _ = _QUERY_SLICES.popitem(last=False)
                    _QUERY_TRACE.pop(old, None)
            if trace_id:
                _QUERY_TRACE[query_id] = str(trace_id)
        # occupancy outside the lock: stored slices are replaced on
        # merge, never mutated, so reading `merged` unlocked is safe
        occ = occupancy(merged.intervals)
        if occ is not None:
            with _LOCK:
                _LAST.clear()
                _LAST.update({
                    "queryId": query_id,
                    "overlapFraction": occ["overlapFraction"],
                    "deviceIdleUs": occ["deviceIdleUs"]})
    except Exception as e:  # noqa: BLE001 - accounting must never
        # fail the query it observes
        try:
            from ..server.metrics import record_suppressed
            record_suppressed("timeline", "note_query", e)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


def timeline_for_query(query_id: str) -> dict:
    """One query's slice as raw local-clock rows plus its occupancy
    and verdict (flight dumps -- same-host post-mortem, where
    monotonic times align)."""
    with _LOCK:
        sl = _QUERY_SLICES.get(query_id)
        tid = _QUERY_TRACE.get(query_id, "")
    if sl is None:
        return {}
    occ = occupancy(sl.intervals)
    return {"intervals": sl.rows(), "dropped": sl.dropped,
            "totals": {h: dict(t) for h, t in sl.totals.items()},
            "occupancy": occ,
            "verdict": bubble_verdict(sl.intervals, occ),
            "traceId": tid}


def last_occupancy() -> dict:
    """The last finalized query's occupancy headline (metrics gauges,
    bench.py); {} until a query with intervals lands."""
    with _LOCK:
        return dict(_LAST)


def timeline_totals() -> Dict[str, int]:
    """Lifetime counters, stable zero shape from process start."""
    with _LOCK:
        return dict(_TOTALS)


def clear_timeline() -> None:
    """Drop the process registry + per-query slices (tests isolate
    state)."""
    with _LOCK:
        _QUERY_SLICES.clear()
        _QUERY_TRACE.clear()
        _LAST.clear()
        for k in _TOTALS:
            _TOTALS[k] = 0


# -- surfaces ------------------------------------------------------------


def _query_entry(sl: TimelineSlice, trace_id: str,
                 now: Optional[int] = None) -> dict:
    occ = occupancy(sl.intervals)
    return {"slice": sl.to_json(now),
            "occupancy": occ,
            "verdict": bubble_verdict(sl.intervals, occ),
            "traceId": trace_id}


def timeline_doc() -> dict:
    """This process's /v1/timeline slice: lifetime counters (zeros
    included -- the shape is stable from the first request on), the
    retained per-query slices (age-form intervals, skew-free) with
    per-query occupancy/verdicts, and the process-lifetime verdict
    over every retained interval."""
    with _LOCK:
        queries = {qid: sl for qid, sl in _QUERY_SLICES.items()}
        traces = dict(_QUERY_TRACE)
    ref = now_us()
    merged_all = TimelineSlice()
    for sl in queries.values():
        merged_all = merged_all.merge(sl)
    return {"processId": _PROCESS_ID,
            "totals": timeline_totals(),
            "queries": {qid: _query_entry(sl, traces.get(qid, ""),
                                          now=ref)
                        for qid, sl in queries.items()},
            "verdict": bubble_verdict(merged_all.intervals)}


def merge_timeline_docs(docs: List[dict],
                        now: Optional[int] = None) -> dict:
    """Fold per-process slices into one cluster view. Slices sharing
    a processId count once (two server shells over one process report
    the same registry); per-query slices merge by the slice law after
    rebasing their age-form intervals onto ONE receiver clock (worker
    slices of the SAME query stitch, skew-free by construction);
    totals sum; every occupancy/verdict is recomputed over the merged
    intervals -- order-independent throughout."""
    ref = now_us() if now is None else int(now)
    seen = set()
    queries: Dict[str, TimelineSlice] = {}
    traces: Dict[str, str] = {}
    totals = {k: 0 for k in ("intervals", "dropped", "queries",
                             "degraded")}
    for doc in docs:
        pid = doc.get("processId") or f"anon-{id(doc):x}"
        if pid in seen:
            continue
        seen.add(pid)
        for qid, entry in (doc.get("queries") or {}).items():
            sl = TimelineSlice.from_json(entry.get("slice") or {},
                                         now=ref)
            queries[qid] = queries[qid].merge(sl) if qid in queries \
                else sl
            if entry.get("traceId") and qid not in traces:
                traces[qid] = str(entry["traceId"])
        for k in totals:
            totals[k] += int((doc.get("totals") or {}).get(k, 0))
    merged_all = TimelineSlice()
    for sl in queries.values():
        merged_all = merged_all.merge(sl)
    return {"totals": totals,
            "queries": {qid: _query_entry(sl, traces.get(qid, ""),
                                          now=ref)
                        for qid, sl in queries.items()},
            "verdict": bubble_verdict(merged_all.intervals)}


def cluster_timeline_doc(worker_urls=(), timeout: float = 3.0) -> dict:
    """The coordinator-side merge: this process's slice plus every
    reachable worker's ``GET /v1/timeline``, folded per query by the
    slice law. Pulls ride the shared best-effort helper
    (server/client.pull_worker_docs) so bearer/TLS/trace headers --
    and the skip-and-count-dead-workers contract -- stay identical to
    the /v1/datapath and /v1/accuracy merges'."""
    from ..server.client import pull_worker_docs
    pulled, workers_seen = pull_worker_docs(
        worker_urls, timeout, lambda c: c.timeline(), "timeline")
    merged = merge_timeline_docs([timeline_doc(), *pulled])
    return {"processId": _PROCESS_ID, "cluster": True,
            "workersPulled": workers_seen, **merged}


def snapshot() -> List[dict]:
    """Per-(query, lane) occupancy rows across the retained queries
    (the system.occupancy table): insertion order by query, catalog
    order within one query."""
    # M001: one row per (retained query, catalog lane)
    _BOUNDED_BY = {"rows": "LRU-bounded queries x two lanes"}
    with _LOCK:
        queries = {qid: sl for qid, sl in _QUERY_SLICES.items()}
    rows = []
    for qid, sl in queries.items():
        occ = occupancy(sl.intervals)
        if occ is None:
            continue
        verdict = bubble_verdict(sl.intervals, occ)
        for lane in LANES:
            rows.append({
                "queryId": qid, "lane": lane,
                "busyUs": occ["lanes"][lane]["busyUs"],
                "busyFraction": occ["lanes"][lane]["busyFraction"],
                "wallUs": occ["wallUs"],
                "overlapFraction": occ["overlapFraction"],
                "deviceIdleUs": occ["deviceIdleUs"],
                "bubbleHop": verdict["hop"] if verdict else ""})
    return rows


def timeline_summary() -> dict:
    """The cheap statement-tier embed: lifetime interval counters and
    the last finalized query's occupancy headline -- no per-interval
    payload."""
    totals = timeline_totals()
    last = last_occupancy()
    return {"queries": totals["queries"],
            "intervals": totals["intervals"],
            "dropped": totals["dropped"],
            "overlapFraction": float(last.get("overlapFraction", 0.0)),
            "deviceIdleUs": int(last.get("deviceIdleUs", 0))}


# -- Chrome trace export -------------------------------------------------


def to_chrome_trace(doc: dict) -> dict:
    """A /v1/timeline document -> Chrome trace-event JSON (the
    Perfetto-loadable format): one ``pid`` per query, one ``tid`` per
    lane, one complete ``"ph": "X"`` span per interval, each span's
    ``args`` carrying the query's /v1/trace traceId (the cross-link).
    Age-form intervals rebase onto a shared zero so every ``ts`` is
    non-negative. Pure function of the document."""
    # M001: one event per shipped interval plus 3 metadata rows/query
    _BOUNDED_BY = {"events": "one span per interval in the document"}
    queries = doc.get("queries") or {}
    parsed = {}
    extent = 0
    for qid, entry in queries.items():
        sl = TimelineSlice.from_json(entry.get("slice") or {}, now=0)
        parsed[qid] = (sl, str(entry.get("traceId") or qid))
        for iv in sl.intervals:
            extent = max(extent, -iv.t0_us)
    events = []
    for pid, qid in enumerate(sorted(parsed), start=1):
        sl, tid = parsed[qid]
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": qid}})
        for li, lane in enumerate(LANES, start=1):
            events.append({"name": "thread_name", "ph": "M",
                           "pid": pid, "tid": li,
                           "args": {"name": lane}})
        for iv in sl.intervals:
            events.append({
                "name": iv.hop, "cat": iv.lane, "ph": "X",
                "ts": iv.t0_us + extent,
                "dur": max(iv.t1_us - iv.t0_us, 0),
                "pid": pid,
                "tid": LANES.index(iv.lane) + 1
                if iv.lane in LANES else 0,
                "args": {"queryId": qid, "traceId": tid,
                         "splitId": iv.split_id, "bytes": iv.bytes}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}
