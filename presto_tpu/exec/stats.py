"""RuntimeStats + structured query telemetry (OperatorStats/StageStats/
QueryStats).

Reference surface: presto-common's RuntimeStats (named add/merge
counters recorded anywhere and returned to clients in QueryStats), the
per-operator OperatorStats wall/cpu/rows plumbing (OperatorContext ->
TaskStats -> QueryStats merge chain), and the cross-worker merge the
coordinator performs when assembling QueryStats from TaskStatus.
Device-side per-operator timing inside one fused XLA program is not
observable (that's the point of fusion); stats here are the
host-visible boundaries: staging, XLA compile, device execute,
exchange pack/unpack, result fetch, rows/bytes -- the numbers EXPLAIN
ANALYZE, /v1/metrics, and the UI surface.

Structure:

  * ``RuntimeStats`` -- free-form named counters (unchanged API).
  * ``OperatorStats`` -- per plan node, where host-visible (scans,
    exchanges, the output root); fused interior nodes carry only
    rows when derivable.
  * ``StageStats`` -- one per host-visible stage boundary: ``staging``,
    ``compile`` (with FLOPs / bytes-accessed from XLA's
    ``cost_analysis``), ``execute``, ``exchange``, ``fetch``.
  * ``QueryStats`` -- the merge root shipped worker -> coordinator in
    TaskStatus and surfaced on the client protocol's ``stats`` field.

The merge law (``QueryStats.merge``) is associative AND commutative:
counters/sums add, ``max`` fields take max, stages/operators merge by
key. That is what lets per-task stats from any number of workers fold
in any order into one query-level document (the reference's
QueryStateMachine::updateQueryInfo aggregation contract).

Compile-time capture rides ``jax.monitoring``: a process-level listener
forwards ``/jax/core/compile/*`` event durations into the ambient
thread-local collector, so cache-hit dispatches naturally report zero
compile micros without instrumenting jit call sites.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from .accuracy import NodeAccuracy, merge_record_maps, \
    record_map_from_json, record_map_to_json
from .datapath import HopStats, hop_map_from_json, hop_map_to_json, \
    merge_hop_maps
from .timeline import TimelineSlice

__all__ = ["RuntimeStats", "timed", "OperatorStats", "StageStats",
           "QueryStats", "StatsCollector", "current_collector",
           "collecting"]


@dataclasses.dataclass
class _Stat:
    count: int = 0
    total: float = 0.0
    max: float = 0.0

    def add(self, v: float):
        self.count += 1
        self.total += v
        self.max = max(self.max, v)


class RuntimeStats:
    def __init__(self):
        self._stats: Dict[str, _Stat] = {}
        self._lock = threading.Lock()

    def add(self, name: str, value: float):
        with self._lock:
            self._stats.setdefault(name, _Stat()).add(value)

    def merge(self, other: "RuntimeStats"):
        # lock both sides (ordered by id to avoid deadlock): _Stat.add is
        # multi-field, so reading `other` unlocked could tear mid-update
        first, second = sorted((self._lock, other._lock), key=id)
        with first, second:
            for k, s in other._stats.items():
                mine = self._stats.setdefault(k, _Stat())
                mine.count += s.count
                mine.total += s.total
                mine.max = max(mine.max, s.max)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: {"count": s.count, "total": round(s.total, 6),
                        "max": round(s.max, 6)}
                    for k, s in self._stats.items()}

    def timed(self, name: str):
        return timed(self, name)


class timed:
    def __init__(self, stats: RuntimeStats, name: str):
        self.stats = stats
        self.name = name

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        self.stats.add(self.name, time.time() - self.t0)
        return False


# ---------------------------------------------------------------------------
# structured telemetry: OperatorStats / StageStats / QueryStats
# ---------------------------------------------------------------------------


def _us(seconds: float) -> int:
    return int(round(seconds * 1_000_000))


@dataclasses.dataclass
class OperatorStats:
    """Per-plan-node stats at the host-visible granularity (the
    OperatorStats analog; interior fused nodes carry rows only when the
    planner can derive them)."""
    node_id: str
    node_type: str = ""
    output_rows: int = 0
    output_bytes: int = 0
    wall_us: int = 0
    task_count: int = 1

    def merge(self, other: "OperatorStats") -> "OperatorStats":
        assert self.node_id == other.node_id, \
            f"merging operators {self.node_id} != {other.node_id}"
        return OperatorStats(
            node_id=self.node_id,
            node_type=self.node_type or other.node_type,
            output_rows=self.output_rows + other.output_rows,
            output_bytes=self.output_bytes + other.output_bytes,
            wall_us=self.wall_us + other.wall_us,
            task_count=self.task_count + other.task_count)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, doc: dict) -> "OperatorStats":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in known})


@dataclasses.dataclass
class StageStats:
    """One host-visible stage boundary: staging, compile, execute,
    exchange pack/unpack, fetch. ``flops``/``bytes_accessed`` come from
    XLA's ``cost_analysis`` of the jitted program (compile stage)."""
    name: str
    wall_us: int = 0
    compile_us: int = 0
    invocations: int = 0
    rows: int = 0
    bytes: int = 0
    flops: float = 0.0
    bytes_accessed: float = 0.0
    max_wall_us: int = 0

    def merge(self, other: "StageStats") -> "StageStats":
        assert self.name == other.name, \
            f"merging stages {self.name} != {other.name}"
        return StageStats(
            name=self.name,
            wall_us=self.wall_us + other.wall_us,
            compile_us=self.compile_us + other.compile_us,
            invocations=self.invocations + other.invocations,
            rows=self.rows + other.rows,
            bytes=self.bytes + other.bytes,
            flops=self.flops + other.flops,
            bytes_accessed=self.bytes_accessed + other.bytes_accessed,
            max_wall_us=max(self.max_wall_us, other.max_wall_us))

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, doc: dict) -> "StageStats":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in known})


@dataclasses.dataclass
class QueryStats:
    """The merge root: per-task stats fold into per-query stats through
    ``merge()`` (associative + commutative), shipped worker ->
    coordinator through the task status path and surfaced on the client
    protocol's ``stats`` field."""
    wall_us: int = 0
    output_rows: int = 0
    output_bytes: int = 0
    peak_memory_bytes: int = 0
    task_count: int = 1
    stages: Dict[str, StageStats] = dataclasses.field(default_factory=dict)
    operators: Dict[str, OperatorStats] = \
        dataclasses.field(default_factory=dict)
    # free-form summed counters (exchange collective counts noted at
    # trace time, cache hits, ...); merged by addition
    counters: Dict[str, int] = dataclasses.field(default_factory=dict)
    # per-hop data-path ledger (exec/datapath.py): bytes/wall per hop,
    # merged by HopStats' own sums-add/maxes-max law -- this is how a
    # worker's hop slice stitches to the coordinator's through the
    # existing task-status path
    datapath: Dict[str, HopStats] = dataclasses.field(default_factory=dict)
    # per-plan-node estimate-vs-actual ledger (exec/accuracy.py):
    # est/actual rows or bytes per node, merged by NodeAccuracy's own
    # estimates-max/rows-add/peaks-max law -- worker slices of one
    # query stitch to the coordinator's through the same path
    accuracy: Dict[str, NodeAccuracy] = \
        dataclasses.field(default_factory=dict)
    # per-query interval-ledger slice (exec/timeline.py): bounded
    # (lane, hop, split, t0, t1, bytes) records merged by the slice's
    # own union-and-truncate law; shipped cross-process as skew-free
    # ages, so a worker's slice stitches to the coordinator's without
    # clock-skew-negative intervals
    timeline: TimelineSlice = \
        dataclasses.field(default_factory=TimelineSlice)

    # -- convenience accessors (the EXPLAIN ANALYZE / CLI summary view) --

    def stage_us(self, name: str) -> int:
        s = self.stages.get(name)
        return s.wall_us if s else 0

    @property
    def compile_us(self) -> int:
        return sum(s.compile_us for s in self.stages.values())

    @property
    def execute_us(self) -> int:
        return self.stage_us("execute")

    def merge(self, other: "QueryStats") -> "QueryStats":
        stages = dict(self.stages)
        for k, s in other.stages.items():
            stages[k] = stages[k].merge(s) if k in stages else s
        operators = dict(self.operators)
        for k, o in other.operators.items():
            operators[k] = operators[k].merge(o) if k in operators else o
        counters = dict(self.counters)
        for k, v in other.counters.items():
            counters[k] = counters.get(k, 0) + v
        return QueryStats(
            wall_us=self.wall_us + other.wall_us,
            output_rows=self.output_rows + other.output_rows,
            output_bytes=self.output_bytes + other.output_bytes,
            peak_memory_bytes=max(self.peak_memory_bytes,
                                  other.peak_memory_bytes),
            task_count=self.task_count + other.task_count,
            stages=stages, operators=operators, counters=counters,
            datapath=merge_hop_maps(self.datapath, other.datapath),
            accuracy=merge_record_maps(self.accuracy, other.accuracy),
            timeline=self.timeline.merge(other.timeline))

    def to_json(self) -> dict:
        return {"wallUs": self.wall_us,
                "outputRows": self.output_rows,
                "outputBytes": self.output_bytes,
                "peakMemoryBytes": self.peak_memory_bytes,
                "taskCount": self.task_count,
                "stages": {k: s.to_json() for k, s in self.stages.items()},
                "operators": {k: o.to_json()
                              for k, o in self.operators.items()},
                "counters": dict(self.counters),
                "datapath": hop_map_to_json(self.datapath),
                "accuracy": record_map_to_json(self.accuracy),
                "timeline": self.timeline.to_json()}

    @classmethod
    def from_json(cls, doc: dict) -> "QueryStats":
        return cls(
            wall_us=int(doc.get("wallUs", 0)),
            output_rows=int(doc.get("outputRows", 0)),
            output_bytes=int(doc.get("outputBytes", 0)),
            peak_memory_bytes=int(doc.get("peakMemoryBytes", 0)),
            task_count=int(doc.get("taskCount", 1)),
            stages={k: StageStats.from_json(s)
                    for k, s in doc.get("stages", {}).items()},
            operators={k: OperatorStats.from_json(o)
                       for k, o in doc.get("operators", {}).items()},
            counters={k: int(v)
                      for k, v in doc.get("counters", {}).items()},
            datapath=hop_map_from_json(doc.get("datapath", {})),
            # old-doc tolerance: records shipped before this field
            # existed deserialize to the empty map (merge identity)
            accuracy=record_map_from_json(doc.get("accuracy", {})),
            # same tolerance: a missing timeline key is the empty
            # slice (merge identity), never an error
            timeline=TimelineSlice.from_json(doc.get("timeline", {})))

    def summary(self) -> str:
        """One-paragraph human summary (the CLI --stats shape)."""
        parts = [f"wall {self.wall_us / 1e6:.3f}s"]
        for name in ("staging", "compile", "execute", "exchange", "fetch"):
            us = self.stage_us(name)
            if us or name in self.stages:
                parts.append(f"{name} {us / 1e6:.3f}s")
        cu = self.compile_us
        if cu:
            parts.append(f"(xla compile {cu / 1e6:.3f}s)")
        parts.append(f"rows {self.output_rows}")
        parts.append(f"bytes {self.output_bytes}")
        if self.peak_memory_bytes:
            parts.append(f"peak mem {self.peak_memory_bytes >> 20}MB")
        if self.task_count > 1:
            parts.append(f"tasks {self.task_count}")
        return ", ".join(parts)


# ---------------------------------------------------------------------------
# ambient collector: stage spans + jax compile-time capture
# ---------------------------------------------------------------------------


class StatsCollector:
    """Per-query collection context. Stage timings are recorded as
    (start, end) spans so the tracer can render one span per stage;
    compile durations from jax.monitoring land on whichever stage is
    open when XLA compiles (the execute dispatch), attributed to the
    ``compile`` stage."""

    def __init__(self, query_id: str = "query"):
        self.query_id = query_id
        self.stats = QueryStats()
        self.spans: List[tuple] = []  # (stage, start_s, end_s, attrs)
        self._compile_s = 0.0
        self._lock = threading.Lock()

    # -- stage spans ----------------------------------------------------

    def stage(self, name: str, **fields):
        return _StageTimer(self, name, fields)

    def record_stage(self, name: str, start_s: float, end_s: float,
                     **fields) -> None:
        wall = _us(end_s - start_s)
        with self._lock:
            st = self.stats.stages.get(name)
            if st is None:
                st = self.stats.stages[name] = StageStats(name)
            st.wall_us += wall
            st.max_wall_us = max(st.max_wall_us, wall)
            st.invocations += 1
            for k, v in fields.items():
                setattr(st, k, getattr(st, k) + v)
            self.spans.append((name, start_s, end_s, dict(fields)))

    def bump_stage(self, name: str, **fields) -> None:
        """Add to a stage's summed fields without opening a timing span
        (rows/bytes learned after the span closed)."""
        with self._lock:
            st = self.stats.stages.get(name)
            if st is None:
                st = self.stats.stages[name] = StageStats(name)
            for k, v in fields.items():
                setattr(st, k, getattr(st, k) + v)

    def add_compile_seconds(self, seconds: float) -> None:
        with self._lock:
            self._compile_s += seconds

    def take_compile_us(self) -> int:
        """Drain accumulated jax compile time (monitoring events)."""
        with self._lock:
            us = _us(self._compile_s)
            self._compile_s = 0.0
            return us

    def stage_span_start(self, name: str) -> Optional[float]:
        """Start time of the most recent recorded span for `name`
        (anchors the synthetic compile span inside the execute window
        it actually happened in)."""
        with self._lock:
            for sname, start_s, _end, _attrs in reversed(self.spans):
                if sname == name:
                    return start_s
        return None

    def operator(self, node_id: str, node_type: str = "", **fields) -> None:
        with self._lock:
            op = self.stats.operators.get(node_id)
            if op is None:
                op = self.stats.operators[node_id] = \
                    OperatorStats(node_id, node_type)
            elif node_type and not op.node_type:
                op.node_type = node_type
            for k, v in fields.items():
                setattr(op, k, getattr(op, k) + v)

    def note(self, name: str, delta: int = 1) -> None:
        """Bump a free-form summed counter (QueryStats.counters)."""
        with self._lock:
            self.stats.counters[name] = \
                self.stats.counters.get(name, 0) + delta

    def emit_spans(self, trace_id: Optional[str] = None,
                   parent_id: Optional[str] = None) -> None:
        """Ship collected stage spans through the tracing emission seam
        (one span per stage boundary, each a child of `parent_id` --
        the enclosing task/query span). emit_span delivers to the
        process tracer AND any thread-local SpanBuffer, and never
        raises (broken tracers are counted, not fatal)."""
        from ..server.tracing import emit_span
        tid = trace_id or self.query_id
        for name, start_s, end_s, attrs in self.spans:
            emit_span(tid, f"stage.{name}", start_s, end_s,
                      {k: v for k, v in attrs.items()},
                      parent_id=parent_id)


class _StageTimer:
    def __init__(self, collector: StatsCollector, name: str, fields: dict):
        self.c = collector
        self.name = name
        self.fields = fields

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        self.c.record_stage(self.name, self.t0, time.time(), **self.fields)
        return False


_tls = threading.local()


def current_collector() -> Optional[StatsCollector]:
    return getattr(_tls, "collector", None)


class collecting:
    """Install `collector` as the ambient collector for this thread."""

    def __init__(self, collector: StatsCollector):
        self.collector = collector

    def __enter__(self):
        self.prev = current_collector()
        _tls.collector = self.collector
        _ensure_compile_listener()
        return self.collector

    def __exit__(self, *exc):
        _tls.collector = self.prev
        return False


_listener_installed = False
_listener_lock = threading.Lock()

# jax.monitoring duration events counted as XLA compilation work.
# Deliberately NOT a "/jax/core/compile/" prefix match: the
# jaxpr_trace_duration events fire NESTED inside MLIR lowering (inner
# jits trace while the outer lowers), so summing every event
# double-counts and compile_us can exceed the dispatch wall that
# contains it. MLIR module conversion + backend compile are the two
# sequential top-level phases; the runner additionally clamps the sum
# to the enclosing execute wall as a backstop against nested-jit
# lowering overlap.
_COMPILE_EVENTS = frozenset([
    "/jax/core/compile/jaxpr_to_mlir_module_duration",
    "/jax/core/compile/backend_compile_duration",
])


def _ensure_compile_listener() -> None:
    """Register the process-wide jax.monitoring listener exactly once.
    Durations route to the calling thread's ambient collector (jit
    compiles on the dispatching thread), so concurrent queries don't
    cross-attribute."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        try:
            import jax.monitoring as _mon

            def _on_duration(name, seconds, **_kw):
                if name not in _COMPILE_EVENTS:
                    return
                c = current_collector()
                if c is not None:
                    c.add_compile_seconds(float(seconds))

            _mon.register_event_duration_secs_listener(_on_duration)
        except Exception:  # noqa: BLE001 - telemetry must never break exec
            pass
        _listener_installed = True
