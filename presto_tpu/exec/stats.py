"""RuntimeStats: named counters threaded through query execution.

Reference surface: presto-common's RuntimeStats (named add/merge
counters recorded anywhere and returned to clients in QueryStats) and
the per-operator OperatorStats wall/cpu/rows plumbing
(OperatorContext). Device-side per-operator timing inside one fused XLA
program is not observable (that's the point of fusion); stats here are
the host-visible boundaries: staging, compile, execute, rows/bytes --
the numbers EXPLAIN ANALYZE and the UI surface.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict

__all__ = ["RuntimeStats", "timed"]


@dataclasses.dataclass
class _Stat:
    count: int = 0
    total: float = 0.0
    max: float = 0.0

    def add(self, v: float):
        self.count += 1
        self.total += v
        self.max = max(self.max, v)


class RuntimeStats:
    def __init__(self):
        self._stats: Dict[str, _Stat] = {}
        self._lock = threading.Lock()

    def add(self, name: str, value: float):
        with self._lock:
            self._stats.setdefault(name, _Stat()).add(value)

    def merge(self, other: "RuntimeStats"):
        # lock both sides (ordered by id to avoid deadlock): _Stat.add is
        # multi-field, so reading `other` unlocked could tear mid-update
        first, second = sorted((self._lock, other._lock), key=id)
        with first, second:
            for k, s in other._stats.items():
                mine = self._stats.setdefault(k, _Stat())
                mine.count += s.count
                mine.total += s.total
                mine.max = max(mine.max, s.max)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: {"count": s.count, "total": round(s.total, 6),
                        "max": round(s.max, 6)}
                    for k, s in self._stats.items()}

    def timed(self, name: str):
        return timed(self, name)


class timed:
    def __init__(self, stats: RuntimeStats, name: str):
        self.stats = stats
        self.name = name

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        self.stats.add(self.name, time.time() - self.t0)
        return False
