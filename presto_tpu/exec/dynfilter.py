"""Dynamic filtering: build-side join keys prune probe-side scans.

Reference surface: operator/DynamicFilterSourceOperator.java:50 (build
side collects its key values at runtime), sql/planner/
LocalDynamicFilter.java:44 (the collected domain pushed into the probe
side's scan), presto-expressions' DynamicFilters.

TPU-first placement: the payoff on this engine is at STAGING -- fewer
fact rows materialized into HBM (smaller static shapes = smaller
programs), not a per-row filter inside the fused plan (XLA would fuse
such a filter for free anyway, but by then the rows were already
staged). So the runner pre-executes small DIMENSION build sides
host-side, derives each probe key's domain (min/max plus an exact
value set when the build is small), and applies it to the fact scan's
host arrays BEFORE they are staged. Results are unchanged by
construction: only rows that cannot join are dropped, and only under
join types that do not preserve unmatched probe rows (INNER/RIGHT).
Counters (dynamic_filter_rows_pruned / dynamic_filters) surface
through EXPLAIN ANALYZE.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..plan import nodes as N

__all__ = ["collect_dynamic_filters", "apply_dynamic_filters"]

# builds estimated beyond this don't qualify (collection would rival
# the scan it prunes; join-max-broadcast-table-size spirit)
_MAX_BUILD_ROWS = 1 << 20
# exact-set filtering (isin) below this many distinct keys; above it
# min/max range pruning still applies
_SET_LIMIT = 1 << 16


def _strip_exchanges(node: N.PlanNode) -> N.PlanNode:
    while isinstance(node, N.ExchangeNode):
        node = node.source
    return node


def _is_dimension_subtree(node: N.PlanNode) -> bool:
    node = _strip_exchanges(node)
    if isinstance(node, N.TableScanNode):
        return True
    if isinstance(node, (N.FilterNode, N.ProjectNode)):
        return _is_dimension_subtree(node.source)
    return False


def _trace_to_scan(node: N.PlanNode, channel: int
                   ) -> Optional[Tuple[N.TableScanNode, int]]:
    """Like plan.stats.column_source, but returns the scan NODE (by
    identity) so the runner can target its staging."""
    from ..expr import ir as E
    if isinstance(node, N.TableScanNode):
        if 0 <= channel < len(node.columns):
            return node, channel
        return None
    if isinstance(node, N.ProjectNode):
        e = node.expressions[channel] \
            if 0 <= channel < len(node.expressions) else None
        if isinstance(e, E.InputReference):
            return _trace_to_scan(node.source, e.channel)
        return None
    if isinstance(node, (N.FilterNode, N.ExchangeNode)):
        # NOT SampleNode: Bernoulli sampling hashes the staged row
        # index, so pre-staging compaction would change which rows
        # survive the sample
        return _trace_to_scan(node.sources[0], channel)
    if isinstance(node, N.JoinNode):
        nleft = len(node.left.output_types())
        if channel < nleft:
            return _trace_to_scan(node.left, channel)
        return None  # build-side columns: a filter there has no fact win
    if isinstance(node, N.SemiJoinNode):
        n_src = len(node.source.output_types())
        if channel < n_src:
            return _trace_to_scan(node.source, channel)
        return None
    return None


def collect_dynamic_filters(root: N.PlanNode, sf: float,
                            ) -> Dict[str, List[Tuple[int, object]]]:
    """Find qualifying joins, EXECUTE their dimension build sides, and
    return {scan_node_id: [(scan_column_index, domain)]} where domain =
    (lo, hi, values-or-None). Joins qualify when the build is a small
    scan/filter/project subtree and the join type drops unmatched probe
    rows (INNER/RIGHT)."""
    from ..plan.stats import estimate_rows

    joins: List[N.JoinNode] = []
    seen: Dict[int, N.PlanNode] = {}
    parent_ids: Dict[int, set] = {}

    def walk(n: N.PlanNode):
        if id(n) in seen:
            return
        seen[id(n)] = n
        if isinstance(n, N.JoinNode):
            joins.append(n)
        for s in n.sources:
            parent_ids.setdefault(id(s), set()).add(id(n))
            walk(s)

    walk(root)

    def _single_consumer(scan: N.PlanNode, join: N.JoinNode) -> bool:
        """The pruned batch is keyed by scan id and shared by every
        reader (plan DAGs: CTE planned once); pruning is only safe when
        each node from the scan up to the join has exactly ONE parent,
        so no other branch reads the filtered rows."""
        cur = scan
        while cur is not join:
            parents = parent_ids.get(id(cur), set())
            if len(parents) != 1:
                return False
            cur = seen[next(iter(parents))]
        return True
    out: Dict[str, List[Tuple[int, object]]] = {}
    for j in joins:
        if j.join_type not in ("inner", "right"):
            continue
        build = _strip_exchanges(j.right)
        if not _is_dimension_subtree(build):
            continue
        est = estimate_rows(build, sf)
        if est is None or est > _MAX_BUILD_ROWS:
            continue
        targets = []
        for probe_ch, build_ch in zip(j.left_keys, j.right_keys):
            hit = _trace_to_scan(j.left, probe_ch)
            ty = build.output_types()[build_ch]
            if hit is None or not (ty.is_integral or ty.is_decimal
                                   or ty.base == "date"):
                continue
            if not _single_consumer(hit[0], j):
                continue
            targets.append((hit, build_ch))
        if not targets:
            continue
        domains = _build_domains(build, sf, [bc for _, bc in targets])
        if domains is None:
            continue
        for (scan, scan_col), dom in zip((t[0] for t in targets), domains):
            if dom is not None:
                out.setdefault(scan.id, []).append((scan_col, dom))
    return out


def _build_domains(build: N.PlanNode, sf: float, channels: List[int]):
    """Run the dimension subtree and pull the key domains to host."""
    import jax

    from ..block import to_numpy
    from .planner import compile_plan

    try:
        plan = compile_plan(build)
        from .runner import _scan_batch
        batches = [_scan_batch(s, sf, None, 8) for s in plan.scan_nodes]
        out, _flags = jax.jit(plan.fn)(batches)
    except Exception:  # noqa: BLE001 - collection is best-effort
        return None
    act = np.asarray(out.active)
    domains = []
    for ch in channels:
        vals, nulls = to_numpy(out.column(ch))
        live = act & ~nulls
        v = vals[live]
        if v.dtype == object:  # long decimals: python ints
            v = np.array([int(x) for x in v], dtype=np.float64)
        if len(v) == 0:
            domains.append((0, -1, np.array([], dtype=np.int64)))
            continue
        uniq = np.unique(v)
        domains.append((v.min(), v.max(),
                        uniq if len(uniq) <= _SET_LIMIT else None))
    return domains


def apply_dynamic_filters(arrays: Dict[str, np.ndarray],
                          columns: List[str],
                          filters: List[Tuple[int, object]],
                          ) -> Tuple[np.ndarray, int]:
    """Row mask for one scan's host arrays under its collected domains.
    Returns (keep_mask, pruned_count)."""
    n = len(arrays[columns[0]])
    keep = np.ones(n, dtype=bool)
    for col_idx, (lo, hi, values) in filters:
        v = arrays[columns[col_idx]]
        if v.dtype == object:
            v = np.array([int(x) for x in v], dtype=np.float64)
        keep &= (v >= lo) & (v <= hi)
        if values is not None:
            keep &= np.isin(v, values)
    return keep, int(n - keep.sum())
