"""Spillable aggregation and join build: host-DRAM offload when state
exceeds an HBM budget.

Reference surface: the revocable-memory spill stack --
operator/aggregation/builder/SpillableHashAggregationBuilder.java:46
(partial group tables spilled when memory is revoked),
operator/HashBuilderOperator.java:166-186 (join build spill states),
presto-main/.../execution/MemoryRevokingScheduler.java (revocation
trigger), spiller/GenericPartitioningSpiller (hash-partitioned spill
files re-read partition by partition).

TPU redesign: the spill tier is HOST DRAM (BASELINE config 5 targets
host-spill, not disk), and the unit of spilling is a GROUPED-EXECUTION
BUCKET rather than an arbitrary page run: inputs hash-partition on the
aggregation/join keys into B buckets whose states are disjoint, the
device processes one bucket at a time, and each completed bucket's
output is COMPACTED to live rows host-side and kept in host memory.
That makes spilling restart-free -- no re-merge of spilled runs is ever
needed, because bucket states never interleave (the property
GenericPartitioningSpiller's partitioned files approximate on disk).

B is sized from the budget: B = ceil(2 * planned_state_bytes / budget)
(two tables coexist during the running merge). Spill movement is
counted in RuntimeStats over COMPACTED row bytes (spilled_bytes /
spill_buckets -- EXPLAIN ANALYZE surfaces them, the reference's
spilledDataSize analog).
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import failpoints
from .. import types as T
from ..block import Batch, batch_from_numpy, to_numpy
from ..connectors import catalog
from ..ops.aggregation import finalize_states
from ..plan import nodes as N
from .planner import compile_plan
from .stats import RuntimeStats

__all__ = ["plan_state_bytes", "run_spilled_agg", "run_spilled_join",
           "spill_bucket_count"]


def _type_bytes(ty: T.Type) -> int:
    """Per-row device bytes of one output column (values + null mask)."""
    if ty.is_string:
        return 64 + 4 + 1  # char matrix row (typical width) + len + null
    if ty.is_decimal and not ty.is_short_decimal:
        return 16 + 1
    try:
        return np.dtype(ty.to_dtype()).itemsize + 1
    except Exception:  # noqa: BLE001 - exotic types: assume wide
        return 17


def plan_state_bytes(agg: N.AggregationNode) -> int:
    """Planned footprint of the aggregation's dense state table."""
    return agg.max_groups * sum(_type_bytes(t) for t in agg.output_types())


def spill_bucket_count(state_bytes: int, hbm_budget_bytes: int) -> int:
    """Buckets needed so ~two bucket tables fit the budget."""
    return max(1, math.ceil(2 * state_bytes / max(hbm_budget_bytes, 1)))


_CPU = None


def _cpu_device():
    global _CPU
    if _CPU is None:
        _CPU = jax.devices("cpu")[0]
    return _CPU


class _HostRows:
    """Compacted host staging: live rows only, as numpy arrays (the
    first spill medium). Appending pulls the batch's ACTIVE rows
    off-device; `to_batch` re-stages them as one padded Batch.

    Disk tier (FileSingleStreamSpiller / TempStorage analog): with a
    `disk_dir`, accumulated host chunks flush to .npz run files once
    they exceed `disk_threshold_bytes`, bounding host DRAM too; reads
    re-load the runs in order. Bucket states are disjoint (module
    docstring), so runs concatenate -- no merge pass."""

    def __init__(self, types: List[T.Type], disk_dir: Optional[str] = None,
                 disk_threshold_bytes: int = 256 << 20):
        self.types = types
        self._cols: List[List[np.ndarray]] = [[] for _ in types]
        self._nulls: List[List[np.ndarray]] = [[] for _ in types]
        self.rows = 0
        self.bytes = 0
        self._mem_bytes = 0
        self.disk_dir = disk_dir
        self.disk_threshold = disk_threshold_bytes
        self._runs: List[str] = []  # flushed .npz paths, in order
        self.disk_bytes = 0

    def append(self, batch: Batch, stats: Optional[RuntimeStats]):
        act = np.asarray(batch.active)
        sel = np.nonzero(act)[0]
        self.rows += len(sel)
        moved = 0
        for c in range(len(self.types)):
            v, nl = to_numpy(batch.column(c))
            v, nl = v[sel], nl[sel]
            self._cols[c].append(v)
            self._nulls[c].append(nl)
            moved += (v.nbytes if v.dtype != object else 32 * len(v)) \
                + nl.nbytes
        self.bytes += moved
        self._mem_bytes += moved
        if stats is not None:
            stats.add("spilled_bytes", moved)
        if self.disk_dir is not None and \
                self._mem_bytes >= self.disk_threshold:
            self._flush_run(stats)

    def _flush_run(self, stats: Optional[RuntimeStats]):
        import os
        import uuid as _uuid
        if self.rows == 0 or not self._cols[0]:
            return
        if failpoints.ARMED:
            # a full/broken spill disk at run-flush time
            failpoints.hit("spill.write")
        os.makedirs(self.disk_dir, exist_ok=True)
        path = os.path.join(self.disk_dir,
                            f"spill_{_uuid.uuid4().hex[:12]}.npz")
        payload = {}
        for c in range(len(self.types)):
            payload[f"v{c}"] = np.concatenate(self._cols[c]) \
                if self._cols[c] else np.array([], dtype=object)
            payload[f"n{c}"] = np.concatenate(self._nulls[c]) \
                if self._nulls[c] else np.array([], dtype=bool)
            self._cols[c] = []
            self._nulls[c] = []
        np.savez(path, **{k: v for k, v in payload.items()})
        self._runs.append(path)
        written = os.path.getsize(path)
        self.disk_bytes += written
        self._mem_bytes = 0
        if stats is not None:
            stats.add("spilled_to_disk_bytes", written)
            stats.add("spill_run_files", 1)

    def columns(self) -> Tuple[List[np.ndarray], List[np.ndarray]]:
        cols_runs: List[List[np.ndarray]] = [[] for _ in self.types]
        nulls_runs: List[List[np.ndarray]] = [[] for _ in self.types]
        if failpoints.ARMED and self._runs:
            # a run file that rotted/vanished between write and re-read
            failpoints.hit("spill.read")
        for path in self._runs:
            with np.load(path, allow_pickle=True) as z:
                for c in range(len(self.types)):
                    cols_runs[c].append(z[f"v{c}"])
                    nulls_runs[c].append(z[f"n{c}"])
        for c in range(len(self.types)):
            cols_runs[c].extend(self._cols[c])
            nulls_runs[c].extend(self._nulls[c])
        cols = [np.concatenate(c) if c else np.array([], dtype=object)
                for c in cols_runs]
        nulls = [np.concatenate(n) if n else np.array([], dtype=bool)
                 for n in nulls_runs]
        return cols, nulls

    def close(self):
        import os
        for path in self._runs:
            try:
                os.unlink(path)
            except OSError:
                pass
        self._runs = []

    def to_batch(self, capacity: Optional[int] = None,
                 on_host: bool = False) -> Batch:
        cols, nulls = self.columns()
        cap = capacity or max(8, -(-self.rows // 8) * 8)
        if on_host:
            with jax.default_device(_cpu_device()):
                return batch_from_numpy(self.types, cols, nulls=nulls,
                                        capacity=cap)
        return batch_from_numpy(self.types, cols, nulls=nulls, capacity=cap)


def run_spilled_agg(root: N.PlanNode, sf: float, split_rows: int,
                    hbm_budget_bytes: int,
                    stats: Optional[RuntimeStats] = None,
                    spill_dir: Optional[str] = None,
                    spill_file_threshold: int = 256 << 20) -> Batch:
    """Streamable aggregation whose state table exceeds the HBM budget:
    grouped execution with per-bucket host offload. The bucket executor
    compiles ONCE (bucket id is a traced scalar); each finished
    bucket's FINALIZED, compacted rows move to host DRAM before the
    next lifespan starts. Returns the result as one host-resident
    Batch."""
    from .streaming import _make_agg_executor, streamable_agg_shape

    shape = streamable_agg_shape(root)
    assert shape is not None, "plan is not a streamable aggregation"
    agg, _scan = shape
    state_bytes = plan_state_bytes(agg)
    n_buckets = spill_bucket_count(state_bytes, hbm_budget_bytes)
    # per-bucket capacity: groups hash-partition about evenly; 2x slack
    # absorbs skew, and the overflow flag still guards correctness
    bucket_groups = max(64, -(-2 * agg.max_groups // n_buckets))
    import dataclasses as _dc
    agg_b = _dc.replace(agg, max_groups=bucket_groups)
    root_b = _rebuild_above(root, agg, agg_b)

    nkeys = len(agg.group_channels)
    runner = _make_agg_executor(root_b, sf, split_rows, n_buckets)
    staged: Optional[_HostRows] = None
    try:
        for b in range(n_buckets):
            r = runner(b)
            if bool(np.asarray(r.overflow)):
                raise RuntimeError(
                    f"spilled aggregation bucket {b} overflowed its "
                    f"{bucket_groups}-group table; raise max_groups")
            out = finalize_states(r.batch, nkeys, agg.aggregates)
            if staged is None:
                staged = _HostRows(
                    [c.type for c in out.columns], disk_dir=spill_dir,
                    disk_threshold_bytes=spill_file_threshold)
            staged.append(out, stats)
            if stats is not None:
                stats.add("spill_buckets", 1)
        return staged.to_batch(on_host=True)
    finally:
        # run files must not outlive the query, success OR failure (a
        # mid-loop overflow raise would otherwise leak every flushed run)
        if staged is not None:
            staged.close()


def _rebuild_above(root: N.PlanNode, old: N.PlanNode,
                   new: N.PlanNode) -> N.PlanNode:
    """Replace `old` (by identity) with `new` in a linear wrapper
    chain."""
    import dataclasses as _dc
    if root is old:
        return new
    assert len(root.sources) == 1, "expected a linear chain"
    return _dc.replace(root, source=_rebuild_above(root.source, old, new))


# ---------------------------------------------------------------------------
# Spillable join build (bucketed partitioned join)
# ---------------------------------------------------------------------------


def _linear_scan(node: N.PlanNode) -> N.TableScanNode:
    cur = node
    while isinstance(cur, (N.FilterNode, N.ProjectNode)):
        cur = cur.source
    assert isinstance(cur, N.TableScanNode), \
        "spilled join streams scan-rooted pipelines"
    return cur


def run_spilled_join(join: N.JoinNode, sf: float, split_rows: int,
                     hbm_budget_bytes: int,
                     stats: Optional[RuntimeStats] = None,
                     out_capacity_per_bucket: Optional[int] = None
                     ) -> Batch:
    """Join two scan-rooted pipelines under a capped HBM budget:

      1. stream BOTH sides split by split; each split's rows
         hash-partition on their join keys and append -- COMPACTED, as
         host numpy arrays -- to per-bucket host staging (the build-side
         spill: every row leaves HBM before the join runs;
         HashBuilderOperator's INPUT_SPILLED state)
      2. per bucket: restage ONLY that bucket's rows into HBM, join,
         and move the compacted result back to host
         (LOOKUP_SOURCE_UNSPILLED: bucket-at-a-time restore)

    Peak HBM = one split batch during partitioning, then one bucket
    pair + its join output. Bucket count is sized so a bucket pair
    fits the budget."""
    from ..ops.join import hash_join
    from ..parallel.exchange import _row_hash
    from functools import partial

    sides = []
    for node, keys in ((join.left, join.left_keys),
                       (join.right, join.right_keys)):
        scan = _linear_scan(node)
        pipeline = compile_plan(node)
        conn = catalog(scan.connector)
        total = conn.table_row_count(scan.table, sf)
        row_bytes = sum(_type_bytes(t) for t in node.output_types())
        sides.append((node, keys, scan, pipeline, conn, total, row_bytes))

    total_bytes = sum(t * rb for *_x, t, rb in sides)
    n_buckets = max(1, math.ceil(3 * total_bytes / max(hbm_budget_bytes, 1)))

    @partial(jax.jit, static_argnums=1)
    def _bucket_of(batch: Batch, key_channels: Tuple[int, ...]):
        h = _row_hash([batch.column(c) for c in key_channels])
        return (h % jnp.uint64(n_buckets)).astype(jnp.int32)

    # phase 1: partition both sides into compacted host bucket staging
    host_buckets: List[List[_HostRows]] = []
    for si, (node, keys, scan, pipeline, conn, total, _rb) in enumerate(sides):
        tys = node.output_types()
        buckets = [_HostRows(tys) for _ in range(n_buckets)]
        host_buckets.append(buckets)
        from .runner import stage_scan_split
        for start in range(0, max(total, 1), split_rows):
            count = min(split_rows, max(total - start, 0))
            # shared narrow-width staging (honors physical_dtypes)
            batch = stage_scan_split(conn, scan, sf, start, count,
                                     split_rows)
            out, _ovf = pipeline.fn((batch,))
            bid = _bucket_of(out, tuple(keys))
            for b in range(n_buckets):
                buckets[b].append(
                    out.with_active(out.active & (bid == b)), stats)
        if stats is not None:
            stats.add("spill_buckets", n_buckets)

    # phase 2: bucket-at-a-time join on device
    result: Optional[_HostRows] = None
    for b in range(n_buckets):
        probe = host_buckets[0][b].to_batch()   # restore into HBM
        build = host_buckets[1][b].to_batch()
        cap = out_capacity_per_bucket or \
            4 * max(probe.capacity, build.capacity)
        r = hash_join(probe, build, join.left_keys, join.right_keys,
                      cap, join.join_type, join.right_output_channels)
        if bool(np.asarray(r.overflow)):
            raise RuntimeError(
                f"spilled join bucket {b} overflowed out_capacity {cap}; "
                "raise out_capacity_per_bucket")
        if result is None:
            result = _HostRows([c.type for c in r.batch.columns])
        result.append(r.batch, stats)
        if stats is not None:
            stats.add("spill_buckets", 1)
    return result.to_batch(on_host=True)
