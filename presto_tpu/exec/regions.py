"""Pipeline-region fusion compiler: plan -> regions, each ONE program.

Reference surface: Flare's whole-stage native compilation (one
generated pipeline per stage instead of one operator at a time) and
SystemML's cost-based operator-fusion-plan selection -- choose WHAT to
fuse and WHERE to materialize from measured costs, not heuristics.

A *pipeline region* is a maximal chain of plan operators staged as one
XLA program: scan -> filter -> project -> partial-agg bodies, and the
exchange-adjacent final-agg -> project -> limit/sort tails, fuse into
single jitted executables; region boundaries are materialized Batch
handoffs in HBM (no host round trip). With fusion ON (the default) a
whole local fragment is normally ONE region -- exactly the fused
whole-fragment program the engine has always staged, now as the
1-region special case of the general executor. The partitioner splits
a would-be region only for CAUSE:

  * **footprint refusal** -- a fusion whose estimated peak intermediate
    exceeds ``kernel_audit_budget_bytes`` is rejected: the static
    estimate (row estimates x output widths, the planner-side
    approximation of kernaudit K005's liveness walk) gates at
    partition time, and the REAL K005 estimate -- fed back per region
    fingerprint whenever the staging-time auditor runs -- overrides
    the estimate on the next submission of the same region.
  * **profiler demotion** -- a region whose fused per-dispatch device
    time regresses beyond the perfgate noise band vs the recorded
    materialized (per-operator) execution of the same span is demoted
    back to materialized boundaries. Both sides of the comparison come
    from the continuous profiler's device-time samples folded into
    :class:`FusionMemory`; the band math is exec/perfgate.py's --
    the ONE regression comparator this repo allows.
  * **fusion off** -- ``fusion`` session property / ``PRESTO_TPU_FUSION=0``
    (registered in KERNEL_MODE_ENVS) runs one region per operator: the
    A/B + bisection mode, and the baseline the demotion contract
    compares against.

Seam invariants (the partition law tests pin): region boundaries sit
EXACTLY at the engine's materialization seams and never inside them --

  * scan/values/remote-source leaves are region INPUTS, never regions;
  * a meshed (SPMD) plan is always one region: its REMOTE exchanges
    lower to collectives gang-scheduled inside one shard_map program,
    and splitting would materialize exchange state host-side
    (parallel/stages.py keeps its contract);
  * the streaming/spill executors (exec/streaming.py, exec/spill.py)
    take over BEFORE region partitioning -- their split-by-split
    programs are their own pipeline form;
  * write/DDL roots re-enter run_query for their inner SELECT, which
    is where partitioning happens.

Region identity: each region's root is a standalone plan tree (cut
children replaced by RemoteSourceNode leaves), so its plan-cache
fingerprint derives from the ORIGINAL plan's structure restricted to
the region span -- a single-region plan keeps the existing whole-plan
fingerprint unchanged, which is what keeps the profiler registry, the
query-history archive and the kernaudit memo keyed exactly as before
this refactor.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Dict, List, Optional

from ..plan import nodes as N
from ..utils.locks import OrderedLock
from .perfgate import MetricSpec, compare

__all__ = ["FUSION_ENV", "fusion_enabled", "RegionInput", "PipelineRegion",
           "RegionPlan", "partition_regions", "fusion_memory",
           "FusionMemory", "estimate_node_bytes"]

FUSION_ENV = "PRESTO_TPU_FUSION"

_LEAF_TYPES = (N.TableScanNode, N.ValuesNode, N.RemoteSourceNode)


def fusion_enabled(session) -> bool:
    """Session property ``fusion``; process default from
    PRESTO_TPU_FUSION (default ON). Spelled literally so tpulint R001
    proves the knob is registered in KERNEL_MODE_ENVS."""
    import os
    env_on = os.environ.get("PRESTO_TPU_FUSION", "1") \
        not in ("0", "", "false")
    from ..utils.config import session_flag
    return session_flag(session, "fusion", env_on)


@dataclasses.dataclass
class RegionInput:
    """One positional input of a region's compiled program, in the
    planner's scan-collection (DFS preorder, identity-deduped) order.
    ``kind="scan"``: `node` is the ORIGINAL plan leaf (stage its batch
    once, by identity). ``kind="region"``: the batch is the output of
    `region` (an upstream PipelineRegion index)."""
    kind: str
    node: Optional[N.PlanNode] = None
    region: int = -1


@dataclasses.dataclass
class PipelineRegion:
    """One fused chain, lowered to ONE program by exec/planner.py."""
    index: int
    root: N.PlanNode           # standalone subtree (cuts = RemoteSource)
    inputs: List[RegionInput]  # positional, planner scan order
    span: str                  # node-chain label (provenance surfaces)
    ops: int                   # fused operator count (non-leaf nodes)
    reason: str                # why this region ends where it does
    est_peak_bytes: int        # static intermediate-footprint estimate

    @property
    def tag(self) -> str:
        return f"R{self.index}"


@dataclasses.dataclass
class RegionPlan:
    root: N.PlanNode
    regions: List[PipelineRegion]   # topological: producers first
    node_region: Dict[int, int]     # id(original node) -> region index
    fused: bool                     # fusion was in force


# ---------------------------------------------------------------------------
# cost model inputs
# ---------------------------------------------------------------------------


def _row_width_bytes(types) -> int:
    """Bytes per row of a node's output at the declared (logical)
    widths + the active/null lanes -- the same shape arithmetic as
    runner._planned_scan_bytes."""
    per_row = 1  # active mask
    for ty in types:
        if ty.is_string:
            per_row += (ty.max_length if ty.max_length < 1 << 20 else 64) + 5
        elif ty.is_decimal and not ty.is_short_decimal:
            per_row += 17  # int128 lanes: hi + lo + null
        else:
            try:
                per_row += ty.to_dtype().itemsize + 1
            except Exception:  # noqa: BLE001 - exotic logical type
                per_row += 9
    return per_row


def estimate_node_bytes(node: N.PlanNode, sf: float) -> int:
    """Static estimate of one operator's materialized output: the
    optimizer row estimate x logical row width. This is the
    partition-time stand-in for kernaudit K005's liveness-walk peak --
    conservative (block capacities pad upward, narrowed lanes shrink
    real bytes) and cheap (no tracing)."""
    from ..plan.stats import estimate_rows
    rows = None
    try:
        rows = estimate_rows(node, sf)
    except Exception:  # noqa: BLE001 - estimates are best-effort
        rows = None
    if rows is None:
        for s in node.sources:
            try:
                child = estimate_rows(s, sf)
            except Exception:  # noqa: BLE001
                child = None
            if child is not None:
                rows = max(rows or 0.0, child)
    if rows is None:
        rows = 1024.0
    try:
        width = _row_width_bytes(node.output_types())
    except Exception:  # noqa: BLE001 - INTERMEDIATE agg state types etc.
        width = 64
    return int(rows) * width


# ---------------------------------------------------------------------------
# fusion memory: measured costs per region fingerprint
# ---------------------------------------------------------------------------


class FusionMemory:
    """Process-wide feedback store for fusion-plan choice.

    Keyed by region fingerprint (exec/plan_cache.plan_fingerprint of
    the region root -- the same identity the executable cache, the
    profiler registry and the kernaudit memo use):

      * ``note_footprint``: kernaudit K005's measured peak-intermediate
        estimate (max over audits); the partitioner prefers it over the
        static estimate when refusing over-budget fusions.
      * ``note_fused`` / ``note_unfused``: per-dispatch device-time
        samples of the FUSED region vs the MATERIALIZED (per-operator)
        execution of the same span (the runner feeds both; the unfused
        side keys on the fingerprint the span WOULD fuse to, so the
        pair compares like for like).
      * ``maybe_demote``: perfgate-band comparison -- a warmed fused
        median regressing beyond the band vs the warmed unfused median
        demotes the fingerprint; demoted regions partition with
        materialized boundaries until the process restarts or
        ``clear()`` (tests, plan-cache clears).

    Bounded maps + bounded sample windows; lock-guarded (the runner's
    hot path appends one sample per dispatch)."""

    _WINDOW = 16
    _MAX_KEYS = 512
    # tpulint C001: the runner's hot path appends samples from every
    # dispatch thread; the partitioner reads across them
    _GUARDED_BY = {"_lock": ("_footprint", "_fused", "_unfused",
                             "_demoted")}
    # device time regresses upward; a fused region must beat its
    # materialized form by more than noise + 10% before demotion is
    # even considered, and micro-kernels under 200us never demote
    # (dispatch jitter dominates them)
    SPEC = MetricSpec("region_device_us", higher_is_worse=True,
                      rel_threshold=0.10, abs_floor=200.0, mad_k=5.0)
    MIN_SAMPLES = 3

    def __init__(self):
        self._lock = OrderedLock("regions.FusionMemory._lock")
        self._footprint: "collections.OrderedDict[str, int]" = \
            collections.OrderedDict()
        self._fused: "collections.OrderedDict[str, collections.deque]" = \
            collections.OrderedDict()
        self._unfused: "collections.OrderedDict[str, collections.deque]" = \
            collections.OrderedDict()
        self._demoted: Dict[str, str] = {}

    def _bump(self, table, key, value) -> None:
        q = table.get(key)
        if q is None:
            q = table[key] = collections.deque(maxlen=self._WINDOW)
            while len(table) > self._MAX_KEYS:
                table.popitem(last=False)
        else:
            table.move_to_end(key)
        q.append(float(value))

    def note_footprint(self, fingerprint: str, peak_bytes: int) -> None:
        with self._lock:
            have = self._footprint.get(fingerprint, 0)
            self._footprint[fingerprint] = max(have, int(peak_bytes))
            self._footprint.move_to_end(fingerprint)
            while len(self._footprint) > self._MAX_KEYS:
                self._footprint.popitem(last=False)

    def footprint(self, fingerprint: str) -> int:
        with self._lock:
            return self._footprint.get(fingerprint, 0)

    def note_fused(self, fingerprint: str, device_us: int) -> None:
        with self._lock:
            self._bump(self._fused, fingerprint, device_us)

    def note_unfused(self, fingerprint: str, device_us: int) -> None:
        with self._lock:
            self._bump(self._unfused, fingerprint, device_us)

    def demoted(self, fingerprint: str) -> Optional[str]:
        with self._lock:
            return self._demoted.get(fingerprint)

    def demote(self, fingerprint: str, reason: str) -> None:
        with self._lock:
            self._demoted[fingerprint] = reason
            while len(self._demoted) > self._MAX_KEYS:
                self._demoted.pop(next(iter(self._demoted)))

    def maybe_demote(self, fingerprint: str) -> Optional[dict]:
        """Compare the fused region's device-time samples against the
        materialized baseline; on a band breach, demote and return the
        verdict (None otherwise). Pure perfgate math -- no clocks."""
        with self._lock:
            if fingerprint in self._demoted:
                return None
            fused = list(self._fused.get(fingerprint) or ())
            base = list(self._unfused.get(fingerprint) or ())
        if len(fused) < self.MIN_SAMPLES or len(base) < self.MIN_SAMPLES:
            return None
        from .perfgate import median
        verdict = compare(median(fused), base, self.SPEC)
        if verdict is None:
            return None
        self.demote(fingerprint, f"device_us {verdict['value']:.0f} vs "
                                 f"materialized median {verdict['median']:.0f}"
                                 f" (band {verdict['band']:.0f})")
        return verdict

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "demoted": dict(self._demoted),
                "footprints": dict(self._footprint),
                "fused_keys": len(self._fused),
                "unfused_keys": len(self._unfused),
            }

    def clear(self) -> None:
        with self._lock:
            self._footprint.clear()
            self._fused.clear()
            self._unfused.clear()
            self._demoted.clear()


def estimate_region_bytes(region: "PipelineRegion",
                          sf: float = 0.01) -> int:
    """Static peak estimate of a carved region, computed on demand
    (partitioning only pays the estimate walk when a budget is set;
    EXPLAIN's region tail asks lazily)."""
    if region.est_peak_bytes:
        return region.est_peak_bytes
    total = 0

    def walk(n):
        nonlocal total
        if not isinstance(n, _LEAF_TYPES):
            total += estimate_node_bytes(n, sf)
        for s in n.sources:
            walk(s)

    walk(region.root)
    return total


_MEMORY = FusionMemory()


def fusion_memory() -> FusionMemory:
    return _MEMORY


# ---------------------------------------------------------------------------
# partitioning
# ---------------------------------------------------------------------------


def _audit_budget(session) -> int:
    from ..audit.staged import _budget
    return _budget(session)


def partition_regions(root: N.PlanNode, *, session=None, sf: float = 0.01,
                      mesh=None, force_per_op: bool = False) -> RegionPlan:
    """Partition a PREPARED plan into pipeline regions (see module
    docstring for the grammar). Deterministic for a given (plan,
    session, kernel mode, FusionMemory state)."""
    fused = fusion_enabled(session) and not force_per_op
    single = mesh is not None       # SPMD programs stay whole
    per_op = not fused and not single
    budget = _audit_budget(session) if not single else 0

    regions: List[PipelineRegion] = []
    node_region: Dict[int, int] = {}
    carved: Dict[int, int] = {}     # id(original subtree root) -> region
    est_memo: Dict[int, int] = {}

    def est(n: N.PlanNode) -> int:
        if id(n) not in est_memo:
            est_memo[id(n)] = estimate_node_bytes(n, sf)
        return est_memo[id(n)]

    def fp_of(region_root: N.PlanNode) -> str:
        from .plan_cache import plan_fingerprint
        return plan_fingerprint(region_root)

    def carve(n: N.PlanNode, materialize_root: bool = False,
              cause: str = "") -> int:
        """Carve the region producing `n`'s output; returns its index.
        `materialize_root=True` re-carves a demoted/refused span: `n`
        runs alone (`cause` says why) and its children re-enter fusion
        independently."""
        if id(n) in carved and not materialize_root:
            return carved[id(n)]

        nodes: List[N.PlanNode] = []
        inputs: List[RegionInput] = []
        seen_leaves: Dict[int, None] = {}
        est_sum = [0]
        reasons: List[str] = []

        def absorb(parent: N.PlanNode, m: N.PlanNode) -> bool:
            """Whether child chain `m` fuses into `parent`'s region."""
            if single:
                return True
            if isinstance(parent, N.OutputNode):
                # Output is a pure rename -- never a region of its own
                return True
            if isinstance(m, N.ExchangeNode):
                # a single-chip ExchangeNode lowers to a no-op: it is
                # transparent (rides with its consumer) and ITS child
                # decides the real cut on the next absorb call
                return True
            if materialize_root or per_op:
                return False
            if budget > 0 and est_sum[0] + est(m) > budget:
                reasons.append("budget")
                return False
            return True

        def rebuild(m: N.PlanNode) -> N.PlanNode:
            nodes.append(m)
            node_region[id(m)] = len(regions)  # provisional; fixed below
            if budget > 0:  # estimates are only consulted by the
                est_sum[0] += est(m)  # budget rule; skip the walk otherwise
            new_sources: List[N.PlanNode] = []
            changed = False
            for c in m.sources:
                if isinstance(c, _LEAF_TYPES):
                    if id(c) not in seen_leaves:
                        seen_leaves[id(c)] = None
                        inputs.append(RegionInput("scan", node=c))
                    new_sources.append(c)
                    continue
                if id(c) in rebuilt:
                    new_sources.append(rebuilt[id(c)])
                    changed = changed or rebuilt[id(c)] is not c
                    continue
                if absorb(m, c):
                    rc = rebuild(c)
                    rebuilt[id(c)] = rc
                    new_sources.append(rc)
                    changed = changed or rc is not c
                    continue
                # cut: the child chain becomes its own (upstream) region
                # and this region reads its materialized batch
                src_region = carve(c)
                leaf = N.RemoteSourceNode(types=c.output_types())
                rebuilt[id(c)] = leaf
                inputs.append(RegionInput("region", region=src_region))
                new_sources.append(leaf)
                changed = True
            if not changed:
                return m
            from ..plan.rules import _replace_sources
            return _replace_sources(m, new_sources)

        rebuilt: Dict[int, N.PlanNode] = {}
        region_root = rebuild(n)

        # demotion check: a fused multi-op region whose fingerprint the
        # profiler has proven regressive re-carves materialized
        if fused and not single and not materialize_root and len(nodes) > 1:
            region_fp = fp_of(region_root)
            why = _MEMORY.demoted(region_fp)
            if why is None and budget > 0:
                # kernaudit K005 feedback: the measured peak of this
                # exact program overrides the static estimate
                if _MEMORY.footprint(region_fp) > budget:
                    why = "footprint"
            if why is not None:
                return carve(n, materialize_root=True,
                             cause=("footprint" if why == "footprint"
                                    else "demoted"))

        idx = len(regions)
        for m in nodes:
            node_region[id(m)] = idx
        from .profiler import plan_label
        reason = ("mesh" if single else
                  (cause or "materialized")
                  if (per_op or materialize_root) else
                  "+".join(sorted(set(reasons))) or "fused")
        regions.append(PipelineRegion(
            index=idx, root=region_root, inputs=inputs,
            span=plan_label(region_root, max_len=120), ops=len(nodes),
            reason=reason, est_peak_bytes=est_sum[0]))
        carved[id(n)] = idx
        return idx

    carve(root)
    return RegionPlan(root=root, regions=regions,
                      node_region=node_region, fused=fused)
