"""Live task/query progress: the in-flight counterpart of QueryStats.

Every observability surface before this one was retrospective --
metrics, traces, kernel profiles and the history archive all describe
queries that already finished, while a RUNNING query reported
``processedBytes: 0`` and an opaque state string. This module keeps a
process-wide registry of **monotonic** progress counters for every
in-flight query/task (the ClusterStatsResource / live QueryInfo analog
of the reference coordinator): current stage, splits done vs planned,
rows/bytes so far, peak reserved memory, and the last-advance
timestamp a stuck-progress watchdog (server/watchdog.py) keys on.

The monotonic law (the property every consumer relies on): between two
polls of one entry, ``rows``, ``bytes``, ``splits_done``,
``peak_memory_bytes`` and ``progress_percent`` never decrease, and
``last_advance`` never moves backwards. ``advance()`` takes deltas
(negative deltas clamp to zero); the percent is a stored high-water
mark over a stage-weighted estimate, so a stage label regressing (a
rerun re-entering ``execute``) cannot pull the bar backwards.

Producers:
  * ``run_query`` (exec/runner.py) drives the local entry for its
    ``query_id`` through plan/staging/execute/fetch;
  * the worker's TaskManager registers its task id the moment the task
    flips RUNNING (so a task wedged before the runner starts is still
    visible -- exactly the window the `hang` failpoint exercises);
  * the coordinator's status polls fold each remote task's shipped
    snapshot back into this registry (:func:`note_remote`), keyed by
    task id and tagged with the query's trace id, so the statement
    tier sees cross-worker progress without a second protocol.

Consumers: the statement tier's ``_base_doc`` (live client stats),
``GET /v1/cluster``, ``system.live_tasks`` / ``system.queries``, the
``presto_tpu_running_tasks`` gauge, and the stuck-progress watchdog.

The registry is bounded: finished entries are retained briefly (final
polls still resolve) and evicted oldest-first past the capacity.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Iterable, List, Optional

from ..utils.locks import OrderedLock

__all__ = ["TaskProgress", "begin", "get_progress", "note_remote",
           "finish_task", "live_snapshots", "snapshots_for_query",
           "live_task_count", "set_capacity", "reset",
           "aggregate_query_progress"]

# stage -> baseline percent estimate; staging interpolates over splits.
# Percents are an operator-facing heuristic, NOT a wall-time promise --
# the stored high-water mark is what makes the rendered bar monotonic.
_STAGE_PCT = {"start": 0.0, "plan": 2.0, "staging": 5.0,
              "execute": 60.0, "fetch": 90.0}
_STAGING_SPAN = (5.0, 60.0)  # staging interpolates splits over this band


class TaskProgress:
    """Monotonic progress counters for one in-flight query or task."""

    # request-handler threads snapshot while the runner thread
    # advances; every mutable field rides the entry lock
    _GUARDED_BY = {"_lock": ("stage", "splits_planned", "splits_done",
                             "rows", "bytes", "peak_memory_bytes",
                             "last_advance", "done", "final_state",
                             "_depth", "_pct")}

    def __init__(self, key: str, kind: str = "query",
                 query: Optional[str] = None,
                 worker: Optional[str] = None, remote: bool = False):
        self.key = str(key)
        self.kind = kind          # "query" | "task"
        self.query = query        # owning query/trace id (cross-link)
        self.worker = worker      # origin node for remote-noted entries
        self.remote = remote
        # speculative-attempt provenance: the coordinator names every
        # straggler re-execution `<taskId>.spec[...]`, so the live
        # surfaces (system.live_tasks, /v1/cluster) can render which
        # in-flight work is a speculation racing its original
        self.speculative = ".spec" in self.key
        self.started_at = time.time()
        self.stage = "start"
        self.splits_planned = 0
        self.splits_done = 0
        self.rows = 0
        self.bytes = 0
        self.peak_memory_bytes = 0
        self.last_advance = self.started_at
        self.done = False
        self.final_state: Optional[str] = None
        self._depth = 1           # re-entrant begin() nesting (writes)
        self._pct = 0.0           # high-water percent (monotonic)
        self._lock = OrderedLock("progress.TaskProgress._lock")

    # -- producer side --------------------------------------------------

    def advance(self, stage: Optional[str] = None, splits: int = 0,
                rows: int = 0, bytes: int = 0) -> None:
        """Apply deltas (clamped non-negative) and bump last_advance.
        Cheap and never raises: this sits on the runner's hot loop."""
        now = time.time()
        with self._lock:
            if stage is not None:
                self.stage = str(stage)
            self.splits_done += max(int(splits), 0)
            self.rows += max(int(rows), 0)
            self.bytes += max(int(bytes), 0)
            if self.last_advance < now:
                self.last_advance = now
            self._pct = max(self._pct, self._percent_locked())

    def set_planned(self, splits: int) -> None:
        """Planned split count (grows only: a replan can add work but a
        shrink would make done/planned jump backwards)."""
        with self._lock:
            self.splits_planned = max(self.splits_planned, int(splits))

    def note_memory(self, reserved_bytes: int) -> None:
        with self._lock:
            self.peak_memory_bytes = max(self.peak_memory_bytes,
                                         int(reserved_bytes))

    def release(self, state: Optional[str] = None) -> None:
        """Leave one begin() scope; the outermost release finishes the
        entry (nested run_query re-entries -- write roots -- don't)."""
        with self._lock:
            self._depth -= 1
            if self._depth > 0:
                return
            self._finish_locked(state)

    def force_finish(self, state: Optional[str] = None) -> None:
        """Terminal regardless of nesting (the worker's task epilogue:
        the task state machine, not the runner, owns task finality)."""
        with self._lock:
            self._depth = 0
            self._finish_locked(state)

    def _finish_locked(self, state: Optional[str]) -> None:
        if self.done:
            return
        self.done = True
        self.final_state = state or "FINISHED"
        self.last_advance = max(self.last_advance, time.time())
        if self.final_state == "FINISHED":
            self._pct = 100.0

    def reenter(self) -> None:
        with self._lock:
            self._depth += 1

    # -- consumer side --------------------------------------------------

    def _percent_locked(self) -> float:
        base = _STAGE_PCT.get(self.stage, 0.0)
        if self.stage == "staging" and self.splits_planned > 0:
            lo, hi = _STAGING_SPAN
            frac = min(self.splits_done / self.splits_planned, 1.0)
            base = lo + (hi - lo) * frac
        return min(max(base, 0.0), 100.0)

    def snapshot(self) -> dict:
        """Consistent copy; ages computed here so remote consumers stay
        clock-skew free (they ship ages, not absolute timestamps)."""
        now = time.time()
        with self._lock:
            pct = max(self._pct, self._percent_locked())
            return {
                "key": self.key,
                "kind": self.kind,
                "query": self.query or self.key,
                "worker": self.worker,
                "speculative": self.speculative,
                "state": (self.final_state or "FINISHED") if self.done
                         else "RUNNING",
                "stage": self.stage,
                "splitsDone": self.splits_done,
                "splitsPlanned": self.splits_planned,
                "rows": self.rows,
                "bytes": self.bytes,
                "peakMemoryBytes": self.peak_memory_bytes,
                "progressPercent": round(100.0 if self.done and
                                         self.final_state == "FINISHED"
                                         else pct, 1),
                "elapsedMs": int((now - self.started_at) * 1000),
                "lastAdvanceTsUs": int(self.last_advance * 1e6),
                "lastAdvanceAgeMs": max(
                    int((now - self.last_advance) * 1000), 0),
            }

    def merge_remote(self, doc: dict) -> None:
        """Fold a remote snapshot into this entry, monotonically: every
        counter takes the max (status polls can arrive out of order),
        and last_advance derives from the shipped AGE (clock-skew
        free). A terminal shipped state finishes the entry."""
        now = time.time()
        with self._lock:
            self.stage = str(doc.get("stage", self.stage))
            self.splits_planned = max(self.splits_planned,
                                      int(doc.get("splitsPlanned", 0)))
            self.splits_done = max(self.splits_done,
                                   int(doc.get("splitsDone", 0)))
            self.rows = max(self.rows, int(doc.get("rows", 0)))
            self.bytes = max(self.bytes, int(doc.get("bytes", 0)))
            self.peak_memory_bytes = max(
                self.peak_memory_bytes,
                int(doc.get("peakMemoryBytes", 0)))
            age_ms = max(int(doc.get("lastAdvanceAgeMs", 0)), 0)
            self.last_advance = max(self.last_advance,
                                    now - age_ms / 1000.0)
            self._pct = max(self._pct,
                            float(doc.get("progressPercent", 0.0)))
            state = doc.get("state")
            if state in ("FINISHED", "FAILED", "ABORTED", "CANCELED"):
                self._depth = 0
                self._finish_locked(state)


# -- process registry ---------------------------------------------------

# entries keyed by query/task id, bounded; finished entries linger so a
# final poll still resolves, evicted oldest-first past capacity (done
# entries first -- a live entry is only evicted when everything is live)
_LOCK = OrderedLock("progress._LOCK")
_ENTRIES: "collections.OrderedDict[str, TaskProgress]" = \
    collections.OrderedDict()
_CAPACITY = 2048


def begin(key: str, kind: str = "query", query: Optional[str] = None,
          worker: Optional[str] = None) -> TaskProgress:
    """The live entry for `key`, created (or re-entered: a nested
    run_query of a write root shares its outer scope's entry)."""
    with _LOCK:
        ent = _ENTRIES.get(key)
        if ent is not None and not ent.done:
            ent.reenter()
            if query and ent.query is None:
                ent.query = query
            return ent
        ent = TaskProgress(key, kind=kind, query=query, worker=worker)
        _ENTRIES[key] = ent
        _ENTRIES.move_to_end(key)
        _evict_locked()
        return ent


def get_progress(key: str) -> Optional[TaskProgress]:
    with _LOCK:
        return _ENTRIES.get(key)


def note_remote(key: str, doc: dict, worker: Optional[str] = None,
                query: Optional[str] = None) -> None:
    """Fold a remote task's shipped progress snapshot into the local
    registry (the coordinator's status-poll hook). Never raises: a
    malformed document is telemetry loss, not a query failure."""
    if not isinstance(doc, dict):
        return
    try:
        with _LOCK:
            ent = _ENTRIES.get(key)
            if ent is None:
                ent = TaskProgress(key, kind="task", query=query,
                                   worker=worker, remote=True)
                _ENTRIES[key] = ent
                _ENTRIES.move_to_end(key)
                _evict_locked()
            elif query and ent.query is None:
                ent.query = query
        ent.merge_remote(doc)
    except Exception:  # noqa: BLE001 - progress is telemetry; the poll
        # that carried it must not fail (counted upstream when it
        # matters: the callers sit on already-best-effort paths)
        pass


def finish_task(key: str, state: str) -> None:
    ent = get_progress(key)
    if ent is not None:
        ent.force_finish(state)


def live_snapshots() -> List[dict]:
    """Snapshots of every in-flight entry (oldest first)."""
    with _LOCK:
        entries = [e for e in _ENTRIES.values() if not e.done]
    return [e.snapshot() for e in entries]


def snapshots_for_query(keys: Iterable[str],
                        include_done: bool = True) -> List[dict]:
    """Snapshots of entries belonging to any of the given query/trace
    ids (matched on the entry key OR its query cross-link)."""
    wanted = {str(k) for k in keys if k}
    with _LOCK:
        entries = [e for e in _ENTRIES.values()
                   if (e.key in wanted or (e.query or "") in wanted)
                   and (include_done or not e.done)]
    return [e.snapshot() for e in entries]


def live_task_count() -> int:
    with _LOCK:
        return sum(1 for e in _ENTRIES.values() if not e.done)


def set_capacity(n: int) -> None:
    """Registry bound (tests shrink it to pin eviction)."""
    global _CAPACITY
    with _LOCK:
        _CAPACITY = max(int(n), 1)
        _evict_locked()


def _evict_locked() -> None:
    while len(_ENTRIES) > _CAPACITY:
        victim = None
        for k, e in _ENTRIES.items():  # oldest done entry first
            if e.done:
                victim = k
                break
        if victim is None:  # everything live: evict the oldest anyway
            victim = next(iter(_ENTRIES))
        del _ENTRIES[victim]


def reset() -> None:
    """Drop every entry (tests isolate registry state)."""
    with _LOCK:
        _ENTRIES.clear()


# aggregate view used by the statement tier (one place so _base_doc,
# /v1/cluster and the watchdog agree on what "query progress" means)
def aggregate_query_progress(keys: Iterable[str]) -> Optional[dict]:
    """Fold the query's own entry plus its tasks' entries into ONE
    progress doc: rows/bytes/splits sum, peaks max, percent averages
    over live tasks, stage and last-advance follow the most recently
    advanced entry. None when nothing was ever registered."""
    docs = snapshots_for_query(keys)
    if not docs:
        return None
    live = [d for d in docs if d["state"] == "RUNNING"] or docs
    latest = max(docs, key=lambda d: d["lastAdvanceTsUs"])
    return {
        "stage": latest["stage"],
        "rows": sum(d["rows"] for d in docs),
        "bytes": sum(d["bytes"] for d in docs),
        "splitsDone": sum(d["splitsDone"] for d in docs),
        "splitsPlanned": sum(d["splitsPlanned"] for d in docs),
        "peakMemoryBytes": max(d["peakMemoryBytes"] for d in docs),
        "progressPercent": round(
            sum(d["progressPercent"] for d in live) / len(live), 1),
        "lastAdvanceAgeMs": min(d["lastAdvanceAgeMs"] for d in docs),
        "tasks": len(docs),
        "runningTasks": sum(1 for d in docs if d["state"] == "RUNNING"),
        "speculativeTasks": sum(1 for d in docs
                                if d.get("speculative")),
    }
