"""Concurrent-query batching executor: N queries, ONE dispatch.

The throughput gap this closes: the "millions of users" workload is
thousands of small concurrent queries -- dashboards and point lookups
-- sharing a handful of plan shapes, yet every statement today stages
and dispatches its kernels alone. Like "Accelerating Presto with GPUs"
(PAPERS.md), the win is keeping the accelerator saturated with batched
work instead of serialized per-query dispatches: queries whose plans
differ only in literals collapse into one vmapped program execution.

Model:

  * **Parameterization** (:func:`parameterize_plan`): a prepared plan's
    Filter/Project expressions are rewritten bottom-up, lifting every
    Constant in a value-safe position (comparison/arithmetic arguments,
    BETWEEN bounds, IN list members; fixed-width non-string types only)
    into a ``BatchParam(index)`` leaf. The rewritten tree is the
    *template*; the lifted values are the query's *parameter vector*.
    Constants the compiler specializes at trace time (LIKE patterns,
    date_add units, casts of structure) are never lifted, so the
    template traces exactly like the original plan.

  * **Batch key**: ``(plan_fingerprint(template), kernel-mode envs, sf,
    join capacity)`` -- the exact identity ``exec/plan_cache.py`` and
    ``exec/profiler.py`` already key on. Queries co-batch ONLY on key
    equality: differing string literals, differing plan shapes, or a
    kernel-mode env flip produce different keys by construction.

  * **Formation window**: the first arrival of a HOT fingerprint leads
    a forming batch and waits ``batch_window_ms`` for followers (or
    until ``batch_max_size``); cold fingerprints never pay the delay.
    Hotness is the fingerprint's recent submission frequency, seeded
    from the query-history archive's per-fingerprint counts
    (server/history.py) so a dashboard fingerprint is hot from the
    first poll after a restart.

  * **Batched dispatch**: the template compiles once through the plan
    cache (hit/miss accounting unchanged); the executable is wrapped as
    ``jax.vmap(fn, in_axes=(None, 0))`` -- scan batches broadcast,
    parameter vectors mapped -- and jitted, so XLA sees one program
    with a leading batch dimension. Scan staging happens ONCE per
    batch. Results fan back per member by slicing the batch axis;
    every member's rows are bit-identical to its serial execution
    (pinned by tests and the chaos ``batch`` round).

  * **Collapse**: any overflow flag, the ``dispatcher.batch_collapse``
    failpoint, or an unexpected batched-dispatch error falls back to
    serial per-query dispatch of every member (counted per reason on
    ``presto_tpu_batch_collapses_total``) -- batching is a fast path,
    never a correctness dependency.

Gating: session property ``query_batching`` / env ``PRESTO_TPU_BATCHING``
(registered in KERNEL_MODE_ENVS; the serial A/B control the loadgen
benchmark measures against).
"""

from __future__ import annotations

import collections
import dataclasses
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from .. import types as T
from ..expr import ir as E
from ..expr.compile import bound_params
from ..plan import nodes as N
from ..utils.locks import OrderedLock

__all__ = ["BATCHING_ENV", "batching_enabled", "parameterize_plan",
           "BatchingExecutor", "get_batching_executor",
           "set_batching_executor", "batching_totals",
           "batching_snapshot", "batch_size_of", "template_fp_of",
           "clear_batching"]

BATCHING_ENV = "PRESTO_TPU_BATCHING"

# literal-masking for the pre-plan hotness gate: numbers and quoted
# strings collapse to "?" so every member of a parameterized family
# shares one shape key WITHOUT planning (the gate only decides whether
# planning for the batched path is worth paying at all)
_SHAPE_RE = re.compile(r"'[^']*'|\b\d+(?:\.\d+)?\b")

# collapse reasons with a stable /v1/metrics zero shape
COLLAPSE_REASONS = ("failpoint", "overflow", "error")


def batching_enabled(session) -> bool:
    """Session property ``query_batching``; process default from
    PRESTO_TPU_BATCHING (default ON). Spelled literally so tpulint R001
    proves the knob is registered in KERNEL_MODE_ENVS. Both the env and
    the session value parse with the registry's bool coercion, so
    'off'/'False'/'no' disable like '0' does."""
    import os
    from ..utils.config import _parse_bool, session_flag
    env_on = _parse_bool(os.environ.get("PRESTO_TPU_BATCHING", "1"))
    return session_flag(session, "query_batching", env_on)


# ---------------------------------------------------------------------------
# plan parameterization
# ---------------------------------------------------------------------------

# Calls whose Constant arguments are pure VALUES: evaluation reads them
# lane-wise, never as trace-time structure, so a BatchParam substitutes
# exactly. Everything else (LIKE patterns, date_add units, sequence
# bounds, row_field indices, ...) keeps its Constants and stays part of
# the template -- queries differing there never co-batch.
_SAFE_CALLS = frozenset({"eq", "ne", "lt", "le", "gt", "ge",
                         "add", "subtract", "multiply", "divide",
                         "modulus"})


def _parameterizable_type(ty: T.Type) -> bool:
    """Fixed-width scalar types whose constant blocks are a dtype'd
    broadcast -- exactly what a traced parameter scalar reproduces.
    Strings (shape-bearing) and long decimals (limb pairs) stay
    literal."""
    if ty.is_string or ty == T.UNKNOWN:
        return False
    if ty.base in ("array", "map", "row"):
        return False
    if ty.is_decimal and not ty.is_short_decimal:
        return False
    try:
        return ty.is_fixed_width
    except Exception:  # noqa: BLE001 - exotic logical type
        return False


def _normalize_param(c: E.Constant) -> Tuple[object, bool]:
    """Constant -> (host value, is_null), mirroring the conversions
    compile._constant_block applies at trace time (dates spelled as
    strings become epoch days) so the parameterized execution stages
    the same scalar the literal would have."""
    if c.value is None:
        return (False if c.type.base == "boolean" else 0), True
    v = c.value
    if c.type.base == "date" and isinstance(v, str):
        v = int((np.datetime64(v)
                 - np.datetime64("1970-01-01")).astype(int))
    if c.type.base == "boolean":
        v = bool(v)
    return v, False


def _null_hint(args) -> Optional[T.Type]:
    """The type an UNTYPED NULL literal (``x = NULL`` plans a
    Constant of UNKNOWN type) is lifted at: its first typed sibling.
    A NULL parameter at the sibling's type evaluates to the same
    all-NULL comparison, and ``x = NULL`` then shares a template with
    ``x = 42`` -- the NULL-parameter co-batching case."""
    for a in args:
        if a.type != T.UNKNOWN:
            return a.type
    return None


def _extract_expr(expr: E.RowExpression, params: List, liftable: bool,
                  hint: Optional[T.Type] = None) -> E.RowExpression:
    """Rewrite one expression tree, lifting value-position Constants
    into BatchParam leaves (preorder index order)."""
    if isinstance(expr, E.Constant):
        ty = expr.type
        if ty == T.UNKNOWN and expr.value is None and hint is not None:
            ty = hint
        if liftable and _parameterizable_type(ty):
            idx = len(params)
            params.append((_normalize_param(
                E.Constant(ty, expr.value)), ty))
            return E.BatchParam(ty, idx)
        return expr
    if isinstance(expr, E.Call):
        ok = expr.name.lower() in _SAFE_CALLS
        h = _null_hint(expr.arguments) if ok else None
        args = tuple(_extract_expr(a, params, ok, hint=h)
                     for a in expr.arguments)
        if all(a is b for a, b in zip(args, expr.arguments)):
            return expr
        return E.Call(expr.type, expr.name, args)
    if isinstance(expr, E.SpecialForm):
        if expr.form in ("BETWEEN", "IN"):
            # args[0] is the probed value (recurse normally); the
            # bounds / list members are pure values
            h = _null_hint(expr.arguments)
            args = tuple([_extract_expr(expr.arguments[0], params, False)]
                         + [_extract_expr(a, params, True, hint=h)
                            for a in expr.arguments[1:]])
        else:
            args = tuple(_extract_expr(a, params, False)
                         for a in expr.arguments)
        if all(a is b for a, b in zip(args, expr.arguments)):
            return expr
        return E.SpecialForm(expr.type, expr.form, args)
    # Lambda bodies / lambda variables: leave untouched (higher-order
    # kernels specialize their structure at trace time)
    return expr


def parameterize_plan(root: N.PlanNode
                      ) -> Tuple[N.PlanNode, List[Tuple[Tuple, T.Type]]]:
    """Prepared plan -> (template plan, parameter vector). The template
    shares every node the rewrite did not touch (scan leaves keep their
    width annotations and identity); parameters list ((value, is_null),
    type) in deterministic DFS-preorder-of-expressions order, so two
    plannings of the same SQL shape extract identically-ordered
    vectors. A plan with no liftable literal returns (root, [])."""
    params: List[Tuple[Tuple, T.Type]] = []
    memo: Dict[int, N.PlanNode] = {}

    def walk(n: N.PlanNode) -> N.PlanNode:
        if id(n) in memo:
            return memo[id(n)]
        new_sources = [walk(s) for s in n.sources]
        src_changed = any(a is not b
                          for a, b in zip(new_sources, n.sources))
        if isinstance(n, N.FilterNode):
            pred = _extract_expr(n.predicate, params, False)
            if pred is not n.predicate or src_changed:
                out = dataclasses.replace(n, source=new_sources[0],
                                          predicate=pred)
            else:
                out = n
        elif isinstance(n, N.ProjectNode):
            exprs = [_extract_expr(e, params, False)
                     for e in n.expressions]
            if src_changed or any(a is not b for a, b
                                  in zip(exprs, n.expressions)):
                out = dataclasses.replace(n, source=new_sources[0],
                                          expressions=exprs)
            else:
                out = n
        elif src_changed:
            from ..plan.rules import _replace_sources
            out = _replace_sources(n, new_sources)
        else:
            out = n
        memo[id(n)] = out
        return out

    return walk(root), params


# ---------------------------------------------------------------------------
# process totals (server/metrics.py batching_families reads these)
# ---------------------------------------------------------------------------

_TOTALS_LOCK = OrderedLock("batching._TOTALS_LOCK")
_TOTALS = {"batches": 0, "batched_queries": 0, "last_batch_size": 0,
           "max_batch_size": 0, "solo_dispatches": 0}
_COLLAPSES = {r: 0 for r in COLLAPSE_REASONS}

# query id -> size of the batch that served it (0/absent = unbatched);
# system.queries' batch_size column reads it. Bounded.
_QUERY_BATCH: "collections.OrderedDict[str, int]" = \
    collections.OrderedDict()
# query id -> template fingerprint (batchable queries, batched or not);
# the history archive attaches it to records so the formation window
# can be driven by archived per-fingerprint frequency
_QUERY_TEMPLATE: "collections.OrderedDict[str, str]" = \
    collections.OrderedDict()
_QUERY_MAP_MAX = 1024

# tpulint C001: module-global write barrier (the process-counter
# idiom; _EXECUTOR is the singleton swap under its own lock)
_GUARDED_BY = {"_TOTALS_LOCK": ("_TOTALS", "_COLLAPSES",
                                "_QUERY_BATCH", "_QUERY_TEMPLATE"),
               "_EXEC_LOCK": ("_EXECUTOR",)}


def _note_query(table: "collections.OrderedDict", query_id: str,
                value) -> None:
    with _TOTALS_LOCK:
        table[query_id] = value
        table.move_to_end(query_id)
        while len(table) > _QUERY_MAP_MAX:
            table.popitem(last=False)


def batch_size_of(query_id: str) -> int:
    with _TOTALS_LOCK:
        return _QUERY_BATCH.get(query_id, 0)


def template_fp_of(query_id: str) -> Optional[str]:
    with _TOTALS_LOCK:
        return _QUERY_TEMPLATE.get(query_id)


def batching_totals() -> dict:
    with _TOTALS_LOCK:
        out = dict(_TOTALS)
        out["collapses"] = dict(_COLLAPSES)
        return out


def reset_batching_totals() -> None:
    """Zero the process counters without dropping the executor (and
    its warm compiled-program cache) -- phase boundaries in benchmarks
    and tests that only assert deltas."""
    with _TOTALS_LOCK:
        for k in _TOTALS:
            _TOTALS[k] = 0
        for k in _COLLAPSES:
            _COLLAPSES[k] = 0
        _QUERY_BATCH.clear()
        _QUERY_TEMPLATE.clear()


def clear_batching() -> None:
    """Reset process totals + the executor (tests isolate state)."""
    global _EXECUTOR
    reset_batching_totals()
    with _EXEC_LOCK:
        _EXECUTOR = None


# ---------------------------------------------------------------------------
# the executor
# ---------------------------------------------------------------------------


class _Pending:
    """One member of a forming batch."""
    __slots__ = ("values", "root", "session", "query_id", "trace_id",
                 "event", "result", "error")

    def __init__(self, values, root, session, query_id, trace_id):
        self.values = values          # [(value, is_null), ...]
        self.root = root              # this query's OWN prepared plan
        self.session = session
        self.query_id = query_id
        self.trace_id = trace_id
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class _Forming:
    """A batch being collected for one key (leader waits the window)."""
    __slots__ = ("key", "entries", "sealed", "full")

    def __init__(self, key):
        self.key = key
        self.entries: List[_Pending] = []
        self.sealed = False
        self.full = threading.Event()


class BatchingExecutor:
    """Process-wide batching executor in the statement dispatch path.

    ``try_execute`` returns a QueryResult when the query was served by
    a formed batch (leader or follower), or None when the caller should
    run the normal serial path (not batchable, batching disabled, or no
    batch formed). Thread-safe; statement _run threads are the
    callers."""

    # tpulint C001: formation/inflight/template registries are shared
    # across every statement _run thread
    _GUARDED_BY = {"_lock": ("_forming", "_inflight", "_recent",
                             "_shape_recent", "_vmapped", "_staged")}

    def __init__(self, window_ms: float = 5.0, max_batch: int = 64,
                 hot_min: int = 2, hot_window_s: float = 30.0,
                 follower_timeout_s: float = 300.0,
                 max_form_s: float = 1.0, max_inflight: int = 8):
        self.window_ms = window_ms
        self.max_batch = max_batch
        self.hot_min = hot_min
        self.hot_window_s = hot_window_s
        self.follower_timeout_s = follower_timeout_s
        # upper bound on formation wait while chained behind an
        # in-flight dispatch (the latency guardrail under saturation)
        self.max_form_s = max_form_s
        # concurrent dispatches allowed per key: dispatch itself is
        # serialized by the plan-cache call lock, but EXECUTION is
        # async -- a small overlap keeps the device fed while the next
        # batch forms, and the cap keeps occupancy adaptive (a full
        # pipeline makes the next leader keep collecting)
        self.max_inflight = max_inflight
        self._lock = OrderedLock("batching.BatchingExecutor._lock")
        self._forming: Dict[tuple, _Forming] = {}
        # key -> count of batched dispatches currently executing: a
        # forming batch keeps COLLECTING while its key's dispatch
        # pipeline is full (the inference-server chaining pattern --
        # occupancy adapts to load: under saturation batches chain
        # back-to-back and the formation window only bounds the idle
        # case), up to max_inflight overlapped executions per key
        self._inflight: Dict[tuple, int] = {}
        # fingerprint -> deque of recent submission times (hotness)
        self._recent: "collections.OrderedDict[str, collections.deque]" \
            = collections.OrderedDict()
        # masked text shape -> recent submissions: the pre-plan gate
        # (one-off statements skip the batched path's plan walk)
        self._shape_recent: \
            "collections.OrderedDict[str, collections.deque]" = \
            collections.OrderedDict()
        # batch key -> jitted vmapped wrapper (the per-shape XLA cache
        # lives inside the one jitted callable)
        self._vmapped: "collections.OrderedDict[tuple, tuple]" = \
            collections.OrderedDict()
        self._vmapped_max = 64
        # (batch key, data versions) -> staged scan Batches: repeat
        # batches of a hot template skip host->HBM staging entirely,
        # guarded by the connectors' data_version seam (the same
        # contract the worker fragment cache keys on)
        self._staged: "collections.OrderedDict[tuple, list]" = \
            collections.OrderedDict()
        self._staged_max = 16
        # exact statement text -> (prepared, template, values, key):
        # zipfian traffic repeats hot literals verbatim, so the plan /
        # prepare / parameterize walk -- pure Python on the per-query
        # hot path -- is paid once per distinct text
        self._plan_memo: "collections.OrderedDict[tuple, tuple]" = \
            collections.OrderedDict()
        self._plan_memo_max = 2048

    # -- knobs resolved per query --------------------------------------

    def _window_s(self, session) -> float:
        from ..utils.config import session_value
        return float(session_value(session, "batch_window_ms",
                                   self.window_ms) or 0.0) / 1e3

    def _max_batch(self, session) -> int:
        from ..utils.config import session_value
        return max(int(session_value(session, "batch_max_size",
                                     self.max_batch)), 1)

    def _hot_min(self, session) -> int:
        from ..utils.config import session_value
        return int(session_value(session, "batch_hot_min", self.hot_min))

    # -- hotness -------------------------------------------------------

    def _note_window(self, table, key: str) -> int:
        """Record one event for `key` in a bounded sliding-window
        table; returns the recent count (this event included)."""
        now = time.time()
        cutoff = now - self.hot_window_s
        with self._lock:
            q = table.get(key)
            if q is None:
                q = table[key] = collections.deque(maxlen=4096)
                while len(table) > 512:
                    table.popitem(last=False)
            else:
                table.move_to_end(key)
            q.append(now)
            while q and q[0] < cutoff:
                q.popleft()
            return len(q)

    def _note_recent(self, fp: str) -> int:
        """Record one submission of `fp`; returns the recent count
        (this submission included)."""
        return self._note_window(self._recent, fp)

    def _hot(self, fp: str, session) -> bool:
        """Whether this fingerprint deserves a formation window: its
        recent in-process frequency, seeded by the history archive's
        per-fingerprint counts (a hot dashboard fingerprint pays zero
        cold starts after a restart)."""
        hot_min = self._hot_min(session)
        n = self._note_recent(fp)
        if hot_min <= 1 or n >= hot_min:
            return True
        try:
            from ..server.history import get_history_archive
            n += get_history_archive().batch_fingerprint_count(fp)
        except Exception:  # noqa: BLE001 - the archive is telemetry;
            pass           # hotness degrades to in-process counts
        return n >= hot_min

    # -- batch key -----------------------------------------------------

    @staticmethod
    def _batch_key(template_fp: str, sf: float,
                   join_capacity: int) -> tuple:
        from .plan_cache import _kernel_mode
        # the exact identity the plan cache and profiler key on:
        # (structural fingerprint, kernel-mode envs) -- plus the scale
        # factor and join capacity that select the staged data/program
        return (template_fp, _kernel_mode(), float(sf),
                int(join_capacity))

    # -- the public seam ----------------------------------------------

    def try_execute(self, text: str, *, sf: float, session: Dict,
                    query_id: str, trace_id=None,
                    max_groups: Optional[int] = None,
                    join_capacity: Optional[int] = None,
                    catalog: Optional[str] = None):
        """Plan `text`, and when it is batchable and a batch forms,
        execute it batched and return this query's QueryResult. Returns
        None whenever the normal serial path should run instead."""
        if not batching_enabled(session):
            return None
        hot_min = self._hot_min(session)
        if hot_min > 1 and \
                self._note_window(self._shape_recent,
                                  _SHAPE_RE.sub("?", text)) < hot_min:
            # cold text SHAPE (literals masked): stay on the pure
            # serial path without paying the batched path's plan walk
            # -- one-off ad-hoc statements cost one regex here, not a
            # second full planning
            return None
        try:
            prepared, template, values, key = self._prepare(
                text, sf=sf, session=session,
                max_groups=max_groups, join_capacity=join_capacity,
                catalog=catalog)
        except Exception:  # noqa: BLE001 - unparseable/unsupported SQL:
            # the serial path owns producing the real error
            return None
        if template is None:
            return None
        _note_query(_QUERY_TEMPLATE, query_id, key[0])
        entry = _Pending(values, prepared, session, query_id, trace_id)
        hot = self._hot(key[0], session)
        window_s = self._window_s(session)
        max_batch = self._max_batch(session)

        with self._lock:
            g = self._forming.get(key)
            if g is not None and not g.sealed \
                    and len(g.entries) < max_batch:
                g.entries.append(entry)
                if len(g.entries) >= max_batch:
                    g.full.set()
                leader = False
            elif hot and window_s > 0:
                g = _Forming(key)
                g.entries.append(entry)
                self._forming[key] = g
                leader = True
            else:
                return None  # cold fingerprint: never pay the window

        if not leader:
            # follower: the leader executes for us
            if not entry.event.wait(self.follower_timeout_s):
                return None  # leader wedged: run serial (duplicate-safe)
            if entry.error is not None:
                raise entry.error
            return entry.result

        # leader: collect followers until the batch fills, or -- once
        # the window has elapsed -- until this key's dispatch pipeline
        # has a free slot (chaining: while max_inflight previous
        # batches execute, this one keeps collecting; max_form_s
        # bounds the wait)
        t_form = time.time()
        while True:
            g.full.wait(window_s)
            with self._lock:
                if len(g.entries) >= max_batch:
                    break
                elapsed = time.time() - t_form
                if elapsed >= window_s and \
                        self._inflight.get(key, 0) < self.max_inflight:
                    break
                if elapsed >= self.max_form_s:
                    break
        with self._lock:
            g.sealed = True
            if self._forming.get(key) is g:
                del self._forming[key]
            entries = list(g.entries)
            counted_inflight = len(entries) > 1
            if counted_inflight:
                self._inflight[key] = self._inflight.get(key, 0) + 1
        if len(entries) == 1:
            # no batch formed. If this key's vmapped program is ALREADY
            # warm (a real batch or precompile built it), ride it as a
            # batch-of-1: the template amortizes the per-literal XLA
            # compile a cold literal would otherwise pay on the serial
            # path. Never COMPILE a program for a singleton -- with no
            # warm program the serial path owns the query (keeps cold
            # workloads, and the test suite's one-off statements, on
            # the exact serial path).
            with self._lock:
                have = self._vmapped.get(key)
                if have is None or have[0] is None:
                    return None
        try:
            self._execute_batch(key, entries, sf=sf,
                                join_capacity=key[3])
        except BaseException as e:  # noqa: BLE001 - every waiting
            # member must wake, whatever broke
            for m in entries:
                if m.result is None and m.error is None:
                    m.error = e
        finally:
            if counted_inflight:  # solo dispatches never incremented
                with self._lock:
                    n = self._inflight.get(key, 0) - 1
                    if n > 0:
                        self._inflight[key] = n
                    else:
                        self._inflight.pop(key, None)
            for m in entries:
                m.event.set()
        if entry.error is not None:
            raise entry.error
        return entry.result

    def precompile(self, text: str, *, sf: float,
                   session: Optional[Dict] = None,
                   sizes: Optional[List[int]] = None,
                   join_capacity: Optional[int] = None,
                   catalog: Optional[str] = None) -> int:
        """Compile (and stage) the vmapped programs for `text`'s
        template at each power-of-two batch-size bucket, so a measured
        or latency-sensitive phase never pays an XLA compile mid-batch
        (benchmark warm-up; a production tier would drive this from the
        history archive's hot fingerprints). Returns the number of
        bucket programs now warm (0 = not batchable)."""
        sess = dict(session or {})
        try:
            _prepared, template, values, key = self._prepare(
                text, sf=sf, session=sess, max_groups=None,
                join_capacity=join_capacity, catalog=catalog)
        except Exception:  # noqa: BLE001 - unbatchable text: nothing
            return 0       # to warm
        if template is None:
            return 0
        fn, plan, call_lock = self._compiled(key, key[3])
        batches = self._stage_inputs(key, plan, sf)
        if sizes is None:
            sizes, s = [], 2
            while s <= self._max_batch(sess):
                sizes.append(s)
                s *= 2
        warmed = 0
        for size in sizes:
            stub = _Pending(values, None, sess, "warm", None)
            params = self._stack_params([stub] * max(int(size), 1))
            with call_lock:
                out, _overflow = fn(tuple(batches), params)
            jax.block_until_ready(out)
            warmed += 1
        return warmed

    def bench_dispatch(self, texts: List[str], *, sf: float,
                       session: Optional[Dict] = None):
        """Execute co-batchable `texts` as ONE batched dispatch with no
        formation window, returning per-text QueryResults in order --
        the direct dispatch-path seam (scripts/loadgen.py's engine
        amortization A/B and white-box tests). Raises ValueError when
        the texts do not share a batch key."""
        sess = dict(session or {})
        entries: List[_Pending] = []
        key0 = None
        for i, text in enumerate(texts):
            _prepared, template, values, key = self._prepare(
                text, sf=sf, session=sess, max_groups=None,
                join_capacity=None, catalog=None)
            if template is None:
                raise ValueError(f"not batchable: {text!r}")
            if key0 is None:
                key0 = key
            elif key != key0:
                raise ValueError("texts do not share a batch key")
            entries.append(_Pending(values, _prepared, sess,
                                    f"bench-{i}", None))
        self._execute_batch(key0, entries, sf=sf,
                            join_capacity=key0[3])
        for m in entries:
            if m.error is not None:
                raise m.error
        return [m.result for m in entries]

    def _prepare(self, text: str, *, sf: float, session: Dict,
                 max_groups: Optional[int],
                 join_capacity: Optional[int],
                 catalog: Optional[str]):
        """Plan + prepare + parameterize one statement, memoized by
        exact text (zipfian repeats skip the whole walk). Returns
        (prepared plan, template-or-None, param values, batch key)."""
        from .plan_cache import _kernel_mode
        # plan-shaping session properties are part of the memo key --
        # two sessions disagreeing on (say) narrow_width_execution
        # must not share a prepared tree
        sess_bits = tuple(
            (k, str((session or {}).get(k)))
            for k in ("iterative_optimizer", "join_reordering_strategy",
                      "stats_capacity_refinement",
                      "narrow_width_execution")
            if (session or {}).get(k) is not None)
        memo_key = (text, float(sf), max_groups, join_capacity,
                    catalog, _kernel_mode(), sess_bits)
        with self._lock:
            hit = self._plan_memo.get(memo_key)
            if hit is not None:
                self._plan_memo.move_to_end(memo_key)
                return hit
        out = self._prepare_uncached(text, sf=sf, session=session,
                                     max_groups=max_groups,
                                     join_capacity=join_capacity,
                                     catalog=catalog)
        with self._lock:
            self._plan_memo[memo_key] = out
            self._plan_memo.move_to_end(memo_key)
            while len(self._plan_memo) > self._plan_memo_max:
                self._plan_memo.popitem(last=False)
        return out

    def _prepare_uncached(self, text: str, *, sf: float, session: Dict,
                          max_groups: Optional[int],
                          join_capacity: Optional[int],
                          catalog: Optional[str]):
        from ..sql import plan_sql
        from .runner import prepare_plan
        kw = {}
        if max_groups is not None:
            kw["max_groups"] = int(max_groups)
        root = plan_sql(text, join_capacity=join_capacity,
                        catalog=catalog, **kw)
        inner = root.source if isinstance(root, N.OutputNode) else root
        if isinstance(inner, (N.DdlNode, N.TableFinishNode,
                              N.TableWriterNode, N.TableRewriteNode)):
            return None, None, None, None
        # the batched path shares staged scans across members, so the
        # per-literal staging optimizations must not specialize them:
        # pushdown pruning and dynamic filters stage different rows for
        # different literals (results stay exact either way -- the
        # Filter above always applies; these only prune)
        bsession = dict(session or {})
        bsession["scan_predicate_pushdown"] = False
        bsession["dynamic_filtering"] = False
        prepared = prepare_plan(root, sf=sf, mesh=None, session=bsession)
        template, params = parameterize_plan(prepared)
        values = [v for v, _ty in params]
        from .plan_cache import plan_fingerprint
        cap = join_capacity if join_capacity is not None else 1 << 16
        key = self._batch_key(plan_fingerprint(template), sf, cap)
        # stash the template + batching session on the key's compile
        # path via instance state-free returns
        self._templates_put(key, template)
        return prepared, template, values, key

    # template per key (bounded; the leader compiles from it)
    def _templates_put(self, key, template) -> None:
        with self._lock:
            self._vmapped.setdefault(key, (None, None, None, None))
            fn, plan, lock, _ = self._vmapped[key]
            self._vmapped[key] = (fn, plan, lock, template)
            self._vmapped.move_to_end(key)
            while len(self._vmapped) > self._vmapped_max:
                self._vmapped.popitem(last=False)

    def _compiled(self, key, join_capacity: int):
        """(vmapped jitted fn, CompiledPlan, dispatch lock) for a batch
        key -- the base program rides the shared plan cache (hit/miss
        accounting identical to serial repeats of the template)."""
        with self._lock:
            fn, plan, lock, template = self._vmapped.get(
                key, (None, None, None, None))
        if fn is not None:
            return fn, plan, lock
        if template is None:  # evicted between prepare and compile
            raise RuntimeError("batch template evicted before compile")
        from .plan_cache import cached_compile
        plan, _jfn, lock = cached_compile(template, None, join_capacity)

        def bfn(batches, params):
            with bound_params(params):
                return plan.fn(batches)

        fn = jax.jit(jax.vmap(bfn, in_axes=(None, 0)))
        with self._lock:
            have = self._vmapped.get(key)
            if have is not None and have[0] is not None:
                return have[0], have[1], have[2]
            self._vmapped[key] = (fn, plan, lock, template)
            self._vmapped.move_to_end(key)
            while len(self._vmapped) > self._vmapped_max:
                self._vmapped.popitem(last=False)
        return fn, plan, lock

    # -- batched dispatch ---------------------------------------------

    def _execute_batch(self, key, entries: List[_Pending], *,
                       sf: float, join_capacity: int) -> None:
        """Run one formed batch: stage scans once, dispatch the vmapped
        program over the stacked parameter vectors, fan results back to
        every member. Any overflow / injected collapse / unexpected
        error falls back to serial per-member dispatch."""
        from .. import failpoints
        from ..server.flight_recorder import record_event
        t0 = time.time()
        nbatch = len(entries)
        if failpoints.ARMED:
            try:
                # a formed batch forced to collapse back to serial
                # dispatch mid-flight (chaos asserts every member still
                # matches its oracle and accounting balances)
                failpoints.hit("dispatcher.batch_collapse")
            except Exception:  # noqa: BLE001 - any injected error class
                record_event("batch_collapse", reason="failpoint",
                             size=nbatch, query_id=entries[0].query_id)
                self._serial_fallback(entries, sf, "failpoint")
                return
        try:
            fn, plan, call_lock = self._compiled(key, join_capacity)
            # ONE progress entry per dispatch (the leader's): per-member
            # entries would put B lock round-trips on a path whose whole
            # point is amortizing per-query cost
            from .progress import begin as progress_begin
            prog = progress_begin(entries[0].query_id)
            try:
                prog.advance(stage="staging")
                batches = self._stage_inputs(key, plan, sf)
                params = self._stack_params(entries)
                prog.advance(stage="execute")
                with call_lock:
                    out, overflow = fn(tuple(batches), params)
                jax.block_until_ready(out)
            finally:
                prog.release(state="FINISHED")
            flags = np.asarray(overflow)
            if int(flags.max()) != 0:
                # a member overflowed a static bucket: the serial
                # ladder owns adaptive reruns; collapse the whole batch
                record_event("batch_collapse", reason="overflow",
                             size=nbatch, query_id=entries[0].query_id)
                self._serial_fallback(entries, sf, "overflow")
                return
        except Exception as e:  # noqa: BLE001 - a vmap/trace corner the
            # serial path handles fine must not fail the members
            from ..server.metrics import record_suppressed
            record_suppressed("batching", "batched_dispatch", e)
            record_event("batch_collapse", reason="error",
                         size=nbatch, query_id=entries[0].query_id)
            self._serial_fallback(entries, sf, "error")
            return
        device_us = int((time.time() - t0) * 1e6)
        self._fan_out(out, plan, entries, device_us)
        self._account(key, entries, device_us)

    def _stage_inputs(self, key, plan, sf: float) -> list:
        """Stage the template's scan batches, replayed from the staged
        cache when every leaf's connector proves its data unchanged
        (data_version -- the worker fragment cache's contract; volatile
        catalogs stage fresh every batch)."""
        versions: Optional[list] = []
        for s in plan.scan_nodes:
            if isinstance(s, N.ValuesNode):
                # VALUES rows are part of the plan fingerprint: static
                versions.append(("values",))
                continue
            if not isinstance(s, N.TableScanNode):
                versions = None
                break
            from ..connectors import catalog
            fn = getattr(catalog(s.connector), "data_version", None)
            if fn is None:
                versions = None
                break
            versions.append((s.connector, s.table, fn(s.table)))
        ckey = (key, tuple(versions)) if versions is not None else None
        if ckey is not None:
            with self._lock:
                hit = self._staged.get(ckey)
                if hit is not None:
                    self._staged.move_to_end(ckey)
                    return hit
        from .runner import _scan_batch
        batches = [_scan_batch(s, sf, None, 8) for s in plan.scan_nodes]
        if ckey is not None:
            with self._lock:
                self._staged[ckey] = batches
                self._staged.move_to_end(ckey)
                while len(self._staged) > self._staged_max:
                    self._staged.popitem(last=False)
        return batches

    def _stack_params(self, entries: List[_Pending]) -> tuple:
        """Member parameter vectors -> tuple over parameter positions
        of ([B] values, [B] nulls) arrays. The batch is padded to a
        power-of-two size with copies of member 0 so XLA compiles one
        program per (template, size bucket), not per exact size."""
        nbatch = len(entries)
        padded = 2  # the smallest precompiled bucket (solo dispatches
        while padded < nbatch:  # of a warm template pad up to it)
            padded *= 2
        nparams = len(entries[0].values)
        out = []
        for pi in range(nparams):
            vals = [m.values[pi][0] for m in entries]
            nulls = [m.values[pi][1] for m in entries]
            vals += [vals[0]] * (padded - nbatch)
            nulls += [nulls[0]] * (padded - nbatch)
            out.append((np.asarray(vals), np.asarray(nulls, dtype=bool)))
        if not out:
            # parameterless batch (identical literal-free statements):
            # vmap still needs a mapped axis to size the batch
            out.append((np.zeros(padded, dtype=np.int32),
                        np.zeros(padded, dtype=bool)))
        return tuple(out)

    def _fan_out(self, out, plan, entries: List[_Pending],
                 device_us: int) -> None:
        """Slice the batched output back into per-member QueryResults
        (member i owns batch row i -- ordering is positional by
        construction). ONE host conversion covers the whole batch;
        members then slice numpy views and row-select by their active
        mask BEFORE any per-row decode, so fan-out cost tracks result
        rows, not table capacity."""
        from ..block import Batch as _Batch
        from .runner import _batch_to_result
        from .stats import QueryStats
        nbatch = len(entries)
        host = jax.tree_util.tree_map(np.asarray, out)
        for i, m in enumerate(entries):
            idx = np.nonzero(host.active[i])[0]
            cols = tuple(
                jax.tree_util.tree_map(lambda x, _i=i: x[_i][idx], col)
                for col in host.columns)
            out_i = _Batch(cols, np.ones(len(idx), dtype=bool))
            res = _batch_to_result(out_i, plan.root)
            qs = QueryStats()
            qs.wall_us = device_us
            qs.output_rows = res.row_count
            qs.counters["batched_queries"] = 1
            qs.counters["batch_size"] = nbatch
            res.query_stats = qs
            res.stats = {"batch": {"size": float(nbatch),
                                   "device_us": float(device_us)}}
            m.result = res
            _note_query(_QUERY_BATCH, m.query_id, nbatch)

    def _account(self, key, entries: List[_Pending],
                 device_us: int) -> None:
        nbatch = len(entries)
        with _TOTALS_LOCK:
            if nbatch > 1:
                _TOTALS["batches"] += 1
                _TOTALS["batched_queries"] += nbatch
                _TOTALS["last_batch_size"] = nbatch
                _TOTALS["max_batch_size"] = max(
                    _TOTALS["max_batch_size"], nbatch)
            else:
                # a batch-of-1 riding a warm template program: counted
                # apart so occupancy stats keep meaning "co-batched"
                _TOTALS["solo_dispatches"] += 1
        # the profiler attributes the batched dispatch to the template
        # fingerprint -- the same identity its plan-cache entry lives
        # under -- so /v1/profile shows the dispatch amortization; ONE
        # registry fold for the whole batch, every member query id
        # cross-linked for history/flight-dump attribution
        from .profiler import note_query_kernel, record_call
        first = entries[0]
        record_call(key[0], label=f"batched[{nbatch}]",
                    device_us=device_us,
                    rows_out=sum(m.result.row_count for m in entries
                                 if m.result),
                    query_id=first.query_id,
                    trace_id=_trace_str(first.trace_id, first.query_id))
        note_query_kernel(key[0],
                          [m.query_id for m in entries[1:]])
        if nbatch > 1:
            from ..server.metrics import observe_histogram
            observe_histogram("presto_tpu_batch_occupancy_queries",
                              float(nbatch),
                              trace_id=_trace_str(first.trace_id,
                                                  first.query_id))

    def _serial_fallback(self, entries: List[_Pending], sf: float,
                         reason: str) -> None:
        """Collapse: run every member through the normal serial engine
        path on this thread (each result is exactly what the unbatched
        execution produces). Per-member errors stay per-member."""
        with _TOTALS_LOCK:
            _COLLAPSES[reason] = _COLLAPSES.get(reason, 0) + 1
        from .runner import run_query
        for m in entries:
            try:
                m.result = run_query(
                    m.root, sf=sf, session=m.session,
                    query_id=m.query_id, prepared=True,
                    trace_id=m.trace_id)
            except BaseException as e:  # noqa: BLE001 - deliver to the
                m.error = e             # member's waiting thread

    def snapshot(self) -> dict:
        """Live view for /v1/cluster: forming-queue depth per key plus
        the process totals."""
        with self._lock:
            pending = [{"fingerprint": k[0][:12],
                        "queued": len(g.entries)}
                       for k, g in self._forming.items()]
        t = batching_totals()
        avg = (t["batched_queries"] / t["batches"]) if t["batches"] \
            else 0.0
        return {"batchesDispatched": t["batches"],
                "queriesBatched": t["batched_queries"],
                "soloDispatches": t["solo_dispatches"],
                "collapses": t["collapses"],
                "lastBatchSize": t["last_batch_size"],
                "maxBatchSize": t["max_batch_size"],
                "avgOccupancy": round(avg, 2),
                "forming": pending}


def _trace_str(trace_id, query_id: str) -> str:
    from ..server.tracing import TraceContext
    if isinstance(trace_id, TraceContext):
        return trace_id.trace_id
    return str(trace_id or query_id)


_EXEC_LOCK = OrderedLock("batching._EXEC_LOCK")
_EXECUTOR: Optional[BatchingExecutor] = None


def get_batching_executor() -> BatchingExecutor:
    global _EXECUTOR
    with _EXEC_LOCK:
        if _EXECUTOR is None:
            _EXECUTOR = BatchingExecutor()
        return _EXECUTOR


def set_batching_executor(executor: Optional[BatchingExecutor]) -> None:
    global _EXECUTOR
    with _EXEC_LOCK:
        _EXECUTOR = executor


def batching_snapshot() -> dict:
    return get_batching_executor().snapshot()
