"""Continuous per-kernel profiler: device time attributed to compiled
kernels, always on.

The observability gap this closes: QueryStats says how long the
``execute`` stage took for ONE query, and /v1/metrics says how much
device time the process burned in total -- but neither says WHICH
compiled kernel burned it. A p99 regression after a planner change, or
one hot dashboard query dominating a worker, is invisible until
someone re-runs bench.py by hand. The reference engine lives on
exactly this attribution (the native worker's per-operator runtime
stats; "Accelerating Presto with GPUs" finds accelerator engines need
per-kernel device-time accounting to be operable at all).

Model: every executed program is keyed by its PLAN-CACHE FINGERPRINT
(exec/plan_cache.plan_fingerprint -- the same identity the compiled
executable is cached under, so profile rows and cache entries describe
the same object). Each entry accumulates calls, device wall time
(the ``block_until_ready`` delta around the runner's existing sync
point -- host-observed device occupancy, the only granularity one
fused XLA program exposes), rows/bytes in and out, retrace count
(dispatches that paid XLA compile), and carries plan-node provenance
(a compact node-chain label + scanned tables) plus the kernaudit K005
intermediate-footprint estimate when auditing ran.

Surfaces:
  * ``GET /v1/profile`` on a worker: this process's slice
    (:func:`profile_doc`).
  * ``GET /v1/profile`` on the statement tier: cluster-merged
    (:func:`cluster_profile_doc` pulls worker slices and folds them by
    fingerprint; slices are deduplicated by ``processId`` so two
    servers sharing one process -- the test topology -- count once).
  * ``SELECT * FROM system.kernels`` (connectors/system.py).
  * EXPLAIN ANALYZE's "kernels" section and flight-recorder dumps
    (cross-linked by fingerprint via :func:`profile_for_query`).

The registry is process-wide and bounded (LRU on last call). Gating:
session property ``continuous_profiling`` (default on), process env
``PRESTO_TPU_PROFILE`` (registered in
``exec.plan_cache.KERNEL_MODE_ENVS`` -- it does not change lowered
programs, but registration keeps tpulint R001's one-list-of-ambient-
knobs contract airtight).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import uuid
from typing import Dict, List, Optional

from ..utils.locks import OrderedLock

__all__ = ["profiling_enabled", "record_call", "note_footprint",
           "note_query_kernel",
           "profile_snapshot", "profile_doc", "profile_for_query",
           "query_fingerprints", "merge_kernel_rows",
           "cluster_profile_doc",
           "clear_profiler", "set_capacity", "plan_label", "plan_tables",
           "PROFILE_ENV"]

PROFILE_ENV = "PRESTO_TPU_PROFILE"

# one id per process: cluster merges deduplicate slices by it, so a
# coordinator that can see the same process through two server shells
# (in-process test clusters) folds that slice exactly once
_PROCESS_ID = uuid.uuid4().hex


def profiling_enabled(session) -> bool:
    """Session property ``continuous_profiling``; process default from
    PRESTO_TPU_PROFILE (default ON -- continuous means continuous).
    The env name is spelled literally so tpulint R001 can prove it is
    registered in KERNEL_MODE_ENVS."""
    import os
    env_on = os.environ.get("PRESTO_TPU_PROFILE", "1") \
        not in ("0", "", "false")
    from ..utils.config import session_flag
    return session_flag(session, "continuous_profiling", env_on)


@dataclasses.dataclass
class KernelProfile:
    """One compiled kernel's accumulated profile. Merges by fingerprint
    with the usual law: sums add, maxes max -- associative and
    commutative, like QueryStats."""
    fingerprint: str
    label: str = ""
    tables: str = ""
    calls: int = 0
    device_us: int = 0
    max_device_us: int = 0
    rows_in: int = 0
    bytes_in: int = 0
    rows_out: int = 0
    bytes_out: int = 0
    retraces: int = 0
    footprint_bytes: int = 0   # kernaudit K005 estimate (max seen)
    last_trace_id: str = ""

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, doc: dict) -> "KernelProfile":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in known})

    def merge(self, other: "KernelProfile") -> "KernelProfile":
        assert self.fingerprint == other.fingerprint
        return KernelProfile(
            fingerprint=self.fingerprint,
            label=self.label or other.label,
            tables=self.tables or other.tables,
            calls=self.calls + other.calls,
            device_us=self.device_us + other.device_us,
            max_device_us=max(self.max_device_us, other.max_device_us),
            rows_in=self.rows_in + other.rows_in,
            bytes_in=self.bytes_in + other.bytes_in,
            rows_out=self.rows_out + other.rows_out,
            bytes_out=self.bytes_out + other.bytes_out,
            retraces=self.retraces + other.retraces,
            footprint_bytes=max(self.footprint_bytes,
                                other.footprint_bytes),
            last_trace_id=self.last_trace_id or other.last_trace_id)


# -- process registry ----------------------------------------------------

# engine threads (run_query), request handlers (/v1/profile, system
# tables) and the flight recorder all touch the registry
_LOCK = OrderedLock("profiler._LOCK")
_REGISTRY: "collections.OrderedDict[str, KernelProfile]" = \
    collections.OrderedDict()
_MAX_ENTRIES = 512
# query id -> fingerprints it executed (the flight-dump cross-link);
# bounded like the registry
_QUERY_KERNELS: "collections.OrderedDict[str, List[str]]" = \
    collections.OrderedDict()
_QUERY_KERNELS_MAX = 256


def set_capacity(max_entries: int) -> int:
    """Bound the registry (tests exercise eviction); returns the
    previous cap."""
    global _MAX_ENTRIES
    with _LOCK:
        prev = _MAX_ENTRIES
        _MAX_ENTRIES = max(1, int(max_entries))
        while len(_REGISTRY) > _MAX_ENTRIES:
            _REGISTRY.popitem(last=False)
    return prev


def clear_profiler() -> None:
    with _LOCK:
        _REGISTRY.clear()
        _QUERY_KERNELS.clear()


def record_call(fingerprint: str, label: str = "", tables: str = "",
                device_us: int = 0, rows_in: int = 0, bytes_in: int = 0,
                rows_out: int = 0, bytes_out: int = 0,
                retraced: bool = False,
                query_id: Optional[str] = None,
                trace_id: Optional[str] = None) -> None:
    """Fold one executed dispatch into the registry (never raises --
    this runs on the query hot path, right after the device sync)."""
    try:
        with _LOCK:
            p = _REGISTRY.get(fingerprint)
            if p is None:
                p = _REGISTRY[fingerprint] = KernelProfile(fingerprint)
                while len(_REGISTRY) > _MAX_ENTRIES:
                    _REGISTRY.popitem(last=False)
            else:
                _REGISTRY.move_to_end(fingerprint)
            if label and not p.label:
                p.label = label
            if tables and not p.tables:
                p.tables = tables
            p.calls += 1
            p.device_us += int(device_us)
            p.max_device_us = max(p.max_device_us, int(device_us))
            p.rows_in += int(rows_in)
            p.bytes_in += int(bytes_in)
            p.rows_out += int(rows_out)
            p.bytes_out += int(bytes_out)
            if retraced:
                p.retraces += 1
            if trace_id:
                p.last_trace_id = str(trace_id)
            if query_id:
                fps = _QUERY_KERNELS.get(query_id)
                if fps is None:
                    fps = _QUERY_KERNELS[query_id] = []
                    while len(_QUERY_KERNELS) > _QUERY_KERNELS_MAX:
                        _QUERY_KERNELS.popitem(last=False)
                else:
                    _QUERY_KERNELS.move_to_end(query_id)
                if fingerprint not in fps:
                    fps.append(fingerprint)
    except Exception as e:  # noqa: BLE001 - profiling must never fail
        # the query it observes; leave the counted trace
        from ..server.metrics import record_suppressed
        record_suppressed("profiler", "record_call", e)


def note_query_kernel(fingerprint: str, query_ids: List[str]) -> None:
    """Cross-link several query ids to one executed fingerprint in a
    single registry pass -- the batched-dispatch path's attribution
    (record_call folds the dispatch once for the leader; followers
    only need the query->fingerprint edge history/flight dumps read)."""
    with _LOCK:
        for query_id in query_ids:
            fps = _QUERY_KERNELS.get(query_id)
            if fps is None:
                fps = _QUERY_KERNELS[query_id] = []
                while len(_QUERY_KERNELS) > _QUERY_KERNELS_MAX:
                    _QUERY_KERNELS.popitem(last=False)
            else:
                _QUERY_KERNELS.move_to_end(query_id)
            if fingerprint not in fps:
                fps.append(fingerprint)


def note_footprint(fingerprint: str, peak_bytes: int) -> None:
    """Attach the kernaudit K005 intermediate-footprint estimate to a
    kernel (max across audits; creates the entry so an audited-but-not-
    yet-dispatched kernel is visible too)."""
    with _LOCK:
        p = _REGISTRY.get(fingerprint)
        if p is None:
            p = _REGISTRY[fingerprint] = KernelProfile(fingerprint)
            while len(_REGISTRY) > _MAX_ENTRIES:
                _REGISTRY.popitem(last=False)
        p.footprint_bytes = max(p.footprint_bytes, int(peak_bytes))


def profile_snapshot(top: Optional[int] = None) -> List[dict]:
    """Registry snapshot as JSON rows, hottest (total device time)
    first."""
    with _LOCK:
        rows = [dataclasses.replace(p) for p in _REGISTRY.values()]
    rows.sort(key=lambda p: (-p.device_us, p.fingerprint))
    if top is not None:
        rows = rows[:top]
    return [p.to_json() for p in rows]


def profile_for_query(query_id: str, top: Optional[int] = None
                      ) -> List[dict]:
    """The kernels a query id executed, cross-linked by fingerprint to
    their CURRENT registry rows (the flight-dump embed)."""
    with _LOCK:
        fps = list(_QUERY_KERNELS.get(query_id, ()))
        rows = [dataclasses.replace(_REGISTRY[fp]) for fp in fps
                if fp in _REGISTRY]
    rows.sort(key=lambda p: (-p.device_us, p.fingerprint))
    if top is not None:
        rows = rows[:top]
    return [p.to_json() for p in rows]


def query_fingerprints(query_id: str) -> List[str]:
    """The plan-cache fingerprints a query id dispatched, in execution
    order (the query-history archive's plan identity; a write query's
    inner SELECT contributes its own fingerprint too)."""
    with _LOCK:
        return list(_QUERY_KERNELS.get(query_id, ()))


def profile_doc() -> dict:
    """This process's /v1/profile slice."""
    return {"processId": _PROCESS_ID, "kernels": profile_snapshot()}


def merge_kernel_rows(docs: List[dict]) -> List[dict]:
    """Fold per-process slices into one per-kernel table. Input docs
    are /v1/profile documents; slices sharing a processId are counted
    once (two server shells over one process report the same
    registry). Order-independent by KernelProfile.merge's law."""
    seen_processes = set()
    merged: Dict[str, KernelProfile] = {}
    for doc in docs:
        pid = doc.get("processId") or f"anon-{id(doc):x}"
        if pid in seen_processes:
            continue
        seen_processes.add(pid)
        for row in doc.get("kernels") or ():
            p = KernelProfile.from_json(row)
            if not p.fingerprint:
                continue
            have = merged.get(p.fingerprint)
            merged[p.fingerprint] = have.merge(p) if have else p
    out = sorted(merged.values(),
                 key=lambda p: (-p.device_us, p.fingerprint))
    return [p.to_json() for p in out]


def cluster_profile_doc(worker_urls=(), timeout: float = 3.0) -> dict:
    """The coordinator-side merge: this process's slice plus every
    reachable worker's ``GET /v1/profile``, folded by fingerprint.
    Pulls ride the shared best-effort helper
    (server/client.pull_worker_docs) so the internal bearer/TLS/trace
    headers -- and the skip-and-count-dead-workers contract -- stay
    identical to the history merge's."""
    from ..server.client import pull_worker_docs
    pulled, workers_seen = pull_worker_docs(
        worker_urls, timeout, lambda c: c.profile(), "profiler")
    docs = [profile_doc(), *pulled]
    return {"processId": _PROCESS_ID, "cluster": True,
            "workersPulled": workers_seen,
            "kernels": merge_kernel_rows(docs)}


# -- plan provenance -----------------------------------------------------


def plan_label(root, max_len: int = 160) -> str:
    """Compact plan-node provenance for a fingerprint: the node-type
    chain in DFS preorder with scan tables inlined, capped."""
    parts: List[str] = []

    def walk(n, depth):
        if len(parts) > 24:
            return
        name = type(n).__name__.replace("Node", "")
        table = getattr(n, "table", None)
        conn = getattr(n, "connector", None)
        if table and conn:
            name += f"[{conn}.{table}]"
        step = getattr(n, "step", None)
        if step and name.startswith("Aggregation"):
            name += f"({step})"
        parts.append(name)
        for s in getattr(n, "sources", ()):
            walk(s, depth + 1)

    walk(root, 0)
    label = " > ".join(parts)
    return label[:max_len]


def plan_tables(root) -> str:
    """Comma-joined connector.table list of a plan's scans."""
    out: List[str] = []

    def walk(n):
        table = getattr(n, "table", None)
        conn = getattr(n, "connector", None)
        if table and conn and f"{conn}.{table}" not in out:
            out.append(f"{conn}.{table}")
        for s in getattr(n, "sources", ()):
            walk(s)

    walk(root)
    return ",".join(out)
