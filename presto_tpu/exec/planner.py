"""Plan -> executable lowering: the LocalExecutionPlanner analog.

Reference surface: sql/planner/LocalExecutionPlanner.java:480+ (PlanNode
visitor emitting OperatorFactory chains: visitTableScan:1711,
visitAggregation:1459, visitJoin:2033, visitExchange:3224) and, on the
native side, PrestoToVeloxQueryPlan.cpp (PlanFragment -> Velox plan).

Here lowering emits ONE pure function over scan batches. Stage
boundaries (REMOTE exchanges) lower to mesh collectives, so a
multi-stage distributed plan becomes a single SPMD program under
shard_map -- XLA gang-schedules what SqlQueryScheduler orchestrates by
hand. Without a mesh the same tree lowers to a single-chip program and
REMOTE exchanges collapse to no-ops (single-worker cluster).

Blocking operators map as: aggregation -> dense-table group_by; join
build -> sorted build side inside hash_join; sort/topN -> lax.sort.
Dynamic result sizes surface as (active-mask, overflow-flag) pairs;
the runner owns the rerun-with-bigger-buckets policy (the memory/
spill feedback loop of the reference's Driver yield + revoke).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import types as T
from ..block import Batch
from ..expr.compile import compile_filter, compile_projections
from ..ops.aggregation import finalize_states, group_by, merge_partials
from ..ops.join import hash_join, semi_join_mask
from ..ops.misc import distinct as distinct_op
from ..ops.misc import limit as limit_op
from ..ops.sort import SortKey, sort_batch, top_n
from ..parallel.exchange import (broadcast_build, exchange_by_hash,
                                 exchange_by_range, gather_to_root)
from ..parallel.mesh import WORKERS_AXIS
from ..plan import nodes as N

__all__ = ["compile_plan", "CompiledPlan"]


@dataclasses.dataclass
class CompiledPlan:
    """fn(scans: Dict[node_id, Batch]) -> (Batch, overflow_flag).
    `scan_nodes` lists the TableScanNode/ValuesNode leaves in the order
    their batches must be supplied; distributed plans expect each scan
    batch shard-able along axis 0 by the mesh."""
    fn: Callable
    scan_nodes: List[N.PlanNode]
    output_types: List[T.Type]
    distributed: bool
    # the exact plan object this program was traced from; a cache hit
    # must route node-id-keyed side computations (dynamic filters,
    # output names) through THIS tree, not the structurally-equal twin
    # the caller handed in (ids differ across plannings)
    root: "N.PlanNode" = None


def _collect_scans(node: N.PlanNode, out: List[N.PlanNode], _seen=None):
    """Leaf collection, identity-deduped: a plan DAG (shared CTE
    subtree) stages each shared scan ONCE."""
    if _seen is None:
        _seen = set()
    if id(node) in _seen:
        return
    _seen.add(id(node))
    if isinstance(node, (N.TableScanNode, N.ValuesNode, N.RemoteSourceNode)):
        out.append(node)
    for s in node.sources:
        _collect_scans(s, out, _seen)


def compile_plan(root: N.PlanNode, mesh=None,
                 default_join_capacity: int = 1 << 16,
                 exchange_slot_scale: int = 1) -> CompiledPlan:
    """`exchange_slot_scale` geometrically grows every exchange's
    per-destination slot capacity (clamped at the sender's row capacity,
    where overflow is impossible): the runner's overflow->rerun policy
    passes 1, 2, 4, ... until the plan fits -- the memory-feedback
    analog of the reference's reserve/revoke loop."""
    scans: List[N.PlanNode] = []
    _collect_scans(root, scans)
    axis = WORKERS_AXIS
    dist = mesh is not None

    def _scaled_slot(base: int, sender_capacity: int) -> int:
        # a sender never has more than `sender_capacity` rows for any
        # one destination, so slots beyond that cannot overflow
        return min(base * exchange_slot_scale, max(sender_capacity, 1))

    def lower(node: N.PlanNode, inputs: Dict[str, Batch]) -> Batch:
        # identity memo: a shared subtree (CTE planned once -> plan DAG)
        # is traced once and its staged batch reused at every reference
        key = id(node)
        if key in _lower_memo:
            return _lower_memo[key]
        out = _lower(node, inputs)
        _lower_memo[key] = out
        return out

    def _lower(node: N.PlanNode, inputs: Dict[str, Batch]) -> Batch:
        if isinstance(node, (N.TableScanNode, N.ValuesNode,
                             N.RemoteSourceNode)):
            return inputs[node.id]
        if isinstance(node, N.FilterNode):
            return compile_filter(node.predicate)(lower(node.source, inputs))
        if isinstance(node, N.ProjectNode):
            return compile_projections(node.expressions)(lower(node.source, inputs))
        if isinstance(node, N.AggregationNode):
            src = lower(node.source, inputs)
            if node.step in ("FINAL", "INTERMEDIATE"):
                # both consume state tables; INTERMEDIATE re-emits
                # merged states (no finalization) for a further merge
                r = merge_partials(src, len(node.group_channels),
                                   node.aggregates, node.max_groups)
            else:  # SINGLE and PARTIAL share the kernel
                r = group_by(src, node.group_channels, node.aggregates,
                             node.max_groups)
            _note_overflow(r.overflow)
            out = r.batch
            if node.step in ("SINGLE", "FINAL"):
                out = finalize_states(out, len(node.group_channels),
                                      node.aggregates)
            if dist and not node.group_channels:
                gathered = (isinstance(node.source, N.ExchangeNode)
                            and node.source.kind == "GATHER"
                            and node.source.scope == "REMOTE")
                if node.step == "SINGLE" and not gathered:
                    raise ValueError(
                        "SINGLE global aggregation under a mesh would emit "
                        "per-shard partials; run AddExchanges "
                        "(plan.distribute) first -- run_query does this "
                        "automatically")
                if node.step == "FINAL" or gathered:
                    # after a GATHER the guaranteed single row belongs to
                    # worker 0 (where gathered rows are active); other
                    # workers would emit spurious empty-state rows
                    is_root = jax.lax.axis_index(axis) == 0
                    out = out.with_active(out.active & is_root)
            return out
        if isinstance(node, N.JoinNode):
            probe = lower(node.left, inputs)
            build = lower(node.right, inputs)
            right_replicated = (isinstance(node.right, N.ExchangeNode)
                                and node.right.kind == "REPLICATE"
                                and node.right.scope == "REMOTE")
            if dist and node.join_type in ("right", "full") \
                    and (node.distribution == "broadcast" or right_replicated):
                raise ValueError(
                    "RIGHT/FULL OUTER join under a mesh needs PARTITIONED "
                    "distribution (a replicated build side would emit its "
                    "unmatched rows once per worker); run AddExchanges "
                    "(plan.distribute) first -- run_query does this "
                    "automatically")
            if dist and node.distribution == "broadcast" \
                    and not right_replicated:  # exchange already gathered
                build = broadcast_build(build, axis)
            cap = node.out_capacity or default_join_capacity
            r = hash_join(probe, build, node.left_keys, node.right_keys,
                          cap, node.join_type, node.right_output_channels)
            _note_overflow(r.overflow)
            return r.batch
        if isinstance(node, N.SemiJoinNode):
            src = lower(node.source, inputs)
            filt = lower(node.filtering_source, inputs)
            filt_replicated = (isinstance(node.filtering_source, N.ExchangeNode)
                               and node.filtering_source.kind == "REPLICATE"
                               and node.filtering_source.scope == "REMOTE")
            if dist and not filt_replicated:
                filt = broadcast_build(filt, axis)
            sk = node.source_key if isinstance(node.source_key, list) \
                else [node.source_key]
            fk = node.filtering_key if isinstance(node.filtering_key, list) \
                else [node.filtering_key]
            m, mnull = semi_join_mask(src, filt, sk, fk,
                                      node.null_keys_match)
            from ..block import Column
            return Batch(src.columns + (Column(m, mnull, T.BOOLEAN),),
                         src.active)
        if isinstance(node, N.SortNode):
            return sort_batch(lower(node.source, inputs),
                              [SortKey(*k) for k in node.keys])
        if isinstance(node, N.TopNNode):
            return top_n(lower(node.source, inputs),
                         [SortKey(*k) for k in node.keys], node.count)
        if isinstance(node, N.LimitNode):
            return limit_op(lower(node.source, inputs), node.count)
        if isinstance(node, N.DistinctNode):
            keys = node.key_channels
            if keys is None:
                keys = list(range(len(node.output_types())))
            out, ovf = distinct_op(lower(node.source, inputs), keys,
                                   node.max_groups)
            _note_overflow(ovf)
            return out
        if isinstance(node, N.UnionNode):
            from ..block import concat_batches
            parts = [lower(s, inputs) for s in node.inputs]
            return concat_batches(parts)
        if isinstance(node, N.SampleNode):
            src = lower(node.source, inputs)
            # deterministic Bernoulli: row-index hash vs threshold
            from ..expr.functions import _mix64
            h = _mix64(jnp.arange(src.capacity, dtype=jnp.uint64))
            thresh = jnp.uint64(int(node.ratio * float(2**64 - 1)))
            return src.with_active(src.active & (h <= thresh))
        if isinstance(node, N.AssignUniqueIdNode):
            from ..block import Column
            src = lower(node.source, inputs)
            rid = jnp.arange(src.capacity, dtype=jnp.int64)
            if dist:
                widx = jax.lax.axis_index(axis).astype(jnp.int64)
                rid = rid | (widx << 40)  # task-salted high bits
            col = Column(rid, jnp.zeros(src.capacity, dtype=bool), T.BIGINT)
            return Batch(src.columns + (col,), src.active)
        if isinstance(node, N.MarkDistinctNode):
            from ..block import Column
            from ..ops.misc import mark_distinct
            src = lower(node.source, inputs)
            m, ovf = mark_distinct(src, node.key_channels, node.max_groups)
            _note_overflow(ovf)
            col = Column(m, jnp.zeros(src.capacity, dtype=bool), T.BOOLEAN)
            return Batch(src.columns + (col,), src.active)
        if isinstance(node, N.WindowNode):
            from ..ops.sort import SortKey as SK
            from ..ops.window import WindowSpec, window
            src = lower(node.source, inputs)
            # the 5th tuple slot is the function's int parameter:
            # ntile's bucket count, lag/lead's offset, nth_value's n
            specs = [WindowSpec(name, ch,
                                T.parse_type(ty) if isinstance(ty, str) else ty,
                                frame,
                                ntile_buckets=(k or 0) if name == "ntile" else 0,
                                offset=((1 if k is None else k)
                                        if name in ("lag", "lead",
                                                    "nth_value") else 1))
                     for name, ch, ty, frame, k in node.functions]
            return window(src, node.partition_channels,
                          [SK(*o) for o in node.order_keys], specs)
        if isinstance(node, N.RowNumberNode):
            from ..ops.window import WindowSpec, window
            src = lower(node.source, inputs)
            out = window(src, node.partition_channels,
                         [SortKey(*k) for k in node.order_keys],
                         [WindowSpec("row_number")])
            if node.max_rows_per_partition is not None:
                rn = out.column(out.num_columns - 1)
                keep = out.active & (rn.values <= node.max_rows_per_partition)
                out = out.with_active(keep)
            return out
        if isinstance(node, N.UnnestNode):
            from ..ops.unnest import unnest as unnest_op
            src = lower(node.source, inputs)
            cap = node.out_capacity or src.capacity * 4
            out, ovf = unnest_op(src, node.array_channel, cap,
                                 node.with_ordinality)
            _note_overflow(ovf)
            return out
        if isinstance(node, N.GroupIdNode):
            from ..block import Column, concat_batches, null_like
            src = lower(node.source, inputs)
            keyset = set(node.key_channels)
            parts = []
            for gi, kept in enumerate(node.grouping_sets):
                cols = []
                for ci, c in enumerate(src.columns):
                    if ci in keyset and ci not in kept:
                        cols.append(null_like(c))
                    else:
                        cols.append(c)
                gid = Column(jnp.full(src.capacity, gi, dtype=jnp.int64),
                             jnp.zeros(src.capacity, dtype=bool), T.BIGINT)
                parts.append(Batch(tuple(cols) + (gid,), src.active))
            return concat_batches(parts)
        if isinstance(node, N.ExchangeNode):
            if node.kind == "MERGE" and dist and node.scope == "REMOTE":
                # MergeOperator analog on the mesh: sampled range
                # repartition + per-worker sort => globally sorted
                # DISTRIBUTED output (the full row set never lands on
                # one device). The local pre-sort below the exchange
                # (which the HTTP tier's producers need for the k-way
                # merge) is redundant here -- the post-exchange sort
                # orders everything -- so lowering skips it.
                src_node = node.source
                if isinstance(src_node, N.SortNode):
                    src_node = src_node.source
                inner = lower(src_node, inputs)
                n_workers = mesh.devices.size
                slot = _scaled_slot(
                    node.slot_capacity
                    or max(4 * inner.capacity // max(n_workers, 1), 64),
                    inner.capacity)
                from ..parallel.stages import _note_exchange
                _note_exchange("range", axis)
                out, ovf = exchange_by_range(inner, node.sort_keys, axis,
                                             slot)
                _note_overflow(ovf, scalable=True)
                return sort_batch(out, [SortKey(*k) for k in node.sort_keys])
            src = lower(node.source, inputs)
            if node.scope == "LOCAL" or not dist:
                return src
            from ..parallel.stages import _note_exchange
            if node.kind == "REPARTITION":
                slot = _scaled_slot(
                    node.slot_capacity or max(src.capacity, 1),
                    src.capacity)
                _note_exchange("hash", axis)
                out, ovf = exchange_by_hash(src, node.partition_channels,
                                            axis, slot)
                _note_overflow(ovf, scalable=True)
                return out
            if node.kind == "REPLICATE":
                _note_exchange("broadcast", axis)
                return broadcast_build(src, axis)
            if node.kind == "GATHER":
                # every worker receives all rows; only worker 0 keeps them
                # active so the global (concatenated) view has one copy
                _note_exchange("gather", axis)
                g = gather_to_root(src, axis)
                is_root = jax.lax.axis_index(axis) == 0
                return g.with_active(g.active & is_root)
            raise ValueError(node.kind)
        if isinstance(node, N.OutputNode):
            return lower(node.source, inputs)
        raise TypeError(type(node))

    overflow_box: List = []
    _lower_memo: Dict[int, Batch] = {}

    def _note_overflow(flag, scalable: bool = False):
        """scalable=True marks exchange-slot overflow, which the runner
        can cure by recompiling with a bigger exchange_slot_scale;
        join/group overflow needs bigger declared capacities instead."""
        overflow_box.append((flag, scalable))

    def run(scan_batches: Sequence[Batch]):
        overflow_box.clear()
        _lower_memo.clear()
        inputs = {n.id: b for n, b in zip(scans, scan_batches)}
        out = lower(root, inputs)
        hard = jnp.zeros((), dtype=bool)   # join/group capacity
        slots = jnp.zeros((), dtype=bool)  # exchange slots (rescalable)
        for f, scalable in overflow_box:
            if scalable:
                slots = slots | f
            else:
                hard = hard | f
        if dist:
            hard = jax.lax.psum(hard.astype(jnp.int32), axis) > 0
            slots = jax.lax.psum(slots.astype(jnp.int32), axis) > 0
        # bitmask: bit0 = hard (non-scalable), bit1 = exchange slots
        return out, hard.astype(jnp.int32) + 2 * slots.astype(jnp.int32)

    if dist:
        in_specs = tuple(P(WORKERS_AXIS) for _ in scans)
        fn = jax.shard_map(run, mesh=mesh, in_specs=(in_specs,),
                           out_specs=(P(WORKERS_AXIS), P()), check_vma=False)
    else:
        fn = run
    return CompiledPlan(fn, scans, root.output_types(), dist, root)
