"""Proven-safe buffer donation for region dispatches.

Closing the allocguard loop: kernaudit K006 (audit/passes/donation.py)
proves, per region program, which jit inputs are aliasable into an
output (shape+dtype-identical, not a passthrough); THIS module carries
the engine-side half of the proof obligation and applies the plan:

  * **engine deadness** -- only region-boundary intermediates whose
    LAST consumer is the dispatching region are candidates (the
    executor's refcounts, exec/runner._execute_regions). Scan-leaf
    batches are never donated: the host tier may still hold references
    (staging stats, fragment caches, test harnesses).
  * **overflow-incapable regions only** -- the rerun ladder re-reads
    the SAME input batches after a capacity overflow, which would be a
    use-after-free on donated buffers; a region whose operators cannot
    set overflow flags (filter/project/output/limit chains) is the
    donation surface.
  * **fallback, never failure** -- any error on the donation path
    (including the ``donation.apply`` failpoint) collapses to the
    normal undonated dispatch BEFORE any buffer is consumed, counted
    in ``presto_tpu_donation_fallbacks_total``.

The donating form compiles a separate wrapper program
(``donate_argnums=0`` over the dead-leaf tuple), memoized per (region
fingerprint, input signature, dead-leaf set); ``PRESTO_TPU_DONATION``
is registered in KERNEL_MODE_ENVS so the mode keys every cached
executable. HBM savings surface in the memory pool's per-query peak
(the intermediate's reservation shrinks by the donated bytes) and the
``presto_tpu_donated_bytes_total`` counter, gated by perfgate's
``peak_memory_bytes`` band.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence, Tuple

from .. import failpoints
from ..plan import nodes as N
from ..utils.locks import OrderedLock

__all__ = ["DONATION_ENV", "donation_enabled", "overflow_incapable",
           "prepare_donation", "PreparedDonation", "donation_totals",
           "note_donation", "note_fallback", "clear_donation_state"]

DONATION_ENV = "PRESTO_TPU_DONATION"

_LEAF_TYPES = (N.TableScanNode, N.ValuesNode, N.RemoteSourceNode)

# operators that can NEVER set an overflow flag: pure mask/compute
# chains with no capacity-bounded state (joins, group tables, unnest
# and exchanges are the overflow producers -- see the dispatch
# ladder). Conservative by construction: an absent node type means
# "no donation", never a use-after-free.
_OVERFLOW_FREE = (N.FilterNode, N.ProjectNode, N.OutputNode,
                  N.LimitNode)


def donation_enabled(session) -> bool:
    """Session property ``buffer_donation``; process default from
    PRESTO_TPU_DONATION (default OFF). Spelled literally so tpulint
    R001 proves the knob is registered in KERNEL_MODE_ENVS."""
    import os
    env_on = os.environ.get("PRESTO_TPU_DONATION", "0") \
        not in ("0", "", "false")
    from ..utils.config import session_flag
    return session_flag(session, "buffer_donation", env_on)


def overflow_incapable(root: N.PlanNode) -> bool:
    """True when every operator in the region subtree is on the
    overflow-free whitelist (leaves excepted) -- the static half of
    the donation-safety proof the rerun ladder demands."""
    if isinstance(root, _LEAF_TYPES):
        return True
    if not isinstance(root, _OVERFLOW_FREE):
        return False
    return all(overflow_incapable(s) for s in root.sources)


# -- process totals (/v1/metrics presto_tpu_donation* families) ---------

# tpulint C001: dispatch threads bump, scrape threads read
_TOTALS_GUARDED_BY = {"_TOTALS_LOCK": ("_TOTALS",)}
_TOTALS_LOCK = OrderedLock("donation._TOTALS_LOCK")
_TOTALS = {"donations": 0, "donated_bytes": 0, "fallbacks": 0}


def note_donation(nbytes: int, leaves: int = 0) -> None:
    with _TOTALS_LOCK:
        _TOTALS["donations"] += 1
        _TOTALS["donated_bytes"] += int(nbytes)


def note_fallback() -> None:
    with _TOTALS_LOCK:
        _TOTALS["fallbacks"] += 1


def donation_totals() -> Dict[str, int]:
    with _TOTALS_LOCK:
        return dict(_TOTALS)


# -- donation-plan memo + donating-wrapper cache ------------------------

_MEMO_LOCK = OrderedLock("donation._MEMO_LOCK")
_MEMO_GUARDED_BY = {"_MEMO_LOCK": ("_MEMO",)}
_MEMO: "collections.OrderedDict[tuple, Optional[PreparedDonation]]" = \
    collections.OrderedDict()
_MEMO_CAP = 256


def clear_donation_state() -> None:
    """Tests: drop the wrapper memo and zero the process totals."""
    with _MEMO_LOCK:
        _MEMO.clear()
    with _TOTALS_LOCK:
        for k in _TOTALS:
            _TOTALS[k] = 0


class PreparedDonation:
    """A memoized donating dispatch: the jitted wrapper (its leading
    tuple argument is donated), the flat leaf indices it donates, and
    the bytes donation saves. One instance per (fingerprint,
    signature, dead-leaf set) -- reusing the same callable keeps the
    jit executable cache warm across queries."""

    __slots__ = ("wrapper", "donate_idx", "donated_bytes", "_treedef")

    def __init__(self, fn, treedef, nleaves: int,
                 donate_idx: Tuple[int, ...], donated_bytes: int):
        import jax
        self.donate_idx = donate_idx
        self.donated_bytes = int(donated_bytes)
        self._treedef = treedef
        donate_set = frozenset(donate_idx)
        kept_idx = tuple(i for i in range(nleaves)
                         if i not in donate_set)

        def _call(donated, kept):
            leaves: List = [None] * nleaves
            for i, leaf in zip(donate_idx, donated):
                leaves[i] = leaf
            for i, leaf in zip(kept_idx, kept):
                leaves[i] = leaf
            return fn(jax.tree_util.tree_unflatten(treedef, leaves))

        self.wrapper = jax.jit(_call, donate_argnums=0)

    def dispatch(self, batches: Sequence):
        """Run the donating form over `batches` (same structure the
        plan memoized on). The donated leaves are DEAD to the caller
        after this returns."""
        import warnings

        import jax
        leaves = jax.tree_util.tree_leaves(tuple(batches))
        donate_set = frozenset(self.donate_idx)
        donated = tuple(leaves[i] for i in self.donate_idx)
        kept = tuple(leaf for i, leaf in enumerate(leaves)
                     if i not in donate_set)
        with warnings.catch_warnings():
            # CPU backends ignore donation ("Some donated buffers were
            # not usable") -- the aliasing only lands on TPU; the
            # ledger model is the TPU behavior either way
            warnings.filterwarnings("ignore", message=".*[Dd]onat.*")
            return self.wrapper(donated, kept)


def _signature(leaves) -> tuple:
    return tuple((tuple(leaf.shape), str(leaf.dtype)) for leaf in leaves)


def prepare_donation(rfp: str, fn, batches: Sequence,
                     dead_leaf_idx: Sequence[int]
                     ) -> Optional[PreparedDonation]:
    """Build (or recall) the donating dispatch for one region program:
    intersect the K006 aliasing proof over ``fn``'s jaxpr with the
    engine's dead-leaf set and wrap the provable subset in a
    ``donate_argnums`` jit. Returns None when nothing is provably
    donatable. Errors (including the ``donation.apply`` failpoint)
    propagate -- the caller falls back to the undonated dispatch;
    no buffer has been consumed yet."""
    if failpoints.ARMED:
        failpoints.hit("donation.apply")
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tuple(batches))
    dead = frozenset(int(i) for i in dead_leaf_idx)
    key = (rfp, _signature(leaves), tuple(sorted(dead)))
    with _MEMO_LOCK:
        if key in _MEMO:
            _MEMO.move_to_end(key)
            return _MEMO[key]

    from ..audit.passes.donation import donation_plan
    closed = jax.make_jaxpr(fn)(tuple(batches))
    plan = donation_plan(closed.jaxpr)
    chosen = [d for d in plan["donatable"] if d["arg"] in dead]
    prepared: Optional[PreparedDonation] = None
    if chosen:
        prepared = PreparedDonation(
            fn, treedef, len(leaves),
            donate_idx=tuple(sorted(d["arg"] for d in chosen)),
            donated_bytes=sum(d["bytes"] for d in chosen))
    with _MEMO_LOCK:
        _MEMO[key] = prepared
        _MEMO.move_to_end(key)
        while len(_MEMO) > _MEMO_CAP:
            _MEMO.popitem(last=False)
    return prepared
