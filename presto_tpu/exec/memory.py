"""Hierarchical memory accounting: the MemoryPool / memory-context analog.

Reference surface: memory/MemoryPool.java:45 (reserve:124, tryReserve:191),
the QueryContext -> TaskContext -> PipelineContext -> OperatorContext
chain, and presto-memory-context's AggregatedMemoryContext /
LocalMemoryContext (user/system/revocable tags).

On TPU the managed resource is HBM. XLA owns actual allocation; this
layer does *admission* accounting: planned batch/table footprints are
reserved against a per-worker pool before a pipeline is launched, so
the exec layer can choose bucket sizes, refuse queries that cannot fit
(query_max_memory), or trigger the host-offload spill tier (the
revocable-memory path) before the device OOMs.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional

from .. import failpoints
from ..block import Batch

__all__ = ["MemoryPool", "MemoryContext", "MemoryReservationError",
           "batch_bytes"]


class MemoryReservationError(RuntimeError):
    pass


def batch_bytes(batch: Batch) -> int:
    """Planned HBM footprint of a Batch.

    Batches (and every Block kind) are registered pytrees, so the
    footprint is the sum over tree leaves — structurally complete for
    any present or future column layout (Int128Column's hi/lo lanes,
    dictionary indices, string char matrices) with no per-kind branch
    to forget. Reference: memory accounting on Page.getSizeInBytes().
    """
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(batch):
        total += int(leaf.size) * leaf.dtype.itemsize
    return total


class MemoryPool:
    """Per-worker reservation pool (MemoryPool.java:45 analog), with
    revocation: holders of REVOCABLE reservations (spillable state --
    partial group tables, join build sides) register a callback that
    moves their device state to host DRAM; a reservation that would
    exceed capacity triggers revocation (largest holdings first, the
    MemoryRevokingScheduler's TASK_REVOCABLE_MEMORY policy) before it
    fails."""

    def __init__(self, capacity_bytes: int, name: str = "general",
                 admission_timeout_s: float = 0.0):
        """`admission_timeout_s` > 0 makes a contended reserve() WAIT
        for other queries to release (bounded by the timeout) instead of
        failing immediately -- the admission-queue behavior concurrent
        worker tasks need (a request that exceeds pool capacity outright
        still fails fast; only contention waits)."""
        self.name = name
        self.capacity = capacity_bytes
        self.admission_timeout_s = admission_timeout_s
        self._reserved: Dict[str, int] = {}
        # revocable registrations: id -> (query_id, bytes, callback)
        self._revocables: Dict[int, tuple] = {}
        self._next_rid = 0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self.revoked_bytes = 0  # counter: surfaced in stats/EXPLAIN
        # high-water marks (telemetry: /v1/metrics + QueryStats.peak)
        self.peak_bytes = 0
        self._query_peak: Dict[str, int] = {}

    @property
    def reserved_bytes(self) -> int:
        with self._lock:
            return sum(self._reserved.values())

    @property
    def free_bytes(self) -> int:
        return self.capacity - self.reserved_bytes

    def register_revocable(self, query_id: str, bytes_: int, revoke_cb
                           ) -> int:
        """Reserve `bytes_` as revocable state; `revoke_cb()` must move
        the state off-device and returns the bytes actually freed.
        Returns a registration id for unregister_revocable."""
        self.reserve(query_id, bytes_)
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            self._revocables[rid] = (query_id, bytes_, revoke_cb)
        return rid

    def unregister_revocable(self, rid: int):
        with self._lock:
            entry = self._revocables.pop(rid, None)
        if entry is not None:
            self.free(entry[0], entry[1])

    def _revoke(self, needed: int) -> int:
        """Revoke registrations (largest first) until `needed` bytes are
        freed or none remain. Called WITHOUT the lock held (callbacks do
        device work). Revocation releases the WHOLE registration: the
        callback's contract is to move all of that state off-device, and
        the reservation is freed even if it raises (the state owner can
        no longer count on the reservation either way -- no residue may
        leak into the pool)."""
        freed_total = 0
        while freed_total < needed:
            with self._lock:
                if not self._revocables:
                    break
                rid, (qid, bytes_, cb) = max(
                    self._revocables.items(), key=lambda kv: kv[1][1])
                del self._revocables[rid]
            try:
                cb()
            finally:
                self.free(qid, bytes_)
                with self._lock:
                    self.revoked_bytes += bytes_
                freed_total += bytes_
        return freed_total

    def reserve(self, query_id: str, bytes_: int):
        """Failure first triggers revocation of spillable state; then,
        when the pool is merely CONTENDED (the request alone would fit
        an empty pool) and admission_timeout_s is set, waits for other
        queries to release; only then does it raise -- the caller then
        downsizes buckets or spills its own inputs."""
        import time as _time
        if failpoints.ARMED:
            try:
                failpoints.hit("memory.reserve")
            except failpoints.InjectedOOM as e:
                # the injected fault speaks this pool's native refusal
                # surface, so callers exercise their REAL degrade paths
                raise MemoryReservationError(str(e)) from None
        deadline = _time.time() + self.admission_timeout_s
        revoke_tried = False
        while True:
            with self._cv:
                total = sum(self._reserved.values()) + bytes_
                if total <= self.capacity:
                    mine = self._reserved.get(query_id, 0) + bytes_
                    self._reserved[query_id] = mine
                    self.peak_bytes = max(self.peak_bytes, total)
                    self._query_peak[query_id] = max(
                        self._query_peak.get(query_id, 0), mine)
                    return
                shortfall = total - self.capacity
                can_revoke = bool(self._revocables) and not revoke_tried
            if can_revoke:
                revoke_tried = self._revoke(shortfall) <= 0
                continue
            remaining = deadline - _time.time()
            if bytes_ <= self.capacity and remaining > 0:
                with self._cv:
                    self._cv.wait(min(0.05, remaining))
                revoke_tried = False  # new revocables may have appeared
                continue
            raise MemoryReservationError(
                f"pool {self.name}: reserve {bytes_} for {query_id} "
                f"exceeds capacity {self.capacity} "
                f"(reserved {self.reserved_bytes})")

    def try_reserve(self, query_id: str, bytes_: int) -> bool:
        try:
            self.reserve(query_id, bytes_)
            return True
        except MemoryReservationError:
            return False

    def free(self, query_id: str, bytes_: Optional[int] = None):
        with self._cv:
            cur = self._reserved.get(query_id, 0)
            if bytes_ is None or bytes_ >= cur:
                self._reserved.pop(query_id, None)
            else:
                self._reserved[query_id] = cur - bytes_
            self._cv.notify_all()  # admission waiters re-check

    def note_usage(self, query_id: str, bytes_: int):
        """Unconditional observed-usage accounting (NO admission
        control): record bytes XLA has already materialized -- region
        -boundary intermediates in the per-op executor -- against the
        query's ledger and both high-water marks. Admission happens
        up-front on planned scan footprints; refusing a query over an
        intermediate that already exists on device would abort work
        the pool cannot reclaim anyway. Never blocks, never raises;
        pair every call with free()."""
        with self._cv:
            mine = self._reserved.get(query_id, 0) + int(bytes_)
            self._reserved[query_id] = mine
            total = sum(self._reserved.values())
            self.peak_bytes = max(self.peak_bytes, total)
            self._query_peak[query_id] = max(
                self._query_peak.get(query_id, 0), mine)

    def query_bytes(self, query_id: str) -> int:
        with self._lock:
            return self._reserved.get(query_id, 0)

    def query_peak_bytes(self, query_id: str, pop: bool = False) -> int:
        """High-water reservation of one query (QueryStats.peak memory).
        ``pop=True`` also forgets it (called once the query is done, so
        the map stays bounded by in-flight queries)."""
        with self._lock:
            if pop:
                return self._query_peak.pop(query_id, 0)
            return self._query_peak.get(query_id, 0)

    def note_audit_estimate(self, query_id: str, bytes_: int) -> bool:
        """Fold the kernel auditor's K005 planned-peak estimate into the
        query's high-water accounting (audit/passes/footprint.py). The
        scan-reservation charge only covers staged INPUTS; the IR
        estimate also sees the program's intermediates, so the max of
        the two is the better QueryStats.peak answer. Returns True when
        the estimate alone exceeds pool capacity -- the caller's cue
        that this plan cannot fit even an empty pool."""
        with self._lock:
            cur = self._query_peak.get(query_id, 0)
            self._query_peak[query_id] = max(cur, int(bytes_))
        return int(bytes_) > self.capacity


@dataclasses.dataclass
class MemoryContext:
    """Operator-level child context (LocalMemoryContext analog)."""
    pool: MemoryPool
    query_id: str
    tag: str = "user"  # user | system | revocable
    local_bytes: int = 0

    def set_bytes(self, bytes_: int):
        delta = bytes_ - self.local_bytes
        if delta > 0:
            self.pool.reserve(self.query_id, delta)
        elif delta < 0:
            self.pool.free(self.query_id, -delta)
        self.local_bytes = bytes_

    def close(self):
        self.set_bytes(0)
