"""Hierarchical memory accounting: the MemoryPool / memory-context analog.

Reference surface: memory/MemoryPool.java:45 (reserve:124, tryReserve:191),
the QueryContext -> TaskContext -> PipelineContext -> OperatorContext
chain, and presto-memory-context's AggregatedMemoryContext /
LocalMemoryContext (user/system/revocable tags).

On TPU the managed resource is HBM. XLA owns actual allocation; this
layer does *admission* accounting: planned batch/table footprints are
reserved against a per-worker pool before a pipeline is launched, so
the exec layer can choose bucket sizes, refuse queries that cannot fit
(query_max_memory), or trigger the host-offload spill tier (the
revocable-memory path) before the device OOMs.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional

from ..block import Batch

__all__ = ["MemoryPool", "MemoryContext", "MemoryReservationError",
           "batch_bytes"]


class MemoryReservationError(RuntimeError):
    pass


def batch_bytes(batch: Batch) -> int:
    """Planned HBM footprint of a Batch.

    Batches (and every Block kind) are registered pytrees, so the
    footprint is the sum over tree leaves — structurally complete for
    any present or future column layout (Int128Column's hi/lo lanes,
    dictionary indices, string char matrices) with no per-kind branch
    to forget. Reference: memory accounting on Page.getSizeInBytes().
    """
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(batch):
        total += int(leaf.size) * leaf.dtype.itemsize
    return total


class MemoryPool:
    """Per-worker reservation pool (MemoryPool.java:45 analog)."""

    def __init__(self, capacity_bytes: int, name: str = "general"):
        self.name = name
        self.capacity = capacity_bytes
        self._reserved: Dict[str, int] = {}
        self._lock = threading.Lock()

    @property
    def reserved_bytes(self) -> int:
        with self._lock:
            return sum(self._reserved.values())

    @property
    def free_bytes(self) -> int:
        return self.capacity - self.reserved_bytes

    def reserve(self, query_id: str, bytes_: int):
        """Blocking semantics in the reference; here reservation failure
        raises and the caller (runner) downsizes buckets or spills."""
        with self._lock:
            total = sum(self._reserved.values()) + bytes_
            if total > self.capacity:
                raise MemoryReservationError(
                    f"pool {self.name}: reserve {bytes_} for {query_id} "
                    f"exceeds capacity {self.capacity} "
                    f"(reserved {total - bytes_})")
            self._reserved[query_id] = self._reserved.get(query_id, 0) + bytes_

    def try_reserve(self, query_id: str, bytes_: int) -> bool:
        try:
            self.reserve(query_id, bytes_)
            return True
        except MemoryReservationError:
            return False

    def free(self, query_id: str, bytes_: Optional[int] = None):
        with self._lock:
            cur = self._reserved.get(query_id, 0)
            if bytes_ is None or bytes_ >= cur:
                self._reserved.pop(query_id, None)
            else:
                self._reserved[query_id] = cur - bytes_

    def query_bytes(self, query_id: str) -> int:
        with self._lock:
            return self._reserved.get(query_id, 0)


@dataclasses.dataclass
class MemoryContext:
    """Operator-level child context (LocalMemoryContext analog)."""
    pool: MemoryPool
    query_id: str
    tag: str = "user"  # user | system | revocable
    local_bytes: int = 0

    def set_bytes(self, bytes_: int):
        delta = bytes_ - self.local_bytes
        if delta > 0:
            self.pool.reserve(self.query_id, delta)
        elif delta < 0:
            self.pool.free(self.query_id, -delta)
        self.local_bytes = bytes_

    def close(self):
        self.set_bytes(0)
