"""Data-path waterfall: per-hop byte/throughput attribution with
roofline bottleneck verdicts.

The observability gap this closes: ROADMAP item 3 names the next perf
frontier precisely -- q1 stages ~168 MB yet achieves ~0.2 GB/s on the
staging path -- but nothing before this module could say WHICH hop caps
it: connector read, decode, narrow-cast, host->device put, kernel
dispatch, exchange serde, network fetch, or client drain. Accelerator
query engines are routinely host<->device-transfer-bound rather than
compute-bound ("Accelerating Presto with GPUs", PAPERS.md), and the
metadata-caching paper could quantify staging wall only because its
authors first built per-hop attribution. This module is that
instrument: the gate ROADMAP item 3's async split pipeline will be
built against, hop by hop, vs measured hardware ceilings.

Model -- three layers, one merge law:

  * ``HopStats`` -- one mergeable record per hop (bytes, wall micros,
    invocations, max wall). The merge law mirrors ``QueryStats.merge``:
    sums add, maxes max -- associative, commutative, with the zero
    record as identity -- so worker slices stitch through the existing
    task-status path (``QueryStats.datapath`` carries these records
    worker -> coordinator, folded by ``QueryStats.merge``).
  * ambient per-query ledger (``DatapathLedger`` + ``recording``):
    ``exec/runner.py`` installs one around each run_query; every
    instrumented seam (connector read/decode, narrow cast, device put,
    kernel dispatch, page serde, exchange fetch, client drain) calls
    :func:`record_hop`, which folds into the ambient ledger AND the
    process-lifetime registry AND the ``presto_tpu_datapath_bytes``
    size histogram (server/metrics.py SIZE_BUCKETS ladder).
  * process-lifetime registry: the ``GET /v1/datapath`` slice (the
    worker serves it; the statement tier merges slices cluster-wide
    via server/client.pull_worker_docs, exactly like /v1/profile),
    ``system.datapath``, and the bench.py per-hop artifact section.

Ceilings probe: one-shot seeded microbenchmarks of host memcpy,
``jax.device_put`` bandwidth, page serde, and loopback HTTP -- cached
process-wide, refreshable (``probe_ceilings(refresh=True)``). The
probe reads its own clock while MEASURING, but the verdict comparator
(:func:`bottleneck_verdict`) is a pure function of (hop records,
ceilings, band): it never reads a clock, so two calls over identical
inputs return identical verdicts. Each hop maps onto one ceiling
(HOP_CEILING); a hop's *utilization* is achieved B/s over that
ceiling, and a query's **bottleneck verdict** is the hop with the
largest wall share whose utilization sits below band.

Hop semantics (cross-hop overlap is deliberate: hops are independent
attributions of one byte stream at different stages, not a partition
of wall time -- exchange_fetch CONTAINS page decode, and both record):

  connector_read      host column materialization (file read or
                      generator) -- bytes are host array bytes
  decode              encoded -> engine-array decode (parquet/ORC row
                      groups, SerializedPage payloads)
  narrow_cast         narrow-width staging-time range re-proof + cast
  device_put          host -> HBM staging (batch_from_numpy); bytes
                      equal the staged batch (what QueryStats'
                      staging stage counts, the 1% reconciliation)
  kernel              compiled-program dispatch wall over staged bytes
  exchange_serialize  SerializedPage production
  exchange_fetch      cross-worker page pull + decode + restage
  client_drain        statement-protocol result polling (HTTP bytes)
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
import uuid
from typing import Dict, List, Optional

from ..utils.locks import OrderedLock

__all__ = ["HOPS", "CEILING_KEYS", "HOP_CEILING", "HopStats",
           "DatapathLedger", "recording", "record_hop", "timed_hop",
           "now_us",
           "merge_hop_maps", "hop_map_to_json", "hop_map_from_json",
           "probe_ceilings", "ceilings_cached", "achieved_b_per_s",
           "bottleneck_verdict", "datapath_doc", "merge_datapath_docs",
           "cluster_datapath_doc", "process_totals", "snapshot",
           "staging_summary", "note_query", "datapath_for_query",
           "clear_datapath"]

# the hop catalog: ONE closed vocabulary every surface shares (metrics
# label presets, /v1/datapath zero shape, system.datapath rows, the
# EXPLAIN ANALYZE tail). Order is data-path order; renderers keep it.
HOPS = ("connector_read", "decode", "narrow_cast", "device_put",
        "kernel", "exchange_serialize", "exchange_fetch", "client_drain")

# which measured ceiling bounds each hop (the roofline each utilization
# ratio is computed against). `kernel` uses the device_put bandwidth as
# its HBM-traffic proxy: one fused program exposes no finer roofline
# host-side, and a scan-heavy kernel is bounded by the same HBM lanes.
CEILING_KEYS = ("host_memcpy", "device_put", "page_serde",
                "loopback_http")
HOP_CEILING = {
    "connector_read": "host_memcpy",
    "decode": "host_memcpy",
    "narrow_cast": "host_memcpy",
    "device_put": "device_put",
    "kernel": "device_put",
    "exchange_serialize": "page_serde",
    "exchange_fetch": "loopback_http",
    "client_drain": "loopback_http",
}

# one id per process: the cluster merge deduplicates slices by it, so
# two server shells over one process (the test topology) count once
_PROCESS_ID = uuid.uuid4().hex

# utilization below this fraction of the hop's ceiling marks the hop
# as under-performing (verdict-eligible); callers can widen/narrow
_DEFAULT_BAND = 0.5


def now_us() -> int:
    """The per-process monotonic microsecond clock -- the ONE clock
    the hop walls and the timeline interval ledger (exec/timeline.py)
    share, so a hop's wall_us sum and its intervals' duration sum
    reconcile by construction (pinned within 1% on q1). Monotonic:
    never steps backward under NTP slew, so intervals cannot go
    negative on the recording process."""
    return int(time.monotonic() * 1e6)


@dataclasses.dataclass
class HopStats:
    """One hop's accumulated bytes/wall. Merges with the usual law:
    sums add, maxes max -- associative and commutative with the zero
    record as identity, like QueryStats."""
    hop: str
    bytes: int = 0
    wall_us: int = 0
    invocations: int = 0
    max_wall_us: int = 0

    def merge(self, other: "HopStats") -> "HopStats":
        assert self.hop == other.hop, \
            f"merging hops {self.hop} != {other.hop}"
        return HopStats(
            hop=self.hop,
            bytes=self.bytes + other.bytes,
            wall_us=self.wall_us + other.wall_us,
            invocations=self.invocations + other.invocations,
            max_wall_us=max(self.max_wall_us, other.max_wall_us))

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, doc: dict) -> "HopStats":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in known})


def achieved_b_per_s(nbytes: int, wall_us: int) -> float:
    """Achieved throughput of one hop record (0.0 when no wall was
    measured -- an unachieved rate, not infinity)."""
    return float(nbytes) / (wall_us / 1e6) if wall_us else 0.0


def merge_hop_maps(a: Dict[str, HopStats],
                   b: Dict[str, HopStats]) -> Dict[str, HopStats]:
    """Fold two hop maps by key (HopStats.merge's law lifts to maps:
    still associative + commutative, empty map as identity)."""
    out = dict(a)
    for k, h in b.items():
        out[k] = out[k].merge(h) if k in out else h
    return out


def hop_map_to_json(hops: Dict[str, HopStats]) -> Dict[str, dict]:
    return {k: h.to_json() for k, h in hops.items()}


def hop_map_from_json(doc: Dict[str, dict]) -> Dict[str, HopStats]:
    out = {}
    for k, h in (doc or {}).items():
        hs = HopStats.from_json({"hop": k, **h})
        out[k] = hs
    return out


class DatapathLedger:
    """Per-query hop accumulator (the ambient collection target).
    Thread-safe: a future pipelined staging path records from host
    prefetch threads while the dispatch thread records the kernel."""

    _GUARDED_BY = {"_lock": ("hops",)}

    def __init__(self):
        self.hops: Dict[str, HopStats] = {}
        self._lock = OrderedLock("datapath.DatapathLedger._lock")

    def record(self, hop: str, nbytes: int, wall_us: int) -> None:
        with self._lock:
            h = self.hops.get(hop)
            if h is None:
                h = self.hops[hop] = HopStats(hop)
            h.bytes += int(nbytes)
            h.wall_us += int(wall_us)
            h.invocations += 1
            h.max_wall_us = max(h.max_wall_us, int(wall_us))

    def snapshot_hops(self) -> Dict[str, HopStats]:
        with self._lock:
            return {k: dataclasses.replace(h)
                    for k, h in self.hops.items()}


# -- ambient (thread-local) attribution ---------------------------------

_tls = threading.local()


def _current_ledger() -> Optional[DatapathLedger]:
    return getattr(_tls, "ledger", None)


class recording:
    """Install `ledger` as this thread's ambient datapath target
    (exec/runner.py wraps each run_query; nested invocations shadow
    and restore, like stats.collecting)."""

    def __init__(self, ledger: DatapathLedger):
        self.ledger = ledger

    def __enter__(self):
        self.prev = _current_ledger()
        _tls.ledger = self.ledger
        return self.ledger

    def __exit__(self, *exc):
        _tls.ledger = self.prev
        return False


# -- process registry ----------------------------------------------------

# request handlers (/v1/datapath, system tables), engine threads
# (record_hop on the staging/serde hot paths) and the flight recorder
# all touch these
_LOCK = OrderedLock("datapath._LOCK")
_PROCESS: Dict[str, HopStats] = {}
# query id -> hop map (the flight-dump cross-link); bounded like the
# profiler's query->fingerprint table
_QUERY_LEDGERS: "collections.OrderedDict[str, Dict[str, HopStats]]" = \
    collections.OrderedDict()
_QUERY_LEDGERS_MAX = 256
_CEILINGS: Optional[Dict[str, float]] = None
# True while some thread runs the microbenchmarks: concurrent first
# callers must WAIT for that result, not probe simultaneously --
# mutually-contending probes each measure ~half the real bandwidth
# and would cache skewed ceilings process-wide
_PROBING = False
_PROBE_DONE = threading.Event()

_GUARDED_BY = {"_LOCK": ("_PROCESS", "_QUERY_LEDGERS", "_CEILINGS",
                         "_PROBING")}


def record_hop(hop: str, nbytes: int, seconds: float,
               end_us: Optional[int] = None,
               split_id: int = -1) -> None:
    """Fold one hop observation into the ambient ledger (when one is
    installed), the process-lifetime registry, the per-hop size
    histogram, and the timeline interval ledger (exec/timeline.py --
    the interval's duration IS this record's wall_us, so hop sums and
    interval durations reconcile exactly). ``end_us`` is the window's
    end on the :func:`now_us` clock; callers recording right after
    the window (the coarse paths) may omit it. Never raises: this
    sits on the staging/serde hot paths. Suppressed while the
    ceilings probe runs (the probe calls the very seams it
    measures)."""
    if getattr(_tls, "suppress", False):
        return
    try:
        wall_us = int(round(seconds * 1e6))
        ledger = _current_ledger()
        if ledger is not None:
            ledger.record(hop, nbytes, wall_us)
        with _LOCK:
            h = _PROCESS.get(hop)
            if h is None:
                h = _PROCESS[hop] = HopStats(hop)
            h.bytes += int(nbytes)
            h.wall_us += wall_us
            h.invocations += 1
            h.max_wall_us = max(h.max_wall_us, wall_us)
        t1 = now_us() if end_us is None else int(end_us)
        from .timeline import record_interval
        record_interval(hop, int(nbytes), t1 - wall_us, t1,
                        split_id=split_id)
        from ..server.metrics import observe_histogram
        observe_histogram("presto_tpu_datapath_bytes", float(nbytes),
                          labels={"hop": hop})
    except Exception as e:  # noqa: BLE001 - attribution must never
        # fail the byte stream it observes; leave the counted trace
        try:
            from ..server.metrics import record_suppressed
            record_suppressed("datapath", "record_hop", e)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


class timed_hop:
    """``with timed_hop("connector_read") as t: ...; t.bytes = n`` --
    records the hop on exit with the measured wall, on the monotonic
    :func:`now_us` clock the interval ledger shares."""

    def __init__(self, hop: str, nbytes: int = 0, split_id: int = -1):
        self.hop = hop
        self.bytes = nbytes
        self.split_id = split_id

    def __enter__(self):
        self.t0_us = now_us()
        return self

    def __exit__(self, *exc):
        end = now_us()
        record_hop(self.hop, self.bytes, (end - self.t0_us) / 1e6,
                   end_us=end, split_id=self.split_id)
        return False


def note_query(query_id: str, hops: Dict[str, HopStats]) -> None:
    """Retain one query's hop map for flight-dump embeds (bounded)."""
    if not hops:
        return
    with _LOCK:
        have = _QUERY_LEDGERS.get(query_id)
        if have is not None:
            _QUERY_LEDGERS[query_id] = merge_hop_maps(have, hops)
            _QUERY_LEDGERS.move_to_end(query_id)
        else:
            _QUERY_LEDGERS[query_id] = dict(hops)
            while len(_QUERY_LEDGERS) > _QUERY_LEDGERS_MAX:
                _QUERY_LEDGERS.popitem(last=False)


def datapath_for_query(query_id: str) -> Dict[str, dict]:
    """The hop map a query id recorded, as JSON rows (flight dumps)."""
    with _LOCK:
        hops = _QUERY_LEDGERS.get(query_id)
        return hop_map_to_json(hops) if hops else {}


def clear_datapath() -> None:
    """Drop the process registry + per-query maps (tests isolate
    state); the cached ceilings survive -- they describe hardware,
    not workload."""
    with _LOCK:
        _PROCESS.clear()
        _QUERY_LEDGERS.clear()


def process_totals() -> Dict[str, HopStats]:
    """Lifetime per-hop totals, every catalog hop present (zero shape
    is stable from process start)."""
    with _LOCK:
        live = {k: dataclasses.replace(h) for k, h in _PROCESS.items()}
    return {hop: live.get(hop, HopStats(hop)) for hop in HOPS}


# -- ceilings probe ------------------------------------------------------


def ceilings_cached() -> Optional[Dict[str, float]]:
    """The cached probe result, or None when nobody probed yet (cheap
    surfaces like /v1/cluster must not pay the probe per frame)."""
    with _LOCK:
        return dict(_CEILINGS) if _CEILINGS is not None else None


def probe_ceilings(refresh: bool = False) -> Dict[str, float]:
    """Measured per-ceiling bytes/s (host memcpy, device_put, page
    serde, loopback HTTP). One-shot: the first call pays the seeded
    microbenchmarks (~0.2s) and the result is cached process-wide;
    ``refresh=True`` re-measures. The MEASUREMENT reads its own clock;
    everything downstream (utilization, verdicts) is a pure function
    of the returned dict. Exactly one thread measures at a time:
    concurrent first callers wait on the prober's result instead of
    running contending microbenchmarks that would each see ~half the
    real bandwidth."""
    global _CEILINGS, _PROBING
    while True:
        with _LOCK:
            if _CEILINGS is not None and not refresh:
                return dict(_CEILINGS)
            if not _PROBING:
                _PROBING = True
                _PROBE_DONE.clear()
                break
        # another thread is measuring: wait for its result, then
        # re-check (bounded, so a died prober cannot park callers;
        # no lock is held across this wait)
        _PROBE_DONE.wait(timeout=30.0)
        refresh = False  # a fresh concurrent measurement satisfies us
    try:
        measured = _measure_ceilings()  # outside the lock: it blocks
        with _LOCK:
            _CEILINGS = measured
    finally:
        with _LOCK:
            _PROBING = False
        _PROBE_DONE.set()
    return dict(measured)


def _measure_ceilings() -> Dict[str, float]:
    """Run the four microbenchmarks with record_hop suppressed (the
    serde/transfer probes exercise the very seams the ledger
    instruments). Each probe degrades to a conservative 1 GB/s floor
    rather than failing -- a broken probe must not take /v1/datapath
    down with it."""
    _tls.suppress = True
    try:
        out: Dict[str, float] = {}
        for key, fn in (("host_memcpy", _probe_host_memcpy),
                        ("device_put", _probe_device_put),
                        ("page_serde", _probe_page_serde),
                        ("loopback_http", _probe_loopback_http)):
            try:
                out[key] = max(float(fn()), 1.0)
            except Exception as e:  # noqa: BLE001 - a probe that cannot
                # run reports the documented floor, counted
                try:
                    from ..server.metrics import record_suppressed
                    record_suppressed("datapath", f"probe_{key}", e)
                except Exception:  # noqa: BLE001
                    pass
                out[key] = 1e9
        return out
    finally:
        _tls.suppress = False


def _probe_host_memcpy(size: int = 8 << 20, reps: int = 4) -> float:
    import numpy as np
    rng = np.random.default_rng(0)
    buf = rng.integers(0, 255, size=size, dtype=np.uint8)
    t0 = time.perf_counter()
    for _ in range(reps):
        buf = buf.copy()
    dt = time.perf_counter() - t0
    return reps * size / max(dt, 1e-9)


def _probe_device_put(size: int = 8 << 20, reps: int = 2) -> float:
    import jax
    import numpy as np
    rng = np.random.default_rng(0)
    host = rng.integers(0, 255, size=size, dtype=np.uint8)
    jax.block_until_ready(jax.device_put(host))  # warm the path
    t0 = time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(jax.device_put(host))
    dt = time.perf_counter() - t0
    return reps * size / max(dt, 1e-9)


def _probe_page_serde(rows: int = 1 << 18, reps: int = 3) -> float:
    import numpy as np

    from .. import types as T
    from ..serde.pages import deserialize_page, serialize_page
    rng = np.random.default_rng(0)
    vals = rng.integers(-(10 ** 9), 10 ** 9, size=rows, dtype=np.int64)
    nulls = np.zeros(rows, dtype=bool)
    cols = [(T.BIGINT, vals, nulls)]
    raw = vals.nbytes
    t0 = time.perf_counter()
    for _ in range(reps):
        page = serialize_page(cols)
        deserialize_page(page, [T.BIGINT])
    dt = time.perf_counter() - t0
    return reps * 2 * raw / max(dt, 1e-9)


def _probe_loopback_http(size: int = 4 << 20, reps: int = 2) -> float:
    import http.server
    import threading as _threading
    import urllib.request

    import numpy as np
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 255, size=size, dtype=np.uint8).tobytes()

    class _H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, fmt, *args):  # quiet
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _H)
    thread = _threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        url = f"http://127.0.0.1:{srv.server_port}/probe"
        with urllib.request.urlopen(url, timeout=10) as r:  # warm
            r.read()
        t0 = time.perf_counter()
        for _ in range(reps):
            with urllib.request.urlopen(url, timeout=10) as r:
                r.read()
        dt = time.perf_counter() - t0
    finally:
        srv.shutdown()
        srv.server_close()
        thread.join(timeout=5)
    return reps * size / max(dt, 1e-9)


# -- verdicts ------------------------------------------------------------


def _as_fields(h) -> dict:
    """HopStats or its JSON row -> {bytes, wall_us} (both shapes flow
    through the verdict: QueryStats carries objects, /v1/datapath
    documents carry rows)."""
    if isinstance(h, HopStats):
        return {"bytes": h.bytes, "wall_us": h.wall_us}
    return {"bytes": int(h.get("bytes", 0)),
            "wall_us": int(h.get("wall_us", 0))}


def bottleneck_verdict(hops, ceilings: Dict[str, float],
                       band: float = _DEFAULT_BAND) -> Optional[dict]:
    """The named verdict: among hops with recorded wall, the one with
    the largest wall share whose utilization (achieved/ceiling) sits
    below ``band``; when every hop runs at-or-above band, the largest
    wall share wins with ``belowBand: false`` (the data path is at the
    hardware, and the verdict says which hop dominates anyway). Pure
    function of its inputs -- no clocks, no env -- so identical
    (ledger, ceilings) always name the same hop. None when no hop
    recorded any wall."""
    rows = []
    total_wall = 0
    for hop, h in hops.items():
        f = _as_fields(h)
        if f["wall_us"] <= 0:
            continue
        total_wall += f["wall_us"]
        ceiling = float(ceilings.get(HOP_CEILING.get(hop, ""), 0.0))
        achieved = achieved_b_per_s(f["bytes"], f["wall_us"])
        util = achieved / ceiling if ceiling > 0 else 0.0
        rows.append((hop, f["wall_us"], achieved, ceiling, util))
    if not rows or total_wall <= 0:
        return None
    below = [r for r in rows if r[4] < band]
    pool = below or rows
    # deterministic pick: wall desc, hop name as the tiebreak
    hop, wall, achieved, ceiling, util = \
        sorted(pool, key=lambda r: (-r[1], r[0]))[0]
    return {"hop": hop,
            "wallShare": round(wall / total_wall, 4),
            "utilization": round(util, 4),
            "achievedBPerS": round(achieved, 1),
            "ceilingBPerS": round(ceiling, 1),
            "band": band,
            "belowBand": bool(below)}


# -- surfaces ------------------------------------------------------------


def _hop_row(h: HopStats, ceilings: Dict[str, float]) -> dict:
    achieved = achieved_b_per_s(h.bytes, h.wall_us)
    ceiling = float(ceilings.get(HOP_CEILING.get(h.hop, ""), 0.0))
    return {**h.to_json(),
            "achievedBPerS": round(achieved, 1),
            "ceilingBPerS": round(ceiling, 1),
            "utilization": round(achieved / ceiling, 4)
            if ceiling > 0 else 0.0}


def datapath_doc() -> dict:
    """This process's /v1/datapath slice: every catalog hop (zeros
    included -- the shape is stable from the first request on), the
    measured ceilings, and the process-lifetime bottleneck verdict."""
    ceilings = probe_ceilings()
    totals = process_totals()
    return {"processId": _PROCESS_ID,
            "hops": {hop: _hop_row(h, ceilings)
                     for hop, h in totals.items()},
            "ceilings": {k: round(v, 1) for k, v in ceilings.items()},
            "verdict": bottleneck_verdict(totals, ceilings)}


def merge_datapath_docs(docs: List[dict]) -> dict:
    """Fold per-process slices into one cluster view. Slices sharing a
    processId count once (two server shells over one process report
    the same registry); hop records merge by HopStats' law; ceilings
    merge by max (the fleet's best measured rate is the closest
    estimate of the true hardware ceiling); the verdict is recomputed
    over the merged hops -- order-independent throughout."""
    seen = set()
    hops: Dict[str, HopStats] = {}
    ceilings: Dict[str, float] = {}
    for doc in docs:
        pid = doc.get("processId") or f"anon-{id(doc):x}"
        if pid in seen:
            continue
        seen.add(pid)
        hops = merge_hop_maps(hops, hop_map_from_json(doc.get("hops")))
        for k, v in (doc.get("ceilings") or {}).items():
            ceilings[k] = max(ceilings.get(k, 0.0), float(v))
    full = {hop: hops.get(hop, HopStats(hop)) for hop in HOPS}
    return {"hops": {hop: _hop_row(h, ceilings)
                     for hop, h in full.items()},
            "ceilings": {k: round(v, 1) for k, v in ceilings.items()},
            "verdict": bottleneck_verdict(full, ceilings)}


def cluster_datapath_doc(worker_urls=(), timeout: float = 3.0) -> dict:
    """The coordinator-side merge: this process's slice plus every
    reachable worker's ``GET /v1/datapath``, folded by hop. Pulls ride
    the shared best-effort helper (server/client.pull_worker_docs) so
    bearer/TLS/trace headers -- and the skip-and-count-dead-workers
    contract -- stay identical to the /v1/profile merge's."""
    from ..server.client import pull_worker_docs
    pulled, workers_seen = pull_worker_docs(
        worker_urls, timeout, lambda c: c.datapath(), "datapath")
    merged = merge_datapath_docs([datapath_doc(), *pulled])
    return {"processId": _PROCESS_ID, "cluster": True,
            "workersPulled": workers_seen, **merged}


def snapshot() -> List[dict]:
    """Per-hop rows in data-path order (the system.datapath table),
    every catalog hop present."""
    ceilings = probe_ceilings()
    totals = process_totals()
    return [_hop_row(totals[hop], ceilings) for hop in HOPS]


def staging_summary() -> dict:
    """The cheap /v1/cluster embed: THIS process's lifetime staging
    rate (device_put hop achieved GB/s -- the whole story on the
    embedded statement tier, where queries stage in-process; a
    separate-process fleet's per-worker rates live on the
    cluster-merged /v1/datapath) plus the bottleneck hop name WHEN
    ceilings were already probed -- a cluster frame never pays the
    probe itself."""
    totals = process_totals()
    put = totals["device_put"]
    doc = {"stagingGbPerS": round(
        achieved_b_per_s(put.bytes, put.wall_us) / 1e9, 3)}
    ceilings = ceilings_cached()
    if ceilings:
        verdict = bottleneck_verdict(totals, ceilings)
        doc["bottleneck"] = verdict["hop"] if verdict else ""
    return doc
