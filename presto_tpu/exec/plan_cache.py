"""Compiled-plan cache: plan fingerprint -> jitted executable.

Reference surface: the reference keeps compiled PageProcessor /
operator-factory artifacts cached per plan (ExpressionCompiler's
CacheLoader in sql/gen/ExpressionCompiler.java, and the native worker
reuses compiled Velox plan translations across identical fragments).
This engine's analog sits one level higher: the WHOLE fragment lowers
to one XLA program, and recompiling it per query submission costs
seconds of trace+compile for a plan the process has already built.
Repeat submissions (CLI sessions, the statement protocol, dashboards
re-running a query) hit the cache and pay only staging + execution.

The key is a *structural* fingerprint of the plan tree: node types and
parameters in traversal order with shared-subtree back-references
(so a CTE DAG and its tree-shaped twin fingerprint differently), node
ids EXCLUDED (the global id counter makes two plannings of the same SQL
differ only in ids). Two plans with equal fingerprints lower to the
same traced program, so batches -- supplied positionally in scan
traversal order -- execute identically under either plan object.

Thread-safety: a per-entry lock serializes dispatch through one cached
executable (tracing mutates the closure's overflow bookkeeping; XLA
execution itself is async and runs outside the lock via the returned
futures). Different plans never contend.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from collections import OrderedDict
from typing import Optional, Tuple

import jax
import numpy as np

from ..plan import nodes as N
from ..utils.locks import OrderedLock
from .planner import CompiledPlan, compile_plan

__all__ = ["plan_fingerprint", "cached_compile", "cache_stats",
           "clear_plan_cache", "KERNEL_MODE_ENVS"]

_MAX_ENTRIES = 64

_lock = OrderedLock("plan_cache._lock")
_cache: "OrderedDict[tuple, _Entry]" = OrderedDict()
_hits = 0
_misses = 0


@dataclasses.dataclass
class _Entry:
    plan: CompiledPlan
    fn: object            # jax.jit-wrapped plan.fn
    call_lock: threading.Lock


def plan_fingerprint(root: N.PlanNode) -> str:
    """Deterministic structural hash of a plan tree (ids excluded,
    object-identity sharing preserved via back-references)."""
    seen: dict = {}
    parts: list = []

    def emit(v):
        if isinstance(v, N.PlanNode):
            walk(v)
        elif isinstance(v, (list, tuple)):
            parts.append("[")
            for x in v:
                emit(x)
            parts.append("]")
        elif isinstance(v, np.ndarray):
            # repr truncates large arrays -- hash the raw bytes instead
            parts.append(f"nd:{v.dtype}:{v.shape}:"
                         f"{hashlib.sha256(v.tobytes()).hexdigest()}")
        else:
            parts.append(repr(v))

    def walk(n):
        if id(n) in seen:
            parts.append(f"@{seen[id(n)]}")
            return
        seen[id(n)] = len(seen)
        parts.append(type(n).__name__)
        for f in dataclasses.fields(n):
            if f.name == "id":
                continue
            parts.append(f.name)
            emit(getattr(n, f.name))

    walk(root)
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def _mesh_key(mesh) -> Optional[tuple]:
    if mesh is None:
        return None
    return (tuple(mesh.axis_names), mesh.devices.shape,
            tuple(d.id for d in mesh.devices.flat))


# Trace-time env knobs that change the lowered program WITHOUT changing
# the plan fingerprint (kernel form A/Bs: small-G scatter vs einsum,
# Pallas on/off, narrow bf16 forms, large-G sort vs hash). Every entry
# is part of the cache key; tpulint's R001 pass rejects any OTHER env
# read in ops/ or exec/ (an unregistered knob would serve stale
# executables compiled under the other mode).
KERNEL_MODE_ENVS = (("PRESTO_TPU_SMALLG", "auto"),
                    ("PRESTO_TPU_SMALLG_PALLAS", "1"),
                    ("PRESTO_TPU_NARROW", "1"),
                    ("PRESTO_TPU_BF16", "auto"),
                    ("PRESTO_TPU_GROUPBY", "sort"),
                    # pipeline-region fusion (exec/regions.py): =0 runs
                    # every operator as its own materialized program;
                    # partitioning changes WHICH programs compile, so
                    # the mode is part of every cached key
                    ("PRESTO_TPU_FUSION", "1"),
                    # staging-time kernel auditing (audit/staged.py):
                    # doesn't change the lowered program, but keying it
                    # keeps audit-memo and executable lifecycles aligned
                    # and satisfies R001's registered-env contract
                    ("PRESTO_TPU_KERNEL_AUDIT", "0"),
                    # continuous per-kernel profiling (exec/profiler.py):
                    # like the audit knob, program-invariant but
                    # registered so every ambient knob exec/ reads lives
                    # in this one R001-checked list
                    ("PRESTO_TPU_PROFILE", "1"),
                    # concurrent-query batching (exec/batching.py): the
                    # batched dispatch traces a vmapped program over the
                    # parameter axis, so the mode is part of every batch
                    # key (and rides the one R001-checked env list)
                    ("PRESTO_TPU_BATCHING", "1"),
                    # proven-safe buffer donation (exec/donation.py):
                    # the donating dispatch compiles a separate wrapper
                    # program (donate_argnums over the dead leaves), so
                    # the mode is part of every cached key (and the env
                    # read rides the one R001-checked list)
                    ("PRESTO_TPU_DONATION", "0"),
                    # execution-timeline interval tracing (exec/
                    # timeline.py): program-invariant observability, but
                    # registered so every ambient knob exec/ reads lives
                    # in this one R001-checked list
                    ("PRESTO_TPU_TIMELINE", "1"))


def _kernel_mode() -> str:
    """The cache-key component built from KERNEL_MODE_ENVS."""
    import os
    # this IS the cache key: the one sanctioned ambient read
    return "|".join(os.environ.get(name, default)  # tpulint: disable=R001
                    for name, default in KERNEL_MODE_ENVS)


def _capacity_sensitive(root: N.PlanNode) -> bool:
    """Whether `default_join_capacity` can change this plan's lowered
    program. The ONLY lowering site that reads it is a JoinNode without
    an explicit out_capacity (exec/planner.py), so join-free plans --
    and plans whose joins all carry planned capacities -- compile
    identically under every default. Keying those on the default would
    fragment the cache across callers that merely configure different
    join defaults (the fragment tier passes the session's
    default_join_capacity on every submission)."""
    seen: set = set()

    def walk(n) -> bool:
        if id(n) in seen:  # shared CTE subtrees visit once (a DAG
            return False   # walked as a tree is exponential)
        seen.add(id(n))
        if isinstance(n, N.JoinNode) and n.out_capacity is None:
            return True
        return any(walk(s) for s in n.sources)
    return walk(root)


def cached_compile(root: N.PlanNode, mesh, default_join_capacity: int,
                   exchange_slot_scale: int = 1
                   ) -> Tuple[CompiledPlan, object, threading.Lock]:
    """(CompiledPlan, jitted fn, per-entry dispatch lock) for this plan,
    compiling at most once per (structure, mesh, capacities, scale).
    Join-free plans are capacity-insensitive: their key ignores
    `default_join_capacity`, so fused scan/agg regions never fragment
    the cache across join-capacity configurations."""
    global _hits, _misses
    cap_key = default_join_capacity if _capacity_sensitive(root) else None
    key = (plan_fingerprint(root), _mesh_key(mesh), cap_key,
           exchange_slot_scale, _kernel_mode())
    with _lock:
        entry = _cache.get(key)
        if entry is not None:
            _cache.move_to_end(key)
            _hits += 1
            return entry.plan, entry.fn, entry.call_lock
        _misses += 1
    # compile outside the cache lock (pure python closure-building, fast;
    # the expensive XLA work happens lazily at first dispatch)
    plan = compile_plan(root, mesh, default_join_capacity,
                        exchange_slot_scale=exchange_slot_scale)
    entry = _Entry(plan, jax.jit(plan.fn), OrderedLock("plan_cache._Entry.call_lock"))
    with _lock:
        have = _cache.get(key)
        if have is not None:     # lost a race: keep the first
            return have.plan, have.fn, have.call_lock
        _cache[key] = entry
        while len(_cache) > _MAX_ENTRIES:
            _cache.popitem(last=False)
    return entry.plan, entry.fn, entry.call_lock


def cache_stats() -> dict:
    with _lock:
        return {"entries": len(_cache), "hits": _hits, "misses": _misses}


def clear_plan_cache() -> None:
    global _hits, _misses
    with _lock:
        _cache.clear()
        _hits = 0
        _misses = 0
    # the kernel-audit memo is keyed by the same (fingerprint, mesh,
    # kernel-mode) identity as cache entries: clearing one without the
    # other would serve stale audit reports for freshly traced programs
    from ..audit.staged import clear_audit_memo
    clear_audit_memo()
