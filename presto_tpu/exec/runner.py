"""Query runner: split generation, execution, result fetch.

Reference surface: the worker task path -- SqlTaskExecution creating
drivers per split (execution/SqlTaskExecution.java:144), the Driver
processing loop (operator/Driver.java:310), and the coordinator pulling
results from the root stage's output buffer.

Round-1 model: one batch per table scan (splits concatenated), one
jit'd program per plan, host-side result extraction. The driver-loop
streaming of bounded batches (double-buffered through HBM) and the
overflow->rerun policy (spill analog) land on top of compile_plan
without changing lowered kernels.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from .. import types as T
from ..block import Batch, batch_from_numpy, to_numpy
from ..plan import nodes as N
from .planner import compile_plan
from .stats import QueryStats, RuntimeStats, StatsCollector, collecting

__all__ = ["run_query", "prepare_plan", "QueryResult"]


@dataclasses.dataclass
class QueryResult:
    columns: List[np.ndarray]
    nulls: List[np.ndarray]
    names: List[str]
    row_count: int
    stats: Dict[str, Dict[str, float]] = dataclasses.field(default_factory=dict)
    types: List[T.Type] = dataclasses.field(default_factory=list)
    # structured telemetry (stages/operators/counters with a merge law);
    # `stats` above stays the flat named-counter snapshot
    query_stats: Optional[QueryStats] = None

    def rows(self) -> List[tuple]:
        # M001: the caller asked for the FINAL RESULT as Python
        # rows -- output cardinality, already materialized above
        _BOUNDED_BY = {"out": "final result rows (caller-requested materialization)"}
        out = []
        for i in range(self.row_count):
            out.append(tuple(None if self.nulls[c][i] else self.columns[c][i]
                             for c in range(len(self.columns))))
        return out

    def canonical_rows(self, digits: int = 6) -> List[tuple]:
        """Order-independent, stringified rows for oracle comparison
        (floats rounded so summation order cannot flip a digit) -- the
        ONE canonicalization the fusion A/B surfaces share."""
        # M001: same output surface as rows() above
        _BOUNDED_BY = {"out": "final result rows (oracle canonicalization)"}
        out = []
        for i in range(self.row_count):
            row = []
            for c in range(len(self.columns)):
                v = None if self.nulls[c][i] else self.columns[c][i]
                if isinstance(v, (float, np.floating)):
                    v = round(float(v), digits)
                row.append(str(v))
            out.append(tuple(row))
        return sorted(out)


def _host_bytes(arrays, nulls=None) -> int:
    """Host-side byte count of generated columns (object lanes count
    pointer bytes -- consistent, if conservative, for strings)."""
    total = sum(getattr(a, "nbytes", 0) for a in arrays)
    if nulls:
        total += sum(getattr(n, "nbytes", 0) for n in nulls)
    return total


def stage_scan_split(conn, node: "N.TableScanNode", sf: float, start: int,
                     count: int, capacity: int) -> Batch:
    """Stage one scan split honoring the node's narrow-width annotation
    (plan/widths.py): host columns generate, the staging-time range
    guard re-proves each narrowed lane against the actual values, and
    the batch stages at the narrowed physical dtypes -- the shared
    staging path of the runner and the streaming executor. Falls back
    to the connector's own generate_batch when the node carries no
    width annotation (or the connector can't produce host columns).

    Every path records its data-path hops (exec/datapath.py):
    connector_read (host column materialization), narrow_cast (the
    staging-time range re-proof), device_put (host -> HBM staging,
    the bytes QueryStats' staging stage counts)."""
    from .datapath import now_us, record_hop, timed_hop
    from .memory import batch_bytes
    phys = getattr(node, "physical_dtypes", None)
    if not phys or not any(phys) or not hasattr(conn, "generate_columns"):
        # the connector stages straight to a device batch: the whole
        # read+put attributes to connector_read (coarse by design --
        # connectors wanting finer hops expose generate_columns)
        t0 = now_us()
        b = conn.generate_batch(node.table, sf, node.columns,
                                start=start, count=count,
                                capacity=capacity)
        end = now_us()
        record_hop("connector_read", batch_bytes(b), (end - t0) / 1e6,
                   end_us=end)
        return b
    from ..plan.widths import checked_physical_dtypes
    with timed_hop("connector_read") as t_read:
        data = conn.generate_columns(node.table, sf, node.columns,
                                     start, count)
        arrays = [data[c] for c in node.columns]
        nulls = None
        if hasattr(conn, "generate_nulls"):
            nmap = conn.generate_nulls(node.table, node.columns, start,
                                       count)
            nulls = [nmap[c] for c in node.columns]
        t_read.bytes = _host_bytes(arrays, nulls)
    with timed_hop("narrow_cast", t_read.bytes):
        checked = checked_physical_dtypes(phys, node.column_types, arrays,
                                          nulls=nulls)
    with timed_hop("device_put") as t_put:
        b = batch_from_numpy(node.column_types, arrays, nulls=nulls,
                             capacity=capacity, physical_dtypes=checked)
        # sync so the measured wall is the transfer, not the async
        # dispatch returning early (bench.py learned this on the
        # chip). The staging loop is synchronous today (stage ->
        # execute, ROADMAP item 3) and the caller host-reads
        # b.active immediately after, so this adds no real
        # serialization; item 3's producer/consumer pipeline will
        # record this hop from its prefetch threads instead.
        jax.block_until_ready(b)
        t_put.bytes = batch_bytes(b)
    return b


def _scan_batch(node: N.PlanNode, sf: float, capacity_hint: Optional[int],
                pad_multiple: int,
                scan_range: Optional[Tuple[int, int]] = None,
                dyn_filters=None, stats=None) -> Batch:
    if isinstance(node, N.ValuesNode):
        arrays = []
        null_masks = []
        for ci, ty in enumerate(node.types):
            col = [r[ci] for r in node.rows]
            nulls = np.array([v is None for v in col], dtype=bool)
            if ty.is_string or ty.base in ("array", "map", "row") or \
                    (ty.is_decimal and not ty.is_short_decimal):
                a = np.empty(len(col), dtype=object)
                for i, v in enumerate(col):
                    a[i] = v
                arrays.append(a)
            else:
                arrays.append(np.array([0 if v is None else v for v in col],
                                       dtype=ty.to_dtype()))
            null_masks.append(nulls)
        cap = capacity_hint or -(-len(node.rows) // pad_multiple) * pad_multiple
        if not node.types:
            # zero-column VALUES (FROM-less SELECT): rows are all mask
            import jax.numpy as jnp
            active = np.zeros(cap, dtype=bool)
            active[:len(node.rows)] = True
            return Batch((), jnp.asarray(active))
        return batch_from_numpy(node.types, arrays, nulls=null_masks,
                                capacity=cap)
    assert isinstance(node, N.TableScanNode)
    from ..connectors import catalog
    conn = catalog(node.connector)
    if scan_range is not None:
        start, count = scan_range
    else:
        start, count = 0, conn.table_row_count(node.table, sf)
    if dyn_filters:
        # dynamic filtering: prune fact rows host-side BEFORE they are
        # staged into HBM (DynamicFilterSourceOperator pushdown; the
        # win here is smaller staged shapes)
        from .datapath import timed_hop
        from .dynfilter import apply_dynamic_filters
        with timed_hop("connector_read") as t_read:
            data = conn.generate_columns(node.table, sf, node.columns,
                                         start, count)
            t_read.bytes = _host_bytes(list(data.values()))
        keep, pruned = apply_dynamic_filters(data, node.columns,
                                             dyn_filters)
        if stats is not None:
            stats.add("dynamic_filter_rows_pruned", pruned)
            stats.add("dynamic_filter_rows_staged", int(keep.sum()))
        arrays = [data[c][keep] for c in node.columns]
        tys = node.column_types
        nrows = len(arrays[0])
        cap = max(-(-nrows // pad_multiple) * pad_multiple, pad_multiple)
        nulls = None
        if hasattr(conn, "generate_nulls"):  # stored tables carry nulls
            nmap = conn.generate_nulls(node.table, node.columns,
                                       start, count)
            nulls = [nmap[c][keep] for c in node.columns]
        phys = getattr(node, "physical_dtypes", None)
        if phys and any(phys):
            from ..plan.widths import checked_physical_dtypes
            with timed_hop("narrow_cast", _host_bytes(arrays, nulls)):
                phys = checked_physical_dtypes(phys, tys, arrays,
                                               nulls=nulls)
        from .memory import batch_bytes
        with timed_hop("device_put") as t_put:
            b = batch_from_numpy(tys, arrays, capacity=cap, nulls=nulls,
                                 physical_dtypes=phys or None)
            jax.block_until_ready(b)
            t_put.bytes = batch_bytes(b)
        return b
    cap = capacity_hint or max(-(-count // pad_multiple) * pad_multiple,
                               pad_multiple)
    if node.pushdown is not None and scan_range is None \
            and hasattr(conn, "row_groups_matching"):
        # connector statistics pruning: skip row groups the pushed-down
        # range provably excludes (the exact Filter still runs above).
        # Coarse datapath attribution like stage_scan_split's fallback:
        # the connector stages straight to device, so the whole
        # read+put attributes to connector_read (the ledger must never
        # show zero bytes for a staged scan)
        from .datapath import now_us, record_hop
        from .memory import batch_bytes
        t0 = now_us()
        b = conn.generate_batch(node.table, sf, node.columns,
                                start=start, count=count, capacity=cap,
                                predicate=tuple(node.pushdown))
        end = now_us()
        record_hop("connector_read", batch_bytes(b), (end - t0) / 1e6,
                   end_us=end)
        return b
    return stage_scan_split(conn, node, sf, start, count, cap)


def prepare_plan(root: N.PlanNode, sf: float = 0.01, mesh=None,
                 session=None) -> N.PlanNode:
    """The plan-shaping pipeline run_query applies before lowering:
    rule-based simplification + channel pruning, cost-based join
    reordering, connector predicate pushdown, NDV capacity refinement,
    AddExchanges (mesh), PlanChecker validation. Exposed so EXPLAIN
    ANALYZE can annotate exactly the tree that executes (pass the
    result back with ``prepared=True``). Write/DDL roots pass through
    untouched -- their inner SELECTs are shaped when the writer
    re-enters run_query."""
    from ..utils.config import session_flag, session_value

    inner_root = root.source if isinstance(root, N.OutputNode) else root
    if isinstance(inner_root, (N.DdlNode, N.TableFinishNode,
                               N.TableWriterNode, N.TableRewriteNode)):
        return root

    def _session_on(name: str) -> bool:
        return session_flag(session, name, True)

    # rule-based simplification + channel pruning (IterativeOptimizer /
    # PruneUnreferencedOutputs analog): narrows intermediates before
    # stats and distribution decide capacities and exchange widths
    if _session_on("iterative_optimizer"):
        from ..plan.rules import optimize_plan
        root = optimize_plan(root)
    # cost-based join reordering (ReorderJoins analog): largest
    # relation stays the streaming probe, smallest builds join first.
    # Runs BEFORE channel pruning of the rebuilt chain would matter --
    # the trailing optimize_plan sweep re-prunes the widened
    # intermediates reorder introduces
    if session_value(session, "join_reordering_strategy",
                     "AUTOMATIC") != "NONE":
        from ..plan.reorder import reorder_joins
        rr = reorder_joins(root, sf)
        if rr is not root and _session_on("iterative_optimizer"):
            from ..plan.rules import optimize_plan
            rr = optimize_plan(rr)
        root = rr
    # connector predicate pushdown: range conjuncts above pushdown-
    # capable scans (parquet row-group statistics) annotate the scan
    if _session_on("scan_predicate_pushdown"):
        from ..plan.pushdown import push_scan_predicates
        root = push_scan_predicates(root)
    # capacity refinement (CBO stats): shrink group tables to the
    # connector-proven NDV bound so group-by rides the scatter-free
    # small-table kernels wherever statistics allow
    if _session_on("stats_capacity_refinement"):
        from ..plan.stats import refine_capacities
        root = refine_capacities(root, sf)
    # narrow-width execution (plan/widths.py): annotate every scan whose
    # column ranges the connector proves with the narrowest safe
    # physical lanes; staging honors them (halved host->HBM bytes for
    # narrowed columns), compute sites widen before arithmetic.
    # PRESTO_TPU_NARROW=0 / session narrow_width_execution=false = wide A/B
    from ..plan.widths import narrow_enabled
    if narrow_enabled(session):
        from ..plan.widths import annotate_widths
        root = annotate_widths(root, sf)
    if mesh is not None:
        # make the plan SPMD-correct: single-node operators get the
        # exchanges they need (AddExchanges; idempotent for plans that
        # already carry PARTIAL/FINAL + exchange structure). The session's
        # join_distribution_type picks broadcast vs partitioned joins
        # (DetermineJoinDistributionType; AUTOMATIC -> broadcast in
        # round 1, CBO pending)
        from ..plan.distribute import add_exchanges
        strategy = "broadcast"
        if session is not None:
            jd = session.get("join_distribution_type")
            if jd == "PARTITIONED":
                strategy = "partitioned"
            elif jd == "AUTOMATIC":
                strategy = "automatic"
        root = add_exchanges(root, join_strategy=strategy, sf=sf)
    from ..plan.validator import validate_plan
    violations = validate_plan(root, distributed=mesh is not None)
    if violations:
        raise ValueError("plan not executable by the TPU engine "
                         f"(PlanChecker): {violations}")
    # estimate stamping (exec/accuracy.py): every prepared node carries
    # its planner row estimate, so EXPLAIN and the runtime's
    # estimate-vs-actual ledger read ONE provenance
    from .accuracy import stamp_estimates
    stamp_estimates(root, sf)
    return root


def run_query(root: N.PlanNode, sf: float = 0.01, mesh=None,
              capacity_hints: Optional[Dict[str, int]] = None,
              default_join_capacity: int = 1 << 16,
              split_rows: Optional[int] = None,
              scan_ranges: Optional[Dict[str, Tuple[int, int]]] = None,
              remote_sources: Optional[Dict[str, Batch]] = None,
              memory_pool=None, query_id: str = "query",
              session=None,
              hbm_budget_bytes: Optional[int] = None,
              prepared: bool = False,
              trace_id=None) -> QueryResult:
    """Plan -> results, end to end (DistributedQueryRunner analog for
    programmatic plans). With a mesh, scan batches are padded to a
    multiple of the mesh size and the plan runs SPMD. With `split_rows`,
    streamable aggregation plans execute split-by-split with bounded
    HBM (exec/streaming.py).

    Every invocation maintains a live-progress entry keyed by
    ``query_id`` (exec/progress.py): monotonic stage/splits/rows/bytes
    counters an in-flight status poll, ``GET /v1/cluster`` and the
    stuck-progress watchdog read while the query is still RUNNING.
    Nested invocations (write roots) share their outer scope's entry.

    A per-query datapath ledger (exec/datapath.py) is ambient for the
    whole invocation: every instrumented hop on this thread
    (connector read, decode, narrow cast, device put, kernel, serde)
    attributes to THIS query; nested invocations (write roots' inner
    SELECTs) shadow-and-restore like the progress entry."""
    from .accuracy import AccuracyLedger
    from .accuracy import recording as _acc_recording
    from .datapath import DatapathLedger
    from .datapath import recording as _dp_recording
    from .progress import begin as _progress_begin
    from .timeline import TimelineLedger, timeline_enabled
    from .timeline import recording as _tl_recording
    prog = _progress_begin(query_id)
    dp = DatapathLedger()
    # the per-query estimate-vs-actual ledger (exec/accuracy.py) is
    # ambient too: measured boundaries (scan outputs, region outputs,
    # K005 footprint audits) attribute to THIS query's plan nodes
    acc = AccuracyLedger()
    # ... and the interval-timeline ledger (exec/timeline.py): every
    # hop the datapath records also lands as a (lane, hop, split,
    # t0, t1) interval, the occupancy/bubble instrument. A disabled
    # ledger (session `timeline` off) makes every record a no-op.
    tl = TimelineLedger(query_id=query_id,
                        enabled=timeline_enabled(session))
    try:
        with _dp_recording(dp), _acc_recording(acc), _tl_recording(tl):
            res = _run_query_inner(
                root, sf=sf, mesh=mesh, capacity_hints=capacity_hints,
                default_join_capacity=default_join_capacity,
                split_rows=split_rows, scan_ranges=scan_ranges,
                remote_sources=remote_sources, memory_pool=memory_pool,
                query_id=query_id, session=session,
                hbm_budget_bytes=hbm_budget_bytes, prepared=prepared,
                trace_id=trace_id, prog=prog, dp=dp, acc=acc, tl=tl)
    except BaseException:
        prog.release(state="FAILED")
        raise
    prog.release(state="FINISHED")
    return res


def _run_query_inner(root: N.PlanNode, sf: float = 0.01, mesh=None,
                     capacity_hints: Optional[Dict[str, int]] = None,
                     default_join_capacity: int = 1 << 16,
                     split_rows: Optional[int] = None,
                     scan_ranges: Optional[Dict[str,
                                                Tuple[int, int]]] = None,
                     remote_sources: Optional[Dict[str, Batch]] = None,
                     memory_pool=None, query_id: str = "query",
                     session=None,
                     hbm_budget_bytes: Optional[int] = None,
                     prepared: bool = False,
                     trace_id=None, prog=None, dp=None,
                     acc=None, tl=None) -> QueryResult:
    # write/DDL roots execute their source on device, then write
    # host-side (TableWriterOperator.java:76 analog -- the sink is a
    # host effect, fed by one DMA-out of the computed rows)
    inner_root = root.source if isinstance(root, N.OutputNode) else root
    if isinstance(inner_root, (N.DdlNode, N.TableFinishNode,
                               N.TableWriterNode, N.TableRewriteNode)):
        from ..server.access import get_access_control
        acl = get_access_control()
        if acl is not None:
            acl.check_plan(root, (session or {}).get("user", ""))
        return _run_write_root(
            inner_root, sf=sf, mesh=mesh, capacity_hints=capacity_hints,
            default_join_capacity=default_join_capacity,
            split_rows=split_rows, scan_ranges=scan_ranges,
            remote_sources=remote_sources, memory_pool=memory_pool,
            query_id=query_id, session=session,
            hbm_budget_bytes=hbm_budget_bytes, trace_id=trace_id)
    if not prepared:
        root = prepare_plan(root, sf=sf, mesh=mesh, session=session)
    if prog is not None:
        prog.advance(stage="plan")
    from ..utils.config import session_flag, session_value
    refine = session_flag(session, "stats_capacity_refinement", True)
    # access control: the analysis-time boundary (AccessControlManager
    # checkCanSelectFromColumns / write checks) -- enforced on the plan
    # before anything touches data
    from ..server.access import get_access_control
    acl = get_access_control()
    if acl is not None:
        acl.check_plan(root, (session or {}).get("user", ""))
    stats = RuntimeStats()
    collector = StatsCollector(query_id)
    t_query0 = time.time()
    hbm_budget = hbm_budget_bytes
    if hbm_budget is None and session is not None:
        hbm_budget = session.get("hbm_budget_bytes")
    if split_rows is not None and mesh is None:
        from .streaming import run_streaming_agg, streamable_agg_shape
        shape = streamable_agg_shape(root)
        if shape is not None:
            agg_node, _ = shape
            if prog is not None:
                prog.advance(stage="execute")
            if hbm_budget:  # 0 / None = uncapped (the config default)
                from .spill import plan_state_bytes, run_spilled_agg
                spill_dir = session_value(session, "spill_path") or None
                spill_thresh = int(session_value(
                    session, "spill_file_threshold_bytes", 256 << 20))
                if 2 * plan_state_bytes(agg_node) > hbm_budget:
                    # the full state table cannot fit the budget: grouped
                    # execution with per-bucket host offload (the
                    # SpillableHashAggregationBuilder path)
                    with stats.timed("spilled_exec_s"), \
                            collecting(collector), \
                            collector.stage("execute"):
                        out_b = run_spilled_agg(
                            root, sf, split_rows, hbm_budget, stats,
                            spill_dir=spill_dir,
                            spill_file_threshold=spill_thresh)
                    res = _batch_to_result(out_b, root)
                    res.stats = stats.snapshot()
                    _finalize_query_stats(collector, res, t_query0, 0,
                                          root, trace_id, dp=dp,
                                          acc=acc, tl=tl, sf=sf)
                    return res
            with stats.timed("streaming_exec_s"), collecting(collector), \
                    collector.stage("execute"):
                r = run_streaming_agg(root, sf, split_rows)
            if bool(np.asarray(r.overflow)):
                raise RuntimeError("streaming aggregation overflowed "
                                   "max_groups; raise AggregationNode.max_groups")
            # the streaming executor accumulates raw states; SINGLE-step
            # plans still owe the evaluateFinal step
            from ..ops.aggregation import finalize_states
            out_b = finalize_states(r.batch, len(agg_node.group_channels),
                                    agg_node.aggregates)
            res = _batch_to_result(out_b, root)
            res.stats = stats.snapshot()
            _finalize_query_stats(collector, res, t_query0, 0, root,
                                  trace_id, dp=dp, acc=acc, tl=tl,
                                  sf=sf)
            return res
    pad = (mesh.devices.size if mesh is not None else 1) * 8
    hints = capacity_hints or {}
    scan_ranges = scan_ranges or {}
    remote_sources = remote_sources or {}
    # Compiled-plan cache (exec/plan_cache.py): repeat submissions of a
    # structurally identical plan reuse the jitted executable instead of
    # re-tracing + re-compiling. Per-node-id kwargs (hints/ranges/remote
    # sources) refer to THIS plan object's ids, which a cached plan does
    # not share -- those callers (the fragment tier) compile fresh.
    use_cache = not hints and not scan_ranges and not remote_sources
    # Pipeline-region partition (exec/regions.py): the prepared plan
    # becomes 1..N regions, each staged as ONE XLA program. With fusion
    # on and nothing refused/demoted this is a single region -- the
    # fused whole-fragment program, compiled and cached exactly as
    # before. Materialized boundaries (fusion off, footprint refusal,
    # profiler demotion) run the general region executor below.
    from .plan_cache import plan_fingerprint
    from .regions import fusion_memory, partition_regions
    rplan = partition_regions(root, session=session, sf=sf, mesh=mesh)
    from .. import failpoints
    if failpoints.ARMED and rplan.fused and mesh is None \
            and len(rplan.regions) == 1 and rplan.regions[0].ops > 1:
        try:
            failpoints.hit("fusion.demote")
        except Exception as e:  # noqa: BLE001 - any injected error class
            # forced demotion mid-query (chaos/bisection): the fused
            # span demotes and THIS query already runs materialized
            fusion_memory().demote(
                plan_fingerprint(rplan.regions[0].root),
                f"failpoint ({type(e).__name__})")
            # the shared demotion counter (both paths) + the forced-
            # path discriminator, correlated by the flight event reason
            stats.add("fusion_demotions", 1)
            stats.add("fusion_forced_demotions", 1)
            collector.note("fusion_demotions")
            from ..server.flight_recorder import record_event
            record_event("fusion_demotion", query_id=query_id,
                         reason="failpoint")
            rplan = partition_regions(root, session=session, sf=sf,
                                      mesh=mesh)
    multi_region = len(rplan.regions) > 1
    if multi_region:
        stats.add("fusion_regions", len(rplan.regions))
        collector.note("fusion_regions", len(rplan.regions))
        plan = jfn = call_lock = None
        fp = None
        scan_leaves: List[N.PlanNode] = []
        from .planner import _collect_scans
        _collect_scans(root, scan_leaves)
    elif use_cache:
        plan, jfn, call_lock = _compile_any(root, mesh,
                                            default_join_capacity, 1, True)
        root = plan.root  # canonical tree: node ids match plan.scan_nodes
        fp = plan_fingerprint(root)
        scan_leaves = plan.scan_nodes
    else:
        plan, jfn, call_lock = _compile_any(root, mesh,
                                            default_join_capacity, 1, False)
        fp = None
        scan_leaves = plan.scan_nodes
    # continuous per-kernel profiling (exec/profiler.py): every executed
    # program is attributed by its plan-cache fingerprint -- computed
    # here even for the fragment tier's uncached compiles (scan ranges /
    # remote sources change batches, not the program's identity). The
    # region executor attributes per REGION fingerprint instead.
    from .profiler import profiling_enabled
    prof_on = profiling_enabled(session)
    fp_prof = fp
    if prof_on and fp_prof is None and not multi_region:
        fp_prof = plan_fingerprint(root)
    adaptive_off = False
    if session is not None:
        try:
            v = session.get("adaptive_capacity")
        except (KeyError, TypeError):
            v = None
        adaptive_off = v is not None and not v
    # dynamic filtering (local tier): dimension build sides run first
    # and their key domains prune fact scans at staging time
    dyn_filters = {}
    if session is None:
        dyn_on = True
    else:
        try:
            v = session.get("dynamic_filtering")
        except (KeyError, TypeError):  # plain dicts / older sessions
            v = None
        dyn_on = True if v is None else bool(v)
    if dyn_on and mesh is None:
        from .dynfilter import collect_dynamic_filters
        with stats.timed("dynamic_filter_collect_s"):
            dyn_filters = collect_dynamic_filters(root, sf)
        if dyn_filters:
            stats.add("dynamic_filters", sum(len(v)
                                             for v in dyn_filters.values()))
    reserved = 0
    if memory_pool is not None:
        # admission accounting (MemoryPool.reserve analog): PLANNED scan
        # footprints are charged before any device allocation, so a
        # reservation failure surfaces before the scan stage can OOM
        reserved = sum(
            _planned_scan_bytes(s, sf, hints.get(s.id), pad,
                                scan_ranges.get(s.id), remote_sources)
            for s in scan_leaves)
        memory_pool.reserve(query_id, reserved)
        stats.add("reserved_bytes", reserved)
        if prog is not None:
            prog.note_memory(reserved)
    try:
        if prog is not None:
            prog.set_planned(len(scan_leaves))
            prog.advance(stage="staging")
        from .timeline import split_scope
        with stats.timed("scan_stage_s"), collector.stage("staging"):
            batches = []
            for si, s in enumerate(scan_leaves):
                t_scan0 = time.time()
                if isinstance(s, N.RemoteSourceNode):
                    assert s.id in remote_sources, \
                        f"no remote source batch supplied for node {s.id}"
                    batches.append(remote_sources[s.id])
                else:
                    # split_scope: the hop seams inside this staging
                    # call attribute their timeline intervals to the
                    # si-th split without threading an index through
                    # every connector signature
                    with split_scope(si):
                        batches.append(_scan_batch(
                            s, sf, hints.get(s.id), pad,
                            scan_ranges.get(s.id),
                            dyn_filters=dyn_filters.get(s.id),
                            stats=stats))
                collector.operator(
                    _scan_key(si, s), _scan_label(s),
                    wall_us=int((time.time() - t_scan0) * 1e6))
                if prog is not None:  # one split staged = one heartbeat
                    prog.advance(splits=1)
    except Exception:
        if memory_pool is not None:
            memory_pool.free(query_id, reserved)
            memory_pool.query_peak_bytes(query_id, pop=True)
        raise
    from .memory import batch_bytes
    from ..plan.widths import batch_narrowed_bytes_saved, note_narrowed
    from .accuracy import est_rows_of as _acc_est
    from .accuracy import record_node as _acc_record
    staged_rows = staged_bytes = 0
    narrowed_cols = narrowed_saved = 0
    for si, (s, b) in enumerate(zip(scan_leaves, batches)):
        rows = int(np.asarray(b.active).sum())
        nbytes = batch_bytes(b)
        staged_rows += rows
        staged_bytes += nbytes
        stats.add("scan_rows", rows)
        collector.operator(_scan_key(si, s), output_rows=rows,
                           output_bytes=nbytes)
        # estimate-vs-actual (exec/accuracy.py): the scan leaf's
        # planner estimate against the rows it actually staged --
        # structural keys line up with the operator rows and across
        # workers running the same fragment
        _acc_record(_scan_key(si, s), _scan_label(s), unit="rows",
                    est=_acc_est(s, sf), actual=rows)
        if prog is not None:  # processed-input counters (monotonic)
            prog.advance(rows=rows, bytes=nbytes)
        if getattr(s, "physical_dtypes", None):
            nc, nb = batch_narrowed_bytes_saved(b)
            narrowed_cols += nc
            narrowed_saved += nb
    collector.bump_stage("staging", rows=staged_rows, bytes=staged_bytes)
    if narrowed_saved:
        # staged bytes saved vs logical lanes: the QueryStats counter the
        # acceptance criteria name, plus the process-lifetime /v1/metrics
        # totals (server/metrics.narrowing_families)
        stats.add("narrowed_bytes_saved", narrowed_saved)
        collector.note("narrowed_bytes_saved", narrowed_saved)
        collector.note("narrowed_columns", narrowed_cols)
        note_narrowed(narrowed_cols, narrowed_saved)
        # narrow-width decisions are exactly the kind of silent plan
        # choice a post-mortem wants on the timeline (flight recorder)
        from ..server.flight_recorder import record_event
        record_event("narrow_width", query_id=query_id,
                     columns=narrowed_cols, bytes_saved=narrowed_saved)
    # staging-time kernel audit (audit/staged.py): with the
    # kernel_audit session property (env PRESTO_TPU_KERNEL_AUDIT) on,
    # trace the fused program once more over the staged batches and run
    # the IR passes -- findings land in QueryStats counters, the
    # process /v1/metrics totals, and one flight-recorder event; the
    # K005 footprint estimate feeds the memory pool. Memoized per
    # (plan fingerprint, mesh, kernel mode, shapes); never fails the
    # query.
    from ..audit.staged import audit_staged_query, kernel_audit_enabled
    if kernel_audit_enabled(session) and not multi_region:
        with stats.timed("kernel_audit_s"):
            audit_report = audit_staged_query(
                plan, batches, mesh=mesh, query_id=query_id,
                session=session, collector=collector, stats=stats,
                memory_pool=memory_pool, plan_fp=fp)
        if audit_report and audit_report.get("peak_bytes_estimate"):
            # ... and the estimate side of the footprint accuracy
            # record (actual fills in at finalize from the pool's
            # measured per-query peak)
            _acc_record("footprint", "MemoryPool", unit="bytes",
                        est=float(audit_report["peak_bytes_estimate"]))
            # the K005 footprint estimate feeds the fusion cost model:
            # a fused span whose measured peak exceeds
            # kernel_audit_budget_bytes is REFUSED on its next
            # submission (exec/regions.py footprint feedback)
            if rplan.fused and mesh is None and rplan.regions[0].ops > 1:
                fusion_memory().note_footprint(
                    fp or plan_fingerprint(root),
                    audit_report["peak_bytes_estimate"])
            if prof_on:
                # ... and rides the kernel's profile row: /v1/profile
                # shows device time AND planned HBM appetite
                from .profiler import note_footprint
                note_footprint(fp_prof, audit_report["peak_bytes_estimate"])
    device_s = 0.0           # summed dispatch+sync wall (all reruns)
    compile_us: Optional[int] = None
    res = None
    if prog is not None:
        prog.advance(stage="execute")
    try:
        with stats.timed("execute_s"), collecting(collector), \
                collector.stage("execute"):
            if multi_region:
                # region executor: each pipeline region dispatches as
                # its own program; boundaries are HBM-resident Batch
                # handoffs (no host round trip), reruns re-dispatch
                # only the overflowing region
                out, device_s, compile_us = _execute_regions(
                    rplan, scan_leaves, batches, default_join_capacity,
                    use_cache, stats, session, adaptive_off, refine,
                    prog, collector, query_id, trace_id, prof_on,
                    memory_pool, plan_fp_root=plan_fingerprint(root),
                    sf=sf)
            else:
                (out, device_s, dispatch_fn, call_lock, cap_scale,
                 scale, plan) = _dispatch_ladder(
                    root, plan, jfn, call_lock, batches, mesh,
                    default_join_capacity, use_cache, fp, stats,
                    adaptive_off, refine, prog)
        # XLA compile cost (compile-time captured via jax.monitoring; a
        # plan-cache hit naturally reports zero) + the program's
        # FLOPs / bytes-accessed from cost_analysis, memoized per plan.
        # Clamped to the execute wall that contains it (nested-jit
        # lowering events can overlap), anchored at execute start so
        # trace timelines render the compile where it happened. The
        # region executor drains compile incrementally per region; any
        # remainder is folded in here.
        compile_us = (compile_us or 0) + collector.take_compile_us()
        exec_stage = collector.stats.stages.get("execute")
        if exec_stage is not None and exec_stage.wall_us:
            compile_us = min(compile_us, exec_stage.wall_us)
        if compile_us:
            anchor = collector.stage_span_start("execute") or t_query0
            collector.record_stage(
                "compile", anchor, anchor + compile_us / 1e6,
                compile_us=compile_us)
            stats.add("compile_s", compile_us / 1e6)
        if session_flag(session, "query_cost_analysis", False) \
                and not multi_region:
            fp_cost = fp if fp is not None else plan_fingerprint(root)
            # cap_scale distinguishes the scaled rerun's program from
            # the unscaled one (same fingerprint + shapes otherwise)
            cost = _stage_cost(dispatch_fn, batches,
                               (fp_cost, cap_scale, scale), call_lock)
            if cost:
                collector.bump_stage("compile", **cost)
                stats.add("xla_flops", cost["flops"])
        if rplan.fused and mesh is None and not multi_region \
                and rplan.regions[0].ops > 1:
            # fused-side sample for the demotion comparator: device
            # occupancy of the fused span, compile excluded. When the
            # profiler's samples show the fused form regressing beyond
            # the perfgate band vs the materialized baseline, the span
            # demotes and the NEXT submission runs materialized.
            mem = fusion_memory()
            span_fp = fp if fp is not None else plan_fingerprint(root)
            mem.note_fused(span_fp,
                           max(int(device_s * 1e6) - compile_us, 0))
            verdict = mem.maybe_demote(span_fp)
            if verdict is not None:
                stats.add("fusion_demotions", 1)
                collector.note("fusion_demotions")
                from ..server.flight_recorder import record_event
                record_event("fusion_demotion", query_id=query_id,
                             reason="profiler",
                             ratio=verdict.get("ratio"))
        # kernel hop (exec/datapath.py): the compiled program's dispatch
        # wall over the bytes it read -- the data-path waterfall's
        # device-side rung, bounded by the device_put ceiling proxy.
        # XLA compile is SUBTRACTED (same correction the profiler and
        # the fusion comparator apply above): a cold dispatch's 1-2s
        # compile would otherwise read as <1% utilization and misname
        # 'kernel' as the bottleneck on every fresh query. Bytes scale
        # with the DISPATCH count (device_s sums every overflow
        # rerun's wall, and each rerun re-reads the staged inputs) so
        # a capacity-rescaled query's achieved rate stays honest.
        from .datapath import record_hop as _dp_record
        _snap = stats.snapshot()
        _dispatches = 1 + \
            int(_snap.get("capacity_reruns", {}).get("total", 0)) + \
            int(_snap.get("exchange_slot_reruns", {}).get("total", 0))
        _dp_record("kernel", staged_bytes * _dispatches,
                   max(device_s - (compile_us or 0) / 1e6, 0.0))
        if prog is not None:
            prog.advance(stage="fetch")
        with stats.timed("fetch_s"), collector.stage("fetch"):
            res = _batch_to_result(out, root)
    finally:
        # always drain the per-query peak (success AND failure paths):
        # the pool's map must stay bounded by in-flight queries
        peak_reserved = 0
        if memory_pool is not None:
            memory_pool.free(query_id, reserved)
            peak_reserved = memory_pool.query_peak_bytes(query_id, pop=True)
        if prof_on and not multi_region:
            # record on success AND failure -- a failed query's device
            # time must stay attributed (its flight dump embeds these
            # rows). The captured XLA-compile wall is SUBTRACTED so
            # device_us is device occupancy, not trace+compile: a cold
            # dispatch would otherwise outrank genuinely hot kernels on
            # every ranking surface. (The region executor attributes
            # per region fingerprint inside its loop instead.)
            cu = compile_us if compile_us is not None \
                else collector.take_compile_us()
            from ..server.tracing import TraceContext as _TC
            from .profiler import plan_label, plan_tables, record_call
            record_call(
                fp_prof, label=plan_label(root),
                tables=plan_tables(root),
                device_us=max(int(device_s * 1e6) - cu, 0),
                rows_in=staged_rows, bytes_in=staged_bytes,
                rows_out=res.row_count if res is not None else 0,
                bytes_out=_result_bytes(res) if res is not None else 0,
                retraced=cu > 0, query_id=query_id,
                trace_id=trace_id.trace_id
                if isinstance(trace_id, _TC) else (trace_id or query_id))
    stats.add("output_rows", res.row_count)
    res.stats = stats.snapshot()
    _finalize_query_stats(collector, res, t_query0, peak_reserved, root,
                          trace_id, dp=dp, acc=acc, tl=tl, sf=sf)
    return res


# adaptive-capacity feedback (HBO-lite, HistoryBasedPlanStatistics
# analog): plan fingerprint -> the capacity scale that made it fit.
# Bounded process-local memory; structurally identical future
# submissions start at the known-good size instead of re-laddering.
_CAPACITY_FEEDBACK: Dict[str, int] = {}
_MAX_CAPACITY_SCALE = 1 << 10


def _dispatch_ladder(root: N.PlanNode, plan, jfn, call_lock, batches,
                     mesh, default_join_capacity: int, use_cache: bool,
                     fp: Optional[str], stats, adaptive_off: bool,
                     refine: bool, prog):
    """The overflow->rerun dispatch loop for ONE compiled program (a
    whole fused plan or a single pipeline region).

    Exchange-slot overflow (flag bit1) -> rerun with geometrically
    larger slots; slots clamp at the sender capacity, where overflow is
    impossible, so this converges. Join/group overflow (bit0) reruns
    with geometrically larger capacities up to the adaptive ceiling.
    This is the memory-feedback loop the reference runs as
    reserve/revoke -- here it recompiles with bigger static buckets
    instead. Under the region executor only the overflowing REGION
    re-dispatches; upstream regions' materialized outputs are reused.

    Returns (out, device_s, dispatch_fn, call_lock, cap_scale, scale,
    plan)."""
    device_s = 0.0
    scale = 1
    cap_scale = _CAPACITY_FEEDBACK.get(fp, 1) if fp else 1
    exec_root = root if cap_scale == 1 else None  # set below
    if cap_scale > 1:
        # HBO-lite: a structurally identical plan overflowed before;
        # start from the capacities that worked
        from ..plan.stats import scale_capacities
        exec_root = scale_capacities(root, cap_scale)
        plan, jfn, call_lock = _compile_any(
            exec_root, mesh, default_join_capacity * cap_scale,
            1, use_cache)
        stats.add("capacity_feedback_scale", cap_scale)
    from .datapath import now_us as _now_us
    while True:
        t_disp0 = _now_us()
        if jfn is None:
            fn = jax.jit(plan.fn)
            dispatch_fn = fn
            out, overflow = fn(tuple(batches))
        else:
            dispatch_fn = jfn
            with call_lock:  # serialize trace-time closure state
                out, overflow = jfn(tuple(batches))
        jax.block_until_ready(out)
        # host-observed device occupancy of this dispatch: the
        # block_until_ready delta around the existing sync point is the
        # only per-kernel timing one fused program exposes -- on the
        # monotonic now_us clock the timeline intervals share
        device_s += (_now_us() - t_disp0) / 1e6
        if prog is not None:  # each landed dispatch advances
            prog.advance()
        flags = int(np.asarray(overflow))
        if flags == 0:
            if cap_scale > 1 and fp:
                _CAPACITY_FEEDBACK[fp] = cap_scale
            break
        if flags & 1:
            # hard (join/group/unnest) overflow: adaptive rerun with
            # geometrically larger capacities (the memory-feedback loop
            # that replaces per-query hand hints; reserve/revoke analog)
            if cap_scale >= _MAX_CAPACITY_SCALE or adaptive_off:
                hint = (" (note: connector NDV statistics shrank "
                        "group capacities this run; set session "
                        "stats_capacity_refinement=false if a "
                        "hand-set max_groups must stand)"
                        if refine else "")
                raise RuntimeError(
                    "plan execution overflowed a static bucket "
                    "(join/group capacity) beyond the adaptive "
                    "rerun ceiling; rerun with larger capacity "
                    "hints (max_groups / join_capacity)" + hint)
            from ..plan.stats import scale_capacities
            cap_scale *= 4
            stats.add("capacity_reruns", 1)
            exec_root = scale_capacities(root, cap_scale)
            scale = 1
            plan, jfn, call_lock = _compile_any(
                exec_root, mesh, default_join_capacity * cap_scale,
                1, use_cache)
            continue
        if mesh is None or scale >= 1 << 20:  # unreachable: clamp
            raise RuntimeError(
                "exchange slot overflow did not converge")
        scale *= 2
        stats.add("exchange_slot_reruns", 1)
        plan, jfn, call_lock = _compile_any(
            exec_root if exec_root is not None else root, mesh,
            default_join_capacity * cap_scale, scale, use_cache)
    return out, device_s, dispatch_fn, call_lock, cap_scale, scale, plan


def _execute_regions(rplan, scan_leaves, batches, default_join_capacity,
                     use_cache, stats, session, adaptive_off, refine,
                     prog, collector, query_id, trace_id, prof_on,
                     memory_pool, plan_fp_root: str, sf: float = 0.01):
    """Materialized region executor (exec/regions.py partition): run
    each pipeline region as its own compiled-and-cached program in
    producer order. Region outputs stay DEVICE-resident Batches handed
    to downstream regions' programs -- a materialized block boundary in
    HBM, never a host round trip. Per-region: the plan cache keys on
    the region fingerprint, the kernel auditor (when armed) audits the
    region's program and feeds its K005 peak into the fusion cost
    model, and the continuous profiler attributes device time to the
    region with its plan-node chain + region tag as provenance.

    Returns (final output Batch, total device seconds, total compile
    micros drained so far)."""
    import contextlib

    from ..audit.staged import audit_staged_query, kernel_audit_enabled
    from ..server.flight_recorder import record_event
    from ..server.tracing import TraceContext as _TC
    from ..utils.config import session_flag
    from .accuracy import est_rows_of as _acc_est
    from .accuracy import record_node as _acc_record
    from .donation import (donation_enabled, note_donation,
                           note_fallback, overflow_incapable,
                           prepare_donation)
    from .memory import batch_bytes
    from .plan_cache import plan_fingerprint
    from .profiler import note_footprint, plan_label, plan_tables, \
        record_call
    from .regions import fusion_memory
    staged_by_id = {id(n): b for n, b in zip(scan_leaves, batches)}
    outputs: Dict[int, Batch] = {}
    # consumer refcounts: a materialized intermediate is dropped after
    # its LAST consumer dispatches, so peak HBM in per-op mode is the
    # max live set, not the sum of every boundary in the chain
    consumers: Dict[int, int] = {}
    for reg in rplan.regions:
        for i in reg.inputs:
            if i.kind == "region":
                consumers[i.region] = consumers.get(i.region, 0) + 1
    total_device_s = 0.0
    total_compile_us = 0
    audit_on = kernel_audit_enabled(session)
    cost_on = session_flag(session, "query_cost_analysis", False)
    donate_on = donation_enabled(session)
    # region-boundary intermediates are real HBM the fused path never
    # materializes: account them against the pool as OBSERVED usage
    # (note_usage, not admission) so the per-query peak reflects the
    # live set -- and shrinks by the donated bytes when donation
    # aliases a dead input into the region's output. The finally
    # balances whatever is still accounted (the caller's bulk free
    # only covers staged scans).
    inter_bytes: Dict[int, int] = {}
    nreg = len(rplan.regions)
    try:
        for reg in rplan.regions:
            rbatches = [staged_by_id[id(i.node)] if i.kind == "scan"
                        else outputs[i.region] for i in reg.inputs]
            plan, jfn, call_lock = _compile_any(reg.root, None,
                                                default_join_capacity, 1,
                                                use_cache)
            rfp = plan_fingerprint(reg.root)
            if audit_on:
                with stats.timed("kernel_audit_s"):
                    report = audit_staged_query(
                        plan, rbatches, mesh=None, query_id=query_id,
                        session=session, collector=collector, stats=stats,
                        memory_pool=memory_pool, plan_fp=rfp)
                if report and report.get("peak_bytes_estimate"):
                    fusion_memory().note_footprint(
                        rfp, report["peak_bytes_estimate"])
                    if prof_on:
                        note_footprint(rfp, report["peak_bytes_estimate"])
                    # per-region K005 estimate: region estimates fold by
                    # max into ONE query-level footprint record (the pool
                    # measures one per-query peak, and intermediates drop
                    # past their last consumer, so max is the honest
                    # planned-peak bound)
                    _acc_record("footprint", "MemoryPool", unit="bytes",
                                est=float(report["peak_bytes_estimate"]))
            # -- proven-safe buffer donation (exec/donation.py) ----------
            # engine half of the K006 proof: candidates are region-kind
            # inputs whose LAST consumer is this region, fed exactly once,
            # under an overflow-incapable root (the rerun ladder re-reads
            # inputs after overflow -- donated buffers would be freed)
            prep = None
            donated_nbytes = 0
            if donate_on and overflow_incapable(reg.root):
                region_uses: Dict[int, int] = {}
                for i in reg.inputs:
                    if i.kind == "region":
                        region_uses[i.region] = \
                            region_uses.get(i.region, 0) + 1
                dead_idx: list = []
                pos = 0
                for i, b in zip(reg.inputs, rbatches):
                    nleaves = len(jax.tree_util.tree_leaves(b))
                    if (i.kind == "region" and consumers[i.region] == 1
                            and region_uses[i.region] == 1):
                        dead_idx.extend(range(pos, pos + nleaves))
                    pos += nleaves
                if dead_idx:
                    try:
                        with (call_lock if call_lock is not None
                              else contextlib.nullcontext()):
                            prep = prepare_donation(rfp, plan.fn,
                                                    rbatches, dead_idx)
                    except Exception as e:
                        # fallback, never failure: nothing was consumed
                        # yet, the undonated dispatch below is untouched
                        prep = None
                        note_fallback()
                        stats.add("donation_fallbacks", 1)
                        if collector is not None:
                            collector.note("donation_fallbacks", 1)
                        record_event("donation_fallback",
                                     query_id=query_id, region=reg.tag,
                                     reason=str(e)[:200])
            if prep is not None:
                from .datapath import now_us as _now_us
                t_don0 = _now_us()
                with (call_lock if call_lock is not None
                      else contextlib.nullcontext()):
                    out, overflow = prep.dispatch(rbatches)
                jax.block_until_ready(out)
                dev_s = (_now_us() - t_don0) / 1e6
                if prog is not None:
                    prog.advance()
                oflags = int(np.asarray(overflow))
                if oflags:  # unreachable: whitelist admits no overflow op
                    raise RuntimeError(
                        f"donated region {reg.tag} set overflow flags "
                        f"{oflags}; the overflow-incapable whitelist is "
                        f"wrong -- this is a bug, not a capacity problem")
                donated_nbytes = prep.donated_bytes
                note_donation(donated_nbytes, len(prep.donate_idx))
                stats.add("donations", 1)
                stats.add("donated_bytes", donated_nbytes)
                if collector is not None:
                    collector.note("donations", 1)
                    collector.note("donated_bytes", donated_nbytes)
                record_event("buffer_donation", query_id=query_id,
                             region=reg.tag, bytes=donated_nbytes,
                             leaves=len(prep.donate_idx))
                dispatch_fn = None
            else:
                out, dev_s, dispatch_fn, dlock, cap_scale, scale, _ = \
                    _dispatch_ladder(
                        reg.root, plan, jfn, call_lock, rbatches, None,
                        default_join_capacity, use_cache, rfp, stats,
                        adaptive_off, refine, prog)
            if cost_on and collector is not None and dispatch_fn is not None:
                # per-region XLA cost analysis: the fused path's FLOPs /
                # bytes-accessed split, summed region by region so EXPLAIN
                # ANALYZE keeps its compile-stage roofline inputs under
                # fusion=0 / refusal / demotion
                cost = _stage_cost(dispatch_fn, rbatches,
                                   (rfp, cap_scale, scale), dlock)
                if cost:
                    collector.bump_stage("compile", **cost)
                    stats.add("xla_flops", cost["flops"])
            outputs[reg.index] = out
            if memory_pool is not None and consumers.get(reg.index, 0) > 0:
                # intermediate output: new HBM is its footprint minus the
                # donated bytes its program aliased in place
                held = max(batch_bytes(out) - donated_nbytes, 0)
                if held:
                    memory_pool.note_usage(query_id, held)
                    inter_bytes[reg.index] = held
            # region-boundary estimate-vs-actual: the region root's planner
            # estimate against the rows its program actually emitted (join
            # build sides that partition into their own region are
            # attributed here; the dispatch already synced, so reading the
            # active mask costs one small host transfer, not a block)
            _acc_record(f"region[{reg.tag}]:{type(reg.root).__name__}",
                        type(reg.root).__name__, unit="rows",
                        est=_acc_est(reg.root, sf),
                        actual=int(np.asarray(out.active).sum()))
            for i in reg.inputs:  # drop intermediates past their last use
                if i.kind == "region":
                    consumers[i.region] -= 1
                    if consumers[i.region] == 0:
                        outputs.pop(i.region, None)
                        freed = inter_bytes.pop(i.region, 0)
                        if memory_pool is not None and freed:
                            memory_pool.free(query_id, freed)
            total_device_s += dev_s
            # incremental compile drain: what accumulated since the last
            # region dispatched is this region's trace+compile share
            cu = collector.take_compile_us() if collector is not None else 0
            total_compile_us += cu
            dev_us = max(int(dev_s * 1e6) - cu, 0)
            stats.add(f"fusion_region_{reg.tag}_device_us", dev_us)
            if prof_on:
                record_call(
                    rfp,
                    label=(f"{plan_label(reg.root, max_len=120)} "
                           f"[region {reg.tag}/{nreg}]"),
                    tables=plan_tables(reg.root),
                    device_us=dev_us, retraced=cu > 0, query_id=query_id,
                    trace_id=trace_id.trace_id if isinstance(trace_id, _TC)
                    else (trace_id or query_id))
    finally:
        # no residue may leak into the pool's per-query ledger: the
        # caller's finally frees exactly the staged-scan reservation
        if memory_pool is not None:
            leftover = sum(inter_bytes.values())
            if leftover:
                memory_pool.free(query_id, leftover)
    # materialized-baseline sample for the demotion comparator: the
    # whole span just ran with materialized boundaries, so its total
    # device time is the unfused side of the span's fused-vs-unfused
    # comparison (keyed by the fingerprint the span fuses to)
    fusion_memory().note_unfused(
        plan_fp_root,
        max(int(total_device_s * 1e6) - total_compile_us, 0))
    return (outputs[rplan.regions[-1].index], total_device_s,
            total_compile_us)


def _scan_key(index: int, node: N.PlanNode) -> str:
    """Structural operator key for the index-th scan leaf (DFS order).
    Structural (not node-id) keys survive plan-cache canonicalization
    AND line up across workers running the same fragment, so per-node
    rows merge cross-worker by plain key equality. The label is part of
    the key so a leaf fragment's TableScan and a consumer fragment's
    RemoteSource at the same index never fold together."""
    return f"scan[{index}]:{_scan_label(node)}"


def _scan_label(node: N.PlanNode) -> str:
    if isinstance(node, N.TableScanNode):
        return f"TableScan[{node.connector}.{node.table}]"
    if isinstance(node, N.RemoteSourceNode):
        return "RemoteSource"
    return type(node).__name__


# cost_analysis memo: (plan fingerprint+scales, batch shapes) ->
# {flops, bytes_accessed}. lower() re-traces the program, so the
# analysis is paid once per distinct (program, shape) and amortized
# across repeats; LRU-evicted so a long-lived server keeps caching.
_COST_MEMO: "collections.OrderedDict[tuple, Optional[dict]]" = \
    collections.OrderedDict()
_COST_MEMO_MAX = 256
_COST_MEMO_LOCK = threading.Lock()


def _stage_cost(dispatch_fn, batches, fingerprint,
                call_lock=None) -> Optional[dict]:
    import contextlib
    key = (fingerprint,
           tuple((b.capacity, b.num_columns) for b in batches))
    with _COST_MEMO_LOCK:
        if key in _COST_MEMO:
            _COST_MEMO.move_to_end(key)
            return _COST_MEMO[key]
    try:
        # lower() re-traces: hold the cached entry's dispatch lock so a
        # concurrent first dispatch's trace-time closure state can't tear
        with call_lock or contextlib.nullcontext():
            lowered = dispatch_fn.lower(tuple(batches))
        analysis = lowered.cost_analysis()
        cost = {"flops": max(float(analysis.get("flops", 0.0)), 0.0),
                "bytes_accessed":
                    max(float(analysis.get("bytes accessed", 0.0)), 0.0)}
    except Exception:  # noqa: BLE001 - cost analysis is best-effort
        cost = None
    with _COST_MEMO_LOCK:
        _COST_MEMO[key] = cost
        while len(_COST_MEMO) > _COST_MEMO_MAX:
            _COST_MEMO.popitem(last=False)
    return cost


def _result_bytes(res: "QueryResult") -> int:
    total = 0
    for vals, nulls in zip(res.columns, res.nulls):
        total += getattr(vals, "nbytes", 0) + getattr(nulls, "nbytes", 0)
    return total


def _finalize_query_stats(collector: StatsCollector, res: "QueryResult",
                          t0: float, peak_reserved_bytes: int,
                          root: Optional[N.PlanNode],
                          trace_id=None, dp=None, acc=None, tl=None,
                          sf: float = 0.01) -> None:
    """Close out the structured stats for one run_query invocation and
    emit one tracer span per collected stage. `peak_reserved_bytes` is
    the pool high-water mark the caller already drained. `dp` is the
    invocation's datapath ledger: its hop map rides QueryStats.datapath
    (stitching worker slices through the task-status path) and the
    bounded per-query registry flight dumps embed from. `tl` is the
    interval-timeline ledger (exec/timeline.py): its slice rides
    QueryStats.timeline the same way, and the per-query registry keeps
    it cross-linked to the query's trace id (the Chrome export)."""
    qs = collector.stats
    if dp is not None:
        from .datapath import merge_hop_maps, note_query
        hops = dp.snapshot_hops()
        if hops:
            qs.datapath = merge_hop_maps(qs.datapath, hops)
            note_query(collector.query_id, hops)
    if tl is not None:
        from ..server.tracing import TraceContext as _TC
        from .timeline import note_query as _tl_note
        sl = tl.snapshot_slice()
        if not sl.is_empty():
            qs.timeline = qs.timeline.merge(sl)
            _tl_note(collector.query_id, sl,
                     trace_id=trace_id.trace_id
                     if isinstance(trace_id, _TC)
                     else (trace_id or collector.query_id))
    # drain any compile time not yet attributed (the streaming/spill
    # early-return paths compile inside their execute stage and never
    # reach the main path's drain); same clamp + anchor as there
    leftover_us = collector.take_compile_us()
    exec_stage = qs.stages.get("execute")
    if exec_stage is not None and exec_stage.wall_us:
        leftover_us = min(leftover_us, exec_stage.wall_us)
    if leftover_us:
        anchor = collector.stage_span_start("execute") or t0
        collector.record_stage("compile", anchor,
                               anchor + leftover_us / 1e6,
                               compile_us=leftover_us)
    qs.wall_us = int((time.time() - t0) * 1e6)
    qs.output_rows = res.row_count
    qs.output_bytes = _result_bytes(res)
    staging = qs.stages.get("staging")
    peak = max(staging.bytes if staging else 0, peak_reserved_bytes)
    qs.peak_memory_bytes = max(qs.peak_memory_bytes, peak)
    if root is not None:
        collector.operator("output", type(root).__name__,
                           output_rows=res.row_count,
                           output_bytes=qs.output_bytes,
                           wall_us=qs.stage_us("fetch"))
    # estimate-vs-actual close-out (exec/accuracy.py): the root's
    # cardinality record, the footprint record's measured side (the
    # pool peak the caller drained), then the whole ledger rides
    # QueryStats.accuracy (stitching worker slices through the
    # task-status path) and folds into the process registry +
    # q-error histogram -- complete records only, at this one seam
    if acc is not None:
        from .accuracy import est_rows_of as _est_of
        from .accuracy import finalize_query as _acc_finalize
        from .accuracy import merge_record_maps as _acc_merge
        if root is not None:
            acc.record("output", node_type=type(root).__name__,
                       unit="rows", est=_est_of(root, sf),
                       actual=float(res.row_count))
        recs = acc.snapshot_records()
        if "footprint" in recs and qs.peak_memory_bytes:
            acc.record("footprint", node_type="MemoryPool",
                       unit="bytes",
                       actual=float(qs.peak_memory_bytes))
            recs = acc.snapshot_records()
        if recs:
            qs.accuracy = _acc_merge(qs.accuracy, recs)
            _acc_finalize(collector.query_id, recs)
    res.query_stats = qs
    # trace_id is either a plain grouping string (legacy) or a
    # TraceContext carrying (trace id, parent span id): with a context,
    # stage spans become children of the propagated task/query span so
    # the distributed trace stitches with valid parent edges
    from ..server.tracing import TraceContext
    if isinstance(trace_id, TraceContext):
        collector.emit_spans(trace_id.trace_id,
                             parent_id=trace_id.span_id)
    else:
        collector.emit_spans(trace_id or collector.query_id)
    # per-stage latency distributions (/v1/metrics histograms): each
    # stage's wall feeds the process histogram, exemplar'd with this
    # query's trace id so a p99 execute spike links to its waterfall
    from ..server.metrics import observe_histogram
    tid = trace_id.trace_id if isinstance(trace_id, TraceContext) \
        else (trace_id or collector.query_id)
    for name, st in qs.stages.items():
        if st.wall_us:
            observe_histogram("presto_tpu_stage_seconds",
                              st.wall_us / 1e6, labels={"stage": name},
                              trace_id=tid)


def _compile_any(root: N.PlanNode, mesh, default_join_capacity: int,
                 slot_scale: int, use_cache: bool):
    """(CompiledPlan, jitted-fn-or-None, lock-or-None) via the
    compiled-plan cache when node-id-keyed kwargs aren't in play."""
    if use_cache:
        from .plan_cache import cached_compile
        return cached_compile(root, mesh, default_join_capacity,
                              exchange_slot_scale=slot_scale)
    return (compile_plan(root, mesh, default_join_capacity,
                         exchange_slot_scale=slot_scale), None, None)


def _count_result(rows: int, name: str = "rows") -> QueryResult:
    return QueryResult([np.array([rows], dtype=np.int64)],
                       [np.array([False])], [name], 1,
                       types=[T.BIGINT])


def _run_write_root(node: N.PlanNode, **kw) -> QueryResult:
    """Execute a DdlNode / TableFinishNode / TableWriterNode root.

    Local + mesh tiers run the whole write under one TableFinish
    (staged handle, atomic publish). On the HTTP tier the fragmenter
    splits writer and finish: each worker task's TableWriterNode
    publishes its own chunk (the presto-memory per-node append
    semantics) and the finish fragment just sums counts."""
    from ..connectors import catalog

    if isinstance(node, N.DdlNode):
        assert node.op == "drop_table", node.op
        catalog(node.connector).drop_table(node.table,
                                           if_exists=node.if_exists)
        res = QueryResult([np.array([True])], [np.array([False])],
                          ["result"], 1, types=[T.BOOLEAN])
        return res

    if isinstance(node, N.TableRewriteNode):
        # DELETE/UPDATE: compute new contents + `changed` flags on
        # device, swap the table host-side, report affected rows. The
        # whole read-compute-swap holds the table's writer lock so a
        # concurrent committed INSERT cannot vanish under the swap.
        mod = catalog(node.connector)
        with mod.write_lock(node.table):
            res = run_query(N.OutputNode(node.source, []), **kw)
            ncols = len(res.columns) - 1
            changed = np.asarray(res.columns[-1]).astype(bool) & \
                ~np.asarray(res.nulls[-1], dtype=bool)
            affected = int(changed.sum())
            if node.kind == "delete":
                keep = ~changed
                cols = [c[keep] for c in res.columns[:ncols]]
                nulls = [n[keep] for n in res.nulls[:ncols]]
            else:
                cols = list(res.columns[:ncols])
                nulls = list(res.nulls[:ncols])
            mod.replace_table(node.table, cols, nulls)
        return _count_result(affected)

    if isinstance(node, N.TableWriterNode):
        res = run_query(N.OutputNode(node.source, node.column_names), **kw)
        mod = catalog(node.connector)
        h = mod.begin_insert(node.table)
        try:
            mod.append(h, res.columns, res.nulls)
            rows = mod.finish_insert(h)
        except BaseException:
            mod.abort_insert(h)
            raise
        return _count_result(rows)

    finish: N.TableFinishNode = node
    mod = catalog(finish.connector)
    src = finish.source
    # single-process execution collapses the writer/finish exchange seam
    while isinstance(src, N.ExchangeNode):
        src = src.source
    if isinstance(src, N.TableWriterNode):
        # single-process (local/mesh) write: stage + atomic publish
        h = mod.begin_insert(
            finish.table,
            create_columns=finish.create_columns if finish.create else None,
            create_types=finish.create_types if finish.create else None)
        try:
            res = run_query(N.OutputNode(src.source, src.column_names),
                            **kw)
            mod.append(h, res.columns, res.nulls)
            rows = mod.finish_insert(h)
        except BaseException:
            mod.abort_insert(h)
            raise
        return _count_result(rows)
    # distributed finish: the source plan delivers per-task counts
    res = run_query(N.OutputNode(finish.source, ["rows"]), **kw)
    total = int(sum(int(v) for v, nl in zip(res.columns[0], res.nulls[0])
                    if not nl))
    return _count_result(total)


def _planned_scan_bytes(node: N.PlanNode, sf: float,
                        capacity_hint: Optional[int], pad_multiple: int,
                        scan_range: Optional[Tuple[int, int]],
                        remote_sources: Dict[str, Batch]) -> int:
    """Planned HBM footprint of a scan input WITHOUT materializing it."""
    if isinstance(node, N.RemoteSourceNode):
        b = remote_sources.get(node.id)
        if b is None:
            return 0
        from .memory import batch_bytes
        return batch_bytes(b)
    if isinstance(node, N.ValuesNode):
        rows = len(node.rows)
        types = node.types
    else:
        from ..connectors import catalog
        conn = catalog(node.connector)
        rows = scan_range[1] if scan_range is not None else \
            conn.table_row_count(node.table, sf)
        types = node.column_types
    cap = capacity_hint or max(-(-rows // pad_multiple) * pad_multiple,
                               pad_multiple)
    per_row = 1  # active mask
    for ty in types:
        if ty.is_string:
            per_row += ty.max_length if ty.max_length < 1 << 20 else 64
            per_row += 5  # lengths + nulls
        else:
            per_row += ty.to_dtype().itemsize + 1
    return cap * per_row


def _batch_to_result(out: Batch, root: N.PlanNode) -> QueryResult:
    act = np.asarray(out.active)
    idx = np.nonzero(act)[0]
    cols, nulls, types = [], [], []
    for c in range(out.num_columns):
        v, n = to_numpy(out.column(c))
        ty = out.column(c).type
        v = v[idx]
        if v.dtype != object and v.dtype.kind in "iu" and ty.is_fixed_width:
            # narrow-width lanes widen back to the logical dtype at the
            # result boundary (device->host already moved narrow bytes;
            # clients/serde see the declared type's width)
            ld = np.dtype(ty.to_dtype())
            if ld.kind in "iu" and v.dtype != ld:
                v = v.astype(ld)
        cols.append(v)
        nulls.append(n[idx])
        types.append(ty)
    names = root.names if isinstance(root, N.OutputNode) else \
        [f"col{i}" for i in range(out.num_columns)]
    return QueryResult(cols, nulls, names, len(idx), types=types)
