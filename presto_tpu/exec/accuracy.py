"""Estimate-accuracy observatory: per-plan-node cardinality and
footprint q-error attribution with misestimate verdicts.

The observability gap this closes: ROADMAP item 2 wants adaptive
execution gated on "estimate band breaches" and item 2(c) wants planner
estimates seeded from the history archive's per-fingerprint row counts
-- but nothing before this module recorded estimate-vs-actual anywhere.
``plan/stats.estimate_rows`` guesses rows (with Presto's
UNKNOWN_FILTER_COEFFICIENT analog ``_FILTER_SELECTIVITY``), kernaudit
K005 guesses peak bytes, the runner measures both, and the two never
met. This module is the meeting point: the instrument ROADMAP items 2
and 3 will be gated against, exactly as the datapath waterfall is the
instrument item 1 is gated against.

Model -- three layers, one merge law (the datapath template):

  * ``NodeAccuracy`` -- one mergeable estimate-vs-actual record per
    plan node, in one of two units: ``rows`` (cardinality) or
    ``bytes`` (K005 estimated-peak vs MemoryPool measured-peak). The
    merge law mirrors ``QueryStats.merge``: estimates max (each worker
    stamps the SAME per-fragment estimate, so max is idempotent),
    row actuals add (worker slices partition the stream), byte actuals
    max (peaks max, like ``peak_memory_bytes``), task counts add --
    associative, commutative, with the zero record as identity, so
    worker slices stitch through the existing task-status path
    (``QueryStats.accuracy`` carries these records worker ->
    coordinator, folded by ``QueryStats.merge``).
  * ambient per-query ledger (``AccuracyLedger`` + ``recording``):
    ``exec/runner.py`` installs one around each run_query; estimates
    are stamped onto the prepared plan at ``prepare_plan`` time
    (:func:`stamp_estimates`, so EXPLAIN and execution share one
    provenance) and every measured boundary (scan outputs, region
    outputs, join build sides via region cuts, streaming/spill root
    counts, K005 footprint audits) calls :func:`record_node`. Records
    may arrive half-open (estimate at audit time, actual at finalize);
    only COMPLETE records -- both sides present -- fold into process
    totals and the ``presto_tpu_q_error`` histogram, at finalize.
  * process-lifetime registry: the ``GET /v1/accuracy`` slice (worker
    serves it; the statement tier merges slices cluster-wide via
    server/client.pull_worker_docs, processId-deduped, stable zero
    shape), ``system.cardinality``, metrics.accuracy_families(),
    flight-dump embeds, and the bench.py per-query artifact section.

The q-error is Moerkotte's metric: ``max(est/act, act/est)`` with both
sides clamped to >= 1 row/byte (a 0-vs-0 estimate is exact, not a
division error), always >= 1.0, direction "under" when the planner
guessed low -- the dangerous direction (undersized joins spill;
oversized reservations merely waste). :func:`misestimate_verdict` is a
pure function of (records, band): it names the worst offender per
query ("JoinNode J3 underestimated 47x") without reading clocks or
env, so identical inputs always name the same node.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import uuid
from typing import Dict, List, Optional

from ..utils.locks import OrderedLock

__all__ = ["UNITS", "NodeAccuracy", "AccuracyLedger", "recording",
           "record_node", "q_error", "direction_of",
           "merge_record_maps", "record_map_to_json",
           "record_map_from_json", "misestimate_verdict",
           "stamp_estimates", "est_rows_of", "finalize_query",
           "note_query", "accuracy_for_query", "query_max_q_error",
           "clear_accuracy", "process_totals", "accuracy_doc",
           "merge_accuracy_docs", "cluster_accuracy_doc", "snapshot",
           "accuracy_summary"]

# the unit catalog: ONE closed vocabulary every surface shares (metrics
# label presets, /v1/accuracy zero shape, system.cardinality rows, the
# EXPLAIN ANALYZE tail). `rows` is cardinality (plan/stats.estimate_rows
# vs measured output rows); `bytes` is footprint (kernaudit K005
# estimated peak vs MemoryPool measured peak).
UNITS = ("rows", "bytes")

# one id per process: the cluster merge deduplicates slices by it, so
# two server shells over one process (the test topology) count once
_PROCESS_ID = uuid.uuid4().hex

# q-error at-or-below this is "within band" (Presto treats estimates
# within a small factor as trustworthy); above it the record counts as
# a misestimate on /v1/metrics and arms the verdict
_DEFAULT_BAND = 2.0

# sentinel distinguishing "attribute absent" from "estimate is None"
_MISSING = object()


@dataclasses.dataclass
class NodeAccuracy:
    """One plan node's estimate-vs-actual record. Merges with the
    usual law: estimates max (idempotent across workers stamping the
    same fragment), row actuals add, byte actuals max, tasks add --
    associative and commutative with the zero record as identity,
    like QueryStats. ``est``/``actual`` are None while that side is
    unknown (half-open records never produce a q-error)."""
    node: str
    node_type: str = ""
    unit: str = "rows"
    est: Optional[float] = None
    actual: Optional[float] = None
    tasks: int = 0

    def merge(self, other: "NodeAccuracy") -> "NodeAccuracy":
        assert self.node == other.node, \
            f"merging nodes {self.node} != {other.node}"
        unit = self.unit or other.unit
        return NodeAccuracy(
            node=self.node,
            node_type=self.node_type or other.node_type,
            unit=unit,
            est=_opt_max(self.est, other.est),
            actual=(_opt_sum(self.actual, other.actual)
                    if unit == "rows"
                    else _opt_max(self.actual, other.actual)),
            tasks=self.tasks + other.tasks)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, doc: dict) -> "NodeAccuracy":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in known})


def _opt_max(a: Optional[float], b: Optional[float]) -> Optional[float]:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


def _opt_sum(a: Optional[float], b: Optional[float]) -> Optional[float]:
    if a is None:
        return b
    if b is None:
        return a
    return a + b


def q_error(est: Optional[float],
            actual: Optional[float]) -> Optional[float]:
    """Moerkotte's q-error: max(est/act, act/est), both sides clamped
    to >= 1 (zero estimated against zero actual is exact, not a
    division error). None while either side is unknown."""
    if est is None or actual is None:
        return None
    e = max(float(est), 1.0)
    a = max(float(actual), 1.0)
    return max(e / a, a / e)


def direction_of(est: Optional[float],
                 actual: Optional[float]) -> str:
    """"under" when the planner guessed low (the dangerous direction),
    "over" when high, "exact" otherwise (including unknown sides)."""
    if est is None or actual is None:
        return "exact"
    if float(est) < float(actual):
        return "under"
    if float(est) > float(actual):
        return "over"
    return "exact"


def merge_record_maps(a: Dict[str, NodeAccuracy],
                      b: Dict[str, NodeAccuracy]
                      ) -> Dict[str, NodeAccuracy]:
    """Fold two record maps by node key (NodeAccuracy.merge's law
    lifts to maps: still associative + commutative, empty map as
    identity)."""
    out = dict(a)
    for k, r in b.items():
        out[k] = out[k].merge(r) if k in out else r
    return out


def record_map_to_json(records: Dict[str, NodeAccuracy]
                       ) -> Dict[str, dict]:
    return {k: r.to_json() for k, r in records.items()}


def record_map_from_json(doc: Dict[str, dict]
                         ) -> Dict[str, NodeAccuracy]:
    out = {}
    for k, r in (doc or {}).items():
        out[k] = NodeAccuracy.from_json({"node": k, **r})
    return out


class AccuracyLedger:
    """Per-query estimate-vs-actual accumulator (the ambient
    collection target). Thread-safe: parallel region dispatch and a
    future pipelined staging path record from worker threads while
    the driver thread records the root."""

    _GUARDED_BY = {"_lock": ("records",)}

    def __init__(self):
        self.records: Dict[str, NodeAccuracy] = {}
        self._lock = OrderedLock("accuracy.AccuracyLedger._lock")

    def record(self, node: str, node_type: str = "",
               unit: str = "rows", est: Optional[float] = None,
               actual: Optional[float] = None) -> None:
        """Fold one observation. Half-open calls are fine: the K005
        audit records the estimate side, finalize fills the actual.
        Within one ledger the law matches the cross-worker merge:
        estimates max, row actuals add (streaming chunks re-record
        the same node), byte actuals max."""
        with self._lock:
            r = self.records.get(node)
            if r is None:
                r = self.records[node] = NodeAccuracy(
                    node, node_type=node_type, unit=unit, tasks=1)
            if node_type and not r.node_type:
                r.node_type = node_type
            if est is not None:
                r.est = _opt_max(r.est, float(est))
            if actual is not None:
                r.actual = (_opt_sum(r.actual, float(actual))
                            if r.unit == "rows"
                            else _opt_max(r.actual, float(actual)))

    def snapshot_records(self) -> Dict[str, NodeAccuracy]:
        with self._lock:
            return {k: dataclasses.replace(r)
                    for k, r in self.records.items()}


# -- ambient (thread-local) attribution ---------------------------------

_tls = threading.local()


def _current_ledger() -> Optional[AccuracyLedger]:
    return getattr(_tls, "ledger", None)


class recording:
    """Install `ledger` as this thread's ambient accuracy target
    (exec/runner.py wraps each run_query; nested invocations shadow
    and restore, like stats.collecting and datapath.recording)."""

    def __init__(self, ledger: AccuracyLedger):
        self.ledger = ledger

    def __enter__(self):
        self.prev = _current_ledger()
        _tls.ledger = self.ledger
        return self.ledger

    def __exit__(self, *exc):
        _tls.ledger = self.prev
        return False


def record_node(node: str, node_type: str = "", unit: str = "rows",
                est: Optional[float] = None,
                actual: Optional[float] = None) -> None:
    """Fold one estimate-vs-actual observation into the ambient
    ledger (when one is installed). Never raises: this sits on the
    scan/region hot paths. Process totals and histograms fold at
    :func:`finalize_query`, not here, so half-open records never
    pollute distributions."""
    try:
        ledger = _current_ledger()
        if ledger is not None:
            ledger.record(node, node_type=node_type, unit=unit,
                          est=est, actual=actual)
    except Exception as e:  # noqa: BLE001 - attribution must never
        # fail the query it observes; leave the counted trace
        try:
            from ..server.metrics import record_suppressed
            record_suppressed("accuracy", "record_node", e)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


# -- estimate stamping ---------------------------------------------------


def stamp_estimates(root, sf: float) -> None:
    """Stamp ``est_rows`` onto every node of a prepared plan (called
    at the end of prepare_plan, so EXPLAIN and execution read the SAME
    estimate -- one provenance). Nodes whose estimate is unknowable
    (stats-free connectors, remote sources) carry None."""
    from ..plan.stats import estimate_rows

    def walk(n) -> None:
        try:
            n.est_rows = estimate_rows(n, sf)
        except Exception:  # noqa: BLE001 - a connector without stats
            # must not fail planning; the node just has no estimate
            n.est_rows = None
        for s in getattr(n, "sources", None) or ():
            walk(s)

    walk(root)


def est_rows_of(node, sf: float) -> Optional[float]:
    """The node's stamped estimate, falling back to a fresh
    ``estimate_rows`` call for trees that lost their stamps (the plan
    cache canonicalizes to an unstamped tree; refine_capacities
    rebuilds nodes via dataclasses.replace). Either way the number is
    the same pure function of (node, sf) -- single provenance."""
    est = getattr(node, "est_rows", _MISSING)
    if est is not _MISSING:
        return est
    try:
        from ..plan.stats import estimate_rows
        return estimate_rows(node, sf)
    except Exception:  # noqa: BLE001
        return None


# -- process registry ----------------------------------------------------

# request handlers (/v1/accuracy, system tables), engine threads
# (finalize_query after each run) and the flight recorder all touch
# these
_LOCK = OrderedLock("accuracy._LOCK")
# query id -> node record map (the flight-dump cross-link AND the
# /v1/accuracy payload); bounded like datapath's query ledgers
_QUERY_RECORDS: "collections.OrderedDict[str, Dict[str, NodeAccuracy]]" \
    = collections.OrderedDict()
_QUERY_RECORDS_MAX = 256
# per-unit lifetime counters: the /v1/metrics families and the cheap
# /v1/cluster embed read these (stable zero shape from process start)
_TOTALS: Dict[str, dict] = {}

_GUARDED_BY = {"_LOCK": ("_QUERY_RECORDS", "_TOTALS")}


def _zero_totals() -> dict:
    return {"records": 0, "under": 0, "over": 0,
            "worstQError": 0.0, "worstNode": ""}


def note_query(query_id: str,
               records: Dict[str, NodeAccuracy]) -> None:
    """Retain one query's record map for flight-dump embeds and the
    /v1/accuracy payload (bounded); re-notes of the same query id
    merge (worker task slices stitch)."""
    if not records:
        return
    with _LOCK:
        have = _QUERY_RECORDS.get(query_id)
        if have is not None:
            _QUERY_RECORDS[query_id] = merge_record_maps(have, records)
            _QUERY_RECORDS.move_to_end(query_id)
        else:
            _QUERY_RECORDS[query_id] = dict(records)
            while len(_QUERY_RECORDS) > _QUERY_RECORDS_MAX:
                _QUERY_RECORDS.popitem(last=False)


def finalize_query(query_id: str,
                   records: Dict[str, NodeAccuracy],
                   band: float = _DEFAULT_BAND) -> None:
    """Fold one finished query's COMPLETE records (both sides known)
    into the process totals, the ``presto_tpu_q_error`` histogram,
    and the bounded per-query registry. Never raises -- the runner
    calls this on every exit path."""
    # M001: one record per PLAN NODE of one query, not per row
    _BOUNDED_BY = {"observed": "one q-error sample per plan node"}
    try:
        note_query(query_id, records)
        observed = []
        with _LOCK:
            for rec in records.values():
                q = q_error(rec.est, rec.actual)
                if q is None:
                    continue
                t = _TOTALS.get(rec.unit)
                if t is None:
                    t = _TOTALS[rec.unit] = _zero_totals()
                t["records"] += 1
                d = direction_of(rec.est, rec.actual)
                if q > band and d in ("under", "over"):
                    t[d] += 1
                if q > t["worstQError"]:
                    t["worstQError"] = q
                    t["worstNode"] = rec.node
                observed.append((rec.unit, q))
        from ..server.metrics import observe_histogram
        for unit, q in observed:
            observe_histogram("presto_tpu_q_error", float(q),
                              labels={"unit": unit})
    except Exception as e:  # noqa: BLE001 - accounting must never
        # fail the query it observes
        try:
            from ..server.metrics import record_suppressed
            record_suppressed("accuracy", "finalize_query", e)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


def accuracy_for_query(query_id: str) -> Dict[str, dict]:
    """The record map a query id produced, as JSON rows (flight
    dumps)."""
    with _LOCK:
        records = _QUERY_RECORDS.get(query_id)
        return record_map_to_json(records) if records else {}


def query_max_q_error(query_id: str) -> Optional[float]:
    """The worst q-error a query's finalized records carry, or None
    while nothing complete was recorded (the ptop per-query column)."""
    with _LOCK:
        records = _QUERY_RECORDS.get(query_id)
        if not records:
            return None
        qs = [q for q in (q_error(r.est, r.actual)
                          for r in records.values())
              if q is not None]
    return max(qs) if qs else None


def clear_accuracy() -> None:
    """Drop the process registry + per-query maps (tests isolate
    state)."""
    with _LOCK:
        _QUERY_RECORDS.clear()
        _TOTALS.clear()


def process_totals() -> Dict[str, dict]:
    """Lifetime per-unit totals, every catalog unit present (zero
    shape is stable from process start)."""
    with _LOCK:
        live = {u: dict(t) for u, t in _TOTALS.items()}
    return {u: live.get(u, _zero_totals()) for u in UNITS}


# -- verdicts ------------------------------------------------------------


def _as_fields(node: str, r) -> dict:
    """NodeAccuracy or its JSON row -> plain fields (both shapes flow
    through the verdict: QueryStats carries objects, /v1/accuracy
    documents carry rows)."""
    if isinstance(r, NodeAccuracy):
        return {"node": r.node or node, "node_type": r.node_type,
                "unit": r.unit, "est": r.est, "actual": r.actual,
                "tasks": r.tasks}
    return {"node": r.get("node") or node,
            "node_type": r.get("node_type", ""),
            "unit": r.get("unit", "rows"),
            "est": r.get("est"), "actual": r.get("actual"),
            "tasks": int(r.get("tasks", 0))}


def misestimate_verdict(records,
                        band: float = _DEFAULT_BAND
                        ) -> Optional[dict]:
    """The named verdict: among COMPLETE records, the one with the
    largest q-error -- "JoinNode J3 underestimated 47x".
    ``withinBand`` is True when even the worst offender sits at or
    under ``band`` (the plan's estimates held; a clean replay stays
    silent). Pure function of its inputs -- no clocks, no env -- so
    identical records always name the same node. None when no record
    has both sides. Deterministic tiebreak: q-error desc, node key
    asc."""
    # M001: one candidate per PLAN NODE of one query
    _BOUNDED_BY = {"rows": "one verdict candidate per plan node"}
    rows = []
    for node, r in dict(records).items():
        f = _as_fields(node, r)
        q = q_error(f["est"], f["actual"])
        if q is None:
            continue
        rows.append((q, f))
    if not rows:
        return None
    q, f = sorted(rows, key=lambda t: (-t[0], t[1]["node"]))[0]
    d = direction_of(f["est"], f["actual"])
    within = q <= band
    label = f["node_type"] or "node"
    if d == "under":
        msg = f"{label} {f['node']} underestimated {q:.1f}x"
    elif d == "over":
        msg = f"{label} {f['node']} overestimated {q:.1f}x"
    else:
        msg = f"{label} {f['node']} estimated exactly"
    return {"node": f["node"], "nodeType": f["node_type"],
            "unit": f["unit"],
            "est": float(f["est"]), "actual": float(f["actual"]),
            "qError": round(q, 4), "direction": d,
            "band": band, "withinBand": within, "message": msg}


# -- surfaces ------------------------------------------------------------


def _record_row(node: str, r: NodeAccuracy) -> dict:
    q = q_error(r.est, r.actual)
    return {**r.to_json(),
            "qError": round(q, 4) if q is not None else None,
            "direction": direction_of(r.est, r.actual)}


def _query_entry(records: Dict[str, NodeAccuracy]) -> dict:
    return {"nodes": {k: _record_row(k, records[k])
                      for k in sorted(records)},
            "verdict": misestimate_verdict(records)}


def accuracy_doc() -> dict:
    """This process's /v1/accuracy slice: per-unit lifetime totals
    (zeros included -- the shape is stable from the first request
    on), the retained per-query record maps with per-query verdicts,
    and the process-lifetime worst verdict across them."""
    with _LOCK:
        queries = {qid: {k: dataclasses.replace(r)
                         for k, r in recs.items()}
                   for qid, recs in _QUERY_RECORDS.items()}
    merged_all: Dict[str, NodeAccuracy] = {}
    for recs in queries.values():
        merged_all = merge_record_maps(merged_all, recs)
    return {"processId": _PROCESS_ID,
            "totals": process_totals(),
            "queries": {qid: _query_entry(recs)
                        for qid, recs in queries.items()},
            "verdict": misestimate_verdict(merged_all)}


def merge_accuracy_docs(docs: List[dict]) -> dict:
    """Fold per-process slices into one cluster view. Slices sharing
    a processId count once (two server shells over one process report
    the same registry); per-query node maps merge by NodeAccuracy's
    law (worker slices of the SAME query stitch -- the distributed
    path's whole point); totals merge by sum for counts, max for
    worst; every verdict is recomputed over the merged records --
    order-independent throughout."""
    seen = set()
    queries: Dict[str, Dict[str, NodeAccuracy]] = {}
    totals = {u: _zero_totals() for u in UNITS}
    for doc in docs:
        pid = doc.get("processId") or f"anon-{id(doc):x}"
        if pid in seen:
            continue
        seen.add(pid)
        for qid, entry in (doc.get("queries") or {}).items():
            recs = record_map_from_json(entry.get("nodes") or {})
            queries[qid] = merge_record_maps(
                queries.get(qid, {}), recs)
        for unit, t in (doc.get("totals") or {}).items():
            if unit not in totals:
                continue
            out = totals[unit]
            out["records"] += int(t.get("records", 0))
            out["under"] += int(t.get("under", 0))
            out["over"] += int(t.get("over", 0))
            if float(t.get("worstQError", 0.0)) > out["worstQError"]:
                out["worstQError"] = float(t.get("worstQError", 0.0))
                out["worstNode"] = t.get("worstNode", "")
    merged_all: Dict[str, NodeAccuracy] = {}
    for recs in queries.values():
        merged_all = merge_record_maps(merged_all, recs)
    return {"totals": totals,
            "queries": {qid: _query_entry(recs)
                        for qid, recs in queries.items()},
            "verdict": misestimate_verdict(merged_all)}


def cluster_accuracy_doc(worker_urls=(), timeout: float = 3.0) -> dict:
    """The coordinator-side merge: this process's slice plus every
    reachable worker's ``GET /v1/accuracy``, folded per query by the
    record merge law. Pulls ride the shared best-effort helper
    (server/client.pull_worker_docs) so bearer/TLS/trace headers --
    and the skip-and-count-dead-workers contract -- stay identical to
    the /v1/profile and /v1/datapath merges'."""
    from ..server.client import pull_worker_docs
    pulled, workers_seen = pull_worker_docs(
        worker_urls, timeout, lambda c: c.accuracy(), "accuracy")
    merged = merge_accuracy_docs([accuracy_doc(), *pulled])
    return {"processId": _PROCESS_ID, "cluster": True,
            "workersPulled": workers_seen, **merged}


def snapshot() -> List[dict]:
    """Per-node rows across the retained queries (the
    system.cardinality table): insertion order by query, node key
    order within one query."""
    with _LOCK:
        queries = {qid: {k: dataclasses.replace(r)
                         for k, r in recs.items()}
                   for qid, recs in _QUERY_RECORDS.items()}
    rows = []
    for qid, recs in queries.items():
        for k in sorted(recs):
            rows.append({"queryId": qid, **_record_row(k, recs[k])})
    return rows


def accuracy_summary() -> dict:
    """The cheap /v1/cluster embed: lifetime complete-record count
    and the worst q-error (with its node) across units -- no locks
    held beyond the totals snapshot, no per-node payload."""
    totals = process_totals()
    worst_unit = max(
        UNITS, key=lambda u: (totals[u]["worstQError"], u))
    worst = totals[worst_unit]
    return {"records": sum(t["records"] for t in totals.values()),
            "misestimates": sum(t["under"] + t["over"]
                                for t in totals.values()),
            "worstQError": round(worst["worstQError"], 2),
            "worstNode": worst["worstNode"]}
