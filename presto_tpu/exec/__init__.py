from .planner import compile_plan, CompiledPlan
from .runner import run_query, QueryResult

__all__ = ["compile_plan", "CompiledPlan", "run_query", "QueryResult"]
