"""Streaming split execution: bounded-HBM scans feeding running
aggregation.

Reference surface: the split-driven Driver loop -- SqlTaskExecution
enqueues one DriverSplitRunner per split (execution/SqlTaskExecution.java:144),
each Driver streams pages scan->ops (operator/Driver.java:310), and
partial aggregation states merge at the end.

TPU model: one jit'd per-split program (scan pipeline -> PARTIAL group
table) plus one jit'd merge program (running table ⊕ split table ->
running table). The Python loop over splits is the driver; each
iteration reuses the same compiled executables (static shapes), so HBM
holds one split batch + two group tables regardless of table size --
the bounded-batch double-buffering the reference gets from page-sized
streaming. Host-side split generation overlaps device compute naturally
(dispatch is async until block_until_ready).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..block import Batch, concat_batches
from ..connectors import catalog
from ..expr import ir as E
from ..ops.aggregation import GroupByResult, group_by, merge_partials
from ..plan import nodes as N
from .planner import compile_plan

__all__ = ["streamable_agg_shape", "run_streaming_agg", "run_grouped_agg",
           "run_spilled_sort"]


def streamable_agg_shape(root: N.PlanNode) -> Optional[Tuple[N.AggregationNode,
                                                             N.TableScanNode]]:
    """Detect Output?(Aggregation(linear filter/project pipeline(Scan)))
    -- the shape streaming supports in round 1 (joins stream via the
    exchange layer instead)."""
    node = root.source if isinstance(root, N.OutputNode) else root
    # identity projections (column renames the planner emits above an
    # aggregation) don't change the streamable shape; a projection that
    # DROPS or reorders columns does (same arity check as
    # plan.rules._is_identity)
    while isinstance(node, N.ProjectNode) and \
            len(node.expressions) == len(node.source.output_types()) and \
            all(isinstance(e, E.InputReference) and e.channel == i
                for i, e in enumerate(node.expressions)):
        node = node.source
    if not isinstance(node, N.AggregationNode) or node.step != "SINGLE":
        return None
    if any(a.canonical in ("count_distinct", "approx_percentile")
           for a in node.aggregates):
        return None  # value-order states don't merge across splits
    cur = node.source
    while isinstance(cur, (N.FilterNode, N.ProjectNode)):
        cur = cur.source
    if isinstance(cur, N.TableScanNode):
        return node, cur
    return None


def _make_agg_executor(root: N.PlanNode, sf: float, split_rows: int,
                       n_buckets: int):
    """Build the jit'd per-split and merge programs ONCE; the returned
    runner executes one bucket lifespan. Buckets share the compiled
    executables (bucket id is a traced device scalar), so grouped
    execution pays n_buckets scan passes but a single compilation."""
    shape = streamable_agg_shape(root)
    assert shape is not None, "plan is not a streamable aggregation"
    agg, scan = shape

    pipeline = compile_plan(agg.source)
    nkeys = len(agg.group_channels)

    @jax.jit
    def split_step(batch: Batch, bucket_: jax.Array):
        b, ovf = pipeline.fn((batch,))
        if n_buckets > 1:
            from ..parallel.exchange import _row_hash
            h = _row_hash([b.column(c) for c in agg.group_channels])
            b = b.with_active(b.active & ((h % jnp.uint64(n_buckets))
                                          == bucket_.astype(jnp.uint64)))
        r = group_by(b, agg.group_channels, agg.aggregates, agg.max_groups)
        return r.batch, ovf | r.overflow

    @jax.jit
    def merge_step(running: Batch, part: Batch):
        both = concat_batches([running, part])
        r = merge_partials(both, nkeys, agg.aggregates, agg.max_groups)
        return r.batch, r.overflow

    conn = catalog(scan.connector)
    total = conn.table_row_count(scan.table, sf)
    starts = list(range(0, total, split_rows)) or [0]  # empty table: one
    # empty split still produces a well-formed (empty) group table

    def run(bucket: int) -> GroupByResult:
        running: Optional[Batch] = None
        overflow = jnp.zeros((), dtype=bool)  # accumulates on device: no
        # per-split host sync, so split generation overlaps device compute
        bucket_arr = jnp.asarray(bucket, dtype=jnp.int32)
        from .runner import stage_scan_split
        for start in starts:
            count = min(split_rows, max(total - start, 0))
            # shared narrow-width staging path: each split honors the
            # scan's physical_dtypes annotation (plan/widths.py), so the
            # per-split program reads narrowed lanes end to end
            batch = stage_scan_split(conn, scan, sf, start, count,
                                     split_rows)
            part, ovf1 = split_step(batch, bucket_arr)
            overflow = overflow | ovf1
            if running is None:
                running = part
            else:
                running, ovf2 = merge_step(running, part)
                overflow = overflow | ovf2
        jax.block_until_ready(running)
        return GroupByResult(running, running.count(), overflow)

    return run


def run_spilled_sort(root: N.PlanNode, sf: float, split_rows: int):
    """External sort with host-DRAM spill: the spill tier
    (spiller/FileSingleStreamSpiller + OrderByOperator's spillable
    PagesIndex analog, retargeted at the TPU memory hierarchy -- HBM
    holds one split, sorted runs spill to host DRAM, the run merge
    happens host-side).

    Supports Output(Sort(linear pipeline(Scan))). Returns (columns,
    nulls, perm-applied order) as host arrays.
    """
    import numpy as np

    out_node = root
    node = root.source if isinstance(root, N.OutputNode) else root
    assert isinstance(node, N.SortNode), "run_spilled_sort needs a Sort root"
    cur = node.source
    while isinstance(cur, (N.FilterNode, N.ProjectNode)):
        cur = cur.source
    assert isinstance(cur, N.TableScanNode), "spilled sort streams one scan"
    scan = cur

    from ..block import to_numpy
    pipeline = compile_plan(node.source)

    @jax.jit
    def split_step(batch: Batch):
        # pipeline only: runs spill unsorted, the host-side combine is a
        # full lexsort so a device pre-sort would be wasted work (a true
        # k-way merge of device-sorted runs is the planned upgrade)
        return pipeline.fn((batch,))

    conn = catalog(scan.connector)
    total = conn.table_row_count(scan.table, sf)
    runs: List[List[np.ndarray]] = []   # per run: one array per column
    run_nulls: List[List[np.ndarray]] = []
    from .runner import stage_scan_split
    for start in range(0, max(total, 1), split_rows):
        count = min(split_rows, max(total - start, 0))
        batch = stage_scan_split(conn, scan, sf, start, count, split_rows)
        sorted_b, _ = split_step(batch)
        act = np.asarray(sorted_b.active)
        sel = np.nonzero(act)[0]
        cols, nulls = [], []
        for c in range(sorted_b.num_columns):
            v, n = to_numpy(sorted_b.column(c))  # spill: leaves HBM here
            cols.append(v[sel])
            nulls.append(n[sel])
        runs.append(cols)
        run_nulls.append(nulls)

    # host-side combine: one lexsort over the spilled runs with
    # tie-PRESERVING keys (equal values share a rank so later sort keys
    # break ties, unlike positional argsort ranks)
    ncols = len(runs[0])
    merged = [np.concatenate([r[c] for r in runs]) for c in range(ncols)]
    merged_nulls = [np.concatenate([r[c] for r in run_nulls])
                    for c in range(ncols)]
    sort_cols = []
    for ch, desc, nulls_last in reversed(node.keys):
        vals = merged[ch]
        nl = merged_nulls[ch]
        if vals.dtype == object:
            svals = np.array([str(x) for x in vals])
            _, key = np.unique(svals, return_inverse=True)
            key = key.astype(np.float64)
        elif np.issubdtype(vals.dtype, np.integer):
            # longdouble's 64-bit mantissa keeps int64 keys exact while
            # still admitting +/-inf null sentinels
            key = vals.astype(np.longdouble)
        else:
            key = vals.astype(np.float64)
        if desc:
            key = -key
        key = np.where(nl, np.inf if nulls_last else -np.inf, key)
        sort_cols.append(key)
    perm = np.lexsort(sort_cols) if sort_cols else np.arange(len(merged[0]))
    merged = [c[perm] for c in merged]
    merged_nulls = [c[perm] for c in merged_nulls]
    names = root.names if isinstance(root, N.OutputNode) else \
        [f"col{i}" for i in range(ncols)]
    return merged, merged_nulls, names


def run_streaming_agg(root: N.PlanNode, sf: float, split_rows: int,
                      n_buckets: int = 1, bucket: int = 0) -> GroupByResult:
    """Execute a streamable aggregation plan split by split.

    With n_buckets > 1 this is one lifespan of grouped execution
    (execution/Lifespan.java:30, GroupedExecutionTagger.java:72 analog):
    only rows whose group-key hash lands in `bucket` are aggregated, so
    the dense table covers ~1/n_buckets of the groups -- trading extra
    scan passes for bounded HBM, exactly the reference's bucket-by-bucket
    memory bound (and its recovery unit)."""
    return _make_agg_executor(root, sf, split_rows, n_buckets)(bucket)


def run_grouped_agg(root: N.PlanNode, sf: float, split_rows: int,
                    n_buckets: int) -> List[GroupByResult]:
    """Grouped execution: run every bucket lifespan sequentially; the
    buckets' group sets are disjoint, so the concatenated tables are the
    full result. Peak HBM = one split batch + two bucket-sized group
    tables, independent of total group count."""
    runner = _make_agg_executor(root, sf, split_rows, n_buckets)
    return [runner(b) for b in range(n_buckets)]
