"""perfgate: the one performance-regression comparator, shared by the
in-engine sentinel and the offline bench gate.

The observability gap this closes: every prior round made the engine
better at explaining ONE query (telemetry, traces, kernel profiles),
but nothing compares runs ACROSS time -- a planner change that doubles
q1's wall, or a staging change that silently re-widens narrowed lanes,
ships invisibly unless a human re-reads bench artifacts. Prior Presto
acceleration work ("Accelerating Presto with GPUs", "Metadata Caching
in Presto") reports exactly this failure mode: offload/caching wins
evaporate without continuous regression detection. This module is the
comparator both detection surfaces share, so the live sentinel
(server/history.py, fed per query completion) and the offline gate
(scripts/perfgate.py, fed committed BENCH artifacts) cannot drift on
what "regressed" means.

The math -- deliberately robust and deliberately boring:

  * baseline center = **median** of the retained samples (a single
    outlier run cannot move it);
  * noise width = **MAD** (median absolute deviation) scaled by 1.4826
    (the consistency constant that makes MAD estimate sigma under
    normal noise);
  * a sample BREACHES when it lands beyond
    ``median +/- max(mad_k * 1.4826 * MAD, rel_threshold * median,
    abs_floor)`` on the metric's worse side. The three-way max means a
    noisy metric widens its own band (MAD term), a quiet metric still
    tolerates proportional drift (rel term), and micro-benchmark jitter
    below the absolute floor never pages anyone.

Everything here is a pure function of its inputs: no clocks, no env
reads (this module lives under ``exec/`` and is linted by tpulint R001
-- ambient knobs belong to the server tier that calls it), no
randomness -- which is what makes two ``scripts/perfgate.py`` runs
over identical artifacts byte-identical, the determinism the gate's
exit code stands on.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict, deque
from typing import Dict, Iterable, List, Optional, Sequence

__all__ = ["MetricSpec", "SENTINEL_SPECS", "BENCH_SPECS", "median",
           "mad", "noise_band", "compare", "compare_metrics",
           "RollingBaseline"]


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """How one metric is gated.

    ``higher_is_worse``: wall times and staged bytes regress upward;
    throughput (rows/s) regresses downward. ``rel_threshold`` is the
    proportional drift always tolerated (0.5 = +50%); ``abs_floor`` is
    the absolute delta below which a breach is never declared (keeps
    sub-noise metrics from gating); ``mad_k`` scales the measured noise
    band."""
    name: str
    higher_is_worse: bool = True
    rel_threshold: float = 0.5
    abs_floor: float = 0.0
    mad_k: float = 5.0


# What the LIVE sentinel gates per completed query (server/history.py
# feeds these from the QueryStats rollup). Compile time is deliberately
# absent: a plan-cache miss legitimately pays seconds the hit does not,
# and wall (which contains it) already gates end-to-end latency.
SENTINEL_SPECS: Sequence[MetricSpec] = (
    MetricSpec("wall_us", rel_threshold=0.75, abs_floor=100_000.0),
    MetricSpec("execute_us", rel_threshold=1.0, abs_floor=100_000.0),
    MetricSpec("staged_bytes", rel_threshold=0.25, abs_floor=1_000_000.0),
    MetricSpec("peak_memory_bytes", rel_threshold=0.5,
               abs_floor=16_000_000.0),
    # estimate-accuracy drift (exec/accuracy.py worst q-error per
    # query): a fingerprint whose estimates DEGRADE across runs --
    # stale connector stats, a data-dependent filter shifting -- fires
    # here before the misestimate is big enough to move latency. The
    # abs_floor is in q-error units: drift inside [1x, 3x] never gates
    # (the planner's UNKNOWN_FILTER_COEFFICIENT guesses live there).
    MetricSpec("max_q_error", rel_threshold=1.0, abs_floor=3.0),
)

# What the OFFLINE gate (scripts/perfgate.py) checks per BENCH
# artifact, against the committed PERF_BASELINE.json. The historical
# CPU-fallback artifacts swing ~8x run to run (shared CI hosts), which
# the MAD term absorbs automatically: a noisy metric measures its own
# band. staged_mb gates tight (0.1 rel) on purpose -- staged bytes are
# deterministic per (query, kernel mode), so ANY growth is a real
# re-widening, exactly the narrow-width win this repo must not lose
# silently.
BENCH_SPECS: Sequence[MetricSpec] = (
    MetricSpec("rows_per_sec", higher_is_worse=False,
               rel_threshold=0.6, abs_floor=0.0),
    MetricSpec("query_wall_s", rel_threshold=0.6, abs_floor=0.5),
    MetricSpec("staged_mb", rel_threshold=0.10, abs_floor=8.0,
               mad_k=3.0),
    # the concurrent-query throughput tier (scripts/loadgen.py
    # LOADGEN_r* artifacts): queries/sec regresses DOWN, tail latency
    # UP -- both on shared-CI noise, so the bands stay proportional
    MetricSpec("qps", higher_is_worse=False,
               rel_threshold=0.6, abs_floor=0.0),
    MetricSpec("p99_ms", rel_threshold=0.75, abs_floor=25.0),
    # the q1 staging rate (exec/datapath.py data-path waterfall; the
    # ROADMAP item-3 headline): host->HBM GB/s regresses DOWN. Keyed
    # (metric|platform) like every BENCH entry -- the CPU fallback and
    # a chip run never share a baseline. Its history starts EMPTY
    # (unbaselined is reported, not failed) and gates from the first
    # --update-baseline on.
    MetricSpec("staging_gb_per_s", higher_is_worse=False,
               rel_threshold=0.5, abs_floor=0.0),
    # per-query pool peak under the materialized executor with buffer
    # donation ON (bench.py donation smoke): the HBM-headroom number
    # proven-safe donation exists to shrink. Deterministic per (query,
    # kernel mode) like staged_mb, so the band is tight -- losing a
    # donation (a K006 proof that stops holding, an eligibility
    # regression) shows up as a step UP in this metric.
    MetricSpec("peak_memory_mb", rel_threshold=0.10, abs_floor=4.0,
               mad_k=3.0),
    # q1 host-staging/device-dispatch overlap fraction (exec/
    # timeline.py occupancy engine; bench.py timeline smoke): today's
    # strictly-serial pipeline measures ~0, which is the committed
    # baseline the ROADMAP item-1 async ingest must visibly RAISE --
    # so the metric regresses DOWN (higher_is_worse=False) and the
    # abs_floor keeps scheduler jitter around zero from tripping it.
    MetricSpec("overlap_fraction", higher_is_worse=False,
               rel_threshold=0.5, abs_floor=0.05),
)

# MAD -> sigma consistency constant for normally distributed noise
_MAD_SIGMA = 1.4826


def median(xs: Sequence[float]) -> float:
    """Plain median (no numpy: the comparator must import in stripped
    tooling environments, and n is tiny)."""
    s = sorted(float(x) for x in xs)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def mad(xs: Sequence[float], center: Optional[float] = None) -> float:
    """Median absolute deviation around `center` (default: median)."""
    if not xs:
        return 0.0
    c = median(xs) if center is None else float(center)
    return median([abs(float(x) - c) for x in xs])


def noise_band(samples: Sequence[float], spec: MetricSpec) -> float:
    """Half-width of the acceptance band around the baseline median:
    the widest of measured noise (k * 1.4826 * MAD), proportional
    drift tolerance, and the absolute floor."""
    med = median(samples)
    return max(spec.mad_k * _MAD_SIGMA * mad(samples, med),
               spec.rel_threshold * abs(med),
               spec.abs_floor)


def compare(value: float, samples: Sequence[float],
            spec: MetricSpec) -> Optional[dict]:
    """One sample vs a baseline sample set -> a breach verdict dict, or
    None when the sample sits inside the band (or regressed in the
    GOOD direction -- getting faster never pages). The verdict carries
    everything a report needs: the median it compared against, the band
    it escaped, and the ratio a human reads first."""
    if not samples:
        return None
    med = median(samples)
    band = noise_band(samples, spec)
    v = float(value)
    delta = (v - med) if spec.higher_is_worse else (med - v)
    if delta <= band:
        return None
    return {"metric": spec.name,
            "value": round(v, 6),
            "median": round(med, 6),
            "band": round(band, 6),
            "samples": len(samples),
            "ratio": round(v / med, 4) if med else 0.0,
            "direction": "above" if spec.higher_is_worse else "below"}


def compare_metrics(current: Dict[str, float],
                    baseline: Dict[str, Sequence[float]],
                    specs: Iterable[MetricSpec]) -> List[dict]:
    """Gate a metric vector against per-metric baseline sample sets.
    Metrics absent from either side are skipped (a new metric starts
    collecting, it does not fail the gate)."""
    out: List[dict] = []
    for spec in specs:
        if spec.name not in current:
            continue
        samples = baseline.get(spec.name) or ()
        verdict = compare(current[spec.name], samples, spec)
        if verdict is not None:
            out.append(verdict)
    return out


class RollingBaseline:
    """Per-key rolling baseline: the live sentinel's performance memory.

    Each key (a plan-cache fingerprint on the statement tier) retains
    the last ``window`` observations of each gated metric. ``observe``
    compares FIRST, then folds the sample in -- so a regressed run is
    judged against the history it is about to join, and a sustained
    regression re-baselines itself over the next ``window`` runs
    instead of alarming forever (drift acceptance, the same policy a
    ratcheted lint baseline encodes). Below ``min_samples`` the key is
    warming up and never breaches.

    Bounded two ways: ``window`` samples per (key, metric) and
    ``max_keys`` keys LRU'd on last observation, so an ad-hoc-query
    workload cannot grow it without bound. Not thread-safe by itself --
    the archive that owns it serializes access under its own lock.
    """

    def __init__(self, window: int = 32, min_samples: int = 5,
                 max_keys: int = 256,
                 specs: Sequence[MetricSpec] = SENTINEL_SPECS):
        assert window >= 1 and min_samples >= 1
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.max_keys = int(max_keys)
        self.specs = tuple(specs)
        self._keys: "OrderedDict[str, Dict[str, deque]]" = OrderedDict()

    def observe(self, key: str, metrics: Dict[str, float],
                gate: bool = True) -> List[dict]:
        """Compare `metrics` against the key's baseline (when `gate`),
        then absorb them. Returns the breach verdicts (empty while
        warming up, in-band, or with gating off)."""
        per = self._keys.get(key)
        if per is None:
            per = self._keys[key] = {}
            while len(self._keys) > self.max_keys:
                self._keys.popitem(last=False)
        else:
            self._keys.move_to_end(key)
        breaches: List[dict] = []
        for spec in self.specs:
            if spec.name not in metrics:
                continue
            samples = per.get(spec.name)
            if samples is None:
                samples = per[spec.name] = deque(maxlen=self.window)
            if gate and len(samples) >= self.min_samples:
                verdict = compare(metrics[spec.name], list(samples), spec)
                if verdict is not None:
                    breaches.append(verdict)
            samples.append(float(metrics[spec.name]))
        return breaches

    def samples_of(self, key: str) -> Dict[str, List[float]]:
        """Retained samples per metric (introspection / tests)."""
        per = self._keys.get(key) or {}
        return {m: list(s) for m, s in per.items()}

    def key_count(self) -> int:
        return len(self._keys)

    def warm(self, key: str, metrics: Dict[str, float]) -> None:
        """Absorb a sample WITHOUT comparing (archive reload at server
        start: history replayed from the JSONL ring must not re-fire
        the alarms it already fired when live)."""
        self.observe(key, metrics, gate=False)
