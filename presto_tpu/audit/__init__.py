"""kernaudit: jaxpr-level IR auditing of staged kernels.

tpulint (presto_tpu/lint/) guards the engine's contracts at the
Python-AST level; this package audits the same contracts where they
are finally true or false -- in the closed jaxpr XLA actually
compiles. A helper called through three layers of indirection can
widen a lane to int64 or smuggle a host callback into a staged
kernel without tripping any AST rule; it cannot hide from the IR.

The framework deliberately reuses tpulint's building blocks: findings
are ``lint.core.Finding`` objects (line-independent fingerprints), the
committed ratchet baseline is ``lint.baseline`` applied to
``kernaudit_baseline.json``, and per-site suppressions are source
comments (``# kernaudit: disable=K001``) resolved through each eqn's
provenance. See DESIGN.md ("Kernel IR auditing") for the pass catalog.
"""

from .core import (AuditPass, AuditResult, KernelIR, all_passes, get_pass,
                   register, run_audit)

__all__ = ["AuditPass", "AuditResult", "KernelIR", "all_passes",
           "get_pass", "register", "run_audit"]
