"""Staging-time kernel auditing: the exec/runner.py <-> kernaudit seam.

When the ``kernel_audit`` session property (env
``PRESTO_TPU_KERNEL_AUDIT``, registered in
``exec.plan_cache.KERNEL_MODE_ENVS``) is on, the runner calls
:func:`audit_staged_query` right after staging and before dispatch:
the plan's fused function is traced to a closed jaxpr over the staged
batches (one extra trace -- which is why the result is memoized by
(plan fingerprint, mesh, kernel mode, batch shapes) and the memo is
cleared together with the plan cache) and every registered IR pass
runs over it.

Findings are telemetry, never failures: they are counted into
QueryStats counters (``kernel_audit.K001`` ...), bumped on the
process-lifetime totals behind
``presto_tpu_kernel_audit_findings_total{pass=...}`` on both tiers'
``/v1/metrics``, recorded as one flight-recorder ``kernel_audit``
event, and the K005 peak estimate feeds the memory pool's accounting.
The gate that FAILS on findings is ``scripts/kernaudit.py`` over the
TPC-H corpus.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, Optional

__all__ = ["kernel_audit_enabled", "audit_staged_query",
           "kernel_audit_totals", "clear_audit_memo", "AUDIT_ENV"]

AUDIT_ENV = "PRESTO_TPU_KERNEL_AUDIT"

# -- process-lifetime totals (/v1/metrics, both tiers) -------------------

_TOTALS_LOCK = threading.Lock()
_FINDINGS_TOTAL: Dict[str, int] = {}   # pass code -> findings surfaced
_KERNELS_TOTAL = {"audited": 0}        # fresh traces (memo hits excluded)


def kernel_audit_totals() -> Dict[str, object]:
    with _TOTALS_LOCK:
        return {"findings": dict(_FINDINGS_TOTAL),
                "kernels": _KERNELS_TOTAL["audited"]}


# -- per-(plan, shapes, mode) memo: audit once per compiled program ------

_MEMO: "collections.OrderedDict[tuple, dict]" = collections.OrderedDict()
_MEMO_MAX = 128
_MEMO_LOCK = threading.Lock()


def clear_audit_memo() -> None:
    """Drop memoized audit reports (called by
    exec.plan_cache.clear_plan_cache so the two lifecycles stay in
    sync: a cleared executable cache means the next submission
    re-traces, and should re-audit)."""
    with _MEMO_LOCK:
        _MEMO.clear()


def kernel_audit_enabled(session) -> bool:
    """Session property ``kernel_audit``; process default from
    ``PRESTO_TPU_KERNEL_AUDIT`` (registered in KERNEL_MODE_ENVS)."""
    import os
    env_on = os.environ.get(AUDIT_ENV, "0") not in ("0", "", "false")
    from ..utils.config import session_flag
    return session_flag(session, "kernel_audit", env_on)


def _budget(session) -> int:
    from ..utils.config import session_value
    try:
        return int(session_value(session, "kernel_audit_budget_bytes", 0)
                   or 0)
    except (TypeError, ValueError):
        return 0


def audit_staged_query(plan, batches, *, mesh=None, query_id: str = "query",
                       session=None, collector=None, stats=None,
                       memory_pool=None,
                       plan_fp: Optional[str] = None) -> Optional[dict]:
    """Audit one staged query's fused program. Returns the report dict
    ``{findings: {code: n}, suppressed, peak_bytes_estimate, memo_hit}``
    or None when auditing failed (counted suppressed -- telemetry must
    never fail the query)."""
    try:
        report = _audit_report(plan, batches, mesh, query_id, session,
                               plan_fp)
    except Exception as e:  # noqa: BLE001 - observability never fails a query
        from ..server.metrics import record_suppressed
        record_suppressed("kernel_audit", "staged_trace", e)
        return None
    # surface the report on this query's telemetry even for memo hits:
    # QueryStats is per-query, the memo only skips the re-trace
    by_code = report["findings"]
    total = sum(by_code.values())
    with _TOTALS_LOCK:
        for code, n in by_code.items():
            _FINDINGS_TOTAL[code] = _FINDINGS_TOTAL.get(code, 0) + n
    if collector is not None:
        collector.note("kernel_audit_kernels")
        for code, n in sorted(by_code.items()):
            collector.note(f"kernel_audit.{code}", n)
        if report["peak_bytes_estimate"]:
            # QueryStats counters merge by SUMMATION across tasks, so
            # on the fragment tier this reads as the sum of per-
            # fragment peak estimates -- an upper bound on cluster-
            # wide audit footprint, not any one device's peak. The
            # max-law per-device peak rides note_audit_estimate below
            # into QueryStats.peak_memory_bytes (which merges by max).
            collector.note("kernel_audit_peak_bytes_estimate",
                           report["peak_bytes_estimate"])
    if stats is not None and total:
        stats.add("kernel_audit_findings", total)
    over_capacity = False
    if memory_pool is not None and report["peak_bytes_estimate"]:
        note = getattr(memory_pool, "note_audit_estimate", None)
        if note is not None:
            over_capacity = bool(note(query_id,
                                      report["peak_bytes_estimate"]))
            if over_capacity and collector is not None:
                # the estimate alone exceeds the WHOLE pool: this plan
                # cannot fit even an empty pool -- surface it on the
                # query's telemetry before execution proves it the
                # hard way
                collector.note("kernel_audit_over_pool_capacity")
    from ..server.flight_recorder import record_event
    record_event("kernel_audit", query_id=query_id, findings=total,
                 passes=",".join(f"{c}:{n}"
                                 for c, n in sorted(by_code.items())),
                 peak_bytes=report["peak_bytes_estimate"],
                 over_pool_capacity=over_capacity or None,
                 memo_hit=report["memo_hit"])
    return report


def _audit_report(plan, batches, mesh, query_id, session,
                  plan_fp) -> dict:
    from .core import KernelIR, run_audit
    if plan_fp is None:
        from ..exec.plan_cache import plan_fingerprint
        plan_fp = plan_fingerprint(plan.root)
    from ..exec.plan_cache import _kernel_mode, _mesh_key
    # the K005 budget is part of the key: the same program audited
    # under a different kernel_audit_budget_bytes must re-run the
    # passes, or a memo hit would serve the other budget's verdict.
    # Batch identity is the full leaf (shape, dtype) signature -- what
    # jit itself keys on: a staging-time range-guard widening (stale
    # stats after a write) changes lane dtypes WITHOUT changing the
    # plan fingerprint or capacities, and must re-audit
    import jax
    leaf_sig = tuple((tuple(l.shape), str(l.dtype))
                     for l in jax.tree_util.tree_leaves(tuple(batches)))
    key = (plan_fp, _mesh_key(mesh), _kernel_mode(), _budget(session),
           leaf_sig)
    with _MEMO_LOCK:
        hit = _MEMO.get(key)
        if hit is not None:
            _MEMO.move_to_end(key)
            return dict(hit, memo_hit=True)
    axes = tuple(mesh.axis_names) if mesh is not None else ()
    kernel = KernelIR.trace(plan.fn, (tuple(batches),), query_id,
                            exchange_axes=axes,
                            footprint_budget_bytes=_budget(session))
    result = run_audit([kernel])
    by_code: Dict[str, int] = {}
    for f in result.findings:
        by_code[f.code] = by_code.get(f.code, 0) + 1
    report = {"findings": by_code, "suppressed": result.suppressed,
              "peak_bytes_estimate":
                  kernel.notes.get("peak_bytes_estimate", 0),
              "memo_hit": False}
    with _TOTALS_LOCK:
        _KERNELS_TOTAL["audited"] += 1
    with _MEMO_LOCK:
        _MEMO[key] = report
        while len(_MEMO) > _MEMO_MAX:
            _MEMO.popitem(last=False)
    return report
