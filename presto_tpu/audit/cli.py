"""kernaudit CLI: the ratcheted TPC-H corpus gate over staged-kernel IR.

Same contract as tpulint's CLI (tests pin both):

  exit 0  clean -- no new findings, no stale baseline entries
  exit 1  new findings and/or stale baseline entries
  exit 2  internal error (a corpus query failed to stage, bad args,
          unreadable fixture, bad baseline)

``--json`` emits the same schema-v1 document shape as tpulint
(``filesScanned`` counts audited KERNELS); ``--format github`` emits
``::error`` annotations pointing at each finding's source site. The
baseline (``kernaudit_baseline.json``, committed EMPTY -- fix, don't
baseline) rides tpulint's ratchet machinery unchanged.

With no positional arguments the gate stages and audits the full
TPC-H q1-q22 corpus (``presto_tpu/queries/tpch_sql.py``) on both the
local tier and the mesh tier. Positional arguments are seeded-kernel
fixture modules (tests/fixtures/kernaudit/): python files exposing
``build() -> (fn, args)`` plus optional ``TRACE_AXES`` (mesh axes to
bind while tracing), ``MESH_AXES`` (the DECLARED exchange spec K004
checks against), and ``FOOTPRINT_BUDGET``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from ..lint.cli import emit_report, run_scoped_baseline
from ..lint.core import REPO
from .core import KernelIR, all_passes, run_audit

__all__ = ["main", "DEFAULT_BASELINE", "DEFAULT_FOOTPRINT_BUDGET"]

DEFAULT_BASELINE = os.path.join(REPO, "kernaudit_baseline.json")

# corpus-gate default for K005: generous enough that the sf=0.01
# corpus (peaks ~125MB) never trips it by noise, tight enough that a
# runaway intermediate (a quadratic blowup) fails the gate
DEFAULT_FOOTPRINT_BUDGET = 1 << 30


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kernaudit",
        description="presto-tpu jaxpr-level IR audit (TPC-H corpus gate)")
    p.add_argument("paths", nargs="*",
                   help="seeded-kernel fixture modules to audit "
                        "(default: stage + audit the TPC-H q1-q22 "
                        "corpus, local and mesh tiers)")
    p.add_argument("--select", metavar="CODES",
                   help="comma-separated pass codes (e.g. K001,K004)")
    p.add_argument("--queries", metavar="NUMS",
                   help="corpus subset, e.g. 1,5-7,22 (default: 1-22)")
    p.add_argument("--tier", choices=("both", "local", "mesh"),
                   default="both",
                   help="which corpus tiers to stage (default both)")
    p.add_argument("--sf", type=float, default=0.01,
                   help="corpus staging scale factor (default 0.01)")
    p.add_argument("--budget-bytes", type=int,
                   default=DEFAULT_FOOTPRINT_BUDGET,
                   help="K005 footprint budget per kernel "
                        f"(default {DEFAULT_FOOTPRINT_BUDGET})")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output (schema v1, same "
                        "shape as tpulint --json)")
    p.add_argument("--format", choices=("text", "github"), default="text",
                   help="finding rendering: human text (default) or "
                        "GitHub Actions ::error annotations; --json "
                        "takes precedence")
    p.add_argument("--baseline", metavar="PATH", default=None,
                   help=f"baseline file (default {DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignore the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to match current findings "
                        "(preserves reasons for surviving entries)")
    p.add_argument("--list-passes", action="store_true",
                   help="list registered IR passes and exit")
    return p


def _parse_queries(spec: Optional[str]) -> List[int]:
    from ..queries.tpch_sql import TPCH_QUERIES
    if not spec:
        return sorted(TPCH_QUERIES)
    out: List[int] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(part))
    unknown = [n for n in out if n not in TPCH_QUERIES]
    if unknown:
        raise ValueError(f"not in the corpus: {unknown}")
    if not out:
        # a reversed range ('7-5') or empty spec must not produce a
        # green gate that audited nothing
        raise ValueError(f"--queries {spec!r} selects no corpus queries")
    return sorted(set(out))


def _fixture_kernel(path: str, budget: int) -> KernelIR:
    """Load one fixture module and trace its kernel. Protocol:
    ``build() -> (fn, args)``; optional ``TRACE_AXES`` binds mesh axes
    (size-1 each) around the trace, ``MESH_AXES`` is the DECLARED
    exchange spec (defaults to TRACE_AXES), ``FOOTPRINT_BUDGET``
    overrides the K005 budget, ``DONATE_ARGNUMS`` requests buffer
    donation of those flat arg indices (K006 audits the request)."""
    import importlib.util

    abs_path = os.path.abspath(path)
    name = os.path.splitext(os.path.basename(abs_path))[0]
    spec = importlib.util.spec_from_file_location(f"_kernaudit_{name}",
                                                 abs_path)
    if spec is None or spec.loader is None:
        raise OSError(f"cannot load fixture module {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.build()
    trace_axes = tuple(getattr(mod, "TRACE_AXES", ()))
    declared = getattr(mod, "MESH_AXES", None)
    declared = tuple(trace_axes if declared is None else declared)
    budget = int(getattr(mod, "FOOTPRINT_BUDGET", budget))
    label = name
    try:  # repo-relative labels only for files actually INSIDE the
        # repo (a string-prefix test would misclassify siblings like
        # /root/repo-backup); same check as KernelIR.site()
        if os.path.commonpath([abs_path, REPO]) == REPO:
            label = os.path.relpath(abs_path, REPO).replace(os.sep, "/")
    except ValueError:
        pass

    if trace_axes:
        import numpy as np
        import jax
        from jax.sharding import Mesh
        from jax.sharding import PartitionSpec as P
        devs = np.array(jax.devices()[:1]).reshape((1,) * len(trace_axes))
        mesh = Mesh(devs, trace_axes)
        traced = jax.shard_map(
            fn, mesh=mesh, in_specs=tuple(P() for _ in args),
            out_specs=P(), check_vma=False)
    else:
        traced = fn
    kernel = KernelIR.trace(traced, args, label, exchange_axes=declared,
                            footprint_budget_bytes=budget)
    donate = getattr(mod, "DONATE_ARGNUMS", None)
    if donate is not None:
        kernel.notes["donation_requested"] = tuple(
            int(i) for i in donate)
    return kernel


def _corpus_kernels(qnums: List[int], sf: float, tier: str,
                    budget: int) -> List[KernelIR]:
    from ..parallel.mesh import make_mesh
    from ..queries.tpch_sql import stage_tpch

    kernels: List[KernelIR] = []
    meshes = []
    if tier in ("both", "local"):
        meshes.append(None)
    if tier in ("both", "mesh"):
        meshes.append(make_mesh(1))
    for mesh in meshes:
        for n in qnums:
            staged = stage_tpch(n, sf=sf, mesh=mesh)
            axes = tuple(mesh.axis_names) if mesh is not None else ()
            kernels.append(KernelIR.trace(
                staged.fn, (staged.batches,), staged.label,
                exchange_axes=axes, footprint_budget_bytes=budget))
    return kernels


def main(argv: Optional[List[str]] = None) -> int:
    args = _parser().parse_args(argv)

    if args.list_passes:
        for p in all_passes():
            print(f"{p.code}  {p.name:22s} {p.description}")
        return 0

    codes = None
    if args.select:
        codes = [c.strip().upper() for c in args.select.split(",")
                 if c.strip()]
        known = {p.code for p in all_passes()}
        unknown = [c for c in codes if c not in known]
        if unknown:
            print(f"kernaudit: unknown pass code(s): "
                  f"{', '.join(unknown)} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2

    try:
        if args.paths:
            kernels = [_fixture_kernel(p, args.budget_bytes)
                       for p in args.paths]
        else:
            qnums = _parse_queries(args.queries)
            kernels = _corpus_kernels(qnums, args.sf, args.tier,
                                      args.budget_bytes)
    except Exception as e:  # staging/tracing failures are ERRORS, not
        # clean runs -- a corpus query that stops planning must turn
        # the gate red-2, never silently green
        print(f"kernaudit: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    result = run_audit(kernels, codes=codes)

    # partial runs (fixtures / --select / --queries / single tier) only
    # ratchet baseline entries whose (pass, kernel) was actually
    # audited -- the same scoped-staleness rule as tpulint's CLI
    partial = bool(args.paths) or bool(args.select) or \
        bool(args.queries) or args.tier != "both"

    def in_scope(entry: dict) -> bool:
        if not partial:
            return True
        return entry.get("code") in result.pass_codes and \
            entry.get("path") in result.kernels

    baselined = 0
    stale: List[dict] = []
    new = result.findings
    if not args.no_baseline:
        try:
            new, baselined, stale = run_scoped_baseline(
                result.findings, args.baseline or DEFAULT_BASELINE,
                args.update_baseline, partial, in_scope)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"kernaudit: bad baseline: {e}", file=sys.stderr)
            return 2

    def github_site(f):
        # whole-kernel findings (K005) carry no source site: anchor
        # them on the gate's entry point -- GitHub drops annotations
        # whose file doesn't exist or whose line is 0
        return (getattr(f, "src_path", "") or "scripts/kernaudit.py",
                max(f.line, 1))

    emit_report(new, stale, baselined=baselined,
                suppressed=result.suppressed,
                pass_codes=result.pass_codes,
                unit_count=result.kernels_audited, unit_noun="kernel",
                as_json=args.as_json, fmt=args.format, tool="kernaudit",
                github_site=github_site,
                github_title=lambda f: f"kernaudit {f.code} [{f.path}]",
                stale_github_file=lambda s: "kernaudit_baseline.json")
    return 1 if (new or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
