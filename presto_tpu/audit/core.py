"""kernaudit core: KernelIR (a walkable, provenance-aware closed
jaxpr), the audit-pass registry, and the run engine.

The contract mirrors ``lint/core.py`` one level down the stack:

  * ``KernelIR`` -- one staged kernel: the closed jaxpr traced from a
    plan's fused function (or a fixture), a stable label
    (``tpch/q01``, a query id), the exchange-axis spec the kernel is
    ALLOWED to communicate over (from ``parallel/stages.py``'s mesh
    wiring -- empty for single-chip kernels), and a footprint budget.
    It owns recursive eqn iteration (descending into pjit / scan /
    cond / shard_map sub-jaxprs) and eqn provenance: each eqn maps
    back through ``source_info`` to a repo file, line, and dotted
    enclosing-function context, which is what makes findings
    fingerprintable, whitelistable, and suppressible exactly like
    tpulint's.
  * ``AuditPass`` -- subclass per IR rule (K001...), registered with
    ``@register``; ``presto_tpu.audit.passes`` imports every pass
    module so importing the package populates the registry (the same
    loading scheme as the lint registry, kept separate so pass codes
    and CLI selection cannot collide).
  * ``run_audit`` -- map selected passes over kernels, drop findings
    whose provenance line carries ``# kernaudit: disable=CODE``,
    return an ``AuditResult``.

Findings reuse ``lint.core.Finding`` (same fingerprint law, so
``lint/baseline.py`` applies unchanged to ``kernaudit_baseline.json``):
``path`` is the KERNEL label (the corpus gate's stable unit), ``line``/
``col`` point at the source site the eqn traces to, ``context`` is the
dotted enclosing function there, and the message names the source file
(line-independent) so fingerprints survive edits above a site.
"""

from __future__ import annotations

import ast
import dataclasses
import functools
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..lint.core import REPO, Finding

__all__ = ["KernelIR", "IRFinding", "AuditPass", "register", "all_passes",
           "get_pass", "AuditResult", "run_audit", "eqn_subjaxprs",
           "CALL_PRIMITIVES"]

_SUPPRESS_RE = re.compile(
    r"#\s*kernaudit:\s*disable=([A-Za-z0-9_,\s]+|all)")

# call-like primitives own sub-jaxprs; dtype rules skip the call eqn
# itself (a pjit whose OUTPUT is int64 is not a widening site -- the
# creation happens inside and is audited there)
CALL_PRIMITIVES = frozenset([
    "pjit", "xla_call", "closed_call", "core_call", "shard_map", "scan",
    "while", "cond", "custom_jvp_call", "custom_vjp_call",
    "custom_vjp_call_jaxpr", "remat", "remat2", "checkpoint", "named_call",
])


@dataclasses.dataclass(frozen=True)
class IRFinding(Finding):
    """A lint Finding plus the source file its eqn traces to --
    ``src_path`` feeds ``--format github`` annotations; it is NOT part
    of the fingerprint or the ``--json`` schema (both stay identical to
    tpulint's)."""

    src_path: str = ""


def eqn_subjaxprs(eqn):
    """Sub-jaxprs owned by one eqn (pjit/scan/cond/shard_map/...),
    normalized to open ``Jaxpr`` objects."""

    def norm(v):
        if hasattr(v, "jaxpr"):  # ClosedJaxpr
            return v.jaxpr
        if hasattr(v, "eqns"):   # already an open Jaxpr
            return v
        return None

    for v in eqn.params.values():
        j = norm(v)
        if j is not None:
            yield j
        elif isinstance(v, (list, tuple)):
            for x in v:
                j = norm(x)
                if j is not None:
                    yield j


@functools.lru_cache(maxsize=256)
def _def_spans(abs_path: str) -> Tuple[Tuple[int, int, Tuple[str, ...]], ...]:
    """(start, end, def-name stack) for every function/class in a
    source file -- provenance lines resolve to dotted contexts the same
    way lint passes compute theirs from the AST."""
    try:
        with open(abs_path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=abs_path)
    except (OSError, SyntaxError, ValueError):
        return ()
    spans: List[Tuple[int, int, Tuple[str, ...]]] = []

    def walk(node, stack):
        for ch in ast.iter_child_nodes(node):
            if isinstance(ch, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                st = stack + (ch.name,)
                end = getattr(ch, "end_lineno", ch.lineno) or ch.lineno
                spans.append((ch.lineno, max(end, ch.lineno), st))
                walk(ch, st)
            else:
                walk(ch, stack)

    walk(tree, ())
    return tuple(spans)


@functools.lru_cache(maxsize=256)
def _suppressions(abs_path: str) -> Dict[int, frozenset]:
    """{line: codes} of ``# kernaudit: disable=...`` comments in a
    source file (the IR-level analog of lint's inline suppressions:
    the comment sits on the source line the eqn traces back to)."""
    out: Dict[int, frozenset] = {}
    try:
        with open(abs_path, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return out
    for i, line in enumerate(lines, start=1):
        if "kernaudit" not in line:
            continue
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = frozenset(
                c.strip() for c in m.group(1).split(",") if c.strip())
    return out


def _user_frame(eqn):
    """The first non-jax frame of an eqn's traceback, or None (e.g.
    jaxprs built programmatically)."""
    try:
        from jax._src import source_info_util
        return source_info_util.user_frame(eqn.source_info)
    except Exception:  # pragma: no cover - jax internals moved
        return None


class KernelIR:
    """One staged kernel under audit: closed jaxpr + metadata."""

    def __init__(self, closed, label: str, *,
                 exchange_axes: Iterable[str] = (),
                 footprint_budget_bytes: int = 0,
                 repo: str = REPO):
        self.closed = closed
        self.jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
        self.label = label
        # axis names this kernel is SANCTIONED to run collectives over
        # (the mesh/stage spec); empty = single-chip kernel, where any
        # collective is a finding
        self.exchange_axes = frozenset(exchange_axes)
        self.footprint_budget_bytes = int(footprint_budget_bytes)
        self.repo = repo
        # pass-computed observations (K005 peak estimate, ...) the
        # staging hook forwards into QueryStats / the memory pool
        self.notes: Dict[str, int] = {}

    @classmethod
    def trace(cls, fn, args: Sequence, label: str, **kw) -> "KernelIR":
        """Trace ``fn(*args)`` to a closed jaxpr (no execution)."""
        import jax
        return cls(jax.make_jaxpr(fn)(*args), label, **kw)

    # -- IR iteration ---------------------------------------------------

    def eqns(self):
        """Yield ``(owner_jaxpr, eqn)`` over the whole program,
        descending into every sub-jaxpr."""

        def walk(jx):
            for e in jx.eqns:
                yield jx, e
                for s in eqn_subjaxprs(e):
                    yield from walk(s)

        yield from walk(self.jaxpr)

    # -- provenance -----------------------------------------------------

    def site(self, eqn) -> Tuple[str, str, int]:
        """(source path, dotted context, line) of an eqn. The path is
        repo-relative when the frame lies inside the repo; context is
        the last two def-stack segments (lint's ``dotted_context``
        rendering) or ``<module>``."""
        frame = _user_frame(eqn)
        if frame is None:
            return "", "<unknown>", 0
        abs_path = frame.file_name
        line = int(frame.start_line or 0)
        best: Optional[Tuple[str, ...]] = None
        for lo, hi, stack in _def_spans(abs_path):
            if lo <= line <= hi and (best is None or len(stack) > len(best)):
                best = stack
        context = ".".join(best[-2:]) if best else "<module>"
        rel = abs_path
        try:
            if os.path.commonpath([abs_path, self.repo]) == self.repo:
                rel = os.path.relpath(abs_path, self.repo).replace(
                    os.sep, "/")
        except ValueError:
            pass
        return rel, context, line

    def site_stack(self, eqn) -> Tuple[str, ...]:
        """Full def-name stack at an eqn's source line (whitelists can
        match the top-level function the way W001's do)."""
        frame = _user_frame(eqn)
        if frame is None:
            return ()
        line = int(frame.start_line or 0)
        best: Tuple[str, ...] = ()
        for lo, hi, stack in _def_spans(frame.file_name):
            if lo <= line <= hi and len(stack) > len(best):
                best = stack
        return best

    def suppressed(self, finding: "IRFinding") -> bool:
        """True when the source line a finding traces to carries a
        ``# kernaudit: disable=<code>`` comment (engine-applied, like
        lint's per-line suppressions)."""
        if not finding.src_path or not finding.line:
            return False
        abs_path = finding.src_path if os.path.isabs(finding.src_path) \
            else os.path.join(self.repo, finding.src_path)
        codes = _suppressions(abs_path).get(finding.line)
        return bool(codes) and (finding.code in codes or "all" in codes)

    # -- finding construction -------------------------------------------

    def finding(self, code: str, eqn, message: str) -> IRFinding:
        """Build a finding anchored at the eqn's provenance. The source
        FILE rides in the message (line-independent, so the fingerprint
        pins code|kernel|context|site-file|claim); the line/col locate
        it for humans and ``--format github``."""
        src, context, line = self.site(eqn)
        if src:
            message = f"{message} [at {src}]"
        return IRFinding(code=code, path=self.label, line=line, col=0,
                         context=context, message=message, src_path=src)

    def kernel_finding(self, code: str, message: str) -> IRFinding:
        """A whole-kernel finding (no single source site -- K005)."""
        return IRFinding(code=code, path=self.label, line=0, col=0,
                         context="<kernel>", message=message, src_path="")


class AuditPass:
    """Base class for IR passes: subclass, set the class attributes,
    implement ``run(kernel) -> [Finding]``. Inline suppression is the
    engine's job -- passes just report."""

    code: str = "K000"
    name: str = "unnamed"
    description: str = ""

    def run(self, kernel: KernelIR) -> List[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, AuditPass] = {}


def register(cls):
    """Class decorator: instantiate and index the pass by its code
    (separate registry from the lint one -- AST and IR passes are
    selected by different CLIs and must not collide)."""
    inst = cls()
    assert inst.code not in _REGISTRY or \
        type(_REGISTRY[inst.code]) is cls, \
        f"duplicate audit pass code {inst.code}"
    _REGISTRY[inst.code] = inst
    return cls


def all_passes() -> List[AuditPass]:
    _load_builtin_passes()
    return [_REGISTRY[c] for c in sorted(_REGISTRY)]


def get_pass(code: str) -> AuditPass:
    _load_builtin_passes()
    return _REGISTRY[code]


def _load_builtin_passes() -> None:
    from . import passes  # noqa: F401


@dataclasses.dataclass
class AuditResult:
    findings: List[Finding]
    suppressed: int
    kernels: List[str]        # labels actually audited
    pass_codes: List[str]

    @property
    def kernels_audited(self) -> int:
        return len(self.kernels)


def run_audit(kernels: Sequence[KernelIR],
              codes: Optional[Iterable[str]] = None) -> AuditResult:
    """Run the selected IR passes (all registered, by default) over the
    given kernels. Source-comment suppressions are applied here;
    baselining is the caller's concern (lint/baseline.py)."""
    _load_builtin_passes()
    selected = [get_pass(c) for c in sorted(codes)] if codes else \
        all_passes()
    findings: List[Finding] = []
    suppressed = 0
    labels: List[str] = []
    for k in kernels:
        labels.append(k.label)
        for p in selected:
            for f in p.run(k):
                if isinstance(f, IRFinding) and k.suppressed(f):
                    suppressed += 1
                else:
                    findings.append(f)
    findings.sort(key=Finding.sort_key)
    return AuditResult(findings=findings, suppressed=suppressed,
                       kernels=labels,
                       pass_codes=[p.code for p in selected])
