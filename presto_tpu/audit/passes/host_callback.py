"""K002: host round-trips inside a staged kernel.

The engine's whole performance model rests on one fused XLA program
per fragment with no host involvement between staging and fetch
(Flare's native-compilation argument). A ``pure_callback`` /
``io_callback`` / ``debug_callback`` eqn -- or a mid-program
``device_put`` -- re-introduces exactly the device->host->device
round-trip fusion exists to eliminate, and serializes every batch on
it. tpulint's H001 catches the obvious AST spellings; this pass
catches whatever actually survived into the IR, however it got there.
"""

from __future__ import annotations

from typing import List

from ..core import AuditPass, KernelIR, register

__all__ = ["HostCallbackPass", "HOST_PRIMITIVES"]

HOST_PRIMITIVES = frozenset([
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_callback_call", "outside_call", "device_put", "infeed",
    "outfeed",
])

_DETAIL = {
    "device_put": "a mid-program transfer splits the fused program at "
                  "the host boundary",
    "infeed": "host infeed stalls the program on the host queue",
    "outfeed": "host outfeed stalls the program on the host queue",
}


@register
class HostCallbackPass(AuditPass):
    code = "K002"
    name = "host-round-trip"
    description = ("pure_callback/io_callback/debug_callback/device_put "
                   "eqns inside a staged kernel (host round-trips that "
                   "split the fused program)")

    def run(self, kernel: KernelIR) -> List:
        findings = []
        for _jx, eqn in kernel.eqns():
            prim = str(eqn.primitive)
            if prim not in HOST_PRIMITIVES:
                continue
            detail = _DETAIL.get(
                prim, "the device waits on a host round-trip on every "
                      "batch")
            findings.append(kernel.finding(
                "K002", eqn,
                f"`{prim}` eqn inside a staged kernel -- {detail}"))
        return findings
