"""K004: collective audit -- every collective runs over a declared
mesh axis, from inside an exchange boundary.

Stage boundaries are the ONLY place this engine communicates: the
planner lowers REMOTE exchanges to collectives via parallel/exchange.py
and parallel/stages.py, gang-scheduled by XLA (stages.py module doc).
A psum/all_gather/ppermute anywhere else -- an ops/ kernel "helpfully"
reducing across workers, or an axis name that is not part of the
kernel's mesh spec -- breaks the SPMD contract in ways that show up as
wrong results or deadlocks only at multi-chip scale, where they are
expensive to debug. The audit checks both properties at trace time:

  * every collective's axis must be in the kernel's declared exchange
    spec (``KernelIR.exchange_axes``, from the mesh the plan compiled
    against; empty for single-chip kernels, where any collective is a
    finding);
  * the collective's provenance must lie in a sanctioned exchange
    module (the planner's lowering or the parallel/ package) --
    "collectives outside exchange boundaries" are findings even on the
    right axis.
"""

from __future__ import annotations

from typing import List, Set

from ..core import AuditPass, KernelIR, register

__all__ = ["CollectiveAuditPass", "COLLECTIVE_PRIMITIVES",
           "EXCHANGE_BOUNDARY_FILES"]

COLLECTIVE_PRIMITIVES = frozenset([
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "ppermute", "pbroadcast", "reduce_scatter", "psum_scatter",
    "pgather", "pshuffle",
])

# modules sanctioned to lower collectives: the exchange layer, the
# stage compositions over it, the mesh plumbing, and the planner's
# exchange/overflow lowering
EXCHANGE_BOUNDARY_FILES: Set[str] = {
    "exchange.py", "stages.py", "mesh.py", "planner.py",
    "tpch_queries.py",  # hand-assembled benchmark pipelines
}


def _axis_names(eqn) -> List[str]:
    for key in ("axes", "axis_name", "axis"):
        v = eqn.params.get(key)
        if v is None:
            continue
        if isinstance(v, (tuple, list)):
            return [str(a) for a in v]
        return [str(v)]
    return []


@register
class CollectiveAuditPass(AuditPass):
    code = "K004"
    name = "collective-audit"
    description = ("collectives checked against the kernel's mesh/stage "
                   "spec: undeclared axis names and collectives outside "
                   "the exchange boundary are findings")

    def run(self, kernel: KernelIR) -> List:
        findings = []
        spec = kernel.exchange_axes
        for _jx, eqn in kernel.eqns():
            prim = str(eqn.primitive)
            if prim not in COLLECTIVE_PRIMITIVES:
                continue
            axes = _axis_names(eqn)
            bad_axes = [a for a in axes if a not in spec]
            if not spec:
                findings.append(kernel.finding(
                    "K004", eqn,
                    f"`{prim}` over axis {axes or '?'} in a single-chip "
                    f"kernel (no exchange spec) -- this program must "
                    f"not communicate"))
                continue
            if bad_axes:
                findings.append(kernel.finding(
                    "K004", eqn,
                    f"`{prim}` over undeclared axis "
                    f"{sorted(bad_axes)} -- the kernel's exchange spec "
                    f"is {sorted(spec)} (parallel/stages.py mesh "
                    f"wiring); an unknown axis deadlocks or silently "
                    f"no-ops at scale"))
                continue
            src, _ctx, _line = kernel.site(eqn)
            base = src.rsplit("/", 1)[-1]
            if base not in EXCHANGE_BOUNDARY_FILES:
                findings.append(kernel.finding(
                    "K004", eqn,
                    f"`{prim}` over {axes} outside the exchange "
                    f"boundary (parallel/exchange.py, parallel/"
                    f"stages.py, plan lowering) -- stage boundaries are "
                    f"the only sanctioned communication points"))
        return findings
