"""K006 donation safety + K007 baked-constant bloat.

Buffer donation (``jax.jit(..., donate_argnums=...)``) lets XLA reuse
an input's HBM for an output, cutting a fused region's peak residency
by up to the donated bytes -- but a donation that XLA cannot honor is
silently copied (the saving evaporates) and a donation the ENGINE
cannot honor (the host still holds a live reference to the batch) is
a use-after-free. The proof obligation splits in two:

  * IR side (K006, here): the donated input must be aliasable AT ALL
    -- it must not be returned unchanged (a passthrough output IS the
    input buffer; nothing can be aliased into it), and some output
    must carry the identical shape+dtype so XLA has a slot to alias it
    into. Greedy first-fit matching over the top-level jaxpr's
    flattened invars/outvars (the order ``jax.tree_util.tree_leaves``
    produces, which is what ``exec/donation.py`` flattens at dispatch
    time).
  * engine side (exec/donation.py + exec/runner.py): the staged batch
    must be dead after dispatch -- reference counting over the plan's
    region wiring, NOT an IR property.

K006 ALWAYS writes the machine-readable plan to
``kernel.notes["donation_plan"]`` (the exec tier's feed) and only
REPORTS when a donation was requested (``kernel.notes
["donation_requested"]``, e.g. a fixture's ``DONATE_ARGNUMS``) that
the proof cannot back -- a requested-but-unprovable donation is the
bug class; an undonated kernel is merely unoptimized.

K007 flags large arrays captured as jaxpr CONSTANTS instead of
arguments: every compiled variant (and the plan cache keeps one per
batch shape / kernel-mode key) bakes its own HBM copy, invisible to
the memory pool's accounting. Constants are how weights leak into
query kernels -- TPC-H lowering passes every relation as an argument,
so any large const in the corpus is a planner bug.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..core import AuditPass, KernelIR, register
from .footprint import _aval_bytes

__all__ = ["DonationSafetyPass", "BakedConstPass", "donation_plan",
           "K007_CONST_BYTES"]

# a baked constant smaller than this is a literal table (format
# strings, month lengths, ...) -- flagging those would be noise
K007_CONST_BYTES = 1 << 20


def _shape_dtype(v) -> Tuple[Optional[tuple], Optional[str]]:
    a = getattr(v, "aval", None)
    shape = getattr(a, "shape", None)
    dt = getattr(a, "dtype", None)
    return (tuple(shape) if shape is not None else None,
            str(dt) if dt is not None else None)


def donation_plan(jaxpr) -> Dict[str, list]:
    """Prove which top-level invars are safely donatable: not a
    passthrough output, and shape+dtype-identical to some output that
    is not itself a passthrough. Greedy first-fit; indices are FLAT
    leaf positions (``jax.tree_util.tree_leaves`` order)."""
    invar_ids = {id(v) for v in jaxpr.invars}
    consumed = {id(v) for _e in jaxpr.eqns for v in _e.invars}
    # eligible alias targets: outputs that are NOT passthrough inputs
    # (a passthrough output's buffer IS its input's -- nothing else
    # can be aliased into it)
    targets: List[Tuple[int, tuple, str]] = []
    for j, ov in enumerate(jaxpr.outvars):
        if id(ov) in invar_ids:
            continue
        shape, dt = _shape_dtype(ov)
        if shape is None or dt is None:
            continue
        targets.append((j, shape, dt))
    out_ids = {id(v) for v in jaxpr.outvars}
    donatable: List[dict] = []
    rejected: List[dict] = []
    claimed: set = set()
    for i, iv in enumerate(jaxpr.invars):
        shape, dt = _shape_dtype(iv)
        if shape is None or dt is None:
            rejected.append({"arg": i, "reason": "abstract input"})
            continue
        if id(iv) in out_ids:
            rejected.append({"arg": i,
                             "reason": "returned unchanged (passthrough "
                                       "output is the input buffer)"})
            continue
        if id(iv) not in consumed:
            rejected.append({"arg": i,
                             "reason": "never consumed (nothing to "
                                       "alias it into)"})
            continue
        match = next((j for j, s, d in targets
                      if j not in claimed and s == shape and d == dt),
                     None)
        if match is None:
            rejected.append({"arg": i,
                             "reason": f"no unclaimed output with shape "
                                       f"{shape} dtype {dt}"})
            continue
        claimed.add(match)
        donatable.append({"arg": i, "out": match,
                          "bytes": _aval_bytes(iv),
                          "shape": list(shape), "dtype": dt})
    return {"version": 1, "donatable": donatable, "rejected": rejected}


@register
class DonationSafetyPass(AuditPass):
    code = "K006"
    name = "donation-safety"
    description = ("prove which jit inputs are aliasable into an "
                   "output (donation plan in kernel notes); requested "
                   "donations the proof cannot back are findings")

    def run(self, kernel: KernelIR) -> List:
        plan = donation_plan(kernel.jaxpr)
        kernel.notes["donation_plan"] = plan
        requested = kernel.notes.get("donation_requested")
        if not requested:
            return []
        proven = {d["arg"] for d in plan["donatable"]}
        reasons = {r["arg"]: r["reason"] for r in plan["rejected"]}
        findings = []
        for i in requested:
            i = int(i)
            if i in proven:
                continue
            if 0 <= i < len(kernel.jaxpr.invars):
                shape, dt = _shape_dtype(kernel.jaxpr.invars[i])
                what = f"arg {i} ({dt}{list(shape or ())})"
                why = reasons.get(i, "not a provable alias")
            else:
                what = f"arg {i}"
                why = (f"index out of range (kernel takes "
                       f"{len(kernel.jaxpr.invars)} flat inputs)")
            findings.append(kernel.kernel_finding(
                "K006",
                f"requested donation of {what} is not provably safe: "
                f"{why} -- XLA would silently copy (or worse, the "
                f"engine would free a live buffer); drop it from "
                f"donate_argnums or restructure the kernel"))
        return findings


@register
class BakedConstPass(AuditPass):
    code = "K007"
    name = "baked-constant-bloat"
    description = ("large arrays captured as jaxpr constants instead "
                   "of arguments (silent HBM duplication per compiled "
                   "variant, invisible to pool accounting)")

    def run(self, kernel: KernelIR) -> List:
        findings = []
        total = 0
        for cv in kernel.jaxpr.constvars:
            nbytes = _aval_bytes(cv)
            total += nbytes
            if nbytes < K007_CONST_BYTES:
                continue
            shape, dt = _shape_dtype(cv)
            findings.append(kernel.kernel_finding(
                "K007",
                f"kernel bakes a {dt}{list(shape or ())} constant "
                f"({nbytes} bytes) into the compiled program -- every "
                f"compiled variant duplicates it in HBM outside pool "
                f"accounting; pass it as an argument (or shrink it "
                f"below {K007_CONST_BYTES} bytes)"))
        kernel.notes["baked_const_bytes"] = total
        return findings
