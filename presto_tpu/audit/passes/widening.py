"""K003: widening chains -- up-cast-then-down-cast sequences.

A ``convert_element_type`` to a wider dtype whose ONLY consumers
convert straight back down to (at most) the original width moved every
element through wide lanes for nothing: on v5e an int64 intermediate
is an emulated i32 pair, so the chain doubles the HBM traffic of the
values it touches and produces bits the program immediately throws
away. These chains are invisible to AST linting (each cast looks
individually reasonable -- typically a helper widening "to be safe"
feeding a caller that narrows) and only appear once the helpers
inline into one jaxpr.

The check is per-jaxpr-level (a var's consumers live in its owning
jaxpr); call-like consumers (pjit/scan/...) conservatively exempt the
chain, since the sub-jaxpr may use the wide bits.
"""

from __future__ import annotations

from typing import List

from ..core import AuditPass, KernelIR, register

__all__ = ["WideningChainPass"]


def _dtype(v):
    return getattr(getattr(v, "aval", None), "dtype", None)


@register
class WideningChainPass(AuditPass):
    code = "K003"
    name = "widening-chain"
    description = ("convert_element_type up-casts whose only consumers "
                   "immediately down-cast (wide HBM round-trips for "
                   "bits the program discards)")

    def run(self, kernel: KernelIR) -> List:
        findings = []
        # one var -> consumers map per jaxpr level (keyed by identity):
        # rescanning jx.eqns per up-cast would be quadratic on fused
        # TPC-H programs with thousands of eqns
        consumer_maps: dict = {}

        def consumers_of(jx, var):
            m = consumer_maps.get(id(jx))
            if m is None:
                m = {}
                for c in jx.eqns:
                    for v in c.invars:
                        m.setdefault(id(v), []).append(c)
                consumer_maps[id(jx)] = m
            return m.get(id(var), ())

        for jx, eqn in kernel.eqns():
            if str(eqn.primitive) != "convert_element_type":
                continue
            src = _dtype(eqn.invars[0])
            dst = _dtype(eqn.outvars[0])
            if src is None or dst is None or \
                    dst.itemsize <= src.itemsize:
                continue  # not an up-cast
            out = eqn.outvars[0]
            # consumers within the owning jaxpr (incl. being an output)
            if any(v is out for v in jx.outvars):
                continue
            consumers = consumers_of(jx, out)
            if not consumers:
                continue
            chain = all(
                str(c.primitive) == "convert_element_type"
                and _dtype(c.outvars[0]) is not None
                and _dtype(c.outvars[0]).itemsize <= src.itemsize
                for c in consumers)
            if not chain:
                continue
            downs = ", ".join(sorted({str(_dtype(c.outvars[0]))
                                      for c in consumers}))
            findings.append(kernel.finding(
                "K003", eqn,
                f"widening chain: {src} up-cast to {dst} is only ever "
                f"down-cast again (to {downs}) -- the wide intermediate "
                f"wastes HBM traffic narrow-width execution saved; "
                f"compute in {src} or fuse the casts"))
        return findings
