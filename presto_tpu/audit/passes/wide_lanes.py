"""K001: wide-lane escapes -- 64-bit avals CREATED from all-narrow
inputs, outside the sanctioned widening kernels.

This is the IR-level ground truth behind tpulint's W001: after
narrow-width execution stages every range-proven column at int32 or
less, any eqn that manufactures an int64/uint64/float64 output from
inputs that are ALL narrower is a lane someone widened -- either a
sanctioned exactness site (int128 limb math, 64-bit key/order words,
count accumulators) or an escape that doubles HBM traffic on v5e.

Wide-in/wide-out eqns are deliberately NOT findings: wideness entering
the program through staged inputs (int128 hi/lo columns, BIGINT lanes
the width-inference layer could not narrow) was sanctioned at staging
time by the planner's range guard, and limb math flowing those lanes
through is the exactness contract, not an escape. The pass looks for
the moment narrow data turns wide IN-IR.

The whitelist mirrors W001's ``WIDE_OK_FUNCS`` (same spirit, same
granularity: enclosing function, matched against the eqn's provenance)
extended with the sites only visible at IR level: the decimal compare/
rescale helpers in expr/functions.py widen narrowed lanes before exact
scaled-int64 arithmetic, and the planner's row-id/grouping-id iotas
are logical BIGINT columns.
"""

from __future__ import annotations

from typing import Dict, List, Set

from ..core import CALL_PRIMITIVES, AuditPass, KernelIR, register

__all__ = ["WideLaneEscapePass", "WIDE_OK_FILES", "WIDE_OK_SITES"]

# whole files where 64-bit lanes ARE the contract: the int128 limb
# kernels (every value is an (int64 hi, uint64 lo) pair by definition)
WIDE_OK_FILES: Set[str] = {"int128.py"}

# (basename -> enclosing functions) sanctioned to create 64-bit lanes
# from narrow inputs; matched against the eqn's full def stack like
# W001 matches its AST stack
WIDE_OK_SITES: Dict[str, Set[str]] = {
    # exact accumulators / packed order words (W001's whitelist, seen
    # from the IR side)
    "aggregation.py": {
        "_fused_limb_sums", "_limb_matmul_sum", "_seg_add", "_seg_count",
        "_sum128", "_SegSumPool.add", "_seg_total", "_padded_cumsum",
        "_acc_columns", "_sorted_states", "finalize_states",
        "finalize_variance", "hll_estimate", "_group_by_sorted",
        "_argbest", "_hll_registers_from_values", "_seg_scan_extreme",
        "_seg_extreme_at", "group_by", "merge_partials",
    },
    "keys.py": {"_fixed_words", "key_words", "_string_words"},
    "join.py": {"_pack_ranks", "hash_join", "semi_join_mask"},
    "window.py": {"window", "_seg_search", "_range_extreme"},
    # decimal comparison/arithmetic widens narrowed lanes to the exact
    # scaled-int64 (or int128 limb) domain before comparing -- the
    # "compute sites widen before arithmetic" half of the narrow-width
    # contract (plan/widths.py)
    "functions.py": {"_as128", "_as128_at_scale", "_binary_cmp",
                     "_cmp_values", "_multiply", "_divide128", "_civil",
                     "_decimal_round", "_date_arith",
                     # the $hashValue analog: a 64-bit hash IS the
                     # contract partitioned exchanges route by
                     "hash64_block", "_mix64"},
    # range-exchange splitter sampling packs order words and sample
    # positions in 64 bits (position arithmetic (2s-1)*count must not
    # wrap at large per-worker counts)
    "exchange.py": {"exchange_by_range", "exchange_by_hash"},
    # row-id / grouping-set-id iotas are logical BIGINT output columns
    # (AssignUniqueIdNode / GroupIdNode lowering)
    "planner.py": {"compile_plan"},
}

_WIDE = 8  # itemsize threshold: int64/uint64/float64


def _dtype(v):
    return getattr(getattr(v, "aval", None), "dtype", None)


def _is_wide(dt) -> bool:
    return dt is not None and dt.kind in "iuf" and dt.itemsize >= _WIDE


def _site_allowed(kernel: KernelIR, eqn) -> bool:
    src, context, _line = kernel.site(eqn)
    base = src.rsplit("/", 1)[-1]
    if base in WIDE_OK_FILES:
        return True
    allowed = WIDE_OK_SITES.get(base)
    if not allowed:
        return False
    if context in allowed:
        return True
    stack = kernel.site_stack(eqn)
    return any(name in allowed for name in stack)


@register
class WideLaneEscapePass(AuditPass):
    code = "K001"
    name = "wide-lane-escape"
    description = ("64-bit avals created from all-narrow inputs outside "
                   "the whitelisted limb/key/accumulator kernels (the "
                   "IR ground truth behind W001)")

    def run(self, kernel: KernelIR) -> List:
        findings = []
        for _jx, eqn in kernel.eqns():
            prim = str(eqn.primitive)
            if prim in CALL_PRIMITIVES:
                continue  # creation sites live inside the sub-jaxpr
            out_dts = [_dtype(o) for o in eqn.outvars]
            if not any(_is_wide(d) for d in out_dts):
                continue
            in_dts = [_dtype(i) for i in eqn.invars]
            if any(_is_wide(d) for d in in_dts):
                continue  # wideness flowed in; sanctioned at staging
            if _site_allowed(kernel, eqn):
                continue
            wide = next(d for d in out_dts if _is_wide(d))
            findings.append(kernel.finding(
                "K001", eqn,
                f"{wide} lanes created by `{prim}` from all-narrow "
                f"inputs -- a wide-lane escape narrow-width execution "
                f"pays for in HBM traffic; widen at a whitelisted "
                f"exactness site or keep the lane narrow"))
        return findings
