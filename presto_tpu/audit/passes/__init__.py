"""Built-in kernaudit IR passes. Importing this package registers
every pass with the audit registry (core.register side effect); add a
new pass by dropping a module here and importing it below."""

from . import (collectives, donation, footprint,  # noqa: F401
               host_callback, wide_lanes, widening)
