"""Built-in kernaudit IR passes. Importing this package registers
every pass with the audit registry (core.register side effect); add a
new pass by dropping a module here and importing it below."""

from . import (collectives, footprint, host_callback,  # noqa: F401
               wide_lanes, widening)
