"""K005: intermediate-footprint estimate vs budget.

A fused fragment program's peak live bytes -- staged inputs plus every
intermediate alive at the widest point of the schedule -- is what
actually has to fit HBM, and nothing at the AST or plan level sees it:
it emerges from the jaxpr's schedule. The estimate here walks eqns in
program order with a last-use liveness map (sub-jaxprs contribute
their own peak as a transient at the call site), the standard
linear-scan upper bound XLA's allocator will generally beat (it
reorders and fuses away intermediates) but never by orders of
magnitude on this engine's shapes.

Kernels whose estimate exceeds the kernel's budget
(``KernelIR.footprint_budget_bytes``; 0 = report-only) are findings.
Whatever the verdict, the estimate lands in ``KernelIR.notes
["peak_bytes_estimate"]`` so the staging hook can feed it to
``exec/memory.py``'s pool accounting (``MemoryPool.note_audit_
estimate``) and QueryStats.
"""

from __future__ import annotations

import itertools
from typing import List

from ..core import AuditPass, KernelIR, eqn_subjaxprs, register

__all__ = ["FootprintPass", "estimate_peak_bytes"]


def _aval_bytes(v) -> int:
    a = getattr(v, "aval", None)
    shape = getattr(a, "shape", None)
    dt = getattr(a, "dtype", None)
    if shape is None or dt is None:
        return 0
    n = 1
    for s in shape:
        try:
            n *= int(s)
        except (TypeError, ValueError):  # symbolic dims: count as 1
            pass
    return n * dt.itemsize


def _jaxpr_peak(jx) -> int:
    from jax.core import Literal
    last = {}
    for i, e in enumerate(jx.eqns):
        for v in e.invars:
            if not isinstance(v, Literal):
                last[v] = i
    outset = {id(v) for v in jx.outvars}
    live = sum(_aval_bytes(v)
               for v in itertools.chain(jx.invars, jx.constvars))
    peak = live
    for i, e in enumerate(jx.eqns):
        transient = max((_jaxpr_peak(s) for s in eqn_subjaxprs(e)),
                        default=0)
        live += sum(_aval_bytes(o) for o in e.outvars)
        peak = max(peak, live + transient)
        seen = set()
        for v in itertools.chain(e.invars, e.outvars):
            if isinstance(v, Literal) or id(v) in seen:
                continue
            seen.add(id(v))
            if last.get(v, -1) <= i and id(v) not in outset:
                live -= _aval_bytes(v)
    return peak


def estimate_peak_bytes(closed_or_jaxpr) -> int:
    """Liveness-walk upper bound on a program's peak live bytes."""
    jx = getattr(closed_or_jaxpr, "jaxpr", closed_or_jaxpr)
    return _jaxpr_peak(jx)


@register
class FootprintPass(AuditPass):
    code = "K005"
    name = "intermediate-footprint"
    description = ("liveness estimate of peak live bytes from eqn "
                   "out-avals, gated against a configurable budget and "
                   "fed to the memory pool's accounting")

    def run(self, kernel: KernelIR) -> List:
        est = estimate_peak_bytes(kernel.jaxpr)
        kernel.notes["peak_bytes_estimate"] = est
        budget = kernel.footprint_budget_bytes
        if budget and est > budget:
            return [kernel.kernel_finding(
                "K005",
                f"estimated peak live bytes {est} exceed the footprint "
                f"budget {budget} -- shrink capacities, stream the "
                f"scan (split_rows), or raise "
                f"kernel_audit_budget_bytes if the footprint is "
                f"intended")]
        return []
