"""SQL frontend: lexer + recursive-descent parser for the SELECT subset.

Reference surface: presto-parser (ANTLR grammar SqlBase.g4, 1071 lines,
SqlParser.java -> AST in com.facebook.presto.sql.tree). This is a
hand-written recursive-descent parser covering the engine's executable
subset (the reference's full grammar -- DDL, lambdas, set operations,
subqueries -- grows in over rounds):

  SELECT [DISTINCT] items FROM t [[AS] a] [joins] [WHERE e]
  [GROUP BY es] [HAVING e] [ORDER BY es [ASC|DESC] [NULLS F/L]] [LIMIT n]

with expressions: arithmetic, comparisons, AND/OR/NOT, BETWEEN, IN
(literal list), LIKE, IS [NOT] NULL, CASE, CAST, function calls,
DATE/INTERVAL literals, qualified names.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Tuple

__all__ = ["parse_sql", "Query", "Select", "TableRef", "Join", "OrderItem",
           "Literal", "Name", "Func", "BinOp", "NotOp", "Between", "InList",
           "Like", "IsNull", "Case", "Cast", "Star"]


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Literal:
    value: object
    kind: str  # "int" | "decimal" | "string" | "bool" | "null" | "date" | "interval_day"


@dataclasses.dataclass
class Name:
    parts: Tuple[str, ...]  # ("t", "col") or ("col",)


@dataclasses.dataclass
class Star:
    pass


@dataclasses.dataclass
class Func:
    name: str
    args: List[object]
    distinct: bool = False


@dataclasses.dataclass
class BinOp:
    op: str
    left: object
    right: object


@dataclasses.dataclass
class Lambda:
    """x -> body or (x, y) -> body (array/map higher-order args)."""
    params: List[str]
    body: object


@dataclasses.dataclass
class NotOp:
    arg: object


@dataclasses.dataclass
class Between:
    value: object
    lo: object
    hi: object
    negate: bool = False


@dataclasses.dataclass
class InList:
    value: object
    items: List[object]
    negate: bool = False


@dataclasses.dataclass
class Like:
    value: object
    pattern: str
    negate: bool = False


@dataclasses.dataclass
class IsNull:
    value: object
    negate: bool = False


@dataclasses.dataclass
class Case:
    operand: Optional[object]
    whens: List[Tuple[object, object]]
    default: Optional[object]


@dataclasses.dataclass
class Cast:
    value: object
    type_name: str
    safe: bool = False  # TRY_CAST: out-of-domain -> NULL


@dataclasses.dataclass
class WindowExpr:
    func: "Func"
    partition_by: List[object]
    order_by: List["OrderItem"]
    # None = default (RANGE UNBOUNDED PRECEDING..CURRENT ROW with ORDER
    # BY, full partition without); else ("rows"|"range", start, end)
    # where start/end is None (unbounded) or a signed row offset
    # (negative = PRECEDING, 0 = CURRENT ROW, positive = FOLLOWING)
    frame: object = None


@dataclasses.dataclass
class SelectItem:
    expr: object
    alias: Optional[str]


@dataclasses.dataclass
class TableRef:
    name: str
    alias: Optional[str]
    subquery: Optional[object] = None  # derived table: (SELECT ...) alias


@dataclasses.dataclass
class Join:
    kind: str  # "inner" | "left" | "right" | "full" | "cross"
    table: TableRef
    condition: object


@dataclasses.dataclass
class OrderItem:
    expr: object
    descending: bool
    nulls_last: bool


@dataclasses.dataclass
class Select:
    items: List[SelectItem]
    distinct: bool


@dataclasses.dataclass
class InSubquery:
    value: object
    query: "Query"
    negate: bool = False


@dataclasses.dataclass
class ScalarSubquery:
    query: object  # Query | SetQuery


@dataclasses.dataclass
class Exists:
    query: "Query"
    negate: bool = False


@dataclasses.dataclass
class Rollup:
    items: List[object]


@dataclasses.dataclass
class Cube:
    items: List[object]


@dataclasses.dataclass
class GroupingSets:
    sets: List[List[object]]


@dataclasses.dataclass
class Query:
    select: Select
    table: TableRef
    joins: List[Join]
    where: Optional[object]
    group_by: List[object]
    having: Optional[object]
    order_by: List[OrderItem]
    limit: Optional[int]


@dataclasses.dataclass
class Insert:
    """INSERT INTO t [(cols)] (SELECT ... | VALUES (...), ...)."""
    table: str                      # bare or catalog-qualified name
    columns: Optional[List[str]]
    query: object                   # Query | SetQuery | ValuesRows


@dataclasses.dataclass
class ValuesRows:
    rows: List[List[object]]        # expression ASTs per cell


@dataclasses.dataclass
class CreateTableAs:
    table: str
    query: object
    if_not_exists: bool = False


@dataclasses.dataclass
class DropTable:
    table: str
    if_exists: bool = False


@dataclasses.dataclass
class Delete:
    """DELETE FROM t [WHERE p]."""
    table: str
    where: object = None


@dataclasses.dataclass
class Update:
    """UPDATE t SET c = e [, ...] [WHERE p]."""
    table: str
    assignments: List[Tuple[str, object]] = dataclasses.field(
        default_factory=list)
    where: object = None


@dataclasses.dataclass
class SetQuery:
    """UNION / INTERSECT / EXCEPT of two query terms."""
    op: str                 # "union" | "intersect" | "except"
    all: bool               # UNION ALL vs set semantics
    left: object            # Query | SetQuery
    right: object


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<number>\d+(?:\.\d+)?)
    | (?P<string>'(?:[^']|'')*')
    | (?P<ident>[A-Za-z_][A-Za-z_0-9]*)
    | (?P<op><>|!=|>=|<=|->|=|<|>|\+|-|\*|/|%|\(|\)|,|\.|\[|\])
    )""", re.VERBOSE)

_KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having", "order",
    "limit", "as", "and", "or", "not", "between", "in", "like", "is", "null",
    "case", "when", "then", "else", "end", "cast", "join", "inner", "left",
    "on", "true", "false", "asc", "desc", "nulls", "first", "last", "date",
    "interval", "day", "month", "year", "extract", "outer", "over",
    "partition", "union", "intersect", "except", "all", "with", "exists",
    "try_cast",
}


def _tokenize(text: str) -> List[Tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise ValueError(f"cannot tokenize at: {text[pos:pos + 30]!r}")
        pos = m.end()
        if m.lastgroup == "number":
            out.append(("number", m.group("number")))
        elif m.lastgroup == "string":
            out.append(("string", m.group("string")[1:-1].replace("''", "'")))
        elif m.lastgroup == "ident":
            word = m.group("ident")
            if word.lower() in _KEYWORDS:
                out.append(("kw", word.lower()))
            else:
                out.append(("ident", word))
        else:
            out.append(("op", m.group("op")))
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> Tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> Tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *words) -> Optional[str]:
        k, v = self.peek()
        if k == "kw" and v in words:
            self.next()
            return v
        return None

    def accept_ident(self, *words) -> Optional[str]:
        """Soft keywords: contextual words (AT TIME ZONE, ...) that stay
        usable as column names elsewhere."""
        k, v = self.peek()
        if k == "ident" and v.lower() in words:
            self.next()
            return v.lower()
        return None

    def expect_kw(self, word: str):
        if not self.accept_kw(word):
            raise ValueError(f"expected {word.upper()}, got {self.peek()}")

    def accept_ctx_kw(self, word: str, before_op: Optional[str] = None,
                      before_kw: Optional[str] = None,
                      before_ident: Optional[str] = None) -> bool:
        """Contextual (non-reserved) keyword: matches an identifier token
        case-insensitively, optionally only when the NEXT token is the
        given operator/keyword -- Presto keeps words like ROLLUP and
        CROSS usable as plain identifiers (SqlBase.g4 nonReserved rule)."""
        k, v = self.peek()
        if k == "ident" and v.lower() == word:
            if before_op is not None:
                k2, v2 = self.toks[self.i + 1]
                if not (k2 == "op" and v2 == before_op):
                    return False
            if before_kw is not None:
                k2, v2 = self.toks[self.i + 1]
                if not (k2 == "kw" and v2 == before_kw):
                    return False
            if before_ident is not None:
                k2, v2 = self.toks[self.i + 1]
                if not (k2 == "ident" and v2.lower() == before_ident):
                    return False
            self.next()
            return True
        return False

    def _paren_expr_list(self) -> List[object]:
        self.expect_op("(")
        items = [self.expr()]
        while self.accept_op(","):
            items.append(self.expr())
        self.expect_op(")")
        return items

    def _grouping_set(self) -> List[object]:
        """One GROUPING SETS element: (a, b) | (single) | () | bare expr."""
        if self.accept_op("("):
            if self.accept_op(")"):
                return []
            items = [self.expr()]
            while self.accept_op(","):
                items.append(self.expr())
            self.expect_op(")")
            return items
        return [self.expr()]

    def accept_op(self, *ops) -> Optional[str]:
        k, v = self.peek()
        if k == "op" and v in ops:
            self.next()
            return v
        return None

    def expect_op(self, op: str):
        if not self.accept_op(op):
            raise ValueError(f"expected {op!r}, got {self.peek()}")

    def expect_ident(self) -> str:
        k, v = self.next()
        if k not in ("ident", "kw"):  # allow keywords as identifiers sparingly
            raise ValueError(f"expected identifier, got {(k, v)}")
        return v

    # -- expressions --------------------------------------------------------

    def expr(self):
        # lambda arguments: x -> body  |  (x, y) -> body
        k, v = self.peek()
        if k == "ident" and self.toks[self.i + 1] == ("op", "->"):
            self.next()
            self.next()
            return Lambda([v.lower()], self.expr())
        if (k, v) == ("op", "("):
            j = self.i + 1
            params = []
            while self.toks[j][0] == "ident":
                params.append(self.toks[j][1].lower())
                j += 1
                if self.toks[j] == ("op", ","):
                    j += 1
                    continue
                break
            if params and self.toks[j] == ("op", ")") \
                    and self.toks[j + 1] == ("op", "->"):
                self.i = j + 2
                return Lambda(params, self.expr())
        return self.or_expr()

    def or_expr(self):
        left = self.and_expr()
        while self.accept_kw("or"):
            left = BinOp("or", left, self.and_expr())
        return left

    def and_expr(self):
        left = self.not_expr()
        while self.accept_kw("and"):
            left = BinOp("and", left, self.not_expr())
        return left

    def not_expr(self):
        if self.accept_kw("not"):
            return NotOp(self.not_expr())
        return self.predicate()

    def predicate(self):
        left = self.additive()
        negate = bool(self.accept_kw("not"))
        if self.accept_kw("between"):
            lo = self.additive()
            self.expect_kw("and")
            hi = self.additive()
            return Between(left, lo, hi, negate)
        if self.accept_kw("in"):
            self.expect_op("(")
            if self.peek() == ("kw", "select"):
                sub = self.query()  # set-op subqueries terminate on ")"
                self.expect_op(")")
                return InSubquery(left, sub, negate)
            items = [self.expr()]
            while self.accept_op(","):
                items.append(self.expr())
            self.expect_op(")")
            return InList(left, items, negate)
        if self.accept_kw("like"):
            k, v = self.next()
            assert k == "string", "LIKE pattern must be a string literal"
            return Like(left, v, negate)
        if self.accept_kw("is"):
            neg = bool(self.accept_kw("not"))
            self.expect_kw("null")
            return IsNull(left, neg)
        assert not negate, "dangling NOT"
        op = self.accept_op("=", "<>", "!=", "<", "<=", ">", ">=")
        if op:
            return BinOp(op, left, self.additive())
        return left

    def additive(self):
        left = self.multiplicative()
        while True:
            op = self.accept_op("+", "-")
            if not op:
                return left
            left = BinOp(op, left, self.multiplicative())

    def multiplicative(self):
        left = self.unary()
        while True:
            op = self.accept_op("*", "/", "%")
            if not op:
                return left
            left = BinOp(op, left, self.unary())

    def unary(self):
        if self.accept_op("-"):
            return Func("negate", [self.unary()])
        e = self.primary()
        # postfix subscript a[i] (1-based; element_at semantics) and
        # AT TIME ZONE 'zone' -- both bind tighter than arithmetic
        while True:
            if self.accept_op("["):
                idx = self.expr()
                k2, v2 = self.next()
                assert (k2, v2) == ("op", "]"), "expected ] after subscript"
                e = Func("element_at", [e, idx])
                continue
            mark = self.i
            if self.accept_ident("at"):
                if self.accept_ident("time") and self.accept_ident("zone"):
                    k, v = self.next()
                    assert k == "string", "AT TIME ZONE needs a zone string"
                    e = Func("at_timezone", [e, Literal(v, "string")])
                    continue
                self.i = mark  # a column actually named "at"
            break
        return e

    def primary(self):
        k, v = self.peek()
        if k == "number":
            self.next()
            if "." in v:
                scale = len(v.split(".")[1])
                return Literal(int(v.replace(".", "")), f"decimal:{scale}")
            return Literal(int(v), "int")
        if k == "string":
            self.next()
            return Literal(v, "string")
        if k == "kw" and v in ("true", "false"):
            self.next()
            return Literal(v == "true", "bool")
        if k == "kw" and v == "null":
            self.next()
            return Literal(None, "null")
        if k == "kw" and v == "date":
            self.next()
            kk, vv = self.next()
            assert kk == "string"
            return Literal(vv, "date")
        if k == "ident" and v.lower() in ("timestamp", "time") \
                and self.toks[self.i + 1][0] == "string":
            self.next()
            _, vv = self.next()
            return Literal(vv, v.lower())
        if k == "kw" and v == "interval":
            self.next()
            kk, vv = self.next()
            assert kk == "string"
            unit = self.next()[1]  # day | month | year
            return Literal((int(vv), unit), "interval")
        if k == "kw" and v in ("cast", "try_cast"):
            self.next()
            self.expect_op("(")
            e = self.expr()
            self.expect_kw("as")
            tname = self._type_name()
            self.expect_op(")")
            return Cast(e, tname, safe=(v == "try_cast"))
        if k == "kw" and v == "case":
            return self._case()
        if k == "kw" and v == "exists":
            self.next()
            self.expect_op("(")
            sub = self.query()
            self.expect_op(")")
            return Exists(sub)
        if k == "kw" and v == "extract":
            self.next()
            self.expect_op("(")
            unit = self.next()[1]
            self.expect_kw("from")
            e = self.expr()
            self.expect_op(")")
            return Func(unit.lower(), [e])
        if k == "op" and v == "(":
            self.next()
            if self.peek() == ("kw", "select"):
                sub = self.query()
                self.expect_op(")")
                return ScalarSubquery(sub)
            e = self.expr()
            self.expect_op(")")
            return e
        if k == "op" and v == "*":
            self.next()
            return Star()
        if k == "ident" and v.lower() == "array" \
                and self.toks[self.i + 1] == ("op", "["):
            self.next()
            self.next()
            items = []
            if self.peek() != ("op", "]"):
                items.append(self.expr())
                while self.accept_op(","):
                    items.append(self.expr())
            k2, v2 = self.next()
            assert (k2, v2) == ("op", "]"), "expected ] in ARRAY literal"
            return Func("array_constructor", items)
        if k == "ident" and v.lower() in ("current_timestamp",
                                          "current_date", "localtimestamp") \
                and self.toks[self.i + 1] != ("op", "("):
            self.next()
            return Func(v.lower(), [])
        if k in ("ident", "kw"):
            self.next()
            if self.peek() == ("op", "("):
                self.next()
                distinct = bool(self.accept_kw("distinct"))
                args: List[object] = []
                if self.peek() != ("op", ")"):
                    args.append(self.expr())
                    while self.accept_op(","):
                        args.append(self.expr())
                self.expect_op(")")
                fn = Func(v.lower(), args, distinct)
                if self.accept_kw("over"):
                    self.expect_op("(")
                    part: List[object] = []
                    order: List[OrderItem] = []
                    if self.accept_kw("partition"):
                        self.expect_kw("by")
                        part.append(self.expr())
                        while self.accept_op(","):
                            part.append(self.expr())
                    if self.accept_kw("order"):
                        self.expect_kw("by")
                        order.append(self._order_item())
                        while self.accept_op(","):
                            order.append(self._order_item())
                    frame = self._window_frame()
                    self.expect_op(")")
                    return WindowExpr(fn, part, order, frame)
                return fn
            parts = [v]
            while self.accept_op("."):
                parts.append(self.expect_ident())
            if len(parts) > 1 and self.peek() == ("op", "("):
                # qualified function call (namespace-managed UDFs:
                # catalog.schema.fn(...))
                self.next()
                args: List[object] = []
                if self.peek() != ("op", ")"):
                    args.append(self.expr())
                    while self.accept_op(","):
                        args.append(self.expr())
                self.expect_op(")")
                return Func(".".join(p.lower() for p in parts), args)
            return Name(tuple(parts))
        raise ValueError(f"unexpected token {(k, v)}")

    def _type_name(self) -> str:
        name = self.expect_ident()
        # multiword type names: TIMESTAMP WITH TIME ZONE,
        # INTERVAL YEAR TO MONTH / DAY TO SECOND, DOUBLE PRECISION
        low = name.lower()
        if low == "timestamp" and self.peek() == ("kw", "with"):
            self.next()
            for w in ("time", "zone"):
                t = self.next()[1].lower()
                assert t == w, f"expected {w!r} in type name, got {t!r}"
            name = "timestamp with time zone"
        elif low == "interval":
            a = self.next()[1].lower()
            self.expect_ident()  # TO
            b = self.next()[1].lower()
            name = f"interval {a} to {b}"
        elif low == "double" and self.peek()[1] == "precision":
            self.next()
            name = "double"
        if self.accept_op("("):
            params = [self.next()[1]]
            while self.accept_op(","):
                params.append(self.next()[1])
            self.expect_op(")")
            return f"{name}({', '.join(params)})"
        return name

    def _case(self):
        self.expect_kw("case")
        operand = None
        if not (self.peek() == ("kw", "when")):
            operand = self.expr()
        whens = []
        while self.accept_kw("when"):
            c = self.expr()
            self.expect_kw("then")
            r = self.expr()
            whens.append((c, r))
        default = None
        if self.accept_kw("else"):
            default = self.expr()
        self.expect_kw("end")
        return Case(operand, whens, default)

    # -- query --------------------------------------------------------------

    def query(self, allow_setops: bool = True):
        # standard precedence: INTERSECT binds tighter than UNION/EXCEPT
        left = self._intersect_term()
        while allow_setops:
            op = self.accept_kw("union", "except")
            if not op:
                break
            is_all = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            right = self._intersect_term()
            left = SetQuery(op, is_all, left, right)
        return left

    def _intersect_term(self):
        left = self._query_term()
        while self.accept_kw("intersect"):
            is_all = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            right = self._query_term()
            left = SetQuery("intersect", is_all, left, right)
        return left

    def _query_term(self) -> Query:
        self.expect_kw("select")
        distinct = bool(self.accept_kw("distinct"))
        items = [self._select_item()]
        while self.accept_op(","):
            items.append(self._select_item())
        if self.accept_kw("from"):
            table = self._table_ref()
        else:
            # FROM-less SELECT: one synthetic single-row source (the
            # reference plans these over a one-row ValuesNode); the
            # normal WHERE/ORDER BY/LIMIT clause loop still applies
            table = TableRef("$dual", None)
        joins = []
        while True:
            # comma-separated FROM items / CROSS JOIN: a join with no ON
            # condition; equi-keys come from WHERE conjuncts (the
            # planner's join-graph extraction, TPC-DS benchmark style)
            if self.accept_op(","):
                joins.append(Join("cross", self._table_ref(), None))
                continue
            if self.accept_ctx_kw("cross", before_kw="join"):
                self.expect_kw("join")
                joins.append(Join("cross", self._table_ref(), None))
                continue
            kind = None
            if self.accept_kw("inner"):
                kind = "inner"
                self.expect_kw("join")
            elif self.accept_kw("left"):
                kind = "left"
                self.accept_kw("outer")
                self.expect_kw("join")
            elif self.accept_ctx_kw("right", before_kw="join") or \
                    self.accept_ctx_kw("right", before_kw="outer"):
                kind = "right"
                self.accept_kw("outer")
                self.expect_kw("join")
            elif self.accept_ctx_kw("full", before_kw="join") or \
                    self.accept_ctx_kw("full", before_kw="outer"):
                kind = "full"
                self.accept_kw("outer")
                self.expect_kw("join")
            elif self.accept_kw("join"):
                kind = "inner"
            if kind is None:
                break
            t = self._table_ref()
            self.expect_kw("on")
            cond = self.expr()
            joins.append(Join(kind, t, cond))
        where = self.expr() if self.accept_kw("where") else None
        group_by: List[object] = []
        if self.accept_kw("group"):
            self.expect_kw("by")
            if self.accept_ctx_kw("rollup", before_op="("):
                group_by.append(Rollup(self._paren_expr_list()))
            elif self.accept_ctx_kw("cube", before_op="("):
                group_by.append(Cube(self._paren_expr_list()))
            elif self.accept_ctx_kw("grouping", before_kw=None,
                                    before_ident="sets"):
                self.next()  # the already-matched SETS token
                self.expect_op("(")
                sets = [self._grouping_set()]
                while self.accept_op(","):
                    sets.append(self._grouping_set())
                self.expect_op(")")
                group_by.append(GroupingSets(sets))
            else:
                group_by.append(self.expr())
                while self.accept_op(","):
                    group_by.append(self.expr())
        having = self.expr() if self.accept_kw("having") else None
        order_by: List[OrderItem] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by.append(self._order_item())
            while self.accept_op(","):
                order_by.append(self._order_item())
        limit = None
        if self.accept_kw("limit"):
            k, v = self.next()
            assert k == "number"
            limit = int(v)
        return Query(Select(items, distinct), table, joins, where, group_by,
                     having, order_by, limit)

    def _select_item(self) -> SelectItem:
        e = self.expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        elif self.peek()[0] == "ident":
            alias = self.next()[1]
        return SelectItem(e, alias)

    def _implicit_alias(self) -> Optional[str]:
        """An identifier alias -- but not the contextual keywords CROSS/
        RIGHT/FULL when they introduce the next join (Presto keeps them
        non-reserved; SqlBase.g4 nonReserved)."""
        if self.peek()[0] != "ident":
            return None
        w = self.peek()[1].lower()
        if w in ("cross", "right", "full"):
            k2, v2 = self.toks[self.i + 1]
            if k2 == "kw" and v2 in ("join", "outer"):
                return None
        return self.next()[1]

    def _table_ref(self) -> TableRef:
        if self.accept_op("("):
            sub = self.query()
            self.expect_op(")")
            alias = None
            if self.accept_kw("as"):
                alias = self.expect_ident()
            else:
                alias = self._implicit_alias()
            if not alias:
                raise ValueError("derived table requires an alias")
            return TableRef(alias.lower(), alias, subquery=sub)
        name = self.expect_ident()
        # catalog-qualified reference: memory.t (two parts; deeper
        # schemas collapse into the catalog-level names this engine uses)
        while True:
            k, v = self.peek()
            if not (k == "op" and v == "."):
                break
            k2, _v2 = self.toks[self.i + 1]
            if k2 != "ident":
                break
            self.next()
            name += "." + self.expect_ident()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        else:
            alias = self._implicit_alias()
        if alias is None and "." in name:
            alias = name.rsplit(".", 1)[1]  # bare table name qualifies
        return TableRef(name.lower(), alias)

    def _window_frame(self):
        """[ROWS|RANGE [BETWEEN] bound [AND bound]] inside OVER (...).
        bound: UNBOUNDED PRECEDING|FOLLOWING, CURRENT ROW, n
        PRECEDING|FOLLOWING. Returns None or (mode, start, end)."""
        mode = None
        if self.accept_ctx_kw("rows"):
            mode = "rows"
        elif self.accept_ctx_kw("range"):
            mode = "range"
        if mode is None:
            return None

        def bound():
            if self.accept_ctx_kw("unbounded"):
                which = self.next()[1].lower()
                assert which in ("preceding", "following"), which
                return "unbounded_precede" if which == "preceding" \
                    else "unbounded_follow"
            if self.accept_ctx_kw("current"):
                k, v = self.next()
                assert v.lower() == "row", (k, v)
                return 0
            k, v = self.next()
            assert k == "number", f"expected frame bound, got {(k, v)}"
            n = float(v) if "." in v else int(v)  # RANGE takes decimals
            which = self.next()[1].lower()
            assert which in ("preceding", "following"), which
            return -n if which == "preceding" else n

        if self.accept_kw("between"):
            start = bound()
            self.expect_kw("and")
            end = bound()
        else:
            start = bound()
            end = 0  # implicit CURRENT ROW
        # normalize to (mode, start, end) with None = unbounded on that
        # side; the invalid corner sentinels are rejected, not coerced
        if start == "unbounded_follow":
            raise ValueError("frame start cannot be UNBOUNDED FOLLOWING")
        if end == "unbounded_precede":
            raise ValueError("frame end cannot be UNBOUNDED PRECEDING")
        start_v = None if start == "unbounded_precede" else start
        end_v = None if end == "unbounded_follow" else end
        # ANSI ordering rule: a bounded start must not sit after a
        # bounded end (covers ROWS n FOLLOWING => implicit CURRENT ROW
        # end, and BETWEEN CURRENT ROW AND n PRECEDING)
        if start_v is not None and end_v is not None and start_v > end_v:
            raise ValueError("window frame start cannot follow frame end")
        return (mode, start_v, end_v)

    def _order_item(self) -> OrderItem:
        e = self.expr()
        desc = False
        if self.accept_kw("desc"):
            desc = True
        else:
            self.accept_kw("asc")
        nulls_last = True  # presto default for ASC; DESC default NULLS LAST too
        if self.accept_kw("nulls"):
            if self.accept_kw("first"):
                nulls_last = False
            else:
                self.expect_kw("last")
        return OrderItem(e, desc, nulls_last)


def parse_expression(text: str):
    """Parse ONE scalar expression (SQL-invoked function bodies)."""
    p = _Parser(_tokenize(text))
    e = p.expr()
    k, v = p.peek()
    if k != "eof":
        raise ValueError(f"trailing tokens in expression at {(k, v)}")
    return e


def parse_sql(text: str):
    p = _Parser(_tokenize(text))
    k, v = p.peek()
    if k == "ident" and v.lower() in ("insert", "create", "drop",
                                      "delete", "update"):
        return _parse_dml(p, v.lower())
    ctes = {}
    if p.accept_kw("with"):
        while True:
            name = p.expect_ident().lower()
            p.expect_kw("as")
            p.expect_op("(")
            ctes[name] = p.query()
            p.expect_op(")")
            if not p.accept_op(","):
                break
    q = p.query()
    k, v = p.peek()
    if k != "eof":
        raise ValueError(f"trailing tokens at {(k, v)}")
    if ctes:
        # earlier CTEs are visible inside later CTE bodies (no recursion)
        names = list(ctes)
        for i, n in enumerate(names):
            _inline_ctes(ctes[n], {m: ctes[m] for m in names[:i]})
        _inline_ctes(q, ctes)
    return q


def _parse_dml(p: "_Parser", first: str):
    """INSERT INTO / CREATE TABLE [IF NOT EXISTS] t AS / DROP TABLE
    [IF EXISTS] t. The write verbs are contextual identifiers (like the
    reference's nonReserved words), matched case-insensitively."""

    def ctx(word):
        k, v = p.peek()
        if k == "ident" and v.lower() == word:
            p.next()
            return True
        return False

    def expect_ctx(word):
        if not ctx(word):
            raise ValueError(f"expected {word.upper()}, got {p.peek()}")

    def qualified_name() -> str:
        name = p.expect_ident()
        while True:
            k, v = p.peek()
            if k == "op" and v == ".":
                p.next()
                name += "." + p.expect_ident()
            else:
                return name.lower()

    p.next()  # consume the verb
    if first == "insert":
        expect_ctx("into")
        table = qualified_name()
        columns = None
        if p.accept_op("("):
            columns = [p.expect_ident().lower()]
            while p.accept_op(","):
                columns.append(p.expect_ident().lower())
            p.expect_op(")")
        if ctx("values"):
            rows = []
            while True:
                p.expect_op("(")
                row = [p.expr()]
                while p.accept_op(","):
                    row.append(p.expr())
                p.expect_op(")")
                rows.append(row)
                if not p.accept_op(","):
                    break
            query = ValuesRows(rows)
        else:
            query = p.query()
        k, _ = p.peek()
        if k != "eof":
            raise ValueError(f"trailing tokens at {p.peek()}")
        return Insert(table, columns, query)
    if first == "create":
        expect_ctx("table")
        if_not_exists = False
        if ctx("if"):
            p.expect_kw("not")
            p.expect_kw("exists")
            if_not_exists = True
        table = qualified_name()
        p.expect_kw("as")
        q = p.query()
        k, _ = p.peek()
        if k != "eof":
            raise ValueError(f"trailing tokens at {p.peek()}")
        return CreateTableAs(table, q, if_not_exists)
    if first == "delete":
        p.expect_kw("from")
        table = qualified_name()
        where = None
        if p.accept_kw("where"):
            where = p.expr()
        k, _ = p.peek()
        if k != "eof":
            raise ValueError(f"trailing tokens at {p.peek()}")
        return Delete(table, where)
    if first == "update":
        table = qualified_name()
        expect_ctx("set")
        assignments = []
        while True:
            col = p.expect_ident().lower()
            p.expect_op("=")
            assignments.append((col, p.expr()))
            if not p.accept_op(","):
                break
        where = None
        if p.accept_kw("where"):
            where = p.expr()
        k, _ = p.peek()
        if k != "eof":
            raise ValueError(f"trailing tokens at {p.peek()}")
        return Update(table, assignments, where)
    # DROP TABLE [IF EXISTS] t
    expect_ctx("table")
    if_exists = False
    if ctx("if"):
        p.expect_kw("exists")
        if_exists = True
    table = qualified_name()
    k, _ = p.peek()
    if k != "eof":
        raise ValueError(f"trailing tokens at {p.peek()}")
    return DropTable(table, if_exists)


def _inline_ctes(q, ctes):
    """CTEs inline as derived tables at each reference -- anywhere in the
    AST, including FROM clauses of scalar/IN subqueries (the reference's
    default; materialized CTEs are an optimizer feature)."""
    seen = set()

    def visit(obj):
        if id(obj) in seen or not dataclasses.is_dataclass(obj):
            return
        seen.add(id(obj))
        if isinstance(obj, TableRef):
            if obj.subquery is None and obj.name in ctes:
                obj.subquery = ctes[obj.name]
            if obj.subquery is not None:
                visit(obj.subquery)
            return
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if dataclasses.is_dataclass(v):
                visit(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if dataclasses.is_dataclass(x):
                        visit(x)
                    elif isinstance(x, tuple):
                        for y in x:
                            if dataclasses.is_dataclass(y):
                                visit(y)

    visit(q)
