from .parser import parse_sql
from .planner import plan_sql, sql

__all__ = ["parse_sql", "plan_sql", "sql"]
