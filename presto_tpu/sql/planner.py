"""SQL analyzer + logical planner: AST -> typed plan.

Reference surface: presto-main-base's StatementAnalyzer.java:397 (name
resolution, type checking, aggregate analysis), LogicalPlanner.java:182
(AST -> PlanNode tree via QueryPlanner), and
SqlToRowExpressionTranslator (expression lowering). Collapsed into one
pass sized to the executable SELECT subset: resolve names against the
tpch catalog, infer types (Presto decimal rules, simplified division
scale), detect aggregates, and emit the same plan shapes the reference's
planner would (scan -> filter -> project -> aggregate -> having ->
project -> sort/topN/limit), with joins left-deep in FROM order.
"""

from __future__ import annotations

import contextvars
import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import types as T
from ..connectors import tpch
from ..expr import ir as E
from ..ops.aggregation import AggSpec
from ..plan import nodes as N
from . import parser as P

__all__ = ["plan_sql", "sql"]

_AGG_NAMES = {"sum", "count", "min", "max", "avg", "approx_distinct",
              "bool_and", "bool_or", "arbitrary", "every", "any_value",
              "stddev", "stddev_samp", "stddev_pop", "variance", "var_samp",
              "var_pop", "corr", "covar_samp", "covar_pop", "regr_slope",
              "regr_intercept", "geometric_mean", "checksum", "min_by",
              "max_by"}

# aggregates taking a second input column (value, order) / (y, x)
_TWO_ARG_AGGS = {"min_by", "max_by", "corr", "covar_samp", "covar_pop",
                 "regr_slope", "regr_intercept"}


@dataclasses.dataclass
class _Scope:
    """Name -> (channel, type); qualified and unqualified forms."""
    channels: Dict[str, int]
    types: List[T.Type]

    def resolve(self, parts: Tuple[str, ...]) -> Tuple[int, T.Type]:
        key = ".".join(parts).lower()
        if key in self.channels:
            ch = self.channels[key]
            return ch, self.types[ch]
        raise KeyError(f"column {key!r} not found; have {sorted(self.channels)}")


def _days(lit: str) -> int:
    return int((np.datetime64(lit) - np.datetime64("1970-01-01")).astype(int))


class _Analyzer:
    def __init__(self, query: P.Query, sf_catalog: str = "tpch"):
        self.q = query
        self.catalog = sf_catalog
        # id(WindowExpr) -> (channel, type) once a window stage planned
        self.window_channels: Dict[int, Tuple[int, T.Type]] = {}
        # id(InSubquery/Exists) -> mask expression, for subqueries in
        # DISJUNCTIVE predicate positions (planned as semijoin mask
        # columns before the enclosing predicate lowers)
        self.subquery_masks: Dict[int, E.RowExpression] = {}

    # -- expression lowering ------------------------------------------------

    def lower(self, node, scope: _Scope) -> E.RowExpression:
        if not isinstance(node, (str, int, float)) and \
                id(node) in self.subquery_masks:
            return self.subquery_masks[id(node)]
        if isinstance(node, P.WindowExpr):
            hit = self.window_channels.get(id(node))
            if hit is None:
                raise NotImplementedError(
                    "window expression outside the planned window stage")
            return E.input_ref(*hit)
        if isinstance(node, P.Literal):
            return self._literal(node)
        if isinstance(node, P.Name):
            lvars = getattr(scope, "lambda_vars", None)
            if lvars and len(node.parts) == 1 \
                    and node.parts[0].lower() in lvars:
                nm = node.parts[0].lower()
                return E.LambdaVariable(lvars[nm], nm)
            ch, ty = scope.resolve(node.parts)
            return E.input_ref(ch, ty)
        if isinstance(node, P.BinOp):
            return self._binop(node, scope)
        if isinstance(node, P.NotOp):
            a = self.lower(node.arg, scope)
            return E.call("not", T.BOOLEAN, a)
        if isinstance(node, P.Between):
            e = E.special("BETWEEN", T.BOOLEAN, self.lower(node.value, scope),
                          *(self._coerce_pair(self.lower(node.value, scope),
                                              self.lower(x, scope))[1]
                            for x in (node.lo, node.hi)))
            return E.call("not", T.BOOLEAN, e) if node.negate else e
        if isinstance(node, P.InList):
            v = self.lower(node.value, scope)
            items = [self._coerce_pair(v, self.lower(x, scope))[1]
                     for x in node.items]
            e = E.special("IN", T.BOOLEAN, v, *items)
            return E.call("not", T.BOOLEAN, e) if node.negate else e
        if isinstance(node, P.Like):
            v = self.lower(node.value, scope)
            e = E.call("like", T.BOOLEAN, v,
                       E.const(node.pattern, T.varchar(len(node.pattern))))
            return E.call("not", T.BOOLEAN, e) if node.negate else e
        if isinstance(node, P.IsNull):
            e = E.special("IS_NULL", T.BOOLEAN, self.lower(node.value, scope))
            return E.call("not", T.BOOLEAN, e) if node.negate else e
        if isinstance(node, P.Case):
            whens = []
            for c, r in node.whens:
                whens.append((self.lower(c, scope), self.lower(r, scope)))
            default = self.lower(node.default, scope) if node.default else None
            rty = _case_result_type([r for _, r in whens]
                                    + ([default] if default else []))
            args: List[E.RowExpression] = []
            if node.operand is not None:
                args.append(self.lower(node.operand, scope))
            else:
                args.append(E.const(True, T.BOOLEAN))
            for c, r in whens:
                args.append(E.special("WHEN", rty, c, _cast_branch(r, rty)))
            if default is not None:
                args.append(_cast_branch(default, rty))
            return E.special("SWITCH", rty, *args)
        if isinstance(node, P.Cast):
            v = self.lower(node.value, scope)
            ty = T.parse_type(node.type_name)
            return E.call("try_cast" if node.safe else "cast", ty, v)
        if isinstance(node, P.Func):
            return self._func(node, scope)
        raise NotImplementedError(f"cannot lower {node}")

    def _literal(self, lit: P.Literal) -> E.Constant:
        if lit.kind == "int":
            return E.const(lit.value, T.BIGINT)
        if lit.kind.startswith("decimal:"):
            scale = int(lit.kind.split(":")[1])
            return E.const(lit.value, T.decimal(38, scale))
        if lit.kind == "string":
            return E.const(lit.value, T.varchar(max(len(lit.value), 1)))
        if lit.kind == "bool":
            return E.const(lit.value, T.BOOLEAN)
        if lit.kind == "null":
            return E.const(None, T.UNKNOWN)
        if lit.kind == "date":
            return E.const(_days(lit.value), T.DATE)
        if lit.kind == "interval":
            n, unit = lit.value
            unit = unit.lower()
            if unit in ("year", "month"):
                months = n * 12 if unit == "year" else n
                return E.const(months, T.INTERVAL_YM)
            us = {"week": 7 * 86_400_000_000, "day": 86_400_000_000,
                  "hour": 3_600_000_000, "minute": 60_000_000,
                  "second": 1_000_000, "millisecond": 1_000}.get(unit)
            if us is None:
                raise NotImplementedError(f"interval unit {unit!r}")
            return E.const(n * us, T.INTERVAL_DS)
        if lit.kind == "timestamp":
            micros, key = _parse_ts_literal(lit.value)
            if key is None:
                return E.const(micros, T.TIMESTAMP)
            return E.const((micros << 12) | key, T.TIMESTAMP_TZ)
        if lit.kind == "time":
            return E.const(_parse_time_literal(lit.value), T.TIME)
        raise NotImplementedError(lit.kind)

    def _coerce_pair(self, a: E.RowExpression, b: E.RowExpression):
        """Implicit coercions for comparisons: align string widths, keep
        numerics (comparison kernels rescale internally)."""
        return a, b

    def _binop(self, node: P.BinOp, scope: _Scope) -> E.RowExpression:
        op = node.op
        if op in ("and", "or"):
            return E.special(op.upper(), T.BOOLEAN,
                             self.lower(node.left, scope),
                             self.lower(node.right, scope))
        a = self.lower(node.left, scope)
        b = self.lower(node.right, scope)
        if op in ("=", "<>", "!=", "<", "<=", ">", ">="):
            name = {"=": "eq", "<>": "ne", "!=": "ne", "<": "lt",
                    "<=": "le", ">": "gt", ">=": "ge"}[op]
            return E.call(name, T.BOOLEAN, a, b)
        # datetime +/- interval, interval +/- interval,
        # datetime - datetime -> INTERVAL DAY TO SECOND
        _DT = ("date", "time", "timestamp", "timestamp with time zone")
        _IV = ("interval year to month", "interval day to second")
        if op in ("+", "-"):
            if a.type.base in _DT and b.type.base in _IV:
                if a.type.base == "date" \
                        and b.type.base == "interval day to second" \
                        and isinstance(b, E.Constant) \
                        and b.value is not None \
                        and b.value % 86_400_000_000 != 0:
                    raise ValueError(
                        "Cannot add hour, minutes or seconds to a date")
                rhs = E.call("negate", b.type, b) if op == "-" else b
                return E.call("datetime_interval_add",
                              _dt_plus_interval_type(a.type, b.type),
                              a, rhs)
            if op == "+" and a.type.base in _IV and b.type.base in _DT:
                return E.call("datetime_interval_add",
                              _dt_plus_interval_type(b.type, a.type), b, a)
            if a.type.base in _IV and b.type.base == a.type.base:
                return E.call("add" if op == "+" else "subtract",
                              a.type, a, b)
            if op == "-" and a.type.base in _DT and b.type.base in _DT \
                    and "time" not in (a.type.base, b.type.base):
                return E.call("datetime_diff_micros", T.INTERVAL_DS, a, b)
        name = {"+": "add", "-": "subtract", "*": "multiply", "/": "divide",
                "%": "modulus"}[op]
        rty = self._arith_type(name, a.type, b.type)
        return E.call(name, rty, a, b)

    def _arith_type(self, name: str, t1: T.Type, t2: T.Type) -> T.Type:
        if t1.is_floating or t2.is_floating:
            return T.DOUBLE
        if t1.is_decimal or t2.is_decimal:
            s1 = t1.scale if t1.is_decimal else 0
            s2 = t2.scale if t2.is_decimal else 0
            if name in ("add", "subtract"):
                return T.decimal(38, max(s1, s2))
            if name == "multiply":
                return T.decimal(38, s1 + s2)
            if name == "divide":
                # the reference computes precision-aware decimal scales on
                # int128; on int64 lanes the dividend rescale overflows for
                # wide operands, so SQL-level decimal division yields DOUBLE
                # (exact decimal division survives where scales stay small,
                # e.g. the avg finalizer)
                return T.DOUBLE
            if name == "modulus":
                return T.decimal(38, max(s1, s2))
        if t1.is_integral and t2.is_integral:
            return T.BIGINT
        if t1.base == "date" and t2.base == "date" and name == "subtract":
            return T.BIGINT
        return t1 if t1.is_numeric else t2

    def _func(self, node: P.Func, scope: _Scope) -> E.RowExpression:
        name = node.name
        if any(isinstance(a, P.Lambda) for a in node.args):
            return self._lambda_func(node, scope)
        args = [self.lower(a, scope) for a in node.args
                if not isinstance(a, P.Star)]
        # special forms spelled as functions (branch types align to the
        # common type, same as CASE -- see _case_result_type)
        if name == "coalesce":
            rty = _case_result_type(args)
            return E.special("COALESCE", rty,
                             *[_cast_branch(a, rty) for a in args])
        if name == "nullif":
            rty = _case_result_type(args[:1])
            return E.special("NULL_IF", rty, *args)
        if name == "if":
            rty = _case_result_type(args[1:])
            return E.special("IF", rty,
                             args[0], *[_cast_branch(a, rty)
                                        for a in args[1:]])
        if name == "try":
            if len(args) != 1:
                raise ValueError("TRY requires exactly one argument")
            # kernels are total (errors produce NULL lanes, never raise),
            # so TRY is the identity on this engine
            return args[0]
        udf_hit = None
        if "." in name:
            from .udf import get_function_namespace_manager
            udf_hit = get_function_namespace_manager().lookup(name)
            if udf_hit is None:
                raise NotImplementedError(f"no function {name!r}")
        if udf_hit is not None:
            return self._expand_udf(udf_hit, args)
        if name in ("now", "current_timestamp"):
            from .. import tz as _tz
            return E.const(_statement_now_us() << 12 | _tz.UTC_KEY,
                           T.TIMESTAMP_TZ)
        if name == "current_date":
            return E.const(_statement_now_us() // 86_400_000_000, T.DATE)
        if name == "localtimestamp":
            return E.const(_statement_now_us(), T.TIMESTAMP)
        try:
            rty = self._func_type(name, args)
        except NotImplementedError:
            # unqualified SQL-invoked functions resolve AFTER builtins
            # (presto.default namespace; the reference's resolution
            # order)
            from .udf import get_function_namespace_manager
            udf = get_function_namespace_manager().lookup(name)
            if udf is None:
                raise
            return self._expand_udf(udf, args)
        return E.call(name, rty, *args)

    def _expand_udf(self, udf, args: List[E.RowExpression]
                    ) -> E.RowExpression:
        """SQL-invoked function: inline the body with parameters bound
        to the lowered argument expressions (a typed macro -- the UDF
        dissolves before XLA sees the plan). Arguments coerce to the
        declared parameter types (mismatches are plan-time errors);
        substitution is scope-aware (lambda parameters shadowing a UDF
        parameter are NOT captured); recursion is rejected."""
        from .udf import body_ast as _body_ast
        if len(args) != len(udf.parameters):
            raise ValueError(
                f"{udf.qualified_name} takes {len(udf.parameters)} "
                f"argument(s), got {len(args)}")
        in_progress = _UDF_EXPANDING.get()
        if udf.qualified_name in in_progress:
            raise ValueError(
                f"recursive SQL function {udf.qualified_name!r}")
        token = _UDF_EXPANDING.set(in_progress | {udf.qualified_name})
        try:
            ls = _Scope({}, [])
            ls.lambda_vars = {p: ty for p, ty in udf.parameters}
            body = self.lower(_body_ast(udf), ls)
        finally:
            _UDF_EXPANDING.reset(token)
        binding = {}
        for (pname, pty), a in zip(udf.parameters, args):
            if a.type != pty:
                compatible = (a.type.is_numeric and pty.is_numeric) or                     (a.type.is_string and pty.is_string) or                     a.type == T.UNKNOWN
                if not compatible:
                    raise ValueError(
                        f"{udf.qualified_name} parameter {pname!r} is "
                        f"{pty}, got {a.type}")
                a = E.call("cast", pty, a)
            binding[pname] = a

        body = _substitute_capture_free(body, binding)
        if body.type != udf.return_type:
            body = E.call("cast", udf.return_type, body)
        return body

    def _lambda_func(self, node: P.Func, scope: _Scope) -> E.RowExpression:
        """Array/map higher-order functions (ArrayTransformFunction.java
        family): lambda bodies lower with parameters as LambdaVariables;
        captures stay plain InputReferences of the enclosing scope."""
        name = node.name

        def lower_lambda(lam: P.Lambda, param_types) -> E.Lambda:
            assert len(lam.params) == len(param_types), \
                f"{name} lambda takes {len(param_types)} parameter(s)"
            import copy
            ls = _Scope(dict(scope.channels), list(scope.types))
            ls.lambda_vars = {**(getattr(scope, "lambda_vars", None) or {}),
                              **dict(zip(lam.params, param_types))}
            body = self.lower(lam.body, ls)
            return E.Lambda(body.type, tuple(lam.params), body)

        arr = self.lower(node.args[0], scope)
        if arr.type.base == "map":
            kty, vty = arr.type.key_type, arr.type.value_type
            if name == "transform_values":
                lam = lower_lambda(node.args[1], [kty, vty])
                return E.call("transform_values", T.map_of(kty, lam.type),
                              arr, lam)
            if name == "transform_keys":
                lam = lower_lambda(node.args[1], [kty, vty])
                return E.call("transform_keys", T.map_of(lam.type, vty),
                              arr, lam)
            if name == "map_filter":
                lam = lower_lambda(node.args[1], [kty, vty])
                return E.call("map_filter", arr.type, arr, lam)
            raise NotImplementedError(f"lambda function {name!r} over map")
        if arr.type.base != "array":
            raise NotImplementedError(f"{name} over {arr.type}")
        ety = arr.type.element_type
        if name == "transform":
            lam = lower_lambda(node.args[1], [ety])
            return E.call("transform", T.array_of(lam.type), arr, lam)
        if name == "filter":
            lam = lower_lambda(node.args[1], [ety])
            return E.call("filter", arr.type, arr, lam)
        if name in ("any_match", "all_match", "none_match"):
            lam = lower_lambda(node.args[1], [ety])
            return E.call(name, T.BOOLEAN, arr, lam)
        if name == "reduce":
            init = self.lower(node.args[1], scope)
            comb = lower_lambda(node.args[2], [init.type, ety])
            if comb.type != init.type:
                raise NotImplementedError(
                    "reduce state type must stay fixed "
                    f"({init.type} vs {comb.type})")
            out = lower_lambda(node.args[3], [init.type])
            return E.call("reduce", out.type, arr, init, comb, out)
        raise NotImplementedError(f"lambda function {name!r}")

    def _func_type(self, name: str, args: List[E.RowExpression]) -> T.Type:
        if name in ("timezone_hour", "timezone_minute"):
            if args[0].type.base != "timestamp with time zone":
                raise NotImplementedError(
                    f"{name} needs TIMESTAMP WITH TIME ZONE, "
                    f"got {args[0].type}")
            return T.BIGINT
        if name in ("year", "month", "day", "quarter", "length", "strpos",
                    "position", "codepoint", "day_of_week", "day_of_year",
                    "date_diff", "sign", "hour", "minute", "second",
                    "millisecond", "json_array_length", "json_size",
                    "crc32", "regexp_position", "regexp_count"):
            return T.BIGINT
        if name == "at_timezone":
            return T.TIMESTAMP_TZ
        if name in ("json_parse", "json_extract"):
            return T.JSON
        if name == "json_format":
            return T.varchar(args[0].type.max_length)
        if name == "json_extract_scalar":
            return T.varchar(args[0].type.max_length)
        if name in ("json_array_contains", "is_json_scalar"):
            return T.BOOLEAN
        if name in ("regexp_extract", "regexp_replace"):
            return T.varchar()
        if name == "to_hex":
            w = args[0].type.max_length
            return T.varchar(2 * w if w < T.UNBOUNDED_LENGTH else w)
        if name in ("from_hex", "to_utf8", "md5", "sha1", "sha256",
                    "sha512"):
            return T.VARBINARY
        if name == "from_utf8":
            return T.varchar(args[0].type.max_length)
        if name in ("upper", "lower", "trim", "ltrim", "rtrim", "reverse",
                    "substr", "split_part"):
            return args[0].type
        if name == "regexp_like":
            return T.BOOLEAN
        if name == "date_format":
            width = 32
            if isinstance(args[1], E.Constant):
                from ..expr.functions import date_format_width
                width = date_format_width(str(args[1].value))
            return T.varchar(width)
        if name == "concat":
            width = sum(a.type.max_length if a.type.is_string else 8
                        for a in args)
            return T.varchar(width)
        if name == "great_circle_distance":
            return T.DOUBLE
        if name in ("bing_tile_x", "bing_tile_y"):
            return T.BIGINT
        if name == "bing_tile_quadkey_at":
            return T.varchar(23)
        if name in ("sqrt", "exp", "ln", "log10", "power", "pow",
                    "sin", "cos", "tan", "asin", "acos", "atan", "atan2",
                    "sinh", "cosh", "tanh", "cbrt", "log2", "log",
                    "degrees", "radians", "to_unixtime"):
            return T.DOUBLE
        if name in ("is_nan", "is_finite", "is_infinite", "ends_with"):
            return T.BOOLEAN
        if name in ("bitwise_and", "bitwise_or", "bitwise_xor",
                    "bitwise_not", "bitwise_left_shift",
                    "bitwise_right_shift", "bitwise_right_shift_arithmetic",
                    "bit_count", "array_position"):
            return T.BIGINT
        if name == "array_sum":
            ety = args[0].type.element_type
            return T.DOUBLE if ety.is_floating else T.BIGINT
        if name == "mod":
            return args[0].type
        if name == "from_unixtime":
            return T.TIMESTAMP
        if name in ("abs", "negate", "floor", "ceil", "ceiling", "round",
                    "truncate", "greatest", "least"):
            return args[0].type
        if name in ("date_trunc", "last_day_of_month", "date_add"):
            return T.DATE
        if name in ("like", "starts_with", "is_distinct_from", "not"):
            return T.BOOLEAN
        if name == "chr":
            return T.varchar(1)
        if name == "cast":
            return args[0].type
        if name == "cardinality":
            return T.BIGINT
        if name == "array_constructor":
            ety = _case_result_type(args) if args else T.UNKNOWN
            return T.array_of(ety)
        if name == "sequence":
            return T.array_of(T.BIGINT)
        if name in ("array_distinct", "array_sort", "slice"):
            return args[0].type
        if name == "element_at":
            t0 = args[0].type
            if t0.base == "map":
                return t0.value_type
            if t0.base == "array":
                return t0.element_type
            raise NotImplementedError(f"element_at over {t0}")
        if name == "contains":
            return T.BOOLEAN
        if name == "map_keys":
            return T.array_of(args[0].type.key_type)
        if name == "map_values":
            return T.array_of(args[0].type.value_type)
        raise NotImplementedError(f"no type rule for function {name!r}")

    # -- aggregate detection ------------------------------------------------

    def find_aggs(self, node, window_args: bool = False) -> List[P.Func]:
        """Collect group-aggregate calls. Window expressions are NOT
        group aggregates themselves; with window_args=True (a GROUP BY
        is present) the aggregates INSIDE a window's arguments/clauses
        are collected (q53's avg(sum(x)) OVER shape), else the whole
        window subtree is skipped (q12's sum(x) OVER over detail rows)."""
        out = []

        def walk(n):
            if isinstance(n, P.WindowExpr):
                if window_args:
                    for a in n.func.args:
                        if dataclasses.is_dataclass(a):
                            walk(a)
                    for p in n.partition_by:
                        if dataclasses.is_dataclass(p):
                            walk(p)
                    for o in n.order_by:
                        if dataclasses.is_dataclass(o.expr):
                            walk(o.expr)
                return
            if isinstance(n, (P.InSubquery, P.Exists, P.ScalarSubquery)):
                return  # subqueries aggregate in their own scope
            if isinstance(n, P.Func) and n.name in _AGG_NAMES:
                out.append(n)
                return  # no nested aggs
            for f in dataclasses.fields(n) if dataclasses.is_dataclass(n) else []:
                v = getattr(n, f.name)
                if dataclasses.is_dataclass(v):
                    walk(v)
                elif isinstance(v, (list, tuple)):
                    for x in v:
                        if dataclasses.is_dataclass(x):
                            walk(x)
                        elif isinstance(x, tuple):
                            for y in x:
                                if dataclasses.is_dataclass(y):
                                    walk(y)
        if dataclasses.is_dataclass(node):
            walk(node)
        return out


# UDF names whose expansion is in progress (recursion detection)
_UDF_EXPANDING: contextvars.ContextVar = contextvars.ContextVar(
    "udf_expanding", default=frozenset())

_FRESH = [0]


def _free_lambda_vars(e) -> set:
    """Names of LambdaVariables FREE in `e` (not bound by a Lambda
    inside `e`)."""
    if isinstance(e, E.LambdaVariable):
        return {e.name}
    if isinstance(e, E.Lambda):
        return _free_lambda_vars(e.body) - set(e.parameters)
    out = set()
    for c in e.children():
        out |= _free_lambda_vars(c)
    return out


def _rename_lambda_vars(e, mapping: dict):
    """Alpha-rename: LambdaVariable occurrences of `mapping` keys take
    the new names; inner lambdas rebinding a key shadow it."""
    if isinstance(e, E.LambdaVariable):
        if e.name in mapping:
            return E.LambdaVariable(e.type, mapping[e.name])
        return e
    if isinstance(e, E.Lambda):
        inner = {k: v for k, v in mapping.items()
                 if k not in e.parameters}
        nb = _rename_lambda_vars(e.body, inner) if inner else e.body
        return e if nb is e.body else E.Lambda(e.type, e.parameters, nb)
    if isinstance(e, E.Call):
        na = tuple(_rename_lambda_vars(x, mapping) for x in e.arguments)
        return e if na == e.arguments else E.Call(e.type, e.name, na)
    if isinstance(e, E.SpecialForm):
        na = tuple(_rename_lambda_vars(x, mapping) for x in e.arguments)
        return e if na == e.arguments else \
            E.SpecialForm(e.type, e.form, na)
    return e


def _substitute_capture_free(e, bnd: dict):
    """Capture-avoiding substitution of LambdaVariables: (a) lambda
    parameters shadowing a binding key bind tighter (the key is not
    substituted inside), and (b) lambda parameters colliding with a
    FREE variable of a substituted value are alpha-renamed first, so a
    caller's lambda variable is never captured by a UDF body lambda."""
    if isinstance(e, E.LambdaVariable):
        return bnd.get(e.name, e)
    if isinstance(e, E.Lambda):
        inner = {k: v for k, v in bnd.items() if k not in e.parameters}
        if not inner:
            return e
        free = set()
        for v in inner.values():
            free |= _free_lambda_vars(v)
        ren = {}
        params = list(e.parameters)
        for i, pname in enumerate(params):
            if pname in free:
                _FRESH[0] += 1
                ren[pname] = f"{pname}__a{_FRESH[0]}"
                params[i] = ren[pname]
        body = _rename_lambda_vars(e.body, ren) if ren else e.body
        nb = _substitute_capture_free(body, inner)
        if nb is e.body and not ren:
            return e
        return E.Lambda(e.type, tuple(params), nb)
    if isinstance(e, E.Call):
        na = tuple(_substitute_capture_free(x, bnd) for x in e.arguments)
        return e if na == e.arguments else E.Call(e.type, e.name, na)
    if isinstance(e, E.SpecialForm):
        na = tuple(_substitute_capture_free(x, bnd) for x in e.arguments)
        return e if na == e.arguments else \
            E.SpecialForm(e.type, e.form, na)
    return e


def _dt_plus_interval_type(dt: T.Type, iv: T.Type) -> T.Type:
    """Result type of datetime + interval: every datetime keeps its
    type (DateTimeOperators.java -- date + interval day-to-second stays
    DATE; sub-day components are rejected at plan time in _binop, the
    'Cannot add hour, minutes or seconds to a date' rule)."""
    return dt


def _parse_ts_literal(s: str):
    """TIMESTAMP 'YYYY-MM-DD hh:mm:ss[.fff][ zone]' -> (utc_micros,
    zone_key or None)."""
    import datetime as _dt
    import re as _re
    from .. import tz as _tz
    s = s.strip()
    key = None
    m = _re.match(r"^(.*?)(?:\s+([A-Za-z_/]+(?:/[A-Za-z_]+)?)|"
                  r"\s*([+-]\d{2}:?\d{2}))$", s)
    body = s
    if m and (m.group(2) or m.group(3)):
        try:
            key = _tz.zone_key(m.group(2) or m.group(3))
            body = m.group(1).strip()
        except ValueError:
            key = None  # not a zone suffix after all
    if " " not in body and "T" not in body:
        body += " 00:00:00"
    d = _dt.datetime.fromisoformat(body)
    micros = (int(_dt.datetime(d.year, d.month, d.day,
                               tzinfo=_dt.timezone.utc).timestamp())
              * 1_000_000
              + (d.hour * 3600 + d.minute * 60 + d.second) * 1_000_000
              + d.microsecond)
    if key is not None:
        # wall clock in `zone` -> UTC instant
        micros -= (key - _tz.UTC_KEY) * 60_000_000
    return micros, key


def _parse_time_literal(s: str) -> int:
    import datetime as _dt
    t = _dt.time.fromisoformat(s.strip())
    return ((t.hour * 3600 + t.minute * 60 + t.second) * 1_000_000
            + t.microsecond)


def _agg_output_type(name: str, input_type: Optional[T.Type]) -> T.Type:
    if name == "count" or name == "approx_distinct":
        return T.BIGINT
    if name in ("bool_and", "bool_or", "every"):
        return T.BOOLEAN
    if name in ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp",
                "var_pop", "corr", "covar_samp", "covar_pop", "regr_slope",
                "regr_intercept", "geometric_mean"):
        return T.DOUBLE
    if name == "checksum":
        return T.BIGINT
    if name == "sum":
        if input_type.is_decimal:
            return T.decimal(38, input_type.scale)
        if input_type.is_floating:
            return T.DOUBLE
        return T.BIGINT
    if name == "avg":
        if input_type.is_decimal:
            return T.decimal(38, input_type.scale)
        return T.DOUBLE
    return input_type  # min/max/arbitrary


# Session catalog search path (the reference resolves unqualified table
# names against the session catalog/schema; `USE tpcds.sf1` analog).
_SEARCH_PATH: contextvars.ContextVar = contextvars.ContextVar(
    "search_path", default=("tpch", "tpcds", "memory"))

# CTE plan-once cache, scoped to one plan_sql call: the parser inlines a
# WITH binding as the SAME Query AST object at every reference, so
# planning memoizes on that object identity and all references share ONE
# plan subtree. The plan becomes a DAG; lowering traces shared nodes
# once (exec/planner memoizes by node identity), so a CTE referenced k
# times is scanned and computed once -- the LogicalCteOptimizer analog,
# realized by compiler-level sharing instead of materialization.
# one clock read per statement: every now()/current_* occurrence in a
# statement sees the SAME instant (the reference fixes the session start
# time per query)
_STMT_NOW_US: contextvars.ContextVar = contextvars.ContextVar(
    "stmt_now_us", default=None)


def _statement_now_us() -> int:
    v = _STMT_NOW_US.get()
    if v is None:
        import time
        v = time.time_ns() // 1000
    return v


_SUBPLAN_CACHE: contextvars.ContextVar = contextvars.ContextVar(
    "subplan_cache", default=None)


def plan_sql(query_text: str, max_groups: int = 1 << 16,
             join_capacity: Optional[int] = None,
             catalog: Optional[str] = None) -> N.PlanNode:
    """SQL text -> plan tree rooted at OutputNode. `catalog` moves that
    catalog to the front of the table-name search path."""
    ast = P.parse_sql(query_text)
    token = None
    if catalog is not None:
        path = (catalog,) + tuple(c for c in _SEARCH_PATH.get()
                                  if c != catalog)
        token = _SEARCH_PATH.set(path)
    cache_token = _SUBPLAN_CACHE.set({})
    import time as _time
    now_token = _STMT_NOW_US.set(_time.time_ns() // 1000)
    try:
        if isinstance(ast, (P.Insert, P.CreateTableAs, P.DropTable,
                            P.Delete, P.Update)):
            return _plan_write(ast, max_groups, join_capacity)
        node, names = _plan_any(ast, max_groups, join_capacity)
    finally:
        _SUBPLAN_CACHE.reset(cache_token)
        _STMT_NOW_US.reset(now_token)
        if token is not None:
            _SEARCH_PATH.reset(token)
    if isinstance(node, N.OutputNode):
        return node
    return N.OutputNode(node, names)


def _writable_target(name: str):
    """'memory.t' or bare 't' -> (connector, table). Writable catalogs
    expose the sink contract (begin_insert/...; ConnectorPageSink
    analog): memory and parquet; the generator connectors stay
    read-only, like the reference's tpch/tpcds connectors."""
    if "." in name:
        conn, table = name.split(".", 1)
    else:
        conn, table = "memory", name
    from ..connectors import catalog as get_cat
    try:
        writable = hasattr(get_cat(conn), "begin_insert")
    except KeyError:
        writable = False
    if not writable:
        raise NotImplementedError(
            f"catalog {conn!r} is read-only; writes go to the memory "
            "or parquet connectors")
    return conn, table


def _plan_write(ast, max_groups: int, join_capacity):
    """INSERT / CTAS / DROP TABLE -> TableWriter/TableFinish/Ddl plans
    (LogicalPlanner.createTableWriterPlan / DataDefinitionTask analog)."""
    from ..connectors import catalog as get_catalog

    if isinstance(ast, P.DropTable):
        conn, table = _writable_target(ast.table)
        return N.OutputNode(N.DdlNode("drop_table", conn, table,
                                      ast.if_exists), ["result"])

    if isinstance(ast, (P.Delete, P.Update)):
        # DELETE/UPDATE as table rewrites: the source computes the
        # table's columns + a trailing BOOLEAN `changed`
        # (NULL predicate = not changed, SQL's WHERE semantics)
        conn, table = _writable_target(ast.table)
        try:
            schema = get_catalog(conn).SCHEMA[table]
        except KeyError:
            raise KeyError(f"memory table {table!r} does not exist") \
                from None
        cols = list(schema)
        tys = [schema[c] for c in cols]
        scan = N.TableScanNode(conn, table, cols, tys)
        bare = table
        chans = {}
        for i, c in enumerate(cols):
            chans[c] = i
            chans[f"{bare}.{c}"] = i
            chans[f"{conn}.{bare}.{c}"] = i
        scope = _Scope(chans, tys)
        an = _Analyzer(None)
        if ast.where is None:
            changed = E.const(True, T.BOOLEAN)
        else:
            p = an.lower(ast.where, scope)
            changed = E.special("COALESCE", T.BOOLEAN, p,
                                E.const(False, T.BOOLEAN))
        if isinstance(ast, P.Delete):
            exprs = [E.input_ref(i, tys[i]) for i in range(len(cols))]
        else:
            assigns = {}
            for c, e in ast.assignments:
                if c not in schema:
                    raise KeyError(f"column {c!r} not in table {table!r}")
                ne = an.lower(e, scope)
                if ne.type != schema[c]:
                    ne = E.call("cast", schema[c], ne)
                assigns[c] = ne
            exprs = []
            for i, c in enumerate(cols):
                old = E.input_ref(i, tys[i])
                if c in assigns:
                    exprs.append(E.special("IF", tys[i], changed,
                                           assigns[c], old))
                else:
                    exprs.append(old)
        proj = N.ProjectNode(scan, exprs + [changed])
        node = N.TableRewriteNode(
            proj, conn, table,
            "delete" if isinstance(ast, P.Delete) else "update")
        return N.OutputNode(node, ["rows"])

    if isinstance(ast, P.CreateTableAs):
        conn, table = _writable_target(ast.table)
        if ast.if_not_exists and table in get_catalog(conn).SCHEMA:
            # no-op create: zero rows written (reference behavior)
            return N.OutputNode(N.ValuesNode([T.BIGINT], [[0]]), ["rows"])
        node, names = _plan_any(ast.query, max_groups, join_capacity)
        node = _strip_output(node)
        types = node.output_types()
        writer = N.TableWriterNode(node, conn, table, list(names))
        finish = N.TableFinishNode(writer, conn, table, create=True,
                                   create_columns=list(names),
                                   create_types=list(types))
        return N.OutputNode(finish, ["rows"])

    # INSERT
    conn, table = _writable_target(ast.table)
    mod = get_catalog(conn)
    try:
        schema = mod.SCHEMA[table]
    except KeyError:
        raise KeyError(f"memory table {table!r} does not exist") from None
    target_cols = list(schema)
    target_types = [schema[c] for c in target_cols]
    insert_cols = ast.columns or target_cols
    for c in insert_cols:
        if c not in schema:
            raise KeyError(f"column {c!r} not in table {table!r}")

    if isinstance(ast.query, P.ValuesRows):
        an = _Analyzer(None)
        scope = _Scope({}, [])
        rows = []
        for row in ast.query.rows:
            if len(row) != len(insert_cols):
                raise ValueError(
                    f"INSERT row arity {len(row)} != column count "
                    f"{len(insert_cols)}")
            rows.append([an.lower(cell, scope) for cell in row])
        # VALUES rows lower to constants; ship them as a ValuesNode in
        # INSERT-column order
        const_rows = []
        for row in rows:
            vals = []
            for e in row:
                if not isinstance(e, E.Constant):
                    raise NotImplementedError(
                        "INSERT ... VALUES cells must be literals")
                vals.append(e)
            const_rows.append(vals)
        src_types = [_common_values_type([r[i] for r in const_rows],
                                         schema[insert_cols[i]])
                     for i in range(len(insert_cols))]
        node = N.ValuesNode(
            src_types,
            [[_coerce_const(e, ty) for e, ty in zip(r, src_types)]
             for r in const_rows])
        names = list(insert_cols)
    else:
        node, names = _plan_any(ast.query, max_groups, join_capacity)
        node = _strip_output(node)
        if len(node.output_types()) != len(insert_cols):
            raise ValueError(
                f"INSERT query produces {len(node.output_types())} "
                f"columns, expected {len(insert_cols)}")

    # project to the FULL target layout: insert columns from the query
    # (cast to the declared type), unmentioned columns as typed NULLs
    src_types = node.output_types()
    exprs = []
    for c, ty in zip(target_cols, target_types):
        if c in insert_cols:
            ch = insert_cols.index(c)
            e = E.input_ref(ch, src_types[ch])
            if src_types[ch] != ty:
                e = E.call("cast", ty, e)
            exprs.append(e)
        else:
            exprs.append(E.const(None, ty))
    proj = N.ProjectNode(node, exprs)
    writer = N.TableWriterNode(proj, conn, table, target_cols)
    # the GATHER seam lets the fragmenter fan writers out per worker
    # while the finish (count sum) runs once (ScaledWriterScheduler's
    # writer-stage/commit-stage split, minus the scaling policy)
    gather = N.ExchangeNode(writer, kind="GATHER", scope="REMOTE")
    finish = N.TableFinishNode(gather, conn, table)
    return N.OutputNode(finish, ["rows"])


def _common_values_type(consts, target_ty: T.Type) -> T.Type:
    """Type a VALUES column: the target type when every literal can
    coerce to it, else the literals' own type."""
    return target_ty


def _coerce_const(e: "E.Constant", ty: T.Type):
    """Literal -> target-type python value (the implicit INSERT
    coercions: integer->decimal scaling, string width, date)."""
    v = e.value
    if v is None:
        return None
    if ty.is_decimal:
        if e.type.is_decimal:
            return v * 10 ** (ty.scale - e.type.scale) \
                if ty.scale >= e.type.scale else \
                _exact_downscale(v, e.type.scale - ty.scale)
        if e.type.is_integral:
            return int(v) * 10 ** ty.scale
        raise TypeError(f"cannot coerce {e.type} literal to {ty}")
    if ty.is_integral or ty.base in ("date", "timestamp"):
        return int(v)
    if ty.is_floating:
        return float(v)
    return v


def _exact_downscale(v: int, drop: int) -> int:
    q, r = divmod(v, 10 ** drop)
    if r:
        raise ValueError(f"literal loses precision at scale -{drop}")
    return q


def _plan_any(ast, max_groups: int, join_capacity: Optional[int]):
    """Query | SetQuery -> (plan node, output names)."""
    if isinstance(ast, P.SetQuery):
        lf, ln = _plan_any(ast.left, max_groups, join_capacity)
        rt, rn = _plan_any(ast.right, max_groups, join_capacity)
        lf = _strip_output(lf)
        rt = _strip_output(rt)
        lt, rtt = lf.output_types(), rt.output_types()
        ncols = len(lt)
        assert ncols == len(rtt), "set operation requires equal column counts"
        for i, (a, b) in enumerate(zip(lt, rtt)):
            assert a.base == b.base or (a.is_numeric and b.is_numeric), \
                f"set operation column {i} type mismatch: {a} vs {b}"
        if ast.op == "union":
            node = N.UnionNode([lf, rt])
            if not ast.all:
                node = N.DistinctNode(node, max_groups=max_groups)
            return node, ln
        # INTERSECT / EXCEPT. Set semantics: distinct left, membership
        # test against right over all channels (NULLs compare EQUAL).
        # Bag (ALL) semantics: tag every row with its occurrence index
        # (row_number over the full row), then the SAME membership test
        # on (row, occurrence) keeps/drops exactly min/excess
        # multiplicities -- the classic tagging decorrelation.
        if ast.all:
            all_chs = list(range(ncols))
            lf = N.RowNumberNode(lf, all_chs, [], max_partitions=max_groups)
            rt = N.RowNumberNode(rt, all_chs, [], max_partitions=max_groups)
            key_chs = all_chs + [ncols]  # row + occurrence tag
            left_in = lf
        else:
            key_chs = list(range(ncols))
            left_in = N.DistinctNode(lf, max_groups=max_groups)
        sj = N.SemiJoinNode(left_in, rt, key_chs, key_chs,
                            null_keys_match=True)
        mask_ch = len(left_in.output_types())
        mask = E.input_ref(mask_ch, T.BOOLEAN)
        pred = mask if ast.op == "intersect" else \
            E.call("not", T.BOOLEAN, mask)
        f = N.FilterNode(sj, pred)
        proj = N.ProjectNode(f, [
            E.input_ref(i, lt[i]) for i in range(ncols)])
        return proj, ln
    return _plan_query(ast, max_groups, join_capacity)


def _strip_output(node: N.PlanNode) -> N.PlanNode:
    return node.source if isinstance(node, N.OutputNode) else node


def _is_single_row(node: N.PlanNode) -> bool:
    """Provably AT-MOST-one-row plan: a global (keyless) aggregation
    under row-count-preserving-or-reducing wrappers. A const-key inner
    join against such a side IS the cross product (0 or 1 matches per
    probe row), so the q61/q90-style scalar-report cross joins are
    safe."""
    if isinstance(node, (N.ProjectNode, N.OutputNode, N.FilterNode,
                         N.LimitNode)):
        return _is_single_row(node.sources[0])
    return (isinstance(node, N.AggregationNode)
            and not node.group_channels
            and node.step in ("SINGLE", "FINAL"))


def _expand_grouping_sets(q: P.Query):
    """ROLLUP/CUBE/GROUPING SETS -> (query with flattened GROUP BY,
    kept-index subsets). The single-pass GroupIdNode expansion replaces
    the k+1-pass UNION rewrite (match: spi/plan/GroupIdNode.java via
    StatementAnalyzer's grouping-set analysis)."""
    g = q.group_by[0]
    if isinstance(g, P.Rollup):
        items = list(g.items)
        sets = [list(range(k)) for k in range(len(items), -1, -1)]
    elif isinstance(g, P.Cube):
        import itertools
        items = list(g.items)
        idx = range(len(items))
        sets = [list(c) for r in range(len(items), -1, -1)
                for c in itertools.combinations(idx, r)]
    else:  # GroupingSets
        items = []
        sets = []
        for s in g.sets:
            one = []
            for e in s:
                for i, it in enumerate(items):
                    if it == e:
                        one.append(i)
                        break
                else:
                    items.append(e)
                    one.append(len(items) - 1)
            sets.append(one)
    return dataclasses.replace(q, group_by=items), sets


def _plan_query(q: P.Query, max_groups: int = 1 << 16,
                join_capacity: Optional[int] = None) -> N.PlanNode:
    grouping_sets = None
    if len(q.group_by) == 1 and isinstance(
            q.group_by[0], (P.Rollup, P.Cube, P.GroupingSets)):
        q, grouping_sets = _expand_grouping_sets(q)
    an = _Analyzer(q)

    # FROM: scans with pruned columns. First collect every referenced name.
    tables: List[P.TableRef] = [q.table] + [j.table for j in q.joins]

    def find_table(name: str):
        # resolution follows the session catalog search path (the
        # reference resolves unqualified names against the session's
        # catalog/schema; both catalogs define e.g. `customer`, and the
        # earlier catalog in the path wins deterministically). A dotted
        # name ("memory.t") names the catalog explicitly.
        from ..connectors import catalogs
        cats = catalogs()
        if "." in name:
            cat, bare = name.split(".", 1)
            if cat not in cats:
                raise KeyError(f"unknown catalog {cat!r}")
            sch = cats[cat].SCHEMA
            if bare not in sch:
                raise KeyError(f"table {bare!r} not in catalog {cat!r}")
            return cat, bare, dict(sch[bare])
        search_path = _SEARCH_PATH.get()
        for cat in search_path:
            sch = cats[cat].SCHEMA
            if name in sch:
                return cat, name, dict(sch[name])
        raise KeyError(f"table {name!r} not found in catalogs {search_path}")

    table_catalog = {}
    table_schemas = {}
    derived_plans: Dict[str, Tuple[N.PlanNode, List[str]]] = {}
    for t in tables:
        if t.subquery is not None:
            # derived table / inlined CTE: plan the sub-select; its
            # output names+types form the "schema". A CTE referenced
            # more than once shares ONE planned subtree (plan-once
            # cache keyed on AST object identity -- see _SUBPLAN_CACHE)
            cache = _SUBPLAN_CACHE.get()
            hit = cache.get(id(t.subquery)) if cache is not None else None
            if hit is not None:
                sub_node, sub_names = hit
            else:
                sub_node, sub_names = _plan_any(t.subquery, max_groups,
                                                join_capacity)
                sub_node = _strip_output(sub_node)
                if cache is not None:
                    cache[id(t.subquery)] = (sub_node, sub_names)
            sub_types = sub_node.output_types()
            table_catalog[t.name] = None
            table_schemas[t.name] = {n.lower(): ty for n, ty in
                                     zip(sub_names, sub_types)}
            derived_plans[t.name] = (sub_node,
                                     [n.lower() for n in sub_names])
        elif t.name == "$dual":
            # FROM-less SELECT: a one-row zero-column source (the
            # reference's single-row ValuesNode for SELECT <exprs>)
            table_catalog[t.name] = None
            table_schemas[t.name] = {}
            derived_plans[t.name] = (N.ValuesNode([], [[]]), [])
        else:
            cat, bare, sch = find_table(t.name)
            table_catalog[t.name] = (cat, bare)
            table_schemas[t.name] = sch

    referenced: Dict[str, List[str]] = {t.name: [] for t in tables}

    def note_name(parts: Tuple[str, ...]):
        parts = tuple(p.lower() for p in parts)
        if len(parts) == 2:
            alias, col = parts
            for t in tables:
                if (t.alias or t.name) == alias and col in table_schemas[t.name]:
                    if col not in referenced[t.name]:
                        referenced[t.name].append(col)
                    return
            raise KeyError(f"unknown qualified column {'.'.join(parts)}")
        col = parts[0]
        hits = [t for t in tables if col in table_schemas[t.name]]
        if not hits:
            raise KeyError(f"unknown column {col}")
        if len(hits) > 1:
            raise KeyError(f"ambiguous column {col}")
        if col not in referenced[hits[0].name]:
            referenced[hits[0].name].append(col)

    def collect_names(n, shadowed=frozenset()):
        if isinstance(n, P.Name):
            if len(n.parts) == 1 and n.parts[0].lower() in shadowed:
                return  # a lambda parameter, not a column
            note_name(n.parts)
        elif isinstance(n, P.Lambda):
            collect_names(n.body,
                          shadowed | {p.lower() for p in n.params})
        elif isinstance(n, P.InSubquery):
            collect_names(n.value)  # the subquery has its own table scope
        elif isinstance(n, P.ScalarSubquery):
            # self-contained except for correlated equalities
            _note_correlated(n.query, note_name)
        elif isinstance(n, P.Exists):
            _note_correlated(n.query, note_name)
        elif dataclasses.is_dataclass(n):
            for f in dataclasses.fields(n):
                v = getattr(n, f.name)
                if dataclasses.is_dataclass(v):
                    collect_names(v, shadowed)
                elif isinstance(v, (list, tuple)):
                    for x in v:
                        if dataclasses.is_dataclass(x):
                            collect_names(x, shadowed)
                        elif isinstance(x, tuple):
                            for y in x:
                                if dataclasses.is_dataclass(y):
                                    collect_names(y, shadowed)

    for item in q.select.items:
        collect_names(item.expr)
    for j in q.joins:
        collect_names(j.condition)
    aliases = {(_item_name(it, i)) for i, it in enumerate(q.select.items)}
    for e in ([q.where] if q.where else []) + q.group_by + \
            ([q.having] if q.having else []):
        collect_names(e)
    for o in q.order_by:
        # select aliases shadow table columns in ORDER BY scope
        if isinstance(o.expr, P.Name) and len(o.expr.parts) == 1 and \
                o.expr.parts[0].lower() in aliases:
            continue
        collect_names(o.expr)

    # -- WHERE-conjunct classification: predicate pushdown + join graph --
    # The PredicatePushDown / EliminateCrossJoins analog
    # (sql/planner/optimizations/PredicatePushDown.java,
    # iterative/rule/EliminateCrossJoins.java): for all-inner queries,
    # single-table WHERE conjuncts are planned as filters directly above
    # that table's scan, and two-table column equalities become edges of
    # a join graph. Comma-style FROM lists (the TPC-DS benchmark shape)
    # are joined greedily over that graph -- largest table first (it
    # stays the probe side; each dimension becomes a build side),
    # smallest connected candidate next -- so generated query text never
    # plans a cross product or builds on the fact table.
    all_inner = all(j.kind in ("inner", "cross") for j in q.joins)
    has_cross = any(j.kind == "cross" for j in q.joins)
    alias_list = [(t.alias or t.name) for t in tables]

    def _resolve_alias(parts) -> Optional[Tuple[str, str]]:
        parts = tuple(p.lower() for p in parts)
        if len(parts) == 2:
            a, col = parts
            for t in tables:
                if (t.alias or t.name) == a and col in table_schemas[t.name]:
                    return a, col
            return None
        col = parts[0]
        hits = [t for t in tables if col in table_schemas[t.name]]
        if len(hits) == 1:
            return (hits[0].alias or hits[0].name), col
        return None

    def _names_in(n, out: List[P.Name]) -> bool:
        """Collect every Name under `n`; False if a subquery lurks."""
        if isinstance(n, (P.InSubquery, P.ScalarSubquery, P.Exists)):
            return False
        if isinstance(n, P.Name):
            out.append(n)
            return True
        ok = True
        if dataclasses.is_dataclass(n):
            for f in dataclasses.fields(n):
                v = getattr(n, f.name)
                for x in (v if isinstance(v, (list, tuple)) else [v]):
                    if isinstance(x, tuple):
                        for y in x:
                            if dataclasses.is_dataclass(y):
                                ok = _names_in(y, out) and ok
                    elif dataclasses.is_dataclass(x):
                        ok = _names_in(x, out) and ok
        return ok

    pushed: Dict[str, list] = {a: [] for a in alias_list}
    edges: List[Tuple[str, str, str, str]] = []
    where_rest: list = []

    def _classify(c, allow_edges: bool):
        if isinstance(c, P.BinOp) and c.op == "or":
            # hoist branch-common conjuncts (join predicates hide inside
            # every OR branch in TPC-DS text -- q13/q25/q48 shape)
            common, rest = _extract_common_or(c)
            if common:
                for x in common:
                    _classify(x, allow_edges)
                if rest is not None:
                    _classify(rest, allow_edges)
                return
        names: List[P.Name] = []
        if not _names_in(c, names):
            where_rest.append(c)
            return
        resolved = [_resolve_alias(nm.parts) for nm in names]
        if any(r is None for r in resolved) or not resolved:
            where_rest.append(c)
            return
        aliases_here = {r[0] for r in resolved}
        if len(aliases_here) == 1:
            pushed[next(iter(aliases_here))].append(c)
            return
        if allow_edges and len(aliases_here) == 2 and \
                isinstance(c, P.BinOp) and c.op == "=" and \
                isinstance(c.left, P.Name) and isinstance(c.right, P.Name):
            la, lc = _resolve_alias(c.left.parts)
            ra, rc = _resolve_alias(c.right.parts)
            edges.append((la, lc, ra, rc))
            return
        where_rest.append(c)

    if all_inner:
        for c in (_conjuncts(q.where) if q.where is not None else []):
            _classify(c, allow_edges=has_cross)
        if has_cross:
            for j in q.joins:
                if j.condition is not None:
                    for c in _conjuncts(j.condition):
                        _classify(c, allow_edges=True)
    else:
        where_rest = _conjuncts(q.where) if q.where is not None else []

    # build scans + running scope over the join chain
    def scan_for(t: P.TableRef) -> Tuple[N.PlanNode, List[str], List[T.Type]]:
        if t.name in derived_plans:
            sub_node, sub_cols = derived_plans[t.name]
            tys = [table_schemas[t.name][c] for c in sub_cols]
            return sub_node, sub_cols, tys
        cols = referenced[t.name] or [next(iter(table_schemas[t.name]))]
        tys = [table_schemas[t.name][c] for c in cols]
        cat, bare = table_catalog[t.name]
        return (N.TableScanNode(cat, bare, cols, tys),
                cols, tys)

    def scan_planned(t: P.TableRef):
        """Scan with this table's pushed-down WHERE filters applied."""
        snode, cols, tys = scan_for(t)
        a = t.alias or t.name
        filters = pushed.get(a, [])
        if filters:
            ch = {f"{a}.{c}": i for i, c in enumerate(cols)}
            for i, c in enumerate(cols):
                ch.setdefault(c, i)
            sc = _Scope(ch, list(tys))
            for c in filters:
                snode = N.FilterNode(snode, an.lower(c, sc))
        return snode, cols, tys

    def make_scope() -> _Scope:
        channels: Dict[str, int] = {}
        seen_unqualified: Dict[str, int] = {}
        for i, (alias, c) in enumerate(scope_entries):
            channels[f"{alias}.{c}"] = i
            seen_unqualified[c] = seen_unqualified.get(c, 0) + 1
        for i, (alias, c) in enumerate(scope_entries):
            if seen_unqualified[c] == 1:
                channels[c] = i
        return _Scope(channels, types)

    scope_entries: List[Tuple[str, str]] = []
    types: List[T.Type] = []

    if has_cross:
        if not all_inner:
            raise NotImplementedError(
                "comma/CROSS JOIN mixed with outer joins")

        def _weight(t: P.TableRef) -> float:
            if t.subquery is not None:
                return 0.0
            from ..connectors import catalogs as _cats
            try:
                cat, bare = table_catalog[t.name]
                return float(_cats()[cat].table_row_count(bare, 1.0))
            except Exception:
                return 1.0

        start = max(tables, key=_weight)  # ties: first in FROM order
        node, cols0, tys0 = scan_planned(start)
        scope_entries += [((start.alias or start.name), c) for c in cols0]
        types += tys0
        joined = {start.alias or start.name}
        remaining = [t for t in tables if t is not start]
        used_edges: set = set()
        while remaining:
            cands = [t for t in remaining
                     if any((e[0] == (t.alias or t.name) and e[2] in joined)
                            or (e[2] == (t.alias or t.name) and e[0] in joined)
                            for e in edges)]
            if not cands:
                # a PROVABLY single-row side (global-aggregate derived
                # table: the q61/q90/q28 "ratio of two scalar reports"
                # shape) cross-joins via a constant key broadcast -- the
                # row count cannot explode. Anything else is a real
                # cross product and stays rejected.
                single = [t for t in remaining
                          if t.name in derived_plans
                          and _is_single_row(derived_plans[t.name][0])]
                if single:
                    nxt = single[0]
                    a = nxt.alias or nxt.name
                    right, rcols, rtys = scan_planned(nxt)
                    nl = len(types)
                    left_p = N.ProjectNode(node, [
                        E.input_ref(i, types[i]) for i in range(nl)
                    ] + [E.const(0, T.BIGINT)])
                    right_p = N.ProjectNode(right, [
                        E.input_ref(i, rtys[i]) for i in range(len(rtys))
                    ] + [E.const(0, T.BIGINT)])
                    j = N.JoinNode(left_p, right_p, [nl], [len(rtys)],
                                   "inner", "broadcast",
                                   right_output_channels=list(
                                       range(len(rtys))),
                                   out_capacity=join_capacity)
                    node = N.ProjectNode(j, [
                        E.input_ref(i, types[i]) for i in range(nl)
                    ] + [E.input_ref(nl + 1 + i, rtys[i])
                         for i in range(len(rtys))])
                    scope_entries += [(a, c) for c in rcols]
                    types += rtys
                    joined.add(a)
                    remaining.remove(nxt)
                    continue
                raise NotImplementedError(
                    "cross product (no equi-join predicate connects "
                    f"{[t.alias or t.name for t in remaining]} to {joined})")
            nxt = min(cands, key=_weight)
            a = nxt.alias or nxt.name
            right, rcols, rtys = scan_planned(nxt)
            lkeys, rkeys = [], []
            for ei, e in enumerate(edges):
                if ei in used_edges:
                    continue
                la, lc, ra, rc = e
                if la == a and ra in joined:
                    la, lc, ra, rc = ra, rc, la, lc
                if ra == a and la in joined:
                    lkeys.append(scope_entries.index((la, lc)))
                    rkeys.append(rcols.index(rc))
                    used_edges.add(ei)
            if not lkeys:
                raise NotImplementedError(
                    f"join graph edge resolution failed for {a}")
            node = N.JoinNode(node, right, lkeys, rkeys, "inner",
                              "partitioned", out_capacity=join_capacity)
            scope_entries += [(a, c) for c in rcols]
            types += rtys
            joined.add(a)
            remaining.remove(nxt)
        if len(used_edges) != len(edges):
            raise NotImplementedError("unconsumed join-graph edge")
    else:
        node, cols0, tys0 = scan_planned(q.table)
        scope_entries += [((q.table.alias or q.table.name), c) for c in cols0]
        types += tys0

        for j in q.joins:
            right, rcols, rtys = scan_planned(j.table)
            # extract equi-join keys from the ON conjunction
            left_scope = make_scope()
            r_alias = j.table.alias or j.table.name
            r_channels = {f"{r_alias}.{c}": i for i, c in enumerate(rcols)}
            for i, c in enumerate(rcols):
                r_channels.setdefault(c, i)
            conds = _conjuncts(j.condition)
            lkeys, rkeys, residual = [], [], []
            for c in conds:
                if isinstance(c, P.BinOp) and c.op == "=" and \
                        isinstance(c.left, P.Name) and \
                        isinstance(c.right, P.Name):
                    lparts = ".".join(c.left.parts).lower()
                    rparts = ".".join(c.right.parts).lower()
                    if lparts in left_scope.channels and rparts in r_channels:
                        lkeys.append(left_scope.channels[lparts])
                        rkeys.append(r_channels[rparts])
                        continue
                    if rparts in left_scope.channels and lparts in r_channels:
                        lkeys.append(left_scope.channels[rparts])
                        rkeys.append(r_channels[lparts])
                        continue
                residual.append(c)
            assert lkeys, f"no equi-join keys in ON {j.condition}"
            # Residual (non-equi) ON conjuncts: for INNER joins a
            # post-join filter is equivalent; for OUTER joins it is NOT
            # (it would drop the preserved side's unmatched rows), so
            # single-side residuals push below the join onto the
            # NON-preserved side (valid: rows failing them simply do not
            # match) and anything else is rejected. Reference:
            # PredicatePushDown.processInnerJoin/processOuterJoin.
            post_join = []
            r_scope = _Scope(dict(r_channels), list(rtys))
            for r in residual:
                names: List[P.Name] = []
                _names_in(r, names)
                keys_ = [".".join(nm.parts).lower() for nm in names]
                only_right = all(k_ in r_channels for k_ in keys_)
                only_left = all(k_ in left_scope.channels for k_ in keys_)
                if j.kind in ("inner", "left") and only_right:
                    right = N.FilterNode(right, an.lower(r, r_scope))
                elif j.kind in ("inner", "right") and only_left:
                    node = N.FilterNode(node, an.lower(r, left_scope))
                elif j.kind == "inner":
                    post_join.append(r)
                else:
                    raise NotImplementedError(
                        f"{j.kind.upper()} JOIN with a residual ON "
                        f"condition that references the preserved side "
                        f"(it cannot be pushed below the join without "
                        f"dropping unmatched rows): {r}")
            node = N.JoinNode(node, right, lkeys, rkeys, j.kind, "partitioned",
                              out_capacity=join_capacity)
            scope_entries += [(r_alias, c) for c in rcols]
            types += rtys
            scope = make_scope()
            for r in post_join:
                node = N.FilterNode(node, an.lower(r, scope))

    scope = make_scope()

    if where_rest:
        # plain conjuncts first: shrink rows before the semijoin probes
        conjs = where_rest

        _MIRROR = {"<": ">", ">": "<", "<=": ">=", ">=": "<=",
                   "=": "=", "<>": "<>", "!=": "!="}

        def _normalize_scalar_side(c):
            # (SELECT ...) op expr  ->  expr mirrored-op (SELECT ...)
            if isinstance(c, P.BinOp) and c.op in _MIRROR and \
                    isinstance(c.left, P.ScalarSubquery) and \
                    not isinstance(c.right, P.ScalarSubquery):
                return P.BinOp(_MIRROR[c.op], c.right, c.left)
            return c

        conjs = [_normalize_scalar_side(c) for c in conjs]

        def has_scalar_sub(c):
            return isinstance(c, P.BinOp) and \
                isinstance(c.right, P.ScalarSubquery)

        def is_exists(c):
            return isinstance(c, P.Exists) or \
                (isinstance(c, P.NotOp) and isinstance(c.arg, P.Exists))

        def is_disjunctive_sub(c):
            """Subqueries in non-conjunct positions (under OR/CASE/...):
            the q45 `zip IN (...) OR id IN (subquery)` / q10
            `EXISTS(...) OR EXISTS(...)` family."""
            if isinstance(c, P.InSubquery) or has_scalar_sub(c) or \
                    is_exists(c):
                return False
            subs: list = []
            _embedded_subqueries(c, subs)
            return bool(subs)

        for c in [c for c in conjs
                  if not isinstance(c, P.InSubquery) and not has_scalar_sub(c)
                  and not is_exists(c) and not is_disjunctive_sub(c)]:
            node = N.FilterNode(node, an.lower(c, scope))
        for c in [c for c in conjs if is_exists(c)]:
            negate = isinstance(c, P.NotOp)
            ex = c.arg if negate else c
            node = _decorrelate_exists(an, node, scope, tables,
                                       table_schemas, ex.query, negate,
                                       max_groups, join_capacity)
        for c in [c for c in conjs if has_scalar_sub(c)]:
            sub_q2 = c.right.query
            corr, residual2 = ([], [])
            if isinstance(sub_q2, P.Query):
                corr, residual2 = _split_correlations(sub_q2, tables,
                                                      table_schemas)
            if corr:
                node = _decorrelate_scalar_agg(
                    an, node, scope, tables, table_schemas,
                    an.lower(c.left, scope), c.op, sub_q2, max_groups,
                    join_capacity, corr, residual2)
            else:
                node = _attach_scalar_filter(node, an.lower(c.left, scope),
                                             c.op, c.right, max_groups,
                                             join_capacity)
        for c in [c for c in conjs if isinstance(c, P.InSubquery)]:
                # uncorrelated IN subquery -> SemiJoinNode + mask filter
                # (IN-predicate planning, sql/planner's apply/semijoin path)
                sub_node, _sub_names = _plan_any(c.query, max_groups,
                                                 join_capacity)
                sub_node = _strip_output(sub_node)
                assert len(sub_node.output_types()) == 1, \
                    "IN subquery must produce one column"
                v = an.lower(c.value, scope)
                assert isinstance(v, E.InputReference), \
                    "IN subquery value must be a column (round 1)"
                nch = len(scope.types)
                sj = N.SemiJoinNode(node, sub_node, v.channel, 0)
                mask = E.input_ref(nch, T.BOOLEAN)
                # the mask carries IN's 3VL NULL; plain Kleene NOT keeps
                # NOT IN correct (NULL rows fail the filter either way)
                pred = E.call("not", T.BOOLEAN, mask) if c.negate else mask
                f = N.FilterNode(sj, pred)
                node = N.ProjectNode(f, [
                    E.input_ref(i, scope.types[i]) for i in range(nch)])
        for c in [c for c in conjs if is_disjunctive_sub(c)]:
            # subqueries under OR/CASE: plan each as a semijoin MASK
            # column, register the mask against the AST node, lower the
            # whole predicate (masks substitute in), then drop the masks
            # (the reference routes these through ApplyNode ->
            # TransformCorrelatedInPredicateToJoin and keeps the
            # 'subquery as boolean expression' semantics; same here)
            subs: list = []
            _embedded_subqueries(c, subs)
            base_types = node.output_types()
            base_nch = len(base_types)
            cur = base_nch
            for s in subs:
                if isinstance(s, P.InSubquery):
                    sub_node, _ = _plan_any(s.query, max_groups,
                                            join_capacity)
                    sub_node = _strip_output(sub_node)
                    assert len(sub_node.output_types()) == 1, \
                        "IN subquery must produce one column"
                    v = an.lower(s.value, scope)
                    assert isinstance(v, E.InputReference), \
                        "IN subquery value must be a column"
                    node = N.SemiJoinNode(node, sub_node, v.channel, 0)
                    mask = E.input_ref(cur, T.BOOLEAN)
                    an.subquery_masks[id(s)] = \
                        E.call("not", T.BOOLEAN, mask) if s.negate else mask
                elif isinstance(s, P.Exists):
                    sub_q3 = s.query
                    assert isinstance(sub_q3, P.Query), \
                        "EXISTS over set operations: later"
                    if sub_q3.group_by or sub_q3.having is not None:
                        raise NotImplementedError(
                            "EXISTS over GROUP BY in disjunction")
                    corr3, residual3 = _split_correlations(
                        sub_q3, tables, table_schemas)
                    if not corr3:
                        raise NotImplementedError(
                            "uncorrelated EXISTS in disjunction")
                    inner_aliases3 = {(t.alias or t.name).lower()
                                      for t in [sub_q3.table]
                                      + [j.table for j in sub_q3.joins]}
                    if any(_has_outer_name(r, tables, table_schemas,
                                           inner_aliases3, sub_q3)
                           for r in residual3):
                        raise NotImplementedError(
                            "correlated residual predicates under EXISTS "
                            "in disjunction")
                    sub_ast3 = dataclasses.replace(
                        sub_q3,
                        select=P.Select([P.SelectItem(inner, None)
                                         for _, inner in corr3], False),
                        where=_and_all(residual3),
                        order_by=[], limit=None)
                    sub_node, _ = _plan_any(sub_ast3, max_groups,
                                            join_capacity)
                    sub_node = _strip_output(sub_node)
                    outer_chs = [an.lower(nm, scope).channel
                                 for nm, _ in corr3]
                    node = N.SemiJoinNode(node, sub_node, outer_chs,
                                          list(range(len(corr3))))
                    mask = E.input_ref(cur, T.BOOLEAN)
                    # EXISTS is two-valued: a NULL mask (null outer key)
                    # means no match -> FALSE
                    an.subquery_masks[id(s)] = E.special(
                        "COALESCE", T.BOOLEAN, mask,
                        E.const(False, T.BOOLEAN))
                else:  # ScalarSubquery inside an expression (BETWEEN
                    # bounds, arithmetic): attach its single-row value
                    if isinstance(s.query, P.Query):
                        corr_sv, _ = _split_correlations(s.query, tables,
                                                         table_schemas)
                        if corr_sv:
                            raise NotImplementedError(
                                "correlated scalar subquery in "
                                "expression position")
                    node, vty = _attach_scalar_value(node, s, max_groups,
                                                     join_capacity)
                    an.subquery_masks[id(s)] = E.input_ref(cur, vty)
                cur += 1
            pred = an.lower(c, scope)
            node = N.ProjectNode(
                N.FilterNode(node, pred),
                [E.input_ref(i, base_types[i]) for i in range(base_nch)])

    # window expressions (possibly nested inside select items or ORDER
    # BY, over base rows OR over aggregation output)
    win_list: list = []
    for item in q.select.items:
        _collect_windows(item.expr, win_list)
    for o in q.order_by:
        _collect_windows(o.expr, win_list)

    # aggregation? (aggregates inside window ARGUMENTS count when the
    # query aggregates -- a GROUP BY, or any group aggregate outside a
    # window; see find_aggs)
    wargs = bool(q.group_by)
    if not wargs:
        probe = [a for item in q.select.items
                 for a in an.find_aggs(item.expr)]
        probe += an.find_aggs(q.having) if q.having else []
        wargs = bool(probe)
    select_aggs: List[P.Func] = []
    for item in q.select.items:
        select_aggs += an.find_aggs(item.expr, window_args=wargs)
    having_aggs = an.find_aggs(q.having) if q.having else []
    order_aggs = [a for o in q.order_by
                  for a in an.find_aggs(o.expr, window_args=wargs)]
    all_aggs = select_aggs + having_aggs + order_aggs

    if win_list and not (all_aggs or q.group_by):
        # windows over detail rows: plan the stage here; the select
        # items then lower normally with WindowExpr channel intercepts
        node, win_map = _plan_window_stages(
            node, win_list, lambda ast: an.lower(ast, scope))
        an.window_channels.update(win_map)

    if all_aggs or q.group_by:
        node, scope, agg_map, key_map = _plan_aggregation(
            an, node, scope, q, all_aggs, max_groups,
            grouping_sets=grouping_sets)
        node, out_exprs, names, having_e, having_subs = _plan_agg_outputs(
            an, q, scope, agg_map, key_map, grouping_sets=grouping_sets,
            node=node, win_list=win_list)
        if having_e is not None:
            node = N.FilterNode(node, having_e)
        for lhs, op, sub in having_subs:
            # HAVING <agg-expr> op (SELECT ...): attach the 1-row scalar
            # to the group table via a const-key broadcast join, filter,
            # and project the agg layout back (q11 shape)
            if isinstance(sub.query, P.Query):
                corr_h, _ = _split_correlations(sub.query, tables,
                                                table_schemas)
                if corr_h:
                    raise NotImplementedError(
                        "correlated scalar subquery in HAVING is not "
                        "supported (decorrelate over the aggregate output "
                        "is a ROADMAP item)")
            node = _attach_scalar_filter(node, lhs, op, sub, max_groups,
                                         join_capacity)
    else:
        # SELECT-position uncorrelated scalar subqueries (the q9 CASE-
        # bucket shape): attach each as a broadcast single-row value
        # channel, registered so an.lower substitutes the channel ref
        sel_subs: list = []
        for item in q.select.items:
            _embedded_subqueries(item.expr, sel_subs)
        for s in sel_subs:
            if id(s) in an.subquery_masks:
                continue
            if not isinstance(s, P.ScalarSubquery):
                raise NotImplementedError(
                    "IN/EXISTS subqueries in SELECT position")
            if isinstance(s.query, P.Query):
                corr_s, _ = _split_correlations(s.query, tables,
                                                table_schemas)
                if corr_s:
                    raise NotImplementedError(
                        "correlated scalar subquery in SELECT position")
            cur_w = len(node.output_types())
            node, vty = _attach_scalar_value(node, s, max_groups,
                                             join_capacity)
            an.subquery_masks[id(s)] = E.input_ref(cur_w, vty)
        out_exprs = []
        names = []
        for i, item in enumerate(q.select.items):
            if isinstance(item.expr, P.Star):
                for ch, (alias, c) in enumerate(scope_entries):
                    out_exprs.append(E.input_ref(ch, types[ch]))
                    names.append(c)
                continue
            e = an.lower(item.expr, scope)
            out_exprs.append(e)
            names.append(_item_name(item, i))

    # ORDER BY/LIMIT operate on the projected outputs; project first.
    # `source_scope` (pre-projection channels) stays available because
    # hidden ORDER BY expressions are spliced INTO the projection and
    # must be lowered in the source channel space, not the output's.
    source_scope = scope
    node = N.ProjectNode(node, out_exprs)
    out_types = [e.type for e in out_exprs]
    scope = _Scope({n.lower(): i for i, n in enumerate(names)}, out_types)

    if q.having is not None and not (all_aggs or q.group_by):
        raise ValueError("HAVING without aggregation")

    if q.select.distinct:
        node = N.DistinctNode(node, max_groups=max_groups)

    if q.order_by:
        keys = []
        for o in q.order_by:
            if isinstance(o.expr, P.Name) and \
                    ".".join(o.expr.parts).lower() in scope.channels:
                ch = scope.channels[".".join(o.expr.parts).lower()]
            elif isinstance(o.expr, P.Literal) and o.expr.kind == "int":
                ch = int(o.expr.value) - 1
            else:
                # expression order key: append a hidden projection channel
                # (source channel space -- it joins out_exprs)
                e = _relower_output(an, o.expr, q, source_scope, out_exprs)
                out_exprs = out_exprs + [e]
                node = _replace_projection(node, out_exprs)
                ch = len(out_exprs) - 1
            keys.append((ch, o.descending, o.nulls_last))
        if q.limit is not None:
            node = N.TopNNode(node, keys, q.limit)
        else:
            node = N.SortNode(node, keys)
        if len(out_exprs) > len(names):
            # drop hidden ORDER BY channels after the sort consumed them
            node = N.ProjectNode(node, [
                E.input_ref(i, out_exprs[i].type) for i in range(len(names))])
    elif q.limit is not None:
        node = N.LimitNode(node, q.limit)

    return node, names


_WINDOW_FN_TYPES = {"row_number": T.BIGINT, "rank": T.BIGINT,
                    "dense_rank": T.BIGINT, "ntile": T.BIGINT,
                    "percent_rank": T.DOUBLE, "cume_dist": T.DOUBLE,
                    "count": T.BIGINT}


def _collect_windows(e, out: list):
    """Every WindowExpr under `e` (windows cannot nest)."""
    if isinstance(e, P.WindowExpr):
        out.append(e)
        return
    if not dataclasses.is_dataclass(e):
        return
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        for x in (v if isinstance(v, (list, tuple)) else [v]):
            if isinstance(x, tuple):
                for y in x:
                    _collect_windows(y, out)
            else:
                _collect_windows(x, out)


def _frame_of(w, order_keys=None, pre_exprs=None) -> object:
    """WindowExpr.frame (parser form) -> the kernel's frame spec.
    Value RANGE frames scale their offsets into the single ascending
    numeric order key's representation (scaled decimals, day numbers)."""
    fr = getattr(w, "frame", None)
    if fr is None:
        return "range_current"
    mode, s, e = fr
    if mode == "range":
        if s is None and e == 0:
            return "range_current"
        if s is None and e is None:
            return "full"
        # value-offset RANGE frame: needs exactly one ASC order key of
        # a numeric/temporal type (the SQL rule)
        if not order_keys or len(order_keys) != 1:
            raise NotImplementedError(
                "RANGE value frames require exactly one ORDER BY key")
        ch, desc, _nl = order_keys[0]
        if desc:
            raise NotImplementedError(
                "RANGE value frames over DESC order keys")
        ty = pre_exprs[ch].type
        if not (ty.is_numeric or ty.base in ("date", "timestamp")):
            raise NotImplementedError(
                f"RANGE value frame over {ty} order key")
        if ty.is_decimal and not ty.is_short_decimal:
            raise NotImplementedError(
                "RANGE value frame over long-decimal order key")

        def conv(x):
            if x is None or x == 0:
                return 0 if x == 0 else None
            if ty.is_decimal:
                return int(round(x * 10 ** ty.scale))
            if ty.is_floating:
                return float(x)
            if x != int(x):
                raise ValueError(
                    f"RANGE offset {x} is fractional but the order key "
                    f"is {ty}")
            return int(x)
        return ("range", conv(s), conv(e))
    for b in (s, e):
        if b is not None and b != int(b):
            raise ValueError("ROWS frame offsets must be integers")
    if s is None and e is None:
        return "full"  # whole partition: cheaper non-tuple kernel path
    return ("rows", s, e)


def _plan_window_stages(node, win_list, lower_expr):
    """Plan every WindowExpr in `win_list`, chaining one WindowNode
    stage per DISTINCT OVER clause (each stage's identity prefix keeps
    the original channel space valid, so later stages and the final
    projection lower against unchanged channel numbers)."""
    groups: List[list] = []
    for w in win_list:
        for g in groups:
            if g[0].partition_by == w.partition_by \
                    and g[0].order_by == w.order_by:
                g.append(w)
                break
        else:
            groups.append([w])
    win_map: Dict[int, Tuple[int, T.Type]] = {}
    for g in groups:
        node, m = _plan_window_stage(node, g, lower_expr,
                                     node.output_types())
        win_map.update(m)
    return node, win_map


def _plan_window_stage(node, win_list, lower_expr, base_types):
    """Append ONE WindowNode computing the WindowExprs in `win_list`
    (all sharing one OVER clause). The pre-projection starts with
    IDENTITY refs of the node's whole channel space, so downstream
    lowering keeps using the same channel numbers; window outputs
    append after. `lower_expr(ast)` lowers a scalar AST in that space
    (an.lower over the base scope, or the aggregation output rewriter).
    Returns (node, {id(WindowExpr): (channel, type)})."""
    w0 = win_list[0]
    pre_exprs: List[E.RowExpression] = [
        E.input_ref(i, t) for i, t in enumerate(base_types)]

    def chan_of(expr_ast) -> int:
        e = lower_expr(expr_ast)
        pre_exprs.append(e)
        return len(pre_exprs) - 1

    part_chans = [chan_of(p) for p in w0.partition_by]
    order_keys = []
    for o in w0.order_by:
        order_keys.append((chan_of(o.expr), o.descending, o.nulls_last))

    functions = []
    win_out_types = []
    for w in win_list:
        f = w.func
        name = f.name
        in_ch = None
        buckets = 0
        if name == "ntile":
            arg = f.args[0]
            assert isinstance(arg, P.Literal) and arg.kind == "int"
            buckets = int(arg.value)
        elif name in ("lag", "lead", "nth_value"):
            if name != "nth_value" and len(f.args) > 2:
                raise NotImplementedError(
                    "lag/lead default-value argument is not supported yet")
            if name == "nth_value" and len(f.args) != 2:
                raise ValueError("nth_value requires exactly two arguments")
            in_ch = chan_of(f.args[0])
            if len(f.args) > 1:
                arg = f.args[1]
                assert isinstance(arg, P.Literal) and arg.kind == "int", \
                    f"{name} offset must be an integer literal"
                buckets = int(arg.value)  # generic int param slot
                if name == "nth_value" and buckets < 1:
                    raise ValueError("nth_value offset must be at least 1")
            else:
                buckets = 1
        elif f.args and not isinstance(f.args[0], P.Star):
            in_ch = chan_of(f.args[0])
        frame = _frame_of(w, order_keys, pre_exprs)
        if name in ("lag", "lead", "nth_value"):
            oty = pre_exprs[in_ch].type
        elif name in _WINDOW_FN_TYPES and not (name == "count" and in_ch is not None):
            oty = _WINDOW_FN_TYPES[name]
        elif name == "count":
            oty = T.BIGINT
        elif name == "sum":
            oty = pre_exprs[in_ch].type
            if oty.is_decimal:
                oty = T.decimal(38, oty.scale)
            elif oty.is_integral:
                oty = T.BIGINT
        elif name == "avg":
            ity = pre_exprs[in_ch].type
            oty = T.decimal(38, ity.scale) if ity.is_decimal else T.DOUBLE
        else:  # min/max/first_value/last_value
            oty = pre_exprs[in_ch].type
        functions.append((name, in_ch, oty, frame, buckets))
        win_out_types.append(oty)

    node = N.ProjectNode(node, pre_exprs)
    node = N.WindowNode(node, part_chans, order_keys, functions)
    nwpre = len(pre_exprs)
    win_map = {id(w): (nwpre + k, win_out_types[k])
               for k, w in enumerate(win_list)}
    return node, win_map


_CMP_NAMES = {"=": "eq", "<>": "ne", "!=": "ne", "<": "lt",
              "<=": "le", ">": "gt", ">=": "ge"}


def _note_correlated(sub_q, note_name):
    """Record the CORRELATED outer columns of a subquery: every name
    under its WHERE that does not bind to an inner table (covers
    residual predicates like q16's `cs1.cs_warehouse_sk <>
    cs2.cs_warehouse_sk`, not just `=` correlations). Names that raise
    KeyError against the outer schemas are inner-only and skipped."""
    if not isinstance(sub_q, P.Query) or sub_q.where is None:
        return

    def walk(n):
        if isinstance(n, P.Name):
            if len(n.parts) == 1 and _inner_binds(sub_q, n.parts[0].lower()):
                return  # innermost scope wins for unqualified names
            try:
                note_name(n.parts)
            except KeyError:
                pass
            return
        if isinstance(n, P.InSubquery):
            walk(n.value)  # the IN's left operand is THIS scope's
            return  # (the subquery body collects on its own pass)
        if isinstance(n, (P.Exists, P.ScalarSubquery)):
            return  # deeper scopes collect on their own pass
        for x in _child_nodes(n):
            walk(x)

    walk(sub_q.where)


def _inner_binds(sub_q, col: str) -> bool:
    """Can an unqualified column bind to one of the subquery's tables?
    SQL scoping prefers the INNERMOST binding, so this check runs before
    any outer-schema lookup. Derived inner tables conservatively bind
    everything (their schema isn't known without planning)."""
    from ..connectors import catalogs
    cats = catalogs()
    for t in [sub_q.table] + [j.table for j in sub_q.joins]:
        if t.subquery is not None:
            return True
        for cat in cats.values():
            if t.name in cat.SCHEMA and col in dict(cat.SCHEMA[t.name]):
                return True
    return False


def _split_correlations(sub_q, outer_tables, outer_schemas):
    """Partition a subquery's WHERE into equality correlations
    [(outer Name, inner Name)] and residual inner-only conjuncts."""
    inner_aliases = {(t.alias or t.name).lower()
                     for t in [sub_q.table] + [j.table for j in sub_q.joins]}
    outer_aliases = {(t.alias or t.name).lower() for t in outer_tables}

    def side_of(nm: P.Name):
        if len(nm.parts) == 2:
            a = nm.parts[0].lower()
            if a in inner_aliases:
                return "inner"
            if a in outer_aliases:
                return "outer"
            return None
        col = nm.parts[0].lower()
        if _inner_binds(sub_q, col):  # innermost scope binds first
            return "inner"
        in_outer = any(col in outer_schemas[t.name] for t in outer_tables)
        return "outer" if in_outer else "inner"

    corr, residual = [], []
    for conj in (_conjuncts(sub_q.where) if sub_q.where is not None else []):
        if isinstance(conj, P.BinOp) and conj.op == "=" and \
                isinstance(conj.left, P.Name) and \
                isinstance(conj.right, P.Name):
            sides = (side_of(conj.left), side_of(conj.right))
            if sides == ("outer", "inner"):
                corr.append((conj.left, conj.right))
                continue
            if sides == ("inner", "outer"):
                corr.append((conj.right, conj.left))
                continue
        residual.append(conj)
    return corr, residual


def _decorrelate_scalar_agg(an, node, scope, outer_tables, outer_schemas,
                            lhs, op, sub_q, max_groups, join_capacity,
                            corr, residual):
    """`expr op (SELECT agg... WHERE inner.k = outer.k ...)` -> group the
    subquery by its correlation columns, LEFT-join on them, compare
    (TransformCorrelatedScalarAggregationToJoin analog). Outer rows with
    no inner group see a NULL scalar (comparison filters them) -- except
    pure count aggregates, whose empty-group value is 0 via COALESCE."""
    assert corr, "not a correlated scalar aggregate"
    if sub_q.group_by:
        raise NotImplementedError(
            "correlated scalar subquery with its own GROUP BY (multi-row "
            "per outer key) is not supported")
    if any(_has_outer_name(c, outer_tables, outer_schemas,
                           {(t.alias or t.name).lower() for t in
                            [sub_q.table] + [j.table for j in sub_q.joins]},
                           sub_q) for c in residual):
        raise NotImplementedError(
            "correlated scalar subquery with non-equality correlations")
    sub_ast = dataclasses.replace(
        sub_q,
        select=P.Select([P.SelectItem(inner, f"_corr{i}")
                         for i, (_, inner) in enumerate(corr)]
                        + list(sub_q.select.items), False),
        where=_and_all(residual),
        group_by=[inner for _, inner in corr],
        order_by=[], limit=None)
    sub_node, _ = _plan_any(sub_ast, max_groups, join_capacity)
    sub_node = _strip_output(sub_node)
    subt = sub_node.output_types()
    ncorr = len(corr)
    assert len(subt) == ncorr + 1, "scalar subquery must produce one column"

    outer_chs = []
    for outer_nm, _ in corr:
        e = an.lower(outer_nm, scope)
        assert isinstance(e, E.InputReference)
        outer_chs.append(e.channel)

    ntypes = node.output_types()
    nch = len(ntypes)
    joined = N.JoinNode(node, sub_node, outer_chs, list(range(ncorr)),
                        "left", "broadcast",
                        right_output_channels=[ncorr],
                        out_capacity=join_capacity)
    scalar_ref = E.input_ref(nch, subt[ncorr])
    sub_aggs = _Analyzer(sub_q).find_aggs(sub_q.select.items[0].expr)
    if sub_aggs and all(a.name == "count" for a in sub_aggs):
        # count over an empty correlation group is 0, not NULL
        scalar_ref = E.special("COALESCE", subt[ncorr], scalar_ref,
                               E.const(0, subt[ncorr]))
    f = N.FilterNode(joined, E.call(_CMP_NAMES[op], T.BOOLEAN, lhs,
                                    scalar_ref))
    return N.ProjectNode(f, [E.input_ref(i, ntypes[i]) for i in range(nch)])


def _and_all(conjs):
    out = None
    for c in conjs:
        out = c if out is None else P.BinOp("and", out, c)
    return out


def _has_outer_name(conj, outer_tables, outer_schemas, inner_aliases,
                    sub_q):
    """Does this conjunct reference any OUTER column? (Innermost scope
    binds unqualified names first, mirroring _split_correlations.)"""
    outer_aliases = {(t.alias or t.name).lower() for t in outer_tables}
    found = []

    def walk(n):
        if isinstance(n, P.Name):
            if len(n.parts) == 2:
                a = n.parts[0].lower()
                if a in outer_aliases and a not in inner_aliases:
                    found.append(n)
            else:
                col = n.parts[0].lower()
                if not _inner_binds(sub_q, col) and \
                        any(col in outer_schemas[t.name]
                            for t in outer_tables):
                    found.append(n)
        elif dataclasses.is_dataclass(n):
            for f in dataclasses.fields(n):
                v = getattr(n, f.name)
                if dataclasses.is_dataclass(v):
                    walk(v)
                elif isinstance(v, (list, tuple)):
                    for x in v:
                        if dataclasses.is_dataclass(x):
                            walk(x)

    walk(conj)
    return bool(found)


def _child_nodes(c):
    """Every dataclass child of an AST node, including those inside
    list/tuple fields and (cond, result) pair tuples -- the ONE shared
    iteration body for this module's recursive AST walkers."""
    if not dataclasses.is_dataclass(c):
        return
    for f in dataclasses.fields(c):
        v = getattr(c, f.name)
        for x in (v if isinstance(v, (list, tuple)) else [v]):
            if isinstance(x, tuple):
                for y in x:
                    if dataclasses.is_dataclass(y):
                        yield y
            elif dataclasses.is_dataclass(x):
                yield x


def _case_result_type(branches) -> T.Type:
    """Common result type across conditional branches (SWITCH/IF/
    COALESCE/NULL_IF -- the coercion the reference's TypeCoercer
    applies): the WIDEST numeric type wins so no branch is narrowed
    (mixed float+fixed -> DOUBLE; any decimal -> decimal at the widest
    precision class and scale; mixed integrals -> BIGINT). Typed-NULL
    branches don't vote."""
    types = [b.type for b in branches
             if not (isinstance(b, E.Constant) and b.value is None)
             and b.type != T.UNKNOWN]
    if not types:
        return branches[0].type if branches else T.UNKNOWN
    if all(t == types[0] for t in types):
        return types[0]
    if any(t.is_floating for t in types):
        return T.DOUBLE if any(t.is_numeric for t in types) else types[0]
    if any(t.is_decimal for t in types):
        scale = max(t.scale for t in types if t.is_decimal)
        prec = max(t.precision for t in types if t.is_decimal)
        return T.decimal(38 if prec > 18 else 18, scale)
    if all(t.is_integral for t in types):
        return T.BIGINT
    return types[0]


def _cast_branch(e: E.RowExpression, rty: T.Type) -> E.RowExpression:
    """Align one CASE branch to the common type: typed NULLs re-type in
    place, everything else casts through the kernel (a same-type cast
    is the identity)."""
    if e.type == rty:
        return e
    if isinstance(e, E.Constant) and e.value is None:
        return E.const(None, rty)
    return E.call("cast", rty, e)


def _embedded_subqueries(c, out):
    """Subquery nodes nested anywhere under `c` (descent stops at each:
    a subquery's own subqueries belong to its scope)."""
    if isinstance(c, (P.InSubquery, P.Exists, P.ScalarSubquery)):
        out.append(c)
        return
    for x in _child_nodes(c):
        _embedded_subqueries(x, out)


def _broadcast_scalar(node: N.PlanNode, sub: "P.ScalarSubquery",
                      max_groups: int, join_capacity: Optional[int]):
    """Shared EnforceSingleRow + cross-join shape for scalar subqueries
    in expression position: collapse the subresult to (value, count)
    through a 1-group aggregation and broadcast-join it on a constant
    key. Returns (joined, value_ref, count_ref, outer_types)."""
    sub_node, _ = _plan_any(sub.query, max_groups, join_capacity)
    sub_node = _strip_output(sub_node)
    subt = sub_node.output_types()
    assert len(subt) == 1, "scalar subquery must produce one column"
    sub_one = N.AggregationNode(
        sub_node, [],
        [AggSpec("min", 0, subt[0]),
         AggSpec("count_star", None, T.BIGINT)],
        step="SINGLE", max_groups=1)
    ntypes = node.output_types()
    nch = len(ntypes)
    left = N.ProjectNode(node, [
        E.input_ref(i, ntypes[i]) for i in range(nch)
    ] + [E.const(1, T.BIGINT)])
    right = N.ProjectNode(sub_one, [E.const(1, T.BIGINT),
                                    E.input_ref(0, subt[0]),
                                    E.input_ref(1, T.BIGINT)])
    joined = N.JoinNode(left, right, [nch], [0], "inner", "broadcast",
                        right_output_channels=[1, 2],
                        out_capacity=join_capacity)
    return (joined, E.input_ref(nch + 1, subt[0]),
            E.input_ref(nch + 2, T.BIGINT), ntypes)


def _attach_scalar_value(node: N.PlanNode, sub: "P.ScalarSubquery",
                         max_groups: int, join_capacity: Optional[int]):
    """Append an UNCORRELATED scalar subquery's value as one new channel
    (scalar subqueries in SELECT/expression position). An empty
    subresult yields NULL per spec; a multi-row subresult also yields
    NULL (the reference raises SCALAR_SUBQUERY_MULTIPLE_ROWS -- routing
    that through the jit-safe error channel is a ROADMAP item). Returns
    (new_node, value_type); the value channel is the last output."""
    joined, value_ref, count_ref, ntypes = _broadcast_scalar(
        node, sub, max_groups, join_capacity)
    nch = len(ntypes)
    guarded = E.special(
        "IF", value_ref.type,
        E.call("eq", T.BOOLEAN, count_ref, E.const(1, T.BIGINT)),
        value_ref, E.const(None, value_ref.type))
    out = N.ProjectNode(joined, [
        E.input_ref(i, ntypes[i]) for i in range(nch)] + [guarded])
    return out, value_ref.type


def _decorrelate_exists(an, node, scope, outer_tables, outer_schemas,
                        sub_q, negate, max_groups, join_capacity):
    """EXISTS/NOT EXISTS with equality correlations -> semi/anti join;
    additional CORRELATED residual predicates (e.g. q21's
    `l2.suppkey <> l1.suppkey`) decorrelate through the general
    unique-id route: join candidates on the equalities, filter the
    residuals over the combined row, and semi-join outer rows on their
    unique ids (TransformCorrelated* rule family)."""
    assert isinstance(sub_q, P.Query), "EXISTS over set operations: later"
    corr, residual = _split_correlations(sub_q, outer_tables, outer_schemas)
    assert corr, ("EXISTS subquery has no `inner.col = outer.col` equality "
                  "correlation; general correlated subqueries are a ROADMAP "
                  "item")
    inner_aliases = {(t.alias or t.name).lower()
                     for t in [sub_q.table] + [j.table for j in sub_q.joins]}
    if sub_q.group_by or sub_q.having is not None:
        raise NotImplementedError(
            "EXISTS over GROUP BY/HAVING subqueries is not supported yet")
    # ORDER BY/LIMIT inside EXISTS don't affect (non)emptiness: drop them
    # rather than letting a LIMIT truncate the filtering side globally
    sub_q = dataclasses.replace(sub_q, order_by=[], limit=None)
    corr_residual = [c for c in residual
                     if _has_outer_name(c, outer_tables, outer_schemas,
                                        inner_aliases, sub_q)]
    inner_residual = [c for c in residual if c not in corr_residual]

    ntypes = node.output_types()
    nch = len(ntypes)

    if not corr_residual:
        # pure equi-correlation: direct semi/anti join
        sub_ast = dataclasses.replace(
            sub_q,
            select=P.Select([P.SelectItem(inner, None) for _, inner in corr],
                            False),
            where=_and_all(inner_residual))
        sub_node, _ = _plan_any(sub_ast, max_groups, join_capacity)
        sub_node = _strip_output(sub_node)
        outer_chs = [an.lower(nm, scope).channel for nm, _ in corr]
        sj = N.SemiJoinNode(node, sub_node, outer_chs,
                            list(range(len(corr))))
        mask = E.input_ref(nch, T.BOOLEAN)
    else:
        # general route: tag outer rows with unique ids, join candidate
        # inner rows on the equalities, filter correlated residuals over
        # the combined row, and test uid membership
        node_u = N.AssignUniqueIdNode(node)
        uid_ch = nch

        # inner select: equality columns first, then every inner column
        # the correlated residuals need
        inner_needed: List[P.Name] = []

        def collect_inner(n):
            if isinstance(n, P.Name):
                if (len(n.parts) == 2 and n.parts[0].lower() in inner_aliases):
                    if n.parts not in [x.parts for x in inner_needed]:
                        inner_needed.append(n)
            elif dataclasses.is_dataclass(n):
                for f in dataclasses.fields(n):
                    v = getattr(n, f.name)
                    if dataclasses.is_dataclass(v):
                        collect_inner(v)
                    elif isinstance(v, (list, tuple)):
                        for x in v:
                            if dataclasses.is_dataclass(x):
                                collect_inner(x)
        for c in corr_residual:
            collect_inner(c)
        sub_ast = dataclasses.replace(
            sub_q,
            select=P.Select([P.SelectItem(inner, None) for _, inner in corr]
                            + [P.SelectItem(nm, None) for nm in inner_needed],
                            False),
            where=_and_all(inner_residual))
        sub_node, _ = _plan_any(sub_ast, max_groups, join_capacity)
        sub_node = _strip_output(sub_node)
        subt = sub_node.output_types()
        ncorr = len(corr)
        outer_chs = [an.lower(nm, scope).channel for nm, _ in corr]
        joined = N.JoinNode(node_u, sub_node, outer_chs,
                            list(range(ncorr)), "inner", "broadcast",
                            right_output_channels=list(
                                range(ncorr, len(subt))),
                            out_capacity=join_capacity)
        # combined scope: outer channels as-is, appended inner columns
        comb_channels = dict(scope.channels)
        comb_types = list(ntypes) + [T.BIGINT] + \
            [subt[ncorr + i] for i in range(len(inner_needed))]
        for i, nm in enumerate(inner_needed):
            comb_channels[".".join(nm.parts).lower()] = nch + 1 + i
        comb_scope = _Scope(comb_channels, comb_types)
        pred = an.lower(_and_all(corr_residual), comb_scope)
        survivors = N.ProjectNode(N.FilterNode(joined, pred),
                                  [E.input_ref(uid_ch, T.BIGINT)])
        sj = N.SemiJoinNode(node_u, survivors, uid_ch, 0)
        mask = E.input_ref(nch + 1, T.BOOLEAN)

    if negate:
        # NOT EXISTS: "no matching row" -- a NULL mask (null outer key)
        # means no match and must KEEP the row (unlike NOT IN)
        pred = E.call("not", T.BOOLEAN, E.special(
            "COALESCE", T.BOOLEAN, mask, E.const(False, T.BOOLEAN)))
    else:
        pred = mask
    f = N.FilterNode(sj, pred)
    return N.ProjectNode(f, [E.input_ref(i, ntypes[i]) for i in range(nch)])


def _attach_scalar_filter(node: N.PlanNode, lhs: E.RowExpression, op: str,
                          sub: "P.ScalarSubquery", max_groups: int,
                          join_capacity: Optional[int]) -> N.PlanNode:
    """Filter `node` rows by `lhs op (scalar subquery)`: the subresult is
    collapsed to (value, count) through a 1-group aggregation (provably
    one build row; rows drop when count != 1 -- EnforceSingleRow's error
    lands with task-level error channels), broadcast-joined on a
    constant key, compared, and the original channel layout projected
    back."""
    joined, scalar_ref, count_ref, ntypes = _broadcast_scalar(
        node, sub, max_groups, join_capacity)
    nch = len(ntypes)
    f = N.FilterNode(joined, E.special(
        "AND", T.BOOLEAN,
        E.call("le", T.BOOLEAN, count_ref, E.const(1, T.BIGINT)),
        E.call(_CMP_NAMES[op], T.BOOLEAN, lhs, scalar_ref)))
    return N.ProjectNode(f, [
        E.input_ref(i, ntypes[i]) for i in range(nch)])


def _item_name(item: P.SelectItem, i: int) -> str:
    if item.alias:
        return item.alias
    if isinstance(item.expr, P.Name):
        return item.expr.parts[-1].lower()
    return f"_col{i}"


def _replace_projection(node: N.PlanNode, exprs) -> N.PlanNode:
    # node is ... -> ProjectNode (possibly wrapped); round 1: node IS the
    # projection (order-by rewrite happens right after projecting)
    assert isinstance(node, N.ProjectNode)
    return N.ProjectNode(node.source, list(exprs))


def _relower_output(an, expr, q, source_scope, out_exprs):
    """Produce a SOURCE-channel-space expression for an ORDER BY key that
    is spliced into the output projection: an identical select
    expression reuses its already-lowered form; otherwise the key
    lowers against the pre-projection scope."""
    for i, item in enumerate(q.select.items):
        if item.expr == expr:
            return out_exprs[i]
    return an.lower(expr, source_scope)


def _conjuncts(e) -> List[object]:
    if isinstance(e, P.BinOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _disjuncts(e) -> List[object]:
    if isinstance(e, P.BinOp) and e.op == "or":
        return _disjuncts(e.left) + _disjuncts(e.right)
    return [e]


def _extract_common_or(c):
    """OR(A AND X, A AND Y) -> ([A], OR(X, Y)).

    The LogicalRowExpressions.extractCommonPredicates analog
    (presto-expressions/.../LogicalRowExpressions.java): TPC-DS text
    hides join predicates inside every branch of an OR (q13/q25/q48
    shape); hoisting the branch-common conjuncts exposes them to the
    join-graph/pushdown classifier. Pure Kleene-logic distributivity,
    so 3VL NULL semantics are preserved. Returns ([], c) when nothing
    is common; residual None when some branch becomes empty (the OR is
    implied by the common part)."""
    ds = _disjuncts(c)
    if len(ds) < 2:
        return [], c
    branch_conjs = [_conjuncts(d) for d in ds]
    common = []
    for cand in branch_conjs[0]:
        if all(any(cand == other for other in bc) for bc in branch_conjs[1:]):
            if not any(cand == x for x in common):
                common.append(cand)
    if not common:
        return [], c
    residuals = []
    for bc in branch_conjs:
        rem = [x for x in bc if not any(x == y for y in common)]
        if not rem:
            return common, None  # a branch reduced to TRUE
        r = rem[0]
        for x in rem[1:]:
            r = P.BinOp("and", r, x)
        residuals.append(r)
    new_or = residuals[0]
    for r in residuals[1:]:
        new_or = P.BinOp("or", new_or, r)
    return common, new_or


def _plan_aggregation(an, node, scope, q, all_aggs, max_groups,
                      grouping_sets=None):
    """Emit pre-projection (+ GroupIdNode for grouping sets) +
    AggregationNode; returns (node, post_scope, agg result channel map,
    group key channel map)."""
    # pre-projection: group keys then agg inputs
    pre_exprs: List[E.RowExpression] = []
    key_map: Dict[int, int] = {}  # index in q.group_by -> channel
    for i, g in enumerate(q.group_by):
        if isinstance(g, P.Literal) and g.kind == "int":
            item = q.select.items[int(g.value) - 1].expr
            e = an.lower(item, scope)
        else:
            e = an.lower(g, scope)
        key_map[i] = len(pre_exprs)
        pre_exprs.append(e)
    specs: List[AggSpec] = []
    agg_map: Dict[int, Tuple[int, AggSpec]] = {}  # id(ast) -> (state ch, spec)
    # grouping sets add a hidden group-id KEY channel after the keys
    state_ch = len(q.group_by) + (1 if grouping_sets is not None else 0)
    seen_asts: List[Tuple[object, int, AggSpec]] = []
    for f in all_aggs:
        # dedupe textually identical aggregates (the q12 family names
        # sum(x) three times: select item, ratio numerator, window arg)
        # so the kernel computes each once
        dup = next(((ch, sp) for ast, ch, sp in seen_asts if ast == f),
                   None)
        if dup is not None:
            agg_map[id(f)] = dup
            continue
        name = f.name
        if name == "count" and (not f.args or isinstance(f.args[0], P.Star)):
            spec = AggSpec("count_star", None, T.BIGINT)
        else:
            arg = an.lower(f.args[0], scope)
            in_ch = len(pre_exprs)
            pre_exprs.append(arg)
            aname = name
            if name == "count" and f.distinct:
                aname = "count_distinct"
            if name in _TWO_ARG_AGGS:
                if len(f.args) != 2:
                    raise ValueError(f"{name} takes two arguments")
                arg2 = an.lower(f.args[1], scope)
                ch2 = len(pre_exprs)
                pre_exprs.append(arg2)
                spec = AggSpec(aname, in_ch,
                               _agg_output_type(name, arg.type),
                               second_channel=ch2, second_type=arg2.type)
            else:
                spec = AggSpec(aname, in_ch,
                               _agg_output_type(name, arg.type))
        specs.append(spec)
        agg_map[id(f)] = (state_ch, spec)
        seen_asts.append((f, state_ch, spec))
        state_ch += 1  # SINGLE-step aggregations emit finalized columns
    node = N.ProjectNode(node, pre_exprs)
    nkeys = len(q.group_by)
    if grouping_sets is not None:
        node = N.GroupIdNode(node, [list(s) for s in grouping_sets])
        group_channels = list(range(nkeys)) + [len(pre_exprs)]
        eff_max_groups = max_groups * len(grouping_sets)
    else:
        group_channels = list(range(nkeys))
        eff_max_groups = max_groups
    agg = N.AggregationNode(node, group_channels, specs,
                            step="SINGLE", max_groups=eff_max_groups)
    return agg, scope, agg_map, key_map


def _plan_agg_outputs(an, q, pre_scope, agg_map, key_map,
                      grouping_sets=None, node=None, win_list=None):
    """Post-aggregation projection: replace aggregate calls with refs to
    the aggregation node's finalized output channels (avg/variance
    finalization happens inside the SINGLE/FINAL aggregation step —
    ops.aggregation.finalize_states), group-by expressions with key
    channels. grouping(col) lowers to a SWITCH over the hidden gid key
    channel (the reference evaluates it from GroupIdNode's set index the
    same way). Window expressions over the aggregation (q53's
    avg(sum(x)) OVER shape) plan as a WindowNode stage above the
    aggregate (after HAVING, per SQL evaluation order); their args/
    partition/order lower through this same rewriter.

    Returns (node, out_exprs, names, having_e, having_subs); having_e
    is None when it was already applied (window staging consumed it)."""
    agg_node_types: Dict[int, T.Type] = {}
    # the ONE window-channel registry lives on the analyzer, so both
    # this rewriter and an.lower (hidden ORDER BY keys) resolve the
    # same planned windows
    window_channels = an.window_channels

    def finalize(f: P.Func) -> E.RowExpression:
        ch, spec = agg_map[id(f)]
        return E.input_ref(ch, spec.output_type)

    def rewrite(nde, scope_keys) -> E.RowExpression:
        if isinstance(nde, P.WindowExpr):
            hit = window_channels.get(id(nde))
            if hit is None:
                raise NotImplementedError(
                    "window expression outside the planned window stage")
            return E.input_ref(*hit)
        if isinstance(nde, P.Func) and id(nde) in agg_map:
            return finalize(nde)
        if isinstance(nde, P.Func) and nde.name == "grouping":
            if grouping_sets is None:
                raise ValueError("grouping() requires GROUP BY "
                                 "ROLLUP/CUBE/GROUPING SETS")
            arg = nde.args[0]
            for ki, g in enumerate(q.group_by):
                if g == arg:
                    break
            else:
                raise ValueError(f"grouping() argument {arg} is not a "
                                 "grouping column")
            gid_ref = E.input_ref(len(q.group_by), T.BIGINT)
            sw = [E.const(True, T.BOOLEAN)]
            for si, s in enumerate(grouping_sets):
                sw.append(E.special(
                    "WHEN", T.BIGINT,
                    E.call("eq", T.BOOLEAN, gid_ref,
                           E.const(si, T.BIGINT)),
                    E.const(0 if ki in s else 1, T.BIGINT)))
            return E.special("SWITCH", T.BIGINT, *sw)
        # group key expression?
        for i, g in enumerate(q.group_by):
            if nde == g or (isinstance(g, P.Literal) and g.kind == "int"
                            and q.select.items[int(g.value) - 1].expr == nde):
                ch = key_map[i]
                return E.input_ref(ch, scope_keys[ch])
        if isinstance(nde, P.BinOp):
            l = rewrite(nde.left, scope_keys)
            r = rewrite(nde.right, scope_keys)
            if nde.op in ("and", "or"):
                return E.special(nde.op.upper(), T.BOOLEAN, l, r)
            if nde.op in ("=", "<>", "!=", "<", "<=", ">", ">="):
                name = {"=": "eq", "<>": "ne", "!=": "ne", "<": "lt",
                        "<=": "le", ">": "gt", ">=": "ge"}[nde.op]
                return E.call(name, T.BOOLEAN, l, r)
            name = {"+": "add", "-": "subtract", "*": "multiply",
                    "/": "divide", "%": "modulus"}[nde.op]
            return E.call(name, an._arith_type(name, l.type, r.type), l, r)
        if isinstance(nde, P.Literal):
            return an._literal(nde)
        if isinstance(nde, P.Func):
            args = [rewrite(a, scope_keys) for a in nde.args]
            return E.call(nde.name, an._func_type(nde.name, args), *args)
        if isinstance(nde, P.Cast):
            v = rewrite(nde.value, scope_keys)
            return E.call("cast", T.parse_type(nde.type_name), v)
        if isinstance(nde, P.Case):
            whens = [(rewrite(c, scope_keys), rewrite(r, scope_keys))
                     for c, r in nde.whens]
            default = rewrite(nde.default, scope_keys) \
                if nde.default is not None else None
            rty = _case_result_type([r for _, r in whens]
                                    + ([default] if default else []))
            args = [rewrite(nde.operand, scope_keys)
                    if nde.operand is not None else E.const(True, T.BOOLEAN)]
            for c, r in whens:
                args.append(E.special("WHEN", rty, c, _cast_branch(r, rty)))
            if default is not None:
                args.append(_cast_branch(default, rty))
            return E.special("SWITCH", rty, *args)
        if isinstance(nde, P.IsNull):
            e = E.special("IS_NULL", T.BOOLEAN, rewrite(nde.value, scope_keys))
            return E.call("not", T.BOOLEAN, e) if nde.negate else e
        if isinstance(nde, P.Between):
            v = rewrite(nde.value, scope_keys)
            e = E.special("BETWEEN", T.BOOLEAN, v,
                          rewrite(nde.lo, scope_keys),
                          rewrite(nde.hi, scope_keys))
            return E.call("not", T.BOOLEAN, e) if nde.negate else e
        raise NotImplementedError(
            f"expression over aggregates not supported: {nde}")

    # key channel types come from the pre-projection
    nkeys = len(q.group_by)
    key_types: Dict[int, T.Type] = {}
    for i, g in enumerate(q.group_by):
        if isinstance(g, P.Literal) and g.kind == "int":
            e = an.lower(q.select.items[int(g.value) - 1].expr, pre_scope)
        else:
            e = an.lower(g, pre_scope)
        key_types[key_map[i]] = e.type

    having_e = None
    having_scalar_subs = []
    if q.having is not None:
        for conj in _conjuncts(q.having):
            if isinstance(conj, P.BinOp) and \
                    isinstance(conj.right, P.ScalarSubquery):
                # lhs rewritten over agg channels; subquery planned by
                # the caller (needs join plumbing above the agg node)
                having_scalar_subs.append(
                    (rewrite(conj.left, key_types), conj.op, conj.right))
            else:
                e = rewrite(conj, key_types)
                having_e = e if having_e is None else \
                    E.special("AND", T.BOOLEAN, having_e, e)

    if win_list:
        # SQL evaluation order: HAVING restricts groups BEFORE window
        # functions see them
        if having_scalar_subs:
            raise NotImplementedError(
                "window functions with HAVING scalar subqueries")
        if having_e is not None:
            node = N.FilterNode(node, having_e)
            having_e = None
        node, win_map = _plan_window_stages(
            node, win_list, lambda ast: rewrite(ast, key_types))
        window_channels.update(win_map)

    out_exprs, names = [], []
    for i, item in enumerate(q.select.items):
        e = rewrite(item.expr, key_types)
        out_exprs.append(e)
        names.append(_item_name(item, i))
    return node, out_exprs, names, having_e, having_scalar_subs


def sql(query_text: str, sf: float = 0.01, mesh=None,
        max_groups: int = 1 << 16, join_capacity: Optional[int] = None,
        catalog: Optional[str] = None, **kwargs):
    """One-call SQL execution over the session catalogs: the query-runner
    front door (DistributedQueryRunner.execute analog)."""
    from ..exec import run_query
    from .statements import _DEFAULT_PREPARED, preprocess
    pre = preprocess(query_text, catalog=catalog or "tpch",
                     prepared=_DEFAULT_PREPARED)
    if pre.ack is not None:
        from ..exec.runner import QueryResult
        return QueryResult([], [], [pre.ack], 0)
    query_text = pre.text
    root = plan_sql(query_text, max_groups=max_groups,
                    join_capacity=join_capacity, catalog=catalog)
    if join_capacity is not None:
        kwargs.setdefault("default_join_capacity", join_capacity)
    return run_query(root, sf=sf, mesh=mesh, **kwargs)
