"""SQL-invoked functions + the function namespace manager.

Reference surface: presto-function-namespace-managers (pluggable
function catalogs keyed catalog.schema.name, versioned SQL UDFs) and
the SQL-invoked function path (CREATE FUNCTION ... RETURNS ... RETURN
<expr>; presto-sql-helpers ships bundles of these). A SQL function is
a typed macro: at plan time the body expression expands inline with
parameters bound to the lowered argument expressions -- by the time
XLA sees the plan, the UDF has dissolved into ordinary fused lanes
(the reference inlines SQL functions before execution the same way).

    CREATE FUNCTION my.math.double_it(x bigint) RETURNS bigint
        RETURN x * 2
    SELECT my.math.double_it(nationkey) FROM nation
    DROP FUNCTION my.math.double_it

Unqualified names register under the default namespace
`presto.default` and are callable unqualified."""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import Dict, List, Optional, Tuple

from .. import types as T

__all__ = ["SqlFunction", "FunctionNamespaceManager",
           "get_function_namespace_manager", "reset_functions",
           "parse_create_function", "parse_drop_function"]

DEFAULT_NAMESPACE = "presto.default"


@dataclasses.dataclass(frozen=True)
class SqlFunction:
    qualified_name: str                 # catalog.schema.name
    parameters: Tuple[Tuple[str, T.Type], ...]
    return_type: T.Type
    body_sql: str                       # the RETURN expression text


class FunctionNamespaceManager:
    """In-memory namespace registry (the mysql/rest-backed managers'
    serving surface; storage is not the architecture)."""

    def __init__(self):
        self._fns: Dict[str, SqlFunction] = {}
        self._lock = threading.Lock()

    def register(self, fn: SqlFunction, replace: bool = False) -> None:
        with self._lock:
            old = self._fns.get(fn.qualified_name)
            if old is not None and not replace:
                raise KeyError(
                    f"function {fn.qualified_name!r} already exists")
            if old is not None:
                _evict_ast(old)
            self._fns[fn.qualified_name] = fn

    def drop(self, qualified_name: str, if_exists: bool = False) -> None:
        with self._lock:
            old = self._fns.pop(self._resolve_key(qualified_name), None)
            if old is None and not if_exists:
                raise KeyError(f"no function {qualified_name!r}")
            if old is not None:
                _evict_ast(old)

    def _resolve_key(self, name: str) -> str:
        if "." not in name:
            return f"{DEFAULT_NAMESPACE}.{name}"
        return name

    def lookup(self, name: str) -> Optional[SqlFunction]:
        with self._lock:
            return self._fns.get(self._resolve_key(name.lower()))

    def list_functions(self) -> List[SqlFunction]:
        with self._lock:
            return sorted(self._fns.values(),
                          key=lambda f: f.qualified_name)


_manager = FunctionNamespaceManager()

# parsed-body cache: bodies parse ONCE (at registration, which also
# surfaces syntax errors at CREATE FUNCTION time, and on first lookup
# after an engine restart)
_AST_CACHE: Dict[str, object] = {}


def _evict_ast(fn: SqlFunction) -> None:
    _AST_CACHE.pop(f"{fn.qualified_name}\x00{fn.body_sql}", None)


def body_ast(fn: SqlFunction):
    key = f"{fn.qualified_name}\x00{fn.body_sql}"
    hit = _AST_CACHE.get(key)
    if hit is None:
        from .parser import parse_expression
        hit = _AST_CACHE[key] = parse_expression(fn.body_sql)
    return hit


def get_function_namespace_manager() -> FunctionNamespaceManager:
    return _manager


def reset_functions() -> None:
    _manager._fns.clear()
    _AST_CACHE.clear()


_CREATE_RE = re.compile(
    r"^\s*create\s+(or\s+replace\s+)?function\s+([\w.]+)\s*\((.*?)\)\s*"
    r"returns\s+(.+?)\s+return\s+(.*)$",
    re.IGNORECASE | re.DOTALL)
_DROP_RE = re.compile(
    r"^\s*drop\s+function\s+(if\s+exists\s+)?([\w.]+)\s*$",
    re.IGNORECASE)


def _split_params(text: str) -> List[Tuple[str, T.Type]]:
    out = []
    depth = 0
    cur: List[str] = []
    parts: List[str] = []
    for ch in text:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        parts.append(tail)
    for p in parts:
        p = p.strip()
        if not p:
            continue
        bits = p.split(None, 1)  # any whitespace (tabs, newlines)
        if len(bits) != 2:
            raise ValueError(f"parameter {p!r} needs `name type`")
        out.append((bits[0].lower(), T.parse_type(bits[1].strip())))
    return out


def parse_create_function(text: str) -> Optional[Tuple[SqlFunction, bool]]:
    """CREATE [OR REPLACE] FUNCTION f(a t, ...) RETURNS t RETURN expr
    -> (SqlFunction, replace) or None when `text` is something else."""
    m = _CREATE_RE.match(text.strip().rstrip(";"))
    if not m:
        return None
    replace = bool(m.group(1))
    name = m.group(2).lower()
    if "." not in name:
        name = f"{DEFAULT_NAMESPACE}.{name}"
    params = tuple(_split_params(m.group(3)))
    rty = T.parse_type(m.group(4).strip())
    fn = SqlFunction(name, params, rty, m.group(5).strip())
    body_ast(fn)  # syntax errors surface at CREATE FUNCTION time
    return fn, replace


def parse_drop_function(text: str) -> Optional[Tuple[str, bool]]:
    m = _DROP_RE.match(text.strip().rstrip(";"))
    if not m:
        return None
    return m.group(2).lower(), bool(m.group(1))
