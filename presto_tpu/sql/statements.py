"""Meta statements: SHOW / DESCRIBE rewrites + prepared statements.

Reference surface: the coordinator's ShowQueriesRewrite
(presto-main-base/.../sql/rewrite/ShowQueriesRewrite.java -- SHOW
TABLES/SCHEMAS/CATALOGS/COLUMNS become SELECTs over information_schema)
and the prepared-statement path (QueuedStatementResource session
headers; sql/analyzer handling of PREPARE/EXECUTE/DEALLOCATE,
presto-parser's `prepare` grammar rules).

`preprocess` is the one entry: given raw statement text it returns
either rewritten SQL to execute, or an immediate acknowledgment result
(PREPARE/DEALLOCATE), or the text untouched. Prepared statements
substitute `?` parameters TEXTUALLY with the EXECUTE ... USING
expressions before parsing -- parameters are client-provided literal
expressions, exactly what the reference inlines at analysis time."""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

__all__ = ["Preprocessed", "preprocess", "PreparedStatements"]


class PreparedStatements(dict):
    """Session-scoped name -> statement text registry."""


# the sql() front door's process-wide session (server sessions carry
# their own PreparedStatements)
_DEFAULT_PREPARED = PreparedStatements()


_SHOW_RE = re.compile(
    r"^\s*show\s+(catalogs|schemas|tables|columns|session|functions)\b(.*)$",
    re.IGNORECASE | re.DOTALL)
_DESCRIBE_RE = re.compile(r"^\s*(?:describe|desc)\s+([\w.]+)\s*$",
                          re.IGNORECASE)
_PREPARE_RE = re.compile(r"^\s*prepare\s+(\w+)\s+from\s+(.*)$",
                         re.IGNORECASE | re.DOTALL)
_EXECUTE_RE = re.compile(r"^\s*execute\s+(\w+)(?:\s+using\s+(.*))?\s*$",
                         re.IGNORECASE | re.DOTALL)
_DEALLOC_RE = re.compile(r"^\s*deallocate\s+prepare\s+(\w+)\s*$",
                         re.IGNORECASE)


@dataclasses.dataclass
class Preprocessed:
    text: Optional[str] = None      # SQL to run (rewritten or original)
    ack: Optional[str] = None       # immediate update-type acknowledgment
    columns: Optional[List[str]] = None


def _split_table(name: str, catalog: str) -> Tuple[str, str]:
    parts = name.split(".")
    if len(parts) == 1:
        return catalog, parts[0]
    if len(parts) == 2:
        return parts[0], parts[1]
    # catalog.schema.table: the single-schema registry ignores schema
    return parts[0], parts[2]


def _split_using(args: str) -> List[str]:
    """Split EXECUTE ... USING arguments on top-level commas (strings
    and parens respected)."""
    out, depth, cur, i = [], 0, [], 0
    in_str = False
    while i < len(args):
        ch = args[i]
        if in_str:
            cur.append(ch)
            if ch == "'":
                if i + 1 < len(args) and args[i + 1] == "'":
                    cur.append("'")
                    i += 1
                else:
                    in_str = False
        elif ch == "'":
            in_str = True
            cur.append(ch)
        elif ch == "(":
            depth += 1
            cur.append(ch)
        elif ch == ")":
            depth -= 1
            cur.append(ch)
        elif ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
        i += 1
    tail = "".join(cur).strip()
    if tail:
        out.append(tail)
    return out


def _substitute_params(text: str, params: List[str]) -> str:
    """Replace `?` placeholders (outside string literals) in order."""
    out, i, p = [], 0, 0
    in_str = False
    while i < len(text):
        ch = text[i]
        if in_str:
            out.append(ch)
            if ch == "'":
                in_str = False
        elif ch == "'":
            in_str = True
            out.append(ch)
        elif ch == "?":
            if p >= len(params):
                raise ValueError(
                    f"prepared statement has more parameters than the "
                    f"{len(params)} provided")
            out.append(f"({params[p]})")
            p += 1
        else:
            out.append(ch)
        i += 1
    if p != len(params):
        raise ValueError(f"prepared statement takes {p} parameter(s), "
                         f"{len(params)} provided")
    return "".join(out)


_FROM_LIKE_RE = re.compile(
    r"^(?:(?:from|in)\s+([\w.]+))?\s*(?:like\s+'((?:[^']|'')*)')?\s*$",
    re.IGNORECASE)


def _from_and_like(rest: str, default_catalog: str):
    """Parse the [FROM catalog] [LIKE 'pattern'] tail of SHOW
    TABLES/SCHEMAS. Unrecognized tails raise instead of silently
    returning the unfiltered set."""
    m = _FROM_LIKE_RE.match(rest)
    if not m:
        raise ValueError(f"cannot parse SHOW clause tail: {rest!r}")
    cat = (m.group(1) or default_catalog).split(".")[0]
    return cat, m.group(2)


def preprocess(text: str, catalog: str = "tpch",
               prepared: Optional[PreparedStatements] = None
               ) -> Preprocessed:
    from .udf import (get_function_namespace_manager,
                      parse_create_function, parse_drop_function)
    cf = parse_create_function(text)
    if cf is not None:
        fn, replace = cf
        get_function_namespace_manager().register(fn, replace=replace)
        return Preprocessed(ack="CREATE FUNCTION")
    df = parse_drop_function(text)
    if df is not None:
        name, if_exists = df
        get_function_namespace_manager().drop(name, if_exists=if_exists)
        return Preprocessed(ack="DROP FUNCTION")
    m = _PREPARE_RE.match(text)
    if m:
        if prepared is None:
            raise ValueError("no prepared-statement session")
        prepared[m.group(1).lower()] = m.group(2).strip()
        return Preprocessed(ack="PREPARE")
    m = _DEALLOC_RE.match(text)
    if m:
        if prepared is None or m.group(1).lower() not in prepared:
            raise KeyError(f"prepared statement {m.group(1)!r} not found")
        del prepared[m.group(1).lower()]
        return Preprocessed(ack="DEALLOCATE")
    m = _EXECUTE_RE.match(text)
    if m:
        if prepared is None or m.group(1).lower() not in prepared:
            raise KeyError(f"prepared statement {m.group(1)!r} not found")
        body = prepared[m.group(1).lower()]
        params = _split_using(m.group(2)) if m.group(2) else []
        return Preprocessed(text=_substitute_params(body, params))
    m = _DESCRIBE_RE.match(text)
    if m:
        cat, tab = _split_table(m.group(1), catalog)
        return Preprocessed(text=(
            "SELECT column_name AS Column, data_type AS Type, "
            "is_nullable AS Null FROM information_schema.columns "
            f"WHERE table_catalog = '{cat}' AND table_name = '{tab}' "
            "ORDER BY ordinal_position"))
    m = _SHOW_RE.match(text)
    if m:
        kind = m.group(1).lower()
        rest = m.group(2).strip().rstrip(";").strip()
        if kind == "catalogs":
            return Preprocessed(text=(
                "SELECT catalog_name AS Catalog FROM system.catalogs "
                "ORDER BY catalog_name"))
        if kind == "schemas":
            cat, like = _from_and_like(rest, catalog)
            return Preprocessed(text=(
                "SELECT schema_name AS Schema FROM "
                "information_schema.schemata "
                f"WHERE catalog_name = '{cat}'"
                + (f" AND schema_name LIKE '{like}'" if like else "")
                + " ORDER BY schema_name"))
        if kind == "tables":
            cat, like = _from_and_like(rest, catalog)
            return Preprocessed(text=(
                "SELECT table_name AS Table FROM information_schema.tables "
                f"WHERE table_catalog = '{cat}'"
                + (f" AND table_name LIKE '{like}'" if like else "")
                + " ORDER BY table_name"))
        if kind == "columns":
            mm = re.match(r"(?:from|in)\s+([\w.]+)$", rest, re.IGNORECASE)
            if not mm:
                raise ValueError("SHOW COLUMNS needs FROM <table>")
            cat, tab = _split_table(mm.group(1), catalog)
            return Preprocessed(text=(
                "SELECT column_name AS Column, data_type AS Type, "
                "is_nullable AS Null FROM information_schema.columns "
                f"WHERE table_catalog = '{cat}' AND table_name = '{tab}' "
                "ORDER BY ordinal_position"))
        if kind == "session":
            return Preprocessed(text=(
                "SELECT name AS Name, default_value AS Value, type AS Type, "
                "description AS Description FROM system.session_properties "
                "ORDER BY name"))
        if kind == "functions":
            return Preprocessed(text=(
                "SELECT function_name AS Function, kind AS Kind "
                "FROM system.functions ORDER BY function_name"))
    return Preprocessed(text=text)
