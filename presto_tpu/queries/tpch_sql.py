"""The TPC-H q1-q22 SQL corpus, in this engine's dialect.

One committed, importable home for the full benchmark suite the test
files exercise piecemeal: each entry is the query text (the
engine-dialect adaptation the SQL test suites pin against numpy
oracles) plus the planning capacities it needs at small scale factors.
The kernaudit corpus gate (``scripts/kernaudit.py``) stages every
query here -- local tier and mesh tier -- and audits the traced IR;
anything else that wants "run all of TPC-H" (benchmarks, soak tests)
should import this module rather than re-transcribing query text.

``stage_tpch`` is the corpus's staging front door: SQL -> plan ->
prepare_plan -> compile_plan -> staged scan batches, stopping right
before dispatch -- exactly the state the staging-time auditor sees.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

__all__ = ["TPCH_QUERIES", "TpchQuery", "tpch_query", "stage_tpch",
           "StagedQuery"]


@dataclasses.dataclass(frozen=True)
class TpchQuery:
    number: int
    text: str
    max_groups: int = 1 << 16
    join_capacity: Optional[int] = None

    @property
    def label(self) -> str:
        return f"tpch/q{self.number:02d}"


TPCH_QUERIES: Dict[int, TpchQuery] = {q.number: q for q in [
    TpchQuery(1, """
      SELECT returnflag, linestatus,
             sum(quantity) AS sum_qty,
             sum(extendedprice) AS sum_base_price,
             sum(extendedprice * (1 - discount)) AS sum_disc_price,
             count(*) AS count_order
      FROM lineitem
      WHERE shipdate <= date '1998-12-01' - interval '90' day
      GROUP BY returnflag, linestatus
      ORDER BY returnflag, linestatus
    """, max_groups=16),
    TpchQuery(2, """
      SELECT s.acctbal, s.name, p.partkey
      FROM part p
      JOIN partsupp ps ON p.partkey = ps.partkey
      JOIN supplier s ON s.suppkey = ps.suppkey
      JOIN nation n ON s.nationkey = n.nationkey
      WHERE p.size = 15 AND n.regionkey = 3
        AND ps.supplycost = (SELECT min(ps2.supplycost)
                             FROM partsupp ps2
                             JOIN supplier s2 ON s2.suppkey = ps2.suppkey
                             JOIN nation n2 ON s2.nationkey = n2.nationkey
                             WHERE ps2.partkey = p.partkey
                               AND n2.regionkey = 3)
      ORDER BY s.acctbal DESC, p.partkey LIMIT 10
    """, max_groups=1 << 13, join_capacity=1 << 17),
    TpchQuery(3, """
      SELECT l.orderkey, sum(l.extendedprice * (1 - l.discount)) AS revenue,
             o.orderdate, o.shippriority
      FROM customer c
      JOIN orders o ON c.custkey = o.custkey
      JOIN lineitem l ON l.orderkey = o.orderkey
      WHERE c.mktsegment = 'BUILDING'
        AND o.orderdate < date '1995-03-15'
        AND l.shipdate > date '1995-03-15'
      GROUP BY l.orderkey, o.orderdate, o.shippriority
      ORDER BY revenue DESC, o.orderdate
      LIMIT 10
    """, max_groups=1 << 14),
    TpchQuery(4, """
      SELECT o.orderpriority, count(*) AS order_count
      FROM orders o
      WHERE o.orderdate >= date '1993-07-01'
        AND o.orderdate < date '1993-10-01'
        AND EXISTS (SELECT l.orderkey FROM lineitem l
                    WHERE l.orderkey = o.orderkey
                      AND l.commitdate < l.receiptdate)
      GROUP BY o.orderpriority ORDER BY o.orderpriority
    """, max_groups=16, join_capacity=1 << 17),
    TpchQuery(5, """
      SELECT n.name, sum(l.extendedprice * (1 - l.discount)) AS revenue
      FROM customer c
      JOIN orders o ON c.custkey = o.custkey
      JOIN lineitem l ON l.orderkey = o.orderkey
      JOIN nation n ON c.nationkey = n.nationkey
      JOIN region r ON n.regionkey = r.regionkey
      WHERE r.name = 'ASIA'
        AND o.orderdate >= date '1994-01-01'
        AND o.orderdate < date '1995-01-01'
      GROUP BY n.name ORDER BY revenue DESC
    """, max_groups=64, join_capacity=1 << 18),
    TpchQuery(6, """
      SELECT sum(extendedprice * discount) AS revenue
      FROM lineitem
      WHERE shipdate >= date '1994-01-01'
        AND shipdate < date '1995-01-01'
        AND discount BETWEEN 0.05 AND 0.07
        AND quantity < 24
    """, max_groups=4),
    TpchQuery(7, """
      SELECT n1.name AS supp_nation, n2.name AS cust_nation,
             sum(l.extendedprice * (1 - l.discount)) AS revenue
      FROM lineitem l
      JOIN supplier s ON l.suppkey = s.suppkey
      JOIN orders o ON l.orderkey = o.orderkey
      JOIN customer c ON o.custkey = c.custkey
      JOIN nation n1 ON s.nationkey = n1.nationkey
      JOIN nation n2 ON c.nationkey = n2.nationkey
      WHERE l.shipdate >= date '1995-01-01' AND l.shipdate <= date '1996-12-31'
        AND ((n1.name = 'FRANCE' AND n2.name = 'GERMANY')
             OR (n1.name = 'GERMANY' AND n2.name = 'FRANCE'))
      GROUP BY n1.name, n2.name ORDER BY supp_nation, cust_nation
    """, max_groups=16, join_capacity=1 << 18),
    TpchQuery(8, """
      SELECT year(o.orderdate) AS o_year,
             sum(CASE WHEN n.name = 'BRAZIL'
                 THEN l.extendedprice * (1 - l.discount) ELSE 0 END) AS brazil,
             sum(l.extendedprice * (1 - l.discount)) AS total
      FROM lineitem l
      JOIN orders o ON l.orderkey = o.orderkey
      JOIN customer c ON o.custkey = c.custkey
      JOIN nation n ON c.nationkey = n.nationkey
      WHERE o.orderdate >= date '1995-01-01' AND o.orderdate <= date '1996-12-31'
      GROUP BY year(o.orderdate) ORDER BY o_year
    """, max_groups=16, join_capacity=1 << 18),
    TpchQuery(9, """
      SELECT n.name AS nation, sum(l.extendedprice * (1 - l.discount)) AS profit
      FROM lineitem l
      JOIN part p ON l.partkey = p.partkey
      JOIN supplier s ON l.suppkey = s.suppkey
      JOIN nation n ON s.nationkey = n.nationkey
      WHERE p.name LIKE '%sleep%'
      GROUP BY n.name ORDER BY profit DESC
    """, max_groups=64, join_capacity=1 << 18),
    TpchQuery(10, """
      SELECT c.custkey, c.name, sum(l.extendedprice * (1 - l.discount)) AS rev,
             c.acctbal, n.name AS nation
      FROM customer c
      JOIN orders o ON c.custkey = o.custkey
      JOIN lineitem l ON l.orderkey = o.orderkey
      JOIN nation n ON c.nationkey = n.nationkey
      WHERE o.orderdate >= date '1993-10-01' AND o.orderdate < date '1994-01-01'
        AND l.returnflag = 'R'
      GROUP BY c.custkey, c.name, c.acctbal, n.name
      ORDER BY rev DESC
      LIMIT 20
    """, max_groups=1 << 14, join_capacity=1 << 18),
    TpchQuery(11, """
      SELECT ps.partkey, sum(ps.supplycost * ps.availqty) AS value
      FROM partsupp ps
      GROUP BY ps.partkey
      HAVING sum(ps.supplycost * ps.availqty) >
             (SELECT sum(supplycost * availqty) * 0.001 FROM partsupp)
      ORDER BY value DESC LIMIT 25
    """, max_groups=1 << 13, join_capacity=1 << 15),
    TpchQuery(12, """
      SELECT shipmode,
             sum(CASE WHEN orderpriority = '1-URGENT'
                       OR orderpriority = '2-HIGH' THEN 1 ELSE 0 END) AS high,
             sum(CASE WHEN orderpriority <> '1-URGENT'
                      AND orderpriority <> '2-HIGH' THEN 1 ELSE 0 END) AS low
      FROM orders o JOIN lineitem l ON o.orderkey = l.orderkey
      WHERE l.shipmode IN ('MAIL', 'SHIP')
        AND l.commitdate < l.receiptdate
        AND l.shipdate < l.commitdate
        AND l.receiptdate >= date '1994-01-01'
        AND l.receiptdate < date '1995-01-01'
      GROUP BY shipmode ORDER BY shipmode
    """, max_groups=16, join_capacity=1 << 18),
    TpchQuery(13, """
      SELECT c_count, count(*) AS custdist
      FROM (SELECT custkey, count(*) AS c_count FROM orders
            GROUP BY custkey) c_orders
      GROUP BY c_count ORDER BY custdist DESC, c_count DESC
    """, max_groups=1 << 13),
    TpchQuery(14, """
      SELECT 100.00 * sum(CASE WHEN p.type LIKE 'PROMO%'
                          THEN l.extendedprice * (1 - l.discount)
                          ELSE 0 END)
             / sum(l.extendedprice * (1 - l.discount)) AS promo_revenue
      FROM lineitem l JOIN part p ON l.partkey = p.partkey
      WHERE l.shipdate >= date '1995-09-01' AND l.shipdate < date '1995-10-01'
    """, max_groups=4, join_capacity=1 << 18),
    TpchQuery(15, """
      WITH revenue AS (
        SELECT suppkey AS supplier_no,
               sum(extendedprice * (1 - discount)) AS total_revenue
        FROM lineitem
        WHERE shipdate >= date '1996-01-01' AND shipdate < date '1996-04-01'
        GROUP BY suppkey)
      SELECT s.suppkey, r.total_revenue
      FROM supplier s JOIN revenue r ON s.suppkey = r.supplier_no
      WHERE r.total_revenue >
            (SELECT max(total_revenue) * 0.999 FROM revenue)
      ORDER BY s.suppkey
    """, max_groups=1 << 13, join_capacity=1 << 15),
    TpchQuery(16, """
      SELECT p.brand, p.type, p.size,
             count(DISTINCT ps.suppkey) AS supplier_cnt
      FROM partsupp ps JOIN part p ON p.partkey = ps.partkey
      WHERE p.brand <> 'Brand#45'
        AND p.size IN (9, 14, 23, 45, 19, 3, 36, 49)
        AND ps.suppkey NOT IN (SELECT suppkey FROM supplier
                               WHERE comment LIKE '%carefully%deposits%')
      GROUP BY p.brand, p.type, p.size
      ORDER BY supplier_cnt DESC, p.brand, p.type, p.size
      LIMIT 20
    """, max_groups=1 << 13, join_capacity=1 << 17),
    TpchQuery(17, """
      SELECT sum(l.extendedprice) AS total
      FROM lineitem l JOIN part p ON p.partkey = l.partkey
      WHERE p.brand = 'Brand#23' AND p.container = 'MED BOX'
        AND l.quantity < (SELECT 0.2 * avg(l2.quantity) FROM lineitem l2
                          WHERE l2.partkey = l.partkey)
    """, max_groups=1 << 13, join_capacity=1 << 17),
    TpchQuery(18, """
      SELECT o.custkey, o.orderkey, o.totalprice
      FROM orders o
      WHERE o.orderkey IN (SELECT orderkey FROM lineitem
                           GROUP BY orderkey HAVING sum(quantity) > 210.00)
      ORDER BY o.totalprice DESC LIMIT 20
    """, max_groups=1 << 14),
    TpchQuery(19, """
      SELECT sum(l.extendedprice * (1 - l.discount)) AS revenue
      FROM lineitem l JOIN part p ON l.partkey = p.partkey
      WHERE (p.brand = 'Brand#12' AND l.quantity BETWEEN 1 AND 11
             AND p.size BETWEEN 1 AND 5)
         OR (p.brand = 'Brand#23' AND l.quantity BETWEEN 10 AND 20
             AND p.size BETWEEN 1 AND 10)
         OR (p.brand = 'Brand#34' AND l.quantity BETWEEN 20 AND 30
             AND p.size BETWEEN 1 AND 15)
    """, max_groups=4, join_capacity=1 << 18),
    TpchQuery(20, """
      SELECT count(*) FROM supplier s
      WHERE s.suppkey IN
            (SELECT ps.suppkey FROM partsupp ps
             WHERE ps.availqty > (SELECT 0.5 * sum(l.quantity)
                                  FROM lineitem l
                                  WHERE l.partkey = ps.partkey
                                    AND l.suppkey = ps.suppkey))
    """, max_groups=1 << 17, join_capacity=1 << 17),
    TpchQuery(21, """
      SELECT s.name, count(*) AS numwait
      FROM supplier s
      JOIN lineitem l1 ON s.suppkey = l1.suppkey
      JOIN orders o ON o.orderkey = l1.orderkey
      WHERE o.orderstatus = 'F'
        AND l1.receiptdate > l1.commitdate
        AND EXISTS (SELECT l2.orderkey FROM lineitem l2
                    WHERE l2.orderkey = l1.orderkey
                      AND l2.suppkey <> l1.suppkey)
        AND NOT EXISTS (SELECT l3.orderkey FROM lineitem l3
                        WHERE l3.orderkey = l1.orderkey
                          AND l3.suppkey <> l1.suppkey
                          AND l3.receiptdate > l3.commitdate)
      GROUP BY s.name ORDER BY numwait DESC, s.name LIMIT 10
    """, max_groups=1 << 13, join_capacity=1 << 18),
    TpchQuery(22, """
      SELECT substr(c.phone, 1, 2) AS cntrycode, count(*) AS numcust,
             sum(c.acctbal) AS totacctbal
      FROM customer c
      WHERE substr(c.phone, 1, 2) IN ('13', '31', '23', '29', '30', '18', '17')
        AND c.acctbal > (SELECT avg(acctbal) FROM customer
                         WHERE acctbal > 0.00)
        AND c.custkey NOT IN (SELECT custkey FROM orders)
      GROUP BY substr(c.phone, 1, 2) ORDER BY cntrycode
    """, max_groups=64, join_capacity=1 << 17),
]}


def tpch_query(number: int) -> TpchQuery:
    q = TPCH_QUERIES.get(number)
    if q is None:
        raise KeyError(f"no TPC-H query q{number} in the corpus (1-22)")
    return q


@dataclasses.dataclass
class StagedQuery:
    """Everything the staging-time auditor sees for one query: the
    fused function and the staged scan batches it will be dispatched
    over (call ``fn(tuple(batches))`` -- or trace it)."""
    label: str
    fn: object
    batches: Tuple
    mesh: Optional[object]


def stage_tpch(number: int, sf: float = 0.01,
               mesh=None) -> StagedQuery:
    """Plan + compile + stage one corpus query without dispatching:
    the exact pre-execution state ``audit_staged_query`` audits."""
    from ..exec.planner import compile_plan
    from ..exec.runner import _scan_batch, prepare_plan
    from ..sql import plan_sql

    q = tpch_query(number)
    root = plan_sql(q.text, max_groups=q.max_groups,
                    join_capacity=q.join_capacity)
    root = prepare_plan(root, sf=sf, mesh=mesh)
    plan = compile_plan(root, mesh, q.join_capacity or 1 << 16)
    pad = (mesh.devices.size if mesh is not None else 1) * 8
    batches = tuple(_scan_batch(s, sf, None, pad)
                    for s in plan.scan_nodes)
    label = q.label if mesh is None else f"{q.label}.mesh"
    return StagedQuery(label=label, fn=plan.fn, batches=batches, mesh=mesh)
