"""Hand-assembled TPC-H query pipelines: the engine's flagship programs.

These are the analog of the reference's hand-built operator benchmarks
(presto-benchmark/.../HandTpchQuery1.java, HandTpchQuery6.java): the
physical plan a LocalExecutionPlanner would emit for the benchmark
queries, assembled directly against the ops/expr layers. The plan/exec
layers lower PlanFragment JSON to exactly these compositions; keeping
the hand versions pinned gives bench.py a stable measurement target and
the plan lowering a reference answer.

All builders return jit-able (or shard_map-able) pure functions over
Batch pytrees.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import types as T
from ..block import Batch
from ..expr import call, compile_filter, compile_projections, const, input_ref, special
from ..ops.aggregation import AggSpec, group_by
from ..ops.sort import SortKey, top_n
from ..parallel.mesh import WORKERS_AXIS
from ..parallel.stages import distributed_hash_join, two_stage_group_by

D2 = T.decimal(12, 2)

# ---------------------------------------------------------------------------
# Q1: pricing summary report
#   select returnflag, linestatus, sum(qty), sum(price), sum(disc_price),
#          sum(charge), avg(qty), avg(price), avg(disc), count(*)
#   from lineitem where shipdate <= date '1998-12-01' - interval '90' day
#   group by returnflag, linestatus
# ---------------------------------------------------------------------------

Q1_COLUMNS = ["returnflag", "linestatus", "quantity", "extendedprice",
              "discount", "tax", "shipdate"]
Q1_MAX_GROUPS = 16


def _q1_stage_ops():
    rf, ls = input_ref(0, T.char(1)), input_ref(1, T.char(1))
    qty, price = input_ref(2, D2), input_ref(3, D2)
    disc, tax = input_ref(4, D2), input_ref(5, D2)
    ship = input_ref(6, T.DATE)
    one = const(100, D2)
    filt = compile_filter(call("le", T.BOOLEAN, ship, const("1998-09-02", T.DATE)))
    disc_price = call("multiply", T.decimal(24, 4), price,
                      call("subtract", D2, one, disc))
    charge = call("multiply", T.decimal(36, 6), disc_price,
                  call("add", D2, one, tax))  # (s=4) x (s=2) -> s=6
    proj = compile_projections([rf, ls, qty, price,
                                disc_price, charge, disc])
    aggs = [AggSpec("sum", 2, T.decimal(38, 2)),   # sum_qty
            AggSpec("sum", 3, T.decimal(38, 2)),   # sum_base_price
            AggSpec("sum", 4, T.decimal(38, 4)),   # sum_disc_price
            AggSpec("sum", 5, T.decimal(38, 6)),   # sum_charge
            AggSpec("avg", 2, D2),                 # avg_qty
            AggSpec("avg", 3, D2),                 # avg_price
            AggSpec("avg", 6, D2),                 # avg_disc
            AggSpec("count_star", None, T.BIGINT)]
    return filt, proj, aggs


def q1_local() -> Callable[[Batch], "GroupByResult"]:
    """Single-chip q1: filter -> project -> single-step group-by."""
    filt, proj, aggs = _q1_stage_ops()

    def run(batch: Batch):
        b = proj(filt(batch))
        return group_by(b, [0, 1], aggs, Q1_MAX_GROUPS)

    return run


def q1_distributed(mesh) -> Callable[[Batch], Tuple["GroupByResult", jnp.ndarray]]:
    """Multi-chip q1: per-worker partial agg, ICI exchange of partial
    states, final agg, replicated result (the 2-stage plan AddExchanges
    emits)."""
    filt, proj, aggs = _q1_stage_ops()

    def step(shard: Batch):
        b = proj(filt(shard))
        return two_stage_group_by(b, [0, 1], aggs, Q1_MAX_GROUPS)

    return jax.shard_map(step, mesh=mesh, in_specs=P(WORKERS_AXIS),
                         out_specs=P(), check_vma=False)


# ---------------------------------------------------------------------------
# Q6: forecasting revenue change (pure filter + global sum)
# ---------------------------------------------------------------------------

Q6_COLUMNS = ["shipdate", "discount", "quantity", "extendedprice"]


def q6_local() -> Callable[[Batch], jnp.ndarray]:
    """Returns fn(batch) -> scalar revenue sum (global aggregation has a
    single group; no group table is built)."""
    ship = input_ref(0, T.DATE)
    disc, qty, price = input_ref(1, D2), input_ref(2, D2), input_ref(3, D2)
    filt = compile_filter(special(
        "AND", T.BOOLEAN,
        call("ge", T.BOOLEAN, ship, const("1994-01-01", T.DATE)),
        call("lt", T.BOOLEAN, ship, const("1995-01-01", T.DATE)),
        special("BETWEEN", T.BOOLEAN, disc, const(5, D2), const(7, D2)),
        call("lt", T.BOOLEAN, qty, const(2400, D2))))
    proj = compile_projections([call("multiply", T.decimal(24, 4), price, disc)])

    def run(batch: Batch):
        b = proj(filt(batch))
        # global aggregation (no keys -> one group): a direct masked sum.
        # decimal(24,4) values ride int128 lanes; the exact-sum recipe is
        # the same 13-bit-limb decomposition the group-by kernel uses.
        vals = b.column(0)
        live = b.active & ~vals.nulls
        from ..block import Int128Column
        if isinstance(vals, Int128Column):
            from ..int128 import combine_limb_totals_128, limbs13_of_128
            limbs = limbs13_of_128(vals.hi, vals.lo)
            totals = jnp.stack([jnp.sum(jnp.where(live, l, 0))
                                for l in limbs], axis=-1)
            hi, lo = combine_limb_totals_128(totals[None, :])
            return hi[0], lo[0]
        return jnp.sum(jnp.where(live, vals.values, 0))

    return run


# ---------------------------------------------------------------------------
# Q3: shipping priority (customer JOIN orders JOIN lineitem, group, top 10)
# ---------------------------------------------------------------------------

Q3_CUSTOMER_COLUMNS = ["custkey", "mktsegment"]
Q3_ORDERS_COLUMNS = ["orderkey", "custkey", "orderdate", "shippriority"]
Q3_LINEITEM_COLUMNS = ["orderkey", "extendedprice", "discount", "shipdate"]
Q3_MAX_GROUPS = 1 << 16


def q3_distributed(mesh, join_capacity: int, max_groups: int = Q3_MAX_GROUPS):
    """Distributed q3:
      customer(filter BUILDING) broadcast-joined to orders(filter date),
      result partitioned-exchanged with lineitem(filter date) by orderkey,
      joined, grouped by (orderkey, orderdate, shippriority), top 10 by
      revenue -- the 3-stage plan with one broadcast and one partitioned
      exchange."""
    cutoff = const("1995-03-15", T.DATE)

    cust_filter = compile_filter(call("eq", T.BOOLEAN,
                                      input_ref(1, T.varchar(10)),
                                      const("BUILDING", T.varchar(10))))
    ord_filter = compile_filter(call("lt", T.BOOLEAN, input_ref(2, T.DATE), cutoff))
    li_filter = compile_filter(call("gt", T.BOOLEAN, input_ref(3, T.DATE), cutoff))
    revenue = call("multiply", T.decimal(24, 4), input_ref(1, D2),
                   call("subtract", D2, const(100, D2), input_ref(2, D2)))

    def step(cust: Batch, orders: Batch, li: Batch):
        c = cust_filter(cust)
        o = ord_filter(orders)
        l = li_filter(li)
        # orders JOIN customer on custkey (broadcast small build side)
        oc, ovf1 = distributed_hash_join(
            o, c, probe_keys=[1], build_keys=[0],
            out_capacity=o.capacity, strategy="broadcast",
            build_output_channels=[])  # customer cols not needed downstream
        # lineitem JOIN (orders x customer) on orderkey, partitioned
        lj, ovf2 = distributed_hash_join(
            l, oc.batch, probe_keys=[0], build_keys=[0],
            out_capacity=join_capacity, strategy="partitioned",
            build_output_channels=[2, 3])  # orderdate, shippriority
        # channels: [l.orderkey, extprice, discount, shipdate, orderdate, shippriority]
        b = compile_projections([
            input_ref(0, T.BIGINT), input_ref(4, T.DATE),
            input_ref(5, T.INTEGER), revenue])(lj.batch)
        g, ovf3 = two_stage_group_by(b, [0, 1, 2],
                                     [AggSpec("sum", 3, T.decimal(38, 4))],
                                     max_groups)
        t = top_n(g.batch, [SortKey(3, descending=True), SortKey(1)], 10)
        return t, (ovf1 | ovf2 | ovf3)

    return jax.shard_map(step, mesh=mesh,
                         in_specs=(P(WORKERS_AXIS), P(WORKERS_AXIS), P(WORKERS_AXIS)),
                         out_specs=P(), check_vma=False)
