from .tpch_queries import (q1_local, q1_distributed, q6_local, q3_distributed,
                           Q1_COLUMNS, Q6_COLUMNS, Q3_LINEITEM_COLUMNS,
                           Q3_ORDERS_COLUMNS, Q3_CUSTOMER_COLUMNS)
from .tpch_sql import TPCH_QUERIES, TpchQuery, stage_tpch, tpch_query

__all__ = ["q1_local", "q1_distributed", "q6_local", "q3_distributed",
           "Q1_COLUMNS", "Q6_COLUMNS", "Q3_LINEITEM_COLUMNS",
           "Q3_ORDERS_COLUMNS", "Q3_CUSTOMER_COLUMNS",
           "TPCH_QUERIES", "TpchQuery", "stage_tpch", "tpch_query"]
