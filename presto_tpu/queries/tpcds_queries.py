"""TPC-DS query corpus (engine dialect).

Shapes follow the published TPC-DS benchmark specification (the same
query text the reference ships in presto-benchto-benchmarks/.../tpcds/
q*.sql -- published spec text, parameter-substituted). Adaptations to
this engine's dialect, applied uniformly:

* ``DECIMAL '100.00'``     -> ``100.00``   (plain decimal literals)
* ``CAST('d' AS DATE)``    -> ``date 'd'`` (+- INTERVAL folded into the
                                            literal)
* decimal/decimal division -> double division or integer-side
  multiplication (``10 * x <= y`` for ``x <= 0.1 * y``) so the oracle
  engine computes the identical value
* mixed LEFT JOIN + comma FROM lists (q40/q93) -> explicit JOIN chains
* spec parameter values that our generator's value domains don't
  contain (city/state names) -> values drawn from the generator's
  domains; selectivity structure is preserved

Tests run every query against an independent SQL engine (sqlite) over
the same generated data (tests/tpcds_harness.py) -- the H2QueryRunner
oracle pattern (presto-tests/.../H2QueryRunner.java).
"""

TPCDS_QUERIES = {
    # q3: star join, brand revenue by year
    "q3": """
SELECT dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
       sum(ss_ext_sales_price) sum_agg
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manufact_id = 128 AND dt.d_moy = 11
GROUP BY dt.d_year, item.i_brand, item.i_brand_id
ORDER BY dt.d_year ASC, sum_agg DESC, brand_id ASC
LIMIT 100
""",
    # q7: demographic/promotion averages per item
    "q7": """
SELECT i_item_id, avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
  AND ss_cdemo_sk = cd_demo_sk AND ss_promo_sk = p_promo_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id ASC
LIMIT 100
""",
    # q13: OR-blocks of demographic/address bands (join keys inside ORs)
    "q13": """
SELECT avg(ss_quantity), avg(ss_ext_sales_price),
       avg(ss_ext_wholesale_cost), sum(ss_ext_wholesale_cost)
FROM store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk AND ss_sold_date_sk = d_date_sk
  AND d_year = 2001
  AND ((ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'M'
        AND cd_education_status = 'Advanced Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00
        AND hd_dep_count = 3)
    OR (ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'S' AND cd_education_status = 'College'
        AND ss_sales_price BETWEEN 50.00 AND 100.00
        AND hd_dep_count = 1)
    OR (ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'W'
        AND cd_education_status = '2 yr Degree'
        AND ss_sales_price BETWEEN 150.00 AND 200.00
        AND hd_dep_count = 1))
  AND ((ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('TX', 'OH', 'IL')
        AND ss_net_profit BETWEEN 100.00 AND 200.00)
    OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('CA', 'WA', 'GA')
        AND ss_net_profit BETWEEN 150.00 AND 300.00)
    OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('NY', 'TN', 'IL')
        AND ss_net_profit BETWEEN 50.00 AND 250.00))
""",
    # q15: catalog sales by zip with OR of zip/state/price predicates
    "q15": """
SELECT ca_zip, sum(cs_sales_price)
FROM catalog_sales, customer, customer_address, date_dim
WHERE cs_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND (substr(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405',
                                '86475', '85392', '85460', '80348', '81792')
       OR ca_state IN ('CA', 'WA', 'GA')
       OR cs_sales_price > 500.00)
  AND cs_sold_date_sk = d_date_sk AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip
ORDER BY ca_zip ASC
LIMIT 100
""",
    # q19: brand revenue where buyer and store zips differ
    "q19": """
SELECT i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id = 8 AND d_moy = 11 AND d_year = 1998
  AND ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  AND ss_store_sk = s_store_sk
GROUP BY i_brand, i_brand_id, i_manufact_id, i_manufact
ORDER BY ext_price DESC, i_brand ASC, i_brand_id ASC,
         i_manufact_id ASC, i_manufact ASC
LIMIT 100
""",
    # q21: inventory before/after a cutoff date, ratio-banded
    "q21": """
SELECT *
FROM (SELECT w_warehouse_name, i_item_id,
             sum(CASE WHEN d_date < date '2000-03-11'
                      THEN inv_quantity_on_hand ELSE 0 END) inv_before,
             sum(CASE WHEN d_date >= date '2000-03-11'
                      THEN inv_quantity_on_hand ELSE 0 END) inv_after
      FROM inventory, warehouse, item, date_dim
      WHERE i_current_price BETWEEN 0.99 AND 9.99
        AND i_item_sk = inv_item_sk
        AND inv_warehouse_sk = w_warehouse_sk
        AND inv_date_sk = d_date_sk
        AND d_date BETWEEN date '1999-09-11' AND date '2000-09-11'
      GROUP BY w_warehouse_name, i_item_id) x
WHERE CASE WHEN inv_before > 0
           THEN CAST(inv_after AS double) / inv_before
           ELSE null END BETWEEN 0.666667 AND 1.500
ORDER BY w_warehouse_name ASC, i_item_id ASC
LIMIT 100
""",
    # q25: store sales -> returns -> catalog re-purchase profit chain
    "q25": """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) store_sales_profit,
       sum(sr_net_loss) store_returns_loss,
       sum(cs_net_profit) catalog_sales_profit
FROM store_sales, store_returns, catalog_sales, date_dim d1,
     date_dim d2, date_dim d3, store, item
WHERE d1.d_moy = 4 AND d1.d_year = 2001
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_moy BETWEEN 4 AND 10 AND d2.d_year = 2001
  AND sr_customer_sk = cs_bill_customer_sk AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_moy BETWEEN 4 AND 10 AND d3.d_year = 2001
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id ASC, i_item_desc ASC, s_store_id ASC, s_store_name ASC
LIMIT 100
""",
    # q26: catalog demographic/promotion averages per item
    "q26": """
SELECT i_item_id, avg(cs_quantity) agg1, avg(cs_list_price) agg2,
       avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
FROM catalog_sales, customer_demographics, date_dim, item, promotion
WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd_demo_sk AND cs_promo_sk = p_promo_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id ASC
LIMIT 100
""",
    # q29: quantity flow through sale -> return -> catalog re-purchase
    "q29": """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) store_sales_quantity,
       sum(sr_return_quantity) store_returns_quantity,
       sum(cs_quantity) catalog_sales_quantity
FROM store_sales, store_returns, catalog_sales, date_dim d1,
     date_dim d2, date_dim d3, store, item
WHERE d1.d_moy = 9 AND d1.d_year = 1999
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_moy BETWEEN 9 AND 12 AND d2.d_year = 1999
  AND sr_customer_sk = cs_bill_customer_sk AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_year IN (1999, 2000, 2001)
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id ASC, i_item_desc ASC, s_store_id ASC, s_store_name ASC
LIMIT 100
""",
    # q37: items with mid-range inventory also sold by catalog
    "q37": """
SELECT i_item_id, i_item_desc, i_current_price
FROM item, inventory, date_dim, catalog_sales
WHERE i_current_price BETWEEN 68.00 AND 98.00
  AND inv_item_sk = i_item_sk AND d_date_sk = inv_date_sk
  AND d_date BETWEEN date '2000-02-01' AND date '2000-07-30'
  AND i_manufact_id BETWEEN 600 AND 700
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND cs_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id ASC
LIMIT 100
""",
    # q40: catalog sales net of returns around a cutoff, by warehouse state
    "q40": """
SELECT w_state, i_item_id,
       sum(CASE WHEN d_date < date '2000-03-11'
                THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
                ELSE 0 END) sales_before,
       sum(CASE WHEN d_date >= date '2000-03-11'
                THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
                ELSE 0 END) sales_after
FROM catalog_sales
LEFT JOIN catalog_returns ON cs_order_number = cr_order_number
                         AND cs_item_sk = cr_item_sk
JOIN warehouse ON cs_warehouse_sk = w_warehouse_sk
JOIN item ON i_item_sk = cs_item_sk
JOIN date_dim ON cs_sold_date_sk = d_date_sk
WHERE i_current_price BETWEEN 0.99 AND 1.49
  AND d_date BETWEEN date '2000-02-10' AND date '2000-04-10'
GROUP BY w_state, i_item_id
ORDER BY w_state ASC, i_item_id ASC
LIMIT 100
""",
    # q42: category revenue for a month
    "q42": """
SELECT dt.d_year, item.i_category_id, item.i_category,
       sum(ss_ext_sales_price) s
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manager_id = 1 AND dt.d_moy = 11 AND dt.d_year = 2000
GROUP BY dt.d_year, item.i_category_id, item.i_category
ORDER BY s DESC, dt.d_year ASC, item.i_category_id ASC,
         item.i_category ASC
LIMIT 100
""",
    # q43: store revenue pivoted by day of week
    "q43": """
SELECT s_store_name, s_store_id,
       sum(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price
                ELSE null END) sun_sales,
       sum(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price
                ELSE null END) mon_sales,
       sum(CASE WHEN d_day_name = 'Tuesday' THEN ss_sales_price
                ELSE null END) tue_sales,
       sum(CASE WHEN d_day_name = 'Wednesday' THEN ss_sales_price
                ELSE null END) wed_sales,
       sum(CASE WHEN d_day_name = 'Thursday' THEN ss_sales_price
                ELSE null END) thu_sales,
       sum(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price
                ELSE null END) fri_sales,
       sum(CASE WHEN d_day_name = 'Saturday' THEN ss_sales_price
                ELSE null END) sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk AND s_store_sk = ss_store_sk
  AND s_gmt_offset = -5.00 AND d_year = 2000
GROUP BY s_store_name, s_store_id
ORDER BY s_store_name ASC, s_store_id ASC
LIMIT 100
""",
    # q46: out-of-town weekend shoppers per trip
    "q46": """
SELECT c_last_name, c_first_name, current_addr.ca_city, bought_city,
       ss_ticket_number, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_store_sk = store.s_store_sk
        AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        AND store_sales.ss_addr_sk = customer_address.ca_address_sk
        AND (household_demographics.hd_dep_count = 4
             OR household_demographics.hd_vehicle_count = 3)
        AND date_dim.d_dow IN (6, 0)
        AND date_dim.d_year IN (1999, 2000, 2001)
        AND store.s_city IN ('Fairview', 'Midway')
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name ASC, c_first_name ASC, current_addr.ca_city ASC,
         bought_city ASC, ss_ticket_number ASC
LIMIT 100
""",
    # q48: OR-banded quantity sum (q13 shape without the group keys)
    "q48": """
SELECT sum(ss_quantity)
FROM store_sales, store, customer_demographics, customer_address,
     date_dim
WHERE s_store_sk = ss_store_sk AND ss_sold_date_sk = d_date_sk
  AND d_year = 2000
  AND ((cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'M'
        AND cd_education_status = '4 yr Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00)
    OR (cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'D'
        AND cd_education_status = '2 yr Degree'
        AND ss_sales_price BETWEEN 50.00 AND 100.00)
    OR (cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'S'
        AND cd_education_status = 'College'
        AND ss_sales_price BETWEEN 150.00 AND 200.00))
  AND ((ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('TX', 'OH', 'IL')
        AND ss_net_profit BETWEEN 0.00 AND 2000.00)
    OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('CA', 'WA', 'GA')
        AND ss_net_profit BETWEEN 150.00 AND 3000.00)
    OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('NY', 'TN', 'IL')
        AND ss_net_profit BETWEEN 50.00 AND 25000.00))
""",
    # q50: return-lag buckets per store
    "q50": """
SELECT s_store_name, s_company_id, s_street_number, s_street_name,
       s_street_type, s_suite_number, s_city, s_county, s_state, s_zip,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk <= 30
                THEN 1 ELSE 0 END) days_30,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 30
                 AND sr_returned_date_sk - ss_sold_date_sk <= 60
                THEN 1 ELSE 0 END) days_31_60,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 60
                 AND sr_returned_date_sk - ss_sold_date_sk <= 90
                THEN 1 ELSE 0 END) days_61_90,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 90
                 AND sr_returned_date_sk - ss_sold_date_sk <= 120
                THEN 1 ELSE 0 END) days_91_120,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 120
                THEN 1 ELSE 0 END) days_gt_120
FROM store_sales, store_returns, store, date_dim d1, date_dim d2
WHERE d2.d_year = 2001 AND d2.d_moy = 8
  AND ss_ticket_number = sr_ticket_number AND ss_item_sk = sr_item_sk
  AND ss_sold_date_sk = d1.d_date_sk
  AND sr_returned_date_sk = d2.d_date_sk
  AND ss_customer_sk = sr_customer_sk AND ss_store_sk = s_store_sk
GROUP BY s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
ORDER BY s_store_name ASC, s_company_id ASC, s_street_number ASC,
         s_street_name ASC, s_street_type ASC, s_suite_number ASC,
         s_city ASC, s_county ASC, s_state ASC, s_zip ASC
LIMIT 100
""",
    # q52: brand revenue for a month
    "q52": """
SELECT dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
       sum(ss_ext_sales_price) ext_price
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manager_id = 1 AND dt.d_moy = 11 AND dt.d_year = 2000
GROUP BY dt.d_year, item.i_brand, item.i_brand_id
ORDER BY dt.d_year ASC, ext_price DESC, brand_id ASC
LIMIT 100
""",
    # q55: brand revenue for a manager's month
    "q55": """
SELECT i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id = 28 AND d_moy = 11 AND d_year = 1999
GROUP BY i_brand, i_brand_id
ORDER BY ext_price DESC, brand_id ASC
LIMIT 100
""",
    # q62: web shipping-lag buckets by warehouse/mode/site
    "q62": """
SELECT substr(w_warehouse_name, 1, 20) wname, sm_type, web_name,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk <= 30
                THEN 1 ELSE 0 END) days_30,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 30
                 AND ws_ship_date_sk - ws_sold_date_sk <= 60
                THEN 1 ELSE 0 END) days_31_60,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 60
                 AND ws_ship_date_sk - ws_sold_date_sk <= 90
                THEN 1 ELSE 0 END) days_61_90,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 90
                 AND ws_ship_date_sk - ws_sold_date_sk <= 120
                THEN 1 ELSE 0 END) days_91_120,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 120
                THEN 1 ELSE 0 END) days_gt_120
FROM web_sales, warehouse, ship_mode, web_site, date_dim
WHERE d_month_seq BETWEEN 1200 AND 1211
  AND ws_ship_date_sk = d_date_sk
  AND ws_warehouse_sk = w_warehouse_sk
  AND ws_ship_mode_sk = sm_ship_mode_sk
  AND ws_web_site_sk = web_site_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, web_name
ORDER BY wname ASC, sm_type ASC, web_name ASC
LIMIT 100
""",
    # q65: items selling below a tenth of their store's average revenue
    # (spec's `revenue <= 0.1 * ave` written integer-side: 10*rev <= ave)
    "q65": """
SELECT s_store_name, i_item_desc, sc.revenue, i_current_price,
       i_wholesale_cost, i_brand
FROM store, item,
     (SELECT ss_store_sk, avg(revenue) ave
      FROM (SELECT ss_store_sk, ss_item_sk, sum(ss_sales_price) revenue
            FROM store_sales, date_dim
            WHERE ss_sold_date_sk = d_date_sk
              AND d_month_seq BETWEEN 1176 AND 1187
            GROUP BY ss_store_sk, ss_item_sk) sa
      GROUP BY ss_store_sk) sb,
     (SELECT ss_store_sk, ss_item_sk, sum(ss_sales_price) revenue
      FROM store_sales, date_dim
      WHERE ss_sold_date_sk = d_date_sk
        AND d_month_seq BETWEEN 1176 AND 1187
      GROUP BY ss_store_sk, ss_item_sk) sc
WHERE sb.ss_store_sk = sc.ss_store_sk
  AND 10 * sc.revenue <= sb.ave
  AND s_store_sk = sc.ss_store_sk
  AND i_item_sk = sc.ss_item_sk
ORDER BY s_store_name ASC, i_item_desc ASC, sc.revenue ASC,
         i_current_price ASC, i_wholesale_cost ASC, i_brand ASC
LIMIT 100
""",
    # q68: two-day city trips with differing current address
    "q68": """
SELECT c_last_name, c_first_name, current_addr.ca_city, bought_city,
       ss_ticket_number, extended_price, extended_tax, list_price
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_ext_sales_price) extended_price,
             sum(ss_ext_list_price) list_price,
             sum(ss_ext_tax) extended_tax
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_store_sk = store.s_store_sk
        AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        AND store_sales.ss_addr_sk = customer_address.ca_address_sk
        AND date_dim.d_dom BETWEEN 1 AND 2
        AND (household_demographics.hd_dep_count = 4
             OR household_demographics.hd_vehicle_count = 3)
        AND date_dim.d_year IN (1999, 2000, 2001)
        AND store.s_city IN ('Midway', 'Fairview')
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name ASC, ss_ticket_number ASC
LIMIT 100
""",
    # q73: frequent-shopper tickets (1-5 items) for big households
    "q73": """
SELECT c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_store_sk = store.s_store_sk
        AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        AND date_dim.d_dom BETWEEN 1 AND 2
        AND (household_demographics.hd_buy_potential = '>10000'
             OR household_demographics.hd_buy_potential = 'Unknown')
        AND household_demographics.hd_vehicle_count > 0
        AND CASE WHEN household_demographics.hd_vehicle_count > 0
                 THEN CAST(household_demographics.hd_dep_count AS double)
                      / household_demographics.hd_vehicle_count
                 ELSE null END > 1
        AND date_dim.d_year IN (1999, 2000, 2001)
        AND store.s_county IN ('Williamson County', 'Franklin Parish',
                               'Bronx County', 'Walker County')
      GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
WHERE ss_customer_sk = c_customer_sk AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name ASC
""",
    # q79: Monday shopping trips for large/mobile households
    "q79": """
SELECT c_last_name, c_first_name, substr(s_city, 1, 30) city,
       ss_ticket_number, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, store.s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_store_sk = store.s_store_sk
        AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        AND (household_demographics.hd_dep_count = 6
             OR household_demographics.hd_vehicle_count > 2)
        AND date_dim.d_dow = 1
        AND date_dim.d_year IN (1999, 2000, 2001)
        AND store.s_number_employees BETWEEN 200 AND 295
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               store.s_city) ms, customer
WHERE ss_customer_sk = c_customer_sk
ORDER BY c_last_name ASC, c_first_name ASC, city ASC, profit ASC,
         ss_ticket_number ASC
LIMIT 100
""",
    # q82: items with mid-range inventory also sold in store
    "q82": """
SELECT i_item_id, i_item_desc, i_current_price
FROM item, inventory, date_dim, store_sales
WHERE i_current_price BETWEEN 62.00 AND 92.00
  AND inv_item_sk = i_item_sk AND d_date_sk = inv_date_sk
  AND d_date BETWEEN date '2000-03-25' AND date '2000-09-24'
  AND i_manufact_id BETWEEN 120 AND 220
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND ss_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id ASC
LIMIT 100
""",
    # q84: income-band customers with store returns
    "q84": """
SELECT c_customer_id customer_id,
       concat(c_last_name, ', ', c_first_name) customername
FROM customer, customer_address, customer_demographics,
     household_demographics, income_band, store_returns
WHERE ca_city = 'Midway' AND c_current_addr_sk = ca_address_sk
  AND ib_lower_bound >= 38128 AND ib_upper_bound <= 88128
  AND ib_income_band_sk = hd_income_band_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND sr_cdemo_sk = cd_demo_sk
ORDER BY c_customer_id ASC
LIMIT 100
""",
    # q91: call-center catalog-return losses for a demographic slice
    "q91": """
SELECT cc_call_center_id call_center, cc_name call_center_name,
       cc_manager manager, sum(cr_net_loss) returns_loss
FROM call_center, catalog_returns, date_dim, customer,
     customer_address, customer_demographics, household_demographics
WHERE cr_call_center_sk = cc_call_center_sk
  AND cr_returned_date_sk = d_date_sk
  AND cr_returning_customer_sk = c_customer_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND ca_address_sk = c_current_addr_sk
  AND d_year = 1998 AND d_moy = 11
  AND ((cd_marital_status = 'M'
        AND cd_education_status IN ('Unknown', 'College', 'Primary',
                                    'Secondary'))
    OR (cd_marital_status = 'W'
        AND cd_education_status IN ('Advanced Degree', '2 yr Degree',
                                    '4 yr Degree')))
  AND hd_buy_potential LIKE 'Unknown%'
  AND ca_gmt_offset = -7.00
GROUP BY cc_call_center_id, cc_name, cc_manager, cd_marital_status,
         cd_education_status
ORDER BY returns_loss DESC
""",
    # q93: actual sales net of returns per customer (explicit-join form
    # of the spec's LEFT JOIN + comma FROM; the WHERE on sr_reason_sk
    # makes the join effectively inner, as in the reference text)
    "q93": """
SELECT ss_customer_sk, sum(act_sales) sumsales
FROM (SELECT ss_item_sk, ss_ticket_number, ss_customer_sk,
             CASE WHEN sr_return_quantity IS NOT NULL
                  THEN (ss_quantity - sr_return_quantity) * ss_sales_price
                  ELSE ss_quantity * ss_sales_price END act_sales
      FROM store_sales
      JOIN store_returns ON sr_item_sk = ss_item_sk
                        AND sr_ticket_number = ss_ticket_number
      JOIN reason ON sr_reason_sk = r_reason_sk
      WHERE r_reason_desc = 'Package was damaged') t
GROUP BY ss_customer_sk
ORDER BY sumsales ASC, ss_customer_sk ASC
LIMIT 100
""",
    # q96: count of store sales in an evening hour to big households
    "q96": """
SELECT count(*) cnt
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = time_dim.t_time_sk
  AND ss_hdemo_sk = household_demographics.hd_demo_sk
  AND ss_store_sk = s_store_sk
  AND time_dim.t_hour = 20 AND time_dim.t_minute >= 30
  AND household_demographics.hd_dep_count = 7
  AND store.s_store_name = 'ese'
ORDER BY count(*) ASC
LIMIT 100
""",
    # q99: catalog shipping-lag buckets by warehouse/mode/call center
    "q99": """
SELECT substr(w_warehouse_name, 1, 20) wname, sm_type, cc_name,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk <= 30
                THEN 1 ELSE 0 END) days_30,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 30
                 AND cs_ship_date_sk - cs_sold_date_sk <= 60
                THEN 1 ELSE 0 END) days_31_60,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 60
                 AND cs_ship_date_sk - cs_sold_date_sk <= 90
                THEN 1 ELSE 0 END) days_61_90,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 90
                 AND cs_ship_date_sk - cs_sold_date_sk <= 120
                THEN 1 ELSE 0 END) days_91_120,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 120
                THEN 1 ELSE 0 END) days_gt_120
FROM catalog_sales, warehouse, ship_mode, call_center, date_dim
WHERE d_month_seq BETWEEN 1200 AND 1211
  AND cs_ship_date_sk = d_date_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_ship_mode_sk = sm_ship_mode_sk
  AND cs_call_center_sk = cc_call_center_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, cc_name
ORDER BY wname ASC, sm_type ASC, cc_name ASC
LIMIT 100
""",
}
