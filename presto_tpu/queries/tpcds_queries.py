"""TPC-DS query corpus (engine dialect).

Shapes follow the published TPC-DS benchmark specification (the same
query text the reference ships in presto-benchto-benchmarks/.../tpcds/
q*.sql -- published spec text, parameter-substituted). Adaptations to
this engine's dialect, applied uniformly:

* ``DECIMAL '100.00'``     -> ``100.00``   (plain decimal literals)
* ``CAST('d' AS DATE)``    -> ``date 'd'`` (+- INTERVAL folded into the
                                            literal)
* decimal/decimal division -> double division or integer-side
  multiplication (``10 * x <= y`` for ``x <= 0.1 * y``) so the oracle
  engine computes the identical value
* mixed LEFT JOIN + comma FROM lists (q40/q93) -> explicit JOIN chains
* spec parameter values that our generator's value domains don't
  contain (city/state names, class/brand lists) -> values drawn from
  the generator's domains; selectivity structure is preserved
* ROLLUP queries (q18/q22/q27/q36/q86) drop their LIMIT so the oracle
  comparison is full-set (LIMIT over tied orderings is ambiguous at
  test scale); sqlite has no ROLLUP, so their oracles are explicit
  UNION ALL level stacks (see TPCDS_ORACLE below)
* q34's cnt band starts at 1 and q76 inverts IS NULL -> IS NOT NULL
  (this generator emits independent ticket lines and no NULL link
  keys; both documented at the query)
* spec CASTs like avg(CAST(x AS DECIMAL(12,2))) read as plain avg(x)
  (same quotient; the comparator tolerates the cents rounding)

Tests run every query against an independent SQL engine (sqlite) over
the same generated data (tests/tpcds_harness.py) -- the H2QueryRunner
oracle pattern (presto-tests/.../H2QueryRunner.java).
"""

TPCDS_QUERIES = {
    # q3: star join, brand revenue by year
    "q3": """
SELECT dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
       sum(ss_ext_sales_price) sum_agg
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manufact_id = 128 AND dt.d_moy = 11
GROUP BY dt.d_year, item.i_brand, item.i_brand_id
ORDER BY dt.d_year ASC, sum_agg DESC, brand_id ASC
LIMIT 100
""",
    # q7: demographic/promotion averages per item
    "q7": """
SELECT i_item_id, avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
  AND ss_cdemo_sk = cd_demo_sk AND ss_promo_sk = p_promo_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id ASC
LIMIT 100
""",
    # q13: OR-blocks of demographic/address bands (join keys inside ORs)
    "q13": """
SELECT avg(ss_quantity), avg(ss_ext_sales_price),
       avg(ss_ext_wholesale_cost), sum(ss_ext_wholesale_cost)
FROM store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk AND ss_sold_date_sk = d_date_sk
  AND d_year = 2001
  AND ((ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'M'
        AND cd_education_status = 'Advanced Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00
        AND hd_dep_count = 3)
    OR (ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'S' AND cd_education_status = 'College'
        AND ss_sales_price BETWEEN 50.00 AND 100.00
        AND hd_dep_count = 1)
    OR (ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'W'
        AND cd_education_status = '2 yr Degree'
        AND ss_sales_price BETWEEN 150.00 AND 200.00
        AND hd_dep_count = 1))
  AND ((ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('TX', 'OH', 'IL')
        AND ss_net_profit BETWEEN 100.00 AND 200.00)
    OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('CA', 'WA', 'GA')
        AND ss_net_profit BETWEEN 150.00 AND 300.00)
    OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('NY', 'TN', 'IL')
        AND ss_net_profit BETWEEN 50.00 AND 250.00))
""",
    # q15: catalog sales by zip with OR of zip/state/price predicates
    "q15": """
SELECT ca_zip, sum(cs_sales_price)
FROM catalog_sales, customer, customer_address, date_dim
WHERE cs_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND (substr(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405',
                                '86475', '85392', '85460', '80348', '81792')
       OR ca_state IN ('CA', 'WA', 'GA')
       OR cs_sales_price > 500.00)
  AND cs_sold_date_sk = d_date_sk AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip
ORDER BY ca_zip ASC
LIMIT 100
""",
    # q19: brand revenue where buyer and store zips differ
    "q19": """
SELECT i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id = 8 AND d_moy = 11 AND d_year = 1998
  AND ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  AND ss_store_sk = s_store_sk
GROUP BY i_brand, i_brand_id, i_manufact_id, i_manufact
ORDER BY ext_price DESC, i_brand ASC, i_brand_id ASC,
         i_manufact_id ASC, i_manufact ASC
LIMIT 100
""",
    # q21: inventory before/after a cutoff date, ratio-banded
    "q21": """
SELECT *
FROM (SELECT w_warehouse_name, i_item_id,
             sum(CASE WHEN d_date < date '2000-03-11'
                      THEN inv_quantity_on_hand ELSE 0 END) inv_before,
             sum(CASE WHEN d_date >= date '2000-03-11'
                      THEN inv_quantity_on_hand ELSE 0 END) inv_after
      FROM inventory, warehouse, item, date_dim
      WHERE i_current_price BETWEEN 0.99 AND 9.99
        AND i_item_sk = inv_item_sk
        AND inv_warehouse_sk = w_warehouse_sk
        AND inv_date_sk = d_date_sk
        AND d_date BETWEEN date '1999-09-11' AND date '2000-09-11'
      GROUP BY w_warehouse_name, i_item_id) x
WHERE CASE WHEN inv_before > 0
           THEN CAST(inv_after AS double) / inv_before
           ELSE null END BETWEEN 0.666667 AND 1.500
ORDER BY w_warehouse_name ASC, i_item_id ASC
LIMIT 100
""",
    # q25: store sales -> returns -> catalog re-purchase profit chain
    "q25": """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) store_sales_profit,
       sum(sr_net_loss) store_returns_loss,
       sum(cs_net_profit) catalog_sales_profit
FROM store_sales, store_returns, catalog_sales, date_dim d1,
     date_dim d2, date_dim d3, store, item
WHERE d1.d_moy = 4 AND d1.d_year = 2001
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_moy BETWEEN 4 AND 10 AND d2.d_year = 2001
  AND sr_customer_sk = cs_bill_customer_sk AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_moy BETWEEN 4 AND 10 AND d3.d_year = 2001
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id ASC, i_item_desc ASC, s_store_id ASC, s_store_name ASC
LIMIT 100
""",
    # q26: catalog demographic/promotion averages per item
    "q26": """
SELECT i_item_id, avg(cs_quantity) agg1, avg(cs_list_price) agg2,
       avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
FROM catalog_sales, customer_demographics, date_dim, item, promotion
WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd_demo_sk AND cs_promo_sk = p_promo_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id ASC
LIMIT 100
""",
    # q29: quantity flow through sale -> return -> catalog re-purchase
    "q29": """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) store_sales_quantity,
       sum(sr_return_quantity) store_returns_quantity,
       sum(cs_quantity) catalog_sales_quantity
FROM store_sales, store_returns, catalog_sales, date_dim d1,
     date_dim d2, date_dim d3, store, item
WHERE d1.d_moy = 9 AND d1.d_year = 1999
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_moy BETWEEN 9 AND 12 AND d2.d_year = 1999
  AND sr_customer_sk = cs_bill_customer_sk AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_year IN (1999, 2000, 2001)
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id ASC, i_item_desc ASC, s_store_id ASC, s_store_name ASC
LIMIT 100
""",
    # q37: items with mid-range inventory also sold by catalog
    "q37": """
SELECT i_item_id, i_item_desc, i_current_price
FROM item, inventory, date_dim, catalog_sales
WHERE i_current_price BETWEEN 68.00 AND 98.00
  AND inv_item_sk = i_item_sk AND d_date_sk = inv_date_sk
  AND d_date BETWEEN date '2000-02-01' AND date '2000-07-30'
  AND i_manufact_id BETWEEN 600 AND 700
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND cs_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id ASC
LIMIT 100
""",
    # q40: catalog sales net of returns around a cutoff, by warehouse state
    "q40": """
SELECT w_state, i_item_id,
       sum(CASE WHEN d_date < date '2000-03-11'
                THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
                ELSE 0 END) sales_before,
       sum(CASE WHEN d_date >= date '2000-03-11'
                THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
                ELSE 0 END) sales_after
FROM catalog_sales
LEFT JOIN catalog_returns ON cs_order_number = cr_order_number
                         AND cs_item_sk = cr_item_sk
JOIN warehouse ON cs_warehouse_sk = w_warehouse_sk
JOIN item ON i_item_sk = cs_item_sk
JOIN date_dim ON cs_sold_date_sk = d_date_sk
WHERE i_current_price BETWEEN 0.99 AND 1.49
  AND d_date BETWEEN date '2000-02-10' AND date '2000-04-10'
GROUP BY w_state, i_item_id
ORDER BY w_state ASC, i_item_id ASC
LIMIT 100
""",
    # q42: category revenue for a month
    "q42": """
SELECT dt.d_year, item.i_category_id, item.i_category,
       sum(ss_ext_sales_price) s
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manager_id = 1 AND dt.d_moy = 11 AND dt.d_year = 2000
GROUP BY dt.d_year, item.i_category_id, item.i_category
ORDER BY s DESC, dt.d_year ASC, item.i_category_id ASC,
         item.i_category ASC
LIMIT 100
""",
    # q43: store revenue pivoted by day of week
    "q43": """
SELECT s_store_name, s_store_id,
       sum(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price
                ELSE null END) sun_sales,
       sum(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price
                ELSE null END) mon_sales,
       sum(CASE WHEN d_day_name = 'Tuesday' THEN ss_sales_price
                ELSE null END) tue_sales,
       sum(CASE WHEN d_day_name = 'Wednesday' THEN ss_sales_price
                ELSE null END) wed_sales,
       sum(CASE WHEN d_day_name = 'Thursday' THEN ss_sales_price
                ELSE null END) thu_sales,
       sum(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price
                ELSE null END) fri_sales,
       sum(CASE WHEN d_day_name = 'Saturday' THEN ss_sales_price
                ELSE null END) sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk AND s_store_sk = ss_store_sk
  AND s_gmt_offset = -5.00 AND d_year = 2000
GROUP BY s_store_name, s_store_id
ORDER BY s_store_name ASC, s_store_id ASC
LIMIT 100
""",
    # q46: out-of-town weekend shoppers per trip
    "q46": """
SELECT c_last_name, c_first_name, current_addr.ca_city, bought_city,
       ss_ticket_number, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_store_sk = store.s_store_sk
        AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        AND store_sales.ss_addr_sk = customer_address.ca_address_sk
        AND (household_demographics.hd_dep_count = 4
             OR household_demographics.hd_vehicle_count = 3)
        AND date_dim.d_dow IN (6, 0)
        AND date_dim.d_year IN (1999, 2000, 2001)
        AND store.s_city IN ('Fairview', 'Midway')
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name ASC, c_first_name ASC, current_addr.ca_city ASC,
         bought_city ASC, ss_ticket_number ASC
LIMIT 100
""",
    # q48: OR-banded quantity sum (q13 shape without the group keys)
    "q48": """
SELECT sum(ss_quantity)
FROM store_sales, store, customer_demographics, customer_address,
     date_dim
WHERE s_store_sk = ss_store_sk AND ss_sold_date_sk = d_date_sk
  AND d_year = 2000
  AND ((cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'M'
        AND cd_education_status = '4 yr Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00)
    OR (cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'D'
        AND cd_education_status = '2 yr Degree'
        AND ss_sales_price BETWEEN 50.00 AND 100.00)
    OR (cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'S'
        AND cd_education_status = 'College'
        AND ss_sales_price BETWEEN 150.00 AND 200.00))
  AND ((ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('TX', 'OH', 'IL')
        AND ss_net_profit BETWEEN 0.00 AND 2000.00)
    OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('CA', 'WA', 'GA')
        AND ss_net_profit BETWEEN 150.00 AND 3000.00)
    OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('NY', 'TN', 'IL')
        AND ss_net_profit BETWEEN 50.00 AND 25000.00))
""",
    # q50: return-lag buckets per store
    "q50": """
SELECT s_store_name, s_company_id, s_street_number, s_street_name,
       s_street_type, s_suite_number, s_city, s_county, s_state, s_zip,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk <= 30
                THEN 1 ELSE 0 END) days_30,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 30
                 AND sr_returned_date_sk - ss_sold_date_sk <= 60
                THEN 1 ELSE 0 END) days_31_60,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 60
                 AND sr_returned_date_sk - ss_sold_date_sk <= 90
                THEN 1 ELSE 0 END) days_61_90,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 90
                 AND sr_returned_date_sk - ss_sold_date_sk <= 120
                THEN 1 ELSE 0 END) days_91_120,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 120
                THEN 1 ELSE 0 END) days_gt_120
FROM store_sales, store_returns, store, date_dim d1, date_dim d2
WHERE d2.d_year = 2001 AND d2.d_moy = 8
  AND ss_ticket_number = sr_ticket_number AND ss_item_sk = sr_item_sk
  AND ss_sold_date_sk = d1.d_date_sk
  AND sr_returned_date_sk = d2.d_date_sk
  AND ss_customer_sk = sr_customer_sk AND ss_store_sk = s_store_sk
GROUP BY s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
ORDER BY s_store_name ASC, s_company_id ASC, s_street_number ASC,
         s_street_name ASC, s_street_type ASC, s_suite_number ASC,
         s_city ASC, s_county ASC, s_state ASC, s_zip ASC
LIMIT 100
""",
    # q52: brand revenue for a month
    "q52": """
SELECT dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
       sum(ss_ext_sales_price) ext_price
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manager_id = 1 AND dt.d_moy = 11 AND dt.d_year = 2000
GROUP BY dt.d_year, item.i_brand, item.i_brand_id
ORDER BY dt.d_year ASC, ext_price DESC, brand_id ASC
LIMIT 100
""",
    # q55: brand revenue for a manager's month
    "q55": """
SELECT i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id = 28 AND d_moy = 11 AND d_year = 1999
GROUP BY i_brand, i_brand_id
ORDER BY ext_price DESC, brand_id ASC
LIMIT 100
""",
    # q62: web shipping-lag buckets by warehouse/mode/site
    "q62": """
SELECT substr(w_warehouse_name, 1, 20) wname, sm_type, web_name,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk <= 30
                THEN 1 ELSE 0 END) days_30,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 30
                 AND ws_ship_date_sk - ws_sold_date_sk <= 60
                THEN 1 ELSE 0 END) days_31_60,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 60
                 AND ws_ship_date_sk - ws_sold_date_sk <= 90
                THEN 1 ELSE 0 END) days_61_90,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 90
                 AND ws_ship_date_sk - ws_sold_date_sk <= 120
                THEN 1 ELSE 0 END) days_91_120,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 120
                THEN 1 ELSE 0 END) days_gt_120
FROM web_sales, warehouse, ship_mode, web_site, date_dim
WHERE d_month_seq BETWEEN 1200 AND 1211
  AND ws_ship_date_sk = d_date_sk
  AND ws_warehouse_sk = w_warehouse_sk
  AND ws_ship_mode_sk = sm_ship_mode_sk
  AND ws_web_site_sk = web_site_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, web_name
ORDER BY wname ASC, sm_type ASC, web_name ASC
LIMIT 100
""",
    # q65: items selling below a tenth of their store's average revenue
    # (spec's `revenue <= 0.1 * ave` written integer-side: 10*rev <= ave)
    "q65": """
SELECT s_store_name, i_item_desc, sc.revenue, i_current_price,
       i_wholesale_cost, i_brand
FROM store, item,
     (SELECT ss_store_sk, avg(revenue) ave
      FROM (SELECT ss_store_sk, ss_item_sk, sum(ss_sales_price) revenue
            FROM store_sales, date_dim
            WHERE ss_sold_date_sk = d_date_sk
              AND d_month_seq BETWEEN 1176 AND 1187
            GROUP BY ss_store_sk, ss_item_sk) sa
      GROUP BY ss_store_sk) sb,
     (SELECT ss_store_sk, ss_item_sk, sum(ss_sales_price) revenue
      FROM store_sales, date_dim
      WHERE ss_sold_date_sk = d_date_sk
        AND d_month_seq BETWEEN 1176 AND 1187
      GROUP BY ss_store_sk, ss_item_sk) sc
WHERE sb.ss_store_sk = sc.ss_store_sk
  AND 10 * sc.revenue <= sb.ave
  AND s_store_sk = sc.ss_store_sk
  AND i_item_sk = sc.ss_item_sk
ORDER BY s_store_name ASC, i_item_desc ASC, sc.revenue ASC,
         i_current_price ASC, i_wholesale_cost ASC, i_brand ASC
LIMIT 100
""",
    # q68: two-day city trips with differing current address
    "q68": """
SELECT c_last_name, c_first_name, current_addr.ca_city, bought_city,
       ss_ticket_number, extended_price, extended_tax, list_price
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_ext_sales_price) extended_price,
             sum(ss_ext_list_price) list_price,
             sum(ss_ext_tax) extended_tax
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_store_sk = store.s_store_sk
        AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        AND store_sales.ss_addr_sk = customer_address.ca_address_sk
        AND date_dim.d_dom BETWEEN 1 AND 2
        AND (household_demographics.hd_dep_count = 4
             OR household_demographics.hd_vehicle_count = 3)
        AND date_dim.d_year IN (1999, 2000, 2001)
        AND store.s_city IN ('Midway', 'Fairview')
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name ASC, ss_ticket_number ASC
LIMIT 100
""",
    # q73: frequent-shopper tickets (1-5 items) for big households
    "q73": """
SELECT c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_store_sk = store.s_store_sk
        AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        AND date_dim.d_dom BETWEEN 1 AND 2
        AND (household_demographics.hd_buy_potential = '>10000'
             OR household_demographics.hd_buy_potential = 'Unknown')
        AND household_demographics.hd_vehicle_count > 0
        AND CASE WHEN household_demographics.hd_vehicle_count > 0
                 THEN CAST(household_demographics.hd_dep_count AS double)
                      / household_demographics.hd_vehicle_count
                 ELSE null END > 1
        AND date_dim.d_year IN (1999, 2000, 2001)
        AND store.s_county IN ('Williamson County', 'Franklin Parish',
                               'Bronx County', 'Walker County')
      GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
WHERE ss_customer_sk = c_customer_sk AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name ASC
""",
    # q79: Monday shopping trips for large/mobile households
    "q79": """
SELECT c_last_name, c_first_name, substr(s_city, 1, 30) city,
       ss_ticket_number, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, store.s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_store_sk = store.s_store_sk
        AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        AND (household_demographics.hd_dep_count = 6
             OR household_demographics.hd_vehicle_count > 2)
        AND date_dim.d_dow = 1
        AND date_dim.d_year IN (1999, 2000, 2001)
        AND store.s_number_employees BETWEEN 200 AND 295
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               store.s_city) ms, customer
WHERE ss_customer_sk = c_customer_sk
ORDER BY c_last_name ASC, c_first_name ASC, city ASC, profit ASC,
         ss_ticket_number ASC
LIMIT 100
""",
    # q82: items with mid-range inventory also sold in store
    "q82": """
SELECT i_item_id, i_item_desc, i_current_price
FROM item, inventory, date_dim, store_sales
WHERE i_current_price BETWEEN 62.00 AND 92.00
  AND inv_item_sk = i_item_sk AND d_date_sk = inv_date_sk
  AND d_date BETWEEN date '2000-03-25' AND date '2000-09-24'
  AND i_manufact_id BETWEEN 120 AND 220
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND ss_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id ASC
LIMIT 100
""",
    # q84: income-band customers with store returns
    "q84": """
SELECT c_customer_id customer_id,
       concat(c_last_name, ', ', c_first_name) customername
FROM customer, customer_address, customer_demographics,
     household_demographics, income_band, store_returns
WHERE ca_city = 'Midway' AND c_current_addr_sk = ca_address_sk
  AND ib_lower_bound >= 38128 AND ib_upper_bound <= 88128
  AND ib_income_band_sk = hd_income_band_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND sr_cdemo_sk = cd_demo_sk
ORDER BY c_customer_id ASC
LIMIT 100
""",
    # q91: call-center catalog-return losses for a demographic slice
    "q91": """
SELECT cc_call_center_id call_center, cc_name call_center_name,
       cc_manager manager, sum(cr_net_loss) returns_loss
FROM call_center, catalog_returns, date_dim, customer,
     customer_address, customer_demographics, household_demographics
WHERE cr_call_center_sk = cc_call_center_sk
  AND cr_returned_date_sk = d_date_sk
  AND cr_returning_customer_sk = c_customer_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND ca_address_sk = c_current_addr_sk
  AND d_year = 1998 AND d_moy = 11
  AND ((cd_marital_status = 'M'
        AND cd_education_status IN ('Unknown', 'College', 'Primary',
                                    'Secondary'))
    OR (cd_marital_status = 'W'
        AND cd_education_status IN ('Advanced Degree', '2 yr Degree',
                                    '4 yr Degree')))
  AND hd_buy_potential LIKE 'Unknown%'
  AND ca_gmt_offset = -7.00
GROUP BY cc_call_center_id, cc_name, cc_manager, cd_marital_status,
         cd_education_status
ORDER BY returns_loss DESC
""",
    # q93: actual sales net of returns per customer (explicit-join form
    # of the spec's LEFT JOIN + comma FROM; the WHERE on sr_reason_sk
    # makes the join effectively inner, as in the reference text)
    "q93": """
SELECT ss_customer_sk, sum(act_sales) sumsales
FROM (SELECT ss_item_sk, ss_ticket_number, ss_customer_sk,
             CASE WHEN sr_return_quantity IS NOT NULL
                  THEN (ss_quantity - sr_return_quantity) * ss_sales_price
                  ELSE ss_quantity * ss_sales_price END act_sales
      FROM store_sales
      JOIN store_returns ON sr_item_sk = ss_item_sk
                        AND sr_ticket_number = ss_ticket_number
      JOIN reason ON sr_reason_sk = r_reason_sk
      WHERE r_reason_desc = 'Package was damaged') t
GROUP BY ss_customer_sk
ORDER BY sumsales ASC, ss_customer_sk ASC
LIMIT 100
""",
    # q96: count of store sales in an evening hour to big households
    "q96": """
SELECT count(*) cnt
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = time_dim.t_time_sk
  AND ss_hdemo_sk = household_demographics.hd_demo_sk
  AND ss_store_sk = s_store_sk
  AND time_dim.t_hour = 20 AND time_dim.t_minute >= 30
  AND household_demographics.hd_dep_count = 7
  AND store.s_store_name = 'ese'
ORDER BY count(*) ASC
LIMIT 100
""",
    # q99: catalog shipping-lag buckets by warehouse/mode/call center
    "q99": """
SELECT substr(w_warehouse_name, 1, 20) wname, sm_type, cc_name,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk <= 30
                THEN 1 ELSE 0 END) days_30,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 30
                 AND cs_ship_date_sk - cs_sold_date_sk <= 60
                THEN 1 ELSE 0 END) days_31_60,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 60
                 AND cs_ship_date_sk - cs_sold_date_sk <= 90
                THEN 1 ELSE 0 END) days_61_90,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 90
                 AND cs_ship_date_sk - cs_sold_date_sk <= 120
                THEN 1 ELSE 0 END) days_91_120,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 120
                THEN 1 ELSE 0 END) days_gt_120
FROM catalog_sales, warehouse, ship_mode, call_center, date_dim
WHERE d_month_seq BETWEEN 1200 AND 1211
  AND cs_ship_date_sk = d_date_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_ship_mode_sk = sm_ship_mode_sk
  AND cs_call_center_sk = cc_call_center_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, cc_name
ORDER BY wname ASC, sm_type ASC, cc_name ASC
LIMIT 100
""",
    # q18: demographic catalog averages, 4-level ROLLUP (GroupIdNode
    # single-pass expansion). Spec CASTs int columns to decimal(12,2)
    # before avg; plain int avg computes the same quotient (comparator
    # tolerance covers rounding).
    "q18": """
SELECT i_item_id, ca_country, ca_state, ca_county,
       avg(cs_quantity) agg1, avg(cs_list_price) agg2,
       avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4,
       avg(cs_net_profit) agg5, avg(c_birth_year) agg6,
       avg(cd1.cd_dep_count) agg7
FROM catalog_sales, customer_demographics cd1,
     customer_demographics cd2, customer, customer_address,
     date_dim, item
WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd1.cd_demo_sk
  AND cs_bill_customer_sk = c_customer_sk
  AND cd1.cd_gender = 'F' AND cd1.cd_education_status = 'Unknown'
  AND c_current_cdemo_sk = cd2.cd_demo_sk
  AND c_current_addr_sk = ca_address_sk
  AND c_birth_month IN (1, 6, 8, 9, 12, 2)
  AND d_year = 1998
  AND ca_state IN ('TX', 'NY', 'OH', 'IL', 'WA', 'GA', 'TN')
GROUP BY ROLLUP(i_item_id, ca_country, ca_state, ca_county)
ORDER BY ca_country ASC, ca_state ASC, ca_county ASC, i_item_id ASC
""",
    # q22: inventory quantity-on-hand, 4-level ROLLUP over item hierarchy
    "q22": """
SELECT i_product_name, i_brand, i_class, i_category,
       avg(inv_quantity_on_hand) qoh
FROM inventory, date_dim, item
WHERE inv_date_sk = d_date_sk AND inv_item_sk = i_item_sk
  AND d_month_seq BETWEEN 1200 AND 1211
GROUP BY ROLLUP(i_product_name, i_brand, i_class, i_category)
ORDER BY qoh ASC, i_product_name ASC, i_brand ASC, i_class ASC,
         i_category ASC
""",
    # q27: store demographics, ROLLUP(i_item_id, s_state) + grouping()
    "q27": """
SELECT i_item_id, s_state, grouping(s_state) g_state,
       avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk AND ss_cdemo_sk = cd_demo_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND d_year = 2002 AND s_state IN ('TN', 'CA')
GROUP BY ROLLUP(i_item_id, s_state)
ORDER BY i_item_id ASC, s_state ASC
""",
    # q97: store/catalog buyer overlap via FULL OUTER JOIN of two
    # grouped CTEs
    "q97": """
WITH ssci AS (
  SELECT ss_customer_sk customer_sk, ss_item_sk item_sk
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk AND d_month_seq BETWEEN 1200 AND 1211
  GROUP BY ss_customer_sk, ss_item_sk
),
csci AS (
  SELECT cs_bill_customer_sk customer_sk, cs_item_sk item_sk
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk AND d_month_seq BETWEEN 1200 AND 1211
  GROUP BY cs_bill_customer_sk, cs_item_sk
)
SELECT sum(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NULL THEN 1 ELSE 0 END) store_only,
       sum(CASE WHEN ssci.customer_sk IS NULL
                 AND csci.customer_sk IS NOT NULL THEN 1 ELSE 0 END)
           catalog_only,
       sum(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NOT NULL THEN 1 ELSE 0 END)
           store_and_catalog
FROM ssci FULL OUTER JOIN csci ON ssci.customer_sk = csci.customer_sk
                              AND ssci.item_sk = csci.item_sk
""",
    # q11: store-vs-web year-over-year growth per customer; the
    # year_total CTE is referenced FOUR times and planned ONCE (plan
    # DAG; LogicalCteOptimizer analog). Alias dyear keeps the reserved
    # word YEAR out of the grammar.
    "q11": """
WITH year_total AS (
  SELECT c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name,
         c_preferred_cust_flag customer_preferred_cust_flag,
         c_birth_country customer_birth_country,
         c_login customer_login,
         c_email_address customer_email_address,
         d_year dyear,
         sum(ss_ext_list_price - ss_ext_discount_amt) year_total,
         's' sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
           c_preferred_cust_flag, c_birth_country, c_login,
           c_email_address, d_year
  UNION ALL
  SELECT c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name,
         c_preferred_cust_flag customer_preferred_cust_flag,
         c_birth_country customer_birth_country,
         c_login customer_login,
         c_email_address customer_email_address,
         d_year dyear,
         sum(ws_ext_list_price - ws_ext_discount_amt) year_total,
         'w' sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk
    AND ws_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
           c_preferred_cust_flag, c_birth_country, c_login,
           c_email_address, d_year
)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name,
       t_s_secyear.customer_preferred_cust_flag,
       t_s_secyear.customer_birth_country, t_s_secyear.customer_login
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2001 AND t_s_secyear.dyear = 2002
  AND t_w_firstyear.dyear = 2001 AND t_w_secyear.dyear = 2002
  AND t_s_firstyear.year_total > 0.000
  AND t_w_firstyear.year_total > 0.000
  AND (CASE WHEN t_w_firstyear.year_total > 0.000
            THEN t_w_secyear.year_total / t_w_firstyear.year_total
            ELSE NULL END)
    > (CASE WHEN t_s_firstyear.year_total > 0.000
            THEN t_s_secyear.year_total / t_s_firstyear.year_total
            ELSE NULL END)
ORDER BY t_s_secyear.customer_id ASC,
         t_s_secyear.customer_first_name ASC,
         t_s_secyear.customer_last_name ASC,
         t_s_secyear.customer_preferred_cust_flag ASC
LIMIT 100
""",
    # q74: like q11 over net_paid with a leaner select list
    "q74": """
WITH year_total AS (
  SELECT c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year dyear,
         sum(ss_net_paid) year_total, 's' sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
    AND d_year IN (2001, 2002)
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year
  UNION ALL
  SELECT c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year dyear,
         sum(ws_net_paid) year_total, 'w' sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk
    AND ws_sold_date_sk = d_date_sk AND d_year IN (2001, 2002)
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year
)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2001 AND t_s_secyear.dyear = 2002
  AND t_w_firstyear.dyear = 2001 AND t_w_secyear.dyear = 2002
  AND t_s_firstyear.year_total > 0.000
  AND t_w_firstyear.year_total > 0.000
  AND (CASE WHEN t_w_firstyear.year_total > 0.000
            THEN t_w_secyear.year_total / t_w_firstyear.year_total
            ELSE NULL END)
    > (CASE WHEN t_s_firstyear.year_total > 0.000
            THEN t_s_secyear.year_total / t_s_firstyear.year_total
            ELSE NULL END)
ORDER BY 1 ASC, 2 ASC, 3 ASC
LIMIT 100
""",
    # q4: q11's shape widened to all three channels (SIX references to
    # one CTE; catalog branch added)
    "q4": """
WITH year_total AS (
  SELECT c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year dyear,
         sum((ss_ext_list_price - ss_ext_wholesale_cost
              - ss_ext_discount_amt + ss_ext_sales_price) / 2)
           year_total,
         's' sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
    AND d_year IN (2001, 2002)
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year
  UNION ALL
  SELECT c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year dyear,
         sum((cs_ext_list_price - cs_ext_wholesale_cost
              - cs_ext_discount_amt + cs_ext_sales_price) / 2)
           year_total,
         'c' sale_type
  FROM customer, catalog_sales, date_dim
  WHERE c_customer_sk = cs_bill_customer_sk
    AND cs_sold_date_sk = d_date_sk AND d_year IN (2001, 2002)
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year
  UNION ALL
  SELECT c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year dyear,
         sum((ws_ext_list_price - ws_ext_wholesale_cost
              - ws_ext_discount_amt + ws_ext_sales_price) / 2)
           year_total,
         'w' sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk
    AND ws_sold_date_sk = d_date_sk AND d_year IN (2001, 2002)
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year
)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_c_firstyear, year_total t_c_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_c_secyear.customer_id
  AND t_s_firstyear.customer_id = t_c_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_c_firstyear.sale_type = 'c'
  AND t_w_firstyear.sale_type = 'w' AND t_s_secyear.sale_type = 's'
  AND t_c_secyear.sale_type = 'c' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2001 AND t_s_secyear.dyear = 2002
  AND t_c_firstyear.dyear = 2001 AND t_c_secyear.dyear = 2002
  AND t_w_firstyear.dyear = 2001 AND t_w_secyear.dyear = 2002
  AND t_s_firstyear.year_total > 0.000
  AND t_c_firstyear.year_total > 0.000
  AND t_w_firstyear.year_total > 0.000
  AND (CASE WHEN t_c_firstyear.year_total > 0.000
            THEN t_c_secyear.year_total / t_c_firstyear.year_total
            ELSE NULL END)
    > (CASE WHEN t_s_firstyear.year_total > 0.000
            THEN t_s_secyear.year_total / t_s_firstyear.year_total
            ELSE NULL END)
  AND (CASE WHEN t_c_firstyear.year_total > 0.000
            THEN t_c_secyear.year_total / t_c_firstyear.year_total
            ELSE NULL END)
    > (CASE WHEN t_w_firstyear.year_total > 0.000
            THEN t_w_secyear.year_total / t_w_firstyear.year_total
            ELSE NULL END)
ORDER BY 1 ASC, 2 ASC, 3 ASC
LIMIT 100
""",
    # q12/q20/q98: per-item revenue share of its class (windowed sum
    # over the aggregation output). Date window folded into literals.
    "q12": """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) itemrevenue,
       sum(ws_ext_sales_price) * 100 / sum(sum(ws_ext_sales_price))
         OVER (PARTITION BY i_class) revenueratio
FROM web_sales, item, date_dim
WHERE ws_item_sk = i_item_sk
  AND i_category IN ('Sports', 'Books', 'Home')
  AND ws_sold_date_sk = d_date_sk
  AND d_date BETWEEN date '1999-02-22' AND date '1999-03-24'
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category ASC, i_class ASC, i_item_id ASC, i_item_desc ASC,
         revenueratio ASC
""",
    "q20": """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) itemrevenue,
       sum(cs_ext_sales_price) * 100 / sum(sum(cs_ext_sales_price))
         OVER (PARTITION BY i_class) revenueratio
FROM catalog_sales, item, date_dim
WHERE cs_item_sk = i_item_sk
  AND i_category IN ('Sports', 'Books', 'Home')
  AND cs_sold_date_sk = d_date_sk
  AND d_date BETWEEN date '1999-02-22' AND date '1999-03-24'
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category ASC, i_class ASC, i_item_id ASC, i_item_desc ASC,
         revenueratio ASC
""",
    "q98": """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) itemrevenue,
       sum(ss_ext_sales_price) * 100 / sum(sum(ss_ext_sales_price))
         OVER (PARTITION BY i_class) revenueratio
FROM store_sales, item, date_dim
WHERE ss_item_sk = i_item_sk
  AND i_category IN ('Sports', 'Books', 'Home')
  AND ss_sold_date_sk = d_date_sk
  AND d_date BETWEEN date '1999-02-22' AND date '1999-03-24'
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category ASC, i_class ASC, i_item_id ASC, i_item_desc ASC,
         revenueratio ASC
""",
    # q53: manufacturer quarterly sales vs their average (window over
    # aggregation + outer deviation filter). Spec's class/brand filter
    # values adapted to the generator's domains; OR structure preserved.
    "q53": """
SELECT * FROM (
  SELECT i_manufact_id, sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) OVER (PARTITION BY i_manufact_id)
           avg_quarterly_sales
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND d_month_seq BETWEEN 1200 AND 1211
    AND ((i_category IN ('Books', 'Children', 'Electronics')
          AND i_class IN ('accent', 'bathroom', 'bedding', 'blinds'))
      OR (i_category IN ('Women', 'Music', 'Men')
          AND i_class IN ('curtains', 'decor', 'flatware', 'kids')))
  GROUP BY i_manufact_id, d_qoy
) tmp1
WHERE CASE WHEN avg_quarterly_sales > 0.000
           THEN abs(sum_sales - avg_quarterly_sales)
                / avg_quarterly_sales
           ELSE NULL END > 0.100
ORDER BY avg_quarterly_sales ASC, sum_sales ASC, i_manufact_id ASC
""",
    # q63: like q53 keyed by manager/month
    "q63": """
SELECT * FROM (
  SELECT i_manager_id, sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) OVER (PARTITION BY i_manager_id)
           avg_monthly_sales
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND d_month_seq BETWEEN 1200 AND 1211
    AND ((i_category IN ('Books', 'Children', 'Electronics')
          AND i_class IN ('accent', 'bathroom', 'bedding', 'blinds'))
      OR (i_category IN ('Women', 'Music', 'Men')
          AND i_class IN ('curtains', 'decor', 'flatware', 'kids')))
  GROUP BY i_manager_id, d_moy
) tmp1
WHERE CASE WHEN avg_monthly_sales > 0.000
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE NULL END > 0.100
ORDER BY i_manager_id ASC, avg_monthly_sales ASC, sum_sales ASC
""",
    # q89: store/brand monthly sales vs category average
    "q89": """
SELECT * FROM (
  SELECT i_category, i_class, i_brand, s_store_name, s_company_name,
         d_moy, sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) OVER (PARTITION BY i_category,
           i_brand, s_store_name, s_company_name) avg_monthly_sales
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk AND d_year = 1999
    AND ((i_category IN ('Books', 'Electronics', 'Sports')
          AND i_class IN ('accent', 'bathroom', 'bedding'))
      OR (i_category IN ('Men', 'Jewelry', 'Women')
          AND i_class IN ('blinds', 'curtains', 'decor')))
  GROUP BY i_category, i_class, i_brand, s_store_name, s_company_name,
           d_moy
) tmp1
WHERE CASE WHEN avg_monthly_sales <> 0.000
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE NULL END > 0.100
ORDER BY sum_sales - avg_monthly_sales ASC, s_store_name ASC,
         sum_sales ASC, i_category ASC, i_class ASC, i_brand ASC
""",
    # q32: excess catalog discounts (correlated scalar subquery per item)
    "q32": """
SELECT sum(cs_ext_discount_amt) excess_discount_amount
FROM catalog_sales, item, date_dim
WHERE i_manufact_id = 977 AND i_item_sk = cs_item_sk
  AND d_date BETWEEN date '2000-01-27' AND date '2000-04-26'
  AND d_date_sk = cs_sold_date_sk
  AND cs_ext_discount_amt > (
    SELECT 1.3 * avg(cs_ext_discount_amt)
    FROM catalog_sales, date_dim
    WHERE cs_item_sk = i_item_sk
      AND d_date BETWEEN date '2000-01-27' AND date '2000-04-26'
      AND d_date_sk = cs_sold_date_sk)
""",
    # q38: customers active in ALL three channels (INTERSECT of
    # distinct name/date sets)
    "q38": """
SELECT count(*) FROM (
  SELECT DISTINCT c_last_name, c_first_name, d_date
  FROM store_sales, date_dim, customer
  WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
    AND store_sales.ss_customer_sk = customer.c_customer_sk
    AND d_month_seq BETWEEN 1200 AND 1211
  INTERSECT
  SELECT DISTINCT c_last_name, c_first_name, d_date
  FROM catalog_sales, date_dim, customer
  WHERE catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
    AND catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
    AND d_month_seq BETWEEN 1200 AND 1211
  INTERSECT
  SELECT DISTINCT c_last_name, c_first_name, d_date
  FROM web_sales, date_dim, customer
  WHERE web_sales.ws_sold_date_sk = date_dim.d_date_sk
    AND web_sales.ws_bill_customer_sk = customer.c_customer_sk
    AND d_month_seq BETWEEN 1200 AND 1211
) hot_cust
""",
    # q87: store-only customers (EXCEPT chain over the same three sets)
    "q87": """
SELECT count(*) FROM (
  SELECT DISTINCT c_last_name, c_first_name, d_date
  FROM store_sales, date_dim, customer
  WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
    AND store_sales.ss_customer_sk = customer.c_customer_sk
    AND d_month_seq BETWEEN 1200 AND 1211
  EXCEPT
  SELECT DISTINCT c_last_name, c_first_name, d_date
  FROM catalog_sales, date_dim, customer
  WHERE catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
    AND catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
    AND d_month_seq BETWEEN 1200 AND 1211
  EXCEPT
  SELECT DISTINCT c_last_name, c_first_name, d_date
  FROM web_sales, date_dim, customer
  WHERE web_sales.ws_sold_date_sk = date_dim.d_date_sk
    AND web_sales.ws_bill_customer_sk = customer.c_customer_sk
    AND d_month_seq BETWEEN 1200 AND 1211
) cool_cust
""",
    # q6: states whose buyers favor items priced 20% above their
    # category average (correlated avg subquery + scalar month lookup)
    "q6": """
SELECT a.ca_state state_, count(*) cnt
FROM customer_address a, customer c, store_sales s, date_dim d, item i
WHERE a.ca_address_sk = c.c_current_addr_sk
  AND c.c_customer_sk = s.ss_customer_sk
  AND s.ss_sold_date_sk = d.d_date_sk
  AND s.ss_item_sk = i.i_item_sk
  AND d.d_month_seq = (SELECT DISTINCT d_month_seq FROM date_dim
                       WHERE d_year = 2001 AND d_moy = 1)
  AND i.i_current_price > (SELECT 1.2 * avg(j.i_current_price)
                           FROM item j
                           WHERE j.i_category = i.i_category)
GROUP BY a.ca_state
HAVING count(*) >= 10
ORDER BY cnt ASC, state_ ASC
""",
    # q33: Electronics manufacturer sales across all three channels
    # (three CTEs with IN-subquery item filters, UNION ALL, re-agg)
    "q33": """
WITH ss AS (
  SELECT i_manufact_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category IN ('Electronics'))
    AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5 AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5.00
  GROUP BY i_manufact_id
),
cs AS (
  SELECT i_manufact_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category IN ('Electronics'))
    AND cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5 AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5.00
  GROUP BY i_manufact_id
),
ws AS (
  SELECT i_manufact_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category IN ('Electronics'))
    AND ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5 AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5.00
  GROUP BY i_manufact_id
)
SELECT i_manufact_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_manufact_id
ORDER BY total_sales ASC, i_manufact_id ASC
""",
    # q34: frequent-ticket buyers. Spec's dep/vehicle CASE ratio is
    # rewritten as the equivalent integer-side multiplication, and the
    # cnt band starts at 1 (this generator's ticket lines are
    # independent rows, so per-(ticket, customer) counts stay small).
    "q34": """
SELECT c_last_name, c_first_name, c_salutation,
       c_preferred_cust_flag, ss_ticket_number, cnt
FROM (
  SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
  FROM store_sales, date_dim, store, household_demographics
  WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
    AND store_sales.ss_store_sk = store.s_store_sk
    AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
    AND (date_dim.d_dom BETWEEN 1 AND 3
         OR date_dim.d_dom BETWEEN 25 AND 28)
    AND (household_demographics.hd_buy_potential = '>10000'
         OR household_demographics.hd_buy_potential = 'Unknown')
    AND household_demographics.hd_vehicle_count > 0
    AND 10 * household_demographics.hd_dep_count
        > 12 * household_demographics.hd_vehicle_count
    AND date_dim.d_year IN (1999, 2000, 2001)
    AND store.s_county IN ('Williamson County', 'Walker County')
  GROUP BY ss_ticket_number, ss_customer_sk
) dn, customer
WHERE ss_customer_sk = c_customer_sk AND cnt BETWEEN 1 AND 20
ORDER BY c_last_name ASC, c_first_name ASC, c_salutation ASC,
         c_preferred_cust_flag DESC, ss_ticket_number ASC
""",
    # q56/q60: three-channel item-id sales unions (color / category
    # item filters; colors drawn from the generator's domain)
    "q56": """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('slate', 'salmon', 'sienna'))
    AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2 AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5.00
  GROUP BY i_item_id
),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('slate', 'salmon', 'sienna'))
    AND cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2 AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5.00
  GROUP BY i_item_id
),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('slate', 'salmon', 'sienna'))
    AND ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2 AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5.00
  GROUP BY i_item_id
)
SELECT i_item_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY total_sales ASC, i_item_id ASC
""",
    "q60": """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category IN ('Music'))
    AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 9 AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5.00
  GROUP BY i_item_id
),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category IN ('Music'))
    AND cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 9 AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5.00
  GROUP BY i_item_id
),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category IN ('Music'))
    AND ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 9 AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5.00
  GROUP BY i_item_id
)
SELECT i_item_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY total_sales ASC, i_item_id ASC
""",
    # q61: promotional share of Jewelry revenue (two single-row scalar
    # reports cross-joined by the const-key broadcast path)
    "q61": """
SELECT promotions, total,
       promotions / cast(total AS double) * 100
FROM (
  SELECT sum(ss_ext_sales_price) promotions
  FROM store_sales, store, promotion, date_dim, customer,
       customer_address, item
  WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
    AND ss_promo_sk = p_promo_sk AND ss_customer_sk = c_customer_sk
    AND ca_address_sk = c_current_addr_sk AND ss_item_sk = i_item_sk
    AND ca_gmt_offset = -5.00 AND i_category = 'Jewelry'
    AND (p_channel_dmail = 'Y' OR p_channel_email = 'Y'
         OR p_channel_tv = 'Y')
    AND s_gmt_offset = -5.00 AND d_year = 1998 AND d_moy = 11
) promotional_sales, (
  SELECT sum(ss_ext_sales_price) total
  FROM store_sales, store, date_dim, customer, customer_address, item
  WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
    AND ss_customer_sk = c_customer_sk
    AND ca_address_sk = c_current_addr_sk AND ss_item_sk = i_item_sk
    AND ca_gmt_offset = -5.00 AND i_category = 'Jewelry'
    AND s_gmt_offset = -5.00 AND d_year = 1998 AND d_moy = 11
) all_sales
ORDER BY promotions ASC, total ASC
""",
    # q88: store activity in eight half-hour bands (eight single-row
    # counts cross-joined)
    "q88": """
SELECT * FROM
 (SELECT count(*) h8_30_to_9 FROM store_sales, household_demographics,
         time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 8 AND time_dim.t_minute >= 30
    AND ((household_demographics.hd_dep_count = 4
          AND household_demographics.hd_vehicle_count <= 6)
      OR (household_demographics.hd_dep_count = 2
          AND household_demographics.hd_vehicle_count <= 4)
      OR (household_demographics.hd_dep_count = 0
          AND household_demographics.hd_vehicle_count <= 2))
    AND store.s_store_name = 'ese') s1,
 (SELECT count(*) h9_to_9_30 FROM store_sales, household_demographics,
         time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 9 AND time_dim.t_minute < 30
    AND ((household_demographics.hd_dep_count = 4
          AND household_demographics.hd_vehicle_count <= 6)
      OR (household_demographics.hd_dep_count = 2
          AND household_demographics.hd_vehicle_count <= 4)
      OR (household_demographics.hd_dep_count = 0
          AND household_demographics.hd_vehicle_count <= 2))
    AND store.s_store_name = 'ese') s2,
 (SELECT count(*) h9_30_to_10 FROM store_sales, household_demographics,
         time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 9 AND time_dim.t_minute >= 30
    AND ((household_demographics.hd_dep_count = 4
          AND household_demographics.hd_vehicle_count <= 6)
      OR (household_demographics.hd_dep_count = 2
          AND household_demographics.hd_vehicle_count <= 4)
      OR (household_demographics.hd_dep_count = 0
          AND household_demographics.hd_vehicle_count <= 2))
    AND store.s_store_name = 'ese') s3,
 (SELECT count(*) h10_to_10_30 FROM store_sales, household_demographics,
         time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 10 AND time_dim.t_minute < 30
    AND ((household_demographics.hd_dep_count = 4
          AND household_demographics.hd_vehicle_count <= 6)
      OR (household_demographics.hd_dep_count = 2
          AND household_demographics.hd_vehicle_count <= 4)
      OR (household_demographics.hd_dep_count = 0
          AND household_demographics.hd_vehicle_count <= 2))
    AND store.s_store_name = 'ese') s4
""",
    # q90: web am/pm activity ratio (two single-row counts)
    "q90": """
SELECT amc / cast(pmc AS double) am_pm_ratio
FROM (
  SELECT count(*) amc FROM web_sales, household_demographics,
         time_dim, web_page
  WHERE ws_sold_time_sk = time_dim.t_time_sk
    AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
    AND ws_web_page_sk = web_page.wp_web_page_sk
    AND time_dim.t_hour BETWEEN 8 AND 9
    AND household_demographics.hd_dep_count = 6
    AND web_page.wp_char_count BETWEEN 2000 AND 5200
) at_, (
  SELECT count(*) pmc FROM web_sales, household_demographics,
         time_dim, web_page
  WHERE ws_sold_time_sk = time_dim.t_time_sk
    AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
    AND ws_web_page_sk = web_page.wp_web_page_sk
    AND time_dim.t_hour BETWEEN 19 AND 20
    AND household_demographics.hd_dep_count = 6
    AND web_page.wp_char_count BETWEEN 2000 AND 5200
) pt
ORDER BY am_pm_ratio ASC
""",
    # q92: excess web discounts (q32's web twin)
    "q92": """
SELECT sum(ws_ext_discount_amt) excess_discount_amount
FROM web_sales, item, date_dim
WHERE i_manufact_id = 350 AND i_item_sk = ws_item_sk
  AND d_date BETWEEN date '2000-01-27' AND date '2000-04-26'
  AND d_date_sk = ws_sold_date_sk
  AND ws_ext_discount_amt > (
    SELECT 1.3 * avg(ws_ext_discount_amt)
    FROM web_sales, date_dim
    WHERE ws_item_sk = i_item_sk
      AND d_date BETWEEN date '2000-01-27' AND date '2000-04-26'
      AND d_date_sk = ws_sold_date_sk)
""",
    # q69: store-only shoppers' demographics (EXISTS + two NOT EXISTS;
    # states drawn from the generator's domain)
    "q69": """
SELECT cd_gender, cd_marital_status, cd_education_status, count(*) cnt1,
       cd_purchase_estimate, count(*) cnt2, cd_credit_rating, count(*) cnt3
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_state IN ('GA', 'TX', 'NY')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT ss_customer_sk FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk AND d_year = 2001
                AND d_moy BETWEEN 4 AND 6)
  AND NOT EXISTS (SELECT ws_bill_customer_sk FROM web_sales, date_dim
                  WHERE c.c_customer_sk = ws_bill_customer_sk
                    AND ws_sold_date_sk = d_date_sk AND d_year = 2001
                    AND d_moy BETWEEN 4 AND 6)
  AND NOT EXISTS (SELECT cs_ship_customer_sk FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk AND d_year = 2001
                    AND d_moy BETWEEN 4 AND 6)
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
ORDER BY cd_gender ASC, cd_marital_status ASC, cd_education_status ASC,
         cd_purchase_estimate ASC, cd_credit_rating ASC
""",
    # q76: channel totals with constant-string group keys over a 3-way
    # UNION ALL. Spec filters on NULL link keys; this generator emits
    # none, so the test inverts to IS NOT NULL to stay non-vacuous.
    "q76": """
SELECT channel, col_name, d_year, d_qoy, i_category,
       count(*) sales_cnt, sum(ext_sales_price) sales_amt
FROM (
  SELECT 'store' channel, 'ss_store_sk' col_name, d_year, d_qoy,
         i_category, ss_ext_sales_price ext_sales_price
  FROM store_sales, item, date_dim
  WHERE ss_store_sk IS NOT NULL AND ss_sold_date_sk = d_date_sk
    AND ss_item_sk = i_item_sk
  UNION ALL
  SELECT 'web' channel, 'ws_ship_customer_sk' col_name, d_year, d_qoy,
         i_category, ws_ext_sales_price ext_sales_price
  FROM web_sales, item, date_dim
  WHERE ws_ship_customer_sk IS NOT NULL AND ws_sold_date_sk = d_date_sk
    AND ws_item_sk = i_item_sk
  UNION ALL
  SELECT 'catalog' channel, 'cs_ship_addr_sk' col_name, d_year, d_qoy,
         i_category, cs_ext_sales_price ext_sales_price
  FROM catalog_sales, item, date_dim
  WHERE cs_ship_addr_sk IS NOT NULL AND cs_sold_date_sk = d_date_sk
    AND cs_item_sk = i_item_sk
) foo
GROUP BY channel, col_name, d_year, d_qoy, i_category
ORDER BY channel ASC, col_name ASC, d_year ASC, d_qoy ASC,
         i_category ASC
""",
    # q83: returned quantities across channels in three chosen weeks
    # (nested IN subqueries + 3-way CTE join)
    "q83": """
WITH sr_items AS (
  SELECT i_item_id item_id, sum(sr_return_quantity) sr_item_qty
  FROM store_returns, item, date_dim
  WHERE sr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq IN (SELECT d_week_seq FROM date_dim
                                        WHERE d_date IN (date '2000-06-30',
                                                         date '2000-09-27',
                                                         date '2000-11-17')))
    AND sr_returned_date_sk = d_date_sk
  GROUP BY i_item_id
),
cr_items AS (
  SELECT i_item_id item_id, sum(cr_return_quantity) cr_item_qty
  FROM catalog_returns, item, date_dim
  WHERE cr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq IN (SELECT d_week_seq FROM date_dim
                                        WHERE d_date IN (date '2000-06-30',
                                                         date '2000-09-27',
                                                         date '2000-11-17')))
    AND cr_returned_date_sk = d_date_sk
  GROUP BY i_item_id
),
wr_items AS (
  SELECT i_item_id item_id, sum(wr_return_quantity) wr_item_qty
  FROM web_returns, item, date_dim
  WHERE wr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq IN (SELECT d_week_seq FROM date_dim
                                        WHERE d_date IN (date '2000-06-30',
                                                         date '2000-09-27',
                                                         date '2000-11-17')))
    AND wr_returned_date_sk = d_date_sk
  GROUP BY i_item_id
)
SELECT sr_items.item_id, sr_item_qty,
       cast(sr_item_qty AS double)
         / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100 sr_dev,
       cr_item_qty,
       cast(cr_item_qty AS double)
         / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100 cr_dev,
       wr_item_qty,
       cast(wr_item_qty AS double)
         / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100 wr_dev,
       (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 average
FROM sr_items, cr_items, wr_items
WHERE sr_items.item_id = cr_items.item_id
  AND sr_items.item_id = wr_items.item_id
ORDER BY sr_items.item_id ASC, sr_item_qty ASC
""",
    # q28: six quantity-band price profiles (single-row cross joins;
    # exact global count(DISTINCT))
    "q28": """
SELECT * FROM
 (SELECT avg(ss_list_price) b1_lp, count(ss_list_price) b1_cnt,
         count(DISTINCT ss_list_price) b1_cntd
  FROM store_sales WHERE ss_quantity BETWEEN 0 AND 5
    AND (ss_list_price BETWEEN 8.00 AND 18.00
         OR ss_coupon_amt BETWEEN 459.00 AND 1459.00
         OR ss_wholesale_cost BETWEEN 57.00 AND 77.00)) b1,
 (SELECT avg(ss_list_price) b2_lp, count(ss_list_price) b2_cnt,
         count(DISTINCT ss_list_price) b2_cntd
  FROM store_sales WHERE ss_quantity BETWEEN 6 AND 10
    AND (ss_list_price BETWEEN 90.00 AND 100.00
         OR ss_coupon_amt BETWEEN 2323.00 AND 3323.00
         OR ss_wholesale_cost BETWEEN 31.00 AND 51.00)) b2,
 (SELECT avg(ss_list_price) b3_lp, count(ss_list_price) b3_cnt,
         count(DISTINCT ss_list_price) b3_cntd
  FROM store_sales WHERE ss_quantity BETWEEN 11 AND 15
    AND (ss_list_price BETWEEN 142.00 AND 152.00
         OR ss_coupon_amt BETWEEN 12214.00 AND 13214.00
         OR ss_wholesale_cost BETWEEN 79.00 AND 99.00)) b3,
 (SELECT avg(ss_list_price) b4_lp, count(ss_list_price) b4_cnt,
         count(DISTINCT ss_list_price) b4_cntd
  FROM store_sales WHERE ss_quantity BETWEEN 16 AND 20
    AND (ss_list_price BETWEEN 135.00 AND 145.00
         OR ss_coupon_amt BETWEEN 6071.00 AND 7071.00
         OR ss_wholesale_cost BETWEEN 38.00 AND 58.00)) b4,
 (SELECT avg(ss_list_price) b5_lp, count(ss_list_price) b5_cnt,
         count(DISTINCT ss_list_price) b5_cntd
  FROM store_sales WHERE ss_quantity BETWEEN 21 AND 25
    AND (ss_list_price BETWEEN 122.00 AND 132.00
         OR ss_coupon_amt BETWEEN 836.00 AND 1836.00
         OR ss_wholesale_cost BETWEEN 17.00 AND 37.00)) b5,
 (SELECT avg(ss_list_price) b6_lp, count(ss_list_price) b6_cnt,
         count(DISTINCT ss_list_price) b6_cntd
  FROM store_sales WHERE ss_quantity BETWEEN 26 AND 30
    AND (ss_list_price BETWEEN 154.00 AND 164.00
         OR ss_coupon_amt BETWEEN 7326.00 AND 8326.00
         OR ss_wholesale_cost BETWEEN 7.00 AND 27.00)) b6
""",
    # q71: brand revenue at breakfast/dinner times across all channels
    "q71": """
SELECT i_brand_id brand_id, i_brand brand, t_hour, t_minute,
       sum(ext_price) ext_price
FROM item, (
  SELECT ws_ext_sales_price ext_price, ws_sold_date_sk sold_date_sk,
         ws_item_sk sold_item_sk, ws_sold_time_sk time_sk
  FROM web_sales, date_dim
  WHERE d_date_sk = ws_sold_date_sk AND d_moy = 11 AND d_year = 1999
  UNION ALL
  SELECT cs_ext_sales_price ext_price, cs_sold_date_sk sold_date_sk,
         cs_item_sk sold_item_sk, cs_sold_time_sk time_sk
  FROM catalog_sales, date_dim
  WHERE d_date_sk = cs_sold_date_sk AND d_moy = 11 AND d_year = 1999
  UNION ALL
  SELECT ss_ext_sales_price ext_price, ss_sold_date_sk sold_date_sk,
         ss_item_sk sold_item_sk, ss_sold_time_sk time_sk
  FROM store_sales, date_dim
  WHERE d_date_sk = ss_sold_date_sk AND d_moy = 11 AND d_year = 1999
) tmp, time_dim
WHERE sold_item_sk = i_item_sk AND i_manager_id = 1
  AND time_sk = t_time_sk
  AND (t_meal_time = 'breakfast' OR t_meal_time = 'dinner')
GROUP BY i_brand, i_brand_id, t_hour, t_minute
ORDER BY ext_price DESC, brand_id ASC, t_hour ASC, t_minute ASC
""",
    # q86: web revenue hierarchy (ROLLUP + grouping() + rank() window
    # over the grouping-set aggregates). Spec's CASE order key is
    # replaced with plain alias keys (deterministic; compared sorted).
    "q86": """
SELECT sum(ws_net_paid) total_sum, i_category, i_class,
       grouping(i_category) + grouping(i_class) lochierarchy,
       rank() OVER (PARTITION BY grouping(i_category)
                                 + grouping(i_class),
                                 CASE WHEN grouping(i_class) = 0
                                      THEN i_category END
                    ORDER BY sum(ws_net_paid) DESC) rank_within_parent
FROM web_sales, date_dim d1, item
WHERE d1.d_month_seq BETWEEN 1200 AND 1211
  AND d1.d_date_sk = ws_sold_date_sk AND i_item_sk = ws_item_sk
GROUP BY ROLLUP(i_category, i_class)
ORDER BY lochierarchy DESC, rank_within_parent ASC, i_category ASC,
         i_class ASC
""",
    # q36: store gross-margin hierarchy (ROLLUP + grouping() + ranked
    # margin ratio; states from the generator domain)
    "q36": """
SELECT sum(ss_net_profit) / sum(ss_ext_sales_price) gross_margin,
       i_category, i_class,
       grouping(i_category) + grouping(i_class) lochierarchy,
       rank() OVER (PARTITION BY grouping(i_category)
                                 + grouping(i_class),
                                 CASE WHEN grouping(i_class) = 0
                                      THEN i_category END
                    ORDER BY sum(ss_net_profit)
                             / sum(ss_ext_sales_price) ASC)
         rank_within_parent
FROM store_sales, date_dim d1, item, store
WHERE d1.d_year = 2001 AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND s_state IN ('TN', 'CA')
GROUP BY ROLLUP(i_category, i_class)
ORDER BY lochierarchy DESC, rank_within_parent ASC, i_category ASC,
         i_class ASC
""",
    # q47/q57: month-over-month deviation around a yearly average (TWO
    # distinct OVER clauses in one CTE, referenced three times and
    # planned once; lag/lead realized as rn-arithmetic self-joins)
    "q47": """
WITH v1 AS (
  SELECT i_category, i_brand, s_store_name, s_company_name, d_year,
         d_moy, sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) OVER (PARTITION BY i_category,
           i_brand, s_store_name, s_company_name, d_year)
           avg_monthly_sales,
         rank() OVER (PARTITION BY i_category, i_brand, s_store_name,
           s_company_name ORDER BY d_year ASC, d_moy ASC) rn
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND (d_year = 1999 OR (d_year = 1998 AND d_moy = 12)
         OR (d_year = 2000 AND d_moy = 1))
  GROUP BY i_category, i_brand, s_store_name, s_company_name,
           d_year, d_moy
),
v2 AS (
  SELECT v1.i_category, v1.i_brand, v1.s_store_name,
         v1.s_company_name, v1.d_year, v1.d_moy, v1.avg_monthly_sales,
         v1.sum_sales, v1_lag.sum_sales psum, v1_lead.sum_sales nsum
  FROM v1, v1 v1_lag, v1 v1_lead
  WHERE v1.i_category = v1_lag.i_category
    AND v1.i_category = v1_lead.i_category
    AND v1.i_brand = v1_lag.i_brand
    AND v1.i_brand = v1_lead.i_brand
    AND v1.s_store_name = v1_lag.s_store_name
    AND v1.s_store_name = v1_lead.s_store_name
    AND v1.s_company_name = v1_lag.s_company_name
    AND v1.s_company_name = v1_lead.s_company_name
    AND v1.rn = v1_lag.rn + 1 AND v1.rn = v1_lead.rn - 1
)
SELECT * FROM v2
WHERE d_year = 1999 AND avg_monthly_sales > 0.000
  AND CASE WHEN avg_monthly_sales > 0.000
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE NULL END > 0.100
ORDER BY sum_sales - avg_monthly_sales ASC, 3 ASC, 1 ASC, 2 ASC,
         4 ASC, 5 ASC, 6 ASC
""",
    "q57": """
WITH v1 AS (
  SELECT i_category, i_brand, cc_name, d_year, d_moy,
         sum(cs_sales_price) sum_sales,
         avg(sum(cs_sales_price)) OVER (PARTITION BY i_category,
           i_brand, cc_name, d_year) avg_monthly_sales,
         rank() OVER (PARTITION BY i_category, i_brand, cc_name
           ORDER BY d_year ASC, d_moy ASC) rn
  FROM item, catalog_sales, date_dim, call_center
  WHERE cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
    AND cc_call_center_sk = cs_call_center_sk
    AND (d_year = 1999 OR (d_year = 1998 AND d_moy = 12)
         OR (d_year = 2000 AND d_moy = 1))
  GROUP BY i_category, i_brand, cc_name, d_year, d_moy
),
v2 AS (
  SELECT v1.i_category, v1.i_brand, v1.cc_name, v1.d_year, v1.d_moy,
         v1.avg_monthly_sales, v1.sum_sales, v1_lag.sum_sales psum,
         v1_lead.sum_sales nsum
  FROM v1, v1 v1_lag, v1 v1_lead
  WHERE v1.i_category = v1_lag.i_category
    AND v1.i_category = v1_lead.i_category
    AND v1.i_brand = v1_lag.i_brand
    AND v1.i_brand = v1_lead.i_brand
    AND v1.cc_name = v1_lag.cc_name AND v1.cc_name = v1_lead.cc_name
    AND v1.rn = v1_lag.rn + 1 AND v1.rn = v1_lead.rn - 1
)
SELECT * FROM v2
WHERE d_year = 1999 AND avg_monthly_sales > 0.000
  AND CASE WHEN avg_monthly_sales > 0.000
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE NULL END > 0.100
ORDER BY sum_sales - avg_monthly_sales ASC, 3 ASC, 1 ASC, 2 ASC,
         4 ASC, 5 ASC
""",
    # q1: customers returning above 1.2x their store's average (CTE
    # referenced twice; correlated scalar subquery over the CTE). LIMIT
    # dropped: full-set oracle comparison (ties under LIMIT ambiguous).
    "q1": """
WITH customer_total_return AS (
  SELECT sr_customer_sk ctr_customer_sk, sr_store_sk ctr_store_sk,
         sum(sr_return_amt) ctr_total_return
  FROM store_returns, date_dim
  WHERE sr_returned_date_sk = d_date_sk AND d_year = 2000
  GROUP BY sr_customer_sk, sr_store_sk)
SELECT c_customer_id
FROM customer_total_return ctr1, store, customer
WHERE ctr1.ctr_total_return > (SELECT avg(ctr_total_return) * 1.2
                               FROM customer_total_return ctr2
                               WHERE ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  AND s_store_sk = ctr1.ctr_store_sk AND s_state = 'TN'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id
""",
    # q30: q1's shape over web returns grouped by customer state
    "q30": """
WITH customer_total_return AS (
  SELECT wr_returning_customer_sk ctr_customer_sk, ca_state ctr_state,
         sum(wr_return_amt) ctr_total_return
  FROM web_returns, date_dim, customer_address
  WHERE wr_returned_date_sk = d_date_sk AND d_year = 2002
    AND wr_returning_addr_sk = ca_address_sk
  GROUP BY wr_returning_customer_sk, ca_state)
SELECT c_customer_id, c_salutation, c_first_name, c_last_name,
       c_preferred_cust_flag, c_birth_day, c_birth_month, c_birth_year,
       c_birth_country, c_login, c_email_address, c_last_review_date_sk,
       ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return > (SELECT avg(ctr_total_return) * 1.2
                               FROM customer_total_return ctr2
                               WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk AND ca_state = 'GA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, ctr_total_return
""",
    # q81: q30's shape over catalog returns (return amount incl. tax)
    "q81": """
WITH customer_total_return AS (
  SELECT cr_returning_customer_sk ctr_customer_sk, ca_state ctr_state,
         sum(cr_return_amt_inc_tax) ctr_total_return
  FROM catalog_returns, date_dim, customer_address
  WHERE cr_returned_date_sk = d_date_sk AND d_year = 2000
    AND cr_returning_addr_sk = ca_address_sk
  GROUP BY cr_returning_customer_sk, ca_state)
SELECT c_customer_id, c_salutation, c_first_name, c_last_name,
       ca_street_number, ca_street_name, ca_street_type, ca_suite_number,
       ca_city, ca_county, ca_state, ca_zip, ca_country, ca_gmt_offset,
       ca_location_type, ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return > (SELECT avg(ctr_total_return) * 1.2
                               FROM customer_total_return ctr2
                               WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk AND ca_state = 'GA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, ctr_total_return
""",
    # q59: year-over-year weekly sales ratios per store (CTE referenced
    # twice; day-of-week pivot sums; ratios via CAST AS double so the
    # oracle computes the identical float). Full-set comparison (the
    # spec ORDER BY is not unique: s_store_id is an SCD business key).
    "q59": """
WITH wss AS (
  SELECT d_week_seq, ss_store_sk,
         sum(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price ELSE NULL END) sun_sales,
         sum(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price ELSE NULL END) mon_sales,
         sum(CASE WHEN d_day_name = 'Tuesday' THEN ss_sales_price ELSE NULL END) tue_sales,
         sum(CASE WHEN d_day_name = 'Wednesday' THEN ss_sales_price ELSE NULL END) wed_sales,
         sum(CASE WHEN d_day_name = 'Thursday' THEN ss_sales_price ELSE NULL END) thu_sales,
         sum(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price ELSE NULL END) fri_sales,
         sum(CASE WHEN d_day_name = 'Saturday' THEN ss_sales_price ELSE NULL END) sat_sales
  FROM store_sales, date_dim
  WHERE d_date_sk = ss_sold_date_sk
  GROUP BY d_week_seq, ss_store_sk)
SELECT s_store_name1, s_store_id1, d_week_seq1,
       CAST(sun_sales1 AS double) / sun_sales2,
       CAST(mon_sales1 AS double) / mon_sales2,
       CAST(tue_sales1 AS double) / tue_sales2,
       CAST(wed_sales1 AS double) / wed_sales2,
       CAST(thu_sales1 AS double) / thu_sales2,
       CAST(fri_sales1 AS double) / fri_sales2,
       CAST(sat_sales1 AS double) / sat_sales2
FROM (SELECT s_store_name s_store_name1, wss.d_week_seq d_week_seq1,
             s_store_id s_store_id1, sun_sales sun_sales1,
             mon_sales mon_sales1, tue_sales tue_sales1,
             wed_sales wed_sales1, thu_sales thu_sales1,
             fri_sales fri_sales1, sat_sales sat_sales1
      FROM wss, store, date_dim d
      WHERE d.d_week_seq = wss.d_week_seq AND ss_store_sk = s_store_sk
        AND d_month_seq BETWEEN 1212 AND 1223) y,
     (SELECT s_store_name s_store_name2, wss.d_week_seq d_week_seq2,
             s_store_id s_store_id2, sun_sales sun_sales2,
             mon_sales mon_sales2, tue_sales tue_sales2,
             wed_sales wed_sales2, thu_sales thu_sales2,
             fri_sales fri_sales2, sat_sales sat_sales2
      FROM wss, store, date_dim d
      WHERE d.d_week_seq = wss.d_week_seq AND ss_store_sk = s_store_sk
        AND d_month_seq BETWEEN 1224 AND 1235) x
WHERE s_store_id1 = s_store_id2 AND d_week_seq1 = d_week_seq2 - 52
ORDER BY s_store_name1, s_store_id1, d_week_seq1
""",
    # q51: web-vs-store cumulative sales race -- FULL OUTER JOIN of two
    # windowed (sum over sum()) series, running max over ROWS frames.
    # ORDER BY (item_sk, d_date) is unique, so the LIMIT is kept and
    # compared as an exact top-k prefix.
    "q51": """
WITH web_v1 AS (
  SELECT ws_item_sk item_sk, d_date,
         sum(sum(ws_sales_price)) OVER (PARTITION BY ws_item_sk
           ORDER BY d_date ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
           cume_sales
  FROM web_sales, date_dim
  WHERE ws_sold_date_sk = d_date_sk AND d_month_seq BETWEEN 1200 AND 1211
    AND ws_item_sk IS NOT NULL
  GROUP BY ws_item_sk, d_date),
store_v1 AS (
  SELECT ss_item_sk item_sk, d_date,
         sum(sum(ss_sales_price)) OVER (PARTITION BY ss_item_sk
           ORDER BY d_date ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
           cume_sales
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk AND d_month_seq BETWEEN 1200 AND 1211
    AND ss_item_sk IS NOT NULL
  GROUP BY ss_item_sk, d_date)
SELECT *
FROM (SELECT item_sk, d_date, web_sales, store_sales,
             max(web_sales) OVER (PARTITION BY item_sk ORDER BY d_date
               ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
               web_cumulative,
             max(store_sales) OVER (PARTITION BY item_sk ORDER BY d_date
               ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
               store_cumulative
      FROM (SELECT CASE WHEN web.item_sk IS NOT NULL THEN web.item_sk
                        ELSE store.item_sk END item_sk,
                   CASE WHEN web.d_date IS NOT NULL THEN web.d_date
                        ELSE store.d_date END d_date,
                   web.cume_sales web_sales, store.cume_sales store_sales
            FROM web_v1 web FULL JOIN store_v1 store
              ON web.item_sk = store.item_sk AND web.d_date = store.d_date
           ) x
     ) y
WHERE web_cumulative > store_cumulative
ORDER BY item_sk, d_date
LIMIT 100
""",
    # q31: county quarter-over-quarter growth, web vs store -- two CTEs
    # each referenced three times, joined six ways (year adapted to a
    # non-vacuous region of the generated data)
    "q31": """
WITH ss AS (
  SELECT ca_county, d_qoy, d_year, sum(ss_ext_sales_price) store_sales
  FROM store_sales, date_dim, customer_address
  WHERE ss_sold_date_sk = d_date_sk AND ss_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year),
ws AS (
  SELECT ca_county, d_qoy, d_year, sum(ws_ext_sales_price) web_sales
  FROM web_sales, date_dim, customer_address
  WHERE ws_sold_date_sk = d_date_sk AND ws_bill_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year)
SELECT ss1.ca_county, ss1.d_year,
       CAST(ws2.web_sales AS double) / ws1.web_sales web_q1_q2_increase,
       CAST(ss2.store_sales AS double) / ss1.store_sales store_q1_q2_increase,
       CAST(ws3.web_sales AS double) / ws2.web_sales web_q2_q3_increase,
       CAST(ss3.store_sales AS double) / ss2.store_sales store_q2_q3_increase
FROM ss ss1, ss ss2, ss ss3, ws ws1, ws ws2, ws ws3
WHERE ss1.d_qoy = 1 AND ss1.d_year = 2001
  AND ss1.ca_county = ss2.ca_county AND ss2.d_qoy = 2 AND ss2.d_year = 2001
  AND ss2.ca_county = ss3.ca_county AND ss3.d_qoy = 3 AND ss3.d_year = 2001
  AND ss1.ca_county = ws1.ca_county AND ws1.d_qoy = 1 AND ws1.d_year = 2001
  AND ws1.ca_county = ws2.ca_county AND ws2.d_qoy = 2 AND ws2.d_year = 2001
  AND ws1.ca_county = ws3.ca_county AND ws3.d_qoy = 3 AND ws3.d_year = 2001
  AND CASE WHEN ws1.web_sales > 0.00
           THEN CAST(ws2.web_sales AS double) / ws1.web_sales
           ELSE NULL END
    > CASE WHEN ss1.store_sales > 0.00
           THEN CAST(ss2.store_sales AS double) / ss1.store_sales
           ELSE NULL END
  AND CASE WHEN ws2.web_sales > 0.00
           THEN CAST(ws3.web_sales AS double) / ws2.web_sales
           ELSE NULL END
    > CASE WHEN ss2.store_sales > 0.00
           THEN CAST(ss3.store_sales AS double) / ss2.store_sales
           ELSE NULL END
ORDER BY ss1.ca_county
""",
    # q41: items whose manufacturer carries attribute-combo products
    # (correlated count(*) scalar subquery; the correlation equality is
    # factored out of the spec's OR -- algebraically identical -- and
    # attribute combos are drawn from the generator's co-occurring
    # domains so the case is non-vacuous)
    "q41": """
SELECT DISTINCT i_product_name
FROM item i1
WHERE i_manufact_id BETWEEN 1 AND 1000
  AND (SELECT count(*) item_cnt FROM item
       WHERE i_manufact = i1.i_manufact
         AND ((i_category = 'Men'
               AND (i_color = 'cyan' OR i_color = 'dim')
               AND (i_units = 'Unknown' OR i_units = 'N/A')
               AND (i_size = 'medium' OR i_size = 'economy'))
           OR (i_category = 'Men'
               AND (i_color = 'firebrick' OR i_color = 'rose')
               AND (i_units = 'Each' OR i_units = 'Ton')
               AND (i_size = 'extra large' OR i_size = 'N/A'))
           OR (i_category = 'Men'
               AND (i_color = 'forest' OR i_color = 'metallic')
               AND (i_units = 'Gross' OR i_units = 'Oz')
               AND (i_size = 'N/A' OR i_size = 'small'))
           OR (i_category = 'Men'
               AND (i_color = 'navajo' OR i_color = 'thistle')
               AND (i_units = 'Tbl' OR i_units = 'Ton')
               AND (i_size = 'medium' OR i_size = 'large')))) > 0
ORDER BY i_product_name
""",
    # q44: best/worst performing items by store-4 average net profit
    # (rank windows over a HAVING gated by an uncorrelated scalar
    # subquery; the spec's null-addr baseline group is empty in this
    # generator, so the baseline is the plain store-wide average)
    "q44": """
SELECT asceding.rnk, i1.i_product_name best_performing,
       i2.i_product_name worst_performing
FROM (SELECT * FROM (SELECT item_sk, rank() OVER (ORDER BY rank_col) rnk
                     FROM (SELECT ss_item_sk item_sk,
                                  avg(ss_net_profit) rank_col
                           FROM store_sales ss1 WHERE ss_store_sk = 4
                           GROUP BY ss_item_sk
                           HAVING avg(ss_net_profit) >
                             (SELECT avg(ss_net_profit) * 0.9
                              FROM store_sales
                              WHERE ss_store_sk = 4)) v1) v11
      WHERE rnk < 11) asceding,
     (SELECT * FROM (SELECT item_sk, rank() OVER (ORDER BY rank_col DESC) rnk
                     FROM (SELECT ss_item_sk item_sk,
                                  avg(ss_net_profit) rank_col
                           FROM store_sales ss1 WHERE ss_store_sk = 4
                           GROUP BY ss_item_sk
                           HAVING avg(ss_net_profit) >
                             (SELECT avg(ss_net_profit) * 0.9
                              FROM store_sales
                              WHERE ss_store_sk = 4)) v2) v21
      WHERE rnk < 11) descending,
     item i1, item i2
WHERE asceding.rnk = descending.rnk
  AND i1.i_item_sk = asceding.item_sk
  AND i2.i_item_sk = descending.item_sk
ORDER BY asceding.rnk
""",
    # q45: web sales by zip/city where zip in a list OR item in a
    # subquery list -- an IN subquery in DISJUNCTIVE position (planned
    # as a semijoin mask column; zips from the generator domain)
    "q45": """
SELECT ca_zip, ca_city, sum(ws_sales_price) s
FROM web_sales, customer, customer_address, date_dim, item
WHERE ws_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND ws_item_sk = i_item_sk
  AND (substr(ca_zip, 1, 5) IN ('99019', '22939', '83468', '99551',
                                '60099', '47792', '43391', '98407',
                                '53519')
       OR i_item_id IN (SELECT i_item_id FROM item
                        WHERE i_item_sk IN (2, 3, 5, 7, 11, 13, 17, 19,
                                            23, 29)))
  AND ws_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip, ca_city
ORDER BY ca_zip, ca_city
""",
    # q10: demographics of store customers also active on web OR
    # catalog -- correlated EXISTS under OR (semijoin mask columns;
    # counties from the generator domain)
    "q10": """
SELECT cd_gender, cd_marital_status, cd_education_status, count(*) cnt1,
       cd_purchase_estimate, count(*) cnt2, cd_credit_rating, count(*) cnt3,
       cd_dep_count, count(*) cnt4, cd_dep_employed_count, count(*) cnt5,
       cd_dep_college_count, count(*) cnt6
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_county IN ('Ziebach County', 'Daviess County', 'Barrow County',
                    'Walker County', 'Fairfield County')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = 2002 AND d_moy BETWEEN 1 AND 4)
  AND (EXISTS (SELECT * FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk
                 AND d_year = 2002 AND d_moy BETWEEN 1 AND 4)
       OR EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = 2002 AND d_moy BETWEEN 1 AND 4))
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
ORDER BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
""",
    # q35: q10's shape with min/max/avg dependent-count profiles
    "q35": """
SELECT ca_state, cd_gender, cd_marital_status, cd_dep_count, count(*) cnt1,
       min(cd_dep_count) mn1, max(cd_dep_count) mx1, avg(cd_dep_count) av1,
       cd_dep_employed_count, count(*) cnt2, min(cd_dep_employed_count) mn2,
       max(cd_dep_employed_count) mx2, avg(cd_dep_employed_count) av2,
       cd_dep_college_count, count(*) cnt3, min(cd_dep_college_count) mn3,
       max(cd_dep_college_count) mx3, avg(cd_dep_college_count) av3
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = 2002 AND d_qoy < 4)
  AND (EXISTS (SELECT * FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk
                 AND d_year = 2002 AND d_qoy < 4)
       OR EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = 2002 AND d_qoy < 4))
GROUP BY ca_state, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
ORDER BY ca_state, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
""",
    # q67: store sales rollup over 8 keys, top-100 rank per category
    # (ROLLUP inside a derived table under a rank window; the sqlite
    # oracle stacks 9 UNION ALL levels -- see TPCDS_ORACLE)
    "q67": """
SELECT i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
       d_moy, s_store_id, sumsales, rk
FROM (SELECT i_category, i_class, i_brand, i_product_name, d_year,
             d_qoy, d_moy, s_store_id, sumsales,
             rank() OVER (PARTITION BY i_category
                          ORDER BY sumsales DESC) rk
      FROM (SELECT i_category, i_class, i_brand, i_product_name, d_year,
                   d_qoy, d_moy, s_store_id,
                   sum(coalesce(ss_sales_price * ss_quantity, 0.00)) sumsales
            FROM store_sales, date_dim, store, item
            WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
              AND ss_store_sk = s_store_sk
              AND d_month_seq BETWEEN 1200 AND 1211
            GROUP BY ROLLUP (i_category, i_class, i_brand, i_product_name,
                             d_year, d_qoy, d_moy, s_store_id)) dw1) dw2
WHERE rk <= 100
ORDER BY i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_id, sumsales, rk
""",
    # q70: state/county profit hierarchy -- ROLLUP + grouping() inside
    # the rank partition + a windowed IN subquery choosing top-5 states.
    # ORDER BY follows the q86 adaptation (plain keys for the spec's
    # CASE key; deterministic full ordering)
    "q70": """
SELECT sum(ss_net_profit) total_sum, s_state, s_county,
       grouping(s_state) + grouping(s_county) lochierarchy,
       rank() OVER (PARTITION BY grouping(s_state) + grouping(s_county),
                    CASE WHEN grouping(s_county) = 0 THEN s_state END
                    ORDER BY sum(ss_net_profit) DESC) rank_within_parent
FROM store_sales, date_dim d1, store
WHERE d1.d_month_seq BETWEEN 1200 AND 1211
  AND d1.d_date_sk = ss_sold_date_sk AND s_store_sk = ss_store_sk
  AND s_state IN (SELECT s_state
                  FROM (SELECT s_state s_state,
                               rank() OVER (PARTITION BY s_state
                                 ORDER BY sum(ss_net_profit) DESC) ranking
                        FROM store_sales, store, date_dim
                        WHERE d_month_seq BETWEEN 1200 AND 1211
                          AND d_date_sk = ss_sold_date_sk
                          AND s_store_sk = ss_store_sk
                        GROUP BY s_state) tmp1
                  WHERE ranking <= 5)
GROUP BY ROLLUP (s_state, s_county)
ORDER BY lochierarchy DESC, rank_within_parent, s_state, s_county
""",
    # q17: items returned in-quarter then re-bought by catalog --
    # ss->sr (ticket) ->cs (customer+item) chain with quantity
    # count/avg/stddev/cov profiles (sqlite has no stddev_samp; the
    # oracle computes sqrt((sumsq - sum^2/n)/(n-1)) -- see TPCDS_ORACLE)
    "q17": """
SELECT i_item_id, i_item_desc, s_state,
       count(ss_quantity) store_sales_quantitycount,
       avg(ss_quantity) store_sales_quantityave,
       stddev_samp(ss_quantity) store_sales_quantitystdev,
       stddev_samp(ss_quantity) / avg(ss_quantity) store_sales_quantitycov,
       count(sr_return_quantity) store_returns_quantitycount,
       avg(sr_return_quantity) store_returns_quantityave,
       stddev_samp(sr_return_quantity) store_returns_quantitystdev,
       stddev_samp(sr_return_quantity) / avg(sr_return_quantity)
         store_returns_quantitycov,
       count(cs_quantity) catalog_sales_quantitycount,
       avg(cs_quantity) catalog_sales_quantityave,
       stddev_samp(cs_quantity) catalog_sales_quantitystdev,
       stddev_samp(cs_quantity) / avg(cs_quantity) catalog_sales_quantitycov
FROM store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, store, item
WHERE d1.d_quarter_name = '2001Q1' AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_quarter_name IN ('2001Q1', '2001Q2', '2001Q3')
  AND sr_customer_sk = cs_bill_customer_sk AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_quarter_name IN ('2001Q1', '2001Q2', '2001Q3')
GROUP BY i_item_id, i_item_desc, s_state
ORDER BY i_item_id, i_item_desc, s_state
""",
    # q9: quantity-band discount/net-paid buckets -- ten UNCORRELATED
    # scalar subqueries in SELECT CASE position (planned as broadcast
    # single-row value channels); thresholds scaled to the suite's
    # sf=0.05 volume (~28.6k rows per 20-quantity band) so the CASE
    # branches split both ways
    "q9": """
SELECT CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) > 25000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) END bucket1,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) > 1000000000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) END bucket2,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) > 15000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) END bucket3,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 61 AND 80) > 1000000000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 61 AND 80)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 61 AND 80) END bucket4,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 81 AND 100) > 15000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 81 AND 100)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 81 AND 100) END bucket5
FROM reason WHERE r_reason_sk = 1
""",
    # q2: web+catalog weekly day-of-week sales, year-over-year ratio
    # (UNION ALL CTE feeding a pivot CTE referenced twice; the spec's
    # d_week_seq1 = d_week_seq2 - 53 offset equality is computed inside
    # the second derived table so it joins as a plain equi-key)
    "q2": """
WITH wscs AS (
  SELECT sold_date_sk, sales_price
  FROM (SELECT ws_sold_date_sk sold_date_sk, ws_ext_sales_price sales_price
        FROM web_sales
        UNION ALL
        SELECT cs_sold_date_sk sold_date_sk, cs_ext_sales_price sales_price
        FROM catalog_sales) x),
wswscs AS (
  SELECT d_week_seq,
         sum(CASE WHEN d_day_name = 'Sunday' THEN sales_price ELSE NULL END) sun_sales,
         sum(CASE WHEN d_day_name = 'Monday' THEN sales_price ELSE NULL END) mon_sales,
         sum(CASE WHEN d_day_name = 'Tuesday' THEN sales_price ELSE NULL END) tue_sales,
         sum(CASE WHEN d_day_name = 'Wednesday' THEN sales_price ELSE NULL END) wed_sales,
         sum(CASE WHEN d_day_name = 'Thursday' THEN sales_price ELSE NULL END) thu_sales,
         sum(CASE WHEN d_day_name = 'Friday' THEN sales_price ELSE NULL END) fri_sales,
         sum(CASE WHEN d_day_name = 'Saturday' THEN sales_price ELSE NULL END) sat_sales
  FROM wscs, date_dim
  WHERE d_date_sk = sold_date_sk
  GROUP BY d_week_seq)
SELECT d_week_seq1,
       CAST(sun_sales1 AS double) / sun_sales2 r1,
       CAST(mon_sales1 AS double) / mon_sales2 r2,
       CAST(tue_sales1 AS double) / tue_sales2 r3,
       CAST(wed_sales1 AS double) / wed_sales2 r4,
       CAST(thu_sales1 AS double) / thu_sales2 r5,
       CAST(fri_sales1 AS double) / fri_sales2 r6,
       CAST(sat_sales1 AS double) / sat_sales2 r7
FROM (SELECT wswscs.d_week_seq d_week_seq1, sun_sales sun_sales1,
             mon_sales mon_sales1, tue_sales tue_sales1,
             wed_sales wed_sales1, thu_sales thu_sales1,
             fri_sales fri_sales1, sat_sales sat_sales1
      FROM wswscs, date_dim
      WHERE date_dim.d_week_seq = wswscs.d_week_seq AND d_year = 2001) y,
     (SELECT wswscs.d_week_seq - 53 d_week_seq2_m53, sun_sales sun_sales2,
             mon_sales mon_sales2, tue_sales tue_sales2,
             wed_sales wed_sales2, thu_sales thu_sales2,
             fri_sales fri_sales2, sat_sales sat_sales2
      FROM wswscs, date_dim
      WHERE date_dim.d_week_seq = wswscs.d_week_seq AND d_year = 2002) z
WHERE d_week_seq1 = d_week_seq2_m53
ORDER BY d_week_seq1
""",
    # q16: catalog orders shipped from multiple warehouses with no
    # returns -- conjunct EXISTS with a correlated INEQUALITY residual
    # (general unique-id decorrelation route) + NOT EXISTS anti join +
    # count(DISTINCT); 60-day window folded into the end date literal
    "q16": """
SELECT count(DISTINCT cs_order_number) order_count,
       sum(cs_ext_ship_cost) total_shipping_cost,
       sum(cs_net_profit) total_net_profit
FROM catalog_sales cs1, date_dim, customer_address, call_center
WHERE d_date BETWEEN date '2002-02-01' AND date '2002-04-02'
  AND cs1.cs_ship_date_sk = d_date_sk
  AND cs1.cs_ship_addr_sk = ca_address_sk
  AND ca_state = 'GA'
  AND cs1.cs_call_center_sk = cc_call_center_sk
  AND cc_county IN ('Bronx County', 'Walker County', 'Franklin Parish')
  AND EXISTS (SELECT * FROM catalog_sales cs2
              WHERE cs1.cs_order_number = cs2.cs_order_number
                AND cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  AND NOT EXISTS (SELECT * FROM catalog_returns cr1
                  WHERE cs1.cs_order_number = cr1.cr_order_number)
ORDER BY count(DISTINCT cs_order_number)
""",
    # q94: q16's shape over web sales
    "q94": """
SELECT count(DISTINCT ws_order_number) order_count,
       sum(ws_ext_ship_cost) total_shipping_cost,
       sum(ws_net_profit) total_net_profit
FROM web_sales ws1, date_dim, customer_address, web_site
WHERE d_date BETWEEN date '1999-02-01' AND date '1999-04-02'
  AND ws1.ws_ship_date_sk = d_date_sk
  AND ws1.ws_ship_addr_sk = ca_address_sk
  AND ca_state = 'IL'
  AND ws1.ws_web_site_sk = web_site_sk
  AND web_company_name = 'pri'
  AND EXISTS (SELECT * FROM web_sales ws2
              WHERE ws1.ws_order_number = ws2.ws_order_number
                AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  AND NOT EXISTS (SELECT * FROM web_returns wr1
                  WHERE ws1.ws_order_number = wr1.wr_order_number)
ORDER BY count(DISTINCT ws_order_number)
""",
    # q95: q94 through a self-join CTE (ws_wh referenced by two IN
    # subqueries, one joined against returns)
    "q95": """
WITH ws_wh AS (
  SELECT ws1.ws_order_number, ws1.ws_warehouse_sk wh1,
         ws2.ws_warehouse_sk wh2
  FROM web_sales ws1, web_sales ws2
  WHERE ws1.ws_order_number = ws2.ws_order_number
    AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
SELECT count(DISTINCT ws_order_number) order_count,
       sum(ws_ext_ship_cost) total_shipping_cost,
       sum(ws_net_profit) total_net_profit
FROM web_sales ws1, date_dim, customer_address, web_site
WHERE d_date BETWEEN date '1999-02-01' AND date '1999-04-02'
  AND ws1.ws_ship_date_sk = d_date_sk
  AND ws1.ws_ship_addr_sk = ca_address_sk
  AND ca_state = 'IL'
  AND ws1.ws_web_site_sk = web_site_sk
  AND web_company_name = 'pri'
  AND ws1.ws_order_number IN (SELECT ws_order_number FROM ws_wh)
  AND ws1.ws_order_number IN (SELECT wr_order_number
                              FROM web_returns, ws_wh
                              WHERE wr_order_number = ws_wh.ws_order_number)
ORDER BY count(DISTINCT ws_order_number)
""",
    # q85: web-return reason profiles under OR-of-AND demographic and
    # geographic blocks (bands widened to the generated domains -- the
    # spec's narrow bands + double demographic match are vacuous at
    # test scale; money comparisons use explicit money literals)
    "q85": """
SELECT substr(r_reason_desc, 1, 20) r, avg(ws_quantity) q,
       avg(wr_refunded_cash) c, avg(wr_fee) f
FROM web_sales, web_returns, web_page, customer_demographics cd1,
     customer_demographics cd2, customer_address, date_dim, reason
WHERE ws_web_page_sk = wp_web_page_sk AND ws_item_sk = wr_item_sk
  AND ws_order_number = wr_order_number AND ws_sold_date_sk = d_date_sk
  AND d_year = 2000
  AND cd1.cd_demo_sk = wr_refunded_cdemo_sk
  AND cd2.cd_demo_sk = wr_returning_cdemo_sk
  AND ca_address_sk = wr_refunded_addr_sk
  AND r_reason_sk = wr_reason_sk
  AND ((cd1.cd_marital_status = 'M'
        AND cd1.cd_marital_status = cd2.cd_marital_status
        AND ws_sales_price BETWEEN 50.00 AND 200.00)
    OR (cd1.cd_marital_status = 'S'
        AND cd1.cd_marital_status = cd2.cd_marital_status
        AND ws_sales_price BETWEEN 0.00 AND 100.00)
    OR (cd1.cd_marital_status = 'W'
        AND cd1.cd_marital_status = cd2.cd_marital_status
        AND ws_sales_price BETWEEN 100.00 AND 300.00))
  AND ((ca_country = 'United States' AND ca_state IN ('IL', 'OH', 'NY')
        AND ws_net_profit BETWEEN -10000.00 AND 10000.00)
    OR (ca_country = 'United States' AND ca_state IN ('WA', 'CA', 'TX')
        AND ws_net_profit BETWEEN -5000.00 AND 10000.00)
    OR (ca_country = 'United States' AND ca_state IN ('TN', 'GA', 'IL')
        AND ws_net_profit BETWEEN 0.00 AND 10000.00))
GROUP BY r_reason_desc
ORDER BY substr(r_reason_desc, 1, 20), avg(ws_quantity),
         avg(wr_refunded_cash), avg(wr_fee)
""",
    # q49: worst return ratios per channel (LEFT JOIN made effective-
    # inner by the return-amount filter, per spec; dual rank windows;
    # UNION distinct across channels; comma date_dim join rewritten as
    # an explicit JOIN -- the engine rejects comma+outer mixes)
    "q49": """
SELECT 'w' channel, w_t.item, w_t.return_ratio,
       w_t.return_rank, w_t.currency_rank
FROM (SELECT item, return_ratio, currency_ratio,
             rank() OVER (ORDER BY return_ratio) return_rank,
             rank() OVER (ORDER BY currency_ratio) currency_rank
      FROM (SELECT web_sales.ws_item_sk item,
                   CAST(sum(coalesce(web_returns.wr_return_quantity, 0)) AS double) /
                     sum(coalesce(web_sales.ws_quantity, 0)) return_ratio,
                   CAST(sum(coalesce(web_returns.wr_return_amt, 0.00)) AS double) /
                     sum(coalesce(web_sales.ws_net_paid, 0.00)) currency_ratio
            FROM web_sales LEFT JOIN web_returns
              ON web_sales.ws_order_number = web_returns.wr_order_number
             AND web_sales.ws_item_sk = web_returns.wr_item_sk
            JOIN date_dim ON web_sales.ws_sold_date_sk = d_date_sk
            WHERE web_returns.wr_return_amt > 100.00
              AND web_sales.ws_net_profit > 1.00
              AND web_sales.ws_net_paid > 0.00
              AND web_sales.ws_quantity > 0
              AND d_year = 2001 AND d_moy = 12
            GROUP BY web_sales.ws_item_sk) in_w) w_t
WHERE w_t.return_rank <= 10 OR w_t.currency_rank <= 10
UNION
SELECT 'c' channel, c_t.item, c_t.return_ratio,
       c_t.return_rank, c_t.currency_rank
FROM (SELECT item, return_ratio, currency_ratio,
             rank() OVER (ORDER BY return_ratio) return_rank,
             rank() OVER (ORDER BY currency_ratio) currency_rank
      FROM (SELECT catalog_sales.cs_item_sk item,
                   CAST(sum(coalesce(catalog_returns.cr_return_quantity, 0)) AS double) /
                     sum(coalesce(catalog_sales.cs_quantity, 0)) return_ratio,
                   CAST(sum(coalesce(catalog_returns.cr_return_amount, 0.00)) AS double) /
                     sum(coalesce(catalog_sales.cs_net_paid, 0.00)) currency_ratio
            FROM catalog_sales LEFT JOIN catalog_returns
              ON catalog_sales.cs_order_number = catalog_returns.cr_order_number
             AND catalog_sales.cs_item_sk = catalog_returns.cr_item_sk
            JOIN date_dim ON catalog_sales.cs_sold_date_sk = d_date_sk
            WHERE catalog_returns.cr_return_amount > 100.00
              AND catalog_sales.cs_net_profit > 1.00
              AND catalog_sales.cs_net_paid > 0.00
              AND catalog_sales.cs_quantity > 0
              AND d_year = 2001 AND d_moy = 12
            GROUP BY catalog_sales.cs_item_sk) in_c) c_t
WHERE c_t.return_rank <= 10 OR c_t.currency_rank <= 10
UNION
SELECT 's' channel, s_t.item, s_t.return_ratio,
       s_t.return_rank, s_t.currency_rank
FROM (SELECT item, return_ratio, currency_ratio,
             rank() OVER (ORDER BY return_ratio) return_rank,
             rank() OVER (ORDER BY currency_ratio) currency_rank
      FROM (SELECT store_sales.ss_item_sk item,
                   CAST(sum(coalesce(store_returns.sr_return_quantity, 0)) AS double) /
                     sum(coalesce(store_sales.ss_quantity, 0)) return_ratio,
                   CAST(sum(coalesce(store_returns.sr_return_amt, 0.00)) AS double) /
                     sum(coalesce(store_sales.ss_net_paid, 0.00)) currency_ratio
            FROM store_sales LEFT JOIN store_returns
              ON store_sales.ss_ticket_number = store_returns.sr_ticket_number
             AND store_sales.ss_item_sk = store_returns.sr_item_sk
            JOIN date_dim ON store_sales.ss_sold_date_sk = d_date_sk
            WHERE store_returns.sr_return_amt > 100.00
              AND store_sales.ss_net_profit > 1.00
              AND store_sales.ss_net_paid > 0.00
              AND store_sales.ss_quantity > 0
              AND d_year = 2001 AND d_moy = 12
            GROUP BY store_sales.ss_item_sk) in_s) s_t
WHERE s_t.return_rank <= 10 OR s_t.currency_rank <= 10
ORDER BY 1, 4, 5, 2
""",
    # q39: inventory demand variability -- stddev/mean coefficient of
    # variation per warehouse/item/month, consecutive-month self-join
    # (CASE branches mix decimal and double: the coercion fix this
    # query motivated). Oracle emulates stddev_samp -- see TPCDS_ORACLE.
    "q39": """
WITH inv AS (
  SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy, stdev, mean,
         CASE mean WHEN 0.0 THEN NULL
              ELSE stdev / mean END cov
  FROM (SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
               stddev_samp(inv_quantity_on_hand) stdev,
               avg(inv_quantity_on_hand) mean
        FROM inventory, item, warehouse, date_dim
        WHERE inv_item_sk = i_item_sk AND inv_warehouse_sk = w_warehouse_sk
          AND inv_date_sk = d_date_sk AND d_year = 2001
        GROUP BY w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy) foo
  WHERE CASE mean WHEN 0.0 THEN 0.0 ELSE stdev / mean END > 1.0)
SELECT inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean,
       inv1.cov, inv2.w_warehouse_sk w2, inv2.i_item_sk i2, inv2.d_moy m2,
       inv2.mean mean2, inv2.cov cov2
FROM inv inv1, inv inv2
WHERE inv1.i_item_sk = inv2.i_item_sk
  AND inv1.w_warehouse_sk = inv2.w_warehouse_sk
  AND inv1.d_moy = 1 AND inv2.d_moy = 2
ORDER BY inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean,
         inv1.cov, inv2.d_moy, inv2.mean, inv2.cov
""",
    # q75: catalog/store/web net sales decline year-over-year for one
    # category (UNION distinct of three LEFT JOIN channel details; the
    # spec ratio `curr/prev < 0.9` compares exactly as
    # 10*curr < 9*prev on the integer side)
    "q75": """
WITH all_sales AS (
  SELECT d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
         sum(sales_cnt) sales_cnt, sum(sales_amt) sales_amt
  FROM (SELECT d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               cs_quantity - COALESCE(cr_return_quantity, 0) sales_cnt,
               cs_ext_sales_price - COALESCE(cr_return_amount, 0.00)
                 sales_amt
        FROM catalog_sales
        JOIN item ON i_item_sk = cs_item_sk
        JOIN date_dim ON d_date_sk = cs_sold_date_sk
        LEFT JOIN catalog_returns ON cs_order_number = cr_order_number
          AND cs_item_sk = cr_item_sk
        WHERE i_category = 'Books'
        UNION
        SELECT d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               ss_quantity - COALESCE(sr_return_quantity, 0) sales_cnt,
               ss_ext_sales_price - COALESCE(sr_return_amt, 0.00) sales_amt
        FROM store_sales
        JOIN item ON i_item_sk = ss_item_sk
        JOIN date_dim ON d_date_sk = ss_sold_date_sk
        LEFT JOIN store_returns ON ss_ticket_number = sr_ticket_number
          AND ss_item_sk = sr_item_sk
        WHERE i_category = 'Books'
        UNION
        SELECT d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               ws_quantity - COALESCE(wr_return_quantity, 0) sales_cnt,
               ws_ext_sales_price - COALESCE(wr_return_amt, 0.00) sales_amt
        FROM web_sales
        JOIN item ON i_item_sk = ws_item_sk
        JOIN date_dim ON d_date_sk = ws_sold_date_sk
        LEFT JOIN web_returns ON ws_order_number = wr_order_number
          AND ws_item_sk = wr_item_sk
        WHERE i_category = 'Books') sales_detail
  GROUP BY d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id)
SELECT prev_yr.d_year prev_year, curr_yr.d_year curr_year,
       curr_yr.i_brand_id, curr_yr.i_class_id, curr_yr.i_category_id,
       curr_yr.i_manufact_id, prev_yr.sales_cnt prev_yr_cnt,
       curr_yr.sales_cnt curr_yr_cnt,
       curr_yr.sales_cnt - prev_yr.sales_cnt sales_cnt_diff,
       curr_yr.sales_amt - prev_yr.sales_amt sales_amt_diff
FROM all_sales curr_yr, all_sales prev_yr
WHERE curr_yr.i_brand_id = prev_yr.i_brand_id
  AND curr_yr.i_class_id = prev_yr.i_class_id
  AND curr_yr.i_category_id = prev_yr.i_category_id
  AND curr_yr.i_manufact_id = prev_yr.i_manufact_id
  AND curr_yr.d_year = 2002 AND prev_yr.d_year = 2001
  AND 10 * curr_yr.sales_cnt < 9 * prev_yr.sales_cnt
ORDER BY sales_cnt_diff, sales_amt_diff
""",
    # q78: store sales of customers also active (unreturned) on web AND
    # catalog in-year. Adaptation: the ws/cs channel CTEs aggregate and
    # join per (year, customer) -- the spec's per-item triple
    # coincidence is vacuous at test scale (benchto's own text already
    # relaxes the cs join via its cs_item_sk = cs_item_sk quirk)
    "q78": """
WITH ws AS (
  SELECT d_year ws_sold_year, ws_bill_customer_sk ws_customer_sk,
         sum(ws_quantity) ws_qty, sum(ws_wholesale_cost) ws_wc,
         sum(ws_sales_price) ws_sp
  FROM web_sales
  LEFT JOIN web_returns ON wr_order_number = ws_order_number
    AND ws_item_sk = wr_item_sk
  JOIN date_dim ON ws_sold_date_sk = d_date_sk
  WHERE wr_order_number IS NULL
  GROUP BY d_year, ws_bill_customer_sk),
cs AS (
  SELECT d_year cs_sold_year, cs_bill_customer_sk cs_customer_sk,
         sum(cs_quantity) cs_qty, sum(cs_wholesale_cost) cs_wc,
         sum(cs_sales_price) cs_sp
  FROM catalog_sales
  LEFT JOIN catalog_returns ON cr_order_number = cs_order_number
    AND cs_item_sk = cr_item_sk
  JOIN date_dim ON cs_sold_date_sk = d_date_sk
  WHERE cr_order_number IS NULL
  GROUP BY d_year, cs_bill_customer_sk),
ss AS (
  SELECT d_year ss_sold_year, ss_item_sk, ss_customer_sk,
         sum(ss_quantity) ss_qty, sum(ss_wholesale_cost) ss_wc,
         sum(ss_sales_price) ss_sp
  FROM store_sales
  LEFT JOIN store_returns ON sr_ticket_number = ss_ticket_number
    AND ss_item_sk = sr_item_sk
  JOIN date_dim ON ss_sold_date_sk = d_date_sk
  WHERE sr_ticket_number IS NULL
  GROUP BY d_year, ss_item_sk, ss_customer_sk)
SELECT ss_sold_year, ss_item_sk, ss_customer_sk,
       CAST(ss_qty AS double) / COALESCE(ws_qty + cs_qty, 1) ratio,
       ss_qty store_qty, ss_wc store_wholesale_cost,
       ss_sp store_sales_price,
       COALESCE(ws_qty, 0) + COALESCE(cs_qty, 0) other_chan_qty,
       COALESCE(ws_wc, 0.00) + COALESCE(cs_wc, 0.00)
         other_chan_wholesale_cost,
       COALESCE(ws_sp, 0.00) + COALESCE(cs_sp, 0.00)
         other_chan_sales_price
FROM ss
LEFT JOIN ws ON ws_sold_year = ss_sold_year
  AND ws_customer_sk = ss_customer_sk
LEFT JOIN cs ON cs_sold_year = ss_sold_year
  AND cs_customer_sk = ss_customer_sk
WHERE COALESCE(ws_qty, 0) > 0 AND COALESCE(cs_qty, 0) > 0
  AND ss_sold_year = 2000
ORDER BY ss_sold_year, ss_item_sk, ss_customer_sk, ss_qty DESC,
         ss_wc DESC, ss_sp DESC
""",
    # q5/q77/q80: per-channel sales/returns/profit summaries rolled up
    # over (channel, id). The sqlite oracles stack the three rollup
    # levels as UNION ALL (see TPCDS_ORACLE). q77's catalog side joins
    # returns per call center (the spec's bare cross join of two
    # grouped CTEs needs an equi key here); comma+outer join mixes are
    # rewritten as explicit JOIN chains throughout.
    "q5": """
WITH ssr AS (
  SELECT s_store_id, sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns, sum(net_loss) profit_loss
  FROM (SELECT ss_store_sk store_sk, ss_sold_date_sk date_sk,
               ss_ext_sales_price sales_price, ss_net_profit profit,
               0.00 return_amt, 0.00 net_loss
        FROM store_sales
        UNION ALL
        SELECT sr_store_sk store_sk, sr_returned_date_sk date_sk,
               0.00 sales_price, 0.00 profit,
               sr_return_amt return_amt, sr_net_loss net_loss
        FROM store_returns) salesreturns, date_dim, store
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN date '2000-08-23' AND date '2000-09-06'
    AND store_sk = s_store_sk
  GROUP BY s_store_id),
csr AS (
  SELECT cp_catalog_page_id, sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns, sum(net_loss) profit_loss
  FROM (SELECT cs_catalog_page_sk page_sk, cs_sold_date_sk date_sk,
               cs_ext_sales_price sales_price, cs_net_profit profit,
               0.00 return_amt, 0.00 net_loss
        FROM catalog_sales
        UNION ALL
        SELECT cr_catalog_page_sk page_sk, cr_returned_date_sk date_sk,
               0.00 sales_price, 0.00 profit,
               cr_return_amount return_amt, cr_net_loss net_loss
        FROM catalog_returns) salesreturns, date_dim, catalog_page
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN date '2000-08-23' AND date '2000-09-06'
    AND page_sk = cp_catalog_page_sk
  GROUP BY cp_catalog_page_id),
wsr AS (
  SELECT web_site_id, sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns, sum(net_loss) profit_loss
  FROM (SELECT ws_web_site_sk wsr_web_site_sk, ws_sold_date_sk date_sk,
               ws_ext_sales_price sales_price, ws_net_profit profit,
               0.00 return_amt, 0.00 net_loss
        FROM web_sales
        UNION ALL
        SELECT ws_web_site_sk wsr_web_site_sk,
               wr_returned_date_sk date_sk,
               0.00 sales_price, 0.00 profit,
               wr_return_amt return_amt, wr_net_loss net_loss
        FROM web_returns
        LEFT JOIN web_sales ON wr_item_sk = ws_item_sk
          AND wr_order_number = ws_order_number) salesreturns,
       date_dim, web_site
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN date '2000-08-23' AND date '2000-09-06'
    AND wsr_web_site_sk = web_site_sk
  GROUP BY web_site_id)

SELECT channel, id, sum(sales) sales, sum(returns) returns,
       sum(profit) profit
FROM
  (SELECT 'store channel' channel, concat('store', s_store_id) id,
          sales, returns, profit - profit_loss profit
   FROM ssr
   UNION ALL
   SELECT 'catalog channel' channel,
          concat('catalog_page', cp_catalog_page_id) id,
          sales, returns, profit - profit_loss profit
   FROM csr
   UNION ALL
   SELECT 'web channel' channel, concat('web_site', web_site_id) id,
          sales, returns, profit - profit_loss profit
   FROM wsr) x

GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
""",
    "q77": """
WITH ss AS (
  SELECT s_store_sk, sum(ss_ext_sales_price) sales,
         sum(ss_net_profit) profit
  FROM store_sales, date_dim, store
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN date '2000-08-23' AND date '2000-09-22'
    AND ss_store_sk = s_store_sk
  GROUP BY s_store_sk),
sr AS (
  SELECT s_store_sk, sum(sr_return_amt) returns,
         sum(sr_net_loss) profit_loss
  FROM store_returns, date_dim, store
  WHERE sr_returned_date_sk = d_date_sk
    AND d_date BETWEEN date '2000-08-23' AND date '2000-09-22'
    AND sr_store_sk = s_store_sk
  GROUP BY s_store_sk),
cs AS (
  SELECT cs_call_center_sk, sum(cs_ext_sales_price) sales,
         sum(cs_net_profit) profit
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN date '2000-08-23' AND date '2000-09-22'
  GROUP BY cs_call_center_sk),
cr AS (
  SELECT cr_call_center_sk, sum(cr_return_amount) returns,
         sum(cr_net_loss) profit_loss
  FROM catalog_returns, date_dim
  WHERE cr_returned_date_sk = d_date_sk
    AND d_date BETWEEN date '2000-08-23' AND date '2000-09-22'
  GROUP BY cr_call_center_sk),
ws AS (
  SELECT wp_web_page_sk, sum(ws_ext_sales_price) sales,
         sum(ws_net_profit) profit
  FROM web_sales, date_dim, web_page
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN date '2000-08-23' AND date '2000-09-22'
    AND ws_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk),
wr AS (
  SELECT wp_web_page_sk, sum(wr_return_amt) returns,
         sum(wr_net_loss) profit_loss
  FROM web_returns, date_dim, web_page
  WHERE wr_returned_date_sk = d_date_sk
    AND d_date BETWEEN date '2000-08-23' AND date '2000-09-22'
    AND wr_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk)

SELECT channel, id, sum(sales) sales, sum(returns) returns,
       sum(profit) profit
FROM
  (SELECT 'store channel' channel, ss.s_store_sk id, sales,
          COALESCE(returns, 0.00) returns,
          profit - COALESCE(profit_loss, 0.00) profit
   FROM ss LEFT JOIN sr ON ss.s_store_sk = sr.s_store_sk
   UNION ALL
   SELECT 'catalog channel' channel, cs_call_center_sk id, sales,
          COALESCE(returns, 0.00) returns,
          profit - COALESCE(profit_loss, 0.00) profit
   FROM cs LEFT JOIN cr ON cs_call_center_sk = cr_call_center_sk
   UNION ALL
   SELECT 'web channel' channel, ws.wp_web_page_sk id, sales,
          COALESCE(returns, 0.00) returns,
          profit - COALESCE(profit_loss, 0.00) profit
   FROM ws LEFT JOIN wr ON ws.wp_web_page_sk = wr.wp_web_page_sk) x

GROUP BY ROLLUP (channel, id)
ORDER BY channel, id, sales
""",
    "q80": """
WITH ssr AS (
  SELECT s_store_id store_id, sum(ss_ext_sales_price) sales,
         sum(COALESCE(sr_return_amt, 0.00)) returns,
         sum(ss_net_profit - COALESCE(sr_net_loss, 0.00)) profit
  FROM store_sales
  LEFT JOIN store_returns ON ss_item_sk = sr_item_sk
    AND ss_ticket_number = sr_ticket_number
  JOIN date_dim ON ss_sold_date_sk = d_date_sk
  JOIN store ON ss_store_sk = s_store_sk
  JOIN item ON ss_item_sk = i_item_sk
  JOIN promotion ON ss_promo_sk = p_promo_sk
  WHERE d_date BETWEEN date '2000-08-23' AND date '2000-09-22'
    AND i_current_price > 50.00 AND p_channel_tv = 'N'
  GROUP BY s_store_id),
csr AS (
  SELECT cp_catalog_page_id catalog_page_id, sum(cs_ext_sales_price) sales,
         sum(COALESCE(cr_return_amount, 0.00)) returns,
         sum(cs_net_profit - COALESCE(cr_net_loss, 0.00)) profit
  FROM catalog_sales
  LEFT JOIN catalog_returns ON cs_item_sk = cr_item_sk
    AND cs_order_number = cr_order_number
  JOIN date_dim ON cs_sold_date_sk = d_date_sk
  JOIN catalog_page ON cs_catalog_page_sk = cp_catalog_page_sk
  JOIN item ON cs_item_sk = i_item_sk
  JOIN promotion ON cs_promo_sk = p_promo_sk
  WHERE d_date BETWEEN date '2000-08-23' AND date '2000-09-22'
    AND i_current_price > 50.00 AND p_channel_tv = 'N'
  GROUP BY cp_catalog_page_id),
wsr AS (
  SELECT web_site_id, sum(ws_ext_sales_price) sales,
         sum(COALESCE(wr_return_amt, 0.00)) returns,
         sum(ws_net_profit - COALESCE(wr_net_loss, 0.00)) profit
  FROM web_sales
  LEFT JOIN web_returns ON ws_item_sk = wr_item_sk
    AND ws_order_number = wr_order_number
  JOIN date_dim ON ws_sold_date_sk = d_date_sk
  JOIN web_site ON ws_web_site_sk = web_site_sk
  JOIN item ON ws_item_sk = i_item_sk
  JOIN promotion ON ws_promo_sk = p_promo_sk
  WHERE d_date BETWEEN date '2000-08-23' AND date '2000-09-22'
    AND i_current_price > 50.00 AND p_channel_tv = 'N'
  GROUP BY web_site_id)

SELECT channel, id, sum(sales) sales, sum(returns) returns,
       sum(profit) profit
FROM
  (SELECT 'store channel' channel, store_id id, sales, returns, profit
   FROM ssr
   UNION ALL
   SELECT 'catalog channel' channel, catalog_page_id id, sales, returns,
          profit
   FROM csr
   UNION ALL
   SELECT 'web channel' channel, web_site_id id, sales, returns, profit
   FROM wsr) x

GROUP BY ROLLUP (channel, id)
ORDER BY channel, id, sales
""",
    # q58: items with balanced revenue across all three channels in one
    # week (nested scalar subquery inside an IN subquery; bands widened
    # to 0.2x..5x -- the spec's +-10% triple coincidence is vacuous at
    # test scale)
    "q58": """
WITH ss_items AS (
  SELECT i_item_id item_id, sum(ss_ext_sales_price) ss_item_rev
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq FROM date_dim
                                       WHERE d_date = date '2000-01-03'))
    AND ss_sold_date_sk = d_date_sk
  GROUP BY i_item_id),
cs_items AS (
  SELECT i_item_id item_id, sum(cs_ext_sales_price) cs_item_rev
  FROM catalog_sales, item, date_dim
  WHERE cs_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq FROM date_dim
                                       WHERE d_date = date '2000-01-03'))
    AND cs_sold_date_sk = d_date_sk
  GROUP BY i_item_id),
ws_items AS (
  SELECT i_item_id item_id, sum(ws_ext_sales_price) ws_item_rev
  FROM web_sales, item, date_dim
  WHERE ws_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq FROM date_dim
                                       WHERE d_date = date '2000-01-03'))
    AND ws_sold_date_sk = d_date_sk
  GROUP BY i_item_id)
SELECT ss_items.item_id, ss_item_rev,
       CAST(ss_item_rev AS double) /
         ((ss_item_rev + cs_item_rev + ws_item_rev) / 3.0) * 100 ss_dev,
       cs_item_rev,
       CAST(cs_item_rev AS double) /
         ((ss_item_rev + cs_item_rev + ws_item_rev) / 3.0) * 100 cs_dev,
       ws_item_rev,
       CAST(ws_item_rev AS double) /
         ((ss_item_rev + cs_item_rev + ws_item_rev) / 3.0) * 100 ws_dev,
       (ss_item_rev + cs_item_rev + ws_item_rev) / 3.0 average
FROM ss_items, cs_items, ws_items
WHERE ss_items.item_id = cs_items.item_id
  AND ss_items.item_id = ws_items.item_id
  AND ss_item_rev BETWEEN 0.2 * cs_item_rev AND 5 * cs_item_rev
  AND ss_item_rev BETWEEN 0.2 * ws_item_rev AND 5 * ws_item_rev
  AND cs_item_rev BETWEEN 0.2 * ss_item_rev AND 5 * ss_item_rev
  AND cs_item_rev BETWEEN 0.2 * ws_item_rev AND 5 * ws_item_rev
  AND ws_item_rev BETWEEN 0.2 * ss_item_rev AND 5 * ss_item_rev
  AND ws_item_rev BETWEEN 0.2 * cs_item_rev AND 5 * cs_item_rev
ORDER BY ss_items.item_id, ss_item_rev
""",
    # q72: promotion effect on late catalog shipments with low same-week
    # inventory -- 11-table join with cross-table inequality residuals
    # (inv qty < order qty; ship > sale + 5 days). hd_buy_potential
    # widened to two buckets (single-bucket is vacuous at test scale).
    # The query that exposed (and now regression-tests) wide composite
    # string-key joins downstream.
    "q72": """
SELECT i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(CASE WHEN p_promo_sk IS NULL THEN 1 ELSE 0 END) no_promo,
       sum(CASE WHEN p_promo_sk IS NOT NULL THEN 1 ELSE 0 END) promo,
       count(*) total_cnt
FROM catalog_sales
JOIN inventory ON cs_item_sk = inv_item_sk
JOIN warehouse ON w_warehouse_sk = inv_warehouse_sk
JOIN item ON i_item_sk = cs_item_sk
JOIN customer_demographics ON cs_bill_cdemo_sk = cd_demo_sk
JOIN household_demographics ON cs_bill_hdemo_sk = hd_demo_sk
JOIN date_dim d1 ON cs_sold_date_sk = d1.d_date_sk
JOIN date_dim d2 ON inv_date_sk = d2.d_date_sk
JOIN date_dim d3 ON cs_ship_date_sk = d3.d_date_sk
LEFT JOIN promotion ON cs_promo_sk = p_promo_sk
LEFT JOIN catalog_returns ON cr_item_sk = cs_item_sk
  AND cr_order_number = cs_order_number
WHERE d1.d_week_seq = d2.d_week_seq
  AND inv_quantity_on_hand < cs_quantity
  AND d3.d_date > d1.d_date + 5
  AND hd_buy_potential IN ('>10000', '5001-10000')
  AND d1.d_year = 1999
  AND cd_marital_status = 'D'
GROUP BY i_item_desc, w_warehouse_name, d1.d_week_seq
ORDER BY total_cnt DESC, i_item_desc, w_warehouse_name, d1.d_week_seq
""",
    # q54: revenue segments of customers acquired through catalog/web
    # who then shop in county-matched stores -- scalar subqueries as
    # BETWEEN bounds (broadcast value channels) and a composite
    # (county, state) STRING-key join: the query that exposed the
    # cross-width string join-key misalignment. Cohort widened to the
    # acquisition year; i_class from the generator domain.
    "q54": """
WITH my_customers AS (
  SELECT DISTINCT c_customer_sk, c_current_addr_sk
  FROM (SELECT cs_sold_date_sk sold_date_sk,
               cs_bill_customer_sk customer_sk, cs_item_sk item_sk
        FROM catalog_sales
        UNION ALL
        SELECT ws_sold_date_sk sold_date_sk,
               ws_bill_customer_sk customer_sk, ws_item_sk item_sk
        FROM web_sales) cs_or_ws_sales, item, date_dim, customer
  WHERE sold_date_sk = d_date_sk AND item_sk = i_item_sk
    AND i_category = 'Women' AND i_class = 'bedding'
    AND c_customer_sk = cs_or_ws_sales.customer_sk
    AND d_year = 1998),
my_revenue AS (
  SELECT c_customer_sk, sum(ss_ext_sales_price) revenue
  FROM my_customers, store_sales, customer_address, store, date_dim
  WHERE c_current_addr_sk = ca_address_sk
    AND ca_county = s_county AND ca_state = s_state
    AND ss_sold_date_sk = d_date_sk
    AND c_customer_sk = ss_customer_sk
    AND d_month_seq BETWEEN (SELECT DISTINCT d_month_seq + 1
                             FROM date_dim
                             WHERE d_year = 1998 AND d_moy = 12)
                        AND (SELECT DISTINCT d_month_seq + 3
                             FROM date_dim
                             WHERE d_year = 1998 AND d_moy = 12)
  GROUP BY c_customer_sk),
segments AS (
  SELECT CAST(revenue / 50 AS integer) segment FROM my_revenue)
SELECT segment, count(*) num_customers, segment * 50 segment_base
FROM segments
GROUP BY segment
ORDER BY segment, num_customers
""",
    # q8: store profits in zip prefixes shared with frequent preferred
    # customers -- INTERSECT of a zip list with a HAVING-filtered
    # aggregate, joined to stores on 2-char zip PREFIXES (the spec's
    # substr()=substr() join keys are computed inside derived tables;
    # zip list drawn from the generator's frequent-preferred set and
    # the HAVING threshold scaled 10 -> 3 so the INTERSECT is
    # non-vacuous at test scale)
    "q8": """
SELECT s_store_name, sum(ss_net_profit) p
FROM store_sales, date_dim,
     (SELECT s_store_sk ss_sk, s_store_name,
             substr(s_zip, 1, 2) s_zip2 FROM store) st,
     (SELECT ca_zip, substr(ca_zip, 1, 2) ca_zip2
      FROM (SELECT substr(ca_zip, 1, 5) ca_zip FROM customer_address
            WHERE substr(ca_zip, 1, 5) IN (
              '10895', '10978', '11325', '11566', '12162', '12866',
              '13735', '14121', '14329', '14685', '14737', '14927',
              '15234', '15628', '15791', '15865', '17095', '17277',
              '17793', '18094')
            INTERSECT
            SELECT ca_zip
            FROM (SELECT substr(ca_zip, 1, 5) ca_zip, count(*) cnt
                  FROM customer_address, customer
                  WHERE ca_address_sk = c_current_addr_sk
                    AND c_preferred_cust_flag = 'Y'
                  GROUP BY ca_zip
                  HAVING count(*) > 3) a1) a2) v1
WHERE ss_store_sk = ss_sk AND ss_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 1998
  AND st.s_zip2 = v1.ca_zip2
GROUP BY s_store_name
ORDER BY s_store_name
""",
    # q14: items selling in ALL THREE channels (3-way INTERSECT over
    # brand/class/category triples) vs the all-channel average --
    # ROLLUP over (channel, brand, class, category); the oracle stacks
    # the five rollup levels (see TPCDS_ORACLE)
    "q14": """
WITH cross_items AS (
  SELECT i_item_sk ss_item_sk
  FROM item,
       (SELECT iss.i_brand_id brand_id, iss.i_class_id class_id,
               iss.i_category_id category_id
        FROM store_sales, item iss, date_dim d1
        WHERE ss_item_sk = iss.i_item_sk
          AND ss_sold_date_sk = d1.d_date_sk
          AND d1.d_year BETWEEN 1999 AND 2001
        INTERSECT
        SELECT ics.i_brand_id, ics.i_class_id, ics.i_category_id
        FROM catalog_sales, item ics, date_dim d2
        WHERE cs_item_sk = ics.i_item_sk
          AND cs_sold_date_sk = d2.d_date_sk
          AND d2.d_year BETWEEN 1999 AND 2001
        INTERSECT
        SELECT iws.i_brand_id, iws.i_class_id, iws.i_category_id
        FROM web_sales, item iws, date_dim d3
        WHERE ws_item_sk = iws.i_item_sk
          AND ws_sold_date_sk = d3.d_date_sk
          AND d3.d_year BETWEEN 1999 AND 2001) x
  WHERE i_brand_id = brand_id AND i_class_id = class_id
    AND i_category_id = category_id),
avg_sales AS (
  SELECT avg(quantity * list_price) average_sales
  FROM (SELECT ss_quantity quantity, ss_list_price list_price
        FROM store_sales, date_dim
        WHERE ss_sold_date_sk = d_date_sk
          AND d_year BETWEEN 1999 AND 2001
        UNION ALL
        SELECT cs_quantity quantity, cs_list_price list_price
        FROM catalog_sales, date_dim
        WHERE cs_sold_date_sk = d_date_sk
          AND d_year BETWEEN 1999 AND 2001
        UNION ALL
        SELECT ws_quantity quantity, ws_list_price list_price
        FROM web_sales, date_dim
        WHERE ws_sold_date_sk = d_date_sk
          AND d_year BETWEEN 1999 AND 2001) x)

SELECT channel, i_brand_id, i_class_id, i_category_id, sum(sales) s,
       sum(number_sales) n
FROM
  (
   SELECT 'store' channel, i_brand_id, i_class_id, i_category_id,
          sum(ss_quantity * ss_list_price) sales, count(*) number_sales
   FROM store_sales, item, date_dim
   WHERE ss_item_sk IN (SELECT ss_item_sk FROM cross_items)
     AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
     AND d_year = 2001 AND d_moy = 11
   GROUP BY i_brand_id, i_class_id, i_category_id
   HAVING sum(ss_quantity * ss_list_price) > (SELECT average_sales FROM avg_sales)
   UNION ALL
   SELECT 'catalog' channel, i_brand_id, i_class_id, i_category_id,
          sum(cs_quantity * cs_list_price) sales, count(*) number_sales
   FROM catalog_sales, item, date_dim
   WHERE cs_item_sk IN (SELECT ss_item_sk FROM cross_items)
     AND cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
     AND d_year = 2001 AND d_moy = 11
   GROUP BY i_brand_id, i_class_id, i_category_id
   HAVING sum(cs_quantity * cs_list_price) > (SELECT average_sales FROM avg_sales)
   UNION ALL
   SELECT 'web' channel, i_brand_id, i_class_id, i_category_id,
          sum(ws_quantity * ws_list_price) sales, count(*) number_sales
   FROM web_sales, item, date_dim
   WHERE ws_item_sk IN (SELECT ss_item_sk FROM cross_items)
     AND ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
     AND d_year = 2001 AND d_moy = 11
   GROUP BY i_brand_id, i_class_id, i_category_id
   HAVING sum(ws_quantity * ws_list_price) > (SELECT average_sales FROM avg_sales)) y

GROUP BY ROLLUP (channel, i_brand_id, i_class_id, i_category_id)
ORDER BY channel, i_brand_id, i_class_id, i_category_id
""",
    # q23: February catalog+web sales of frequently-store-sold items to
    # the best store customers (HAVING against a max-over-sums CTE
    # scalar; count threshold 4 -> 1 and 0.500 written with 3 decimals
    # for the cents-literal convention)
    "q23": """
WITH frequent_ss_items AS (
  SELECT substr(i_item_desc, 1, 30) itemdesc, i_item_sk item_sk,
         d_date solddate, count(*) cnt
  FROM store_sales, date_dim, item
  WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
    AND d_year IN (2000, 2001, 2002, 2003)
  GROUP BY substr(i_item_desc, 1, 30), i_item_sk, d_date
  HAVING count(*) > 1),
max_store_sales AS (
  SELECT max(csales) tpcds_cmax
  FROM (SELECT c_customer_sk, sum(ss_quantity * ss_sales_price) csales
        FROM store_sales, customer, date_dim
        WHERE ss_customer_sk = c_customer_sk
          AND ss_sold_date_sk = d_date_sk
          AND d_year IN (2000, 2001, 2002, 2003)
        GROUP BY c_customer_sk) x),
best_ss_customer AS (
  SELECT c_customer_sk, sum(ss_quantity * ss_sales_price) ssales
  FROM store_sales, customer
  WHERE ss_customer_sk = c_customer_sk
  GROUP BY c_customer_sk
  HAVING sum(ss_quantity * ss_sales_price) >
         (SELECT 0.500 * tpcds_cmax FROM max_store_sales))
SELECT sum(sales) total
FROM (SELECT cs_quantity * cs_list_price sales
      FROM catalog_sales, date_dim
      WHERE d_year = 2000 AND d_moy = 2 AND cs_sold_date_sk = d_date_sk
        AND cs_item_sk IN (SELECT item_sk FROM frequent_ss_items)
        AND cs_bill_customer_sk IN (SELECT c_customer_sk
                                    FROM best_ss_customer)
      UNION ALL
      SELECT ws_quantity * ws_list_price sales
      FROM web_sales, date_dim
      WHERE d_year = 2000 AND d_moy = 2 AND ws_sold_date_sk = d_date_sk
        AND ws_item_sk IN (SELECT item_sk FROM frequent_ss_items)
        AND ws_bill_customer_sk IN (SELECT c_customer_sk
                                    FROM best_ss_customer)) y
""",
    # q24: returned-item spenders above 5% of the average (CTE
    # referenced by the outer query AND its HAVING scalar subquery;
    # upper(ca_country) computed in a derived table so it joins as a
    # plain key; geographic link at state level -- the generated
    # s_zip/ca_zip domains share only 2 values; market 7 and a domain
    # color; explicit JOIN chain keeps intermediates customer-bounded)
    "q24": """
WITH ssales AS (
  SELECT c_last_name, c_first_name, s_store_name, ca_state, s_state,
         i_color, i_current_price, i_manager_id, i_units, i_size,
         sum(ss_net_paid) netpaid
  FROM store_sales
  JOIN store_returns ON ss_ticket_number = sr_ticket_number
    AND ss_item_sk = sr_item_sk
  JOIN customer ON ss_customer_sk = c_customer_sk
  JOIN (SELECT ca_address_sk, ca_state, upper(ca_country) ca_country_up
        FROM customer_address) ca
    ON c_current_addr_sk = ca_address_sk
    AND c_birth_country = ca_country_up
  JOIN store ON ss_store_sk = s_store_sk AND ca_state = s_state
  JOIN item ON ss_item_sk = i_item_sk
  WHERE s_market_id = 7
  GROUP BY c_last_name, c_first_name, s_store_name, ca_state, s_state,
           i_color, i_current_price, i_manager_id, i_units, i_size)
SELECT c_last_name, c_first_name, s_store_name, sum(netpaid) paid
FROM ssales
WHERE i_color = 'blue'
GROUP BY c_last_name, c_first_name, s_store_name
HAVING sum(netpaid) > (SELECT 0.050 * avg(netpaid) FROM ssales)
ORDER BY c_last_name, c_first_name, s_store_name
""",
    # q64: items returned and re-bought at the same store across
    # consecutive years -- the 17-table cross_sales CTE (profitable
    # catalog items via a HAVING sum > 2*sum gate, both customer
    # demographic/address/income-band sides, a cross-table
    # marital-status inequality) self-joined on item+store. Colors from
    # the generator domain; price band widened (the spec double band is
    # vacuous at test scale).
    "q64": """
WITH cs_ui AS (
  SELECT cs_item_sk,
         sum(cs_ext_list_price) sale,
         sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit)
           refund
  FROM catalog_sales, catalog_returns
  WHERE cs_item_sk = cr_item_sk AND cs_order_number = cr_order_number
  GROUP BY cs_item_sk
  HAVING sum(cs_ext_list_price) >
         2 * sum(cr_refunded_cash + cr_reversed_charge
                 + cr_store_credit)),
cross_sales AS (
  SELECT i_product_name product_name, i_item_sk item_sk,
         s_store_name store_name, s_zip store_zip,
         ad1.ca_street_number b_street_number,
         ad1.ca_street_name b_street_name, ad1.ca_city b_city,
         ad1.ca_zip b_zip, ad2.ca_street_number c_street_number,
         ad2.ca_street_name c_street_name, ad2.ca_city c_city,
         ad2.ca_zip c_zip, d1.d_year syear, d2.d_year fsyear,
         d3.d_year s2year, count(*) cnt, sum(ss_wholesale_cost) s1,
         sum(ss_list_price) s2, sum(ss_coupon_amt) s3
  FROM store_sales, store_returns, cs_ui, date_dim d1, date_dim d2,
       date_dim d3, store, customer, customer_demographics cd1,
       customer_demographics cd2, promotion, household_demographics hd1,
       household_demographics hd2, customer_address ad1,
       customer_address ad2, income_band ib1, income_band ib2, item
  WHERE ss_store_sk = s_store_sk AND ss_sold_date_sk = d1.d_date_sk
    AND ss_customer_sk = c_customer_sk AND ss_cdemo_sk = cd1.cd_demo_sk
    AND ss_hdemo_sk = hd1.hd_demo_sk AND ss_addr_sk = ad1.ca_address_sk
    AND ss_item_sk = i_item_sk AND ss_item_sk = sr_item_sk
    AND ss_ticket_number = sr_ticket_number
    AND ss_item_sk = cs_ui.cs_item_sk
    AND c_current_cdemo_sk = cd2.cd_demo_sk
    AND c_current_hdemo_sk = hd2.hd_demo_sk
    AND c_current_addr_sk = ad2.ca_address_sk
    AND c_first_sales_date_sk = d2.d_date_sk
    AND c_first_shipto_date_sk = d3.d_date_sk
    AND ss_promo_sk = p_promo_sk
    AND hd1.hd_income_band_sk = ib1.ib_income_band_sk
    AND hd2.hd_income_band_sk = ib2.ib_income_band_sk
    AND cd1.cd_marital_status <> cd2.cd_marital_status
    AND i_color IN ('azure', 'blue', 'black', 'beige', 'coral', 'cream')
    AND i_current_price BETWEEN 10.00 AND 90.00
  GROUP BY i_product_name, i_item_sk, s_store_name, s_zip,
           ad1.ca_street_number, ad1.ca_street_name, ad1.ca_city,
           ad1.ca_zip, ad2.ca_street_number, ad2.ca_street_name,
           ad2.ca_city, ad2.ca_zip, d1.d_year, d2.d_year, d3.d_year)
SELECT cs1.product_name, cs1.store_name, cs1.store_zip,
       cs1.b_street_number, cs1.b_street_name, cs1.b_city, cs1.b_zip,
       cs1.c_street_number, cs1.c_street_name, cs1.c_city, cs1.c_zip,
       cs1.syear, cs1.cnt, cs1.s1 s11, cs1.s2 s21, cs1.s3 s31,
       cs2.s1 s12, cs2.s2 s22, cs2.s3 s32, cs2.syear syear2, cs2.cnt cnt2
FROM cross_sales cs1, cross_sales cs2
WHERE cs1.item_sk = cs2.item_sk AND cs1.syear = 1999
  AND cs2.syear = 2000 AND cs2.cnt <= cs1.cnt
  AND cs1.store_name = cs2.store_name
  AND cs1.store_zip = cs2.store_zip
ORDER BY cs1.product_name, cs1.store_name, cs2.cnt
""",
}

# q66: warehouse monthly pivot over web+catalog (36 pivot aggregates per
# channel; generated, not hand-written -- the spec's text is the same
# 12-month template stamped out). Money ratios divide dollars on the
# engine; the oracle divides its raw cents by 100 to match.
_Q66_MONTHS = ["jan", "feb", "mar", "apr", "may", "jun",
               "jul", "aug", "sep", "oct", "nov", "dec"]


def _q66_channel(tbl, price, qty, date_sk, time_sk, ship_mode_sk, wh_sk):
    piv = []
    for i, m in enumerate(_Q66_MONTHS):
        piv.append(f"sum(CASE WHEN d_moy = {i+1} THEN {price} * {qty} "
                   f"ELSE 0.00 END) {m}_sales")
    for i, m in enumerate(_Q66_MONTHS):
        piv.append(f"sum(CASE WHEN d_moy = {i+1} THEN {qty} "
                   f"ELSE 0 END) {m}_net")
    cols = ",\n         ".join(piv)
    return f"""
SELECT w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
       w_country, 'DHL,BARIAN' ship_carriers, d_year yr,
         {cols}
FROM {tbl}, warehouse, date_dim, time_dim, ship_mode
WHERE {date_sk} = d_date_sk AND {wh_sk} = w_warehouse_sk
  AND {time_sk} = t_time_sk AND {ship_mode_sk} = sm_ship_mode_sk
  AND d_year = 2001 AND t_time BETWEEN 30838 AND 59238
  AND sm_carrier IN ('DHL', 'BARIAN')
GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
         w_country, d_year"""


def _q66_text() -> str:
    sums = ",\n       ".join(
        [f"sum({m}_sales) {m}_sales" for m in _Q66_MONTHS]
        + [f"sum(CAST({m}_sales AS double) / w_warehouse_sq_ft) "
           f"{m}_sales_per_sq_foot" for m in _Q66_MONTHS]
        + [f"sum({m}_net) {m}_net" for m in _Q66_MONTHS])
    web = _q66_channel("web_sales", "ws_ext_sales_price", "ws_quantity",
                       "ws_sold_date_sk", "ws_sold_time_sk",
                       "ws_ship_mode_sk", "ws_warehouse_sk")
    cat = _q66_channel("catalog_sales", "cs_ext_sales_price",
                       "cs_quantity", "cs_sold_date_sk",
                       "cs_sold_time_sk", "cs_ship_mode_sk",
                       "cs_warehouse_sk")
    return f"""
SELECT w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
       w_country, ship_carriers, yr,
       {sums}
FROM ({web}
UNION ALL
{cat}) x
GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
         w_country, ship_carriers, yr
ORDER BY w_warehouse_name
"""


TPCDS_QUERIES["q66"] = _q66_text()


def _rollup_oracle(select_cols, aggs, from_where, keys, order_by):
    """Build the sqlite oracle for a ROLLUP query (sqlite has no
    ROLLUP): UNION ALL of one grouped SELECT per prefix, dropped keys
    projected as NULL."""
    parts = []
    for k in range(len(keys), -1, -1):
        kept = keys[:k]
        sel = []
        for c in select_cols:
            sel.append(c if c in kept else f"NULL AS {c}")
        gb = f" GROUP BY {', '.join(kept)}" if kept else ""
        parts.append(f"SELECT {', '.join(sel)}, {aggs} {from_where}{gb}")
    return "\nUNION ALL\n".join(parts) + (f"\n{order_by}" if order_by else "")


# sqlite-dialect oracle variants where the engine text cannot run on
# sqlite verbatim: ROLLUP (unsupported there) becomes explicit UNION
# ALL; decimal/decimal division (cents/cents would integer-divide in
# sqlite) gets CAST(... AS REAL).
_Q18_FROM = """
FROM catalog_sales, customer_demographics cd1,
     customer_demographics cd2, customer, customer_address,
     date_dim, item
WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd1.cd_demo_sk
  AND cs_bill_customer_sk = c_customer_sk
  AND cd1.cd_gender = 'F' AND cd1.cd_education_status = 'Unknown'
  AND c_current_cdemo_sk = cd2.cd_demo_sk
  AND c_current_addr_sk = ca_address_sk
  AND c_birth_month IN (1, 6, 8, 9, 12, 2)
  AND d_year = 1998
  AND ca_state IN ('TX', 'NY', 'OH', 'IL', 'WA', 'GA', 'TN')
"""

_Q22_FROM = """
FROM inventory, date_dim, item
WHERE inv_date_sk = d_date_sk AND inv_item_sk = i_item_sk
  AND d_month_seq BETWEEN 1200 AND 1211
"""

_Q27_FROM = """
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk AND ss_cdemo_sk = cd_demo_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND d_year = 2002 AND s_state IN ('TN', 'CA')
"""


def _q27_oracle():
    parts = []
    for k in range(2, -1, -1):
        kept = ["i_item_id", "s_state"][:k]
        sel = []
        for c in ["i_item_id", "s_state"]:
            sel.append(c if c in kept else f"NULL AS {c}")
        g_state = 0 if "s_state" in kept else 1
        gb = f" GROUP BY {', '.join(kept)}" if kept else ""
        parts.append(
            f"SELECT {', '.join(sel)}, {g_state} g_state, "
            "avg(ss_quantity) agg1, avg(ss_list_price) agg2, "
            "avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4 "
            + _Q27_FROM + gb)
    return "\nUNION ALL\n".join(parts)


def _yoy_oracle(text: str) -> str:
    """q4/q11/q74 oracle: cast the ratio numerators to REAL (and q4's
    /2 to /2.0) so sqlite's cents/cents division matches the engine's
    real division."""
    import re as _re
    out = _re.sub(r"THEN (t_\w+)\.year_total /",
                  r"THEN CAST(\1.year_total AS REAL) /", text)
    return out.replace(" / 2)", " / 2.0)")


def _cents_avg_window_oracle(name: str) -> str:
    """q53/q63/q89 oracle: the engine's window avg over decimal cents
    rounds half-away to cents (Presto decimal avg); sqlite's avg is
    real. Round the oracle's windowed avg so the deviation-threshold
    row inclusion matches exactly."""
    import re as _re
    return _re.sub(
        r"avg\(sum\((ss|cs)_sales_price\)\) OVER \(PARTITION BY[^)]*\)",
        lambda m: f"round({m.group(0)})", TPCDS_QUERIES[name])



_Q86_BASE = """
  SELECT sum(ws_net_paid) total_sum, i_category, i_class,
         0 lochierarchy
  FROM web_sales, date_dim d1, item
  WHERE d1.d_month_seq BETWEEN 1200 AND 1211
    AND d1.d_date_sk = ws_sold_date_sk AND i_item_sk = ws_item_sk
  GROUP BY i_category, i_class
  UNION ALL
  SELECT sum(ws_net_paid), i_category, NULL, 1
  FROM web_sales, date_dim d1, item
  WHERE d1.d_month_seq BETWEEN 1200 AND 1211
    AND d1.d_date_sk = ws_sold_date_sk AND i_item_sk = ws_item_sk
  GROUP BY i_category
  UNION ALL
  SELECT sum(ws_net_paid), NULL, NULL, 2
  FROM web_sales, date_dim d1, item
  WHERE d1.d_month_seq BETWEEN 1200 AND 1211
    AND d1.d_date_sk = ws_sold_date_sk AND i_item_sk = ws_item_sk
"""

_Q86_ORACLE = ("SELECT total_sum, i_category, i_class, lochierarchy, "
               "rank() OVER (PARTITION BY lochierarchy, "
               "CASE WHEN lochierarchy = 0 THEN i_category END "
               "ORDER BY total_sum DESC) rank_within_parent "
               "FROM (" + _Q86_BASE + ") base")


_Q36_BASE = """
  SELECT CAST(sum(ss_net_profit) AS REAL) / sum(ss_ext_sales_price)
           gross_margin,
         i_category, i_class, 0 lochierarchy
  FROM store_sales, date_dim d1, item, store
  WHERE d1.d_year = 2001 AND d1.d_date_sk = ss_sold_date_sk
    AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
    AND s_state IN ('TN', 'CA')
  GROUP BY i_category, i_class
  UNION ALL
  SELECT CAST(sum(ss_net_profit) AS REAL) / sum(ss_ext_sales_price),
         i_category, NULL, 1
  FROM store_sales, date_dim d1, item, store
  WHERE d1.d_year = 2001 AND d1.d_date_sk = ss_sold_date_sk
    AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
    AND s_state IN ('TN', 'CA')
  GROUP BY i_category
  UNION ALL
  SELECT CAST(sum(ss_net_profit) AS REAL) / sum(ss_ext_sales_price),
         NULL, NULL, 2
  FROM store_sales, date_dim d1, item, store
  WHERE d1.d_year = 2001 AND d1.d_date_sk = ss_sold_date_sk
    AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
    AND s_state IN ('TN', 'CA')
"""

_Q36_ORACLE = ("SELECT gross_margin, i_category, i_class, lochierarchy, "
               "rank() OVER (PARTITION BY lochierarchy, "
               "CASE WHEN lochierarchy = 0 THEN i_category END "
               "ORDER BY gross_margin ASC) rank_within_parent "
               "FROM (" + _Q36_BASE + ") base")


def _q47_oracle(name: str) -> str:
    return _cents_avg_window_oracle(name).replace(
        "THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales",
        "THEN abs(sum_sales - avg_monthly_sales) / "
        "CAST(avg_monthly_sales AS REAL)")


_Q67_KEYS = ["i_category", "i_class", "i_brand", "i_product_name",
             "d_year", "d_qoy", "d_moy", "s_store_id"]
_Q67_FROM = """
FROM store_sales, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk AND d_month_seq BETWEEN 1200 AND 1211
"""
_Q67_ORACLE = ("""
SELECT i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
       d_moy, s_store_id, sumsales, rk
FROM (SELECT i_category, i_class, i_brand, i_product_name, d_year,
             d_qoy, d_moy, s_store_id, sumsales,
             rank() OVER (PARTITION BY i_category
                          ORDER BY sumsales DESC) rk
      FROM (""" + _rollup_oracle(
    _Q67_KEYS,
    "sum(coalesce(ss_sales_price * ss_quantity, 0.00)) sumsales",
    _Q67_FROM, _Q67_KEYS, "") + """) dw1) dw2
WHERE rk <= 100
""")

_Q70_ORACLE = """
WITH base AS (
  SELECT s_state, s_county, ss_net_profit
  FROM store_sales, date_dim d1, store
  WHERE d1.d_month_seq BETWEEN 1200 AND 1211
    AND d1.d_date_sk = ss_sold_date_sk AND s_store_sk = ss_store_sk
    AND s_state IN (SELECT s_state
                    FROM (SELECT s_state s_state,
                                 rank() OVER (PARTITION BY s_state
                                   ORDER BY sum(ss_net_profit) DESC) ranking
                          FROM store_sales, store, date_dim
                          WHERE d_month_seq BETWEEN 1200 AND 1211
                            AND d_date_sk = ss_sold_date_sk
                            AND s_store_sk = ss_store_sk
                          GROUP BY s_state) tmp1
                    WHERE ranking <= 5)),
rolled AS (
  SELECT sum(ss_net_profit) total_sum, s_state, s_county, 0 lochierarchy
  FROM base GROUP BY s_state, s_county
  UNION ALL
  SELECT sum(ss_net_profit), s_state, NULL, 1 FROM base GROUP BY s_state
  UNION ALL
  SELECT sum(ss_net_profit), NULL, NULL, 2 FROM base)
SELECT total_sum, s_state, s_county, lochierarchy,
       rank() OVER (PARTITION BY lochierarchy,
                    CASE WHEN lochierarchy = 0 THEN s_state END
                    ORDER BY total_sum DESC) rank_within_parent
FROM rolled
"""

_Q44_ORACLE = TPCDS_QUERIES["q44"].replace(
    "avg(ss_net_profit) rank_col",
    "avg(CAST(ss_net_profit AS REAL)) rank_col")



def _sqlite_stddev(col: str) -> str:
    """stddev_samp emulation for sqlite (no stddev builtin)."""
    n = f"CAST(count({col}) AS REAL)"
    return (f"CASE WHEN count({col}) > 1 THEN "
            f"sqrt(max(0.0, (sum(1.0*{col}*{col}) - "
            f"sum(1.0*{col})*sum(1.0*{col})/{n}) / (count({col}) - 1))) "
            f"ELSE NULL END")


def _q17_oracle() -> str:
    text = TPCDS_QUERIES["q17"]
    for c in ("ss_quantity", "sr_return_quantity", "cs_quantity"):
        text = text.replace(f"stddev_samp({c})", _sqlite_stddev(c))
    return text

def _q39_oracle() -> str:
    text = TPCDS_QUERIES["q39"].replace(
        "stddev_samp(inv_quantity_on_hand)",
        _sqlite_stddev("inv_quantity_on_hand")).replace(
        "avg(inv_quantity_on_hand) mean",
        "avg(1.0*inv_quantity_on_hand) mean")
    return text


def _rollup_stack_oracle(name: str, keys) -> str:
    """Derive a sqlite ROLLUP oracle from the REGISTERED query text:
    the GROUP BY ROLLUP (keys...) tail becomes len(keys)+1 stacked
    UNION ALL levels (dropped keys projected as typed NULLs), so
    oracle and engine provably run the same CTEs."""
    text = TPCDS_QUERIES[name]
    key_str = ", ".join(keys)
    head = text.rindex("\nSELECT " + keys[0] + ",")
    tail = text.index("GROUP BY ROLLUP", head)
    prefix, selbase = text[:head], text[head:tail]
    assert key_str in selbase, name
    parts = []
    for k in range(len(keys), -1, -1):
        kept = list(keys[:k])
        sel = ", ".join(kept + [f"NULL {c}" for c in keys[k:]])
        gb = f"GROUP BY {', '.join(kept)}" if kept else ""
        parts.append(selbase.replace(key_str, sel, 1) + gb)
    return prefix + "\nUNION ALL".join(parts)


def _channel_rollup_oracle(name: str) -> str:
    return _rollup_stack_oracle(name, ["channel", "id"])


TPCDS_ORACLE = {
    "q17": _q17_oracle(),
    # engine money math is in dollars; sqlite sees raw cents. Presto's
    # CAST(double AS integer) ROUNDS; sqlite CAST truncates.
    "q54": TPCDS_QUERIES["q54"].replace(
        "CAST(revenue / 50 AS integer)",
        "CAST(round(revenue / 100.0 / 50.0) AS integer)"),
    "q58": TPCDS_QUERIES["q58"].replace(
        "ws_item_rev) / 3.0 average",
        "ws_item_rev) / 3.0 / 100.0 average"),
    "q5": _channel_rollup_oracle("q5"),
    "q14": _rollup_stack_oracle(
        "q14", ["channel", "i_brand_id", "i_class_id", "i_category_id"]),
    "q77": _channel_rollup_oracle("q77"),
    "q80": _channel_rollup_oracle("q80"),
    "q39": _q39_oracle(),
    "q66": TPCDS_QUERIES["q66"].replace(
        "AS double) / w_warehouse_sq_ft",
        "AS double) / 100.0 / w_warehouse_sq_ft"),
    "q67": _Q67_ORACLE,
    "q70": _Q70_ORACLE,
    "q44": _Q44_ORACLE,
    "q47": _q47_oracle("q47"),
    "q57": _q47_oracle("q57"),
    "q36": _Q36_ORACLE,
    "q86": _Q86_ORACLE,
    "q53": _cents_avg_window_oracle("q53"),
    "q63": _cents_avg_window_oracle("q63"),
    "q89": _cents_avg_window_oracle("q89"),
    "q18": _rollup_oracle(
        ["i_item_id", "ca_country", "ca_state", "ca_county"],
        "avg(cs_quantity) agg1, avg(cs_list_price) agg2, "
        "avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4, "
        "avg(cs_net_profit) agg5, avg(c_birth_year) agg6, "
        "avg(cd1.cd_dep_count) agg7",
        _Q18_FROM, ["i_item_id", "ca_country", "ca_state", "ca_county"],
        ""),
    "q22": _rollup_oracle(
        ["i_product_name", "i_brand", "i_class", "i_category"],
        "avg(inv_quantity_on_hand) qoh",
        _Q22_FROM, ["i_product_name", "i_brand", "i_class", "i_category"],
        ""),
    "q27": _q27_oracle(),
    "q11": _yoy_oracle(TPCDS_QUERIES["q11"]),
    "q74": _yoy_oracle(TPCDS_QUERIES["q74"]),
    "q4": _yoy_oracle(TPCDS_QUERIES["q4"]),
}
