"""Device-resident columnar data model: the Page/Block analog.

Reference surface: presto-common/.../common/Page.java:107,163 and
presto-common/.../common/block/ (73 files: LongArrayBlock, IntArrayBlock,
VariableWidthBlock, DictionaryBlock, RunLengthEncodedBlock, LazyBlock...).

TPU-first redesign (NOT a translation of the JVM layout):

* A `Column` is a flat value array plus a boolean null mask, resident in
  HBM. Fixed-width SQL types map 1:1 to a dtype'd vector (the
  LongArrayBlock/IntArrayBlock/... family collapses into one class
  parameterized by dtype).
* Strings (`StringColumn`) are a fixed-width padded `(N, L) uint8` matrix
  plus a length vector -- vectorizable on the 8x128 VPU, unlike the
  reference's offsets+bytes heap (VariableWidthBlock). Wide or
  low-cardinality string columns should be wrapped in `DictionaryColumn`.
* `DictionaryColumn` (DictionaryBlock analog) is (indices:int32,
  dictionary:Block). RunLengthEncodedBlock is a DictionaryColumn with a
  1-row dictionary.
* A `Batch` is the Page analog: a tuple of equal-length columns plus an
  `active` row mask. XLA requires static shapes, so every Batch has a
  fixed `capacity`; rows beyond the real row count -- and rows dropped by
  filters -- are simply inactive in the mask. This replaces the
  reference's SelectedPositions selection vectors
  (operator/project/PageProcessor.java:112, SelectedPositions.java:21)
  with a form the VPU can consume without gathers.

All classes are JAX pytrees: they flow through jit/shard_map/scan, and
sharding annotations apply leaf-wise.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import types as T

__all__ = ["Column", "StringColumn", "DictionaryColumn", "Int128Column",
           "Batch", "Block", "from_numpy", "to_numpy", "concat_batches"]


def _register(cls, data_fields, meta_fields):
    jax.tree_util.register_dataclass(cls, data_fields=list(data_fields),
                                     meta_fields=list(meta_fields))
    return cls


@dataclasses.dataclass
class Column:
    """Fixed-width column: `values` (N,) dtype'd array, `nulls` (N,) bool
    (True = SQL NULL). Value slots under a null are unspecified but must be
    finite/in-domain so padded lanes never poison reductions."""

    values: jax.Array
    nulls: jax.Array
    type: T.Type = dataclasses.field(metadata=dict(static=True))

    def __len__(self):
        return self.values.shape[0]

    @property
    def capacity(self) -> int:
        return self.values.shape[0]


_register(Column, ["values", "nulls"], ["type"])


@dataclasses.dataclass
class StringColumn:
    """Padded string column: `chars` (N, L) uint8, `lengths` (N,) int32,
    `nulls` (N,) bool. chars[i, k] for k >= lengths[i] must be 0 so
    equality can compare full rows without masking."""

    chars: jax.Array
    lengths: jax.Array
    nulls: jax.Array
    type: T.Type = dataclasses.field(metadata=dict(static=True))

    def __len__(self):
        return self.chars.shape[0]

    @property
    def capacity(self) -> int:
        return self.chars.shape[0]

    @property
    def max_len(self) -> int:
        return self.chars.shape[1]


_register(StringColumn, ["chars", "lengths", "nulls"], ["type"])


@dataclasses.dataclass
class DictionaryColumn:
    """Dictionary-encoded column (DictionaryBlock analog): row i's value is
    dictionary[indices[i]]. `nulls` is the top-level null mask (a null row
    may point at any dictionary slot)."""

    indices: jax.Array
    dictionary: Union[Column, StringColumn]
    nulls: jax.Array
    type: T.Type = dataclasses.field(metadata=dict(static=True))

    def __len__(self):
        return self.indices.shape[0]

    @property
    def capacity(self) -> int:
        return self.indices.shape[0]

    def decode(self) -> Union[Column, StringColumn]:
        """Materialize the flat column (gather through the dictionary)."""
        d = self.dictionary
        if isinstance(d, StringColumn):
            return StringColumn(d.chars[self.indices], d.lengths[self.indices],
                                self.nulls, self.type)
        return Column(d.values[self.indices], self.nulls, self.type)


_register(DictionaryColumn, ["indices", "dictionary", "nulls"], ["type"])


@dataclasses.dataclass
class ArrayColumn:
    """Fixed-fanout array column (ArrayBlock analog, TPU layout): row i's
    array is elements[i, :lengths[i]]. The reference stores arrays as
    offsets into a flat child block (pointer-shaped); a (N, K) matrix
    keeps element access vectorizable -- K is the per-batch max
    cardinality (shape bucketing, like string widths). Fixed-width
    element types in round 1."""

    elements: jax.Array    # (N, K) element values
    elem_nulls: jax.Array  # (N, K)
    lengths: jax.Array     # (N,)
    nulls: jax.Array       # (N,) top-level null array
    type: T.Type = dataclasses.field(metadata=dict(static=True))

    def __len__(self):
        return self.elements.shape[0]

    @property
    def capacity(self) -> int:
        return self.elements.shape[0]

    @property
    def max_cardinality(self) -> int:
        return self.elements.shape[1]


_register(ArrayColumn, ["elements", "elem_nulls", "lengths", "nulls"], ["type"])


@dataclasses.dataclass
class Int128Column:
    """Long-decimal lanes (Int128ArrayBlock / Decimals.java analog):
    value = hi * 2^64 + lo in two's complement, stored SoA (two flat
    64-bit lanes) so every op stays a plain VPU elementwise op -- see
    int128.py for the arithmetic."""

    hi: jax.Array   # int64
    lo: jax.Array   # uint64
    nulls: jax.Array
    type: T.Type = dataclasses.field(metadata=dict(static=True))

    def __len__(self):
        return self.hi.shape[0]

    @property
    def capacity(self) -> int:
        return self.hi.shape[0]


_register(Int128Column, ["hi", "lo", "nulls"], ["type"])


@dataclasses.dataclass
class MapColumn:
    """Fixed-fanout map column (MapBlock analog, TPU layout): row i's
    entries are (keys[i, j], values[i, j]) for j < lengths[i]. Keys are
    non-null by SQL contract; fixed-width key/value types in this
    revision (string keys ride dictionary-encoded ints upstream)."""

    keys: jax.Array        # (N, K) key lanes
    values: jax.Array      # (N, K) value lanes
    value_nulls: jax.Array  # (N, K)
    lengths: jax.Array     # (N,)
    nulls: jax.Array       # (N,) top-level null map
    type: T.Type = dataclasses.field(metadata=dict(static=True))

    def __len__(self):
        return self.keys.shape[0]

    @property
    def capacity(self) -> int:
        return self.keys.shape[0]

    @property
    def max_cardinality(self) -> int:
        return self.keys.shape[1]


_register(MapColumn, ["keys", "values", "value_nulls", "lengths", "nulls"],
          ["type"])


@dataclasses.dataclass
class RowColumn:
    """Struct column (RowBlock analog): one child Block per field plus a
    top-level null mask -- already SoA, the natural TPU layout (the
    reference's RowBlock is the same design)."""

    fields: Tuple["Block", ...]
    nulls: jax.Array
    type: T.Type = dataclasses.field(metadata=dict(static=True))

    def __len__(self):
        return self.nulls.shape[0]

    @property
    def capacity(self) -> int:
        return self.nulls.shape[0]

    def field(self, i: int) -> "Block":
        return self.fields[i]


_register(RowColumn, ["fields", "nulls"], ["type"])

Block = Union[Column, StringColumn, DictionaryColumn, ArrayColumn,
              Int128Column, MapColumn, RowColumn]


@dataclasses.dataclass
class Batch:
    """The Page analog: equal-capacity columns + an active-row mask.

    `active[i]` False means row i is padding or was filtered out. All
    kernels must honor the mask; `count()` is the live row count.
    """

    columns: Tuple[Block, ...]
    active: jax.Array

    def __len__(self):
        return self.capacity

    @property
    def capacity(self) -> int:
        return self.active.shape[0]

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def count(self) -> jax.Array:
        return jnp.sum(self.active.astype(jnp.int32))

    def column(self, i: int) -> Block:
        return self.columns[i]

    def with_columns(self, columns: Sequence[Block]) -> "Batch":
        return Batch(tuple(columns), self.active)

    def with_active(self, active: jax.Array) -> "Batch":
        return Batch(self.columns, active)


_register(Batch, ["columns", "active"], [])


# --------------------------------------------------------------------------
# Host <-> device staging
# --------------------------------------------------------------------------

def _pad(arr: np.ndarray, capacity: int, fill=0) -> np.ndarray:
    n = arr.shape[0]
    if n == capacity:
        return arr
    pad_width = [(0, capacity - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width, constant_values=fill)


def _pad_cast(arr: np.ndarray, capacity: int, dt, fill=0) -> np.ndarray:
    """Fused cast+pad: allocate the (capacity, ...) staging buffer at
    the target dtype once and slice-assign into it, instead of the
    cast-then-pad chain that materializes two host copies of the same
    column (M003 copy amplification)."""
    dt = np.dtype(dt)
    n = arr.shape[0]
    if n == capacity and arr.dtype == dt:
        return arr
    out = np.full((capacity,) + arr.shape[1:], fill, dtype=dt)
    out[:n] = arr
    return out


def from_numpy(ty: T.Type, values: np.ndarray, nulls: Optional[np.ndarray] = None,
               capacity: Optional[int] = None,
               physical_dtype=None) -> Block:
    """Stage a host column to a device Block. For string types `values`
    must be an object/str numpy array or a (N, L) uint8 matrix; for
    array types, an object array of Python lists (None elements = null,
    None rows = null array).

    `physical_dtype` (narrow-width execution, plan/widths.py) overrides
    the staged lane dtype for fixed-width columns whose value range the
    planner proved fits a narrower lane -- host->device transfer and
    HBM residency shrink accordingly; the logical `ty` is unchanged and
    compute sites widen before arithmetic."""
    if ty.base == "array":
        ety = ty.element_type
        rows = list(values)
        n = len(rows)
        capacity = capacity or n
        k = max((len(r) for r in rows if r is not None), default=1) or 1
        elems = np.zeros((n, k), dtype=ety.to_dtype())
        enulls = np.ones((n, k), dtype=bool)
        lengths = np.zeros(n, dtype=np.int32)
        topn = np.zeros(n, dtype=bool) if nulls is None else \
            np.asarray(nulls, dtype=bool).copy()
        for i, r in enumerate(rows):
            if r is None or topn[i]:
                topn[i] = True
                continue
            lengths[i] = len(r)
            for j, v in enumerate(r):
                if v is None:
                    continue
                elems[i, j] = v
                enulls[i, j] = False
        return ArrayColumn(jnp.asarray(_pad(elems, capacity)),
                           jnp.asarray(_pad(enulls, capacity, fill=True)),
                           jnp.asarray(_pad(lengths, capacity)),
                           jnp.asarray(_pad(topn, capacity, fill=True)), ty)
    if ty.base == "map":
        # object array of python dicts (None = null map)
        kty, vty = ty.key_type, ty.value_type
        rows = list(values)
        n = len(rows)
        capacity = capacity or n
        k = max((len(r) for r in rows if r is not None), default=1) or 1
        keys = np.zeros((n, k), dtype=kty.to_dtype())
        vals = np.zeros((n, k), dtype=vty.to_dtype())
        vnulls = np.ones((n, k), dtype=bool)
        lengths = np.zeros(n, dtype=np.int32)
        topn = np.zeros(n, dtype=bool) if nulls is None else \
            np.asarray(nulls, dtype=bool).copy()
        for i, r in enumerate(rows):
            if r is None or topn[i]:
                topn[i] = True
                continue
            lengths[i] = len(r)
            for j, (kk, vv) in enumerate(r.items()):
                keys[i, j] = kk
                if vv is not None:
                    vals[i, j] = vv
                    vnulls[i, j] = False
        return MapColumn(jnp.asarray(_pad(keys, capacity)),
                         jnp.asarray(_pad(vals, capacity)),
                         jnp.asarray(_pad(vnulls, capacity, fill=True)),
                         jnp.asarray(_pad(lengths, capacity)),
                         jnp.asarray(_pad(topn, capacity, fill=True)), ty)
    if ty.base == "row":
        # object array of python tuples/lists (None = null row)
        ftys = ty.field_types
        rows = list(values)
        n = len(rows)
        capacity = capacity or n
        topn = np.zeros(n, dtype=bool) if nulls is None else \
            np.asarray(nulls, dtype=bool).copy()
        fields = []
        for fi, fty in enumerate(ftys):
            col = np.empty(n, dtype=object)
            for i, r in enumerate(rows):
                col[i] = None if (r is None or topn[i]) else r[fi]
            if not (fty.is_string or fty.base in ("array", "map", "row")
                    or (fty.is_decimal and not fty.is_short_decimal)):
                fn = np.array([v is None for v in col], dtype=bool)
                col = np.array([0 if v is None else v for v in col],
                               dtype=fty.to_dtype())
                fields.append(from_numpy(fty, col, fn, capacity))
            else:
                fields.append(from_numpy(fty, col, None, capacity))
        for i, r in enumerate(rows):
            if r is None:
                topn[i] = True
        return RowColumn(tuple(fields),
                         jnp.asarray(_pad(topn, capacity, fill=True)), ty)
    n = values.shape[0]
    capacity = capacity or n
    if nulls is None:
        if values.dtype == object:
            nulls = np.array([v is None for v in values], dtype=bool)
        else:
            nulls = np.zeros(n, dtype=bool)
    nulls = _pad_cast(nulls, capacity, bool, fill=True)
    if ty.is_string and values.dtype != np.uint8:
        encoded = [str(v).encode("utf-8") if v is not None else b"" for v in values]
        max_len = max((len(b) for b in encoded), default=1) or 1
        chars = np.zeros((n, max_len), dtype=np.uint8)
        lengths = np.zeros(n, dtype=np.int32)
        for i, b in enumerate(encoded):
            chars[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
            lengths[i] = len(b)
        return StringColumn(jnp.asarray(_pad(chars, capacity)),
                            jnp.asarray(_pad(lengths, capacity)),
                            jnp.asarray(nulls), ty)
    if ty.is_string:
        # length = position after the last nonzero byte (strings may
        # contain interior NULs; trailing zeros are padding by invariant)
        nonzero = values != 0
        any_nz = nonzero.any(axis=1)
        lengths = np.where(any_nz,
                           values.shape[1] - np.argmax(nonzero[:, ::-1], axis=1),
                           0).astype(np.int32)
        return StringColumn(jnp.asarray(_pad(values, capacity)),
                            jnp.asarray(_pad(lengths, capacity)),
                            jnp.asarray(nulls), ty)
    if ty.is_decimal and not ty.is_short_decimal:
        # long decimals stage as 128-bit lane pairs (Int128Column); host
        # values arrive as Python ints (exact) or any int64-safe array
        from .int128 import python_to_int128
        if values.dtype == object:
            hi, lo = python_to_int128(list(values))
        else:
            v = np.asarray(values, dtype=np.int64)
            hi, lo = (v >> 63).astype(np.int64), v.astype(np.uint64)
        return Int128Column(jnp.asarray(_pad(hi, capacity)),
                            jnp.asarray(_pad(lo, capacity)),
                            jnp.asarray(nulls), ty)
    dt = np.dtype(physical_dtype) if physical_dtype is not None \
        else ty.to_dtype()
    values = _pad_cast(values, capacity, dt)
    return Column(jnp.asarray(values), jnp.asarray(nulls), ty)


def batch_from_numpy(types: Sequence[T.Type], arrays: Sequence[np.ndarray],
                     nulls: Optional[Sequence[Optional[np.ndarray]]] = None,
                     capacity: Optional[int] = None,
                     physical_dtypes=None) -> Batch:
    n = arrays[0].shape[0]
    capacity = capacity or n
    nulls = nulls or [None] * len(arrays)
    physical_dtypes = physical_dtypes or [None] * len(arrays)
    cols = tuple(from_numpy(t, a, m, capacity, physical_dtype=p)
                 for t, a, m, p in zip(types, arrays, nulls,
                                       physical_dtypes))
    active = np.zeros(capacity, dtype=bool)
    active[:n] = True
    return Batch(cols, jnp.asarray(active))


def to_numpy(block: Block) -> Tuple[np.ndarray, np.ndarray]:
    """Fetch (values, nulls) to host. Strings come back as an object
    array; arrays as an object array of Python lists."""
    if isinstance(block, DictionaryColumn):
        return to_numpy(block.decode())
    if isinstance(block, ArrayColumn):
        elems = np.asarray(block.elements)
        enulls = np.asarray(block.elem_nulls)
        lengths = np.asarray(block.lengths)
        nulls = np.asarray(block.nulls)
        out = np.empty(len(lengths), dtype=object)
        for i in range(len(lengths)):
            out[i] = None if nulls[i] else [
                None if enulls[i, j] else elems[i, j].item()
                for j in range(lengths[i])]
        return out, nulls
    if isinstance(block, StringColumn):
        chars = np.asarray(block.chars)
        lengths = np.asarray(block.lengths)
        vals = np.array([chars[i, : lengths[i]].tobytes().decode("utf-8", "replace")
                         for i in range(chars.shape[0])], dtype=object)
        return vals, np.asarray(block.nulls)
    if isinstance(block, Int128Column):
        from .int128 import int128_to_python
        vals = int128_to_python(np.asarray(block.hi), np.asarray(block.lo))
        return vals, np.asarray(block.nulls)
    if isinstance(block, MapColumn):
        keys = np.asarray(block.keys)
        vals = np.asarray(block.values)
        vnulls = np.asarray(block.value_nulls)
        lengths = np.asarray(block.lengths)
        nulls = np.asarray(block.nulls)
        out = np.empty(len(lengths), dtype=object)
        for i in range(len(lengths)):
            out[i] = None if nulls[i] else {
                keys[i, j].item(): (None if vnulls[i, j]
                                    else vals[i, j].item())
                for j in range(lengths[i])}
        return out, nulls
    if isinstance(block, RowColumn):
        nulls = np.asarray(block.nulls)
        fvals = [to_numpy(f) for f in block.fields]
        out = np.empty(len(nulls), dtype=object)
        for i in range(len(nulls)):
            out[i] = None if nulls[i] else tuple(
                None if fn[i] else (fv[i].item()
                                    if isinstance(fv[i], np.generic)
                                    else fv[i])
                for fv, fn in fvals)
        return out, nulls
    return np.asarray(block.values), np.asarray(block.nulls)


def gather_block(b: Block, idx: jax.Array, valid: Optional[jax.Array] = None
                 ) -> Block:
    """Row gather for every Block kind (the one shared implementation
    behind join/aggregation/unnest/sort row movement). `valid=None`
    means a pure permutation (nulls ride along); with a mask, invalid
    output rows become NULL/empty."""
    if isinstance(b, DictionaryColumn):
        if valid is None:
            return DictionaryColumn(b.indices[idx], b.dictionary,
                                    b.nulls[idx], b.type)
        b = b.decode()
    if isinstance(b, StringColumn):
        lengths = b.lengths[idx]
        nulls = b.nulls[idx]
        if valid is not None:
            lengths = jnp.where(valid, lengths, 0)
            nulls = jnp.where(valid, nulls, True)
        return StringColumn(b.chars[idx], lengths, nulls, b.type)
    if isinstance(b, ArrayColumn):
        lengths = b.lengths[idx]
        nulls = b.nulls[idx]
        if valid is not None:
            lengths = jnp.where(valid, lengths, 0)
            nulls = jnp.where(valid, nulls, True)
        return ArrayColumn(b.elements[idx], b.elem_nulls[idx], lengths,
                           nulls, b.type)
    if isinstance(b, MapColumn):
        lengths = b.lengths[idx]
        nulls = b.nulls[idx]
        if valid is not None:
            lengths = jnp.where(valid, lengths, 0)
            nulls = jnp.where(valid, nulls, True)
        return MapColumn(b.keys[idx], b.values[idx], b.value_nulls[idx],
                         lengths, nulls, b.type)
    if isinstance(b, RowColumn):
        nulls = b.nulls[idx]
        if valid is not None:
            nulls = jnp.where(valid, nulls, True)
        return RowColumn(tuple(gather_block(f, idx, valid)
                               for f in b.fields), nulls, b.type)
    if isinstance(b, Int128Column):
        nulls = b.nulls[idx]
        if valid is not None:
            nulls = jnp.where(valid, nulls, True)
        return Int128Column(b.hi[idx], b.lo[idx], nulls, b.type)
    nulls = b.nulls[idx]
    if valid is not None:
        nulls = jnp.where(valid, nulls, True)
    return Column(b.values[idx], nulls, b.type)


def null_like(b: Block) -> Block:
    """An all-NULL block with the same capacity/type/layout as `b`
    (GroupIdNode's dropped-key columns; the reference materializes the
    same via null Blocks in GroupIdOperator)."""
    n = len(b)
    ones = jnp.ones(n, dtype=bool)
    if isinstance(b, DictionaryColumn):
        b = b.decode()
    if isinstance(b, StringColumn):
        return StringColumn(b.chars, jnp.zeros(n, dtype=jnp.int32), ones,
                            b.type)
    if isinstance(b, ArrayColumn):
        return ArrayColumn(b.elements, b.elem_nulls,
                           jnp.zeros(n, dtype=jnp.int32), ones, b.type)
    if isinstance(b, Int128Column):
        return Int128Column(b.hi, b.lo, ones, b.type)
    return Column(b.values, ones, b.type)


def concat_batches(batches: Sequence[Batch]) -> Batch:
    """Concatenate batches (device-side). Capacities add."""
    cols = []
    for ci in range(batches[0].num_columns):
        blocks = [b.columns[ci] for b in batches]
        blocks = [b.decode() if isinstance(b, DictionaryColumn) else b for b in blocks]
        b0 = blocks[0]
        if isinstance(b0, StringColumn):
            max_l = max(b.max_len for b in blocks)
            chars = jnp.concatenate([
                jnp.pad(b.chars, ((0, 0), (0, max_l - b.max_len))) for b in blocks])
            cols.append(StringColumn(chars,
                                     jnp.concatenate([b.lengths for b in blocks]),
                                     jnp.concatenate([b.nulls for b in blocks]),
                                     b0.type))
        elif isinstance(b0, Int128Column):
            cols.append(Int128Column(
                jnp.concatenate([b.hi for b in blocks]),
                jnp.concatenate([b.lo for b in blocks]),
                jnp.concatenate([b.nulls for b in blocks]), b0.type))
        elif isinstance(b0, ArrayColumn):
            max_k = max(b.elements.shape[1] for b in blocks)
            cols.append(ArrayColumn(
                jnp.concatenate([
                    jnp.pad(b.elements,
                            ((0, 0), (0, max_k - b.elements.shape[1])))
                    for b in blocks]),
                jnp.concatenate([
                    jnp.pad(b.elem_nulls,
                            ((0, 0), (0, max_k - b.elements.shape[1])))
                    for b in blocks]),
                jnp.concatenate([b.lengths for b in blocks]),
                jnp.concatenate([b.nulls for b in blocks]), b0.type))
        elif isinstance(b0, MapColumn):
            max_k = max(b.keys.shape[1] for b in blocks)

            def cat2(field):
                return jnp.concatenate([
                    jnp.pad(getattr(b, field),
                            ((0, 0), (0, max_k - b.keys.shape[1])))
                    for b in blocks])
            cols.append(MapColumn(
                cat2("keys"), cat2("values"), cat2("value_nulls"),
                jnp.concatenate([b.lengths for b in blocks]),
                jnp.concatenate([b.nulls for b in blocks]), b0.type))
        elif isinstance(b0, RowColumn):
            fields = tuple(
                concat_batches([Batch((b.fields[fi],),
                                      jnp.ones(len(b), dtype=bool))
                                for b in blocks]).columns[0]
                for fi in range(len(b0.fields)))
            cols.append(RowColumn(
                fields, jnp.concatenate([b.nulls for b in blocks]),
                b0.type))
        else:
            cols.append(Column(jnp.concatenate([b.values for b in blocks]),
                               jnp.concatenate([b.nulls for b in blocks]), b0.type))
    active = jnp.concatenate([b.active for b in batches])
    return Batch(tuple(cols), active)
