"""Time-zone keys and TIMESTAMP WITH TIME ZONE packing.

Reference surface: presto-common/.../common/type/TimeZoneKey.java and
TimestampWithTimeZoneType.java -- Presto packs (millis << 12) | zoneKey
into one long. This engine packs (MICROS << 12) | zone_key (timestamps
are micros here); 12 bits of key leave |micros| < 2^51 us ~ year 2041+
of range, same envelope as the reference's packing.

Zone keys (subset of the reference's zone-index table):
  2048          UTC (and its aliases)
  2048 + m      fixed offset of +m minutes  (m in -2047..+2047 covers
                every real offset, which lie within +-18h)
Named region zones resolve through a small alias table to their
STANDARD fixed offset (no DST database on an accelerator; the reference
links full tzdata -- documented engine difference)."""

from __future__ import annotations

import re

import jax.numpy as jnp

UTC_KEY = 2048
MICROS_PER_MINUTE = 60_000_000

# named zones -> standard offset minutes (tiny alias table; fixed-offset
# spellings are parsed structurally below)
_NAMED = {
    "utc": 0, "z": 0, "gmt": 0, "greenwich": 0, "universal": 0,
    "america/new_york": -5 * 60, "america/chicago": -6 * 60,
    "america/denver": -7 * 60, "america/los_angeles": -8 * 60,
    "europe/london": 0, "europe/paris": 60, "europe/berlin": 60,
    "europe/moscow": 3 * 60, "asia/kolkata": 5 * 60 + 30,
    "asia/shanghai": 8 * 60, "asia/tokyo": 9 * 60,
    "australia/sydney": 10 * 60, "pacific/auckland": 12 * 60,
}

_OFFSET = re.compile(r"^(?:utc|gmt)?([+-])(\d{1,2})(?::?(\d{2}))?$")


def zone_key(name: str) -> int:
    """Zone spelling -> key. Raises ValueError on unknown zones."""
    s = name.strip().lower()
    m = _OFFSET.match(s)
    if m:
        sign = -1 if m.group(1) == "-" else 1
        minutes = sign * (int(m.group(2)) * 60 + int(m.group(3) or 0))
        if not -2047 <= minutes <= 2047:
            raise ValueError(f"zone offset out of range: {name!r}")
        return UTC_KEY + minutes
    if s in _NAMED:
        return UTC_KEY + _NAMED[s]
    raise ValueError(f"unknown time zone: {name!r}")


def zone_name(key: int) -> str:
    minutes = key - UTC_KEY
    if minutes == 0:
        return "UTC"
    sign = "+" if minutes >= 0 else "-"
    m = abs(minutes)
    return f"{sign}{m // 60:02d}:{m % 60:02d}"


def pack(utc_micros, key):
    """(instant, zone) -> packed int64 lane."""
    return (jnp.asarray(utc_micros, dtype=jnp.int64) << 12) | jnp.int64(key)


def unpack_micros(packed):
    """Packed lane -> UTC micros (arithmetic shift keeps pre-epoch
    instants correct)."""
    return jnp.asarray(packed, dtype=jnp.int64) >> 12


def unpack_key(packed):
    return (jnp.asarray(packed, dtype=jnp.int64) & jnp.int64(0xFFF)
            ).astype(jnp.int32)


def local_micros(packed):
    """Wall-clock micros in the value's own zone (what EXTRACT,
    date_format and date_trunc operate on)."""
    p = jnp.asarray(packed, dtype=jnp.int64)
    offset = ((p & jnp.int64(0xFFF)) - UTC_KEY) * MICROS_PER_MINUTE
    return (p >> 12) + offset
