"""PEP-249-style DBAPI: the presto-jdbc / presto-client analog for
Python programs.

Reference surface: presto-jdbc (PrestoDriver/PrestoConnection/
PrestoStatement over the REST client protocol) and presto-client's
StatementClientV1. Local mode executes in-process; server mode will ride
the worker/coordinator HTTP protocol once the client protocol endpoint
lands (ROADMAP).

    import presto_tpu.dbapi as db
    conn = db.connect(sf=0.1)
    cur = conn.cursor()
    cur.execute("SELECT custkey, count(*) FROM orders GROUP BY custkey")
    print(cur.fetchmany(5))
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

apilevel = "2.0"
threadsafety = 1
paramstyle = "qmark"

__all__ = ["connect", "Connection", "Cursor", "Error", "ProgrammingError"]


class Error(Exception):
    pass


class ProgrammingError(Error):
    pass


def connect(sf: float = 0.01, mesh=None, max_groups: int = 1 << 16,
            server: Optional[str] = None, user: str = "presto",
            **kwargs):
    """Local mode embeds the engine; `server="http://host:port"` speaks
    the client statement protocol to a coordinator (PrestoDriver's
    jdbc:presto://host URL analog)."""
    if server is not None:
        session = dict(kwargs.pop("session", None) or {})
        session.setdefault("sf", str(sf))
        return HttpConnection(server, user=user, session=session, **kwargs)
    return Connection(sf=sf, mesh=mesh, max_groups=max_groups, **kwargs)


class Connection:
    def __init__(self, sf: float, mesh=None, max_groups: int = 1 << 16,
                 read_only: bool = True, **kwargs):
        from .transaction import TransactionManager
        self.sf = sf
        self.mesh = mesh
        self.max_groups = max_groups
        self.read_only = read_only  # implicit-transaction mode; pass
        # read_only=False once the table-writer path lands
        self.kwargs = kwargs
        self._closed = False
        # PEP-249 implicit transaction: begun lazily on first execute,
        # ended by commit()/rollback() (TransactionManager analog)
        self._txn_manager = TransactionManager()
        self._txn_id = None

    def cursor(self) -> "Cursor":
        if self._closed:
            raise ProgrammingError("connection is closed")
        return Cursor(self)

    def close(self):
        if self._txn_id is not None:
            self._txn_manager.rollback(self._txn_id)
            self._txn_id = None
        self._closed = True

    def _current_txn(self) -> str:
        if self._txn_id is None:
            self._txn_id = self._txn_manager.begin(
                read_only=self.read_only)
        return self._txn_id

    def _end_txn(self, end) -> None:
        if self._closed:
            raise ProgrammingError("connection is closed")
        if self._txn_id is not None:
            end(self._txn_id)
            self._txn_id = None

    def commit(self):
        self._end_txn(self._txn_manager.commit)

    def rollback(self):
        self._end_txn(self._txn_manager.rollback)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Cursor:
    arraysize = 1

    def __init__(self, conn: Connection):
        self.conn = conn
        self._rows: Optional[List[tuple]] = None
        self._pos = 0
        self.description = None
        self.rowcount = -1

    def execute(self, sql_text: str, parameters: Sequence[Any] = ()):
        if self.conn._closed:
            raise ProgrammingError("connection is closed")
        self.conn._current_txn()  # PEP-249 implicit transaction
        if parameters:
            sql_text = _bind(sql_text, parameters)
        from .sql import sql as run_sql
        try:
            res = run_sql(sql_text, sf=self.conn.sf, mesh=self.conn.mesh,
                          max_groups=self.conn.max_groups, **self.conn.kwargs)
        except Error:
            raise
        except Exception as e:  # noqa: BLE001 - DBAPI error contract
            raise ProgrammingError(str(e)) from e
        self._rows = res.rows()
        self._pos = 0
        self.rowcount = res.row_count
        self.description = [
            (res.names[i], str(res.types[i]) if res.types else None,
             None, None, None, None, None)
            for i in range(len(res.names))]
        return self

    def executemany(self, sql_text: str, seq_of_params):
        for p in seq_of_params:
            self.execute(sql_text, p)
        return self

    def fetchone(self) -> Optional[tuple]:
        self._check()
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None) -> List[tuple]:
        self._check()
        size = size or self.arraysize
        out = self._rows[self._pos:self._pos + size]
        self._pos += len(out)
        return out

    def fetchall(self) -> List[tuple]:
        self._check()
        out = self._rows[self._pos:]
        self._pos = len(self._rows)
        return out

    def close(self):
        self._rows = None

    def _check(self):
        if self._rows is None:
            raise ProgrammingError("no result set; call execute() first")

    def __iter__(self):
        self._check()
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row


def _bind(sql_text: str, parameters: Sequence[Any]) -> str:
    """qmark substitution that ignores '?' inside string literals."""
    out = []
    pi = 0
    in_str = False
    i = 0
    while i < len(sql_text):
        ch = sql_text[i]
        if in_str:
            out.append(ch)
            if ch == "'":
                if i + 1 < len(sql_text) and sql_text[i + 1] == "'":
                    out.append("'")
                    i += 1  # escaped quote stays inside the literal
                else:
                    in_str = False
        elif ch == "'":
            in_str = True
            out.append(ch)
        elif ch == "?":
            if pi >= len(parameters):
                raise ProgrammingError(
                    f"more placeholders than parameters ({len(parameters)})")
            out.append(_quote(parameters[pi]))
            pi += 1
        else:
            out.append(ch)
        i += 1
    if pi != len(parameters):
        raise ProgrammingError(
            f"{pi} placeholders but {len(parameters)} parameters")
    return "".join(out)


def _quote(v: Any) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, (int, float)):
        return repr(v)
    s = str(v).replace("'", "''")
    return f"'{s}'"


# ---------------------------------------------------------------------------
# HTTP mode: the statement protocol (StatementClientV1 / presto-jdbc wire)
# ---------------------------------------------------------------------------


import datetime as _datetime
import decimal as _decimal


def _parse_wire_value(v, type_sig: str):
    """Wire JSON -> python value (reference client conventions: decimals
    as Decimal, dates/timestamps as datetime objects)."""
    if v is None:
        return None
    datetime, decimal = _datetime, _decimal
    base = type_sig.split("(", 1)[0].strip()
    if base == "decimal":
        return decimal.Decimal(v)
    if base == "date":
        return datetime.date.fromisoformat(v)
    if base == "timestamp":
        return datetime.datetime.fromisoformat(v)
    if base == "array":
        inner = type_sig.split("(", 1)[1].rsplit(")", 1)[0]
        return [_parse_wire_value(e, inner) for e in v]
    return v


class HttpConnection:
    """PEP-249 connection over the client statement protocol."""

    def __init__(self, server: str, user: str = "presto",
                 session: Optional[dict] = None, **kwargs):
        self.server = server.rstrip("/")
        self.user = user
        self.session = dict(session or {})
        self._txn_id: Optional[str] = None
        self._closed = False

    def cursor(self) -> "HttpCursor":
        if self._closed:
            raise ProgrammingError("connection is closed")
        return HttpCursor(self)

    def _run(self, text: str):
        from .client import QueryError, execute
        try:
            client = execute(self.server, text, user=self.user,
                             session=self.session,
                             transaction_id=self._txn_id)
        except QueryError as e:
            raise ProgrammingError(str(e)) from e
        # apply server-directed session/transaction mutations
        self.session.update(client.set_session)
        if client.started_transaction_id:
            self._txn_id = client.started_transaction_id
        if client.clear_transaction:
            self._txn_id = None
        return client

    def _ensure_txn(self):
        if self._txn_id is None:
            self._run("START TRANSACTION")

    def commit(self):
        if self._closed:
            raise ProgrammingError("connection is closed")
        if self._txn_id is not None:
            self._run("COMMIT")

    def rollback(self):
        if self._closed:
            raise ProgrammingError("connection is closed")
        if self._txn_id is not None:
            self._run("ROLLBACK")

    def close(self):
        if self._txn_id is not None:
            try:
                self._run("ROLLBACK")
            except Exception:  # noqa: BLE001 - close is best-effort
                pass
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class HttpCursor(Cursor):
    """Cursor whose execute() rides the wire protocol."""

    def __init__(self, conn: HttpConnection):
        self.conn = conn
        self._rows = None
        self._pos = 0
        self.description = None
        self.rowcount = -1

    def execute(self, sql_text: str, parameters: Sequence[Any] = ()):
        if self.conn._closed:
            raise ProgrammingError("connection is closed")
        if parameters:
            sql_text = _bind(sql_text, parameters)
        self.conn._ensure_txn()
        client = self.conn._run(sql_text)
        cols = client.columns or []
        self.description = [(c["name"], c["type"], None, None, None,
                             None, None) for c in cols]
        types = [c["type"] for c in cols]
        self._rows = [tuple(_parse_wire_value(v, types[i])
                            for i, v in enumerate(row))
                      for row in client.data]
        self._pos = 0
        self.rowcount = len(self._rows)
        return self
