"""failpoints: deterministic fault injection at named sites.

Reference surface: the failpoint discipline production query engines
grow before they can trust their own recovery code -- FreeBSD's
fail(9), TiKV's fail-rs, etcd's gofail: named sites compiled into the
hot paths, zero-cost until armed, driven by an expression grammar so a
test (or a chaos driver against a live cluster) can make *exactly* the
k-th page pull fail, bit-identically, run after run. The engine's
resilience machinery -- task resubmission, retry-URL reselection,
stale-socket HTTP retries, heartbeat exclusion, flight dumps -- is
only trustworthy insofar as every one of those paths is reachable on
demand; this package makes them reachable.

The site idiom (the ONLY code a hot path pays when disarmed is one
module-attribute truth test)::

    from .. import failpoints
    if failpoints.ARMED:
        failpoints.hit("exchange.fetch")

``hit(site, payload=None)`` evaluates the site's armed action/trigger
and either returns ``payload`` untouched (no fault), returns a
corrupted copy (``corrupt_page``), sleeps (``delay``/``hang``), or
raises (``error``/``oom``/``drop_conn``). Every FIRED fault is counted
per (site, action) -- exported as
``presto_tpu_failpoint_hits_total{site,action}`` on both tiers'
``/v1/metrics`` -- and logged as a flight-recorder ``failpoint`` event
cross-linked to the ambient trace context.

Actions:    ``error(ExcName)`` | ``delay(ms)`` | ``hang(ms)`` |
            ``corrupt_page`` | ``oom`` | ``drop_conn``
Triggers:   ``always`` | ``once`` | ``every(n)`` | ``after(n)`` |
            ``prob(p[,seed])``
Spec:       ``action[:trigger]`` (trigger defaults to ``always``)
Config:     ``site=spec,site=spec,...`` -- the grammar of the
            ``PRESTO_TPU_FAILPOINTS`` env var, the ``failpoints``
            session property, and ``POST /v1/failpoint``.

Determinism contract: ``prob`` draws from a ``random.Random`` seeded
by ``(seed, site)``, and every other trigger is a pure function of the
site's evaluation count -- so for a fixed schedule and a fixed number
of site evaluations, the fired-fault sequence replays bit-identically.
``hang(ms)`` is a BOUNDED stall (a watchdog can prove timeout handling
without wedging the process); an unbounded hang is spelled with a
large ms.

The registry is process-wide (one per process, both tiers), like the
flight recorder next door.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.locks import OrderedLock
from .sites import SITES, sites_by_layer

__all__ = ["ARMED", "hit", "arm", "disarm", "disarm_all", "configure",
           "active", "failpoint_totals", "armed_count", "session_scope",
           "parse_spec", "parse_config",
           "admin_get_doc", "admin_post", "admin_delete",
           "FailpointError", "InjectedConnDrop", "InjectedOOM",
           "FailpointSpecError", "SITES", "sites_by_layer"]

# The one module-level bool every site reads. True iff >= 1 site is
# armed; flipped only by the registry (under its lock), read lock-free
# on hot paths -- a stale read costs one extra no-op evaluate() at
# worst, never a missed *armed* fault for the thread that armed it.
ARMED: bool = False


class FailpointError(RuntimeError):
    """Default injected exception class (``error`` with no name)."""


class InjectedConnDrop(ConnectionResetError):
    """``drop_conn``: a ConnectionError subclass, so client-side retry
    machinery treats it exactly like a real peer reset; server-side
    handlers catch it and close the socket without a response."""


class InjectedOOM(MemoryError):
    """``oom``: sites translate this into their native out-of-memory
    surface (MemoryPool.reserve -> MemoryReservationError)."""


class FailpointSpecError(ValueError):
    """Unparseable action/trigger/config expression."""


# exception classes `error(Name)` may name: the engine's retry paths
# discriminate by type, so injection must be able to speak each one
_EXC_CLASSES = {
    "FailpointError": FailpointError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
    "IOError": OSError,
    "OSError": OSError,
    "ConnectionError": ConnectionError,
    "ConnectionResetError": ConnectionResetError,
    "TimeoutError": TimeoutError,
    "KeyError": KeyError,
    "MemoryError": MemoryError,
}

_ACTIONS = ("error", "delay", "hang", "corrupt_page", "oom", "drop_conn")
_TRIGGERS = ("always", "once", "every", "after", "prob")


class _Action:
    """Parsed action: kind + argument (exception class or millis)."""

    def __init__(self, kind: str, arg=None):
        self.kind = kind
        self.arg = arg

    def __repr__(self):
        if self.kind == "error":
            return f"error({self.arg.__name__})"
        if self.kind in ("delay", "hang"):
            return f"{self.kind}({int(self.arg)})"
        return self.kind


class _Trigger:
    """Parsed trigger + its deterministic decision function. State is
    the owning _Armed's evaluation counter (and, for ``prob``, a PRNG
    seeded by (seed, site)); should_fire is called under the registry
    lock, so the count/PRNG advance atomically per evaluation."""

    def __init__(self, kind: str, n: int = 0, p: float = 0.0,
                 seed: int = 0, site: str = ""):
        self.kind = kind
        self.n = n
        self.p = p
        self.seed = seed
        self._rng = random.Random(f"{seed}:{site}") \
            if kind == "prob" else None

    def should_fire(self, evals: int) -> bool:
        """`evals` is 1-based: the count INCLUDING this evaluation."""
        if self.kind == "always":
            return True
        if self.kind == "once":
            return evals == 1
        if self.kind == "every":
            return evals % max(self.n, 1) == 0
        if self.kind == "after":
            return evals > self.n
        return self._rng.random() < self.p  # prob

    def __repr__(self):
        if self.kind in ("every", "after"):
            return f"{self.kind}({self.n})"
        if self.kind == "prob":
            return f"prob({self.p},{self.seed})"
        return self.kind


def _parse_call(expr: str) -> Tuple[str, List[str]]:
    """``name`` or ``name(a,b)`` -> (name, [args])."""
    expr = expr.strip()
    if "(" not in expr:
        return expr, []
    if not expr.endswith(")"):
        raise FailpointSpecError(f"unbalanced parens in {expr!r}")
    name, _, inner = expr[:-1].partition("(")
    args = [a.strip() for a in inner.split(",")] if inner.strip() else []
    return name.strip(), args


def _parse_action(expr: str) -> _Action:
    name, args = _parse_call(expr)
    if name not in _ACTIONS:
        raise FailpointSpecError(
            f"unknown action {name!r} (one of {', '.join(_ACTIONS)})")
    if name == "error":
        exc_name = args[0] if args else "FailpointError"
        exc = _EXC_CLASSES.get(exc_name)
        if exc is None:
            raise FailpointSpecError(
                f"unknown exception class {exc_name!r} "
                f"(one of {', '.join(sorted(_EXC_CLASSES))})")
        return _Action("error", exc)
    if name in ("delay", "hang"):
        if len(args) != 1:
            raise FailpointSpecError(f"{name} takes exactly one arg (ms)")
        return _Action(name, float(args[0]))
    if args:
        raise FailpointSpecError(f"action {name} takes no arguments")
    return _Action(name)


def _parse_trigger(expr: str, site: str) -> _Trigger:
    name, args = _parse_call(expr)
    if name not in _TRIGGERS:
        raise FailpointSpecError(
            f"unknown trigger {name!r} (one of {', '.join(_TRIGGERS)})")
    if name in ("every", "after"):
        if len(args) != 1:
            raise FailpointSpecError(f"{name} takes exactly one arg (n)")
        return _Trigger(name, n=int(args[0]), site=site)
    if name == "prob":
        if len(args) not in (1, 2):
            raise FailpointSpecError("prob takes (p) or (p, seed)")
        p = float(args[0])
        if not 0.0 <= p <= 1.0:
            raise FailpointSpecError(f"prob p={p} outside [0, 1]")
        seed = int(args[1]) if len(args) == 2 else 0
        return _Trigger("prob", p=p, seed=seed, site=site)
    if args:
        raise FailpointSpecError(f"trigger {name} takes no arguments")
    return _Trigger(name, site=site)


def parse_spec(site: str, spec: str) -> Tuple[_Action, _Trigger]:
    """``action[:trigger]`` -> (_Action, _Trigger). The trigger PRNG is
    seeded per (seed, site), so identical specs on different sites draw
    independent -- but each individually reproducible -- sequences."""
    spec = spec.strip()
    if not spec:
        raise FailpointSpecError("empty failpoint spec")
    action_s, sep, trigger_s = spec.partition(":")
    action = _parse_action(action_s)
    trigger = _parse_trigger(trigger_s if sep else "always", site)
    return action, trigger


def parse_config(config: str) -> List[Tuple[str, str]]:
    """``site=action:trigger,site=...`` -> [(site, spec)]. Commas split
    entries only at paren depth zero (``prob(0.1,42)`` stays whole)."""
    entries: List[Tuple[str, str]] = []
    depth = 0
    cur: List[str] = []
    parts: List[str] = []
    for ch in config or "":
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(depth - 1, 0)
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    for part in parts:
        part = part.strip()
        if not part:
            continue
        site, sep, spec = part.partition("=")
        if not sep or not site.strip() or not spec.strip():
            raise FailpointSpecError(
                f"bad failpoint entry {part!r} (want site=action:trigger)")
        entries.append((site.strip(), spec.strip()))
    return entries


class _Armed:
    """One armed site: spec + live trigger state. Mutated only under
    the registry lock."""

    def __init__(self, site: str, spec: str, action: _Action,
                 trigger: _Trigger):
        self.site = site
        self.spec = spec
        self.action = action
        self.trigger = trigger
        self.evals = 0  # evaluations since armed
        self.fires = 0  # faults fired since armed
        # scoped-arm bookkeeping (apply_scoped/revert_scoped): the
        # entry this one displaced, and whether the scope that
        # installed THIS entry has exited (a dead entry must never be
        # resurrected by a later-exiting overlapping scope)
        self.prev: Optional["_Armed"] = None
        self.dead = False

    def doc(self) -> dict:
        return {"spec": self.spec, "action": repr(self.action),
                "trigger": repr(self.trigger),
                "evals": self.evals, "fires": self.fires}


class FailpointRegistry:
    """Process-wide armed-site table + lifetime fire counters.

    Lifetime counters survive disarm (the /v1/metrics contract: a
    counter never decreases); trigger state resets on re-arm."""

    # request handlers, task threads and engine threads all evaluate
    # concurrently; every write rides the one lock (tpulint C001)
    _GUARDED_BY = {"_lock": ("_armed", "_totals")}

    def __init__(self):
        self._armed: Dict[str, _Armed] = {}
        # lifetime (site, action-kind) -> fired count
        self._totals: Dict[Tuple[str, str], int] = {}
        self._lock = OrderedLock("failpoints.FailpointRegistry._lock")

    def arm(self, site: str, spec: str) -> None:
        action, trigger = parse_spec(site, spec)
        with self._lock:
            self._armed[site] = _Armed(site, spec, action, trigger)
            self._sync_locked()

    def disarm(self, site: str) -> bool:
        with self._lock:
            found = self._armed.pop(site, None) is not None
            self._sync_locked()
        return found

    def disarm_all(self) -> None:
        with self._lock:
            self._armed = {}
            self._sync_locked()

    def configure(self, config: str) -> List[str]:
        """Arm every entry of a config string; returns the armed site
        names. Parses the WHOLE string before arming anything, so a
        trailing typo cannot leave a half-applied schedule."""
        parsed = [(site, spec, *parse_spec(site, spec))
                  for site, spec in parse_config(config)]
        with self._lock:
            for site, spec, action, trigger in parsed:
                self._armed[site] = _Armed(site, spec, action, trigger)
            self._sync_locked()
        return [site for site, _spec, _a, _t in parsed]

    def _sync_locked(self) -> None:
        # only the PROCESS registry drives the module-level fast gate:
        # scratch instances (tests, tools) must not flip sites armed on
        # the singleton on or off
        global ARMED
        if globals().get("_REGISTRY") is self:
            ARMED = bool(self._armed)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {site: a.doc() for site, a in self._armed.items()}

    def apply_scoped(self, config: str) -> Dict[str, "_Armed"]:
        """Arm a config string, returning {site: the _Armed THIS scope
        installed} -- revert_scoped's undo log. Each installed entry
        chains to the one it displaced (`prev`), so scoping is per
        SITE, not a whole-table swap: two queries' disjoint schedules
        compose, and overlapping scopes on the SAME site unwind safely
        in either exit order (last-writer-wins only while both are
        live)."""
        parsed = [(site, spec, *parse_spec(site, spec))
                  for site, spec in parse_config(config)]
        with self._lock:
            saved: Dict[str, _Armed] = {}
            for site, spec, action, trigger in parsed:
                installed = _Armed(site, spec, action, trigger)
                # a site repeated WITHIN one config collapses: the
                # scope's own earlier entry must not be resurrected
                installed.prev = saved[site].prev if site in saved \
                    else self._armed.get(site)
                saved[site] = installed
                self._armed[site] = installed
            self._sync_locked()
        return saved

    def revert_scoped(self, saved: Dict[str, "_Armed"]) -> None:
        """Undo apply_scoped: for each site, mark this scope's entry
        dead; if it is still the live one, restore the nearest
        still-live ancestor (or pop). An entry someone ELSE armed
        meanwhile stands, and a dead entry is never resurrected by a
        later-exiting overlapping scope -- so no per-query schedule
        can outlive every scope that armed it."""
        with self._lock:
            for site, installed in saved.items():
                installed.dead = True
                if self._armed.get(site) is not installed:
                    continue  # re-armed by someone else: theirs stands
                prev = installed.prev
                while prev is not None and prev.dead:
                    prev = prev.prev
                if prev is None:
                    self._armed.pop(site, None)
                else:
                    self._armed[site] = prev
            self._sync_locked()

    def armed_table(self) -> Dict[str, "_Armed"]:
        with self._lock:
            return dict(self._armed)

    def totals(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self._totals)

    def armed_count(self) -> int:
        with self._lock:
            return len(self._armed)

    def evaluate(self, site: str) -> Optional[Tuple[_Action, int]]:
        """One site evaluation: advance trigger state; (action, seq)
        when the fault fires, else None. seq is the site's 1-based
        fired-fault ordinal since arming (the fault-sequence id chaos
        schedules replay)."""
        with self._lock:
            armed = self._armed.get(site)
            if armed is None:
                return None
            armed.evals += 1
            if not armed.trigger.should_fire(armed.evals):
                return None
            armed.fires += 1
            key = (site, armed.action.kind)
            self._totals[key] = self._totals.get(key, 0) + 1
            return armed.action, armed.fires


_REGISTRY = FailpointRegistry()


def _configure_from_env(registry: FailpointRegistry) -> List[str]:
    """Arm PRESTO_TPU_FAILPOINTS on `registry` (the import-time hook,
    split out so tests drive it without a fresh interpreter). Zero-cost
    when unset; ARMED stays False."""
    config = os.environ.get("PRESTO_TPU_FAILPOINTS")
    return registry.configure(config) if config else []


_configure_from_env(_REGISTRY)


def _corrupt(payload: bytes) -> bytes:
    """Deterministic corruption: XOR one mid-payload byte (past the
    21-byte SerializedPage header when the buffer has one, so headers
    parse and the CHECKSUM is what catches it -- the validation path
    under test)."""
    if not payload:
        return b"\xff"
    buf = bytearray(payload)
    idx = 21 + (len(buf) - 21) // 2 if len(buf) > 21 else len(buf) // 2
    buf[idx] ^= 0xFF
    return bytes(buf)


def _record_fire(site: str, action: _Action, seq: int) -> None:
    """Flight-recorder ``failpoint`` event, cross-linked to the active
    trace. Lazy imports: this package sits below server/, and the event
    only matters on the (armed, fired) path."""
    try:
        from ..server.flight_recorder import record_event
        from ..server.tracing import current_context
        ctx = current_context()
        record_event("failpoint", site=site, action=action.kind,
                     seq=seq,
                     trace=ctx.trace_id if ctx is not None else None)
    except Exception as e:  # noqa: BLE001 - the injected fault must
        # land even when telemetry is mid-bootstrap; count the gap
        try:
            from ..server.metrics import record_suppressed
            record_suppressed("failpoints", "record_fire", e)
        except Exception:  # tpulint: disable=S001 - interpreter
            # teardown: metrics module already unloaded
            pass


def hit(site: str, payload=None):
    """Evaluate `site`; perform the armed fault when its trigger fires.
    Returns `payload` (corrupted for ``corrupt_page``); raises for
    ``error``/``oom``/``drop_conn``; sleeps for ``delay``/``hang``.
    Call behind an ``if failpoints.ARMED:`` guard -- the guard, not
    this function, is the disarmed hot path."""
    fired = _REGISTRY.evaluate(site)
    if fired is None:
        return payload
    action, seq = fired
    _record_fire(site, action, seq)
    if action.kind == "error":
        raise action.arg(f"failpoint {site} (injected, fire #{seq})")
    if action.kind in ("delay", "hang"):
        time.sleep(float(action.arg) / 1000.0)
        return payload
    if action.kind == "corrupt_page":
        return _corrupt(payload) if isinstance(payload, (bytes, bytearray,
                                                         memoryview)) \
            else payload
    if action.kind == "oom":
        raise InjectedOOM(
            f"failpoint {site}: injected out-of-memory (fire #{seq})")
    # drop_conn
    raise InjectedConnDrop(
        f"failpoint {site}: injected connection drop (fire #{seq})")


# -- module-level registry facade ---------------------------------------

def arm(site: str, spec: str) -> None:
    _REGISTRY.arm(site, spec)


def disarm(site: str) -> bool:
    return _REGISTRY.disarm(site)


def disarm_all() -> None:
    _REGISTRY.disarm_all()


def configure(config: str) -> List[str]:
    return _REGISTRY.configure(config)


def active() -> Dict[str, dict]:
    """{site: {spec, action, trigger, evals, fires}} of armed sites."""
    return _REGISTRY.snapshot()


def failpoint_totals() -> Dict[Tuple[str, str], int]:
    """Lifetime fired-fault counts per (site, action kind) -- the
    /v1/metrics ``presto_tpu_failpoint_hits_total`` source."""
    return _REGISTRY.totals()


def armed_count() -> int:
    return _REGISTRY.armed_count()


class session_scope:
    """Context manager applying a ``failpoints`` session-property spec
    for one query's execution scope, reverting ON EXIT exactly the
    sites it configured (so a per-query schedule cannot leak into the
    next query, and CONCURRENT queries' scopes compose instead of
    clobbering each other -- only the same site armed by two live
    scopes is last-writer-wins). Falsy spec = no-op. Lifetime fire
    counters are never restored -- counters never decrease.

    The registry stays PROCESS-WIDE (the fail-rs/gofail model): the
    scope bounds a schedule's LIFETIME, not which query trips it -- a
    concurrent query passing an armed site while the scope is live
    evaluates it too. Drivers wanting strict isolation serialize their
    fault-injected queries (scripts/chaos.py runs one round at a
    time)."""

    def __init__(self, spec: Optional[str]):
        self.spec = spec or ""
        self._saved: Optional[Dict[str, _Armed]] = None

    def __enter__(self):
        if self.spec:
            self._saved = _REGISTRY.apply_scoped(self.spec)
        return self

    def __exit__(self, *exc):
        if self._saved is not None:
            _REGISTRY.revert_scoped(self._saved)
        return False


# -- admin API document builders (shared by both tiers' handlers) -------

def admin_get_doc() -> dict:
    """``GET /v1/failpoint``: armed table + lifetime totals + the
    committed site catalog."""
    return {
        "armed": active(),
        "hits": {f"{site}|{action}": n
                 for (site, action), n in sorted(failpoint_totals().items())},
        "sites": {name: {"layer": layer, "description": desc}
                  for name, (layer, desc) in sorted(SITES.items())},
    }


def admin_post(body: dict) -> Tuple[dict, int]:
    """``POST /v1/failpoint``: ``{"site": ..., "spec": ...}`` arms one
    site; ``{"config": "site=spec,..."}`` arms a whole schedule.
    Returns (response doc, HTTP status)."""
    try:
        if "config" in body:
            armed = configure(str(body["config"]))
        elif "site" in body and "spec" in body:
            arm(str(body["site"]), str(body["spec"]))
            armed = [str(body["site"])]
        else:
            return ({"error": "want {site, spec} or {config}"}, 400)
    except (FailpointSpecError, ValueError) as e:
        return ({"error": f"{type(e).__name__}: {e}"}, 400)
    return ({"armed": armed, "active": active()}, 200)


def admin_delete(site: Optional[str]) -> dict:
    """``DELETE /v1/failpoint[/{site}]``: disarm one site (or all)."""
    if site:
        return {"disarmed": [site] if disarm(site) else []}
    before = sorted(active())
    disarm_all()
    return {"disarmed": before}
