"""The failpoint site catalog: every named injection point in the engine.

One committed registry of ``<layer>.<verb>`` names so the admin API can
list what exists, the chaos harness (scripts/chaos.py) can generate
schedules over real sites, and DESIGN.md's naming convention has a
single source of truth. Adding a site = instrument the code path with
the two-line armed-check idiom (see the package docstring) AND add its
row here; a site that fires but is absent from this catalog still
works (the registry arms any name), it just won't be offered to
schedule generators or described by ``GET /v1/failpoint``.
"""

from __future__ import annotations

from typing import Dict, Tuple

__all__ = ["SITES", "sites_by_layer"]

# name -> (layer, description). Layers mirror the engine's seams; the
# chaos soak's coverage invariant counts DISTINCT LAYERS fired.
SITES: Dict[str, Tuple[str, str]] = {
    "exchange.fetch": (
        "exchange",
        "cross-worker page pull entry (http_exchange.fetch_remote_batch): "
        "a consumer task's view of a dead/slow upstream"),
    "exchange.serve": (
        "exchange",
        "worker result-buffer serve (GET /v1/task/.../results/...): "
        "drop_conn here exercises the client's stale-socket retry"),
    "serde.serialize": (
        "serde",
        "SerializedPage encode epilogue (serde/pages.serialize_page): "
        "corrupt_page flips payload bytes AFTER the checksum is stamped"),
    "serde.deserialize": (
        "serde",
        "SerializedPage decode entry (serde/pages.deserialize_page): "
        "corrupt_page feeds the checksum/bounds validation paths"),
    "task.submit": (
        "task",
        "coordinator task-submission hop (Coordinator._submit): "
        "errors exercise submission failover to the next worker"),
    "task.status": (
        "task",
        "coordinator task-status poll (Coordinator._await_or_retry): "
        "errors exercise abort + resubmit-elsewhere recovery"),
    "task.result": (
        "task",
        "coordinator final result pull (fetch_results): errors exercise "
        "the re-run-final-task recovery path"),
    "worker.run_task": (
        "task",
        "worker task execution entry (TaskManager._run_task, after the "
        "RUNNING transition): error = crash mid-task, hang/delay = "
        "wedged or slow worker"),
    "client.request": (
        "task",
        "WorkerClient HTTP request (one per hop): drop_conn exercises "
        "the stale-keep-alive retry with backoff"),
    "discovery.announce": (
        "discovery",
        "worker announcement PUT (Announcer.announce_once): a worker "
        "that cannot reach discovery"),
    "discovery.probe": (
        "discovery",
        "heartbeat probe (HeartbeatProber._probe): a probe failure "
        "feeds the decayed failure rate that gates scheduling"),
    "dispatcher.admit": (
        "dispatcher",
        "query admission entry (Dispatcher.submit, before the resource-"
        "group queue): delay = admission stall, error = failed dispatch"),
    "memory.reserve": (
        "memory",
        "HBM admission reservation (MemoryPool.reserve): the oom action "
        "surfaces as MemoryReservationError, the real refusal path"),
    "spill.write": (
        "spill",
        "spill run-file flush (exec/spill._HostRows._flush_run): a full "
        "or broken spill disk"),
    "spill.read": (
        "spill",
        "spill run-file re-read (exec/spill._HostRows.columns): a run "
        "file that vanished or rotted between write and read"),
    "statement.execute": (
        "statement",
        "statement-tier engine execution entry (StatementServer."
        "_run_engine): hang here pins the client's poll deadline"),
    "discovery.unannounce_lost": (
        "discovery",
        "graceful-goodbye DELETE (Announcer.stop unannounce): an error "
        "here loses the unannouncement, so the node lingers in "
        "discovery until its announcement ages out -- the silent-"
        "age-out path the elastic-fleet membership code must survive"),
    "worker.drain_stall": (
        "fleet",
        "graceful-drain migration step (TpuWorkerServer.begin_drain, "
        "after running tasks settle, before buffered pages migrate): "
        "delay/hang = a drain stuck behind a slow peer, error = a "
        "migration hop that dies mid-drain (pages stay local and are "
        "served until consumed -- drain degrades, never loses pages)"),
    "coordinator.heartbeat_lapse": (
        "fleet",
        "coordinator->resource-manager heartbeat send "
        "(ClusterStateSender.send_once): error = a lost heartbeat; "
        "enough consecutive losses age the primary out of the RM view "
        "and the standby's failover monitor takes over statement "
        "execution (server/resource_manager.StandbyCoordinator)"),
    "dispatcher.batch_collapse": (
        "dispatcher",
        "formed-batch dispatch gate (exec/batching.py, after the "
        "formation window seals, before the vmapped dispatch): an "
        "error action COLLAPSES the batch back to serial per-query "
        "dispatch mid-flight -- every member must still match its "
        "serial oracle, the fallback is counted "
        "presto_tpu_batch_collapses_total{reason=failpoint} and "
        "recorded as a batch_collapse flight event"),
    "fusion.demote": (
        "fusion",
        "pipeline-region fusion gate (exec/runner.py, before dispatch "
        "of a fused multi-op region): an error action forces the span "
        "to DEMOTE mid-query -- the query re-partitions and runs with "
        "materialized boundaries, and the demotion sticks for later "
        "submissions (exec/regions.FusionMemory)"),
    "donation.apply": (
        "fusion",
        "buffer-donation prepare step (exec/donation.prepare_donation, "
        "before any buffer is consumed): an error action collapses the "
        "region to the normal undonated dispatch -- results must still "
        "match the donation-off oracle, the fallback is counted "
        "presto_tpu_donation_fallbacks_total and recorded as a "
        "donation_fallback flight event"),
    "timeline.record": (
        "timeline",
        "execution-timeline interval append (exec/timeline."
        "record_interval, before the ledger fold): an error action "
        "degrades the query's ledger STICKY to counted totals -- "
        "intervals drop (counted in `dropped`), the query succeeds with "
        "matching rows, the degradation is counted in the process "
        "registry and recorded as a timeline_degraded flight event"),
}


def sites_by_layer() -> Dict[str, list]:
    """{layer: [site, ...]} over the committed catalog (schedule
    generators pick per-layer; deterministic order)."""
    out: Dict[str, list] = {}
    for name in sorted(SITES):
        out.setdefault(SITES[name][0], []).append(name)
    return out
