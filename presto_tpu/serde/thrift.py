"""Thrift binary protocol for the hot status structs.

Reference surface: the optional thrift transport for TaskStatus /
TaskInfo -- presto-main-base/.../server/thrift/ThriftTaskClient.java
and the native worker's generated main/thrift/presto_thrift.thrift
(JSON parse dominates status-poll cost at cluster scale; thrift
decodes in microseconds). This module implements the standard Thrift
Binary Protocol wire format (strict version header not required for
struct payloads) for a declared field schema, plus the TaskStatus
mapping used by the worker's `Accept: application/x-thrift` content
negotiation.

Scope: flat structs of BOOL/I32/I64/DOUBLE/STRING and LIST<STRING> --
exactly what TaskStatus needs. The vocabulary lives in _TASK_STATUS
below; unknown incoming fields are skipped field-by-field (standard
thrift forward compatibility).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple

__all__ = ["encode_struct", "decode_struct", "TASK_STATUS_SCHEMA",
           "encode_task_status", "decode_task_status"]

# thrift type ids (TBinaryProtocol)
T_STOP, T_BOOL, T_I32, T_I64 = 0, 2, 8, 10
T_DOUBLE, T_STRING, T_LIST = 4, 11, 15

# field schema: name -> (field_id, ttype)
TASK_STATUS_SCHEMA: Dict[str, Tuple[int, int]] = {
    "taskId": (1, T_STRING),
    "state": (2, T_STRING),
    "self": (3, T_STRING),
    "version": (4, T_I64),
    "memoryReservationInBytes": (5, T_I64),
    "outputBufferUtilization": (6, T_DOUBLE),
    "outputBufferOverutilized": (7, T_BOOL),
    "runningPartitionedDrivers": (8, T_I32),
    "queuedPartitionedDrivers": (9, T_I32),
    "failureMessages": (10, T_LIST),
    "taskAgeInMillis": (11, T_I64),
}


def _enc_value(ttype: int, v, out: List[bytes]) -> None:
    if ttype == T_BOOL:
        out.append(struct.pack("!b", 1 if v else 0))
    elif ttype == T_I32:
        out.append(struct.pack("!i", int(v)))
    elif ttype == T_I64:
        out.append(struct.pack("!q", int(v)))
    elif ttype == T_DOUBLE:
        out.append(struct.pack("!d", float(v)))
    elif ttype == T_STRING:
        b = str(v).encode("utf-8")
        out.append(struct.pack("!i", len(b)))
        out.append(b)
    elif ttype == T_LIST:  # list<string>
        items = list(v or [])
        out.append(struct.pack("!bi", T_STRING, len(items)))
        for it in items:
            _enc_value(T_STRING, it, out)
    else:
        raise ValueError(f"unsupported thrift type {ttype}")


def encode_struct(doc: dict, schema: Dict[str, Tuple[int, int]]) -> bytes:
    """dict -> TBinaryProtocol struct bytes (fields in id order;
    absent/None fields are omitted, thrift optional semantics)."""
    out: List[bytes] = []
    for name, (fid, ttype) in sorted(schema.items(), key=lambda kv: kv[1]):
        v = doc.get(name)
        if v is None:
            continue
        out.append(struct.pack("!bh", ttype, fid))
        _enc_value(ttype, v, out)
    out.append(struct.pack("!b", T_STOP))
    return b"".join(out)


def _dec_value(ttype: int, buf: memoryview, pos: int):
    if ttype == T_BOOL:
        return bool(buf[pos]), pos + 1
    if ttype == T_I32:
        return struct.unpack_from("!i", buf, pos)[0], pos + 4
    if ttype == T_I64:
        return struct.unpack_from("!q", buf, pos)[0], pos + 8
    if ttype == T_DOUBLE:
        return struct.unpack_from("!d", buf, pos)[0], pos + 8
    if ttype == T_STRING:
        n = struct.unpack_from("!i", buf, pos)[0]
        pos += 4
        return bytes(buf[pos:pos + n]).decode("utf-8"), pos + n
    if ttype == T_LIST:
        et, n = struct.unpack_from("!bi", buf, pos)
        pos += 5
        items = []
        for _ in range(n):
            v, pos = _dec_value(et, buf, pos)
            items.append(v)
        return items, pos
    raise ValueError(f"unsupported thrift type {ttype}")


def _skip(ttype: int, buf: memoryview, pos: int) -> int:
    """Advance past a value of ANY thrift wire type (the standard
    forward-compatibility skip, covering types this build never emits:
    struct=12, map=13, set=14, byte=3, i16=6)."""
    if ttype == T_BOOL or ttype == 3:
        return pos + 1
    if ttype == 6:
        return pos + 2
    if ttype == T_I32:
        return pos + 4
    if ttype in (T_I64, T_DOUBLE):
        return pos + 8
    if ttype == T_STRING:
        n = struct.unpack_from("!i", buf, pos)[0]
        return pos + 4 + n
    if ttype in (T_LIST, 14):  # list / set
        et, n = struct.unpack_from("!bi", buf, pos)
        pos += 5
        for _ in range(n):
            pos = _skip(et, buf, pos)
        return pos
    if ttype == 13:  # map
        kt, vt, n = struct.unpack_from("!bbi", buf, pos)
        pos += 6
        for _ in range(n):
            pos = _skip(kt, buf, pos)
            pos = _skip(vt, buf, pos)
        return pos
    if ttype == 12:  # struct
        while True:
            ft = struct.unpack_from("!b", buf, pos)[0]
            pos += 1
            if ft == T_STOP:
                return pos
            pos += 2  # field id
            pos = _skip(ft, buf, pos)
    raise ValueError(f"cannot skip thrift type {ttype}")


def decode_struct(data: bytes, schema: Dict[str, Tuple[int, int]]) -> dict:
    """TBinaryProtocol struct bytes -> dict; unknown field ids (and
    fields of types this build does not decode) skip by wire type, the
    standard thrift forward compatibility."""
    by_id = {fid: (name, ttype) for name, (fid, ttype) in schema.items()}
    buf = memoryview(data)
    pos = 0
    out: dict = {}
    while True:
        ttype = struct.unpack_from("!b", buf, pos)[0]
        pos += 1
        if ttype == T_STOP:
            break
        fid = struct.unpack_from("!h", buf, pos)[0]
        pos += 2
        hit = by_id.get(fid)
        if hit is not None and hit[1] == ttype:
            v, pos = _dec_value(ttype, buf, pos)
            out[hit[0]] = v
        else:
            pos = _skip(ttype, buf, pos)
    return out


def encode_task_status(doc: dict, task_id: str = "") -> bytes:
    """The worker's JSON TaskStatus document -> thrift bytes."""
    flat = dict(doc)
    flat.setdefault("taskId", task_id)
    flat["failureMessages"] = [f.get("message", "")
                               for f in doc.get("failures", [])]
    return encode_struct(flat, TASK_STATUS_SCHEMA)


def decode_task_status(data: bytes) -> dict:
    out = decode_struct(data, TASK_STATUS_SCHEMA)
    out["failures"] = [{"message": m, "type": "USER_ERROR"}
                      for m in out.pop("failureMessages", [])]
    return out
