"""SerializedPage wire format: the exchange/spool byte contract.

Reference surface: presto-spi/.../spi/page/PagesSerde.java,
SerializedPage.java:26, PagesSerdeUtil.java:64,79 and the public format
specification presto-docs/src/main/sphinx/develop/serialized-page.rst
(implemented here from that spec, not from the Java code):

  header: rows(i32) codec(u8: 1=compressed 2=encrypted 4=checksummed)
          uncompressed_size(i32) size(i32) checksum(u64-le)
  then:   column_count(i32), per column: name_len(i32) + encoding name
          + encoding-specific payload.

Checksum is CRC32 over [payload, codec, rows, uncompressed_size] per the
spec. Compression algorithm is out-of-band cluster config in the
reference (PagesSerdeFactory LZ4/GZIP/ZSTD); this build supports zstd
(degrading to zlib when the `zstandard` wheel is absent) and zlib; LZ4
arrives with the native serde kernels.

Encodings: BYTE/SHORT/INT/LONG/INT128_ARRAY, VARIABLE_WIDTH, DICTIONARY,
RLE. Nested ARRAY/MAP/ROW land with nested-type Block support.

Hot packing loops (non-null compaction, null bitpacking, varwidth
concat) dispatch to the C++ kernels in presto_tpu/native when built
(ctypes), else vectorized numpy.
"""

from __future__ import annotations

import dataclasses
import struct
import time
import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import failpoints
from .. import types as T
from ..block import Batch, Block, Column, DictionaryColumn, StringColumn, to_numpy
from ..native import kernels as nk

__all__ = ["PageCodec", "serialize_page", "deserialize_page",
           "serialize_batch", "deserialize_to_arrays"]

_COMPRESSED = 1
_ENCRYPTED = 2
_CHECKSUMMED = 4

_ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"


_zstd_mod = None  # unresolved; False once the import failed


def _zstd():
    """The `zstandard` module, or None when the wheel is absent (some
    images ship without it; PageCodec then degrades to zlib). The
    probe result is cached: Python does not cache FAILED imports, and
    this runs per page on the exchange hot path of a wheel-less node."""
    global _zstd_mod
    if _zstd_mod is None:
        try:
            import zstandard
            _zstd_mod = zstandard
        except ImportError:
            _zstd_mod = False
    return _zstd_mod or None

def _bounded_zlib(payload: bytes, uncompressed_size: int) -> bytes:
    """zlib.decompress with the declared-size output bound every codec
    branch enforces: a corrupt/crafted page that inflates past its page
    header's uncompressed_size is rejected, never allocated."""
    d = zlib.decompressobj()
    out = d.decompress(payload, uncompressed_size + 1)
    if len(out) > uncompressed_size:
        raise ValueError(
            "zlib page inflates past its declared uncompressed size "
            f"({uncompressed_size} bytes)")
    if not d.eof:
        # decompressobj returns partial output where zlib.decompress
        # raised; keep rejecting truncated/incomplete streams
        raise ValueError(
            "truncated zlib page: stream ended before its compressed "
            "data was complete")
    return out


_FIXED_ENC = {1: b"BYTE_ARRAY", 2: b"SHORT_ARRAY", 4: b"INT_ARRAY",
              8: b"LONG_ARRAY", 16: b"INT128_ARRAY"}
_ENC_WIDTH = {v: k for k, v in _FIXED_ENC.items()}


@dataclasses.dataclass
class PageCodec:
    compression: Optional[str] = None  # None | "zstd" | "zlib"
    checksum: bool = True

    def compress(self, payload: bytes) -> bytes:
        if self.compression == "zstd":
            z = _zstd()
            if z is None:
                # `zstandard` wheel absent on this image: degrade to the
                # stdlib codec rather than failing the exchange. Both
                # directions of a PageCodec degrade together (decompress
                # detects the zstd magic), so in-cluster pages stay
                # symmetric; only a true-zstd peer would notice.
                return zlib.compress(payload)
            return z.ZstdCompressor().compress(payload)
        if self.compression == "zlib":
            return zlib.compress(payload)
        if self.compression == "lz4":
            return nk.lz4_compress(payload)
        raise ValueError(self.compression)

    def decompress(self, payload: bytes, uncompressed_size: int) -> bytes:
        if self.compression == "zstd":
            # Sniff the frame magic on BOTH branches: in a mixed-image
            # cluster a peer without the wheel sends zlib-fallback pages
            # (0x78 first byte, never the zstd magic), and a zstd-capable
            # node must still read them.
            if payload[:4] != _ZSTD_MAGIC:
                # fallback-compressed; keep the bounded-output guarantee
                # the zstd branch gets from max_output_size, so a crafted
                # page cannot inflate past its declared size
                return _bounded_zlib(payload, uncompressed_size)
            z = _zstd()
            if z is None:
                raise RuntimeError(
                    "page is zstd-compressed but the `zstandard` "
                    "module is not installed on this node")
            return z.ZstdDecompressor().decompress(
                payload, max_output_size=uncompressed_size)
        if self.compression == "zlib":
            return _bounded_zlib(payload, uncompressed_size)
        if self.compression == "lz4":
            return nk.lz4_decompress(payload, uncompressed_size)
        raise ValueError(self.compression)


def _bitpack_nulls(nulls: np.ndarray) -> bytes:
    """has-nulls byte + big-endian-bit packed null flags (spec: first
    flag of each byte is the high bit). Accepts any 0/1 mask dtype;
    the one host conversion happens here, so callers must not
    pre-convert (M003 copy amplification)."""
    if not nulls.any():
        return b"\x00"
    return b"\x01" + np.packbits(np.asarray(nulls, dtype=np.uint8)).tobytes()


def _bitunpack_nulls(buf: memoryview, pos: int, rows: int
                     ) -> Tuple[np.ndarray, int]:
    has = buf[pos]
    pos += 1
    if not has:
        return np.zeros(rows, dtype=bool), pos
    nbytes = (rows + 7) // 8
    bits = np.unpackbits(np.frombuffer(buf[pos:pos + nbytes], dtype=np.uint8))
    return bits[:rows].astype(bool), pos + nbytes


def _item(v):
    return v.item() if isinstance(v, np.generic) else v


def _fixed_dtype(width: int, ty: Optional[T.Type]) -> np.dtype:
    if ty is not None:
        return ty.to_dtype()
    return {1: np.int8, 2: np.int16, 4: np.int32, 8: np.int64}[width]


def _serialize_fixed(values: np.ndarray, nulls: np.ndarray) -> bytes:
    width = values.dtype.itemsize
    if values.dtype == np.bool_:
        width = 1
        values = values.astype(np.int8)
    enc = _FIXED_ENC[width]
    out = [struct.pack("<i", len(enc)), enc,
           struct.pack("<i", values.shape[0]),
           _bitpack_nulls(nulls),
           nk.pack_nonnull(values, nulls)]
    return b"".join(out)


def _serialize_int128(vals: np.ndarray, nulls: np.ndarray) -> bytes:
    """Long decimals: INT128_ARRAY of (lo, hi) u64 pairs per non-null
    position. `vals` holds exact Python ints (object) or int64s."""
    rows = len(vals)
    enc = _FIXED_ENC[16]
    nn = [int(vals[i]) for i in range(rows) if not nulls[i]]
    pairs = np.zeros((len(nn), 2), dtype=np.uint64)
    for i, v in enumerate(nn):
        pairs[i, 0] = np.uint64(v & ((1 << 64) - 1))
        pairs[i, 1] = np.uint64((v >> 64) & ((1 << 64) - 1))
    return b"".join([struct.pack("<i", len(enc)), enc,
                     struct.pack("<i", rows),
                     _bitpack_nulls(nulls),
                     pairs.tobytes()])


def _serialize_varwidth(vals: np.ndarray, nulls: np.ndarray) -> bytes:
    """vals: object array of str/bytes."""
    rows = len(vals)
    encoded = [b"" if (nulls[i] or vals[i] is None)
               else (vals[i].encode("utf-8") if isinstance(vals[i], str)
                     else bytes(vals[i]))
               for i in range(rows)]
    lengths = np.array([len(b) for b in encoded], dtype=np.int64)
    offsets = np.cumsum(lengths).astype(np.int32)  # spec: end offsets per row
    blob = b"".join(encoded)
    enc = b"VARIABLE_WIDTH"
    return b"".join([
        struct.pack("<i", len(enc)), enc,
        struct.pack("<i", rows),
        offsets.tobytes(),
        _bitpack_nulls(nulls),
        struct.pack("<i", len(blob)),
        blob])


def _serialize_array(vals: np.ndarray, nulls: np.ndarray,
                     ty: T.Type) -> bytes:
    """ARRAY encoding (ArrayBlockEncoding.java): flattened child block,
    then positionCount, then N+1 cumulative offsets, then null bits.
    `vals` is an object array of per-row lists (None = null row)."""
    rows = len(vals)
    elem_ty = ty.element_type
    flat, offsets = [], [0]
    for i in range(rows):
        if nulls[i] or vals[i] is None:
            offsets.append(offsets[-1])
            continue
        flat.extend(vals[i])
        offsets.append(offsets[-1] + len(vals[i]))
    fnulls = np.array([e is None for e in flat], dtype=bool)
    if elem_ty.is_string:
        fvals = np.array(["" if e is None else e for e in flat],
                         dtype=object)
        child = _serialize_varwidth(fvals, fnulls)
    elif elem_ty.is_decimal and not elem_ty.is_short_decimal:
        fvals = np.array([0 if e is None else e for e in flat],
                         dtype=object)
        child = _serialize_int128(fvals, fnulls)
    else:
        fvals = np.array([0 if e is None else e for e in flat],
                         dtype=elem_ty.to_dtype())
        child = _serialize_fixed(fvals, fnulls)
    enc = b"ARRAY"
    return b"".join([struct.pack("<i", len(enc)), enc, child,
                     struct.pack("<i", rows),
                     np.asarray(offsets, dtype=np.int32).tobytes(),
                     _bitpack_nulls(nulls)])


def _serialize_child(vals, nulls, ty: T.Type) -> bytes:
    """Serialize a flattened child column by type (shared by the nested
    encodings)."""
    if ty.is_string:
        return _serialize_varwidth(np.asarray(vals, dtype=object),
                                   np.asarray(nulls, dtype=bool))
    if ty.is_decimal and not ty.is_short_decimal:
        return _serialize_int128(np.asarray(vals, dtype=object),
                                 np.asarray(nulls, dtype=bool))
    if ty.base == "array":
        return _serialize_array(np.asarray(vals, dtype=object),
                                np.asarray(nulls, dtype=bool), ty)
    if ty.base == "map":
        return _serialize_map(np.asarray(vals, dtype=object),
                              np.asarray(nulls, dtype=bool), ty)
    if ty.base == "row":
        return _serialize_row(np.asarray(vals, dtype=object),
                              np.asarray(nulls, dtype=bool), ty)
    return _serialize_fixed(np.asarray(vals, dtype=ty.to_dtype()),
                            np.asarray(nulls, dtype=bool))


def _serialize_map(vals: np.ndarray, nulls: np.ndarray,
                   ty: T.Type) -> bytes:
    """MAP encoding (MapBlockEncoding.java): key block, value block,
    hashtable length (-1 = absent), positionCount, N+1 offsets, null
    bits. `vals` = object array of dicts."""
    rows = len(vals)
    flat_k, flat_v, flat_vn, offsets = [], [], [], [0]
    for i in range(rows):
        if nulls[i] or vals[i] is None:
            offsets.append(offsets[-1])
            continue
        for k, v in vals[i].items():
            flat_k.append(k)
            flat_v.append(0 if v is None else v)
            flat_vn.append(v is None)
        offsets.append(offsets[-1] + len(vals[i]))
    enc = b"MAP"
    kn = np.zeros(len(flat_k), dtype=bool)
    return b"".join([
        struct.pack("<i", len(enc)), enc,
        _serialize_child(flat_k, kn, ty.key_type),
        _serialize_child(flat_v, np.asarray(flat_vn, dtype=bool),
                         ty.value_type),
        struct.pack("<i", -1),  # no precomputed hash table
        struct.pack("<i", rows),
        np.asarray(offsets, dtype=np.int32).tobytes(),
        _bitpack_nulls(nulls)])


def _serialize_row(vals: np.ndarray, nulls: np.ndarray,
                   ty: T.Type) -> bytes:
    """ROW encoding (RowBlockEncoding.java): numFields, field blocks
    (non-null rows only), positionCount, N+1 offsets, null bits.
    `vals` = object array of tuples."""
    rows = len(vals)
    ftys = ty.field_types
    present = [i for i in range(rows)
               if not (nulls[i] or vals[i] is None)]
    offsets = [0]
    for i in range(rows):
        offsets.append(offsets[-1]
                       + (0 if (nulls[i] or vals[i] is None) else 1))
    enc = b"ROW"
    parts = [struct.pack("<i", len(enc)), enc,
             struct.pack("<i", len(ftys))]
    for fi, fty in enumerate(ftys):
        fvals = [vals[i][fi] for i in present]
        fnulls = np.array([v is None for v in fvals], dtype=bool)
        fvals = [0 if v is None else v for v in fvals]
        parts.append(_serialize_child(fvals, fnulls, fty))
    parts.append(struct.pack("<i", rows))
    parts.append(np.asarray(offsets, dtype=np.int32).tobytes())
    parts.append(_bitpack_nulls(nulls))
    return b"".join(parts)


def _serialize_block(block: Block) -> bytes:
    if isinstance(block, DictionaryColumn):
        rows = len(block)
        inner = _serialize_block(block.dictionary)
        enc = b"DICTIONARY"
        idx = np.asarray(block.indices, dtype=np.int32)
        # 24-byte dictionary id (instance ids in the reference; zeros here)
        return b"".join([struct.pack("<i", len(enc)), enc,
                         struct.pack("<i", rows), inner, idx.tobytes(),
                         b"\x00" * 24])
    v, n = to_numpy(block)
    if isinstance(block, StringColumn):
        return _serialize_varwidth(v, n)
    from ..block import ArrayColumn, Int128Column, MapColumn, RowColumn
    if isinstance(block, Int128Column):
        return _serialize_int128(v, n)
    if isinstance(block, ArrayColumn):
        return _serialize_array(v, n, block.type)
    if isinstance(block, MapColumn):
        return _serialize_map(v, n, block.type)
    if isinstance(block, RowColumn):
        return _serialize_row(v, n, block.type)
    return _serialize_fixed(v, n)


def serialize_batch(batch: Batch, codec: PageCodec = PageCodec()) -> bytes:
    """Serialize the ACTIVE rows of a device Batch (compacts padding --
    the wire format is the dense world; masks are an on-device concept)."""
    act = np.asarray(batch.active)
    idx = np.nonzero(act)[0]
    cols = []
    for c in range(batch.num_columns):
        v, n = to_numpy(batch.column(c))
        ty = batch.column(c).type
        cols.append((ty, v[idx], n[idx]))
    return serialize_page(cols, codec)


def _observe_serde(op: str, seconds: float, nbytes: int = 0) -> None:
    """Page serde work feeds the shared /v1/metrics histogram registry
    (per-page serialize/deserialize latency on both tiers) AND the
    data-path waterfall (exec/datapath.py): serialization is the
    ``exchange_serialize`` hop, deserialization the ``decode`` hop,
    each carrying the page's wire bytes. Imports are deferred and
    shielded: serde loads before the server package during bootstrap,
    and attribution must never fail a page."""
    try:
        from ..server.metrics import observe_histogram
        observe_histogram("presto_tpu_page_serde_seconds", seconds,
                          labels={"op": op})
        from ..exec.datapath import record_hop
        record_hop("exchange_serialize" if op == "serialize"
                   else "decode", nbytes, seconds)
    except Exception:  # noqa: BLE001 - interpreter teardown / circular
        # bootstrap import: drop the observation, never the page
        pass


def serialize_page(columns: Sequence[Tuple[T.Type, np.ndarray, np.ndarray]],
                   codec: PageCodec = PageCodec()) -> bytes:
    t_page0 = time.time()
    rows = len(columns[0][1]) if columns else 0
    body = [struct.pack("<i", len(columns))]
    for ty, vals, nulls in columns:
        if ty.is_string:
            body.append(_serialize_varwidth(vals, nulls))
        elif ty.base == "array":
            body.append(_serialize_array(vals,
                                         np.asarray(nulls, dtype=bool), ty))
        elif ty.base == "map":
            body.append(_serialize_map(vals,
                                       np.asarray(nulls, dtype=bool), ty))
        elif ty.base == "row":
            body.append(_serialize_row(vals,
                                       np.asarray(nulls, dtype=bool), ty))
        elif ty.is_decimal and not ty.is_short_decimal:
            body.append(_serialize_int128(vals,
                                          np.asarray(nulls, dtype=bool)))
        else:
            body.append(_serialize_fixed(np.asarray(vals, dtype=ty.to_dtype()),
                                         np.asarray(nulls, dtype=bool)))
    payload = b"".join(body)
    uncompressed = len(payload)
    flags = 0
    if codec.compression:
        compressed = codec.compress(payload)
        if len(compressed) < uncompressed:
            payload = compressed
            flags |= _COMPRESSED
    checksum = 0
    if codec.checksum:
        flags |= _CHECKSUMMED
        checksum = _checksum(payload, flags, rows, uncompressed)
    header = struct.pack("<iBiiq", rows, flags, uncompressed, len(payload),
                         checksum)
    page = header + payload
    if failpoints.ARMED:
        # corrupt_page flips payload bytes AFTER the checksum stamp, so
        # the consumer's checksum validation is what catches it
        page = failpoints.hit("serde.serialize", page)
    _observe_serde("serialize", time.time() - t_page0, len(page))
    return page


def _checksum(payload: bytes, codec_flags: int, rows: int,
              uncompressed: int) -> int:
    crc = zlib.crc32(payload)
    crc = zlib.crc32(struct.pack("<B", codec_flags), crc)
    crc = zlib.crc32(struct.pack("<i", rows), crc)
    crc = zlib.crc32(struct.pack("<i", uncompressed), crc)
    return crc


def deserialize_page(buf: bytes, types: Sequence[T.Type],
                     codec: PageCodec = PageCodec()
                     ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """-> [(values, nulls)] per column. `types` guide dtype mapping
    (the wire encoding alone cannot distinguish e.g. BIGINT from DOUBLE)."""
    t_page0 = time.time()
    if failpoints.ARMED:
        buf = failpoints.hit("serde.deserialize", buf)
    rows, flags, uncompressed, size, checksum = struct.unpack_from("<iBiiq", buf)
    payload = bytes(memoryview(buf)[21:21 + size])
    if flags & _CHECKSUMMED:
        want = _checksum(payload, flags, rows, uncompressed)
        if want != checksum:
            raise ValueError(f"page checksum mismatch: {want} != {checksum}")
    if flags & _ENCRYPTED:
        raise NotImplementedError("encrypted pages")
    if flags & _COMPRESSED:
        payload = codec.decompress(payload, uncompressed)
    mv = memoryview(payload)
    (ncols,) = struct.unpack_from("<i", mv, 0)
    pos = 4
    out = []
    for ci in range(ncols):
        ty = types[ci] if ci < len(types) else None
        (vals, nulls), pos = _deserialize_block(mv, pos, ty)
        out.append((vals, nulls))
    # decode-hop bytes are the DECODED engine arrays (same unit the
    # parquet/ORC readers record): wire bytes may be zstd-compressed,
    # and mixing encoded and decoded bytes in one hop would make its
    # achieved B/s a meaningless blend
    _observe_serde("deserialize", time.time() - t_page0,
                   sum(v.nbytes + n.nbytes for v, n in out))
    return out


def _deserialize_block(mv: memoryview, pos: int, ty: Optional[T.Type]):
    (name_len,) = struct.unpack_from("<i", mv, pos)
    pos += 4
    enc = bytes(mv[pos:pos + name_len])
    pos += name_len
    if enc in _ENC_WIDTH:
        width = _ENC_WIDTH[enc]
        (rows,) = struct.unpack_from("<i", mv, pos)
        pos += 4
        nulls, pos = _bitunpack_nulls(mv, pos, rows)
        n_nonnull = rows - int(nulls.sum())
        dt = _fixed_dtype(width, ty)
        if width == 16:
            # INT128_ARRAY: (lo, hi) u64 pairs -> exact Python ints in
            # an object array (Int128Column lanes on the device side)
            pairs = np.frombuffer(mv[pos:pos + n_nonnull * 16],
                                  dtype=np.int64).reshape(-1, 2)
            lo, hi = pairs[:, 0].astype(np.uint64), pairs[:, 1]
            pos += n_nonnull * 16
            nn_vals = np.empty(n_nonnull, dtype=object)
            for i in range(n_nonnull):
                nn_vals[i] = int(hi[i]) * (1 << 64) + int(lo[i])
            vals = np.zeros(rows, dtype=object)
            vals[~nulls] = nn_vals
            return (vals, nulls), pos
        raw = np.frombuffer(mv[pos:pos + n_nonnull * width],
                            dtype=dt if dt.itemsize == width else
                            {1: np.int8, 2: np.int16, 4: np.int32,
                             8: np.int64}[width])
        pos += n_nonnull * width
        vals = nk.unpack_nonnull(raw, nulls)
        if dt == np.bool_:
            vals = vals.astype(bool)
        elif vals.dtype != dt and dt.itemsize == width:
            vals = vals.view(dt)
        return (vals, nulls), pos
    if enc == b"VARIABLE_WIDTH":
        (rows,) = struct.unpack_from("<i", mv, pos)
        pos += 4
        offsets = np.frombuffer(mv[pos:pos + rows * 4], dtype=np.int32)
        pos += rows * 4
        nulls, pos = _bitunpack_nulls(mv, pos, rows)
        (blob_len,) = struct.unpack_from("<i", mv, pos)
        pos += 4
        blob = bytes(mv[pos:pos + blob_len])
        pos += blob_len
        starts = np.concatenate([[0], offsets[:-1]]) if rows else offsets
        vals = np.array([blob[starts[i]:offsets[i]].decode("utf-8", "replace")
                         for i in range(rows)], dtype=object)
        return (vals, nulls), pos
    if enc == b"DICTIONARY":
        (rows,) = struct.unpack_from("<i", mv, pos)
        pos += 4
        (dvals, dnulls), pos = _deserialize_block(mv, pos, ty)
        idx = np.frombuffer(mv[pos:pos + rows * 4], dtype=np.int32)
        pos += rows * 4
        pos += 24  # dictionary instance id
        return (dvals[idx], dnulls[idx]), pos
    if enc == b"RLE":
        (rows,) = struct.unpack_from("<i", mv, pos)
        pos += 4
        (dvals, dnulls), pos = _deserialize_block(mv, pos, ty)
        return (np.repeat(dvals[:1], rows), np.repeat(dnulls[:1], rows)), pos
    if enc == b"MAP":
        kty = ty.key_type if ty is not None and ty.base == "map" else None
        vty = ty.value_type if ty is not None and ty.base == "map" else None
        (kvals, _kn), pos = _deserialize_block(mv, pos, kty)
        (vvals, vnulls), pos = _deserialize_block(mv, pos, vty)
        (ht_len,) = struct.unpack_from("<i", mv, pos)
        pos += 4
        if ht_len >= 0:
            pos += ht_len * 4  # precomputed hash table: skip
        (rows,) = struct.unpack_from("<i", mv, pos)
        pos += 4
        offsets = np.frombuffer(mv[pos:pos + (rows + 1) * 4],
                                dtype=np.int32)
        pos += (rows + 1) * 4
        nulls, pos = _bitunpack_nulls(mv, pos, rows)
        vals = np.empty(rows, dtype=object)
        for i in range(rows):
            if nulls[i]:
                vals[i] = None
            else:
                vals[i] = {
                    _item(kvals[k]): (None if vnulls[k]
                                      else _item(vvals[k]))
                    for k in range(offsets[i], offsets[i + 1])}
        return (vals, nulls), pos
    if enc == b"ROW":
        (nfields,) = struct.unpack_from("<i", mv, pos)
        pos += 4
        ftys = ty.field_types if ty is not None and ty.base == "row" \
            else [None] * nfields
        fcols = []
        for fi in range(nfields):
            (fv, fn), pos = _deserialize_block(mv, pos, ftys[fi])
            fcols.append((fv, fn))
        (rows,) = struct.unpack_from("<i", mv, pos)
        pos += 4
        offsets = np.frombuffer(mv[pos:pos + (rows + 1) * 4],
                                dtype=np.int32)
        pos += (rows + 1) * 4
        nulls, pos = _bitunpack_nulls(mv, pos, rows)
        vals = np.empty(rows, dtype=object)
        for i in range(rows):
            if nulls[i]:
                vals[i] = None
            else:
                k = offsets[i]
                vals[i] = tuple(None if fn[k] else _item(fv[k])
                                for fv, fn in fcols)
        return (vals, nulls), pos
    if enc == b"ARRAY":
        elem_ty = ty.element_type if ty is not None and \
            ty.base == "array" else None
        (evals, enulls), pos = _deserialize_block(mv, pos, elem_ty)
        (rows,) = struct.unpack_from("<i", mv, pos)
        pos += 4
        offsets = np.frombuffer(mv[pos:pos + (rows + 1) * 4],
                                dtype=np.int32)
        pos += (rows + 1) * 4
        nulls, pos = _bitunpack_nulls(mv, pos, rows)
        vals = np.empty(rows, dtype=object)
        for i in range(rows):
            if nulls[i]:
                vals[i] = None
            else:
                vals[i] = [None if enulls[k] else
                           (evals[k].item() if isinstance(evals[k],
                                                          np.generic)
                            else evals[k])
                           for k in range(offsets[i], offsets[i + 1])]
        return (vals, nulls), pos
    raise NotImplementedError(f"block encoding {enc!r}")


def deserialize_to_arrays(buf: bytes, types: Sequence[T.Type], codec=PageCodec()):
    return deserialize_page(buf, types, codec)
