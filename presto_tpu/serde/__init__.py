from .pages import (serialize_page, deserialize_page, PageCodec,
                    serialize_batch, deserialize_to_arrays)

__all__ = ["serialize_page", "deserialize_page", "PageCodec",
           "serialize_batch", "deserialize_to_arrays"]
