"""Transaction management: the TransactionManager analog.

Reference surface: presto-main-base's transaction/ package
(InMemoryTransactionManager: begin/commit/rollback, per-connector
transaction handles created lazily on first access, auto-commit
single-statement transactions, idle-timeout reaping) and the SPI's
ConnectorTransactionHandle. The TPU engine's connectors are read-only
generators today, so connector handles carry isolation metadata rather
than write state -- but the lifecycle, the auto-commit contract, and
the access bookkeeping mirror the reference so the DBAPI layer and the
coordinator speak the same protocol as Presto clients expect
(START TRANSACTION / COMMIT / ROLLBACK in the statement API).
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Dict, Optional

__all__ = ["TransactionManager", "TransactionInfo", "IsolationLevel",
           "NotInTransaction"]


# SQL standard levels the reference accepts (spi/transaction/IsolationLevel)
ISOLATION_LEVELS = ("READ UNCOMMITTED", "READ COMMITTED",
                    "REPEATABLE READ", "SERIALIZABLE")
IsolationLevel = str


class NotInTransaction(RuntimeError):
    """Operation referenced an unknown/expired transaction id."""


@dataclasses.dataclass
class TransactionInfo:
    transaction_id: str
    isolation: IsolationLevel
    read_only: bool
    auto_commit: bool
    created_at: float
    # connector name -> opaque transaction handle (lazily created on
    # first catalog access, like InMemoryTransactionManager)
    connector_handles: Dict[str, dict] = dataclasses.field(
        default_factory=dict)
    last_access: float = 0.0
    # True while run_autocommit is executing the statement body; an
    # in-flight context must never be reaped no matter how long the
    # statement runs (nothing touches last_access during execution).
    in_use: bool = False

    def to_json(self) -> dict:
        return {"transactionId": self.transaction_id,
                "isolationLevel": self.isolation,
                "readOnly": self.read_only,
                "autoCommitContext": self.auto_commit,
                "catalogs": sorted(self.connector_handles)}


class TransactionManager:
    """begin/commit/rollback + auto-commit contexts + idle reaping."""

    def __init__(self, idle_timeout_s: float = 300.0):
        self._lock = threading.Lock()
        self._txns: Dict[str, TransactionInfo] = {}
        self.idle_timeout_s = idle_timeout_s

    def begin(self, isolation: IsolationLevel = "READ UNCOMMITTED",
              read_only: bool = False,
              auto_commit: bool = False) -> str:
        if isolation not in ISOLATION_LEVELS:
            raise ValueError(f"unknown isolation level {isolation!r}")
        tid = f"tx_{uuid.uuid4().hex[:16]}"
        now = time.time()
        with self._lock:
            self._reap_locked(now)
            self._txns[tid] = TransactionInfo(
                tid, isolation, read_only, auto_commit, now,
                last_access=now)
        return tid

    def get(self, tid: str) -> TransactionInfo:
        with self._lock:
            info = self._txns.get(tid)
            if info is None:
                raise NotInTransaction(f"unknown transaction {tid}")
            info.last_access = time.time()
            return info

    def connector_handle(self, tid: str, connector: str) -> dict:
        """Lazily create the per-connector handle on first access
        (InMemoryTransactionManager.getConnectorTransaction). Lookup
        and create happen under ONE lock acquisition so a concurrent
        commit/rollback can't race a handle onto a finished txn."""
        with self._lock:
            info = self._txns.get(tid)
            if info is None:
                raise NotInTransaction(f"unknown transaction {tid}")
            info.last_access = time.time()
            handle = info.connector_handles.get(connector)
            if handle is None:
                handle = {"connector": connector,
                          "transactionId": tid,
                          "readOnly": info.read_only,
                          "isolation": info.isolation}
                info.connector_handles[connector] = handle
            return handle

    def access_check_write(self, tid: str, connector: str) -> None:
        """Reject writes in read-only transactions (the reference's
        checkConnectorWrite); the engine has no write path yet, so this
        is the seam INSERT/CTAS will call."""
        info = self.get(tid)
        if info.read_only:
            raise RuntimeError(
                f"transaction {tid} is read-only; cannot write to "
                f"{connector}")

    def _end(self, tid: str) -> None:
        with self._lock:
            if self._txns.pop(tid, None) is None:
                raise NotInTransaction(f"unknown transaction {tid}")

    def commit(self, tid: str) -> None:
        self._end(tid)

    def rollback(self, tid: str) -> None:
        self._end(tid)

    def active(self) -> list:
        with self._lock:
            return [t.to_json() for t in self._txns.values()]

    def run_autocommit(self, fn, *, read_only: bool = True):
        """Single-statement auto-commit context: begin, run, commit on
        success / rollback on error (DispatchManager's autocommit
        wrapping of bare statements)."""
        tid = self.begin(read_only=read_only, auto_commit=True)
        with self._lock:
            self._txns[tid].in_use = True
        try:
            out = fn(tid)
        except BaseException:
            self.rollback(tid)
            raise
        self.commit(tid)
        return out

    def _reap_locked(self, now: float) -> None:
        # Idle autocommit transactions are reaped too: one begun via
        # begin(auto_commit=True) and abandoned holds no client state,
        # so letting it linger would only leak _txns entries. In-flight
        # run_autocommit contexts are exempt (in_use).
        cutoff = now - self.idle_timeout_s
        for tid in [t for t, info in self._txns.items()
                    if info.last_access < cutoff and not info.in_use]:
            del self._txns[tid]
