"""SQL type system for the TPU engine.

Reference surface: presto-common/src/main/java/com/facebook/presto/common/type/
(~80 files: BigintType, DoubleType, VarcharType, DecimalType, ArrayType, ...)
and the type-signature parser the native worker keeps in
presto-native-execution/presto_cpp/main/types/TypeParser.cpp.

TPU mapping decisions (deliberately different from the JVM/Velox layouts):

* Integral SQL types map to the narrowest JAX integer dtype; arithmetic is
  exact on-device.
* DECIMAL(p, s) maps to a scaled int64 -- exact fixed-point arithmetic
  on the VPU. In round 1 this includes p > 18 (LongDecimalType): long
  decimals ride int64 lanes too (exact at TPC-H-scale magnitudes,
  documented overflow risk beyond +/-9.2e18 of scaled value); the
  int128 (hi64, lo64) lane pair is the planned upgrade.
* VARCHAR/CHAR map to fixed-width padded uint8 matrices + a length vector
  (TPU has no pointers; offsets+bytes heaps don't vectorize). Dictionary
  encoding is the preferred representation for wide/low-cardinality
  string columns.
* DATE is days-since-epoch int32; TIMESTAMP is micros-since-epoch int64
  (reference stores millis; micros match TPU-friendly 64-bit math and
  modern Presto semantics).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Tuple

import numpy as np

__all__ = [
    "Type",
    "BOOLEAN", "TINYINT", "SMALLINT", "INTEGER", "BIGINT",
    "REAL", "DOUBLE", "DATE", "TIME", "TIMESTAMP", "TIMESTAMP_TZ",
    "VARBINARY", "JSON", "INTERVAL_YM", "INTERVAL_DS", "UNKNOWN",
    "varchar", "char", "decimal", "array_of", "map_of", "row_of",
    "parse_type",
]


@dataclasses.dataclass(frozen=True)
class Type:
    """A SQL type. `base` is the lowercase base name ("bigint", "varchar",
    "decimal", "array", ...); `parameters` hold numeric or nested-type
    parameters exactly as in a Presto TypeSignature."""

    base: str
    parameters: Tuple[object, ...] = ()

    # ---- classification -------------------------------------------------
    @property
    def is_integral(self) -> bool:
        return self.base in ("tinyint", "smallint", "integer", "bigint")

    @property
    def is_floating(self) -> bool:
        return self.base in ("real", "double")

    @property
    def is_decimal(self) -> bool:
        return self.base == "decimal"

    @property
    def is_string(self) -> bool:
        """Types stored as (padded uint8 char matrix, lengths): text,
        raw bytes (VARBINARY) and canonical JSON text share the layout;
        semantic distinctions live in the function layer."""
        return self.base in ("varchar", "char", "varbinary", "json")

    @property
    def is_numeric(self) -> bool:
        return self.is_integral or self.is_floating or self.is_decimal

    @property
    def is_fixed_width(self) -> bool:
        return not (self.is_string or self.base in ("array", "map", "row"))

    # ---- decimal helpers ------------------------------------------------
    @property
    def precision(self) -> int:
        assert self.is_decimal
        return int(self.parameters[0])

    @property
    def scale(self) -> int:
        assert self.is_decimal
        return int(self.parameters[1])

    @property
    def is_short_decimal(self) -> bool:
        return self.is_decimal and self.precision <= 18

    # ---- string helpers -------------------------------------------------
    @property
    def max_length(self) -> int:
        """Declared length for varchar(n)/char(n); UNBOUNDED_LENGTH if none."""
        if self.parameters:
            return int(self.parameters[0])
        return UNBOUNDED_LENGTH

    # ---- container helpers ----------------------------------------------
    @property
    def element_type(self) -> "Type":
        assert self.base == "array"
        return self.parameters[0]

    @property
    def key_type(self) -> "Type":
        assert self.base == "map"
        return self.parameters[0]

    @property
    def value_type(self) -> "Type":
        assert self.base == "map"
        return self.parameters[1]

    @property
    def field_types(self) -> Tuple["Type", ...]:
        assert self.base == "row"
        return tuple(p[1] if isinstance(p, tuple) else p for p in self.parameters)

    # ---- dtype mapping --------------------------------------------------
    def to_dtype(self) -> np.dtype:
        """numpy/JAX dtype of the on-device value array for this type."""
        d = _DTYPES.get(self.base)
        if d is not None:
            return np.dtype(d)
        if self.is_decimal:
            # long decimals (p > 18) live as Int128Column (hi, lo) lane
            # pairs on device (block.py); host-side long-decimal arrays
            # are object arrays of exact Python ints. int64 here is the
            # dtype of each LANE (and the staging dtype for values that
            # happen to fit 64 bits).
            return np.dtype(np.int64)
        if self.is_string:
            return np.dtype(np.uint8)
        raise ValueError(f"no device dtype for type {self}")

    # ---- display --------------------------------------------------------
    def __str__(self) -> str:
        if not self.parameters:
            return self.base
        if self.base == "varchar" and self.parameters[0] == UNBOUNDED_LENGTH:
            return "varchar"
        parts = []
        for p in self.parameters:
            if isinstance(p, tuple):  # row field (name, type)
                parts.append(f"{p[0]} {p[1]}")
            else:
                parts.append(str(p))
        return f"{self.base}({', '.join(parts)})"

    def __repr__(self) -> str:
        return f"Type[{self}]"


UNBOUNDED_LENGTH = 2**31 - 1

_DTYPES = {
    "boolean": np.bool_,
    "tinyint": np.int8,
    "smallint": np.int16,
    "integer": np.int32,
    "bigint": np.int64,
    "real": np.float32,
    "double": np.float64,
    "date": np.int32,
    "time": np.int64,                     # micros since midnight
    "timestamp": np.int64,                # micros since epoch
    # packed (utc_micros << 12) | zone_key -- the reference's
    # TimestampWithTimeZoneType packing (millis<<12|key) adapted to this
    # engine's micros; comparisons/keys unpack to the instant
    "timestamp with time zone": np.int64,
    "interval year to month": np.int64,   # months
    "interval day to second": np.int64,   # micros
    "unknown": np.bool_,
}

BOOLEAN = Type("boolean")
TINYINT = Type("tinyint")
SMALLINT = Type("smallint")
INTEGER = Type("integer")
BIGINT = Type("bigint")
REAL = Type("real")
DOUBLE = Type("double")
DATE = Type("date")
TIME = Type("time")
TIMESTAMP = Type("timestamp")
TIMESTAMP_TZ = Type("timestamp with time zone")
VARBINARY = Type("varbinary")
JSON = Type("json")
INTERVAL_YM = Type("interval year to month")
INTERVAL_DS = Type("interval day to second")
UNKNOWN = Type("unknown")  # the NULL literal's type


def varchar(length: int = UNBOUNDED_LENGTH) -> Type:
    return Type("varchar", (length,))


def char(length: int) -> Type:
    return Type("char", (length,))


def decimal(precision: int, scale: int) -> Type:
    return Type("decimal", (precision, scale))


def array_of(elem: Type) -> Type:
    return Type("array", (elem,))


def map_of(key: Type, value: Type) -> Type:
    return Type("map", (key, value))


def row_of(*fields) -> Type:
    """row_of(T1, T2) or row_of(("name", T1), ...)."""
    return Type("row", tuple(fields))


# --------------------------------------------------------------------------
# Type-signature parsing (TypeParser.cpp / TypeSignature.parse analog).
# Grammar: base ( "(" param ("," param)* ")" )?  where param is an integer,
# a nested signature, or `name type` for row fields.
# --------------------------------------------------------------------------

_TOKEN = re.compile(r"\s*([(),]|[^\s(),]+)")

# multiword base names fold to one token for the parser, then unfold
_MULTIWORD = {
    "timestamp with time zone": "timestamp_with_time_zone",
    "interval year to month": "interval_year_to_month",
    "interval day to second": "interval_day_to_second",
}
_UNFOLD = {v: k for k, v in _MULTIWORD.items()}


def parse_type(signature: str) -> Type:
    for phrase, folded in _MULTIWORD.items():
        signature = re.sub(re.escape(phrase), folded, signature,
                           flags=re.IGNORECASE)
    tokens = [_UNFOLD.get(t.lower(), t) for t in _TOKEN.findall(signature)]
    ty, rest = _parse(tokens)
    if rest:
        raise ValueError(f"trailing tokens in type signature {signature!r}: {rest}")
    return ty


def _parse(tokens):
    if not tokens:
        raise ValueError("empty type signature")
    base = tokens[0].lower()
    tokens = tokens[1:]
    if not tokens or tokens[0] != "(":
        return _finish(base, ()), tokens
    tokens = tokens[1:]  # consume "("
    params = []
    while True:
        if tokens and tokens[0] == ")":
            tokens = tokens[1:]
            break
        if tokens and tokens[0].isdigit():
            # could be `123` param or a quoted field name; integers only here
            params.append(int(tokens[0]))
            tokens = tokens[1:]
        else:
            # row field may be `name type`; detect by lookahead
            if base == "row" and len(tokens) >= 2 and tokens[1] not in ("(", ")", ","):
                name = tokens[0]
                ty, tokens = _parse(tokens[1:])
                params.append((name, ty))
            else:
                ty, tokens = _parse(tokens)
                params.append(ty)
        if tokens and tokens[0] == ",":
            tokens = tokens[1:]
    return _finish(base, tuple(params)), tokens


def _finish(base: str, params: tuple) -> Type:
    if base == "varchar" and not params:
        return varchar()
    return Type(base, params)
